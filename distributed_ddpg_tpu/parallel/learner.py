"""The sharded TPU learner (SURVEY.md §7 step 5; BASELINE.json:5's
"one pmap'd learner step replaces N separate backward passes" — realized
with the modern jit+sharding idiom instead of pmap).

Two execution modes over the same pure step function (learner.py):

- "auto" (default): `jax.jit` with NamedSharding in/out specs over the
  (data, model) mesh. Batches shard over 'data'; params/opt-state replicate
  (or TP-shard over 'model', mesh.py). XLA's SPMD partitioner inserts the
  gradient AllReduce over ICI — the collective that replaces the
  reference's async gRPC parameter-server push/pull (SURVEY.md §3.3).
- "explicit": `jax.shard_map` over the 'data' axis with a hand-written
  `jax.lax.pmean` in the step (axis_name plumbed through
  make_learner_step). Data-parallel only; exists to make the collective
  visible/testable and as the escape hatch if auto partitioning ever
  mis-schedules.

Both modes expose `run_chunk`: K learner steps per dispatch via `lax.scan`
over a stacked [K, B, ...] super-batch. One dispatch per K steps amortizes
host->device latency (critical under this environment's tunneled TPU, and
free pipelining on real hardware); the donated TrainState never leaves HBM
between steps.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import (
    METRIC_KEYS,
    StepOutput,
    init_train_state,
    make_learner_step,
)
from distributed_ddpg_tpu.parallel import mesh as mesh_lib
from distributed_ddpg_tpu.types import (
    Batch,
    OptState,
    TrainState,
    pack_batch_np,
    unpack_batch,
)

def _ingest_lock(device_replay):
    """The replay's dispatch lock (replay/device.py): chunk dispatch must
    not interleave with the async ingest shipper's donate-and-swap of
    storage (a donated-away buffer read mid-swap is a deleted-array
    error), and the PER read -> dispatch -> set_per_state sequence must be
    atomic against shipper priority stamps (a stamp landing inside that
    window would be overwritten and leave fresh rows at priority 0).
    Dispatch is async, so the hold time is the enqueue, not the compute."""
    return getattr(device_replay, "dispatch_lock", None) or contextlib.nullcontext()


def resolve_learner_chunk(config: DDPGConfig) -> int:
    """Production learner steps-per-dispatch: config.learner_chunk when set,
    else measured defaults — 800 on kernel-native TPU backends (the rate
    saturates around chunk 800 while one dispatch stays ~4 ms; see the
    latest BENCH_r*.json chunk sweep), 8 elsewhere (CPU scan dispatches in
    dev/test stay snappy). train_jax and bench.py both resolve through
    here so the trainer and the benchmark run the same program
    (VERDICT.md round-2 Weak #3)."""
    if config.learner_chunk > 0:
        return config.learner_chunk
    from distributed_ddpg_tpu.ops.fused_chunk import runs_native

    return 800 if runs_native() else 8


class ShardedLearner:
    def __init__(
        self,
        config: DDPGConfig,
        obs_dim: int,
        act_dim: int,
        action_scale,
        action_offset=0.0,
        mesh: Optional[Mesh] = None,
        mode: str = "auto",
        chunk_size: int = 1,
        unroll: int = 4,
        replay_sharding: str = "replicated",
    ):
        if mode not in ("auto", "explicit"):
            raise ValueError(f"mode must be 'auto' or 'explicit', got {mode!r}")
        if replay_sharding not in ("replicated", "sharded"):
            raise ValueError(
                f"replay_sharding must be 'replicated' or 'sharded', got "
                f"{replay_sharding!r}"
            )
        # Sharded device replay (docs/REPLAY_SHARDING.md): the sampling
        # chunk programs take storage partitioned over 'data' (strided
        # ownership) and reassemble each replica-identical index draw into
        # the global minibatch with a masked-gather + psum exchange.
        self._replay_sharded = replay_sharding == "sharded"
        self.config = config
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(
            config.data_axis, config.model_axis
        )
        if mode == "explicit" and self.mesh.shape["model"] != 1:
            raise ValueError("explicit (shard_map) mode is data-parallel only")
        self.mode = mode
        self.chunk_size = int(chunk_size)
        # Scan-body unroll factor. Each learner step is ~25 small (<=64x256x256)
        # ops, so per-iteration scan overhead is material: unroll=4 measured
        # 89.5k vs 59.5k steps/s (v5e-1, chunk=800, pre-gathered batches).
        # lax.scan handles unroll > length, so no clamping to chunk sizes.
        # (Rejecting <1 rather than clamping: lax.scan gives unroll=0 its own
        # meaning — full unroll — which a silent clamp would invert.)
        if int(unroll) < 1:
            raise ValueError(f"unroll must be >= 1, got {unroll}")
        self.unroll = int(unroll)
        self.data_size = self.mesh.shape["data"]
        # Rows drawn per learner step on the device-sampling paths.
        # scale_batch_with_data (config.py): per-device independent draws —
        # every data-axis device effectively samples its own batch_size rows
        # from the replicated storage (one global (K, B*D) draw sharded over
        # 'data'; storage is replicated, so this IS D independent draws),
        # and the loss mean spans the global batch, merged by the
        # sharding-induced AllReduce. Equivalent algorithm to one big batch;
        # scales throughput with the mesh instead of slicing 64 rows ever
        # thinner (VERDICT.md round-2 Missing #4).
        self.global_batch = (
            config.batch_size * self.data_size
            if config.scale_batch_with_data
            else config.batch_size
        )
        if self.global_batch % self.data_size:
            raise ValueError(
                f"batch_size={config.batch_size} not divisible by data axis "
                f"size {self.data_size}"
            )

        self.obs_dim, self.act_dim = obs_dim, act_dim
        # Numerical-health guardrails (guardrails.py): the chunk programs
        # thread a small replicated GuardState through the scan and emit a
        # per-chunk health word. Off (default) builds the exact pre-
        # guardrail programs — the parity test pins bit-identity.
        self.guard_enabled = bool(config.guardrails)
        self._numeric_inject = (
            config.fault_plan().numeric_steps()
            if self.guard_enabled and config.faults
            else {}
        )
        self._health_cur = None
        # Superstep first-bad-beat accounting (parallel/superstep.py): the
        # anomaly count (nonfinite + spikes) as of the LAST poll, so a
        # stacked [B, 5] health fetch can localize which beat of the
        # superstep first went bad. Survives reset_guard — the cumulative
        # counters it differences against survive too.
        self._health_prev_anom = 0
        # LR cooldown hook (train.py rollback-repair): both LRs scale by
        # _lr_scale; set_lr_scale rebuilds the (lazily compiled) programs.
        self._lr_scale = 1.0
        state = init_train_state(config, obs_dim, act_dim, config.seed)
        self._state_sharding = mesh_lib.to_named(
            self.mesh, mesh_lib.state_pspec(state, self.mesh)
        )
        # Minibatches cross host->HBM as ONE packed [.., B, D] array
        # (types.pack_batch_np): per-array transfer overhead is the dominant
        # feed cost, so 6 field arrays -> 1 wire array is a ~10x cut.
        self._batch_sharding = NamedSharding(self.mesh, P("data", None))
        self._chunk_sharding = NamedSharding(self.mesh, P(None, "data", None))
        self.state: TrainState = jax.device_put(state, self._state_sharding)
        self._action_scale = action_scale
        self._action_offset = action_offset
        # Unified transfer scheduler (docs/TRANSFER.md): when train_jax
        # attaches one, the learner's d2h pulls run through its inline
        # d2h class — absolute priority (no queueing on the hot path) but
        # full bytes/latency accounting in the transfer_* family.
        self.transfer = None
        self._build_programs()
        self._key = jax.device_put(
            jax.random.PRNGKey(config.seed),
            NamedSharding(self.mesh, P()),
        )
        if self.guard_enabled:
            from distributed_ddpg_tpu import guardrails as guard_lib

            self._guard = jax.device_put(
                guard_lib.init_guard_state(),
                NamedSharding(self.mesh, P()),
            )

    def set_value_bounds(self, v_min: float, v_max: float) -> None:
        """Swap the C51 support bounds and rebuild the (lazily compiled)
        chunk programs in place. Mesh, state, and the sampling key are
        untouched, so the training stream continues exactly where it was;
        the next dispatch pays one XLA recompile. The auto-support
        controller (config.v_support_auto, ops/support_auto.py) calls this
        once at warmup resolution and on each geometric expansion — O(log)
        times per run."""
        self.config = self.config.replace(v_min=float(v_min), v_max=float(v_max))
        self._build_programs()

    def _build_programs(self) -> None:
        """Build every jitted step/chunk program from self.config. jax.jit
        is lazy, so (re)building costs nothing until the next dispatch."""
        # A Mosaic/kernel failure recorded by an earlier dispatch must
        # survive a rebuild: re-arming the fused path would re-pay the
        # known-failing multi-second compile on every support expansion AND
        # wipe the fused_chunk_error diagnostic that tpu_child/multihost
        # probes read afterwards.
        prior_kernel_error = getattr(self, "fused_chunk_error", None)
        config = self.config
        if self._lr_scale != 1.0:
            # Guardrail LR cooldown (train.py rollback-repair): the scale
            # applies at program build, so every path — scan, PER, fused —
            # sees the identical effective LR.
            config = config.replace(
                actor_lr=config.actor_lr * self._lr_scale,
                critic_lr=config.critic_lr * self._lr_scale,
            )
        mode = self.mode
        obs_dim, act_dim = self.obs_dim, self.act_dim
        action_scale = self._action_scale
        action_offset = self._action_offset
        state = self.state

        if mode == "auto":
            step = make_learner_step(config, action_scale, action_offset=action_offset)
        else:
            inner = make_learner_step(
                config, action_scale, axis_name="data", action_offset=action_offset
            )
            state_spec = mesh_lib.state_pspec(state, self.mesh)
            bspec = mesh_lib.batch_pspec()

            def step(s: TrainState, b: Batch) -> StepOutput:
                return mesh_lib.shard_map(
                    inner,
                    mesh=self.mesh,
                    in_specs=(state_spec, bspec),
                    out_specs=StepOutput(
                        state=state_spec,
                        td_errors=P("data"),
                        metrics={k: P() for k in METRIC_KEYS},
                    ),
                )(s, b)

        replicated = NamedSharding(self.mesh, P())
        td_sharding = NamedSharding(self.mesh, P("data"))

        def packed_step(s: TrainState, packed):
            return step(s, unpack_batch(packed, obs_dim, act_dim))

        self._step = jax.jit(
            packed_step,
            in_shardings=(self._state_sharding, self._batch_sharding),
            out_shardings=StepOutput(
                state=self._state_sharding,
                td_errors=td_sharding,
                metrics={k: replicated for k in METRIC_KEYS},
            ),
            donate_argnums=(0,),
        )

        # Shared scan body: one step over a [K, B, ...] Batch pytree, metrics
        # averaged over the chunk (used by both the host-fed and the
        # fused-sampling chunk paths).
        def scan_steps(s: TrainState, batches: Batch) -> StepOutput:
            def body(carry, b):
                out = step(carry, b)
                return out.state, (out.td_errors, out.metrics)

            s, (tds, ms) = jax.lax.scan(body, s, batches, unroll=self.unroll)
            return StepOutput(
                state=s,
                td_errors=tds,
                metrics=jax.tree.map(lambda x: jnp.mean(x), ms),
            )

        # K-steps-per-dispatch scan over host-fed packed batches.
        def chunk_fn(s: TrainState, packed):
            return scan_steps(s, unpack_batch(packed, obs_dim, act_dim))

        td_chunk_sharding = NamedSharding(self.mesh, P(None, "data"))
        self._chunk_step = jax.jit(
            chunk_fn,
            in_shardings=(self._state_sharding, self._chunk_sharding),
            out_shardings=StepOutput(
                state=self._state_sharding,
                td_errors=td_chunk_sharding,
                metrics={k: replicated for k in METRIC_KEYS},
            ),
            donate_argnums=(0,),
        )

        # Fused-sampling chunk over a DeviceReplay: K steps per dispatch with
        # uniform sampling + gather done ON DEVICE — zero h2d inside the
        # chunk (replay/device.py). PRNG key lives on device too.
        batch_size = self.global_batch

        # Sample ALL of the chunk's minibatch indices up front and gather
        # them in ONE [K*B]-row gather. Storage is immutable for the whole
        # dispatch (ingest lands between chunks), so the distribution is
        # identical to sampling inside the scan body — but one fused gather
        # replaces K tiny ones: 59.5k -> 89.5k steps/s with unroll=4
        # (v5e-1, chunk=800). Shared by the scan and megakernel paths so
        # their index streams stay bit-identical (parity tests rely on it).
        def draw_chunk_idx(key, size):
            key, sub = jax.random.split(key)
            idx = jax.random.randint(
                sub, (self.chunk_size, batch_size), 0, jnp.maximum(size, 1)
            )
            return key, idx

        # Row gather behind every sampling path. Replicated storage: a
        # plain local gather. Sharded storage (docs/REPLAY_SHARDING.md):
        # indices are drawn replica-identically (same key on every
        # device), then each shard gathers the rows IT owns (logical
        # position p lives on shard p % N at local slot p // N) and a
        # psum — each row has exactly one owner, everyone else
        # contributes zeros, and x + 0.0 is exact in f32 — reassembles
        # the replicated minibatch: the index-exchange that replaces the
        # replicated copy. Same indices + same logical row contents =>
        # the sampled minibatch is BIT-IDENTICAL to replicated mode (the
        # parity oracle in tests/test_replay_sharding.py).
        n_shards = self.data_size

        def gather_rows(storage, idx):
            if not self._replay_sharded:
                return storage[idx]

            def body(st, ix):
                s = jax.lax.axis_index("data")
                owner = ix % n_shards
                rows = st[jnp.where(owner == s, ix // n_shards, 0)]
                return jax.lax.psum(
                    jnp.where((owner == s)[..., None], rows, 0.0), "data"
                )

            return mesh_lib.shard_map(
                body, self.mesh,
                in_specs=(P("data", None), P()), out_specs=P(),
            )(storage, idx)

        def draw_chunk(key, storage, size):
            key, idx = draw_chunk_idx(key, size)
            return key, gather_rows(storage, idx)

        def sample_chunk_fn(s: TrainState, key, storage, size):
            key, packed = draw_chunk(key, storage, size)
            packed = jax.lax.with_sharding_constraint(
                packed, NamedSharding(self.mesh, P(None, "data", None))
            )
            return scan_steps(s, unpack_batch(packed, obs_dim, act_dim)), key

        # Pallas megakernel path (ops/fused_chunk.py): the whole chunk in one
        # kernel, params VMEM-resident.
        from distributed_ddpg_tpu.ops import fused_chunk as fused_chunk_lib

        # "auto" additionally requires a real TPU (elsewhere the kernel would
        # run in pallas interpret mode — correct but far slower than the XLA
        # scan; "on" forces it anywhere, tests use this) and mode="auto":
        # mode="explicit" exists to make the shard_map path observable, so it
        # must never be silently replaced by the megakernel.
        envelope_ok = (
            config.fused_chunk != "off"
            # Guardrails need the probe threaded through every step — the
            # megakernel has no slot for it, so the scan path wins
            # (config validation rejects fused_chunk='on' + guardrails).
            and not config.guardrails
            # Sharded replay: the kernel reads replicated storage whole;
            # the shard-exchange gather lives in the XLA scan path only
            # (config validation rejects fused_chunk='on' + sharded).
            and not self._replay_sharded
            and self.mode == "auto"
            and fused_chunk_lib.supported(config)
            and fused_chunk_lib.fits_vmem(config, obs_dim, act_dim)
            and (config.fused_chunk == "on" or fused_chunk_lib.runs_native())
        )
        # Mesh composition (config.fused_mesh, VERDICT.md r3 Missing #3):
        # on a DATA-only mesh every device runs the megakernel on its own
        # independent draws for the whole chunk; float state is pmean'd at
        # the chunk boundary (K-step local SGD — one params AllReduce per
        # K steps, NOT K gradient psums, which would evict params from VMEM
        # every step and forfeit the kernel's HBM-traffic win). TP
        # (model_axis > 1) shards the param tensors the kernel needs whole,
        # so the scan path keeps those meshes.
        self.fused_mesh_active = (
            envelope_ok
            and self.mesh.size > 1
            and self.mesh.shape["model"] == 1
            and config.fused_mesh != "off"
        )
        self.fused_chunk_active = envelope_ok and (
            self.mesh.size == 1 or self.fused_mesh_active
        )
        if config.fused_chunk == "on" and not self.fused_chunk_active:
            raise ValueError(
                "fused_chunk='on' but the config/mesh is outside the kernel "
                "envelope: needs mode='auto', a single-device or data-only "
                "mesh (model_axis == 1, and fused_mesh != 'off' for "
                "multi-device), plus action_insert_layer=1, critic_l2=0, "
                "fused_update=False, >=2 critic hidden layers, and nets "
                "small enough for VMEM (ops/fused_chunk.fits_vmem)"
            )
        scan_sample_chunk_fn = sample_chunk_fn
        fused_run = None  # set on the single-device kernel path; PER reuses it
        if self.fused_chunk_active and not self.fused_mesh_active:
            run_fused = fused_chunk_lib.make_fused_chunk_fn(
                config, obs_dim, act_dim, action_scale, action_offset,
                chunk_size=self.chunk_size,
            )
            fused_run = run_fused

            def fused_sample_chunk_fn(s: TrainState, key, storage, size):
                key, packed = draw_chunk(key, storage, size)
                new_s, tds, ms = run_fused(s, packed)
                return StepOutput(state=new_s, td_errors=tds, metrics=ms), key

            sample_chunk_fn = fused_sample_chunk_fn
        elif self.fused_mesh_active:
            sample_chunk_fn = self._make_fused_mesh_fn(
                fused_chunk_lib, action_scale, action_offset
            )

        # PER fused chunk (replay/device.py DevicePrioritizedReplay,
        # VERDICT.md round-1 Missing #4): stratified proportional draw from
        # the device-resident priority vector, IS-weighted scan, and the
        # (|td|+eps)^alpha scatter update — one dispatch, zero h2d. The
        # priority vector is donated in and handed back updated.
        from distributed_ddpg_tpu.replay.device import (
            draw_per_indices,
            make_sharded_per_draw,
        )

        # Sharded PER (docs/REPLAY_SHARDING.md): shard-local cumsums under
        # a replicated top-level sampler replace the full-vector cumsum,
        # and the post-chunk priority scatter routes each update to the
        # owner shard (drop-mode, exactly one owner per index).
        per_draw = (
            make_sharded_per_draw(self.mesh)
            if self._replay_sharded
            else draw_per_indices
        )

        def scatter_prios(priorities, idx_flat, vals_flat):
            if not self._replay_sharded:
                return priorities.at[idx_flat].set(vals_flat)

            def body(pr, ix, vals):
                s = jax.lax.axis_index("data")
                loc = jnp.where(
                    ix % n_shards == s, ix // n_shards, pr.shape[0]
                )
                return pr.at[loc].set(vals, mode="drop")

            return mesh_lib.shard_map(
                body, self.mesh,
                in_specs=(P("data"), P(), P()), out_specs=P("data"),
            )(priorities, idx_flat, vals_flat)

        def per_sample_chunk_fn(s, key, storage, size, priorities, maxp,
                                beta, alpha, eps):
            key, sub = jax.random.split(key)
            idx, weights = per_draw(
                sub, priorities, size, (self.chunk_size, batch_size), beta
            )
            packed = gather_rows(storage, idx)
            packed = jax.lax.with_sharding_constraint(
                packed, NamedSharding(self.mesh, P(None, "data", None))
            )
            weights = jax.lax.with_sharding_constraint(
                weights, NamedSharding(self.mesh, P(None, "data"))
            )
            batches = unpack_batch(packed, obs_dim, act_dim)._replace(
                weight=weights
            )
            out = scan_steps(s, batches)
            new_p = (jnp.abs(out.td_errors) + eps) ** alpha
            priorities = scatter_prios(
                priorities, idx.reshape(-1), new_p.reshape(-1)
            )
            maxp = jnp.maximum(maxp, new_p.max())
            return out, key, priorities, maxp

        storage_sharding = NamedSharding(
            self.mesh,
            P("data", None) if self._replay_sharded else P(None, None),
        )
        prio_sharding = NamedSharding(
            self.mesh, P("data") if self._replay_sharded else P(None)
        )

        def _jit_per_chunk(fn):
            return jax.jit(
                fn,
                in_shardings=(
                    self._state_sharding, replicated, storage_sharding,
                    replicated, prio_sharding, replicated, replicated,
                    replicated, replicated,
                ),
                out_shardings=(
                    StepOutput(
                        state=self._state_sharding,
                        td_errors=NamedSharding(self.mesh, P(None, "data")),
                        metrics={k: replicated for k in METRIC_KEYS},
                    ),
                    replicated,
                    prio_sharding,
                    replicated,
                ),
                donate_argnums=(0, 1, 4),
            )

        self._scan_per_sample_chunk_step = _jit_per_chunk(per_sample_chunk_fn)
        self.fused_per_active = fused_run is not None
        if self.fused_per_active:
            # PER x megakernel: the stratified proportional draw and the
            # priority scatter live OUTSIDE the kernel (they're cheap,
            # bandwidth-bound ops XLA handles fine); only the K learner
            # steps run in the single pallas launch. The IS weights ride in
            # through the packed wire row's trailing weight column — the
            # kernel already reads per-row weights from there, so the
            # kernel needs no PER-specific change. Draw order matches the
            # scan path exactly (split -> draw_per_indices with identical
            # shapes), so the two paths are bit-comparable and the fused
            # path inherits the same priority semantics.
            def fused_per_sample_chunk_fn(s, key, storage, size, priorities,
                                          maxp, beta, alpha, eps):
                key, sub = jax.random.split(key)
                idx, weights = draw_per_indices(
                    sub, priorities, size, (self.chunk_size, batch_size), beta
                )
                packed = storage[idx].at[..., -1].set(weights)
                new_s, tds, ms = fused_run(s, packed)
                out = StepOutput(state=new_s, td_errors=tds, metrics=ms)
                new_p = (jnp.abs(tds) + eps) ** alpha
                priorities = priorities.at[idx.reshape(-1)].set(
                    new_p.reshape(-1)
                )
                maxp = jnp.maximum(maxp, new_p.max())
                return out, key, priorities, maxp

            self._per_sample_chunk_step = _jit_per_chunk(
                fused_per_sample_chunk_fn
            )
        else:
            self._per_sample_chunk_step = self._scan_per_sample_chunk_step
        self._per_chunk_compiled = False
        def _jit_sample_chunk(fn):
            return jax.jit(
                fn,
                in_shardings=(
                    self._state_sharding, replicated, storage_sharding, replicated
                ),
                out_shardings=(
                    StepOutput(
                        state=self._state_sharding,
                        td_errors=td_chunk_sharding,
                        metrics={k: replicated for k in METRIC_KEYS},
                    ),
                    replicated,
                ),
                donate_argnums=(0, 1),
            )

        # jax.jit is lazy, so holding BOTH paths costs nothing until called:
        # the scan jit is the first-dispatch fallback target if the
        # megakernel fails to compile on this backend (VERDICT.md round-2
        # Weak #2 — a Mosaic failure must degrade, not kill the caller; the
        # failure can only surface at compile, i.e. first dispatch, so no
        # extra probe compile is paid on healthy backends).
        self._scan_sample_chunk_step = _jit_sample_chunk(scan_sample_chunk_fn)
        self._sample_chunk_step = (
            _jit_sample_chunk(sample_chunk_fn)
            if self.fused_chunk_active
            else self._scan_sample_chunk_step
        )
        self._sample_chunk_compiled = False

        if self.guard_enabled:
            # --- guarded chunk programs (guardrails.py) ---
            # The same scan bodies with the health probe threaded through:
            # each program additionally takes/returns the replicated
            # GuardState (donated) and emits the per-chunk health word;
            # the sampling paths also screen the raw gathered rows and
            # capture bad replay indices for source attribution. jit is
            # lazy, so the unguarded builds above cost nothing.
            from distributed_ddpg_tpu import guardrails as guard_lib

            gstep = guard_lib.make_guarded_step(
                step,
                zmax=config.guardrail_zmax,
                warmup=config.guardrail_warmup_steps,
                inject=self._numeric_inject,
            )

            def guarded_scan(s, g, batches, pre_bad):
                def body(carry, x):
                    cs, cg = carry
                    b, pb = x
                    ns, ng, td, ms = gstep(cs, cg, b, pb)
                    return (ns, ng), (td, ms)

                (s, g), (tds, ms) = jax.lax.scan(
                    body, (s, g), (batches, pre_bad), unroll=self.unroll
                )
                return StepOutput(
                    state=s,
                    td_errors=tds,
                    metrics=jax.tree.map(lambda x: jnp.mean(x), ms),
                ), g

            def guard_chunk_fn(s: TrainState, packed, g):
                # Host-fed path: the sampler owns replay indices, so the
                # row screen reports counts only (bad_idx rides as -1s).
                pre_bad, bad_count, _ = guard_lib.batch_row_health(
                    packed, None
                )
                g = g._replace(bad_rows=g.bad_rows + bad_count)
                out, g = guarded_scan(
                    s, g, unpack_batch(packed, obs_dim, act_dim), pre_bad
                )
                return out, g, guard_lib.health_vector(g)

            self._chunk_step = jax.jit(
                guard_chunk_fn,
                in_shardings=(
                    self._state_sharding, self._chunk_sharding, replicated,
                ),
                out_shardings=(
                    StepOutput(
                        state=self._state_sharding,
                        td_errors=td_chunk_sharding,
                        metrics={k: replicated for k in METRIC_KEYS},
                    ),
                    replicated,
                    replicated,
                ),
                donate_argnums=(0, 2),
            )

            def guard_sample_chunk_fn(s: TrainState, key, storage, size, g):
                key, idx = draw_chunk_idx(key, size)
                packed = gather_rows(storage, idx)
                packed = jax.lax.with_sharding_constraint(
                    packed, NamedSharding(self.mesh, P(None, "data", None))
                )
                pre_bad, bad_count, bad_idx = guard_lib.batch_row_health(
                    packed, idx
                )
                g = g._replace(bad_rows=g.bad_rows + bad_count)
                out, g = guarded_scan(
                    s, g, unpack_batch(packed, obs_dim, act_dim), pre_bad
                )
                return out, key, g, guard_lib.health_vector(g), bad_idx

            guard_out = (
                StepOutput(
                    state=self._state_sharding,
                    td_errors=td_chunk_sharding,
                    metrics={k: replicated for k in METRIC_KEYS},
                ),
                replicated,  # key
                replicated,  # guard state
                replicated,  # health word
                replicated,  # bad replay indices
            )
            self._sample_chunk_step = jax.jit(
                guard_sample_chunk_fn,
                in_shardings=(
                    self._state_sharding, replicated, storage_sharding,
                    replicated, replicated,
                ),
                out_shardings=guard_out,
                donate_argnums=(0, 1, 4),
            )
            self._scan_sample_chunk_step = self._sample_chunk_step

            def guard_per_sample_chunk_fn(s, key, storage, size, priorities,
                                          maxp, beta, alpha, eps, g):
                key, sub = jax.random.split(key)
                idx, weights = per_draw(
                    sub, priorities, size, (self.chunk_size, batch_size),
                    beta,
                )
                packed = gather_rows(storage, idx)
                packed = jax.lax.with_sharding_constraint(
                    packed, NamedSharding(self.mesh, P(None, "data", None))
                )
                weights = jax.lax.with_sharding_constraint(
                    weights, NamedSharding(self.mesh, P(None, "data"))
                )
                pre_bad, bad_count, bad_idx = guard_lib.batch_row_health(
                    packed, idx
                )
                g = g._replace(bad_rows=g.bad_rows + bad_count)
                batches = unpack_batch(packed, obs_dim, act_dim)._replace(
                    weight=weights
                )
                out, g = guarded_scan(s, g, batches, pre_bad)
                # A bad step's td errors are zeroed by the probe, so its
                # sampled rows re-stamp at the (eps)^alpha floor instead
                # of inheriting NaN priorities that would poison every
                # later draw.
                new_p = (jnp.abs(out.td_errors) + eps) ** alpha
                priorities = scatter_prios(
                    priorities, idx.reshape(-1), new_p.reshape(-1)
                )
                maxp = jnp.maximum(maxp, new_p.max())
                return (
                    out, key, priorities, maxp, g,
                    guard_lib.health_vector(g), bad_idx,
                )

            self._per_sample_chunk_step = jax.jit(
                guard_per_sample_chunk_fn,
                in_shardings=(
                    self._state_sharding, replicated, storage_sharding,
                    replicated, prio_sharding, replicated, replicated,
                    replicated, replicated, replicated,
                ),
                out_shardings=(
                    StepOutput(
                        state=self._state_sharding,
                        td_errors=NamedSharding(self.mesh, P(None, "data")),
                        metrics={k: replicated for k in METRIC_KEYS},
                    ),
                    replicated,
                    prio_sharding,
                    replicated,
                    replicated,
                    replicated,
                    replicated,
                ),
                donate_argnums=(0, 1, 4, 9),
            )
            self._scan_per_sample_chunk_step = self._per_sample_chunk_step

        # --- fused-megastep composition (parallel/megastep.py) ---
        # The pure (unjitted) XLA-scan sampling bodies, for composition
        # into the fused beat program. Always the SCAN variants: the
        # megastep composes whole-chunk bodies, and the Pallas megakernel
        # has no slot inside a larger traced program. Rebuilt with every
        # _build_programs call (LR backoff, support expansion), so the
        # version counter below lets the megastep detect staleness and
        # rebuild its beat program in step.
        self._pure_scan_fns = {
            "uniform": scan_sample_chunk_fn,
            "per": per_sample_chunk_fn,
        }
        if self.guard_enabled:
            self._pure_scan_fns["uniform.guarded"] = guard_sample_chunk_fn
            self._pure_scan_fns["per.guarded"] = guard_per_sample_chunk_fn
        self.programs_version = getattr(self, "programs_version", 0) + 1

        self.fused_chunk_error: Optional[str] = None
        if prior_kernel_error is not None:
            # Stay degraded (see note at the top of this method) — same
            # assignments as the run_sample_chunk fallback branch.
            self.fused_chunk_error = prior_kernel_error
            self.fused_chunk_active = False
            self.fused_mesh_active = False
            self.fused_per_active = False
            self._sample_chunk_step = self._scan_sample_chunk_step
            self._per_sample_chunk_step = self._scan_per_sample_chunk_step

    def _make_fused_mesh_fn(self, fused_chunk_lib, action_scale, action_offset):
        """Megakernel x data-parallel mesh (VERDICT.md r3 Missing #3).

        Every 'data'-axis device runs the whole K-step chunk in ONE pallas
        launch on its OWN independent minibatch draws (storage is replicated,
        so per-device draws from the full buffer are D independent batch
        streams), then the float state — params, targets, Adam moments — is
        pmean'd across the axis at the chunk boundary. That is K-step local
        SGD: one params-sized AllReduce per K steps instead of the scan
        path's K per-step gradient psums. Per-step sync inside the kernel
        would force params back to HBM every step, forfeiting exactly the
        VMEM-residency win the kernel exists for; at K=800 the boundary
        AllReduce (~5 MB of state) amortizes to ~6 KB/step — below even the
        batch stream. Divergence between replicas is bounded by O(lr * K)
        drift per chunk (each replica's Adam update is clipped to ~lr per
        step by normalization); docs/PERF_NOTES.md carries the measured
        parity + staleness argument. Adam counts/step advance identically
        on every replica and pass through un-averaged."""
        K = self.chunk_size
        b_local = self.global_batch // self.data_size
        run_fused = fused_chunk_lib.make_fused_chunk_fn(
            self.config.replace(batch_size=b_local),
            self.obs_dim, self.act_dim, action_scale, action_offset,
            chunk_size=K,
        )
        mesh = self.mesh
        state_spec = mesh_lib.state_pspec(self.state, mesh)

        twin_noise = self.config.twin_critic and self.config.target_noise > 0
        sac = self.config.sac

        def local_chunk(s, sub, storage, size):
            axis_idx = jax.lax.axis_index("data")
            dkey = jax.random.fold_in(sub, axis_idx)
            idx = jax.random.randint(
                dkey, (K, b_local), 0, jnp.maximum(size, 1)
            )
            eps = None
            if twin_noise:
                # Per-device iid smoothing noise: the scan path's
                # fold_in(seed, step) stream with the device index folded
                # on top (mirrors make_learner_step's axis_name handling).
                eps = fused_chunk_lib.td3_noise_eps(
                    self.config, s.step, K, b_local, self.act_dim,
                    device_fold=axis_idx,
                )
            elif sac:
                # Same discipline for SAC's two sampling streams.
                eps = fused_chunk_lib.sac_noise_eps(
                    self.config, s.step, K, b_local, self.act_dim,
                    device_fold=axis_idx,
                )
            new_s, tds, ms = run_fused(s, storage[idx], eps=eps)
            avg = lambda x: jax.lax.pmean(x, "data")
            favg = lambda tree: jax.tree.map(avg, tree)
            # SAC temperature state is float — it local-SGDs inside the
            # chunk and pmeans at the boundary like every other float leaf.
            extra = {}
            if new_s.log_alpha is not None:
                extra["log_alpha"] = avg(new_s.log_alpha)
            if new_s.alpha_opt is not None:
                extra["alpha_opt"] = OptState(
                    mu=avg(new_s.alpha_opt.mu),
                    nu=avg(new_s.alpha_opt.nu),
                    count=new_s.alpha_opt.count,
                )
            new_s = TrainState(
                actor_params=favg(new_s.actor_params),
                critic_params=favg(new_s.critic_params),
                target_actor_params=favg(new_s.target_actor_params),
                target_critic_params=favg(new_s.target_critic_params),
                actor_opt=OptState(
                    mu=favg(new_s.actor_opt.mu),
                    nu=favg(new_s.actor_opt.nu),
                    count=new_s.actor_opt.count,
                ),
                critic_opt=OptState(
                    mu=favg(new_s.critic_opt.mu),
                    nu=favg(new_s.critic_opt.nu),
                    count=new_s.critic_opt.count,
                ),
                step=new_s.step,
                **extra,
            )
            return new_s, tds, {k: avg(v) for k, v in ms.items()}

        sharded = mesh_lib.shard_map(
            local_chunk,
            mesh=mesh,
            in_specs=(state_spec, P(), P(None, None), P()),
            out_specs=(
                state_spec,
                P(None, "data"),
                {k: P() for k in METRIC_KEYS},
            ),
        )

        def fused_mesh_sample_chunk_fn(s: TrainState, key, storage, size):
            key, sub = jax.random.split(key)
            new_s, tds, ms = sharded(s, sub, storage, size)
            return StepOutput(state=new_s, td_errors=tds, metrics=ms), key

        return fused_mesh_sample_chunk_fn

    # --- single step ---

    def step(self, np_batch: Dict[str, np.ndarray]) -> StepOutput:
        packed = jax.device_put(pack_batch_np(np_batch), self._batch_sharding)
        out = self._step(self.state, packed)
        self.state = out.state
        return out

    # --- K steps per dispatch ---

    def run_chunk(self, np_batches: Dict[str, np.ndarray]) -> StepOutput:
        """np_batches fields are [K, B, ...] stacked minibatches."""
        return self.run_chunk_async(self.put_chunk(np_batches))

    def run_chunk_async(self, device_chunk) -> StepOutput:
        """Same as run_chunk but takes an already-device_put packed chunk
        (from the prefetch pipeline) and does not block — callers sync on
        the outputs."""
        if self.guard_enabled:
            out, self._guard, health = self._chunk_step(
                self.state, device_chunk, self._guard
            )
            self._health_cur = (health, None)
            self.state = out.state
            return out
        out = self._chunk_step(self.state, device_chunk)
        self.state = out.state
        return out

    def put_chunk(self, np_batches: Dict[str, np.ndarray]):
        """Pack a [K, B, field] dict into the single wire array and start
        its (async) transfer to HBM with the chunk sharding."""
        with trace.span("chunk_h2d"):
            return jax.device_put(
                pack_batch_np(np_batches), self._chunk_sharding
            )

    # --- K steps per dispatch, sampling fused on device ---

    def run_sample_chunk(self, device_replay) -> StepOutput:
        """K learner steps sampling uniformly from a DeviceReplay — the
        zero-h2d steady-state path (batches never touch the host).

        In fused_chunk='auto' mode a megakernel COMPILE failure on the
        first dispatch degrades to the XLA scan path; 'on' lets the error
        propagate for tests/explicit opt-in. The fallback is confined to
        the first dispatch and to intact inputs: donation consumes buffers
        at invoke (not on success), so a post-compile execution failure
        must re-raise rather than retry against deleted arrays."""
        with _ingest_lock(device_replay):
            storage, size = device_replay.device_state()
            if self.guard_enabled:
                out, self._key, self._guard, health, bad_idx = (
                    self._sample_chunk_step(
                        self.state, self._key, storage, size, self._guard
                    )
                )
                self._health_cur = (health, bad_idx)
                self.state = out.state
                return out
            try:
                out, self._key = self._sample_chunk_step(
                    self.state, self._key, storage, size
                )
            except Exception as e:
                retryable = (
                    self.fused_chunk_active
                    and self.config.fused_chunk == "auto"
                    and not self._sample_chunk_compiled
                    and not any(
                        getattr(leaf, "is_deleted", lambda: False)()
                        for leaf in jax.tree.leaves((self.state, self._key))
                    )
                )
                if not retryable:
                    raise
                import warnings

                warnings.warn(
                    "fused_chunk='auto': megakernel failed on this backend; "
                    f"falling back to the XLA scan path: {e!r}"
                )
                self.fused_chunk_error = repr(e)[:800]
                self.fused_chunk_active = False
                self.fused_mesh_active = False  # scan = per-step psum semantics
                # Same kernel program backs the PER variant — don't re-fail there.
                self.fused_per_active = False
                self._per_sample_chunk_step = self._scan_per_sample_chunk_step
                self._sample_chunk_step = self._scan_sample_chunk_step
                out, self._key = self._sample_chunk_step(
                    # lint: ok(donation-safety): retry gated on `retryable`,
                    # which verified no leaf of (state, key) is_deleted —
                    # the failed dispatch never consumed the buffers
                    self.state, self._key, storage, size
                )
            self._sample_chunk_compiled = True
            self.state = out.state
            return out

    def run_sample_chunk_per(self, device_replay, beta: float) -> StepOutput:
        """K learner steps with proportional PER sampling + priority update
        fused on device (DevicePrioritizedReplay) — the same zero-h2d
        steady state as the uniform path; beta anneals host-side and rides
        in as a scalar argument. With the megakernel active the K steps
        run in one pallas launch (draw + priority scatter stay XLA ops);
        a kernel COMPILE failure on the first dispatch degrades to the
        scan path exactly like run_sample_chunk."""
        with _ingest_lock(device_replay):
            storage, size, priorities, maxp = device_replay.per_state()
            args = (
                np.float32(beta), np.float32(device_replay.alpha),
                np.float32(device_replay.eps),
            )
            if self.guard_enabled:
                out, self._key, new_p, new_maxp, self._guard, health, bad_idx = (
                    self._per_sample_chunk_step(
                        self.state, self._key, storage, size, priorities,
                        maxp, *args, self._guard,
                    )
                )
                self._health_cur = (health, bad_idx)
                self.state = out.state
                device_replay.set_per_state(new_p, new_maxp)
                return out
            try:
                out, self._key, new_p, new_maxp = self._per_sample_chunk_step(
                    self.state, self._key, storage, size, priorities, maxp, *args
                )
            except Exception as e:
                retryable = (
                    self.fused_per_active
                    and self.config.fused_chunk == "auto"
                    and not self._per_chunk_compiled
                    and not any(
                        getattr(leaf, "is_deleted", lambda: False)()
                        for leaf in jax.tree.leaves(
                            (self.state, self._key, priorities)
                        )
                    )
                )
                if not retryable:
                    raise
                import warnings

                warnings.warn(
                    "fused_chunk='auto': PER megakernel failed on this backend; "
                    f"falling back to the XLA scan path: {e!r}"
                )
                self.fused_chunk_error = repr(e)[:800]
                self.fused_per_active = False
                # Same kernel program backs the uniform variant — don't re-fail.
                self.fused_chunk_active = False
                self._sample_chunk_step = self._scan_sample_chunk_step
                self._per_sample_chunk_step = self._scan_per_sample_chunk_step
                out, self._key, new_p, new_maxp = self._per_sample_chunk_step(
                    # lint: ok(donation-safety): retry gated on `retryable`,
                    # which verified no leaf of (state, key, priorities)
                    # is_deleted — the failed dispatch never consumed them
                    self.state, self._key, storage, size, priorities, maxp, *args
                )
            self._per_chunk_compiled = True
            self.state = out.state
            device_replay.set_per_state(new_p, new_maxp)
            return out

    # --- fused-megastep composition hooks (parallel/megastep.py) ---

    def pure_scan_sample_fn(self, per: bool):
        """The pure scan-path sampling-chunk body matching this learner's
        guard mode — uniform: (state, key, storage, size[, guard]);
        PER: (state, key, storage, size, priorities, maxp, beta, alpha,
        eps[, guard]). The fused megastep composes it with the rollout and
        ring insert into one beat program; using the identical body is
        what makes fused-vs-separate dispatch bit-identity hold."""
        key = ("per" if per else "uniform") + (
            ".guarded" if self.guard_enabled else ""
        )
        return self._pure_scan_fns[key]

    def note_fused_health(self, guard, health, bad_idx) -> None:
        """Install the guard state + health word(s) a fused dispatch
        returned, so poll_health()/bad_indices() (the train.py guardrail
        monitor) read the fused program's probe exactly as they read a
        standalone guarded chunk's. A megastep beat hands a scalar health
        word (int32[5]) and bad-row capture (int32[GUARD_BAD_IDX]); a
        B-beat superstep (parallel/superstep.py) hands the stacked
        per-beat VECTORS (int32[B, 5] / int32[B, GUARD_BAD_IDX]) — the
        final row is the chunk-end cumulative counters, and the per-row
        deltas localize the first bad beat."""
        self._guard = guard
        self._health_cur = (health, bad_idx)

    # --- host-side views ---

    def actor_params_to_host(self):
        """Numpy actor params for broadcast to CPU rollout workers. The
        span matters: this d2h syncs the in-flight chunk, and on a
        tunneled TPU it is the single most expensive host-visible call —
        the timeline shows it as the learner-thread gap before every
        param refresh / eval snapshot."""
        def fetch():
            with trace.span("params_d2h"):
                return jax.tree.map(
                    np.asarray, jax.device_get(self.state.actor_params)
                )

        if self.transfer is None:
            return fetch()
        return self.transfer.run_inline(
            "d2h", fetch, label="params_d2h",
            nbytes_of=lambda r: sum(l.nbytes for l in jax.tree.leaves(r)),
        )

    def metrics_to_host(self, out: StepOutput) -> Dict[str, float]:
        def fetch():
            with trace.span("metrics_d2h"):
                return {
                    k: float(v)
                    for k, v in jax.device_get(out.metrics).items()
                }

        if self.transfer is None:
            return fetch()
        return self.transfer.run_inline(
            "d2h", fetch, label="metrics_d2h",
            nbytes_of=lambda r: 8 * len(r),
        )

    # --- numerical-health guardrails (guardrails.py) ---

    def poll_health(self) -> Optional[Dict[str, int]]:
        """Cumulative probe counters of the most recent guarded dispatch
        — the one tiny d2h the guardrail monitor pays per sync point (it
        syncs the health word only, never params). None before the first
        guarded dispatch or with guardrails off.

        A superstep's stacked int32[B, 5] health vector (note_fused_
        health) syncs in the SAME single device_get: the returned dict is
        the final row (chunk-end cumulative counters, exactly what B
        sequential polls would have converged to), plus a
        "first_bad_beat" entry — the 0-based index of the first beat
        whose cumulative anomaly count (nonfinite + spikes) moved past
        the previous poll's, or -1 when the superstep was clean. Scalar
        fetches carry no such key, so GuardrailStats.absorb's .get-based
        delta accounting is untouched."""
        if not self.guard_enabled or self._health_cur is None:
            return None
        from distributed_ddpg_tpu import guardrails as guard_lib

        def fetch():
            with trace.span("health_d2h"):
                vec = np.asarray(jax.device_get(self._health_cur[0]))
            if vec.ndim == 1:
                return dict(
                    zip(guard_lib.HEALTH_KEYS, (int(v) for v in vec))
                )
            # Stacked [B, 5] superstep vector: one fetch, per-beat rows.
            keys = guard_lib.HEALTH_KEYS
            anom = (
                vec[:, keys.index("nonfinite")] + vec[:, keys.index("spikes")]
            ).astype(np.int64)
            fresh = np.flatnonzero(anom > self._health_prev_anom)
            h = dict(zip(keys, (int(v) for v in vec[-1])))
            h["first_bad_beat"] = int(fresh[0]) if fresh.size else -1
            return h

        if self.transfer is None:
            h = fetch()
        else:
            h = self.transfer.run_inline(
                "d2h", fetch, label="health_d2h",
                nbytes_of=lambda r: 4 * len(r),
            )
        if h is not None:
            self._health_prev_anom = (
                int(h.get("nonfinite", 0)) + int(h.get("spikes", 0))
            )
        return h

    def bad_indices(self) -> np.ndarray:
        """Replay indices of the non-finite rows the last guarded chunk
        sampled (first guardrails.GUARD_BAD_IDX; device pads with -1,
        filtered here). Fetch only when the health word shows fresh
        bad_rows — this d2h rides the rare bad path."""
        if not self.guard_enabled or self._health_cur is None:
            return np.empty(0, np.int64)
        bad = self._health_cur[1]
        if bad is None:
            return np.empty(0, np.int64)
        arr = np.asarray(jax.device_get(bad)).astype(np.int64)
        # A superstep hands the stacked [B, GUARD_BAD_IDX] capture;
        # beat order is row order, so a flatten preserves it.
        return arr.reshape(-1)[arr.reshape(-1) >= 0]

    def reset_guard(self) -> None:
        """Re-arm the probe after a rollback: EWMA statistics reset (the
        restored params have the pre-divergence loss scale), cumulative
        counters and the monotonic step clock survive (the host's delta
        accounting and the numeric-fault ordinals key on them)."""
        if not self.guard_enabled:
            return
        from distributed_ddpg_tpu import guardrails as guard_lib

        h = self.poll_health() or {}
        self._guard = jax.device_put(
            guard_lib.init_guard_state(
                total=h.get("total", 0),
                nonfinite=h.get("nonfinite", 0),
                spikes=h.get("spikes", 0),
                skipped=h.get("skipped", 0),
                bad_rows=h.get("bad_rows", 0),
            ),
            NamedSharding(self.mesh, P()),
        )
        self._health_cur = None

    def reseed(self, salt: int) -> None:
        """Fold `salt` into the device sampling key. Rollback-repair calls
        this so the resumed trajectory draws DIFFERENT minibatches than
        the one that diverged — restoring state alone would replay the
        identical sample stream into the identical divergence."""
        self._key = jax.random.fold_in(self._key, int(salt))

    @property
    def lr_scale(self) -> float:
        return self._lr_scale

    def set_lr_scale(self, scale: float) -> None:
        """Scale both learner LRs (guardrail rollback cooldown). Rebuilds
        the lazily-compiled chunk programs like set_value_bounds — one XLA
        recompile at the next dispatch, state/key/guard untouched."""
        scale = float(scale)
        if scale == self._lr_scale:
            return
        self._lr_scale = scale
        self._build_programs()


# ---------------------------------------------------------------------------
# program-contract analyzer hook (analysis/programs.py; docs/ANALYSIS.md
# "Layer 2")
# ---------------------------------------------------------------------------


def program_specs():
    """Every hot learner chunk program, built tiny (8-wide batch, 16-wide
    hiddens, chunk of 2) under the 2-device CPU probe mesh. jit is lazy,
    so each build costs one trace and zero compiles. The guarded and
    unguarded variants of each chunk shape dispatch at the SAME lockstep
    site (train.py picks per config), so they share a beat_group: their
    explicitly-staged collective order must be identical or a pod mixing
    configs would fork."""
    from distributed_ddpg_tpu.analysis.programs import (
        BuiltProgram,
        ProgramSpec,
        probe_config,
        probe_mesh,
    )

    OWNER = "parallel/learner.py"
    cache: Dict[tuple, ShardedLearner] = {}

    def learner(
        guard: bool = False, sharded: bool = False, tp: bool = False
    ) -> ShardedLearner:
        key = (guard, sharded, tp)
        if key not in cache:
            cache[key] = ShardedLearner(
                probe_config(guardrails=guard, model_axis=2 if tp else 1),
                obs_dim=3,
                act_dim=1,
                action_scale=np.ones(1, np.float32),
                mesh=probe_mesh(2 if tp else 1),
                chunk_size=2,
                replay_sharding="sharded" if sharded else "replicated",
            )
        return cache[key]

    def storage_for(L: ShardedLearner):
        width = 2 * L.obs_dim + L.act_dim + 3  # the packed replay row
        spec = P("data", None) if L._replay_sharded else P(None, None)
        storage = jax.device_put(
            np.zeros((64, width), np.float32), NamedSharding(L.mesh, spec)
        )
        return storage, np.int32(64)

    def hostfed(guard: bool):
        def build():
            L = learner(guard=guard)
            width = 2 * L.obs_dim + L.act_dim + 3
            chunk = jax.device_put(
                np.zeros((L.chunk_size, L.global_batch, width), np.float32),
                L._chunk_sharding,
            )
            if guard:
                return BuiltProgram(
                    L._chunk_step, (L.state, chunk, L._guard), (0, 2)
                )
            return BuiltProgram(L._chunk_step, (L.state, chunk), (0,))
        return build

    def uniform(guard: bool, sharded: bool, tp: bool = False):
        def build():
            L = learner(guard=guard, sharded=sharded, tp=tp)
            storage, size = storage_for(L)
            if guard:
                return BuiltProgram(
                    L._sample_chunk_step,
                    (L.state, L._key, storage, size, L._guard),
                    (0, 1, 4),
                )
            return BuiltProgram(
                L._sample_chunk_step, (L.state, L._key, storage, size),
                (0, 1),
            )
        return build

    def per(guard: bool, sharded: bool, tp: bool = False):
        def build():
            L = learner(guard=guard, sharded=sharded, tp=tp)
            storage, size = storage_for(L)
            prios = jax.device_put(
                np.zeros(64, np.float32),
                NamedSharding(
                    L.mesh, P("data") if L._replay_sharded else P(None)
                ),
            )
            scalars = (np.float32(1.0), np.float32(0.4), np.float32(0.6),
                       np.float32(1e-6))
            if guard:
                return BuiltProgram(
                    L._per_sample_chunk_step,
                    (L.state, L._key, storage, size, prios, *scalars,
                     L._guard),
                    (0, 1, 4, 9),
                )
            return BuiltProgram(
                L._per_sample_chunk_step,
                (L.state, L._key, storage, size, prios, *scalars),
                (0, 1, 4),
            )
        return build

    specs = []
    for guard in (False, True):
        tag = ".guarded" if guard else ""
        specs.extend([
            ProgramSpec(
                f"learner.chunk.hostfed{tag}", OWNER, hostfed(guard),
                beat_group="learner-beat-hostfed",
            ),
            ProgramSpec(
                f"learner.chunk.uniform{tag}", OWNER,
                uniform(guard, sharded=False),
                beat_group="learner-beat-uniform",
            ),
            ProgramSpec(
                f"learner.chunk.per{tag}", OWNER, per(guard, sharded=False),
                beat_group="learner-beat-per",
            ),
            ProgramSpec(
                f"learner.chunk.uniform.sharded{tag}", OWNER,
                uniform(guard, sharded=True),
                beat_group="learner-beat-uniform-sharded",
            ),
            ProgramSpec(
                f"learner.chunk.per.sharded{tag}", OWNER,
                per(guard, sharded=True),
                beat_group="learner-beat-per-sharded",
            ),
        ])
    # TP variants (docs/MESH.md): the same sharded sampling chunks under
    # the (data=2, model=2) probe mesh — the 'data'-axis gather/psum
    # exchange must stay collective-order-stable when params shard on
    # 'model' (the SPMD partitioner's own collectives are downstream of
    # this jaxpr and follow it deterministically). They SHARE the 1D
    # sharded variants' beat_group so the cross-variant order equality
    # is enforced by the group check, not just per-program goldens.
    specs.extend([
        ProgramSpec(
            "learner.chunk.uniform.sharded.tp", OWNER,
            uniform(False, sharded=True, tp=True),
            beat_group="learner-beat-uniform-sharded",
        ),
        ProgramSpec(
            "learner.chunk.per.sharded.tp", OWNER,
            per(False, sharded=True, tp=True),
            beat_group="learner-beat-per-sharded",
        ),
    ])
    return specs
