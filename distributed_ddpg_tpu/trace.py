"""Flight-recorder tracing: a preallocated, lock-light ring of span events
every hot component brackets (the cross-component timeline visibility
Podracer/TorchBeast attribute their scaling wins to — PAPERS.md
arXiv 2104.06272 / 1910.03552).

The system is a five-thread machine — learner loop, ingest shipper,
ChunkPrefetcher, eval worker, checkpoint writer, plus N actor processes —
and point metrics (PhaseTimers means, IngestStats) cannot answer "what was
every thread doing in the seconds before the wedge/regression". This
module answers it cheaply enough to leave ON in production runs:

  - `TraceRecorder`: a fixed-size ring of event tuples. Recording is one
    `perf_counter_ns` + one tuple build + one list-slot store behind a
    GIL-atomic `itertools.count` — no lock on the hot path, no allocation
    growth, old events silently overwritten (that is the flight-recorder
    contract: the LAST window is always available, a run of any length
    never grows memory).
  - `span(name)` / `instant(name)` / `complete(name, t0, dur)`: the
    bracket API. Thread identity is captured per event, so the exported
    timeline separates learner / shipper / prefetcher / eval / saver
    activity into Perfetto tracks.
  - `export(path)`: Chrome trace-event JSON (the `{"traceEvents": [...]}`
    wrapper), loadable in Perfetto (ui.perfetto.dev) or chrome://tracing.
    Exports happen on demand (SIGUSR2 in train.py), on clean exit, and —
    critically — from the watchdog's stall path (watchdog.py), so every
    hang ships the last-N-seconds timeline next to the stack dump.
  - `stall_report(...)`: the structured stall artifact: thread list with
    stacks as JSON (machine-parseable, unlike the faulthandler dump) plus
    the trace tail.

Enablement: module-level singleton, off by default (every `span()` is then
a shared no-op context manager — the <2% overhead guard in test_trace.py
holds for the ENABLED path; disabled is nanoseconds). train_jax enables it
when `config.trace_dir` is set; actor worker processes (separate
interpreters) enable their own recorder and export per-process files that
Perfetto merges by pid.

Consistency note: the ring index is advanced atomically but slot writes
are not fenced against concurrent export — an export racing a writer can
see a slot from either side of the wrap. Exports sort by timestamp and
tolerate a torn tail; this is diagnostics, not accounting.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

# Event kinds (Chrome trace "ph" phases we emit).
_SPAN = "X"      # complete event: ts + dur
_INSTANT = "i"   # instant event: ts only


class _Span:
    """Reusable-shape span context manager: records ONE complete event at
    exit (one ring slot per span, not a begin/end pair — halves ring
    pressure and keeps export trivially well-formed)."""

    __slots__ = ("_rec", "_name", "_args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, args):
        self._rec = rec
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        self._rec._record(
            _SPAN, self._name, self._t0, t1 - self._t0, self._args
        )
        return False


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class TraceRecorder:
    def __init__(self, capacity: int = 65_536):
        if capacity < 16:
            raise ValueError(f"capacity must be >= 16, got {capacity}")
        self.capacity = int(capacity)
        # Preallocated slots. Each holds a tuple:
        #   (ph, name, t_ns, dur_ns, thread_name, thread_id, args|None)
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._n = itertools.count()          # GIL-atomic slot allocator
        self._t0_ns = time.perf_counter_ns() # export time origin
        # Thread identity cached per thread: current_thread() each event
        # would be ~10% of the span budget (the <2% overhead guard).
        self._tl = threading.local()
        # Wall-clock anchor for correlating trace timestamps with JSONL
        # wall_time / log lines — and for re-basing N per-host traces
        # onto one timeline (tools.runs merge-trace): absolute wall time
        # of any event is wall_t0 + ts/1e6.
        self._wall_t0 = time.time()
        # Caller-attached export metadata (set_meta): the multi-host
        # clock handshake lands its per-host offsets here so the merge
        # tool can correct cross-host wall-clock skew.
        self._meta: Dict[str, Any] = {}
        self._meta_lock = threading.Lock()

    def set_meta(self, **kv: Any) -> None:
        """Attach key/values to the export's otherData block (merged over
        the defaults). JSON-serializable values only."""
        with self._meta_lock:
            self._meta.update(kv)

    # --- recording (hot path) ---

    def _record(self, ph: str, name: str, t_ns: int, dur_ns: int, args) -> None:
        tl = self._tl
        try:
            tname, tid = tl.info
        except AttributeError:
            t = threading.current_thread()
            tname, tid = tl.info = (t.name, t.ident)
        self._buf[next(self._n) % self.capacity] = (
            ph, name, t_ns, dur_ns, tname, tid, args
        )

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        self._record(_INSTANT, name, time.perf_counter_ns(), 0, args or None)

    def complete(self, name: str, start_s: float, dur_s: float, **args) -> None:
        """Record a span from explicit perf_counter()-based times — for
        sites that already measured a wait/stall and only want to log it
        when it actually happened (e.g. ingest backpressure)."""
        self._record(
            _SPAN, name, int(start_s * 1e9), int(dur_s * 1e9), args or None
        )

    # --- export ---

    def events(self, window_s: Optional[float] = None) -> List[Dict[str, Any]]:
        """Chrome trace-event dicts, oldest first. `window_s` keeps only
        events ENDING within the last `window_s` seconds — the stall path's
        "what led up to the wedge" view."""
        n = next(self._n)  # burns one slot index; harmless (diagnostics)
        live = min(n, self.capacity)
        raw = [e for e in self._buf[:live] if e is not None]
        raw.sort(key=lambda e: e[2])
        if window_s is not None:
            cutoff = time.perf_counter_ns() - int(window_s * 1e9)
            raw = [e for e in raw if e[2] + e[3] >= cutoff]
        pid = os.getpid()
        out: List[Dict[str, Any]] = []
        seen_tids = {}
        for ph, name, t_ns, dur_ns, tname, tid, args in raw:
            if tid not in seen_tids:
                seen_tids[tid] = tname
            ev: Dict[str, Any] = {
                "name": name,
                "ph": ph,
                "pid": pid,
                "tid": tid,
                "ts": (t_ns - self._t0_ns) / 1e3,  # microseconds
            }
            if ph == _SPAN:
                ev["dur"] = dur_ns / 1e3
            else:
                ev["s"] = "t"  # instant scope: thread
            if args:
                ev["args"] = dict(args)
            out.append(ev)
        # Thread-name metadata so Perfetto labels tracks "learner",
        # "ingest-ship", "prefetch", ... instead of bare thread ids.
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
            for tid, tname in seen_tids.items()
        ]
        return meta + out

    def export(self, path: str, window_s: Optional[float] = None) -> int:
        """Write Chrome trace JSON; returns the number of events written.
        Parent directories are created; failures raise (callers on crash
        paths wrap in try/except — see watchdog.py)."""
        events = self.events(window_s=window_s)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with self._meta_lock:
            meta = dict(self._meta)
        with open(path, "w") as f:
            json.dump(
                {
                    "traceEvents": events,
                    "displayTimeUnit": "ms",
                    "otherData": {
                        "wall_t0": self._wall_t0,
                        "pid": os.getpid(),
                        "argv": " ".join(sys.argv[:6]),
                        **meta,
                    },
                },
                f,
            )
        return len(events)


# ---------------------------------------------------------------------------
# Module-level singleton: the recorder every subsystem brackets against.
# Off by default; `configure()` turns it on (train.py, worker.py, tests).
# ---------------------------------------------------------------------------

_recorder: Optional[TraceRecorder] = None


def configure(capacity: int = 65_536) -> TraceRecorder:
    """Enable tracing process-wide (idempotent: reconfiguring replaces the
    ring, so tests get a fresh one)."""
    global _recorder
    _recorder = TraceRecorder(capacity=capacity)
    return _recorder


def disable() -> None:
    global _recorder
    _recorder = None


def enabled() -> bool:
    return _recorder is not None


def get() -> Optional[TraceRecorder]:
    return _recorder


def span(name: str, **args):
    r = _recorder
    if r is None:
        return _NULL_SPAN
    return r.span(name, **args)


def instant(name: str, **args) -> None:
    r = _recorder
    if r is not None:
        r.instant(name, **args)


def complete(name: str, start_s: float, dur_s: float, **args) -> None:
    r = _recorder
    if r is not None:
        r.complete(name, start_s, dur_s, **args)


def export(path: str, window_s: Optional[float] = None) -> int:
    """Export the singleton's ring; 0 events (and no file) when disabled."""
    r = _recorder
    if r is None:
        return 0
    return r.export(path, window_s=window_s)


def set_meta(**kv) -> None:
    """Attach otherData metadata to the singleton's exports (no-op while
    disabled) — the clock-handshake / process-identity hook."""
    r = _recorder
    if r is not None:
        r.set_meta(**kv)


def install_signal_export(path: str) -> bool:
    """Install a SIGUSR2 handler that exports the singleton's ring to
    `path` — the live-run timeline poke (train.py arms it alongside the
    watchdog; the /trace endpoint is the network sibling). Returns True
    when installed; False on platforms without SIGUSR2 or off the main
    thread (embedded callers), where signals cannot be installed. The
    handler never raises: a read-only diagnostic poke must not crash the
    healthy run it inspects."""
    import signal as _signal

    if not hasattr(_signal, "SIGUSR2"):
        return False

    def _export_on_signal(*_):
        try:
            export(path)
        except Exception as e:
            print(f"[trace] SIGUSR2 export failed: {e!r}",
                  file=sys.stderr, flush=True)

    try:
        _signal.signal(_signal.SIGUSR2, _export_on_signal)
    except ValueError:
        return False  # not on the main thread
    return True


# ---------------------------------------------------------------------------
# Stall artifacts (the watchdog's structured crash report)
# ---------------------------------------------------------------------------

STALL_REPORT = "stall_report.json"
STALL_TRACE = "stall_trace.json"


def thread_stacks() -> List[Dict[str, Any]]:
    """Every live thread's stack as structured JSON (the machine-parseable
    complement to faulthandler's stderr dump)."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        out.append(
            {
                "ident": ident,
                "name": t.name if t else f"<unknown-{ident}>",
                "daemon": bool(t.daemon) if t else None,
                "stack": [
                    f"{fs.filename}:{fs.lineno} {fs.name}: {fs.line or ''}"
                    for fs in traceback.extract_stack(frame)
                ],
            }
        )
    return out


def stall_report(
    directory: str,
    reason: str,
    timeout_s: float = 0.0,
    window_s: float = 30.0,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, str]:
    """Write `stall_report.json` (+ `stall_trace.json` when tracing is on)
    into `directory`. Returns {artifact: path}. Never raises — this runs on
    the crash path, where a secondary failure must not mask the stall dump
    (each artifact is attempted independently)."""
    paths: Dict[str, str] = {}
    try:
        os.makedirs(directory, exist_ok=True)
    except Exception:
        return paths
    trace_path = os.path.join(directory, STALL_TRACE)
    n_events = 0
    try:
        n_events = export(trace_path, window_s=window_s)
        if n_events:
            paths["trace"] = trace_path
    except Exception:
        pass
    report_path = os.path.join(directory, STALL_REPORT)
    try:
        report = {
            "reason": reason,
            "timeout_s": timeout_s,
            "wall_time": time.time(),
            "pid": os.getpid(),
            "argv": sys.argv,
            "threads": thread_stacks(),
            "trace_events": n_events,
            "trace_path": paths.get("trace"),
            **(extra or {}),
        }
        with open(report_path, "w") as f:
            json.dump(report, f, indent=1)
        paths["report"] = report_path
    except Exception:
        pass
    return paths
