"""Adaptive ingest-coalesce controller (docs/TRANSFER.md).

`config.ingest_coalesce` was a static cap on how many staged blocks fold
into one super-block ship (replay/device.py). The right value depends on
the actor:learner throughput ratio, which varies per env, per host, and
over a run's lifetime (ROADMAP: "an adaptive controller — grow k while
ingest_queue_rows trends up, shrink when stall appears — would self-tune
across actor:learner throughput ratios"). This controller owns the
EFFECTIVE cap, a power of two in [1, hi]:

  - GROW (x2) when, after a ship, the staging queue still holds at least
    one full super-block at the current cap — inflow is outpacing the
    dispatch cadence, so bigger super-blocks amortize better.
  - SHRINK (/2) when a full-cap ship's per-block dispatch time blows past
    `stall_ratio` x the EWMA — a dispatch stall (backend congestion, a
    competing transfer class, host memory pressure) means smaller ships
    release the bus sooner and interleave better.

Correctness does not depend on the cap sequence: the coalesced scatter
lands every row at exactly the serial sequence's position for ANY k
(replay/device.py `_coalesce_k` invariant), so the controller can only
change WHEN rows land, never WHERE — the adaptive parity tests in
tests/test_ingest_pipeline.py assert storage stays bit-identical to the
serial reference under an adversarially jittered cap.

Multi-host note: lockstep `sync_ship` derives its k sequence from an
all-gathered minimum and must be identical on every process, while this
controller is driven by process-LOCAL wall-clock timings — so it applies
ONLY to single-process shipping paths; the collective path keeps the
static cap (replay/device.py).
"""

from __future__ import annotations

import threading
from typing import Dict


class AdaptiveCoalesce:
    def __init__(
        self,
        hi: int,
        block_size: int,
        lo: int = 1,
        stall_ratio: float = 3.0,
        ewma_alpha: float = 0.2,
    ):
        if lo < 1 or hi < lo:
            raise ValueError(f"need 1 <= lo <= hi, got lo={lo} hi={hi}")
        self._lo = 1 << (int(lo).bit_length() - 1)
        self._hi = 1 << (int(hi).bit_length() - 1)
        self._block = int(block_size)
        self._ratio = float(stall_ratio)
        self._alpha = float(ewma_alpha)
        # Start at the floor and earn headroom from observed backlog: the
        # first ships after a quiet period stay small (short bus holds),
        # and a sustained flood reaches the ceiling in log2(hi) ships.
        self._cap = self._lo
        self._ewma_per_block = 0.0
        self.grows = 0
        self.shrinks = 0
        self._lock = threading.Lock()

    def cap(self) -> int:
        """Current effective max_coalesce (power of two in [lo, hi])."""
        return self._cap

    def observe_ship(self, blocks: int, ship_s: float, queue_rows: int) -> None:
        """Feed one completed ship: blocks coalesced, dispatch wall time,
        and the staging-queue depth AFTER the pop. Called from whichever
        thread shipped (scheduler or inline); cheap and lock-tight."""
        if blocks <= 0:
            return
        per_block = ship_s / blocks
        with self._lock:
            prev = self._ewma_per_block
            self._ewma_per_block = (
                per_block
                if prev == 0.0
                else (1.0 - self._alpha) * prev + self._alpha * per_block
            )
            if (
                prev > 0.0
                and per_block > self._ratio * prev
                and self._cap > self._lo
            ):
                # Dispatch stall: back off before growing again.
                self._cap >>= 1
                self.shrinks += 1
            elif (
                queue_rows >= self._cap * self._block
                and self._cap < self._hi
            ):
                # Backlog still holds a full next-size super-block: grow.
                self._cap <<= 1
                self.grows += 1

    def snapshot(self) -> Dict[str, int]:
        """The adaptive-trajectory observability fields riding the
        transfer_* family (cap is a gauge; grows/shrinks cumulative)."""
        return {
            "transfer_coalesce_cap": self._cap,
            "transfer_coalesce_grows": self.grows,
            "transfer_coalesce_shrinks": self.shrinks,
        }
