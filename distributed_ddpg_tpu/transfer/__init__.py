"""Unified host<->device transfer scheduling (docs/TRANSFER.md).

One subsystem owns every host<->device stream the trainer produces —
inbound replay ingest super-blocks, outbound chunk-prefetch h2d, learner
params/metrics d2h, policy-inference batch dispatches (the `serve` class;
serve/, docs/SERVING.md), and the multi-host lockstep ingest collective —
replacing the two private per-component threads (the `_IngestShipper` in
replay/device.py and the `ChunkPrefetcher`'s inline `device_put`) that
previously competed blindly for h2d bandwidth.

  - scheduler.TransferScheduler: the single dispatch thread + prioritized
    work classes with fair bandwidth balancing.
  - adaptive.AdaptiveCoalesce: the ingest_coalesce controller (grow while
    the staging queue trends up, shrink when dispatch stall appears).
  - hostbuf.HostBufferPool: reusable staged host buffers for super-block
    device_put, fenced on the consuming insert's output.
"""

from distributed_ddpg_tpu.transfer.adaptive import AdaptiveCoalesce
from distributed_ddpg_tpu.transfer.hostbuf import HostBufferPool
from distributed_ddpg_tpu.transfer.scheduler import (
    TransferError,
    TransferScheduler,
    TransferTicket,
)

__all__ = [
    "AdaptiveCoalesce",
    "HostBufferPool",
    "TransferError",
    "TransferScheduler",
    "TransferTicket",
]
