"""The unified host<->device transfer scheduler (docs/TRANSFER.md).

Podracer-style TPU architectures (PAPERS.md arXiv 2104.06272) and
TorchBeast's actor->learner ingest (arXiv 1910.03552) draw their
throughput from the same discipline: treat host<->device transfer as ONE
scheduled resource overlapping compute, instead of letting each component
own a private thread that competes blindly for the bus. Before this
module the repo had exactly that anti-pattern — `_IngestShipper`
(replay/device.py) and `ChunkPrefetcher` (parallel/prefetch.py) each ran
their own daemon thread and queue, and the PR-3 flight-recorder timelines
(`ingest_ship` / `prefetch_h2d` spans landing back-to-back on separate
tracks) showed them serializing against each other on the transfer
stream with no policy at all.

`TransferScheduler` is one dispatch thread plus prioritized work classes:

  lockstep   multi-host collective beats (background sync_ship + any
             other host-initiated collective). STRICT FIFO and absolute
             priority: every process must execute the identical sequence
             of collectives in the identical order, so these never
             reorder against each other (docs/TRANSFER.md has the token
             protocol).
  ingest     inbound staged-replay super-blocks (h2d + jitted insert).
  prefetch   outbound sampled-chunk h2d (host-replay mode).
  serve      policy-inference batch dispatches (serve/; docs/SERVING.md):
             the obs-batch h2d + policy apply + action d2h of one
             dynamic-batched inference call. Byte-fair alongside
             ingest/prefetch — serving traffic shares the bus under the
             same accounting as training traffic, and can never jump
             ahead of a lockstep collective.
  d2h        learner params/metrics pulls. These are learner-critical
             and synchronous by nature, so they run INLINE on the caller
             thread with absolute priority — the scheduler accounts
             their bytes/latency (they feed the balance bookkeeping and
             the transfer_* observability) without adding queueing
             latency to the hot path.

Between `ingest`, `prefetch`, and `serve` the scheduler start-time
fair-queues by bytes (virtual-time per class, weight-scaled): under an
ingest flood a newly arrived prefetch or serve item is picked as soon as
the in-flight item finishes, and vice versa — no stream can starve
another by more than one item's dispatch time (tests/test_transfer.py
pins the bound).
A class idle for a long stretch re-enters at the current virtual time,
so it cannot bank unbounded credit and then starve everyone else.

Failure contract (mirrors `_IngestShipper`): an exception thrown by a
work item lands in that item's ticket (the submitter's problem — replay
ingest turns it into its bounded-restart/IngestError path); an exception
in the scheduler LOOP itself (including an injected
`transfer:dispatch:crash@k` fault, faults.py) kills the thread, which
restarts itself up to `max_restarts` times (`transfer_restarts` counter,
`transfer_restart` trace instant) — within the budget the crash is
TRANSPARENT to submitters: the not-yet-executed in-flight item returns
to the head of its queue and runs on the restarted thread (no prefetch
worker or lockstep beat dies because the scheduler hiccuped). Past the
budget the scheduler declares itself dead and every pending and future
ticket raises `TransferError`.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.metrics import TransferStats

# Work classes. Order here is documentation only; scheduling policy is
# lockstep-first, then byte-fair between ingest/prefetch/serve, d2h inline.
LOCKSTEP = "lockstep"
INGEST = "ingest"
PREFETCH = "prefetch"
SERVE = "serve"
D2H = "d2h"
# Sharded-replay beat exchanges (replay_sharding='sharded';
# docs/REPLAY_SHARDING.md): an ORDERED item type that shares the lockstep
# lane's deque — strict FIFO ACROSS both classes (a shard-exchange beat
# and a plain lockstep collective must never reorder against each other:
# both are global device programs whose per-process issue order is the
# pod's correctness invariant) — and the same pod-deadline wrap, but its
# own transfer_shard_exchange_* accounting so exchange cost is visible
# next to ordinary beats.
SHARD_EXCHANGE = "shard_exchange"

_QUEUED_CLASSES = (LOCKSTEP, INGEST, PREFETCH, SERVE, SHARD_EXCHANGE)
# Classes sharing the strict-FIFO ordered lane (one deque, LOCKSTEP's).
_ORDERED_CLASSES = (LOCKSTEP, SHARD_EXCHANGE)
_FAIR_CLASSES = (INGEST, PREFETCH, SERVE)


class TransferError(RuntimeError):
    """The transfer scheduler thread is dead (restart budget exhausted) —
    the original exception rides along as __cause__, mirroring
    replay.device.IngestError's surfacing discipline."""


class TransferTicket:
    """Completion handle for one submitted work item. `result()` returns
    the item's return value, re-raises the item's exception, or raises
    TransferError if the scheduler died before the item ran."""

    __slots__ = ("label", "_done", "_result", "_exc")

    def __init__(self, label: str = ""):
        self.label = label
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def _finish(self, result=None, exc: Optional[BaseException] = None) -> None:
        self._result = result
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block up to `timeout` for completion; True when done. Unlike
        result(), never raises — the stop-responsive polling wait for
        callers that must keep checking their own shutdown flags."""
        return self._done.wait(timeout)

    @property
    def exception(self) -> Optional[BaseException]:
        return self._exc if self._done.is_set() else None

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"transfer item {self.label or '<unnamed>'} not done "
                f"within {timeout}s"
            )
        if self._exc is not None:
            raise self._exc
        return self._result


class _Item:
    __slots__ = ("cls", "fn", "nbytes", "ticket")

    def __init__(self, cls: str, fn: Callable, nbytes: int, ticket: TransferTicket):
        self.cls = cls
        self.fn = fn
        self.nbytes = int(nbytes)
        self.ticket = ticket


class TransferScheduler:
    def __init__(
        self,
        stats: Optional[TransferStats] = None,
        fault=None,
        max_restarts: int = 3,
        weights: Optional[Dict[str, float]] = None,
        lockstep_timeout_s: float = 0.0,
    ):
        self.stats = stats or TransferStats()
        # Chaos harness (faults.py): ticked once per dequeued item, OUTSIDE
        # the per-item try — transfer:dispatch:crash@k therefore kills the
        # scheduler THREAD (the bounded-restart path under test), while a
        # work item's own exception only fails its ticket.
        self._fault = fault
        # Pod collective deadline (parallel/multihost.call_with_deadline;
        # docs/RESILIENCE.md pod rows): every LOCKSTEP item — multi-host
        # collective beats — is bounded by this many seconds, so a beat
        # whose peer died surfaces as a typed PodPeerLost in its ticket
        # (in-flight lockstep tickets FAIL, they never hang) instead of
        # wedging the lane forever. 0 = off (single-process runs pay
        # zero overhead — the wrapper short-circuits).
        self._lockstep_timeout_s = float(lockstep_timeout_s)
        self._max_restarts = int(max_restarts)
        self.restarts = 0
        self._cv = threading.Condition()
        # SHARD_EXCHANGE items enqueue into the LOCKSTEP deque (see the
        # class-constant note): one ordered lane, two accounted classes.
        self._queues: Dict[str, deque] = {
            c: deque() for c in (LOCKSTEP,) + _FAIR_CLASSES
        }
        # Start-time fair queuing state: per-class virtual time advanced by
        # bytes/weight on dispatch; an empty class re-enters at the global
        # virtual time so idle periods never bank starvation-scale credit.
        self._weights = {c: 1.0 for c in _FAIR_CLASSES}
        self._weights.update(weights or {})
        self._vt = {c: 0.0 for c in _FAIR_CLASSES}
        self._global_vt = 0.0
        self._stop = False
        self._dead_exc: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle ---

    def start(self) -> "TransferScheduler":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="transfer-sched"
        )
        self._thread.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        """Stop the dispatch thread. Queued-but-undispatched tickets fail
        with TransferError BEFORE the join — close() must not execute
        stale work (a queued lockstep beat run at teardown would fire a
        collective against a cluster that may already be gone); only the
        single in-flight item (if any) runs to completion. Submitters
        that need their items landed must flush() first."""
        with self._cv:
            self._stop = True
        self._fail_pending(TransferError("transfer scheduler closed"))
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        # A straggler that raced the stop flag (submitted between the
        # fail and the join) still gets failed, not stranded.
        self._fail_pending(TransferError("transfer scheduler closed"))

    def flush(self, timeout: float = 30.0) -> None:
        """Block until every currently queued item has been dispatched
        (their tickets resolved, successfully or not)."""
        deadline = time.monotonic() + timeout
        tickets = []
        with self._cv:
            for q in self._queues.values():
                tickets.extend(item.ticket for item in q)
        for t in tickets:
            t._done.wait(max(0.0, deadline - time.monotonic()))

    @property
    def alive(self) -> bool:
        return (
            self._thread is not None
            and self._thread.is_alive()
            and self._dead_exc is None
        )

    # --- submission ---

    def submit(
        self, cls: str, fn: Callable, nbytes: int = 0, label: str = ""
    ) -> TransferTicket:
        """Queue one transfer work item; returns its ticket. An INGEST
        callable may return an int to report the actual bytes moved (the
        size is unknown at submit time under coalescing); other classes'
        return values are payloads delivered through the ticket."""
        if cls not in _QUEUED_CLASSES:
            raise ValueError(f"unknown transfer class {cls!r}")
        ticket = TransferTicket(label or cls)
        with self._cv:
            if self._dead_exc is not None:
                raise TransferError(
                    "transfer scheduler thread died"
                ) from self._dead_exc
            if self._stop:
                raise TransferError("transfer scheduler closed")
            q = self._queues[LOCKSTEP if cls == SHARD_EXCHANGE else cls]
            if cls in self._vt and not q:
                # Class re-enters the fair queue at the current virtual
                # time (see module docstring).
                self._vt[cls] = max(self._vt[cls], self._global_vt)
            q.append(_Item(cls, fn, nbytes, ticket))
            self.stats.record_queue_depth(cls, len(q))
            self._cv.notify_all()
        return ticket

    def run_ordered(self, fn: Callable, label: str = "", timeout: float = 600.0):
        """Execute `fn` on the scheduler thread in the LOCKSTEP lane and
        wait for its result. Multi-host callers route every host-initiated
        collective outside jitted chunk dispatch through here so all
        processes execute the identical collective sequence in the
        identical order (docs/TRANSFER.md token protocol)."""
        return self.submit(LOCKSTEP, fn, label=label).result(timeout=timeout)

    def run_inline(self, cls: str, fn: Callable, nbytes_of=None, label: str = ""):
        """Execute `fn` on the CALLER's thread, accounting it as transfer
        traffic of class `cls` (learner-critical d2h: absolute priority,
        zero queueing latency, full observability)."""
        t0 = time.perf_counter()
        with trace.span(f"transfer_{cls}", label=label):
            result = fn()
        nbytes = int(nbytes_of(result)) if nbytes_of is not None else 0
        self.stats.record_dispatch(cls, nbytes, time.perf_counter() - t0)
        return result

    def queue_depths(self) -> Dict[str, int]:
        with self._cv:
            return {c: len(q) for c, q in self._queues.items()}

    def snapshot(self) -> Dict[str, float]:
        """The transfer_* observability fields (metrics.TransferStats),
        including current queue depths and the cumulative restart count."""
        return self.stats.snapshot(
            queue_depths=self.queue_depths(), restarts=self.restarts
        )

    # --- dispatch loop ---

    def _pick_locked(self) -> Optional[_Item]:
        if self._queues[LOCKSTEP]:
            return self._queues[LOCKSTEP].popleft()
        backlogged = [c for c in _FAIR_CLASSES if self._queues[c]]
        if not backlogged:
            return None
        cls = min(backlogged, key=lambda c: self._vt[c])
        return self._queues[cls].popleft()

    def _charge(self, cls: str, nbytes: int) -> None:
        if cls in self._vt:
            # Floor of one unit per item so zero-byte probes still rotate.
            self._vt[cls] += max(nbytes, 1) / self._weights.get(cls, 1.0)
            self._global_vt = self._vt[cls]

    def _run(self) -> None:
        item: Optional[_Item] = None
        try:
            while True:
                with self._cv:
                    item = self._pick_locked()
                    while item is None and not self._stop:
                        self._cv.wait(0.1)
                        item = self._pick_locked()
                    if item is None and self._stop:
                        return
                if self._fault is not None:
                    self._fault.tick()
                self._dispatch(item)
                item = None  # completed: never requeued by a later crash
        except BaseException as e:
            self._on_thread_death(e, item)

    def _dispatch(self, item: _Item) -> None:
        t0 = time.perf_counter()
        try:
            with trace.span(f"transfer_{item.cls}", label=item.ticket.label):
                if item.cls in _ORDERED_CLASSES and self._lockstep_timeout_s > 0:
                    from distributed_ddpg_tpu.parallel import multihost

                    ret = multihost.call_with_deadline(
                        item.fn,
                        timeout_s=self._lockstep_timeout_s,
                        label=item.ticket.label or "lockstep",
                    )
                else:
                    ret = item.fn()
        except BaseException as e:  # the submitter's problem, not ours
            self.stats.record_dispatch(
                item.cls, item.nbytes, time.perf_counter() - t0
            )
            self._charge(item.cls, item.nbytes)
            item.ticket._finish(exc=e)
            return
        # Ingest items report the bytes they moved via their return value
        # (the size is unknown at submit time — coalescing). ONLY the
        # ingest class gets this reading: other classes' integer results
        # are payloads (a lockstep beat returns rows moved, run_ordered
        # returns arbitrary values like env-step sums), not byte counts.
        nbytes = (
            int(ret)
            if item.cls == INGEST and item.nbytes == 0
            and isinstance(ret, (int, float)) and not isinstance(ret, bool)
            else item.nbytes
        )
        self.stats.record_dispatch(item.cls, nbytes, time.perf_counter() - t0)
        self._charge(item.cls, nbytes)
        item.ticket._finish(result=ret)

    def _on_thread_death(self, exc: BaseException, item: Optional[_Item]) -> None:
        """The scheduler loop itself died (injected fault or a bug in the
        pick/wait machinery — every such crash point sits BEFORE the
        item's callable runs; _dispatch catches around the callable, and
        a completed item is nulled before the next pick). Within the
        restart budget the crash is therefore transparent to submitters:
        the in-flight item goes back to the head of its queue and the
        thread restarts. Past the cap the failure is structural and every
        waiter must see it."""
        if self.restarts < self._max_restarts and not self._stop:
            self.restarts += 1
            trace.instant("transfer_restart", n=self.restarts)
            print(
                f"[transfer] scheduler thread died ({exc!r}); restarting "
                f"({self.restarts}/{self._max_restarts})",
                file=sys.stderr, flush=True,
            )
            with self._cv:
                if item is not None and not item.ticket.done():
                    self._queues[
                        LOCKSTEP if item.cls == SHARD_EXCHANGE else item.cls
                    ].appendleft(item)
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="transfer-sched"
            )
            self._thread.start()
            return
        if item is not None and not item.ticket.done():
            item.ticket._finish(exc=exc)
        with self._cv:
            self._dead_exc = exc
        self._fail_pending(
            TransferError("transfer scheduler thread died"), cause=exc
        )

    def _fail_pending(self, err: TransferError, cause=None) -> None:
        if cause is not None:
            err.__cause__ = cause
        with self._cv:
            items = [i for q in self._queues.values() for i in q]
            for q in self._queues.values():
                q.clear()
        for i in items:
            if not i.ticket.done():
                i.ticket._finish(exc=err)
