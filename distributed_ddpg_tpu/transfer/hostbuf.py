"""Staged host-buffer pool for super-block device_put (docs/TRANSFER.md).

The ingest path used to materialize every super-block with a fresh
`np.empty` (`HostStagingRing.pop`'s owned copy) and hand that pageable
allocation to `jax.device_put`. On TPU hosts the runtime then stages the
pageable pages into its own transfer buffer — a copy that lands inside
`ingest_ship_ms` on the dispatching thread (ROADMAP: "Staged super-block
device_put goes through pageable host memory; a pinned-buffer pool ...
would cut the host-side copy out of ingest_ship_ms").

`HostBufferPool` keeps a small set of long-lived buffers per super-block
shape (the power-of-two coalesce sizes give a bounded key set) and
recycles them double-buffered:

  acquire(rows)            -> a writable [rows, width] float32 buffer
  commit(buf, fence)       -> returns the buffer to the pool; it is not
                              handed out again until `fence` (a device
                              array produced by the op that CONSUMED the
                              transferred data — replay uses the insert's
                              output `size` scalar) reports ready.

Fencing on the consumer's OUTPUT — not on the device_put result — makes
reuse safe even when the backend aliases host memory zero-copy (dlpack
or CPU fast paths): the buffer only recirculates after the insert that
read it has executed. On backends that copy eagerly the fence is already
satisfied by the time the next ship needs the buffer, so steady state
never blocks and never allocates.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Tuple

import numpy as np


class HostBufferPool:
    def __init__(self, width: int, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.width = int(width)
        self.depth = int(depth)
        self._lock = threading.Lock()
        self._free: Dict[int, deque] = {}
        # rows -> deque of (buf, fence) awaiting their consumer.
        self._inflight: Dict[int, deque] = {}
        self._allocated: Dict[int, int] = {}
        self.allocations = 0
        self.fence_waits = 0

    def acquire(self, rows: int) -> np.ndarray:
        """A writable [rows, width] float32 buffer. Recycles a free one,
        allocates while under `depth` buffers for this shape, else blocks
        on the oldest in-flight fence (classic double buffering)."""
        rows = int(rows)
        fence_entry = None
        with self._lock:
            free = self._free.setdefault(rows, deque())
            if free:
                return free.popleft()
            inflight = self._inflight.setdefault(rows, deque())
            if self._allocated.get(rows, 0) < self.depth or not inflight:
                # Under depth, OR every pooled buffer for this shape was
                # lost (a caller that failed between acquire and commit):
                # allocate rather than crash — a leak degrades to the
                # unpooled behavior, it must never mask the real error.
                self._allocated[rows] = self._allocated.get(rows, 0) + 1
                self.allocations += 1
                return np.empty((rows, self.width), np.float32)
            fence_entry = inflight.popleft()
        # Wait OUTSIDE the lock: the fence completes on the device stream
        # regardless of host locks, and commit() must stay callable.
        buf, fence = fence_entry
        self.fence_waits += 1
        _wait_fence(fence)
        return buf

    def commit(self, buf: np.ndarray, fence) -> None:
        """Return `buf` to the pool, gated on `fence` (any object with
        block_until_ready/is_ready, or None for an immediate return)."""
        rows = buf.shape[0]
        with self._lock:
            if fence is None:
                self._free.setdefault(rows, deque()).append(buf)
            else:
                self._inflight.setdefault(rows, deque()).append((buf, fence))

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "transfer_pool_buffers": sum(self._allocated.values()),
                "transfer_pool_fence_waits": self.fence_waits,
            }


def _wait_fence(fence) -> None:
    """Block until a device array is safe to overwrite its source for —
    i.e. its producing computation (which consumed the host buffer) has
    executed. Tolerates deleted/donated arrays and foreign objects: a
    fence that cannot be queried is treated as already satisfied (the
    conservative direction for copying backends, the only ones that can
    produce such a fence)."""
    try:
        fence.block_until_ready()
    except Exception:
        pass
