"""Checkpoint / resume via orbax (SURVEY.md §3.5, §5 'Checkpoint / resume').

The reference checkpoints only the parameter-server variables through
`tf.train.Saver`; replay contents are lost on restart (SURVEY.md §3.5).
Here a checkpoint is the COMPLETE learner-side state:
  - TrainState (params, targets, both Adam states, step counter),
  - the host replay buffer (via its state_dict — uniform or PER, including
    priorities), so a restored run resumes the same data distribution,
  - the config (for a mismatch warning on restore).

Saves go through a throwaway directory + atomic rename via orbax's own
finalization, and happen off the hot loop (call cadence is
config.checkpoint_every).

Robustness (docs/RESILIENCE.md): every successful save also writes
`manifest_<step>.json` — per-file sizes + a cheap head/tail crc32 — so
restore can verify a checkpoint BEFORE handing it to orbax. Writes retry
with exponential backoff on OSError (`retries=`, wired from
config.ckpt_write_retries; injectable via a faults.FaultSite). Restore
with no explicit step walks the retained checkpoints newest-first and
falls back past any that fail verification or fail to load — a corrupt or
half-written latest checkpoint costs one cadence of progress, not the run.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
import shutil
import sys
import time
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.types import TrainState


# Fields that must match between a checkpoint and the run restoring it —
# shapes/semantics of the restored state depend on them. (orbax restores the
# CHECKPOINT's shapes regardless of the template, so a silent mismatch here
# would surface as a crash or corruption far from the root cause.)
COMPAT_FIELDS = (
    "env_id",
    "actor_hidden",
    "critic_hidden",
    "action_insert_layer",
    "distributional",
    "twin_critic",  # rank-3 ensemble critic leaves vs rank-2 plain ones
    "sac",  # double-width Gaussian head + twin leaves + log_alpha node
    "sac_autotune",  # alpha_opt presence changes the TrainState tree
    "num_atoms",
    "v_min",
    "v_max",
    "prioritized",
    "replay_capacity",
    "n_step",
)


def _snapshot(
    step: int, state: TrainState, replay, env_steps: int,
    v_bounds=None,
) -> Dict[str, Any]:
    """Materialize everything host-side. This is the only part that touches
    device memory; once it returns, the learner is free to mutate/donate
    its state — the write can proceed on any thread."""
    ckpt: Dict[str, Any] = {
        "state": jax.device_get(state),
        "meta": {"env_steps": np.asarray(env_steps, np.int64)},
    }
    if v_bounds is not None:
        # Auto-sized C51 support (config.v_support_auto): the RESOLVED
        # bounds must ride the checkpoint — mean_q-driven expansions are
        # unrecoverable from reward statistics, and restoring the critic's
        # logits over re-derived (smaller) atom values would silently
        # reinterpret every probability as a wrong Q.
        ckpt["meta"]["v_bounds"] = np.asarray(v_bounds, np.float64)
    if replay is not None:
        ckpt["replay"] = replay.state_dict()
    return ckpt


def _checkpointer() -> "ocp.StandardCheckpointer":
    """A StandardCheckpointer whose cross-process barriers are scoped to
    THIS process only. The repo's checkpoint discipline is single-writer
    (train.py: process 0 writes the replicated state; pod aborts add
    per-process emergency dirs — docs/RESILIENCE.md pod rows), so
    orbax's default all-process barrier is wrong twice over: a lone
    writer's `sync_global_devices` is a COLLECTIVE the other processes
    never join, which both wedges the save and interleaves a mismatched
    op into the training pod's lockstep gloo streams (observed as
    `gloo EnforceNotMet op.preamble.length <= op.nbytes` corruption on
    the 3-process chaos harness); and at pod-abort time an all-process
    barrier can never complete — the dead peer is exactly why we are
    checkpointing. Subset barriers (active_processes = {this process})
    keep orbax's atomic-rename machinery intact with zero cross-process
    traffic. Single-process runs keep stock options (every barrier is
    already skipped)."""
    import jax

    if jax.process_count() == 1:
        return ocp.StandardCheckpointer()
    me = jax.process_index()
    mp = ocp.options.MultiprocessingOptions(
        primary_host=me,
        active_processes={me},
        barrier_sync_key_prefix=f"proc{me}",
    )
    # use_ocdbt=False: OCDBT's per-process write + merge machinery also
    # assumes an all-process save (the merge validated a partial world
    # and rejected single-writer saves with "params missing"); the
    # classic per-param layout has no cross-process step at all.
    return ocp.Checkpointer(
        ocp.PyTreeCheckpointHandler(
            use_ocdbt=False, multiprocessing_options=mp
        ),
        multiprocessing_options=mp,
    )


# Checkpoints RETAINED after each successful write (latest N). A
# checkpoint with a full 1M-row replay is ~3 GB; without retention a
# 2M-step Humanoid run at checkpoint_every=10k writes ~200 of them
# (~hundreds of GB) and fills the disk mid-run — observed round 5 at
# 6.4 GB by 340k steps. 3 matches the spirit of the reference family's
# tf.train.Saver default (keep a few, not all): latest for resume, two
# back in case the newest write raced a crash.
KEEP_CHECKPOINTS = 3


def _steps(directory: str):
    """All step numbers present in a checkpoint directory — THE parser for
    the step_N naming scheme, shared by pruning and latest_step so the two
    can never disagree about what exists."""
    return sorted(
        int(name.split("_", 1)[1])
        for name in os.listdir(directory)
        if name.startswith("step_") and name.split("_", 1)[1].isdigit()
    )


def _prune(directory: str, keep: int, current: int) -> None:
    """Delete old step_*/config_*/manifest_* triples, retaining `current`
    (the checkpoint that just landed) plus the newest `keep`-1 steps BELOW
    it. Steps ABOVE current are stale by definition — leftovers of a
    previous run sharing the directory (the --resume=false reuse workflow
    check_config_compatible suggests) or of a diverged timeline a
    guardrail rollback rewound past — and are pruned too, loudly: left in
    place they would permanently occupy the retention slots (every save
    would delete the run's OWN previous checkpoint, losing the keep-1
    crash redundancy) and keep latest_step()/resume pointing at state this
    run never produced. Runs on the writer thread after a successful save;
    best-effort (a failed unlink must not fail the save that just
    landed)."""
    if keep <= 0:
        return
    steps = _steps(directory)
    stale_above = [s for s in steps if s > current]
    below = [s for s in steps if s < current]
    if stale_above:
        print(
            f"[checkpoint] pruning stale checkpoint(s) above the current "
            f"save step_{current}: "
            + ", ".join(f"step_{s}" for s in stale_above)
            + " (previous-run or pre-rollback leftovers — resume must "
            "track THIS run's latest state)",
            file=sys.stderr, flush=True,
        )
    doomed = stale_above + (below[: -(keep - 1)] if keep > 1 else below)
    # Elastic-pod protection: never delete the newest step whose replay
    # slice set is complete (latest_complete_slice_step) — on a pod whose
    # membership shrank, that set is the ONLY recoverable copy of the dead
    # peer's shard, and survivors keep checkpointing learner state past it
    # (slice sets at newer steps stay incomplete until the peer returns).
    protected = latest_complete_slice_step(directory)
    if protected is not None and protected in doomed:
        doomed = [s for s in doomed if s != protected]
    for old in doomed:
        try:
            shutil.rmtree(os.path.join(directory, f"step_{old}"),
                          ignore_errors=True)
            shutil.rmtree(_slice_step_dir(directory, old),
                          ignore_errors=True)
            for side in (f"config_{old}.json", f"manifest_{old}.json"):
                side_path = os.path.join(directory, side)
                if os.path.exists(side_path):
                    os.unlink(side_path)
        except OSError:
            pass


# --- integrity manifest (restore-time verification) -----------------------

# Digest window per file: crc32 over the first and last MiB + the size.
# A full-stream hash of a ~3 GB replay checkpoint would add seconds to
# every save; head+tail+size catches the real-world corruptions (truncated
# write, zeroed header, wrong-length file) at microsecond cost.
_DIGEST_CAP = 1 << 20


def _digest_file(path: str) -> Tuple[int, int]:
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        crc = zlib.crc32(f.read(_DIGEST_CAP))
        if size > _DIGEST_CAP:
            f.seek(max(size - _DIGEST_CAP, _DIGEST_CAP))
            crc = zlib.crc32(f.read(_DIGEST_CAP), crc)
    return size, crc


def _write_manifest(directory: str, step: int) -> None:
    """Record every file under step_<step> with size + head/tail crc32.
    Written AFTER orbax finalizes (the atomic rename), so a manifest's
    existence certifies 'this checkpoint finished writing'; its contents
    let restore detect post-finalize corruption."""
    root = os.path.join(directory, f"step_{step}")
    files: Dict[str, Any] = {}
    for dirpath, _, names in os.walk(root):
        for name in sorted(names):
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            size, crc = _digest_file(full)
            files[rel] = [size, crc]
    path = os.path.join(directory, f"manifest_{step}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"step": step, "files": files}, f)
    os.replace(tmp, path)


def verify_checkpoint(directory: str, step: int) -> Tuple[bool, str]:
    """Cheap integrity check of one retained checkpoint against its
    manifest. Returns (ok, why). A checkpoint written before manifests
    existed verifies as ok ('no manifest') — the orbax restore itself is
    the backstop for those; restore()'s fallback chain catches its
    failure too."""
    directory = os.path.abspath(directory)
    root = os.path.join(directory, f"step_{step}")
    if not os.path.isdir(root):
        return False, "missing checkpoint directory"
    mpath = os.path.join(directory, f"manifest_{step}.json")
    if not os.path.exists(mpath):
        return True, "no manifest (pre-manifest checkpoint)"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        entries = manifest["files"]
    except (OSError, json.JSONDecodeError, KeyError, TypeError) as e:
        return False, f"unreadable manifest: {e!r}"
    for rel, (size, crc) in entries.items():
        full = os.path.join(root, rel)
        try:
            got_size, got_crc = _digest_file(full)
        except OSError:
            return False, f"missing/unreadable file {rel}"
        if got_size != size:
            return False, f"size mismatch {rel}: {got_size} != {size}"
        if got_crc != crc:
            return False, f"digest mismatch {rel}"
    # Per-slice digests (elastic pod): a torn replay-slice write
    # quarantines ONLY that slice — the learner-state step above already
    # verified, and slice adoption has its own fallback chain
    # (latest_complete_slice_step), so a bad slice must never cost the
    # whole step.
    verify_replay_slices(directory, step, quarantine=True)
    return True, "ok"


def _quarantine_corrupt(directory: str, step: int) -> None:
    """Move a verification-failed checkpoint out of the step_N namespace
    (-> corrupt_step_N) so a resumed run that re-reaches step N can write
    a fresh checkpoint there — orbax refuses to overwrite an existing
    destination, and without this the corrupt leftovers would fail every
    later save at that step. Renamed, not deleted: the payload stays on
    disk for forensics. Best-effort (fallback must proceed regardless)."""
    directory = os.path.abspath(directory)
    src = os.path.join(directory, f"step_{step}")
    dst = os.path.join(directory, f"corrupt_step_{step}")
    try:
        if os.path.isdir(dst):
            shutil.rmtree(dst, ignore_errors=True)
        os.rename(src, dst)
        for side in (f"manifest_{step}.json", f"config_{step}.json"):
            side_path = os.path.join(directory, side)
            if os.path.exists(side_path):
                os.unlink(side_path)
        print(
            f"[checkpoint] quarantined corrupt step_{step} -> "
            f"corrupt_step_{step}",
            file=sys.stderr, flush=True,
        )
    except OSError:
        pass


def _write_once(directory: str, step: int, ckpt: Dict[str, Any],
                config: Optional[DDPGConfig],
                keep: int = KEEP_CHECKPOINTS,
                devactor_state: Optional[Dict[str, Any]] = None) -> str:
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    # A leftover directory at this step (a corrupt checkpoint restore
    # skipped, or a prior attempt whose sidecar write failed) would make
    # orbax refuse the save; this writer is the single authority for the
    # step, so clear it.
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
    with _checkpointer() as ckptr:
        ckptr.save(path, ckpt)
    if devactor_state:
        # Device-actor rollout carry (actors/device_pool.carry_state_dict;
        # docs/DEVICE_ACTORS.md): a flat-leaf npz INSIDE the step dir —
        # written after orbax finalizes and before the manifest walk, so
        # the manifest's size+crc verification covers it like every orbax
        # payload file. A sidecar, not an orbax subtree: the carry's tree
        # shape is env/config-dependent, and restore() must be able to
        # read it back BEFORE the pool (hence the template) exists.
        with open(os.path.join(path, "devactor_carry.npz"), "wb") as f:
            np.savez(f, **devactor_state)
    if config is not None:
        # nan (the v_min/v_max auto sentinel) would serialize as the
        # non-RFC bare `NaN` token — unreadable by jq and strict parsers.
        # null keeps the file valid JSON; _compat_eq maps it back.
        fields = {
            k: (None if isinstance(v, float) and math.isnan(v) else v)
            for k, v in dataclasses.asdict(config).items()
        }
        with open(os.path.join(os.path.dirname(path), f"config_{step}.json"), "w") as f:
            json.dump(fields, f, indent=2, default=list)
    _write_manifest(os.path.dirname(path), step)
    _prune(os.path.dirname(path), keep, step)
    return path


def _write(directory: str, step: int, ckpt: Dict[str, Any],
           config: Optional[DDPGConfig], keep: int = KEEP_CHECKPOINTS,
           retries: int = 0, backoff_s: float = 0.5,
           fault=None,
           devactor_state: Optional[Dict[str, Any]] = None) -> Tuple[str, int]:
    """Write with bounded retry + exponential backoff on OSError (full
    disk blips, NFS hiccups, injected ckpt:write:ioerror faults). Returns
    (path, retries_used). `fault` is a faults.FaultSite ticked once per
    ATTEMPT — retries advance the ordinal, so 'ioerror@2' scripts 'the
    second attempt overall fails'."""
    from distributed_ddpg_tpu import trace

    for attempt in range(retries + 1):
        try:
            if fault is not None:
                fault.tick()
            return _write_once(
                directory, step, ckpt, config, keep=keep,
                devactor_state=devactor_state,
            ), attempt
        except OSError as e:
            # A failed attempt may leave a partially-finalized step dir
            # (or a completed dir whose sidecar write failed) — clear it
            # so the retry's orbax save starts clean.
            shutil.rmtree(
                os.path.join(os.path.abspath(directory), f"step_{step}"),
                ignore_errors=True,
            )
            if attempt >= retries:
                raise
            delay = backoff_s * (2.0 ** attempt)
            trace.instant("ckpt_write_retry", step=step,
                          attempt=attempt + 1)
            print(
                f"[checkpoint] write of step_{step} failed ({e!r}); "
                f"retry {attempt + 1}/{retries} in {delay:.2f}s",
                file=sys.stderr, flush=True,
            )
            time.sleep(delay)
    raise AssertionError("unreachable")


def save(
    directory: str,
    step: int,
    state: TrainState,
    replay=None,
    config: Optional[DDPGConfig] = None,
    env_steps: int = 0,
    v_bounds=None,
    keep: int = KEEP_CHECKPOINTS,
    retries: int = 0,
    backoff_s: float = 0.5,
    fault=None,
    devactor_state=None,
) -> str:
    """Write checkpoint `directory/step_N` synchronously. Returns the path.
    `retries`/`backoff_s` bound the OSError retry loop (_write); `fault`
    is an optional faults.FaultSite for the chaos harness.
    `devactor_state` (actors/device_pool.carry_state_dict) rides as the
    devactor_carry.npz sidecar inside the step dir."""
    path, _ = _write(
        directory, step,
        _snapshot(step, state, replay, env_steps, v_bounds=v_bounds),
        config,
        keep=keep,
        retries=retries,
        backoff_s=backoff_s,
        fault=fault,
        devactor_state=devactor_state,
    )
    return path


class AsyncSaver:
    """Checkpointing off the hot loop (SURVEY.md §5 'async save off the hot
    loop'; VERDICT.md round-1 Weak #6). save_async snapshots device state on
    the caller's thread — one HBM->host copy, fast at memory bandwidth —
    and hands serialization + the multi-hundred-MB disk write to a single
    background writer. If the writer is still busy when the next cadence
    fires, that save is SKIPPED (coalesced): a fresher checkpoint is always
    coming, and queueing would grow host memory by a full replay copy per
    backlog entry."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.skipped = 0
        self.errors: list = []
        # Cumulative OSError retries consumed by background writes — the
        # `ckpt_write_retries` recovery counter train.py logs.
        self.write_retries = 0

    @property
    def busy(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def save_async(
        self,
        directory: str,
        step: int,
        state: TrainState,
        replay=None,
        config: Optional[DDPGConfig] = None,
        env_steps: int = 0,
        v_bounds=None,
        keep: int = KEEP_CHECKPOINTS,
        retries: int = 0,
        backoff_s: float = 0.5,
        fault=None,
        devactor_state=None,
    ) -> bool:
        """Snapshot now, write in the background. Returns False (and skips)
        if the previous write is still in flight. `devactor_state` must
        already be host-side numpy (device_pool.carry_state_dict pulls it
        on the caller's thread, same discipline as the state snapshot)."""
        import threading

        with self._lock:
            if self.busy:
                self.skipped += 1
                return False
            ckpt = _snapshot(step, state, replay, env_steps, v_bounds=v_bounds)

            def _run():
                from distributed_ddpg_tpu import trace

                try:
                    with trace.span("ckpt_write", step=step):
                        _, used = _write(
                            directory, step, ckpt, config, keep=keep,
                            retries=retries, backoff_s=backoff_s,
                            fault=fault, devactor_state=devactor_state,
                        )
                    self.write_retries += used
                except Exception as e:  # surfaced via .errors / wait()
                    self.errors.append(e)

            self._thread = threading.Thread(
                target=_run, name=f"ckpt-writer-{step}", daemon=True
            )
            self._thread.start()
            return True

    def wait(self) -> None:
        """Block until the in-flight write (if any) lands; re-raise its
        error if it failed. Call before reading back a checkpoint or at
        shutdown."""
        t = self._thread
        if t is not None:
            t.join()
        if self.errors:
            raise self.errors[-1]


def check_config_compatible(directory: str, step: int, config: DDPGConfig) -> None:
    """Raise ValueError if the checkpoint was written under a config whose
    COMPAT_FIELDS differ from the current run's."""
    path = os.path.join(os.path.abspath(directory), f"config_{step}.json")
    if not os.path.exists(path):
        return
    with open(path) as f:
        saved = json.load(f)
    current = dataclasses.asdict(config)
    mismatches = [
        f"{k}: checkpoint={saved[k]!r} run={_listify(current[k])!r}"
        for k in COMPAT_FIELDS
        if k in saved and not _compat_eq(saved[k], _listify(current[k]))
    ]
    if mismatches:
        raise ValueError(
            f"checkpoint {directory}/step_{step} is incompatible with this "
            "run's config (pass --resume=false or a fresh --checkpoint_dir):\n  "
            + "\n  ".join(mismatches)
        )


def _listify(v):
    return list(v) if isinstance(v, tuple) else v


def _compat_eq(a, b) -> bool:
    # nan == nan for compat purposes: v_min/v_max use nan as the 'auto'
    # sentinel (config.py), and two auto runs ARE compatible — IEEE
    # inequality would reject every auto-support resume. The saved side
    # serializes the sentinel as null (_write), so None matches nan too.
    def _is_auto(v) -> bool:
        return v is None or (isinstance(v, float) and math.isnan(v))

    if _is_auto(a) and _is_auto(b):
        return True
    return a == b


def discard_above(directory: str, step: int) -> list:
    """Quarantine every retained checkpoint NEWER than `step` out of the
    step_N namespace (-> diverged_step_N; sidecars removed so
    latest_step()/valid_steps() stop seeing them, payload kept for
    forensics — the _quarantine_corrupt discipline). The guardrail
    rollback (train.py) calls this right after restoring `step`:
    checkpoints written after the divergence began are poisoned by
    assumption, and a crash landing before the next clean save must
    resume from `step`, not from them. Returns the steps discarded."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return []
    discarded = []
    for s in _steps(directory):
        if s <= step:
            continue
        src = os.path.join(directory, f"step_{s}")
        dst = os.path.join(directory, f"diverged_step_{s}")
        try:
            if os.path.isdir(dst):
                shutil.rmtree(dst, ignore_errors=True)
            os.rename(src, dst)
            for side in (f"manifest_{s}.json", f"config_{s}.json"):
                side_path = os.path.join(directory, side)
                if os.path.exists(side_path):
                    os.unlink(side_path)
            discarded.append(s)
        except OSError:
            pass
    if discarded:
        print(
            "[checkpoint] rollback quarantined diverged checkpoint(s): "
            + ", ".join(f"step_{s} -> diverged_step_{s}" for s in discarded),
            file=sys.stderr, flush=True,
        )
    return discarded


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = _steps(directory)
    return max(steps) if steps else None


def valid_steps(directory: str, limit: Optional[int] = None):
    """Manifest-valid retained steps, ascending (verify_checkpoint passes
    — pre-manifest checkpoints count as valid, matching restore()'s
    fallback semantics). The input to the pod resume-step election
    (parallel/multihost.elect_resume_step): a pod restarting after a
    clean abort restores the greatest step valid on EVERY process, so
    per-process step lists must be cheap and honest. `limit` keeps only
    the newest N."""
    if not directory or not os.path.isdir(directory):
        return []
    out = [s for s in _steps(directory) if verify_checkpoint(directory, s)[0]]
    return out[-limit:] if limit else out


# --- all-writer replay slices (elastic pod; docs/REPLAY_SHARDING.md) ------
#
# Multi-host SHARDED replay spans processes, so no single writer can put
# its contents inside the orbax tree (state_dict raises there by design).
# Instead EVERY process writes its own slice — the logical ring positions
# it owns plus the packed rows (and PER priorities) at those positions —
# into a shared sibling namespace:
#
#   directory/replay_slices/step_<N>/slice_<k>_of_<n>.npz   (payload)
#   directory/replay_slices/step_<N>/slice_<k>_of_<n>.json  (digest sidecar)
#
# Filenames are per-writer, so the single-writer-per-file discipline holds
# on a shared filesystem with zero cross-process coordination; the digest
# sidecar (size + head/tail crc32, written AFTER the payload's atomic
# rename) certifies "this slice finished writing". The slice format is
# position-indexed, so a restore can merge any complete set and re-scatter
# to a DIFFERENT process count (replay/device.py merge_slice_states +
# the reshard program) — the wire format is placement-portable like the
# logical-order state_dict it slices.

SLICE_DIRNAME = "replay_slices"
_SLICE_RE = re.compile(r"^slice_(\d+)_of_(\d+)\.npz$")


def _slice_step_dir(directory: str, step: int) -> str:
    return os.path.join(
        os.path.abspath(directory), SLICE_DIRNAME, f"step_{step}"
    )


def _slice_steps(directory: str):
    """Step numbers with any slice directory present, ascending."""
    root = os.path.join(os.path.abspath(directory), SLICE_DIRNAME)
    if not os.path.isdir(root):
        return []
    return sorted(
        int(name.split("_", 1)[1])
        for name in os.listdir(root)
        if name.startswith("step_") and name.split("_", 1)[1].isdigit()
    )


def write_replay_slice(
    directory: str, step: int, proc: int, nprocs: int,
    slice_state: Dict[str, Any], fault=None,
) -> str:
    """Write this process's replay slice for `step` (atomic tmp+rename),
    then its digest sidecar. `slice_state` is replay/device.py
    slice_state_dict() output (positions + rows + ring scalars, PER adds
    priorities). `fault` is a faults.FaultSite for the chaos harness: a
    `kill` kind fires before any byte lands (peer lost DURING checkpoint
    — the slice simply never exists), `ioerror` raises to the caller,
    and `corrupt` tears the payload AFTER the digest sidecar was
    computed — the torn-shard-write case restore-time verification must
    quarantine without failing the step."""
    torn = False
    if fault is not None:
        from distributed_ddpg_tpu.faults import InjectedCorruption

        try:
            fault.tick()
        except InjectedCorruption:
            torn = True
    root = _slice_step_dir(directory, step)
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, f"slice_{proc}_of_{nprocs}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **slice_state)
    os.replace(tmp, path)
    size, crc = _digest_file(path)
    jpath = os.path.join(root, f"slice_{proc}_of_{nprocs}.json")
    jtmp = jpath + ".tmp"
    with open(jtmp, "w") as f:
        json.dump(
            {"step": step, "proc": proc, "nprocs": nprocs,
             "digest": [size, crc]},
            f,
        )
    os.replace(jtmp, jpath)
    if torn:
        # Injected torn write: the digest above covered the intact file,
        # the payload on disk is now shorter — exactly what a crash
        # mid-flush past the rename window leaves behind.
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    return path


def _verify_slice(root: str, proc: int, nprocs: int) -> Tuple[bool, str]:
    path = os.path.join(root, f"slice_{proc}_of_{nprocs}.npz")
    jpath = os.path.join(root, f"slice_{proc}_of_{nprocs}.json")
    if not os.path.exists(path):
        return False, "missing slice"
    if not os.path.exists(jpath):
        return False, "no digest sidecar (write did not finish)"
    try:
        with open(jpath) as f:
            size, crc = json.load(f)["digest"]
    except (OSError, json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
        return False, f"unreadable digest sidecar: {e!r}"
    try:
        got_size, got_crc = _digest_file(path)
    except OSError:
        return False, "unreadable slice"
    if got_size != size:
        return False, f"size mismatch: {got_size} != {size}"
    if got_crc != crc:
        return False, "digest mismatch"
    return True, "ok"


def slice_status(directory: str, step: int):
    """-> (complete, nprocs, {proc: (ok, why)}). A step's slice set is
    COMPLETE when some world size n has all n slices present and
    digest-valid. `nprocs` is that n (or the largest world size seen when
    incomplete; None when no slices exist at all)."""
    root = _slice_step_dir(directory, step)
    if not os.path.isdir(root):
        return False, None, {}
    by_n: Dict[int, set] = {}
    for name in os.listdir(root):
        m = _SLICE_RE.match(name)
        if m:
            by_n.setdefault(int(m.group(2)), set()).add(int(m.group(1)))
    if not by_n:
        return False, None, {}
    # Prefer a world size whose file set is full; verify digests for it.
    for n in sorted(by_n, reverse=True):
        if by_n[n] == set(range(n)):
            status = {k: _verify_slice(root, k, n) for k in range(n)}
            complete = all(ok for ok, _ in status.values())
            return complete, n, status
    n = max(by_n)
    status = {k: _verify_slice(root, k, n) for k in sorted(by_n[n])}
    return False, n, status


def verify_replay_slices(directory: str, step: int,
                         quarantine: bool = True) -> Tuple[bool, int]:
    """Verify the step's slice set; with `quarantine`, move each
    digest-failed slice out of the slice namespace (-> .corrupt, the
    _quarantine_corrupt discipline: payload kept for forensics, the set
    reads as incomplete afterwards). Returns (complete, nprocs or 0).
    A torn slice quarantines ONLY itself — the learner-state step stays
    valid (verify_checkpoint), and adoption falls back to the newest
    OLDER complete set (latest_complete_slice_step)."""
    complete, n, status = slice_status(directory, step)
    if quarantine:
        root = _slice_step_dir(directory, step)
        for proc, (ok, why) in status.items():
            if ok or why == "missing slice":
                continue
            src = os.path.join(root, f"slice_{proc}_of_{n}.npz")
            if not os.path.exists(src):
                continue
            try:
                os.replace(src, src + ".corrupt")
                print(
                    f"[checkpoint] quarantined corrupt replay slice "
                    f"{proc}/{n} at step_{step} ({why}) -> .corrupt; the "
                    "step's learner state stays valid",
                    file=sys.stderr, flush=True,
                )
            except OSError:
                pass
    return complete, (n or 0)


def latest_complete_slice_step(
    directory: str, at_or_below: Optional[int] = None,
) -> Optional[int]:
    """Newest step (optionally <= `at_or_below`) whose replay slice set is
    complete and digest-valid — the adoption input for an elastic
    restart (train.py): the dead peer's slice comes from its last
    verified write, so replay may be a few cadences staler than the
    elected learner step. Returns None when no step qualifies (the
    exit-76 fallback branch)."""
    if not directory:
        return None
    for s in sorted(_slice_steps(directory), reverse=True):
        if at_or_below is not None and s > at_or_below:
            continue
        complete, _, _ = slice_status(directory, s)
        if complete:
            return s
    return None


def load_replay_slices(directory: str, step: int):
    """Read back the complete slice set at `step` as a list of dicts of
    host arrays (one per writer, any order — merge is position-driven)."""
    complete, n, status = slice_status(directory, step)
    if not complete:
        bad = {k: why for k, (ok, why) in status.items() if not ok}
        raise RuntimeError(
            f"replay slice set at step_{step} is incomplete "
            f"(world={n}, failures={bad})"
        )
    root = _slice_step_dir(directory, step)
    out = []
    for k in range(n):
        with np.load(os.path.join(root, f"slice_{k}_of_{n}.npz")) as z:
            out.append({key: z[key] for key in z.files})
    return out


def restore(
    directory: str,
    state_template: TrainState,
    replay=None,
    step: Optional[int] = None,
    config: Optional[DDPGConfig] = None,
    meta_out: Optional[Dict[str, Any]] = None,
) -> Tuple[TrainState, int, int]:
    """Restore (TrainState, step, env_steps). If `replay` is given its
    contents are restored in place. `state_template` supplies the tree
    structure/shapes (orbax restores into abstract targets). When `config`
    is given, the checkpoint's saved config is validated against it first.
    `meta_out`, when given, is filled with the checkpoint's extra metadata
    (currently: "v_bounds" — the resolved auto-support bounds, present only
    on checkpoints from auto-support runs).

    With `step=None` the retained checkpoints are walked NEWEST-FIRST and
    any that fails manifest verification (verify_checkpoint) or fails to
    load is skipped with a loud stderr note — a corrupt or half-written
    latest checkpoint costs one cadence of progress, not the run. An
    explicit `step` restores exactly that step (no fallback); a config
    incompatibility always raises (it is a contract violation, not
    corruption)."""
    if step is None:
        candidates = (
            _steps(os.path.abspath(directory))
            if os.path.isdir(directory) else []
        )
        if not candidates:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        failures = []
        for s in sorted(candidates, reverse=True):
            ok, why = verify_checkpoint(directory, s)
            if not ok:
                print(
                    f"[checkpoint] step_{s} failed verification ({why}); "
                    "falling back to the previous retained checkpoint",
                    file=sys.stderr, flush=True,
                )
                failures.append(f"step_{s}: {why}")
                _quarantine_corrupt(directory, s)
                continue
            # Config compatibility is checked HERE, outside the load
            # try/except, so its ValueError raises through (a contract
            # violation, not corruption) while a ValueError from orbax's
            # own load (tree mismatch on a subtly-corrupt checkpoint that
            # passed the crc spot-check) still falls back.
            if config is not None:
                check_config_compatible(directory, s, config)
            try:
                return restore(
                    directory, state_template, replay=replay, step=s,
                    config=None, meta_out=meta_out,
                )
            except Exception as e:
                print(
                    f"[checkpoint] step_{s} failed to load ({e!r}); "
                    "falling back to the previous retained checkpoint",
                    file=sys.stderr, flush=True,
                )
                failures.append(f"step_{s}: load error: {e!r}")
        raise RuntimeError(
            f"no restorable checkpoint under {directory}; tried newest-"
            "first: " + "; ".join(failures)
        )
    if config is not None:
        check_config_compatible(directory, step, config)
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    template: Dict[str, Any] = {
        "state": jax.device_get(state_template),
        "meta": {"env_steps": np.zeros((), np.int64)},
    }
    if replay is not None:
        template["replay"] = replay.state_dict()
    with _checkpointer() as ckptr:
        # Checkpoints written before the 'meta' entry existed lack that
        # subtree, and orbax requires the template to match the on-disk tree
        # exactly. Probe the saved structure rather than catching ValueError,
        # so genuine template mismatches keep their original diagnostic.
        has_bounds = False
        has_replay = replay is not None
        try:
            on_disk = ckptr.metadata(path)
            # The saved tree's location varies by orbax version: current
            # StandardCheckpointer returns StepMetadata with the tree under
            # .item_metadata.tree; older versions exposed .tree or the raw
            # tree itself.
            tree = getattr(on_disk, "tree", None)
            if tree is None:
                tree = getattr(
                    getattr(on_disk, "item_metadata", None), "tree", None
                )
            if tree is None:
                tree = on_disk
            has_meta = "meta" in tree
            has_bounds = has_meta and "v_bounds" in tree["meta"]
            has_replay = "replay" in tree
        except Exception:
            has_meta = True  # metadata unreadable: let restore() report it
        if not has_meta:
            template.pop("meta")  # env_steps then resumes as 0
        elif has_bounds:
            template["meta"]["v_bounds"] = np.zeros(2, np.float64)
        if not has_replay and replay is not None:
            # Checkpoints from multi-host SHARDED runs omit replay
            # contents from the orbax tree (no single-writer snapshot
            # spans the shards — replay/device.py state_dict,
            # docs/REPLAY_SHARDING.md): the buffer resumes empty here;
            # the caller may adopt the all-writer slice set afterwards
            # (latest_complete_slice_step + load_replay_slices).
            template.pop("replay", None)
            print(
                f"[checkpoint] step_{step} carries no replay contents "
                "(multi-host sharded writer); the buffer resumes empty "
                "unless a verified slice set is adopted",
                file=sys.stderr, flush=True,
            )
        elif has_replay and replay is None:
            # A replay-carrying checkpoint restored without a buffer to
            # land it in (e.g. a replicated-mode checkpoint resumed by a
            # multi-host sharded run): orbax needs the template to cover
            # the on-disk tree, and silently dropping GBs of experience
            # would mask a placement-mode switch — surface it instead.
            raise RuntimeError(
                f"checkpoint step_{step} carries replay contents but this "
                "run cannot restore them (multi-host sharded replay has "
                "no single-writer snapshot; docs/REPLAY_SHARDING.md) — "
                "resume with the original replay placement, or start a "
                "fresh checkpoint_dir"
            )
        restored = ckptr.restore(path, template)
    if replay is not None and "replay" in restored:
        replay.load_state_dict(restored["replay"])
    state = jax.tree.map(np.asarray, restored["state"])
    meta = restored.get("meta", {})
    env_steps = int(meta.get("env_steps", 0))
    if meta_out is not None:
        # Whether the checkpoint's orbax tree carried replay contents —
        # the slice-adoption gate (train.py adopts the all-writer slice
        # set only when the tree did NOT restore the buffer).
        meta_out["ckpt_has_replay"] = bool(has_replay)
        if "v_bounds" in meta:
            vb = np.asarray(meta["v_bounds"], np.float64)
            meta_out["v_bounds"] = (float(vb[0]), float(vb[1]))
        carry_path = os.path.join(path, "devactor_carry.npz")
        if os.path.exists(carry_path):
            # Device-actor rollout carry sidecar (save's devactor_state):
            # handed back as host arrays — the pool that consumes it is
            # built AFTER restore (its warmup budget needs env_steps), so
            # it cannot contribute a template here.
            try:
                with np.load(carry_path) as z:
                    meta_out["devactor_carry"] = {k: z[k] for k in z.files}
            except (OSError, ValueError) as e:
                print(
                    f"[checkpoint] devactor_carry.npz unreadable ({e!r}); "
                    "rollout state starts fresh",
                    file=sys.stderr, flush=True,
                )
    return state, step, env_steps
