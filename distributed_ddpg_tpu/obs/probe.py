"""One health probe of a (possibly remote) peer's telemetry ingress —
the supervisor's rejoin gate input (supervisor/prober.py; ISSUE 19).

`probe_healthz` answers the only question the grow decision needs:
"would relaunching the pod with this host succeed?" It layers two
checks, degrading honestly:

  1. TCP connect to the peer's exporter port. Refused / unreachable /
     timed out -> the host (or its network path) is still gone.
  2. GET /healthz (obs/exporter.py). A 200 means the typed state machine
     says `healthy`; 503 means `degraded` or `draining` — reachable but
     NOT a rejoin candidate (a draining peer is mid-teardown; growing
     onto it would re-lose it immediately).

The documented fallback: a host whose port accepts TCP but does not
speak HTTP (exporter disabled, or a bare nc-style liveness listener in a
drill) counts as healthy-by-reachability — `ProbeResult.state == "tcp"`
marks the reduced confidence so event logs can tell the two apart.

Stdlib only, no jax: the supervisor process must never pay (or risk) a
device runtime just to poll a socket.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import socket

# Per-probe deadline. Probes run on the supervisor's background prober
# thread at probe_interval_s cadence — one wedged peer must delay the
# NEXT probe, never the supervisor's child-reaping loop.
PROBE_TIMEOUT_S = 2.0


@dataclasses.dataclass(frozen=True)
class ProbeResult:
    reachable: bool   # TCP connect succeeded
    healthy: bool     # rejoin-candidate verdict (the gate's input)
    state: str        # healthy|degraded|draining|down|tcp|http:<status>
    detail: str = ""  # raw body / error repr, for event-log attribution

    def __bool__(self) -> bool:
        return self.healthy


def probe_healthz(
    host: str, port: int, timeout_s: float = PROBE_TIMEOUT_S
) -> ProbeResult:
    """One probe, never raises (module docstring for the layering)."""
    try:
        sock = socket.create_connection((host, int(port)), timeout=timeout_s)
        sock.close()
    except OSError as e:
        return ProbeResult(False, False, "down", repr(e))
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout_s)
    try:
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
    except (OSError, http.client.HTTPException) as e:
        # Reachable but not speaking HTTP: the documented TCP-reachability
        # fallback (reduced confidence, state="tcp").
        return ProbeResult(True, True, "tcp", repr(e))
    finally:
        conn.close()
    try:
        state = str(json.loads(body).get("state", ""))
    except ValueError:
        state = ""
    if resp.status == 200:
        return ProbeResult(True, True, state or "healthy", body.strip())
    return ProbeResult(
        True, False, state or f"http:{resp.status}", body.strip()
    )
