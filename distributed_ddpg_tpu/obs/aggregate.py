"""Pod-level metric aggregation + straggler attribution
(docs/OBSERVABILITY.md §4 "pod record").

A multi-host run writes N disjoint per-process JSONL streams; nothing
cross-references them until an operator joins the files by hand. This
module closes that gap at the source: on each log cadence every process
contributes a tiny fixed-shape snapshot vector to one all-gather, and
rank 0 emits a single `kind:"pod"` record carrying per-host min/max/
spread for the beat-time, ingest, and transfer families plus a clock-
spread gauge and a straggler attribution — the layer
`pod_collective_slack_p95_ms` (a scalar over ALL hosts) cannot provide.

Transport: the snapshot is encoded as a milli-scaled int64 vector of at
most `multihost._UNIFORM_SLOTS` slots, so the gather rides the SAME
uniform int64[8] all-gather executable as every other pod-layer
collective — one compiled program, one wire size, nothing new for the
gloo interleaving hazard to chew on (parallel/multihost.py). The gather
callable is injected (train.py passes `allgather_scalar`), keeping this
module import-light and unit-testable without a pod.

Straggler detection runs on the gathered beat-time vector, identically
on every rank (same data): the z-score test needs a population (>= 4
hosts); below that a relative-to-median test fires instead, since a
2-host pod's z-scores are pinned at +/-1 by construction. A flagged
host increments `PodStats.record_straggler` (the `pod_stragglers` /
`pod_straggler_host` fields on every later train record) and drops a
`pod_straggler` instant on the flight-recorder timeline, so the merged
pod trace (tools.runs merge-trace) shows WHEN attribution fired against
what every host was doing.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from distributed_ddpg_tpu import trace

# Snapshot vector layout (milli-scaled int64; <= 8 slots so the uniform
# all-gather transport applies — see module docstring).
SLOT_BEAT_MS = 0          # wall ms per learner chunk since last gather
SLOT_INGEST_RATE = 1      # host env-steps ingested per second
SLOT_TRANSFER_BACKLOG = 2  # transfer scheduler queue depth (gauge)
SLOT_UNIX_MS = 3          # this host's wall clock, ms (clock spread)
SLOTS = 4
_SCALE = 1000.0


def detect_straggler(
    beat_ms,
    *,
    z_thresh: float = 3.0,
    rel_thresh: float = 2.0,
    min_abs_ms: float = 5.0,
) -> int:
    """Index of the straggling host in a per-host beat-time vector, or
    -1. Two tests (module docstring): population z-score when >= 4 hosts,
    relative-to-median otherwise; both gated on an absolute floor so
    microsecond jitter on a fast pod never attributes."""
    v = np.asarray(beat_ms, dtype=float)
    if v.size < 2:
        return -1
    worst = int(np.argmax(v))
    # Baseline = median of the OTHER hosts: a median over the full vector
    # would include the suspect, and at 2 hosts that makes the relative
    # test unsatisfiable (worst >= 2*mean(worst, other) needs other <= 0).
    med = float(np.median(np.delete(v, worst)))
    if float(v[worst]) - med < min_abs_ms:
        return -1
    if v.size >= 4:
        std = float(v.std())
        if std > 0.0 and (float(v[worst]) - float(v.mean())) / std >= z_thresh:
            return worst
    if med > 0.0 and float(v[worst]) >= rel_thresh * med:
        return worst
    return -1


class PodAggregator:
    """Builds this host's snapshot vector, gathers all hosts', and
    reduces to the `kind:"pod"` record fields (module docstring).

    `gather_fn(vec)` must return a [process_count, len(vec)] array —
    train.py passes `multihost.allgather_scalar` (on bg_sync runs
    wrapped in the scheduler's ordered lane, like every host-initiated
    collective). Rates are computed against the previous collect() call,
    so the first record after warmup reflects the first full interval.
    """

    def __init__(
        self,
        *,
        gather_fn: Callable[[np.ndarray], Any],
        stats=None,
        z_thresh: float = 3.0,
        rel_thresh: float = 2.0,
        min_abs_ms: float = 5.0,
    ):
        self._gather = gather_fn
        self._stats = stats
        self._z = z_thresh
        self._rel = rel_thresh
        self._min_abs = min_abs_ms
        self._last_t = time.perf_counter()
        self._last_beats = 0
        self._last_rows = 0

    def sample(self, *, beats: int, ingest_rows: int,
               transfer_backlog: int) -> np.ndarray:
        """This host's int64 snapshot vector for one gather."""
        now = time.perf_counter()
        dt = max(1e-9, now - self._last_t)
        d_beats = max(0, int(beats) - self._last_beats)
        d_rows = max(0, int(ingest_rows) - self._last_rows)
        self._last_t = now
        self._last_beats = int(beats)
        self._last_rows = int(ingest_rows)
        vec = np.zeros((SLOTS,), np.int64)
        vec[SLOT_BEAT_MS] = round(_SCALE * 1000.0 * dt / max(1, d_beats))
        vec[SLOT_INGEST_RATE] = round(_SCALE * d_rows / dt)
        vec[SLOT_TRANSFER_BACKLOG] = round(_SCALE * max(0, int(transfer_backlog)))
        vec[SLOT_UNIX_MS] = int(time.time() * 1000.0)
        return vec

    def collect(self, *, beats: int, ingest_rows: int,
                transfer_backlog: int = 0) -> Optional[Dict[str, Any]]:
        """One cadence: sample, gather, reduce. Returns the pod record
        fields (every rank gets them — the CALLER logs on rank 0 only),
        or None when the gather yields fewer than 2 hosts."""
        vec = self.sample(beats=beats, ingest_rows=ingest_rows,
                          transfer_backlog=transfer_backlog)
        gathered = np.asarray(self._gather(vec), dtype=np.int64)
        if gathered.ndim != 2 or gathered.shape[0] < 2:
            return None
        beat = gathered[:, SLOT_BEAT_MS] / _SCALE
        rate = gathered[:, SLOT_INGEST_RATE] / _SCALE
        backlog = gathered[:, SLOT_TRANSFER_BACKLOG] / _SCALE
        unix_ms = gathered[:, SLOT_UNIX_MS].astype(float)
        straggler = detect_straggler(
            beat, z_thresh=self._z, rel_thresh=self._rel,
            min_abs_ms=self._min_abs,
        )
        if straggler >= 0:
            if self._stats is not None:
                self._stats.record_straggler(straggler)
            trace.instant(
                "pod_straggler", host=straggler,
                beat_ms=round(float(beat[straggler]), 3),
                median_ms=round(float(np.median(beat)), 3),
            )

        def fam(prefix: str, v: np.ndarray) -> Dict[str, float]:
            lo, hi = float(v.min()), float(v.max())
            return {
                f"{prefix}_min": round(lo, 3),
                f"{prefix}_max": round(hi, 3),
                f"{prefix}_spread": round(hi - lo, 3),
            }

        return {
            "pod_agg_hosts": int(gathered.shape[0]),
            **fam("pod_beat_ms", beat),
            **fam("pod_ingest_rows_per_s", rate),
            **fam("pod_transfer_backlog", backlog),
            "pod_clock_spread_ms": round(
                float(unix_ms.max() - unix_ms.min()), 3
            ),
            "pod_straggler_host": int(straggler),
        }
