"""Telemetry plane (docs/OBSERVABILITY.md §4): live per-process ingress
(`/metrics` + `/healthz` + `/trace`, obs/exporter.py), the typed health
state machine (obs/health.py), and cross-host metric aggregation with
straggler attribution (obs/aggregate.py). Everything here is stdlib +
numpy — no jax import, so the exporter and aggregator unit-test without
a device runtime (the multihost gather is injected by train.py)."""

from distributed_ddpg_tpu.obs import health
from distributed_ddpg_tpu.obs.aggregate import PodAggregator, detect_straggler
from distributed_ddpg_tpu.obs.exporter import ObsExporter, render_prometheus
from distributed_ddpg_tpu.obs.probe import ProbeResult, probe_healthz

__all__ = [
    "health",
    "PodAggregator",
    "detect_straggler",
    "ObsExporter",
    "render_prometheus",
    "ProbeResult",
    "probe_healthz",
]
