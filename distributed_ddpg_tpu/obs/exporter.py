"""Live telemetry ingress: one stdlib HTTP exporter thread per process
(docs/OBSERVABILITY.md §4; `--obs_port`, off by default).

Until this module, every observability surface was offline — JSONL files
and trace rings read after the run. The ROADMAP's auto-rejoin supervisor
and the serving front's canary gate both need to ask a LIVE process how
it is doing, so each process serves three endpoints:

  /metrics   Prometheus text exposition (version 0.0.4), rendered from
             the latest MetricsLogger record per kind plus caller-provided
             cumulative counters. Field names are sanitized into one
             `ddpg_<field>{kind="..."}` gauge family per JSONL field —
             the JSONL schema IS the scrape schema, no second registry
             to drift.
  /healthz   The typed state machine (obs/health.py): 200 + JSON while
             healthy, 503 + JSON (state, reasons) when degraded or
             draining — a canary gate or supervisor keys off the status
             code alone and reads the reasons for attribution.
  /trace     On-demand flight-recorder export (trace.py) — the live
             sibling of the SIGUSR2 poke, for scraping a timeline off a
             box you cannot signal. Writes `trace_ondemand.json` next to
             the run's trace artifacts so it never clobbers the clean-
             exit `trace.json`.

Everything here is stdlib (`http.server`) and OFF the hot path: the
server thread blocks in accept(), rendering happens on the scrape
thread, and the only train-loop cost is MetricsLogger's latest-record
bookkeeping — tests/test_obs.py pins the whole plane under the same
<2% overhead guard the flight recorder carries.

The server binds all interfaces (a pod's rank-0 scrape target must be
reachable from the operator's Prometheus, not just localhost) and serves
read-only diagnostics with no auth: point it at a private interconnect,
not the internet (docs/OPERATIONS.md scrape recipes).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.obs import health as health_mod

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

# stop() bounds its wait for the serve_forever thread: a scrape handler
# wedged on a dead client must delay shutdown, not hang it (the thread is
# a daemon — an expired join leaks nothing the exit won't reap).
_STOP_JOIN_TIMEOUT_S = 5.0


def _sanitize(name: str) -> str:
    """JSONL field name -> Prometheus metric name segment."""
    out = _NAME_RE.sub("_", str(name))
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _numeric(v: Any) -> Optional[float]:
    """Prometheus sample value for a JSONL field: bools as 0/1, numbers
    as-is, everything else (strings, None, nested) unexportable."""
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    return None


def render_prometheus(
    latest_by_kind: Optional[Dict[str, Dict[str, Any]]],
    counters: Optional[Dict[str, Any]] = None,
    health: Optional[health_mod.HealthState] = None,
) -> str:
    """Prometheus text format. Samples are grouped per metric family
    (the exposition format forbids interleaving a family's samples), one
    `# TYPE ... gauge` line ahead of each family."""
    families: Dict[str, List[str]] = {}

    def add(name: str, value: float, labels: str = "") -> None:
        families.setdefault(name, []).append(f"{name}{labels} {value:g}")

    if health is not None:
        state, _ = health.state()
        add("ddpg_health_code", float(health_mod.CODES[state]))
        for s in (health_mod.HEALTHY, health_mod.DEGRADED,
                  health_mod.DRAINING):
            add("ddpg_health", float(s == state), f'{{state="{s}"}}')
    for name in sorted(counters or {}):
        num = _numeric((counters or {})[name])
        if num is not None:
            add(f"ddpg_{_sanitize(name)}", num)
    for kind in sorted(latest_by_kind or {}):
        rec = (latest_by_kind or {})[kind]
        for key in sorted(rec):
            if key == "kind":
                continue
            num = _numeric(rec[key])
            if num is not None:
                add(f"ddpg_{_sanitize(key)}", num, f'{{kind="{kind}"}}')
    lines: List[str] = []
    for name in sorted(families):
        lines.append(f"# TYPE {name} gauge")
        lines.extend(families[name])
    return "\n".join(lines) + "\n"


class ObsExporter:
    """The per-process exporter thread (module docstring).

    `latest_fn` returns `{kind: latest record}` (MetricsLogger.latest);
    `counters_fn` returns extra cumulative gauges (uptime, t_unix_base,
    process index). Both are polled per scrape, never cached. port=0
    binds an ephemeral port (tests); the bound port is `self.port` after
    start().
    """

    def __init__(
        self,
        port: int,
        *,
        health: Optional[health_mod.HealthState] = None,
        latest_fn: Optional[Callable[[], Dict[str, Dict[str, Any]]]] = None,
        counters_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        trace_dir: str = "",
        host: str = "",
    ):
        self._health = health if health is not None else health_mod.get()
        self._latest_fn = latest_fn
        self._counters_fn = counters_fn
        self._trace_dir = trace_dir
        self._host = host
        self.port = int(port)
        self._t0 = time.time()
        self._scrapes = 0
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ObsExporter":
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *_a):  # scrapes must not spam stderr
                pass

            def do_GET(self):
                try:
                    exporter._route(self)
                except (BrokenPipeError, ConnectionError):
                    pass  # scraper hung up mid-response
                except Exception as e:  # diagnostics must not crash
                    try:
                        exporter._send(self, 500, "text/plain",
                                       f"exporter error: {e!r}\n")
                    except Exception:
                        pass

        server = ThreadingHTTPServer((self._host, self.port), _Handler)
        server.daemon_threads = True
        self._server = server
        self.port = server.server_address[1]
        self._thread = threading.Thread(
            target=server.serve_forever, name="obs-exporter", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        server = self._server
        if server is not None:
            self._server = None
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=_STOP_JOIN_TIMEOUT_S)
            self._thread = None

    def url(self, path: str = "/metrics") -> str:
        return f"http://127.0.0.1:{self.port}{path}"

    # -- routing ---------------------------------------------------------

    def _counters(self) -> Dict[str, Any]:
        with self._lock:
            scrapes = self._scrapes
        out = {
            "obs_scrapes_total": scrapes,
            "obs_uptime_s": round(time.time() - self._t0, 3),
            "pid": os.getpid(),
        }
        if self._counters_fn is not None:
            try:
                out.update(self._counters_fn())
            except Exception:
                pass  # a failing counter source degrades to the basics
        return out

    def _route(self, handler: BaseHTTPRequestHandler) -> None:
        path = handler.path.split("?", 1)[0]
        if path == "/metrics":
            with self._lock:
                self._scrapes += 1
            latest = {}
            if self._latest_fn is not None:
                try:
                    latest = self._latest_fn()
                except Exception:
                    latest = {}
            body = render_prometheus(latest, self._counters(), self._health)
            self._send(handler, 200,
                       "text/plain; version=0.0.4; charset=utf-8", body)
        elif path == "/healthz":
            snap = self._health.snapshot()
            status = 200 if snap["state"] == health_mod.HEALTHY else 503
            self._send(handler, status, "application/json",
                       json.dumps(snap) + "\n")
        elif path == "/trace":
            if not trace.enabled():
                self._send(handler, 200, "application/json",
                           json.dumps({"enabled": False, "events": 0}) + "\n")
                return
            out = os.path.join(self._trace_dir or ".", "trace_ondemand.json")
            n = trace.export(out)
            self._send(handler, 200, "application/json",
                       json.dumps({"enabled": True, "events": n,
                                   "path": out}) + "\n")
        else:
            self._send(handler, 404, "text/plain",
                       "endpoints: /metrics /healthz /trace\n")

    @staticmethod
    def _send(handler: BaseHTTPRequestHandler, status: int, ctype: str,
              body: str) -> None:
        data = body.encode("utf-8")
        handler.send_response(status)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)
