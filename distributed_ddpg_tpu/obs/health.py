"""Typed process-liveness state machine for the telemetry plane
(docs/OBSERVABILITY.md §4).

The `/healthz` endpoint (obs/exporter.py) needs ONE answer to "should a
supervisor keep this process in rotation" — a boolean is not enough,
because the three actionable answers differ:

  healthy    keep serving / keep training.
  degraded   still making progress but impaired (pod shrank below the
             slice set's writer count, a guardrail quarantine fired, the
             serve queue is saturated): a canary gate must stop shifting
             traffic toward it, a supervisor should plan a relaunch.
  draining   terminal — the process is on its way out (watchdog stall,
             SIGTERM preemption): route nothing new, expect the exit.

Degraded conditions are NAMED and reversible (`note(name, active)`):
an elastic pod that grows back to full membership clears its
`pod_state_degraded` condition and the state returns to healthy.
Draining is latched — there is no way back from a stall or a preemption
inside one process lifetime, so the first `drain()` wins and later
condition churn cannot flap the endpoint while teardown runs.

Live probes (`register_probe`) are evaluated AT READ TIME on the scrape
thread, not cached: the serve queue-saturation probe (serve/server.py
`overloaded`) must reflect the queue as it is now, not as it was at the
last cadence. A probe that raises counts as a degraded condition
(`<name>:probe_error`) — for a canary gate, "cannot determine health"
and "unhealthy" must read the same.

One module-level instance per process (`get()`), mirroring trace.py's
singleton: the watchdog's stall path (watchdog.py) and the pod abort
path (parallel/multihost.py) both flip it without plumbing a handle
through every layer.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Tuple

HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"

# Numeric encoding for the /metrics gauge (ddpg_health_code): ordered by
# severity so alert rules can threshold on `> 0`.
CODES = {HEALTHY: 0, DEGRADED: 1, DRAINING: 2}


class HealthState:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._conditions: Dict[str, float] = {}  # name -> unix time flagged
        self._probes: Dict[str, Callable[[], bool]] = {}
        self._draining = ""
        self._since = time.time()

    # -- writers (train loop / watchdog / multihost abort path) ---------

    def note(self, name: str, active: bool = True) -> None:
        """Set (active=True) or clear a named degraded condition. Setting
        an already-active condition keeps its original flag time."""
        with self._lock:
            if active:
                self._conditions.setdefault(name, time.time())
            else:
                self._conditions.pop(name, None)

    def drain(self, reason: str) -> None:
        """Latch the terminal draining state. First reason wins — the
        original cause must survive teardown's condition churn."""
        with self._lock:
            if not self._draining:
                self._draining = reason

    def register_probe(self, name: str, fn: Callable[[], bool]) -> None:
        """Attach a live degraded-condition probe, evaluated at read
        time on the scrape thread. `fn` returns True while degraded."""
        with self._lock:
            self._probes[name] = fn

    def reset(self) -> None:
        """Back to a fresh healthy state (tests; a new run in the same
        interpreter must not inherit the previous run's conditions)."""
        with self._lock:
            self._conditions.clear()
            self._probes.clear()
            self._draining = ""
            self._since = time.time()

    # -- readers (exporter) ---------------------------------------------

    def state(self) -> Tuple[str, List[str]]:
        """(state, reasons). Draining dominates; any active condition or
        truthy probe yields degraded; else healthy with no reasons."""
        with self._lock:
            if self._draining:
                return DRAINING, [self._draining]
            reasons = sorted(self._conditions)
            probes = list(self._probes.items())
        for name, fn in probes:
            try:
                if fn():
                    reasons.append(name)
            except Exception:
                # "Cannot determine health" must gate like "unhealthy".
                reasons.append(f"{name}:probe_error")
        return (DEGRADED, reasons) if reasons else (HEALTHY, [])

    def snapshot(self) -> Dict[str, object]:
        """The /healthz JSON body (docs/OBSERVABILITY.md §4)."""
        state, reasons = self.state()
        return {
            "state": state,
            "code": CODES[state],
            "reasons": reasons,
            "since_unix": round(self._since, 3),
            "t_unix": round(time.time(), 3),
        }


_STATE = HealthState()


def get() -> HealthState:
    """The process-wide health singleton (module docstring)."""
    return _STATE
