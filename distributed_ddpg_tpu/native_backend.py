"""`--backend native`: the pure-numpy learner (SURVEY.md §7 step 2).

This is BOTH of the reference-parity roles named in BASELINE.json:5:
1. the CPU baseline whose grad-steps/sec is the denominator of the >=20x
   target (the reference publishes no numbers, BASELINE.md — measuring this
   path IS the baseline), and
2. the bit-comparability oracle: identical math to the jitted TPU step —
   same MLP shapes, same loss formulas, same Adam formulation
   (ops/optim.py), same Polyak lerp — written with hand-derived numpy
   backprop so agreement with the JAX path is an independent check, not a
   tautology. Equivalence is tolerance-bounded (f32 accumulation order
   differs under XLA fusion; SURVEY.md §7 'hard parts (c)').

Scope matches the reference's algorithm surface: plain DDPG (uniform or PER
batches, n-step discounts folded upstream). The D4PG distributional critic is
a TPU-path extension and is rejected here.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.ops.optim import B1, B2, EPS


def _to_numpy_tree(tree):
    import jax

    return jax.tree.map(lambda x: np.asarray(x, np.float32), tree)


class NativeLearner:
    """Numpy mirror of learner.make_learner_step for non-distributional DDPG."""

    def __init__(self, config: DDPGConfig, state, action_scale, action_offset=0.0):
        if config.distributional or config.twin_critic:
            raise NotImplementedError(
                "--backend native implements the reference's plain-DDPG surface; "
                "the distributional (D4PG) and twin (TD3) critics are "
                "jax_tpu-only"
            )
        self.config = config
        self.scale = np.asarray(action_scale, np.float32)
        self.offset = np.asarray(action_offset, np.float32)
        s = _to_numpy_tree(state)
        self.actor = [dict(l) for l in s.actor_params]
        self.critic = [dict(l) for l in s.critic_params]
        self.target_actor = [dict(l) for l in s.target_actor_params]
        self.target_critic = [dict(l) for l in s.target_critic_params]
        self.actor_opt = {
            "mu": [dict(l) for l in s.actor_opt.mu],
            "nu": [dict(l) for l in s.actor_opt.nu],
            "count": int(s.actor_opt.count),
        }
        self.critic_opt = {
            "mu": [dict(l) for l in s.critic_opt.mu],
            "nu": [dict(l) for l in s.critic_opt.nu],
            "count": int(s.critic_opt.count),
        }
        self.step_count = int(s.step)

    # ---- forward passes (mirror models/mlp.py) ----

    def actor_forward(self, obs) -> Tuple[np.ndarray, list]:
        x = obs
        cache = []
        for layer in self.actor[:-1]:
            z = x @ layer["w"] + layer["b"]
            cache.append((x, z))
            x = np.maximum(z, 0.0)
        z = x @ self.actor[-1]["w"] + self.actor[-1]["b"]
        cache.append((x, z))
        t = np.tanh(z)
        return t * self.scale + self.offset, cache + [t]

    def _critic_forward(self, params, obs, action) -> Tuple[np.ndarray, list]:
        ail = self.config.action_insert_layer
        x = obs
        cache = []
        n = len(params)
        for i, layer in enumerate(params):
            if i == ail:
                x = np.concatenate([x, action], axis=-1)
            z = x @ layer["w"] + layer["b"]
            cache.append((x, z))
            x = np.maximum(z, 0.0) if i < n - 1 else z
        return x[:, 0], cache

    def _critic_backward(self, params, cache, dq) -> Tuple[list, np.ndarray]:
        """Backprop dL/dq -> (param grads, dL/d_action)."""
        ail = self.config.action_insert_layer
        act_dim = self.actor[-1]["w"].shape[1]
        n = len(params)
        grads = [None] * n
        dx = dq[:, None]  # d wrt pre-activation of last layer (linear output)
        d_action = None
        for i in range(n - 1, -1, -1):
            x, z = cache[i]
            if i < n - 1:
                dz = dx * (z > 0.0)
            else:
                dz = dx
            grads[i] = {
                "w": x.T @ dz,
                "b": dz.sum(axis=0),
            }
            dx = dz @ params[i]["w"].T
            if i == ail:
                d_action = dx[:, -act_dim:]
                dx = dx[:, :-act_dim]
        return grads, d_action

    def _actor_backward(self, cache, d_action) -> list:
        """Backprop dL/d_mu(s) through tanh*scale+offset and the MLP."""
        t = cache[-1]
        layer_caches = cache[:-1]
        n = len(self.actor)
        grads = [None] * n
        dz = d_action * self.scale * (1.0 - t * t)  # through tanh & scale
        for i in range(n - 1, -1, -1):
            x, z = layer_caches[i]
            if i < n - 1:
                dz = dz * (z > 0.0)
            grads[i] = {"w": x.T @ dz, "b": dz.sum(axis=0)}
            if i > 0:
                dz = dz @ self.actor[i]["w"].T
        return grads

    # ---- Adam + Polyak (mirror ops/optim.py, ops/polyak.py) ----

    def _adam(self, params, grads, opt, lr):
        opt["count"] += 1
        c = float(opt["count"])
        bc1 = 1.0 - B1**c
        bc2 = 1.0 - B2**c
        for p, g, m, v in zip(params, grads, opt["mu"], opt["nu"]):
            for k in ("w", "b"):
                m[k] = B1 * m[k] + (1.0 - B1) * g[k]
                v[k] = B2 * v[k] + (1.0 - B2) * g[k] * g[k]
                p[k] = p[k] - lr * (m[k] / bc1) / (np.sqrt(v[k] / bc2) + EPS)

    def _polyak(self, online, target, tau):
        for o, t in zip(online, target):
            for k in ("w", "b"):
                t[k] = tau * o[k] + (1.0 - tau) * t[k]

    # ---- the step (mirror learner.make_learner_step) ----

    def step(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        cfg = self.config
        obs = batch["obs"]
        action = batch["action"]
        reward = batch["reward"]
        discount = batch["discount"]
        next_obs = batch["next_obs"]
        weight = batch.get("weight", np.ones_like(reward))
        bsz = obs.shape[0]

        # critic TD loss
        next_action, _ = self._target_actor_forward(next_obs)
        next_q, _ = self._critic_forward(self.target_critic, next_obs, next_action)
        y = reward + discount * next_q
        q, ccache = self._critic_forward(self.critic, obs, action)
        td = y - q
        closs = float(np.mean(weight * td * td))
        dq = -2.0 * weight * td / bsz
        cgrads, _ = self._critic_backward(self.critic, ccache, dq)
        if cfg.critic_l2 > 0.0:
            closs += cfg.critic_l2 * sum(float(np.sum(l["w"] ** 2)) for l in self.critic)
            for g, p in zip(cgrads, self.critic):
                g["w"] = g["w"] + 2.0 * cfg.critic_l2 * p["w"]

        # actor DPG loss (pre-update critic, matching learner.py)
        mu, acache = self.actor_forward(obs)
        q_pi, pcache = self._critic_forward(self.critic, obs, mu)
        aloss = -float(np.mean(q_pi))
        dq_pi = np.full(bsz, -1.0 / bsz, np.float32)
        _, d_action = self._critic_backward(self.critic, pcache, dq_pi)
        agrads = self._actor_backward(acache, d_action)

        self._adam(self.critic, cgrads, self.critic_opt, cfg.critic_lr)
        self._adam(self.actor, agrads, self.actor_opt, cfg.actor_lr)
        self._polyak(self.actor, self.target_actor, cfg.tau)
        self._polyak(self.critic, self.target_critic, cfg.tau)
        self.step_count += 1

        return {
            "critic_loss": closs,
            "actor_loss": aloss,
            "mean_q": -aloss,
            "td_abs_mean": float(np.mean(np.abs(td))),
            "td_errors": td,
        }

    def _target_actor_forward(self, obs):
        x = obs
        for layer in self.target_actor[:-1]:
            x = np.maximum(x @ layer["w"] + layer["b"], 0.0)
        z = x @ self.target_actor[-1]["w"] + self.target_actor[-1]["b"]
        return np.tanh(z) * self.scale + self.offset, None

    def act(self, obs: np.ndarray) -> np.ndarray:
        out, _ = self.actor_forward(np.atleast_2d(obs))
        return out

    def params_close_to(self, state, rtol=1e-4, atol=1e-5) -> bool:
        """Tolerance-bounded comparison against a JAX TrainState."""
        import jax

        other = _to_numpy_tree(state)
        mine = (self.actor, self.critic, self.target_actor, self.target_critic)
        theirs = (
            other.actor_params,
            other.critic_params,
            other.target_actor_params,
            other.target_critic_params,
        )
        for m_net, t_net in zip(mine, theirs):
            for m_l, t_l in zip(m_net, t_net):
                for k in ("w", "b"):
                    if not np.allclose(m_l[k], t_l[k], rtol=rtol, atol=atol):
                        return False
        return True
