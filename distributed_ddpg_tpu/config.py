"""Single frozen dataclass config, CLI-overridable (SURVEY.md §5 'Config').

Replaces the reference's `tf.app.flags`/`settings.py` constants module
(SURVEY.md §2 #8). Hyperparameter defaults follow the DDPG paper
(arXiv 1509.02971) as recorded in SURVEY.md §2 #8: gamma=0.99, tau=1e-3,
lr_actor=1e-4, lr_critic=1e-3, batch=64, buffer ~1e6, OU theta=0.15 sigma=0.2.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class DDPGConfig:
    """Hyperparameters and topology for one training run."""

    # --- environment ---
    env_id: str = "Pendulum-v1"
    seed: int = 0

    # --- networks (SURVEY.md §2 #3/#4: ~2 hidden layers, 400/300 or 256/256) ---
    actor_hidden: Sequence[int] = (256, 256)
    critic_hidden: Sequence[int] = (256, 256)
    # Classic DDPG injects the action at the second critic layer (SURVEY.md §2 #4).
    action_insert_layer: int = 1

    # --- algorithm ---
    gamma: float = 0.99
    tau: float = 1e-3                # Polyak soft-update coefficient
    actor_lr: float = 1e-4
    critic_lr: float = 1e-3
    critic_l2: float = 0.0           # weight decay on critic (paper uses 1e-2)
    batch_size: int = 64
    n_step: int = 1                  # n-step returns (D4PG, arXiv 1804.08617)

    # --- distributional critic (D4PG) ---
    distributional: bool = False
    num_atoms: int = 51
    # Value-support bounds. nan = AUTO (CLI: --v_min=auto --v_max=auto, both
    # together): sized from warmup reward statistics at learner start, then
    # expanded geometrically whenever mean_q approaches an edge
    # (ops/support_auto.py — kills the per-env hand knob that needed ±400
    # for LunarLander and [-1600, 0] for Pendulum, docs/EVIDENCE.md §3).
    # nan, not 0/inf, is the sentinel — same convention as target_entropy:
    # any concrete float is a legitimate hand-set bound.
    v_min: float = -150.0
    v_max: float = 150.0

    # --- TD3 (arXiv 1802.09477; beyond-parity family like D4PG) ---
    # twin_critic: a 2-critic ensemble (params stacked on a leading axis,
    # applied via vmap — one MXU-batched program, not two sequential nets),
    # with min-over-ensemble Bellman targets (clipped double-Q).
    twin_critic: bool = False
    # Actor + target nets update once per `policy_delay` critic steps.
    policy_delay: int = 1
    # Target-policy smoothing: clip(N(0, target_noise), +-clip) added to
    # the target action inside the critic target (0 = off). The noise key
    # derives from fold_in(seed, state.step) — deterministic, replayable,
    # and identical across data-parallel replicas.
    target_noise: float = 0.0
    target_noise_clip: float = 0.5

    # --- SAC (arXiv 1801.01290/1812.05905; third beyond-parity family) ---
    # sac: stochastic tanh-Gaussian actor (head outputs [mean | log_std],
    # reparameterized sampling, tanh log-prob correction), twin critics
    # stacked on a leading axis exactly like TD3's, and entropy-regularized
    # Bellman targets min_i Q_i(s',a') - alpha * log pi(a'|s'). Exploration
    # comes from the policy itself: workers sample (no OU noise), eval acts
    # on tanh(mean).
    sac: bool = False
    # Entropy temperature. With sac_autotune the learner treats log(alpha)
    # as a learned scalar driving policy entropy toward target_entropy
    # (nan = auto = -act_dim + sum(log action_scale) — the 1812.05905
    # -act_dim heuristic expressed in this codebase's env-unit log-probs;
    # see learner.sac_step. nan, not 0, is the sentinel: an exact-zero
    # entropy target is inside the knob's valid domain); sac_alpha is then
    # just the initial value.
    sac_alpha: float = 0.2
    sac_autotune: bool = True
    target_entropy: float = float("nan")
    # log_std clamp for the Gaussian head (standard SAC stability bounds).
    sac_log_std_min: float = -5.0
    sac_log_std_max: float = 2.0
    # Uniform-random action warmup (SAC's classic `start_steps`): for the
    # first N env steps actions are drawn uniformly from the action box
    # instead of the policy. SAC NEEDS this: its exploration is the
    # policy's own (initially narrow, entropy-bounded) Gaussian, and
    # without broad seed data swing-up style tasks never see the good
    # region (measured: Pendulum stuck ~-1100 @25k without, solved -78
    # with — docs/EVIDENCE.md §3). OU-driven families explore broadly from
    # step 0, so warmup only applies where configured. -1 = auto
    # (replay_min_size when sac, else 0); 0 = off. In the actor pool the
    # budget is split evenly across workers.
    warmup_uniform_steps: int = -1

    # --- replay (SURVEY.md §2 #5/#7) ---
    replay_capacity: int = 1_000_000
    replay_min_size: int = 1_000     # warmup before learning starts
    prioritized: bool = False
    per_alpha: float = 0.6
    per_beta: float = 0.4
    per_beta_final: float = 1.0
    per_eps: float = 1e-6
    # Force the host replay + prefetch pipeline in train_jax instead of the
    # HBM-resident DeviceReplay. The fallback for buffers too large for
    # device memory; the device path (uniform AND prioritized) is the
    # flagship zero-h2d steady state.
    host_replay: bool = False
    # Device-replay placement (replay/device.py; docs/REPLAY_SHARDING.md).
    # "replicated" (default): every device holds an identical copy kept
    # bit-identical via lockstep sync_ship — aggregate capacity equals ONE
    # device's HBM, every ingested row is copied to all N devices, and
    # this mode stays the bit-exact parity oracle. "sharded": the same
    # logical ring partitioned over the mesh's 'data' axis (strided
    # ownership — position p on shard p % N), so per-device storage is
    # capacity/N rows (~N× aggregate capacity at fixed HBM) and each
    # staged row is shipped only to its owner (~1/N landed ingest bytes,
    # the BENCH_SHARDED_REPLAY A/B headline). Sampling draws replica-
    # identical indices and reassembles the minibatch with an owner-masked
    # gather + psum inside the jitted chunk; sampled minibatches are
    # bit-identical to replicated mode. Forces the XLA scan path (the
    # megakernel reads replicated storage whole) and composes with
    # model_axis > 1 (ring on 'data' x params on 'model' — docs/MESH.md);
    # multi-host sharded runs omit replay contents from checkpoints (no
    # single-writer snapshot spans the shards).
    replay_sharding: str = "replicated"
    # Device-replay ingest pipeline (replay/device.py; docs/INGEST.md).
    # ingest_async moves single-process host->HBM shipping onto a
    # background shipper thread (bounded by the staging ring; a full ring
    # blocks the drain — backpressure) so insert dispatch overlaps learner
    # compute. Forced off under strict_sync (row-landing timing would make
    # the sampled stream a function of host scheduling, breaking the
    # bit-identical-two-runs contract) and on multi-host (rows leave only
    # via the lockstep sync_ship collective). ingest_coalesce caps how
    # many staged blocks fold into one device_put + jitted scatter
    # (power-of-two groups; 1 = the seed's serial block-at-a-time ships).
    ingest_async: bool = True
    ingest_coalesce: int = 8
    # --- unified transfer scheduler (transfer/; docs/TRANSFER.md) ---
    # One dispatch thread owns every host<->device stream — replay-ingest
    # super-blocks, prefetch chunk h2d, learner d2h accounting, and the
    # multi-host lockstep ingest collective — with prioritized work
    # classes fair-queued by bytes so prefetch never starves under an
    # ingest flood (and vice versa). Forced off under strict_sync: the
    # scheduler thread's dispatch timing would make the metrics stream a
    # function of host scheduling.
    transfer_scheduler: bool = True
    # Adaptive ingest_coalesce controller (transfer/adaptive.py): the
    # EFFECTIVE coalesce cap grows (x2, up to ingest_coalesce) while the
    # staging queue trends up and shrinks on dispatch stall. Replay
    # contents are bit-identical to the serial path for ANY cap sequence;
    # strict_sync disables it anyway because the cap trajectory (hence
    # the ingest_coalesce_mean metric) is wall-clock-driven. Single-
    # process shipping only — the lockstep collective keeps the static
    # cap so every process computes the identical k sequence.
    ingest_coalesce_adaptive: bool = True
    # Staged host-buffer pool for super-block device_put
    # (transfer/hostbuf.py): recycles the per-ship staging copy through
    # long-lived buffers fenced on the consuming insert, cutting the
    # pageable alloc+copy churn out of ingest_ship_ms.
    transfer_host_pool: bool = True
    # Multi-host: run the lockstep sync_ship collective as BACKGROUND
    # beats on the scheduler's ordered lane (replay/device.py
    # sync_ship_begin) instead of blocking the learner thread at every
    # chunk boundary. Lockstep semantics are preserved by the token
    # protocol (docs/TRANSFER.md): pending counts snapshot at beat-issue
    # time, strict FIFO lane, and the learner gates its next dispatch on
    # the previous beat's enqueue. No effect single-process.
    sync_ship_background: bool = True

    # --- batched policy-inference service (serve/; docs/SERVING.md) ---
    # Serve actor workers from one InferenceServer instead of each worker
    # running its own private act(): workers send observations over a
    # bounded mp queue, a dynamic batcher dispatches at serve_max_batch OR
    # serve_max_latency_ms (whichever fires first — TorchBeast's knobs,
    # PAPERS.md arXiv 1910.03552), and actions flow back per worker. Off
    # by default: the per-worker act() path stays the default AND the
    # parity oracle (served actions are bit-identical to it under the
    # numpy serve backend — tests/test_serve.py). Workers that cannot get
    # a served action (overload, stall, dispatch failure) DEGRADE to their
    # local policy mirror for serve_fallback_s instead of blocking — a
    # broken serving stack costs latency, never a deadlock.
    serve_actors: bool = False
    # Dispatch triggers: a collected batch goes out when it reaches
    # serve_max_batch rows or when its oldest request has waited
    # serve_max_latency_ms, whichever comes first.
    serve_max_batch: int = 32
    serve_max_latency_ms: float = 5.0
    # Bounded request queue: submissions past this raise typed
    # ServeOverload (shed + degrade, never unbounded buffering).
    serve_queue: int = 1024
    # Served-client deadline: a worker waits this long for its action
    # before falling back to the local act() path...
    serve_timeout_s: float = 1.0
    # ...and stays on the local path this long before trying the server
    # again (degraded-mode cooldown; counted in serve_client_fallbacks).
    serve_fallback_s: float = 5.0
    # Serve compute backend: "numpy" = the bit-identical parity oracle
    # (row-wise NumpyPolicy — same kernels as the per-worker act());
    # "jax" = device-resident params, one jitted apply over batches padded
    # to the fixed (serve_max_batch, obs_dim) shape (float-tolerance
    # parity, like the learner itself).
    serve_backend: str = "numpy"

    # --- network serving front (serve/front/; docs/SERVING.md §front) ---
    # External ingress over the same Batcher the served actors use:
    # a length-prefixed-frame TCP server plus an HTTP/JSON adapter,
    # versioned policy snapshots with canary promote, and per-tenant QoS.
    # 0 = disabled (the default: serving stays in-process/mp-queue only);
    # any other value binds that port on localhost (0 is also what tests
    # pass programmatically to FrontServer for an ephemeral port — the
    # config knob reserves 0 for "off" and FrontServer itself treats 0 as
    # "pick one", matching the obs/ exporter convention).
    front_port: int = 0
    front_http_port: int = 0
    # Server-side deadline: a request older than this when its batch
    # completes is answered with a typed `timeout` wire error.
    front_timeout_s: float = 2.0
    # Canary split: fraction of traffic deterministically routed to the
    # candidate version while one is staged (crc32(tenant:request_id)
    # bucketing — replayable, not random).
    front_canary_fraction: float = 0.1
    # The live gate needs this many latency samples on BOTH stable and
    # candidate before it can promote (ci_gate's arm-on-first-capture
    # discipline applied to live traffic: never promote on thin data).
    front_canary_min_requests: int = 50
    # Allowed relative p95-latency regression of candidate vs stable;
    # past it the canary auto-rolls-back (THRESHOLD's live twin).
    front_canary_threshold: float = 0.5
    # Tenant table: "name:priority[:rate[:burst]];..." — priority 0 is
    # highest (never depth-shed), rate is tokens/s (0 = uncapped),
    # burst defaults to max(1, rate). Unknown tenants get
    # front_default_priority and no rate cap.
    front_tenants: str = ""
    front_default_priority: int = 1
    # Queue-depth fraction where priority shedding begins: the LOWEST
    # priority class sheds at this depth, higher classes at staggered
    # deeper thresholds, priority 0 only at a full queue (typed
    # overload) — the "sheds lowest-priority first" contract.
    front_shed_start: float = 0.5

    # --- device-actor backend (actors/device_pool.py; docs/DEVICE_ACTORS.md) ---
    # Where rollouts run on the jax_tpu path. "host" (default): N worker
    # PROCESSES step CPU envs, OU noise runs in numpy, and rows cross
    # host->HBM through the ingest pipeline — the only option for
    # Gym/Mujoco envs. "device": a Podracer/Anakin-style vectorized actor
    # (PAPERS.md arXiv 2104.06272) — one jitted lax.scan advances
    # device_actor_envs copies of the JAX env (envs/jax_envs.py), the
    # policy mu(s) and per-env OU noise run in the same program, and the
    # transition rows scatter STRAIGHT into DeviceReplay's HBM ring with a
    # donated insert: no host staging, no transfer-scheduler ingest class,
    # zero host<->device bytes on the experience path. Param refresh is a
    # device-side pointer swap from the learner's live params. Requires a
    # JAX env implementation (has_jax_env), validated at parse. Unlike
    # backend='jax_ondevice' (the fused monolith), the learner keeps its
    # full feature set — PER, guardrails, serving, multi-host — and the
    # host pool can run alongside (num_actors > 0) feeding the same replay.
    actor_backend: str = "host"
    # E: vectorized envs advanced per device-actor chunk (the rollout's
    # vmap width). Thousands are cheap on a TPU — env physics is a few
    # FLOPs per step; CPU tests use small values.
    device_actor_envs: int = 1024
    # K: env steps per rollout dispatch (the lax.scan length); each chunk
    # produces K * device_actor_envs transitions in one program.
    # 0 = auto: 64 on kernel-native TPU backends, 8 elsewhere (mirrors
    # learner_chunk's resolution discipline).
    device_actor_chunk: int = 0

    # --- fused training megastep (parallel/megastep.py; docs/FUSED_BEAT.md) ---
    # Anakin-style fused beat (PAPERS.md arXiv 2104.06272): compile the
    # whole rollout -> ring-scatter -> sample -> K-learner-updates beat
    # into ONE jitted program per loop iteration, so the host dispatches a
    # single program per beat (zero host round-trips inside it) instead of
    # three. Composes the device-actor rollout, the DeviceReplay insert
    # (replicated or sharded), and the learner's XLA-scan sampling chunk —
    # guarded or unguarded: the PR-7 guardrail probe threads through the
    # fused program, so guardrails=True keeps the fast path. "auto"
    # (default): fuse whenever actor_backend='device' on the device-replay
    # path with free-running ratios and the Pallas megakernel inactive
    # (the kernel has no rollout/probe slot inside a larger program);
    # "on": require it (config error when the composition is impossible);
    # "off": always dispatch per phase. Bit-identical to the separate
    # dispatch sequence for fixed seeds (tests/test_megastep.py).
    fused_beat: str = "auto"
    # Compile-once multi-beat superstep (parallel/superstep.py): compose B
    # fused beats inside one donated-carry lax.fori_loop, so an entire
    # epoch — B x (sample+learn, rollout, scatter, guardrail probe) — is a
    # SINGLE XLA program per dispatch and per-beat host Python goes to
    # zero (the full Anakin epoch-as-one-dispatch shape, PAPERS.md arXiv
    # 2104.06272; host-orchestration overhead per arXiv 2012.04210).
    # Stats/health accumulate in a device-side carry with ONE device_get
    # per superstep; multi-host sync_ship/ingest beats still ride BETWEEN
    # supersteps. 1 (default) = today's per-beat dispatch, bit-identical
    # oracle; B > 1 requires the fused beat to be active (fused_beat !=
    # 'off') and produces bit-identical state to B sequential beats
    # (tests/test_superstep.py). Budget/cadence checks run once per
    # superstep, so env-budget overshoot is bounded by B x rows-per-beat.
    superstep_beats: int = 1

    # --- exploration (SURVEY.md §2 #6) ---
    ou_theta: float = 0.15
    ou_sigma: float = 0.2
    ou_dt: float = 1.0

    # --- distributed topology ---
    num_actors: int = 1
    # Actor->learner experience transport: "shm" = per-worker C++ SPSC ring
    # in shared memory (native/replay_core.cpp, zero pickling); "queue" =
    # mp.Queue; "auto" = shm when the native toolchain is available.
    transport: str = "auto"
    # Per-worker ring capacity (rows). Sized to absorb a learner-dispatch
    # of production smoothing, not to buffer stalls: a full ring BLOCKS its
    # worker (worker.py flush), mirroring the queue transport's backpressure.
    shm_ring_rows: int = 4096
    # {"native", "jax_tpu", "jax_ondevice"} (BASELINE.json:5). jax_ondevice
    # runs env physics + replay + learner fused in one XLA program
    # (ondevice.py); num_actors then means on-device vector envs.
    backend: str = "jax_tpu"
    data_axis: int = -1              # -1: all devices on data axis
    # Tensor-parallel degree over hidden dims (the mesh's 'model' axis).
    # Params + Adam moments shard per the regex rule tables in
    # parallel/partition.py (per-device param+opt HBM / model_axis);
    # composes with sharded replay, device actors, the serve jax backend,
    # and the fused megastep — see docs/MESH.md for the decision table.
    model_axis: int = 1
    # Data-parallel batch semantics for the device-sampling learner paths:
    # True (default) = batch_size is PER-DEVICE — each data-axis device
    # draws its own batch_size rows and the global batch grows with the
    # mesh (grads merge via the sharding-induced AllReduce), so adding
    # chips adds throughput. False = batch_size is the GLOBAL batch sharded
    # ever thinner across devices (round-2 semantics, kept for fixed-batch
    # scaling studies; collective latency swamps compute past ~2 devices).
    scale_batch_with_data: bool = True
    train_every: int = 1             # env steps between learner steps (sync mode)
    # Async ingest rate limiter (the staleness-control knob SURVEY.md §7
    # 'hard parts (b)' calls for): cap drained env steps at
    # replay_min_size + ratio * learner_steps. When actors outpace the
    # learner the rings/queues fill and workers block, throttling the env
    # stepping itself. 0 = free-running async (the reference's semantics).
    max_ingest_ratio: float = 0.0
    # Learner-rate cap (the converse of max_ingest_ratio, and the knob the
    # equal-return quality gate turns): learner steps <= replay_min_size +
    # ratio * env steps. The reference's sync semantics are ratio = 1/
    # train_every; 0 = free-running async (learner as fast as the TPU goes).
    max_learn_ratio: float = 0.0
    # Experiment knob: per-env-step sleep (seconds) inside each worker.
    # 0 = off (production). Nonzero slows env production so the LEARNER can
    # saturate the ratio caps on hosts where it otherwise couldn't — the
    # staleness sweep (docs/EVIDENCE.md §4) needs learner capability >>
    # cap x env rate for a cap to bind at all; on the 1-core CPU host the
    # unthrottled 16-actor config keeps the effective ratio < 1 and every
    # sweep point would silently measure the same thing. Wall-clock only:
    # the algorithmic quantity (grad steps per env step) is unchanged.
    actor_throttle_s: float = 0.0
    # Lockstep debug mode (SURVEY.md §5 race detection): actors run INLINE
    # on the driver thread (actors/sync_pool.py) in deterministic
    # round-robin order, eval runs synchronously, and the wall-clock floors
    # on param refresh / metrics logging are ignored — two runs of the same
    # config produce bit-identical metrics, so any divergence against an
    # async run isolates a race in the async machinery. Requires both
    # ratio gates armed (the drain budget is the deterministic schedule).
    strict_sync: bool = False
    param_refresh_every: int = 1     # learner steps between actor param refresh
    # Wall-clock floor between actor param broadcasts in train_jax. A
    # broadcast must sync the in-flight chunk and round-trip params
    # device->host, which costs ~chunk-compute x20 on a tunneled TPU; the
    # floor bounds that overhead to a fixed fraction of wall time while
    # param_refresh_every keeps the learner-step semantics.
    param_refresh_interval_s: float = 0.1
    prefetch_depth: int = 2          # host->HBM double-buffer depth
    # Learner steps per dispatch (lax.scan / megakernel chunk length) in
    # train_jax. 0 = auto: 800 on kernel-native TPU backends (measured —
    # the rate saturates around 800 while one dispatch stays ~4 ms, see
    # BENCH_r*.json), 8 elsewhere (CPU dev/test dispatches stay snappy).
    # Ingest, param refresh, and the env-step budget check all run once per
    # chunk, so the chunk also bounds ingest latency and budget overshoot.
    learner_chunk: int = 0

    # --- precision ---
    compute_dtype: str = "float32"   # bit-comparability oracle needs f32
    fused_update: bool = False       # pallas fused Adam+Polyak kernel
    # Pallas megakernel: the whole K-step chunk in one kernel launch, params
    # VMEM-resident across the chunk (ops/fused_chunk.py). "auto" uses it on
    # the single-device TPU sample-chunk path whenever the config is in the
    # kernel's envelope; "on" requires it (error if unsupported); "off" never.
    fused_chunk: str = "auto"
    # Megakernel x mesh composition (parallel/learner.py fused-mesh path):
    # on a multi-device DATA-parallel mesh each device runs the megakernel
    # on its own independent minibatch draws for the whole K-step chunk,
    # and float state (params, targets, Adam moments) is AVERAGED across
    # the data axis at chunk boundaries (one params-sized AllReduce per K
    # steps instead of K per-step gradient psums — per-step sync would
    # evict params from VMEM every step and forfeit the kernel's entire
    # HBM-traffic win). This is K-step local SGD: sync semantics differ
    # from the scan path's per-step psum by a bounded O(lr*K) divergence
    # (docs/PERF_NOTES.md has the staleness argument + measured parity).
    # "auto": compose whenever the megakernel is active and the mesh is
    # data-only (model_axis == 1); "off": multi-device meshes always use
    # the scan path (exact per-step sync).
    fused_mesh: str = "auto"

    # --- run control ---
    # Stall watchdog (watchdog.py): if the jax_tpu trainer makes no
    # progress for this many seconds — including during learner
    # construction and the first params d2h, both unbounded blocking
    # device calls on a tunneled TPU — dump every thread's stack and
    # hard-exit(70) instead of hanging silently. 0 = off (tests and
    # interactive runs); production/ladder runs should set ~300.
    watchdog_s: float = 0.0
    total_env_steps: int = 100_000
    eval_every: int = 5_000
    eval_episodes: int = 5
    checkpoint_every: int = 10_000
    checkpoint_dir: str = ""
    # Latest-N retention: a full-replay checkpoint is ~3 GB (1M rows), so
    # keeping every cadence point fills a disk mid-run (round-5 incident:
    # 6.4 GB by 340k steps of a 2M-step Humanoid run). 0 = keep all.
    checkpoint_keep: int = 3
    resume: bool = True              # auto-restore latest checkpoint_dir state
    log_path: str = ""               # JSONL metrics path ("" = stdout only)
    tb_dir: str = ""                 # TensorBoard summary dir ("" = off)
    profile_dir: str = ""            # jax.profiler trace dir ("" = off)
    # Flight-recorder tracing (trace.py): when set, train_jax records
    # thread-tagged spans from every hot component (learner phases, ingest
    # shipper, prefetcher, eval/ckpt threads, actor workers) into a
    # preallocated ring and writes Perfetto-loadable Chrome trace JSON
    # here on clean exit, on SIGUSR2, and from the watchdog's stall path
    # (which also drops stall_report.json). "" = off (the span calls are
    # shared no-op context managers). Cheap enough to leave on for every
    # production run — see docs/OBSERVABILITY.md.
    trace_dir: str = ""
    # Ring capacity in events; at steady state ~4 events per learner chunk
    # + shipper/eval activity, 65536 holds tens of minutes of timeline.
    trace_events: int = 65_536
    # Telemetry-plane ingress (obs/; docs/OBSERVABILITY.md §4): when > 0,
    # train_jax starts one stdlib HTTP exporter thread on this port
    # serving /metrics (Prometheus text from the latest JSONL record),
    # /healthz (the typed healthy/degraded/draining state machine the
    # supervisor and canary gate consume), and /trace (on-demand
    # flight-recorder export). Read-only, no auth, binds all interfaces —
    # private networks only. 0 = off (default). Multi-process pods give
    # each process its OWN port (e.g. base + process index).
    obs_port: int = 0

    # --- fault injection & supervised recovery (docs/RESILIENCE.md) ---
    # Deterministic fault schedule (faults.FaultPlan grammar), e.g.
    # --faults='worker:2:crash@5000;worker:0:hang@8000;ckpt:write:ioerror@2'
    # — scripts crashes/hangs/slowdowns/IO errors into actor workers, the
    # ingest shipper, the prefetcher, and the checkpoint writer. Replaces
    # the old one-shot --inject_fault hook (its 'actor:<id>:<step>' form
    # still parses, as a worker crash). "" = no faults (production).
    faults: str = ""
    # Pool monitor: respawn a worker silent past this many seconds
    # (actors/pool.py heartbeats — SURVEY.md §5 'Failure detection').
    heartbeat_timeout_s: float = 30.0
    # Actor-side blind spot (watchdog.py coverage note): respawn a worker
    # that HEARTBEATS but has produced zero experience rows for this many
    # seconds. 0 = off — the default, because legitimate zero-row windows
    # (very long episodes with n-step holdback, heavy backpressure) are
    # config-dependent; chaos runs and production fleets should set it to
    # a few multiples of the expected flush interval.
    actor_no_progress_s: float = 0.0
    # Respawn backoff: the k-th recent failure of the SAME worker slot
    # waits min(base * 2^(k-1), max) seconds before the respawn — a
    # crash-looping worker must not be respawned in a tight loop (every
    # respawn re-pays cold-start cost and can itself re-trigger the
    # boot stampede the heartbeat sentinel exists for).
    respawn_backoff_s: float = 0.5
    respawn_backoff_max_s: float = 30.0
    # Crash-loop circuit breaker: this many failures of the same slot
    # within quarantine_window_s quarantines the slot — the pool logs
    # loudly, stops respawning it, and training continues degraded on the
    # remaining workers (SURVEY.md §5; a stampede of doomed respawns is
    # strictly worse than one missing actor). 0 = breaker off.
    quarantine_respawns: int = 5
    quarantine_window_s: float = 60.0
    # Quarantine probing (docs/RESILIENCE.md): after this cooldown the
    # monitor PROBES a quarantined slot with a single respawn attempt —
    # sustained progress (rows delivered + surviving quarantine_window_s)
    # un-quarantines it (counter actor_unquarantined), any failure during
    # the probe re-quarantines immediately for another cooldown. A
    # half-capacity fleet whose fault was transient (OOM storm, env-server
    # restart) recovers without a run restart. 0 = never probe (the
    # pre-PR-5 behavior: quarantine is permanent for the run's lifetime).
    quarantine_probe_s: float = 300.0
    # Checkpoint write retry (checkpoint.py): transient IO failures retry
    # up to this many times with exponential backoff before surfacing.
    ckpt_write_retries: int = 2
    ckpt_retry_backoff_s: float = 0.5
    # --- numerical-health guardrails (guardrails.py; docs/RESILIENCE.md) ---
    # On-device divergence detection fused into the learner chunk: finite
    # checks on TD targets/grads/updated params plus EWMA z-score anomaly
    # detection on critic loss & grad norm; a bad step's update is DROPPED
    # on device (bad-batch quarantine), non-finite sampled replay rows are
    # recorded for ingest-source attribution, and sustained divergence
    # triggers automatic rollback to the last manifest-valid checkpoint.
    # Off by default: guardrails force the XLA scan path (the Pallas
    # megakernel has no probe slot), add one tiny health-word d2h sync per
    # chunk, and the disabled path is pinned bit-identical to the
    # pre-guardrail programs (tests/test_guardrails.py parity). Turn on
    # for unattended/production runs.
    guardrails: bool = False
    # One-sided z-score threshold for the loss/grad-norm anomaly detector
    # (divergence is always UP). Generous by default: a false skip drops
    # one update; a false rollback costs a checkpoint cadence.
    guardrail_zmax: float = 8.0
    # Clean steps the EWMA absorbs before z-scores arm (early-training
    # loss scale is nonstationary; finite checks are armed from step 1).
    guardrail_warmup_steps: int = 64
    # Rollback trigger: this many anomalous (skipped) learner steps within
    # guardrail_rollback_window steps -> restore the last manifest-valid
    # checkpoint (PR-4 restore walk; pods coordinate the step through the
    # PR-6 election). 0 = detect/skip/quarantine only, never roll back.
    guardrail_rollback_k: int = 8
    guardrail_rollback_window: int = 256
    # Rollback budget: a run that needs more than this many rollbacks (or
    # needs one with no restorable checkpoint) aborts with the documented
    # EXIT_NUMERIC (77) instead of thrashing restore/diverge forever.
    guardrail_max_rollbacks: int = 3
    # LR cooldown on rollback: both learner LRs scale by this factor after
    # a rollback and restore once guardrail_lr_cooldown_steps clean steps
    # pass (each transition costs one XLA recompile, like a support
    # expansion). 1.0 = off.
    guardrail_lr_backoff: float = 0.5
    guardrail_lr_cooldown_steps: int = 2000
    # Ingest-source quarantine: this many non-finite replay rows attributed
    # to the same actor slot quarantine that slot through the pool's
    # breaker machinery (probing un-quarantines it later). 0 = off.
    guardrail_source_offenses: int = 3
    # --- pod resilience (parallel/multihost.py; docs/RESILIENCE.md) ---
    # Deadline on every host-initiated DCN collective (sync_ship beats,
    # the env-budget all-gather, the scheduler's lockstep lane): a
    # collective whose peer died surfaces as a typed PodPeerLost within
    # this many seconds — coordinated clean abort, emergency checkpoint,
    # exit EXIT_POD_DEGRADED (76) — instead of blocking the pod forever.
    # Armed only on multi-process runs (single-process collectives
    # short-circuit, zero overhead); known-long windows (first-chunk XLA
    # compile, support expansion) get the same grant the stall watchdog
    # gets, so compile skew between processes is not read as peer death.
    # Keep it well under watchdog_s where both are armed — peer loss
    # should exit 76 (resumable pod abort), not 70 (wedged device).
    # 0 = off (the pre-PR-6 block-forever behavior).
    pod_collective_timeout_s: float = 60.0
    # One-time startup rendezvous grace (multihost.startup_barrier),
    # deliberately much larger than the steady-state deadline: backend
    # init / import skew under host load is absorbed once at startup
    # instead of false-firing the per-beat deadline (the documented gloo
    # child startup flake, CHANGES.md PR 5).
    pod_startup_grace_s: float = 300.0

    def replace(self, **kwargs) -> "DDPGConfig":
        return dataclasses.replace(self, **kwargs)

    def fault_plan(self):
        """The parsed (seeded) FaultPlan for this run. Parsed on demand —
        validation already ran in __post_init__, so this cannot raise."""
        from distributed_ddpg_tpu.faults import FaultPlan

        return FaultPlan.parse(self.faults, seed=self.seed)

    def resolved_warmup_uniform(self) -> int:
        """Global uniform-warmup env-step budget (see warmup_uniform_steps:
        -1 = auto = replay_min_size for SAC, 0 otherwise)."""
        if self.warmup_uniform_steps >= 0:
            return self.warmup_uniform_steps
        return self.replay_min_size if self.sac else 0

    @classmethod
    def from_flags(cls, argv: Sequence[str]) -> "DDPGConfig":
        """Parse `--key=value` / `--key value` CLI overrides onto the defaults."""
        import argparse

        parser = argparse.ArgumentParser(prog="distributed_ddpg_tpu")
        for field in dataclasses.fields(cls):
            if field.type in ("bool", bool):
                parser.add_argument(
                    f"--{field.name}",
                    type=lambda s: s.lower() in ("1", "true", "yes"),
                    default=field.default,
                )
            elif field.name in ("actor_hidden", "critic_hidden"):
                parser.add_argument(
                    f"--{field.name}",
                    type=lambda s: tuple(int(x) for x in s.split(",")),
                    default=field.default,
                )
            elif field.name in ("v_min", "v_max"):
                # "auto" -> nan sentinel (warmup-derived support sizing).
                parser.add_argument(
                    f"--{field.name}",
                    type=lambda s: float("nan") if s == "auto" else float(s),
                    default=field.default,
                )
            else:
                ftype = {"int": int, "float": float, "str": str}.get(
                    str(field.type), str
                )
                parser.add_argument(f"--{field.name}", type=ftype, default=field.default)
        # Deprecated alias (pre-chaos-harness scripts): --inject_fault's
        # 'actor:<id>:<step>' one-shot crash folds into the --faults plan,
        # whose grammar accepts the legacy form directly.
        parser.add_argument("--inject_fault", type=str, default="")
        args = vars(parser.parse_args(argv))
        legacy = args.pop("inject_fault")
        if legacy:
            args["faults"] = ";".join(filter(None, [args["faults"], legacy]))
        return cls(**args)

    @property
    def v_support_auto(self) -> bool:
        """True when the C51 support is auto-sized (v_min/v_max = nan).
        Consumers must resolve concrete bounds (support_auto.initial_bounds)
        before building a learner step — linspace over nan is all-nan."""
        return math.isnan(self.v_min)

    def __post_init__(self):
        if self.backend not in ("native", "jax_tpu", "jax_ondevice"):
            raise ValueError(
                "backend must be 'native', 'jax_tpu', or 'jax_ondevice', "
                f"got {self.backend!r}"
            )
        if self.n_step < 1:
            raise ValueError("n_step must be >= 1")
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be 'float32' or 'bfloat16', got "
                f"{self.compute_dtype!r}"
            )
        if self.compute_dtype == "bfloat16" and self.backend == "native":
            raise ValueError(
                "compute_dtype='bfloat16' requires a JAX backend: the "
                "native numpy learner is the f32 bit-comparability oracle"
            )
        if self.fused_chunk not in ("auto", "on", "off"):
            raise ValueError(
                f"fused_chunk must be 'auto', 'on', or 'off', got "
                f"{self.fused_chunk!r}"
            )
        if self.fused_mesh not in ("auto", "off"):
            raise ValueError(
                f"fused_mesh must be 'auto' or 'off', got {self.fused_mesh!r}"
            )
        if self.ingest_coalesce < 1:
            raise ValueError("ingest_coalesce must be >= 1")
        if self.replay_sharding not in ("replicated", "sharded"):
            raise ValueError(
                f"replay_sharding must be 'replicated' or 'sharded', got "
                f"{self.replay_sharding!r}"
            )
        if self.replay_sharding == "sharded":
            if self.backend != "jax_tpu":
                raise ValueError(
                    "replay_sharding='sharded' partitions the DeviceReplay "
                    "HBM ring over the jax_tpu mesh; the native/ondevice "
                    "backends have no sharded ring"
                )
            if self.host_replay:
                raise ValueError(
                    "replay_sharding='sharded' shards the DEVICE replay; "
                    "host_replay has no device ring to shard — disable one"
                )
            if self.fused_chunk == "on":
                raise ValueError(
                    "replay_sharding='sharded' forces the XLA scan path "
                    "(the Pallas megakernel reads replicated storage "
                    "whole) — incompatible with fused_chunk='on'; use "
                    "'auto' (degrades to scan) or 'off'"
                )
            if self.data_axis > 0:
                # Mesh-dependent alignment checks run again at replay
                # construction with the ACTUAL device count; with an
                # explicit data_axis they can fail fast at parse.
                if self.replay_capacity % self.data_axis:
                    raise ValueError(
                        f"replay_capacity {self.replay_capacity} must "
                        f"divide evenly over data_axis={self.data_axis} "
                        "shards (replay_sharding='sharded')"
                    )
                if self.actor_backend == "device":
                    from distributed_ddpg_tpu.actors.device_pool import (
                        resolve_device_actor_chunk,
                    )

                    rows = (
                        self.device_actor_envs
                        * resolve_device_actor_chunk(self)
                    )
                    if rows % self.data_axis:
                        raise ValueError(
                            f"one device-actor chunk produces {rows} rows, "
                            f"which do not divide over data_axis="
                            f"{self.data_axis} replay shards — sharded "
                            "mode requires every insert_device_rows "
                            "scatter to move a multiple of the shard "
                            "count (keeps the ring pointer shard-aligned)."
                            " Adjust device_actor_envs/device_actor_chunk"
                        )
        # --- tensor parallelism (model_axis > 1; parallel/partition.py,
        # docs/MESH.md). The composition matrix: TP is LEGAL with sharded
        # replay (ring on 'data' x params on 'model'), device actors, the
        # serve jax backend, and the fused megastep; the genuine
        # rejections below each name the knob to flip. ---
        if self.model_axis < 1:
            raise ValueError(
                f"model_axis must be >= 1, got {self.model_axis} (1 = "
                "data-parallel only)"
            )
        if self.model_axis > 1:
            if self.backend == "native":
                raise ValueError(
                    "model_axis > 1 shards params over a jax mesh; the "
                    "native numpy backend has no mesh — use "
                    "backend='jax_tpu' (or 'jax_ondevice'), or set "
                    "model_axis=1"
                )
            if self.fused_chunk == "on":
                raise ValueError(
                    "model_axis > 1 shards the param tensors the Pallas "
                    "megakernel needs VMEM-whole — incompatible with "
                    "fused_chunk='on'; use fused_chunk='auto' (degrades "
                    "to the XLA scan path) or 'off', or set model_axis=1"
                )
            for knob in ("actor_hidden", "critic_hidden"):
                bad = [
                    d for d in getattr(self, knob)
                    if d % self.model_axis != 0
                ]
                if bad:
                    raise ValueError(
                        f"model_axis={self.model_axis} cannot shard "
                        f"{knob}={tuple(getattr(self, knob))}: hidden "
                        f"dim(s) {bad} do not divide the model axis, so "
                        "every layer would silently replicate and TP "
                        f"would buy nothing — pick {knob} dims divisible "
                        f"by {self.model_axis}, or lower model_axis"
                    )
        if self.policy_delay < 1:
            raise ValueError("policy_delay must be >= 1")
        if self.target_noise < 0 or self.target_noise_clip < 0:
            raise ValueError("target_noise/target_noise_clip must be >= 0")
        if not self.twin_critic and (
            self.policy_delay > 1 or self.target_noise > 0
        ):
            raise ValueError(
                "policy_delay/target_noise are TD3 knobs consumed only by "
                "the twin-critic step — set twin_critic=True or they would "
                "silently do nothing"
            )
        v_min_auto = math.isnan(self.v_min)
        v_max_auto = math.isnan(self.v_max)
        if v_min_auto != v_max_auto:
            raise ValueError(
                "v_min/v_max auto-sizing derives BOTH bounds from the same "
                "warmup statistics — set both to 'auto' or neither"
            )
        if v_min_auto and not self.distributional:
            raise ValueError(
                "v_min/v_max='auto' sizes the distributional critic's "
                "support; it requires distributional=True"
            )
        if v_min_auto and not 0.0 < self.gamma < 1.0:
            raise ValueError(
                f"v_min/v_max='auto' needs 0 < gamma < 1 (got {self.gamma}): "
                "the sizing bound r/(1-gamma^n) blows up at gamma=1, and 51 "
                "atoms over a near-infinite range cannot resolve real "
                "returns — pass concrete bounds for undiscounted setups"
            )
        if v_min_auto and self.backend == "jax_ondevice":
            raise ValueError(
                "v_min/v_max='auto' sizes the support from host-visible "
                "warmup replay rewards; the fused on-device backend has no "
                "such window — pass concrete bounds"
            )
        if not v_min_auto and self.distributional and self.v_min >= self.v_max:
            raise ValueError(
                f"v_min ({self.v_min}) must be < v_max ({self.v_max})"
            )
        if self.twin_critic and self.distributional:
            raise ValueError(
                "twin_critic (TD3) and distributional (D4PG) are separate "
                "algorithm families; enable one"
            )
        if self.sac and (self.twin_critic or self.distributional):
            raise ValueError(
                "sac is its own algorithm family (it builds its twin-critic "
                "ensemble internally); disable twin_critic/distributional"
            )
        if self.sac and self.fused_update:
            raise ValueError(
                "sac composes with the stock Adam+Polyak tree update (the "
                "alpha scalar rides the same path), not the fused_update "
                "kernel"
            )
        if self.sac and self.backend == "native":
            raise ValueError(
                "sac requires a JAX backend: the native numpy learner is "
                "the plain-DDPG bit-comparability oracle"
            )
        if self.sac_alpha <= 0:
            raise ValueError("sac_alpha must be > 0 (it is exp(log_alpha))")
        if self.sac_log_std_min >= self.sac_log_std_max:
            raise ValueError("sac_log_std_min must be < sac_log_std_max")
        if self.twin_critic and self.fused_update:
            raise ValueError(
                "twin_critic composes with the stock Adam+Polyak tree update"
                " (delayed via lax.cond), not the fused_update kernel"
            )
        if self.twin_critic and self.backend == "native":
            raise ValueError(
                "twin_critic requires a JAX backend: the native numpy "
                "learner is the plain-DDPG bit-comparability oracle"
            )
        if self.max_ingest_ratio < 0:
            raise ValueError("max_ingest_ratio must be >= 0 (0 = unlimited)")
        if self.learner_chunk < 0:
            raise ValueError("learner_chunk must be >= 0 (0 = auto)")
        if self.checkpoint_keep < 0:
            raise ValueError("checkpoint_keep must be >= 0 (0 = keep all)")
        if self.max_learn_ratio < 0:
            raise ValueError("max_learn_ratio must be >= 0 (0 = unlimited)")
        if self.actor_throttle_s < 0:
            raise ValueError("actor_throttle_s must be >= 0 (0 = off)")
        if self.strict_sync:
            if self.backend != "jax_tpu":
                raise ValueError(
                    "strict_sync is a train_jax (jax_tpu backend) debug "
                    "mode; the native backend is already single-threaded "
                    "and deterministic, and the fused on-device backend "
                    "has no host actor loop to make lockstep"
                )
            if self.max_learn_ratio <= 0 or self.max_ingest_ratio <= 0:
                raise ValueError(
                    "strict_sync derives its deterministic ingest schedule "
                    "from the ratio gates; set max_learn_ratio and "
                    "max_ingest_ratio (1.0 each = the reference's "
                    "synchronous 1:1 schedule)"
                )
            if self.host_replay:
                raise ValueError(
                    "strict_sync requires the device replay path: the host "
                    "prefetch thread samples concurrently with ingest, "
                    "which is exactly the nondeterminism this mode removes"
                )
        if self.warmup_uniform_steps < -1:
            raise ValueError(
                "warmup_uniform_steps must be >= -1 (-1 = auto, 0 = off)"
            )
        if (
            self.max_learn_ratio > 0
            and self.max_ingest_ratio > 0
            and self.max_learn_ratio * self.max_ingest_ratio < 1.0
        ):
            raise ValueError(
                "max_learn_ratio * max_ingest_ratio < 1 livelocks: each "
                "counter waits on the other and neither allowance can ever "
                "open. With product >= 1 (e.g. both 1.0 — the equal-return "
                "gate pinning ~1 grad step per env step from BOTH sides) "
                "the two advance together at the slower side's pace."
            )
        if self.param_refresh_interval_s < 0:
            raise ValueError("param_refresh_interval_s must be >= 0")
        if self.serve_max_batch < 1:
            raise ValueError("serve_max_batch must be >= 1")
        if self.serve_max_latency_ms < 0:
            raise ValueError("serve_max_latency_ms must be >= 0")
        if self.serve_queue < 1:
            raise ValueError("serve_queue must be >= 1")
        if self.serve_timeout_s <= 0:
            raise ValueError("serve_timeout_s must be > 0")
        if self.serve_fallback_s < 0:
            raise ValueError("serve_fallback_s must be >= 0")
        if self.serve_backend not in ("numpy", "jax"):
            raise ValueError(
                f"serve_backend must be 'numpy' or 'jax', got "
                f"{self.serve_backend!r}"
            )
        if self.serve_actors:
            if self.backend != "jax_tpu":
                raise ValueError(
                    "serve_actors serves the actor POOL (jax_tpu backend); "
                    "the native/ondevice backends have no worker fleet to "
                    "serve"
                )
            if self.strict_sync:
                raise ValueError(
                    "serve_actors is incompatible with strict_sync: batch "
                    "composition and dispatch timing are wall-clock-driven, "
                    "which breaks the bit-identical-two-runs contract"
                )
            # SAC is served too (PR 20): the server holds per-client
            # sampling keys derived from (seed, tenant, request_id) and
            # returns already-sampled actions (serve/server.py `sample`;
            # docs/SERVING.md 'SAC serve head') — the old rejection of
            # sac + serve_actors is lifted.
        if self.front_port < 0 or self.front_port > 65535:
            raise ValueError("front_port must be in [0, 65535] (0 = off)")
        if self.front_http_port < 0 or self.front_http_port > 65535:
            raise ValueError(
                "front_http_port must be in [0, 65535] (0 = off)"
            )
        if (
            self.front_port
            and self.front_http_port
            and self.front_port == self.front_http_port
        ):
            raise ValueError(
                "front_port and front_http_port must differ: the frame "
                "server and the HTTP adapter each bind their own socket"
            )
        if self.front_timeout_s <= 0:
            raise ValueError("front_timeout_s must be > 0")
        if not 0.0 < self.front_canary_fraction < 1.0:
            raise ValueError(
                "front_canary_fraction must be in (0, 1): 0 would starve "
                "the candidate of gate samples forever, 1 would route ALL "
                "traffic through an unproven version"
            )
        if self.front_canary_min_requests < 1:
            raise ValueError("front_canary_min_requests must be >= 1")
        if self.front_canary_threshold <= 0:
            raise ValueError("front_canary_threshold must be > 0")
        if self.front_default_priority < 0:
            raise ValueError("front_default_priority must be >= 0")
        if not 0.0 < self.front_shed_start <= 1.0:
            raise ValueError("front_shed_start must be in (0, 1]")
        if self.front_tenants:
            # Fail fast at parse, not at first shed: a typo'd tenant
            # table discovered mid-run would silently misprioritize.
            from distributed_ddpg_tpu.serve.front.qos import parse_tenants

            parse_tenants(self.front_tenants)
        if (self.front_port or self.front_http_port) and not self.serve_actors:
            raise ValueError(
                "the network front rides the serve subsystem's "
                "InferenceServer: set serve_actors=True (docs/SERVING.md "
                "'Network front')"
            )
        if self.actor_backend not in ("host", "device"):
            raise ValueError(
                f"actor_backend must be 'host' or 'device', got "
                f"{self.actor_backend!r}"
            )
        if self.device_actor_envs < 1:
            raise ValueError("device_actor_envs must be >= 1")
        if self.device_actor_chunk < 0:
            raise ValueError("device_actor_chunk must be >= 0 (0 = auto)")
        if self.num_actors < 0 or (
            self.num_actors == 0 and self.actor_backend != "device"
        ):
            raise ValueError(
                "num_actors must be >= 1 (0 is allowed only with "
                "actor_backend='device', where the on-device rollout loop "
                "is the experience source and the host pool runs empty)"
            )
        if self.actor_backend == "device":
            if self.backend != "jax_tpu":
                raise ValueError(
                    "actor_backend='device' runs the vectorized rollout "
                    "loop inside the jax_tpu trainer; the native backend "
                    "has no device, and jax_ondevice already fuses its "
                    "envs into the learner monolith — use backend='jax_tpu'"
                )
            # Lazy import: jax_envs pulls in jax, which config parsing must
            # not pay for on the (default) host path.
            from distributed_ddpg_tpu.envs.jax_envs import (
                _JAX_ENVS,
                has_jax_env,
            )

            if not has_jax_env(self.env_id):
                raise ValueError(
                    f"actor_backend='device' needs an on-device (JAX) "
                    f"implementation of {self.env_id!r}; available: "
                    f"{sorted(set(_JAX_ENVS))} — keep actor_backend='host' "
                    "for Gym/Mujoco envs (docs/DEVICE_ACTORS.md)"
                )
            if self.serve_actors:
                raise ValueError(
                    "serve_actors batches host workers' act() requests; "
                    "device actors never call act() on the host — mu(s) "
                    "runs inside the rollout program. Disable serve_actors "
                    "(or serve a host pool alongside via actor_backend="
                    "'host')"
                )
            if self.n_step != 1:
                raise ValueError(
                    "actor_backend='device' stores 1-step transitions "
                    "(the n-step window is a host-side accumulator, "
                    "replay/nstep.py); use the host pool for n_step > 1"
                )
            if self.host_replay:
                raise ValueError(
                    "actor_backend='device' scatters rollout rows "
                    "directly into DeviceReplay's HBM ring; host_replay "
                    "has no device ring to insert into — disable one"
                )
            if self.strict_sync:
                raise ValueError(
                    "strict_sync's lockstep schedule is defined over the "
                    "host pool's deterministic drain budget; device-actor "
                    "chunks dispatch outside it — use actor_backend='host' "
                    "for lockstep debugging"
                )
            from distributed_ddpg_tpu.actors.device_pool import (
                resolve_device_actor_chunk,
            )

            rows = self.device_actor_envs * resolve_device_actor_chunk(self)
            if rows > self.replay_capacity:
                raise ValueError(
                    f"one device-actor chunk produces {rows} rows "
                    f"(device_actor_envs={self.device_actor_envs} x "
                    f"chunk {resolve_device_actor_chunk(self)}) — more "
                    f"than replay_capacity={self.replay_capacity}: the "
                    "scatter insert would write duplicate ring positions "
                    "in unspecified order. Shrink the chunk/env count or "
                    "grow the replay"
                )
        if self.fused_beat not in ("auto", "on", "off"):
            raise ValueError(
                f"fused_beat must be 'auto', 'on', or 'off', got "
                f"{self.fused_beat!r}"
            )
        if self.fused_beat == "on":
            # The fused megastep composes the device-actor rollout, the
            # device-replay insert, and the learner chunk into one program
            # (docs/FUSED_BEAT.md); every leg must exist. The device-actor
            # validation above already rejects n_step > 1, serve_actors,
            # host_replay, and strict_sync for actor_backend='device', so
            # those combinations fail through their own messages.
            if self.backend != "jax_tpu":
                raise ValueError(
                    "fused_beat='on' fuses the jax_tpu training loop; the "
                    "native backend has no device programs and "
                    "jax_ondevice is already a fused monolith (ondevice.py)"
                )
            if self.actor_backend != "device":
                raise ValueError(
                    "fused_beat='on' needs the on-device rollout leg "
                    "(actor_backend='device'): host actor processes step "
                    "envs outside XLA and cannot be compiled into the "
                    "beat — use the dispatch-per-phase loop for host "
                    "actors"
                )
            if self.fused_chunk == "on":
                raise ValueError(
                    "fused_beat='on' composes the XLA scan sampling chunk "
                    "(the Pallas megakernel has no rollout/probe slot "
                    "inside a larger traced program) — incompatible with "
                    "fused_chunk='on'; use 'auto' or 'off'"
                )
            if self.max_ingest_ratio > 0.0 or self.max_learn_ratio > 0.0:
                raise ValueError(
                    "fused_beat='on' fixes the rollout:learn ratio inside "
                    "one program (device_actor_envs x chunk rows per "
                    "learner_chunk steps, every beat); the "
                    "max_ingest_ratio/max_learn_ratio gates need "
                    "independently dispatchable phases to throttle — "
                    "disable the gates or use fused_beat='auto'/'off'"
                )
        if self.superstep_beats < 1:
            raise ValueError(
                f"superstep_beats must be >= 1, got {self.superstep_beats}"
            )
        if self.superstep_beats > 1 and self.fused_beat == "off":
            raise ValueError(
                "superstep_beats > 1 composes B FUSED beats into one "
                "lax.fori_loop program (parallel/superstep.py) — it has "
                "no unfused dispatch to wrap; use fused_beat='auto'/'on' "
                "or superstep_beats=1"
            )
        # Fail fast on fault-grammar typos: a bad spec must die at config
        # parse, not hours later when the fault was scheduled to fire.
        from distributed_ddpg_tpu.faults import FaultPlan

        FaultPlan.parse(self.faults, seed=self.seed)
        if self.heartbeat_timeout_s <= 0:
            raise ValueError("heartbeat_timeout_s must be > 0")
        if self.actor_no_progress_s < 0:
            raise ValueError("actor_no_progress_s must be >= 0 (0 = off)")
        if self.respawn_backoff_s < 0 or self.respawn_backoff_max_s < 0:
            raise ValueError("respawn backoff values must be >= 0")
        if self.quarantine_respawns < 0:
            raise ValueError("quarantine_respawns must be >= 0 (0 = off)")
        if self.quarantine_window_s <= 0:
            raise ValueError("quarantine_window_s must be > 0")
        if self.quarantine_probe_s < 0:
            raise ValueError("quarantine_probe_s must be >= 0 (0 = off)")
        if self.ckpt_write_retries < 0:
            raise ValueError("ckpt_write_retries must be >= 0")
        if self.ckpt_retry_backoff_s < 0:
            raise ValueError("ckpt_retry_backoff_s must be >= 0")
        if self.guardrails:
            if self.backend != "jax_tpu":
                raise ValueError(
                    "guardrails instrument the sharded-learner chunk "
                    "programs (jax_tpu backend); the native/ondevice "
                    "backends have no probe slot"
                )
            if self.fused_chunk == "on":
                raise ValueError(
                    "guardrails=True forces the XLA scan path (the Pallas "
                    "megakernel has no health-probe slot) — incompatible "
                    "with fused_chunk='on'; use 'auto' (degrades to scan) "
                    "or 'off'"
                )
        if self.guardrail_zmax <= 0:
            raise ValueError("guardrail_zmax must be > 0")
        if self.guardrail_warmup_steps < 1:
            raise ValueError("guardrail_warmup_steps must be >= 1")
        if self.guardrail_rollback_k < 0:
            raise ValueError(
                "guardrail_rollback_k must be >= 0 (0 = never roll back)"
            )
        if self.guardrail_rollback_window < 1:
            raise ValueError("guardrail_rollback_window must be >= 1")
        if self.guardrail_max_rollbacks < 0:
            raise ValueError("guardrail_max_rollbacks must be >= 0")
        if not 0.0 < self.guardrail_lr_backoff <= 1.0:
            raise ValueError(
                "guardrail_lr_backoff must be in (0, 1] (1.0 = off)"
            )
        if self.guardrail_lr_cooldown_steps < 1:
            raise ValueError("guardrail_lr_cooldown_steps must be >= 1")
        if self.guardrail_source_offenses < 0:
            raise ValueError(
                "guardrail_source_offenses must be >= 0 (0 = off)"
            )
        if self.pod_collective_timeout_s < 0:
            raise ValueError("pod_collective_timeout_s must be >= 0 (0 = off)")
        if self.pod_startup_grace_s < 0:
            raise ValueError("pod_startup_grace_s must be >= 0")
        if self.trace_events < 16:
            raise ValueError("trace_events must be >= 16")
        if not 0 <= self.obs_port < 65536:
            raise ValueError(
                f"obs_port must be 0 (off) or a valid TCP port, "
                f"got {self.obs_port}"
            )
        if self.transport not in ("auto", "shm", "queue"):
            raise ValueError(
                f"transport must be 'auto', 'shm', or 'queue', got "
                f"{self.transport!r}"
            )
        if not 0 <= self.action_insert_layer <= len(self.critic_hidden):
            raise ValueError(
                f"action_insert_layer={self.action_insert_layer} out of range "
                f"for critic with {len(self.critic_hidden) + 1} layers"
            )
