"""DDPG/D4PG agent: ties networks, replay, noise, and the jitted learner step
together behind the reference's agent surface — `act(state)`,
`observe(transition)`, `train_step()` (SURVEY.md §1 'Agent / algorithm',
§2 #2 `ddpg.py`).

This class is the single-process composition (ladder rung 1,
BASELINE.json:7). The distributed composition reuses the same pieces:
actors/ run `act`+`observe` in worker processes, the train.py driver loop
runs `train_step` against the sharded mesh learner (parallel/learner.py).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs.registry import EnvSpec
from distributed_ddpg_tpu.learner import (
    StepOutput,
    init_train_state,
    jit_learner_step,
    make_act_fn,
    make_sample_fn,
)
from distributed_ddpg_tpu.ops import support_auto
from distributed_ddpg_tpu.ops.noise import OUNoise
from distributed_ddpg_tpu.replay import NStepAccumulator, make_replay
from distributed_ddpg_tpu.types import Batch, batch_from_numpy


class DDPGAgent:
    def __init__(self, config: DDPGConfig, spec: EnvSpec):
        self.config = config
        self.spec = spec
        self.state = init_train_state(config, spec.obs_dim, spec.act_dim, config.seed)
        self._step_fn = jit_learner_step(
            config, spec.action_scale, action_offset=spec.action_offset
        )
        self._act_fn = make_act_fn(
            config, spec.action_scale, action_offset=spec.action_offset
        )
        # SAC explores by sampling its own policy; OU noise stays unused.
        self._sample_fn = (
            make_sample_fn(config, spec.action_scale, action_offset=spec.action_offset)
            if config.sac
            else None
        )
        self._act_key = jax.random.PRNGKey(config.seed + 2) if config.sac else None
        # Uniform-random warmup (SAC start_steps; config.warmup_uniform_steps).
        self._warmup_uniform = config.resolved_warmup_uniform()
        self._warmup_rng = np.random.default_rng(config.seed + 3)
        self._env_steps = 0
        self.replay = make_replay(config, spec.obs_dim, spec.act_dim)
        self.noise = OUNoise(
            (spec.act_dim,),
            theta=config.ou_theta,
            sigma=config.ou_sigma,
            dt=config.ou_dt,
            seed=config.seed + 1,
        )
        self.nstep = NStepAccumulator(config.n_step, config.gamma)
        self._learn_steps = 0
        # Auto C51 support (resolved lazily at the first train_step; the
        # flag must outlive the resolution — after it self.config carries
        # concrete bounds and v_support_auto reads False).
        self._support_auto_active = config.distributional and config.v_support_auto
        self._support_controller = support_auto.SupportController()

    def _set_value_bounds(self, v_min: float, v_max: float) -> None:
        self.config = self.config.replace(v_min=float(v_min), v_max=float(v_max))
        self._step_fn = jit_learner_step(
            self.config, self.spec.action_scale,
            action_offset=self.spec.action_offset,
        )

    # --- acting (SURVEY.md §3.2) ---

    def act(self, obs: np.ndarray, explore: bool = True) -> np.ndarray:
        if explore and self._env_steps < self._warmup_uniform:
            return self._warmup_rng.uniform(
                self.spec.action_low, self.spec.action_high
            ).astype(np.float32)
        if explore and self.config.sac:
            self._act_key, k = jax.random.split(self._act_key)
            action = np.asarray(
                self._sample_fn(self.state.actor_params, obs[None], k)
            )[0]
            return np.clip(action, self.spec.action_low, self.spec.action_high)
        action = np.asarray(self._act_fn(self.state.actor_params, obs[None]))[0]
        if explore:
            action = action + self.noise() * self.spec.action_scale
        return np.clip(action, self.spec.action_low, self.spec.action_high)

    def reset_episode(self) -> None:
        self.noise.reset()
        self.nstep.reset()

    # --- experience (SURVEY.md §3.2 replay.add) ---

    def observe(self, obs, action, reward, done, next_obs) -> None:
        self._env_steps += 1
        for o, a, r, disc, nobs in self.nstep.push(
            obs[None], action[None], [reward], [done], next_obs[None]
        ):
            self.replay.add(o, a, r, disc, nobs)

    # --- learning (SURVEY.md §3.3) ---

    def can_train(self) -> bool:
        return len(self.replay) >= max(self.config.replay_min_size, self.config.batch_size)

    def train_step(self) -> Optional[Dict[str, float]]:
        if not self.can_train():
            return None
        if self.config.distributional and self.config.v_support_auto:
            # Auto C51 support (ops/support_auto.py): the replay just crossed
            # the warmup threshold, so size the bounds from its reward
            # statistics and rebuild the (lazily jitted) step — no compile
            # has happened yet, so this costs nothing extra. After this the
            # config carries concrete bounds and the branch never re-enters.
            # Running expansion: the SupportController check further down.
            v_lo, v_hi = support_auto.replay_data_bounds(
                self.replay, self.config.gamma, self.config.n_step
            )
            self._set_value_bounds(v_lo, v_hi)
        sample = self.replay.sample(self.config.batch_size)
        indices = sample.pop("indices")
        batch = batch_from_numpy(sample)
        out: StepOutput = self._step_fn(self.state, batch)
        self.state = out.state
        self._learn_steps += 1
        support_metrics = {}
        if self._support_auto_active and self._learn_steps % 50 == 0:
            # Corroborated against the replay's CURRENT rewards — a
            # diverging mean_q must not drag the support up
            # (support_auto docstring, seed-1 incident).
            grown = self._support_controller.check(
                self.config.v_min, self.config.v_max,
                float(out.metrics["mean_q"]), self._learn_steps,
                data_bounds_fn=lambda: support_auto.replay_data_bounds(
                    self.replay, self.config.gamma, self.config.n_step
                ),
            )
            if grown is not None:
                self._set_value_bounds(*grown)
        if self._support_auto_active:
            # Same observability as the train_jax path: the refusal count
            # is the diverging-critic signature.
            support_metrics = dict(
                support_refusals=self._support_controller.refusals
            )
        if self.config.prioritized:
            # The only extra device->host transfer PER costs (uniform replay
            # skips it entirely — update_priorities would be a no-op).
            self.replay.update_priorities(indices, np.asarray(out.td_errors))
            frac = min(1.0, self._learn_steps / self._expected_learn_steps())
            self.replay.set_beta(
                self.config.per_beta
                + frac * (self.config.per_beta_final - self.config.per_beta)
            )
        return {
            **{k: float(v) for k, v in jax.device_get(out.metrics).items()},
            **support_metrics,
        }

    def _expected_learn_steps(self) -> int:
        """Learner steps this run will take — the PER beta annealing horizon
        (learner steps lag env steps by the warmup and by train_every)."""
        cfg = self.config
        return max(1, (cfg.total_env_steps - cfg.replay_min_size) // cfg.train_every)

    # --- evaluation ---

    def evaluate(self, env, episodes: int = 5, seed: int = 10_000) -> float:
        returns = []
        for ep in range(episodes):
            obs, _ = env.reset(seed=seed + ep)
            done = False
            total = 0.0
            while not done:
                action = self.act(obs, explore=False)
                obs, r, terminated, truncated, _ = env.step(action)
                total += r
                done = terminated or truncated
            returns.append(total)
        return float(np.mean(returns))
