"""The BASELINE.md benchmark ladder as runnable configs (SURVEY.md §7 step 8:
'Benchmark harness — runs the §6 ladder, emits the metric').

Each rung of BASELINE.json:6-12 maps to a DDPGConfig; `run(rung)` trains it
and emits the primary metric (learner grad-steps/sec + final return) as one
JSONL record per rung. `--smoke` shrinks every rung — step budgets AND net
sizes — so each completes in seconds; topology (actors, backend, mesh,
PER) is unchanged.

Rungs (BASELINE.md):
  1 Pendulum-v1          1 actor   uniform       native (CPU baseline)
  2 LunarLanderContinuous 4 actors  uniform      jax_tpu, 1 core
  3 BipedalWalker-v3      8 actors  prioritized  jax_tpu, data-parallel mesh
  4 HalfCheetah-v4       16 actors  uniform      jax_tpu, full local mesh
  5 Humanoid-v4          64 actors  uniform      jax_tpu, multi-host
    (rung 5 spans hosts via JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/
     JAX_PROCESS_ID — parallel/multihost.py; single-host it degrades to the
     local mesh.)

Usage:
    python -m distributed_ddpg_tpu.ladder --rungs=1,2 --smoke
    python -m distributed_ddpg_tpu.ladder --rungs=4          # full rung 4
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict

from distributed_ddpg_tpu.config import DDPGConfig

_COMMON = dict(actor_hidden=(256, 256), critic_hidden=(256, 256))
# The jax rungs pin ~1 grad step per env step from BOTH sides
# (config.py: ratio product >= 1 is livelock-free): that is the
# reference's sync replay ratio, which the equal-return gate compares
# against. Free-running async (the throughput mode bench.py measures)
# is a flag away: --max_learn_ratio=0 --max_ingest_ratio=0.
# watchdog_s: ladder runs are driver-managed wall-clock budgets — a wedged
# device/tunnel must crash loudly (watchdog.py, exit 70) instead of eating
# the budget as a silent hang (observed in-round: a PJRT init that never
# returned after the remote tunnel dropped).
_GATED = dict(
    max_learn_ratio=1.0, max_ingest_ratio=1.0, watchdog_s=300.0, **_COMMON
)

RUNGS: Dict[int, DDPGConfig] = {
    1: DDPGConfig(
        env_id="Pendulum-v1", backend="native", num_actors=1,
        total_env_steps=50_000, **_COMMON,
    ),
    2: DDPGConfig(
        env_id="LunarLanderContinuous-v2", backend="jax_tpu", num_actors=4,
        total_env_steps=300_000, **_GATED,
    ),
    3: DDPGConfig(
        env_id="BipedalWalker-v3", backend="jax_tpu", num_actors=8,
        prioritized=True, total_env_steps=1_000_000,
        # n-step 3: vanilla (1-step) plateaus at 74 final / eval peak 141
        # over 1M steps; 3-step credit assignment SOLVES the env — eval 301
        # by 400k, final 293 at 600k (runs/r4_rung3_nstep3.jsonl). BASELINE
        # pins env/actors/PER for this rung, not the return horizon.
        n_step=3, **_GATED,
    ),
    4: DDPGConfig(
        env_id="HalfCheetah-v4", backend="jax_tpu", num_actors=16,
        total_env_steps=1_000_000, **_GATED,
    ),
    5: DDPGConfig(
        env_id="Humanoid-v4", backend="jax_tpu", num_actors=64,
        total_env_steps=2_000_000, **_GATED,
    ),
}

_SMOKE = dict(
    total_env_steps=3_000,
    replay_min_size=256,
    # Small dispatches, explicitly: the TPU auto chunk (800) exceeds the
    # gated rungs' initial allowance at replay_min 256 (train_jax's
    # startup-livelock check would refuse to run).
    learner_chunk=8,
    eval_every=3_000,
    eval_episodes=1,
    replay_capacity=50_000,
    # Smoke means seconds-per-rung: shrink the nets too, or rung 1's
    # (256,256) native numpy learner alone blows the budget.
    actor_hidden=(64, 64),
    critic_hidden=(64, 64),
    # Pace ingest so smoke runs exercise a real actor/learner interleaving
    # instead of the actors blowing through the whole step budget during
    # first-chunk compile (free-running ratio 0 is meaningless at this
    # scale: 8 learner steps against 16k env steps).
    max_ingest_ratio=50.0,
)


def run(rung: int, smoke: bool = False, log_dir: str = "") -> Dict[str, float]:
    from distributed_ddpg_tpu.train import train

    config = RUNGS[rung]
    if smoke:
        config = config.replace(**_SMOKE)
    if log_dir:
        import os

        os.makedirs(log_dir, exist_ok=True)
        config = config.replace(
            log_path=os.path.join(log_dir, f"rung{rung}_{config.env_id}.jsonl")
        )
    summary = train(config)
    # platform: the backend field says which CODE PATH ran (jax_tpu = the
    # sharded mesh learner); the platform says which HARDWARE it ran on —
    # a jax_tpu rung executes fine on CPU (dev boxes, outages), and a
    # record that doesn't say so misreads as a TPU measurement. The native
    # rung is CPU by definition and must stay off the accelerator: an
    # unconditional jax.devices() here would INITIALIZE the default (TPU)
    # backend that the whole native path deliberately never touches — and
    # hang the finished measurement on a wedged tunnel. For jax backends
    # the train run already initialized the backend, so this is a lookup,
    # not an init.
    if config.backend == "native":
        platform = "cpu"
    else:
        import jax

        platform = jax.devices()[0].platform

    record = {
        "kind": "ladder",
        "rung": rung,
        "env_id": config.env_id,
        "backend": config.backend,
        "platform": platform,
        "num_actors": config.num_actors,
        "prioritized": config.prioritized,
        **{k: round(v, 3) if isinstance(v, float) else v for k, v in summary.items()},
    }
    print(json.dumps(record), flush=True)
    return record


def main(argv=None) -> None:
    from distributed_ddpg_tpu.platform_util import honor_jax_platforms

    honor_jax_platforms()
    p = argparse.ArgumentParser(prog="distributed_ddpg_tpu.ladder")
    p.add_argument("--rungs", default="1,2,3,4,5",
                   help="comma-separated rung numbers from BASELINE.md")
    p.add_argument("--smoke", action="store_true",
                   help="seconds-per-rung budgets (topology unchanged)")
    p.add_argument("--log_dir", default="",
                   help="write per-rung JSONL metrics under this directory")
    args = p.parse_args(argv if argv is not None else sys.argv[1:])
    for rung in (int(r) for r in args.rungs.split(",")):
        run(rung, smoke=args.smoke, log_dir=args.log_dir)


if __name__ == "__main__":
    main()
