"""Structured metrics: JSONL + stdout + optional TensorBoard (SURVEY.md §5
'Metrics / logging').

The reference's only observability was TensorBoard scalar summaries
[RECALL]; here the primary sink is append-only JSONL (one object per event,
machine-parseable by the bench harness) plus optional human lines, with a
TensorBoard sink (`tb_dir`) kept for parity — scalars land under
`<kind>/<field>`. Tracked quantities follow SURVEY.md §5: episode return,
losses, mean Q, grad norms, buffer fill, actor/learner steps/sec, staleness.
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time
import warnings
import zlib
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from distributed_ddpg_tpu import trace


class MetricsLogger:
    def __init__(self, path: str = "", echo: bool = True, tb_dir: str = ""):
        self._file = open(path, "a", buffering=1) if path else None
        self._echo = echo
        self._t0 = time.time()
        # log() is called from the train loop AND from the background eval
        # thread (train.py); serialize sinks so JSONL lines never interleave.
        self._lock = threading.Lock()
        # Latest record per kind: the live /metrics endpoint's source
        # (obs/exporter.py) — a scrape must never replay the file.
        self._latest: Dict[str, Dict[str, Any]] = {}
        self._tb = None
        if tb_dir:
            try:
                # torch (CPU) is a baked-in dependency; its pure-Python event
                # writer needs no torch tensors for scalars.
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tb_dir)
            except Exception as e:  # degrade to JSONL-only, loudly once
                warnings.warn(f"tb_dir={tb_dir!r} requested but TensorBoard "
                              f"writer unavailable: {e}")
        # Every stream opens with ONE header record carrying the absolute
        # wall-clock base: `wall_time` below is seconds since logger
        # creation, so without this a pod's N per-process JSONL files (or
        # two runs of one config) cannot be joined on time at all —
        # merge tooling computes absolute event time as
        # t_unix_base + wall_time (docs/OBSERVABILITY.md §1).
        self.t_unix_base = round(self._t0, 6)
        self.log("header", 0, t_unix_base=self.t_unix_base, pid=os.getpid())

    def log(self, kind: str, step: int, **fields: Any) -> Dict[str, Any]:
        rec = {
            "kind": kind,
            "step": step,
            "wall_time": round(time.time() - self._t0, 3),
            **{k: _jsonable(v) for k, v in fields.items()},
        }
        line = json.dumps(rec)
        with self._lock:
            self._latest[kind] = rec
            if self._file:
                self._file.write(line + "\n")
            if self._echo:
                print(line, file=sys.stdout, flush=True)
            if self._tb is not None:
                for k, v in rec.items():
                    if k in ("kind", "step") or not isinstance(v, (int, float)):
                        continue
                    self._tb.add_scalar(f"{kind}/{k}", v, step)
        return rec

    def latest(self) -> Dict[str, Dict[str, Any]]:
        """{kind: most recent record} — the /metrics render source
        (obs/exporter.py). Shallow-copied so the scrape thread iterates
        a stable dict while the train loop keeps logging."""
        with self._lock:
            return dict(self._latest)

    def close(self) -> None:
        if self._file:
            self._file.close()
        if self._tb is not None:
            self._tb.close()


def _jsonable(v):
    """JSONL field coercion. Bools and ints pass through AS THEIR TYPE —
    the old blanket float() turned `fused_chunk_active: true` into `1.0`
    in every record, which downstream parsers (tools/runs.py) then can't
    distinguish from a measured scalar. Floats (incl. numpy scalars) keep
    the 6-decimal rounding that bounds record size."""
    if isinstance(v, bool):
        return v
    if isinstance(v, int):
        return v
    if hasattr(v, "item") and not hasattr(v, "__len__"):
        # numpy/JAX zero-dim scalar: unwrap to the native type first so
        # np.bool_/np.int64 survive as bool/int.
        try:
            return _jsonable(v.item())
        except (TypeError, ValueError):
            return v
    try:
        return round(float(v), 6)
    except (TypeError, ValueError):
        return v


class _Reservoir:
    """Fixed-size uniform sample of per-call durations (Vitter's
    Algorithm R) + exact running max: the memory-bounded way to carry tail
    latencies (p50/p95) across an arbitrary-length logging interval.
    Deterministically seeded so strict_sync's bit-identical-metrics
    contract survives — two identical runs admit identical samples."""

    __slots__ = ("k", "n", "buf", "max", "_rng")

    def __init__(self, k: int, seed: int):
        self.k = k
        self.n = 0
        self.buf: List[float] = []
        self.max = 0.0
        self._rng = random.Random(seed)

    def add(self, x: float) -> None:
        self.n += 1
        if x > self.max:
            self.max = x
        if len(self.buf) < self.k:
            self.buf.append(x)
        else:
            j = self._rng.randrange(self.n)
            if j < self.k:
                self.buf[j] = x

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the reservoir (q in [0, 1])."""
        s = sorted(self.buf)
        if not s:
            return 0.0
        return s[min(len(s) - 1, int(q * len(s)))]


class PhaseTimers:
    """Per-phase wall-time counters + tail latencies (SURVEY.md §5
    'per-step timing of sample→h2d→step→d2h'; VERDICT.md round-1 Weak #9).
    Phases are whatever the caller brackets — train_jax uses dispatch
    (chunk submit), ingest (actor h2d), sync (metrics d2h), sample_wait
    (host-prefetch starvation), ckpt, eval_snapshot. snapshot() emits per
    interval and resets:

      t_<name>_ms    mean ms per call (the seed's field — kept)
      n_<name>       calls in the interval
      t_<name>_p50 / t_<name>_p95 / t_<name>_max
                     reservoir percentiles + exact max, ms

    The percentiles are the point: the 8-device ingest regression in
    BENCH_r05 hid behind a healthy MEAN — a per-interval p95/max puts a
    one-in-fifty 600ms dispatch straight into the JSONL record instead of
    averaging it into noise. Every phase bracket also emits a flight-
    recorder span (trace.py) under the phase's name, so the same bracket
    feeds both the scalar record and the Perfetto timeline."""

    # Reservoir size: 256 doubles/phase bounds memory; p95 over a typical
    # 50-call interval is exact (reservoir bigger than the population).
    RESERVOIR_K = 256

    def __init__(self, seed: int = 0):
        self._acc: Dict[str, float] = {}
        self._n: Dict[str, int] = {}
        self._res: Dict[str, _Reservoir] = {}
        self._seed = seed

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        with trace.span(name):
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self._acc[name] = self._acc.get(name, 0.0) + dt
                self._n[name] = self._n.get(name, 0) + 1
                r = self._res.get(name)
                if r is None:
                    # Phase-name-derived seed: deterministic per phase
                    # AND per process — crc32, not hash(), because str
                    # hashing is salted per interpreter (PYTHONHASHSEED)
                    # and a run-varying seed would make which samples
                    # survive the reservoir (hence reported p50/p95)
                    # partly run-to-run noise.
                    r = self._res[name] = _Reservoir(
                        self.RESERVOIR_K,
                        (zlib.crc32(name.encode()) ^ self._seed) & 0x7FFFFFFF,
                    )
                r.add(dt)

    def snapshot(self, reset: bool = True) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name, total in self._acc.items():
            n = max(self._n.get(name, 1), 1)
            out[f"t_{name}_ms"] = round(1000.0 * total / n, 3)
            out[f"n_{name}"] = self._n.get(name, 0)
            r = self._res.get(name)
            if r is not None and r.buf:
                out[f"t_{name}_p50"] = round(1000.0 * r.percentile(0.50), 3)
                out[f"t_{name}_p95"] = round(1000.0 * r.percentile(0.95), 3)
                out[f"t_{name}_max"] = round(1000.0 * r.max, 3)
        if reset:
            self._acc.clear()
            self._n.clear()
            self._res.clear()
        return out


class IngestStats:
    """Thread-safe counters for the replay ingest pipeline (docs/INGEST.md;
    the inbound mirror of PhaseTimers' outbound sample/h2d breakdown).

    Producers call record_push (rows staged + time spent stalled on a full
    staging ring); the shipper calls record_ship (rows/blocks moved to HBM
    per device call + the dispatch wall time). snapshot() emits the
    `ingest_*` fields each train/bench record carries and resets the
    interval, so every JSONL line describes its own window:

      ingest_rows_per_sec   rows landed in HBM over the interval
      ingest_rows_staged    rows pushed into the staging ring over the
                            interval (staged - shipped trending up =
                            backlog growth)
      ingest_ship_calls     device_put+insert dispatches in the interval
      ingest_coalesce_mean  staged blocks folded into one dispatch (>=1;
                            1.0 = no coalescing happened = inflow arrived
                            slower than one block per ship)
      ingest_stall_ms       total time producers blocked on backpressure
      ingest_ship_ms        mean dispatch wall time per ship call
      ingest_queue_rows     staged rows not yet shipped (queue depth)
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._rows_in = 0
        self._rows_shipped = 0
        self._blocks_shipped = 0
        self._ship_calls = 0
        self._stall_s = 0.0
        self._ship_s = 0.0

    def record_push(self, rows: int, stall_s: float = 0.0) -> None:
        with self._lock:
            self._rows_in += int(rows)
            self._stall_s += stall_s

    def record_ship(self, rows: int, blocks: int, ship_s: float = 0.0) -> None:
        with self._lock:
            self._rows_shipped += int(rows)
            self._blocks_shipped += int(blocks)
            self._ship_calls += 1
            self._ship_s += ship_s

    def snapshot(self, pending_rows: int = 0, reset: bool = True) -> Dict[str, float]:
        with self._lock:
            dt = max(time.monotonic() - self._t0, 1e-9)
            calls = self._ship_calls
            out = {
                "ingest_rows_per_sec": round(self._rows_shipped / dt, 1),
                "ingest_rows_staged": self._rows_in,
                "ingest_ship_calls": calls,
                "ingest_coalesce_mean": (
                    round(self._blocks_shipped / calls, 3) if calls else 0.0
                ),
                "ingest_stall_ms": round(1000.0 * self._stall_s, 3),
                "ingest_ship_ms": (
                    round(1000.0 * self._ship_s / calls, 3) if calls else 0.0
                ),
                "ingest_queue_rows": int(pending_rows),
            }
            if reset:
                self._t0 = time.monotonic()
                self._rows_in = 0
                self._rows_shipped = 0
                self._blocks_shipped = 0
                self._ship_calls = 0
                self._stall_s = 0.0
                self._ship_s = 0.0
        return out


class ReplayShardStats:
    """Thread-safe counters for the device-replay placement layer
    (replay/device.py; docs/REPLAY_SHARDING.md) — the `replay_*` family
    every train/bench record carries on the device-replay path, and the
    BENCH_SHARDED_REPLAY A/B's raw input. Byte counters are MEASURED from
    the device_put result's addressable shards (one copy per replica in
    replicated mode, exactly one owner copy in sharded mode), so the
    bytes-per-row headline is an observation, not arithmetic:

      replay_ingest_bytes          h2d bytes landed on devices this
                                   interval (sum over device copies)
      replay_ingest_bytes_per_row  interval mean landed bytes per row —
                                   ~width*4*N replicated, ~width*4
                                   sharded (the 1/N ingest claim; the
                                   ci_gate lower-is-better key)
      replay_shard_count           gauge: storage shards (1 = replicated)
      replay_device_storage_bytes  gauge: storage bytes ONE device holds
                                   (capacity*width*4/N sharded — the N×
                                   aggregate-capacity claim at fixed HBM)
      replay_shard_fill_min/max    gauge: live rows on the emptiest/
                                   fullest shard (strided ownership keeps
                                   them within 1 of each other)
      replay_exchange_ms_p50/p95   interval ship-dispatch tails (the
                                   shard-exchange latency signal)
    """

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = seed
        self._reset_locked()

    def _reset_locked(self) -> None:
        self._rows = 0
        self._bytes = 0
        self._res = _Reservoir(
            PhaseTimers.RESERVOIR_K,
            (zlib.crc32(b"replay_exchange") ^ self._seed) & 0x7FFFFFFF,
        )

    def record_ship(self, rows: int, nbytes: int, dur_s: float) -> None:
        with self._lock:
            self._rows += int(rows)
            self._bytes += int(nbytes)
            self._res.add(dur_s)

    def snapshot(
        self,
        n_shards: int = 1,
        device_storage_bytes: int = 0,
        fill: int = 0,
        reset: bool = True,
    ) -> Dict[str, float]:
        with self._lock:
            rows = self._rows
            out = {
                "replay_ingest_bytes": self._bytes,
                "replay_ingest_bytes_per_row": (
                    round(self._bytes / rows, 2) if rows else 0.0
                ),
                "replay_shard_count": int(n_shards),
                "replay_device_storage_bytes": int(device_storage_bytes),
                # Shard s owns live logical rows {p < fill : p % N == s}.
                "replay_shard_fill_min": (
                    int(fill) // int(n_shards) if n_shards else 0
                ),
                "replay_shard_fill_max": (
                    -(-int(fill) // int(n_shards)) if n_shards else 0
                ),
                "replay_exchange_ms_p50": round(
                    1000.0 * self._res.percentile(0.50), 3
                ),
                "replay_exchange_ms_p95": round(
                    1000.0 * self._res.percentile(0.95), 3
                ),
            }
            if reset:
                self._reset_locked()
        return out


class MeshStats:
    """Placement facts for the (data, model) mesh (parallel/mesh.py +
    parallel/partition.py; docs/MESH.md) — the `mesh_*` family every
    train/final JSONL record carries on the jax_tpu path. All gauges,
    recomputed at log cadence from leaf SHARDING METADATA only (shapes x
    shard shapes — zero d2h, zero device work):

      mesh_data_axis               the mesh's data-parallel degree
      mesh_model_axis              the mesh's tensor-parallel degree
      mesh_param_bytes_per_device  TrainState bytes (params + targets +
                                   both Adam states) resident on ONE
                                   device — the /model_axis HBM headline
                                   the rule tables buy (docs/MESH.md)
      mesh_param_bytes_total       logical (unsharded) TrainState bytes,
                                   the per-device value's denominator

    No lock: the fields derive from immutable mesh shape + per-leaf
    metadata reads, and only the learner thread snapshots them."""

    def __init__(self, data_axis: int, model_axis: int):
        self._data = int(data_axis)
        self._model = int(model_axis)

    def snapshot(self, state_leaves) -> Dict[str, float]:
        per_device = 0
        total = 0
        for leaf in state_leaves:
            shape = tuple(getattr(leaf, "shape", ()))
            itemsize = int(getattr(getattr(leaf, "dtype", None),
                                   "itemsize", 4))
            n = 1
            for d in shape:
                n *= int(d)
            total += n * itemsize
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and hasattr(sharding, "shard_shape"):
                m = 1
                for d in sharding.shard_shape(shape):
                    m *= int(d)
                per_device += m * itemsize
            else:
                per_device += n * itemsize
        return {
            "mesh_data_axis": self._data,
            "mesh_model_axis": self._model,
            "mesh_param_bytes_per_device": per_device,
            "mesh_param_bytes_total": total,
        }


class DevActorStats:
    """Counters for the device-actor subsystem (actors/device_pool.py;
    docs/DEVICE_ACTORS.md) — the `devactor_*` family every train/final
    JSONL record carries when actor_backend='device'. Throughput and the
    per-chunk dispatch tails are interval-scoped (each record describes
    its own window, the IngestStats discipline); restarts and the episode
    counter are cumulative. Single-threaded by construction (only the
    learner thread dispatches rollouts), but locked anyway so a future
    driver thread can't silently race it:

      devactor_rows_per_s   transition rows landed in HBM over the interval
      devactor_chunks       rollout dispatches in the interval
      devactor_chunk_ms     mean wall time per rollout dispatch (enqueue +
                            donated insert — NOT the on-device compute,
                            which overlaps the learner under async dispatch)
      devactor_chunk_p50/p95/max
                            reservoir tails of the same (the per-chunk
                            step-tail signal: a p95 spike means rollout
                            dispatch started synchronizing with the
                            learner stream)
      devactor_env_steps    cumulative env steps produced by this pool
      devactor_episodes     cumulative finished episodes
      devactor_episode_return
                            mean return of episodes finished this interval
      devactor_restarts     cumulative bounded-restart recoveries
    """

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = seed
        self._t0 = time.monotonic()
        self._rows = 0
        self._chunks = 0
        self._dur_s = 0.0
        self._res = _Reservoir(
            PhaseTimers.RESERVOIR_K,
            (zlib.crc32(b"devactor_chunk") ^ seed) & 0x7FFFFFFF,
        )

    def record_chunk(self, rows: int, dur_s: float) -> None:
        with self._lock:
            self._rows += int(rows)
            self._chunks += 1
            self._dur_s += dur_s
            self._res.add(dur_s)

    def snapshot(self, reset: bool = True) -> Dict[str, float]:
        with self._lock:
            dt = max(time.monotonic() - self._t0, 1e-9)
            n = self._chunks
            out = {
                "devactor_rows_per_s": round(self._rows / dt, 1),
                "devactor_chunks": n,
                "devactor_chunk_ms": (
                    round(1000.0 * self._dur_s / n, 3) if n else 0.0
                ),
                "devactor_chunk_p50": round(
                    1000.0 * self._res.percentile(0.50), 3
                ),
                "devactor_chunk_p95": round(
                    1000.0 * self._res.percentile(0.95), 3
                ),
                "devactor_chunk_max": round(1000.0 * self._res.max, 3),
            }
            if reset:
                self._t0 = time.monotonic()
                self._rows = 0
                self._chunks = 0
                self._dur_s = 0.0
                self._res = _Reservoir(
                    PhaseTimers.RESERVOIR_K,
                    (zlib.crc32(b"devactor_chunk") ^ self._seed) & 0x7FFFFFFF,
                )
        return out


class FusedBeatStats:
    """Counters for the fused training megastep (parallel/megastep.py;
    docs/FUSED_BEAT.md) — the `fused_*` family every train/final JSONL
    record carries when the fused beat is active. All interval-scoped
    (each record describes its own window, the DevActorStats discipline);
    single-threaded by construction (only the learner thread dispatches
    beats), locked anyway like its siblings:

      fused_beats           fused beat dispatches in the interval
      fused_steps_per_s     learner grad steps retired over the interval
                            (the BENCH_FUSED headline / ci_gate key)
      fused_rows_per_s      rollout transition rows landed over the
                            interval (the beat's in-program insert)
      fused_beat_ms         mean wall time per beat dispatch (enqueue +
                            donated-carry sync, one program per beat)
      fused_beat_p50/p95/max
                            reservoir tails of the same (a p95 spike
                            means the single beat program started
                            synchronizing against the host)
      fused_supersteps      superstep DISPATCHES in the interval — equals
                            fused_beats for the plain megastep, and
                            fused_beats / B for a B-beat superstep
                            (parallel/superstep.py): the host-overhead
                            amortization the BENCH_SUPERSTEP row measures
      fused_superstep_beats beats per dispatch over the interval (B; 1.0
                            for the plain megastep)
    """

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = seed
        self._t0 = time.monotonic()
        self._beats = 0
        self._supersteps = 0
        self._steps = 0
        self._rows = 0
        self._dur_s = 0.0
        self._res = _Reservoir(
            PhaseTimers.RESERVOIR_K,
            (zlib.crc32(b"fused_beat") ^ seed) & 0x7FFFFFFF,
        )

    def record_beat(self, learn_steps: int, rows: int, dur_s: float,
                    beats: int = 1) -> None:
        # One call per DISPATCH: a B-beat superstep records its whole
        # loop here (beats=B), so fused_beats keeps counting training
        # beats while the dispatch counter amortizes by B. The duration
        # reservoir keeps whole-dispatch wall times — tails measure what
        # the host actually waits on.
        with self._lock:
            self._beats += int(beats)
            self._supersteps += 1
            self._steps += int(learn_steps)
            self._rows += int(rows)
            self._dur_s += dur_s
            self._res.add(dur_s)

    def snapshot(self, reset: bool = True) -> Dict[str, float]:
        with self._lock:
            dt = max(time.monotonic() - self._t0, 1e-9)
            n = self._beats
            out = {
                "fused_beats": n,
                "fused_steps_per_s": round(self._steps / dt, 1),
                "fused_rows_per_s": round(self._rows / dt, 1),
                "fused_beat_ms": (
                    round(1000.0 * self._dur_s / n, 3) if n else 0.0
                ),
                "fused_beat_p50": round(
                    1000.0 * self._res.percentile(0.50), 3
                ),
                "fused_beat_p95": round(
                    1000.0 * self._res.percentile(0.95), 3
                ),
                "fused_beat_max": round(1000.0 * self._res.max, 3),
                "fused_supersteps": self._supersteps,
                "fused_superstep_beats": (
                    round(n / self._supersteps, 2) if self._supersteps
                    else 0.0
                ),
            }
            if reset:
                self._t0 = time.monotonic()
                self._beats = 0
                self._supersteps = 0
                self._steps = 0
                self._rows = 0
                self._dur_s = 0.0
                self._res = _Reservoir(
                    PhaseTimers.RESERVOIR_K,
                    (zlib.crc32(b"fused_beat") ^ self._seed) & 0x7FFFFFFF,
                )
        return out


class TransferStats:
    """Thread-safe counters for the unified transfer scheduler
    (transfer/scheduler.py; docs/TRANSFER.md) — the scheduler-level
    complement to IngestStats' pipeline view. Per work class (lockstep /
    ingest / prefetch / d2h) it tracks items dispatched, bytes moved, and
    dispatch wall time with a deterministic reservoir for tails; queue
    depths ride in at snapshot time as gauges. snapshot() emits the
    `transfer_*` fields each train/bench record carries and resets the
    interval (restart count and queue depths are cumulative/gauge):

      transfer_dispatches        scheduled items dispatched this interval
      transfer_<cls>_items       per-class dispatches
      transfer_<cls>_bytes       per-class bytes moved
      transfer_<cls>_ms          mean dispatch wall time per item
      transfer_<cls>_p95         reservoir p95 dispatch time (ms)
      transfer_queue_<cls>       current queue depth (gauge)
      transfer_queue_<cls>_max   max depth seen this interval (the
                                 instantaneous gauge is ~0 at the log
                                 cadence — the scheduler drains between
                                 records; the max is the backlog signal)
      transfer_restarts          cumulative scheduler-thread restarts
    """

    # d2h runs inline on the caller thread (scheduler.run_inline) but is
    # accounted identically; it is excluded from transfer_dispatches,
    # which counts the SCHEDULED classes the dispatch thread executed.
    # shard_exchange rides the lockstep deque (one ordered lane) but is
    # accounted as its own class (docs/REPLAY_SHARDING.md).
    SCHEDULED = ("lockstep", "shard_exchange", "ingest", "prefetch", "serve")
    CLASSES = SCHEDULED + ("d2h",)

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = seed
        self._reset_locked()

    def _reset_locked(self) -> None:
        self._items = {c: 0 for c in self.CLASSES}
        self._bytes = {c: 0 for c in self.CLASSES}
        self._time_s = {c: 0.0 for c in self.CLASSES}
        self._res = {
            c: _Reservoir(64, (zlib.crc32(c.encode()) ^ self._seed) & 0x7FFFFFFF)
            for c in self.CLASSES
        }
        self._depth_max = {c: 0 for c in self.SCHEDULED}

    def record_dispatch(self, cls: str, nbytes: int, dur_s: float) -> None:
        with self._lock:
            if cls not in self._items:
                return
            self._items[cls] += 1
            self._bytes[cls] += int(nbytes)
            self._time_s[cls] += dur_s
            self._res[cls].add(dur_s)

    def record_queue_depth(self, cls: str, depth: int) -> None:
        with self._lock:
            if cls in self._depth_max and depth > self._depth_max[cls]:
                self._depth_max[cls] = depth

    def snapshot(self, queue_depths=None, restarts: int = 0, reset: bool = True) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {
                "transfer_dispatches": sum(
                    self._items[c] for c in self.SCHEDULED
                ),
                "transfer_restarts": int(restarts),
            }
            for c in self.CLASSES:
                n = self._items[c]
                out[f"transfer_{c}_items"] = n
                out[f"transfer_{c}_bytes"] = self._bytes[c]
                out[f"transfer_{c}_ms"] = (
                    round(1000.0 * self._time_s[c] / n, 3) if n else 0.0
                )
                out[f"transfer_{c}_p95"] = round(
                    1000.0 * self._res[c].percentile(0.95), 3
                )
            for c, d in (queue_depths or {}).items():
                out[f"transfer_queue_{c}"] = int(d)
            for c, d in self._depth_max.items():
                out[f"transfer_queue_{c}_max"] = int(d)
            if reset:
                self._reset_locked()
        return out


class PodStats:
    """Thread-safe pod-resilience counters (parallel/multihost.py;
    docs/RESILIENCE.md pod rows) — the `pod_*` family every train/final
    JSONL record carries on multi-process runs. Counters are CUMULATIVE
    (peer loss and aborts are rare, terminal events; interval-resetting
    them would hide the one record that matters):

      pod_peer_lost               collectives declared lost (deadline
                                  timeout or mid-flight transport error)
      pod_aborts                  coordinated clean aborts taken (the
                                  EXIT_POD_DEGRADED path)
      pod_resume_step_elected     the step the coordinated resume election
                                  agreed on (-1 = no election ran / no
                                  common step)
      pod_beats                   heartbeat-bearing lockstep beats gathered
      pod_collective_near_misses  guarded collectives that consumed > 80%
                                  of their deadline (the tune-the-timeout
                                  signal BEFORE a false PodPeerLost)
      pod_collective_slack_p95_ms deadline headroom at the p95-slowest
                                  collective (deadline - p95 elapsed);
                                  trending toward 0 = deadline too tight

    Elastic-pod events (docs/RESILIENCE.md shrink/grow state machine):

      pod_slices_adopted          replay slice sets adopted at restore
                                  (all-writer checkpoints)
      pod_slice_adopted_step      the step the adopted slice set was
                                  written at (-1 = none; may trail the
                                  elected resume step — replay is allowed
                                  to be a few cadences staler)
      pod_shrinks                 restarts that adopted a slice set from
                                  a LARGER world (training continues at
                                  reduced membership -> degraded)
      pod_grows                   restarts that resharded a smaller
                                  world's slices back up (rejoin ->
                                  healthy)
      pod_state_degraded          1 while the pod trains below the slice
                                  set's writer count, 0 once grown back

    Straggler attribution (obs/aggregate.py; docs/OBSERVABILITY.md §4):

      pod_stragglers              cadences on which the per-host beat-time
                                  detector attributed a straggling host
      pod_straggler_host          the most recently attributed host index
                                  (-1 = never attributed)
    """

    NEAR_MISS_FRAC = 0.8

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.peer_lost = 0
        self.aborts = 0
        self.resume_step_elected = -1
        self.beats = 0
        self.near_misses = 0
        self.slices_adopted = 0
        self.slice_adopted_step = -1
        self.shrinks = 0
        self.grows = 0
        self.degraded = False
        self.stragglers = 0
        self.straggler_host = -1
        self._deadline_s = 0.0
        self._elapsed = _Reservoir(
            64, (zlib.crc32(b"pod_collective") ^ seed) & 0x7FFFFFFF
        )

    def record_collective(self, elapsed_s: float, deadline_s: float) -> None:
        with self._lock:
            self._deadline_s = deadline_s
            self._elapsed.add(elapsed_s)
            if elapsed_s > self.NEAR_MISS_FRAC * deadline_s:
                self.near_misses += 1

    def record_peer_lost(self) -> None:
        with self._lock:
            self.peer_lost += 1

    def record_abort(self) -> None:
        with self._lock:
            self.aborts += 1

    def record_resume_elected(self, step: int) -> None:
        with self._lock:
            self.resume_step_elected = int(step)

    def record_slice_adopted(self, step: int) -> None:
        with self._lock:
            self.slices_adopted += 1
            self.slice_adopted_step = int(step)

    def record_shrink(self) -> None:
        """Adopted a slice set written by a LARGER world: the pod keeps
        training at reduced membership in a typed degraded state."""
        with self._lock:
            self.shrinks += 1
            self.degraded = True

    def record_grow(self) -> None:
        """Resharded a smaller world's slices back up (rejoin): degraded
        clears — the pod is healthy at its new membership."""
        with self._lock:
            self.grows += 1
            self.degraded = False

    def record_straggler(self, host: int) -> None:
        """One straggler attribution from the pod aggregator's per-host
        beat-time detector (obs/aggregate.py)."""
        with self._lock:
            self.stragglers += 1
            self.straggler_host = int(host)

    def elastic_events(self) -> int:
        """Nonzero when any elastic transition happened — the gate for
        surfacing pod_* fields on runs that shrank to one process
        (train_jax logs pod fields when is_multi OR this)."""
        with self._lock:
            return self.slices_adopted + self.shrinks + self.grows

    def note_beat(self) -> None:
        with self._lock:
            self.beats += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            slack_ms = 0.0
            if self._elapsed.buf and self._deadline_s > 0:
                slack_ms = round(
                    1000.0
                    * (self._deadline_s - self._elapsed.percentile(0.95)),
                    3,
                )
            return {
                "pod_peer_lost": self.peer_lost,
                "pod_aborts": self.aborts,
                "pod_resume_step_elected": self.resume_step_elected,
                "pod_beats": self.beats,
                "pod_collective_near_misses": self.near_misses,
                "pod_collective_slack_p95_ms": slack_ms,
                "pod_slices_adopted": self.slices_adopted,
                "pod_slice_adopted_step": self.slice_adopted_step,
                "pod_shrinks": self.shrinks,
                "pod_grows": self.grows,
                "pod_state_degraded": int(self.degraded),
                "pod_stragglers": self.stragglers,
                "pod_straggler_host": self.straggler_host,
            }


class SupervisorStats:
    """Thread-safe pod-supervisor counters (supervisor/core.py;
    docs/OPERATIONS.md supervisor runbook) — the `supervisor_*` family
    the supervisor's JSONL event stream carries on its final record, so
    a long soak's whole restart history is auditable from one line.
    CUMULATIVE across generations, like PodStats (every event here is a
    rare, decision-bearing transition):

      supervisor_generations      pod generations launched (gen 1 counts)
      supervisor_spawns           child processes spawned, all generations
      supervisor_relaunches       same-membership relaunches (70/75/76 or
                                  untyped crashes)
      supervisor_shrinks          shrink relaunches taken on exit 78
                                  (membership reduced to the survivors)
      supervisor_grows            health-gated grow relaunches (stop-the-
                                  world resize back toward full strength)
      supervisor_backoffs         exponential-backoff waits served
      supervisor_backoff_wait_s   total seconds spent in those waits
      supervisor_breaker_trips    crash-loop circuit-breaker trips (each
                                  one is terminal: the SupervisorGaveUp
                                  report path)
      supervisor_numeric_refusals numeric aborts (77) refused past the
                                  supervisor_max_numeric budget
      supervisor_probe_ready      lost-peer slots that cleared the
                                  K-consecutive-healthy rejoin gate
      supervisor_probe_flaps      healthy->unhealthy probe regressions
                                  (each restarts that slot's gate)
      supervisor_gave_up          1 once the supervisor exited through
                                  the typed give-up path, else 0
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.generations = 0
        self.spawns = 0
        self.relaunches = 0
        self.shrinks = 0
        self.grows = 0
        self.backoffs = 0
        self.backoff_wait_s = 0.0
        self.breaker_trips = 0
        self.numeric_refusals = 0
        self.probe_ready = 0
        self.probe_flaps = 0
        self.gave_up = False

    def record_generation(self, nprocs: int) -> None:
        with self._lock:
            self.generations += 1
            self.spawns += int(nprocs)

    def record_relaunch(self) -> None:
        with self._lock:
            self.relaunches += 1

    def record_shrink(self) -> None:
        with self._lock:
            self.shrinks += 1

    def record_grow(self) -> None:
        with self._lock:
            self.grows += 1

    def record_backoff(self, wait_s: float) -> None:
        with self._lock:
            self.backoffs += 1
            self.backoff_wait_s = round(self.backoff_wait_s + wait_s, 3)

    def record_breaker_trip(self) -> None:
        with self._lock:
            self.breaker_trips += 1
            self.gave_up = True

    def record_numeric_refusal(self) -> None:
        with self._lock:
            self.numeric_refusals += 1
            self.gave_up = True

    def record_probe_ready(self) -> None:
        with self._lock:
            self.probe_ready += 1

    def record_probe_flap(self) -> None:
        with self._lock:
            self.probe_flaps += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "supervisor_generations": self.generations,
                "supervisor_spawns": self.spawns,
                "supervisor_relaunches": self.relaunches,
                "supervisor_shrinks": self.shrinks,
                "supervisor_grows": self.grows,
                "supervisor_backoffs": self.backoffs,
                "supervisor_backoff_wait_s": self.backoff_wait_s,
                "supervisor_breaker_trips": self.breaker_trips,
                "supervisor_numeric_refusals": self.numeric_refusals,
                "supervisor_probe_ready": self.probe_ready,
                "supervisor_probe_flaps": self.probe_flaps,
                "supervisor_gave_up": int(self.gave_up),
            }


class GuardrailStats:
    """Host-side numerical-health counters (guardrails.py;
    docs/RESILIENCE.md 'Numerical health') — the `guardrail_*` family
    every train/final JSONL record carries when guardrails are armed.
    CUMULATIVE like PodStats (divergence events are rare and terminal-ish;
    interval resets would hide the one record that matters):

      guardrail_anomalies          anomalous learner steps (nonfinite +
                                   z-score spikes) — the rollback trigger's
                                   input
      guardrail_nonfinite_steps    steps skipped for a non-finite
                                   TD/grad/param value
      guardrail_loss_spikes        steps skipped by the EWMA z-score
                                   detector (finite but absurd)
      guardrail_skipped_updates    total updates dropped on device
      guardrail_bad_rows           non-finite sampled replay rows seen
      guardrail_rollbacks          checkpoint rollback-repairs taken
      guardrail_last_rollback_step the manifest-valid step the latest
                                   rollback restored (-1 = none)
      guardrail_lr_cooldowns       LR backoff->restore cycles completed
      guardrail_source_quarantines ingest sources quarantined for
                                   repeatedly feeding non-finite rows

    `absorb(health)` mirrors the device probe's cumulative counters and
    returns the DELTA since the previous read — the rolling-window input
    for the rollback trigger (train.py)."""

    def __init__(self):
        self.nonfinite = 0
        self.spikes = 0
        self.skipped = 0
        self.bad_rows = 0
        self.total_steps = 0
        self.rollbacks = 0
        self.last_rollback_step = -1
        self.lr_cooldowns = 0
        self.source_quarantines = 0

    def absorb(self, health: Dict[str, int]) -> Dict[str, int]:
        delta = {
            "nonfinite": int(health.get("nonfinite", 0)) - self.nonfinite,
            "spikes": int(health.get("spikes", 0)) - self.spikes,
            "skipped": int(health.get("skipped", 0)) - self.skipped,
            "bad_rows": int(health.get("bad_rows", 0)) - self.bad_rows,
        }
        self.nonfinite = int(health.get("nonfinite", 0))
        self.spikes = int(health.get("spikes", 0))
        self.skipped = int(health.get("skipped", 0))
        self.bad_rows = int(health.get("bad_rows", 0))
        self.total_steps = int(health.get("total", 0))
        delta["anomalies"] = delta["nonfinite"] + delta["spikes"]
        return delta

    def record_rollback(self, step: int) -> None:
        self.rollbacks += 1
        self.last_rollback_step = int(step)

    def record_lr_cooldown(self) -> None:
        self.lr_cooldowns += 1

    def record_source_quarantine(self) -> None:
        self.source_quarantines += 1

    def snapshot(self) -> Dict[str, int]:
        return {
            "guardrail_anomalies": self.nonfinite + self.spikes,
            "guardrail_nonfinite_steps": self.nonfinite,
            "guardrail_loss_spikes": self.spikes,
            "guardrail_skipped_updates": self.skipped,
            "guardrail_bad_rows": self.bad_rows,
            "guardrail_rollbacks": self.rollbacks,
            "guardrail_last_rollback_step": self.last_rollback_step,
            "guardrail_lr_cooldowns": self.lr_cooldowns,
            "guardrail_source_quarantines": self.source_quarantines,
        }


class ServeStats:
    """Thread-safe counters for the batched policy-inference service
    (serve/; docs/SERVING.md) — the `serve_*` family every train/final
    JSONL record carries when serving is armed, and the digest
    tools.serve_bench / bench.py BENCH_SERVE emit.

    COUNTERS are cumulative (requests/batches/overloads/errors/refreshes:
    the run's serving history; a nonzero overload anywhere matters even if
    the last interval was quiet). TAILS are interval-scoped: the latency,
    batch-fill, and queue-depth reservoirs reset at snapshot so each
    record's p50/p95 describes its own window — the same PhaseTimers
    reservoir discipline (deterministic seeds) the t_* phases use:

      serve_requests        requests accepted by the batcher (cumulative)
      serve_batches         batches dispatched (cumulative)
      serve_overloads       submissions rejected by the bounded queue —
                            typed ServeOverload backpressure (cumulative)
      serve_errors          batch dispatches that failed; every request in
                            the batch got a typed error (cumulative)
      serve_param_refreshes params reloaded from the broadcast buffer
                            (cumulative)
      serve_fill_mean       rows per dispatched batch / max_batch over the
                            whole run (1.0 = every batch full)
      serve_fill_p50/p95    interval batch-fill fraction tails
      serve_p50_ms/p95_ms/max_ms
                            interval request latency tails, enqueue ->
                            response delivered (the ci_gate -serve_p95_ms
                            key pins the p95)
      serve_queue_depth     request-queue depth at snapshot (gauge)
      serve_queue_depth_p95 interval p95 of the depth seen at each submit
                            (the ci_gate -serve_queue_depth_p95 key)
    """

    def __init__(self, seed: int = 0, max_batch: int = 1):
        self._lock = threading.Lock()
        self._seed = seed
        self.max_batch = max(1, int(max_batch))
        self.requests = 0
        self.batches = 0
        self.batch_rows = 0
        self.overloads = 0
        self.errors = 0
        self.refreshes = 0
        self._reset_reservoirs()

    def _reset_reservoirs(self) -> None:
        def res(name: str) -> _Reservoir:
            return _Reservoir(
                PhaseTimers.RESERVOIR_K,
                (zlib.crc32(name.encode()) ^ self._seed) & 0x7FFFFFFF,
            )

        self._lat = res("serve_latency")
        self._fill = res("serve_fill")
        self._depth = res("serve_depth")

    def record_request(self, queue_depth: int) -> None:
        with self._lock:
            self.requests += 1
            self._depth.add(float(queue_depth))

    def record_batch(self, rows: int, latencies_s) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += int(rows)
            self._fill.add(rows / self.max_batch)
            for lat in latencies_s:
                self._lat.add(lat)

    def record_overload(self) -> None:
        with self._lock:
            self.overloads += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_refresh(self) -> None:
        with self._lock:
            self.refreshes += 1

    def snapshot(self, queue_depth: int = 0, reset: bool = True) -> Dict[str, float]:
        with self._lock:
            out = {
                "serve_requests": self.requests,
                "serve_batches": self.batches,
                "serve_overloads": self.overloads,
                "serve_errors": self.errors,
                "serve_param_refreshes": self.refreshes,
                "serve_fill_mean": (
                    round(self.batch_rows / (self.batches * self.max_batch), 4)
                    if self.batches
                    else 0.0
                ),
                "serve_fill_p50": round(self._fill.percentile(0.50), 4),
                "serve_fill_p95": round(self._fill.percentile(0.95), 4),
                "serve_p50_ms": round(1000.0 * self._lat.percentile(0.50), 3),
                "serve_p95_ms": round(1000.0 * self._lat.percentile(0.95), 3),
                "serve_max_ms": round(1000.0 * self._lat.max, 3),
                "serve_queue_depth": int(queue_depth),
                "serve_queue_depth_p95": round(self._depth.percentile(0.95), 3),
            }
            if reset:
                self._reset_reservoirs()
        return out


class FrontStats:
    """Thread-safe counters for the network serving front (serve/front/;
    docs/SERVING.md 'Network front') — the `front_*` family every
    train/final JSONL record carries when the front is armed, and the
    digest tools.serve_bench --transport socket emits.

    COUNTERS are cumulative (the run's ingress history — a shed or
    rollback anywhere in the run matters even if the last interval was
    quiet). The wire-latency TAIL is interval-scoped and resets at
    snapshot, the same PhaseTimers reservoir discipline ServeStats uses:

      front_requests        frames accepted over TCP (cumulative)
      front_http_requests   requests accepted over the HTTP adapter
                            (cumulative; NOT a subset of front_requests)
      front_bad_frames      undecodable/oversized frames answered with a
                            typed bad_frame error (cumulative)
      front_sheds           requests rejected by per-tenant QoS before
                            reaching the batcher (cumulative; TenantStats
                            splits this by cause and tenant)
      front_overloads       requests the batcher's bounded queue rejected
                            past QoS admission — typed overload on the
                            wire (cumulative)
      front_timeouts        requests that missed front_timeout_s waiting
                            for their batch — typed timeout (cumulative)
      front_errors          dispatch failures surfaced as typed wire
                            errors (cumulative)
      front_canary_requests requests routed to the candidate version by
                            the deterministic canary split (cumulative)
      front_promotes        candidate versions atomically promoted to
                            stable by the live gate (cumulative)
      front_rollbacks       candidates rolled back by the gate — latency
                            or error-rate regression vs stable
                            (cumulative)
      front_wire_p50_ms/front_wire_p95_ms/front_wire_max_ms
                            interval wire latency tails, frame decoded ->
                            response queued (the ci_gate
                            -front_wire_p95_ms key pins the p95)
    """

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = seed
        self.requests = 0
        self.http_requests = 0
        self.bad_frames = 0
        self.sheds = 0
        self.overloads = 0
        self.timeouts = 0
        self.errors = 0
        self.canary_requests = 0
        self.promotes = 0
        self.rollbacks = 0
        self._reset_reservoirs()

    def _reset_reservoirs(self) -> None:
        self._wire = _Reservoir(
            PhaseTimers.RESERVOIR_K,
            (zlib.crc32(b"front_wire") ^ self._seed) & 0x7FFFFFFF,
        )

    def record_request(self, http: bool = False) -> None:
        with self._lock:
            if http:
                self.http_requests += 1
            else:
                self.requests += 1

    def record_bad_frame(self) -> None:
        with self._lock:
            self.bad_frames += 1

    def record_shed(self) -> None:
        with self._lock:
            self.sheds += 1

    def record_overload(self) -> None:
        with self._lock:
            self.overloads += 1

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_canary_request(self) -> None:
        with self._lock:
            self.canary_requests += 1

    def record_promote(self) -> None:
        with self._lock:
            self.promotes += 1

    def record_rollback(self) -> None:
        with self._lock:
            self.rollbacks += 1

    def record_wire_latency(self, seconds: float) -> None:
        with self._lock:
            self._wire.add(float(seconds))

    def snapshot(self, reset: bool = True) -> Dict[str, float]:
        with self._lock:
            out = {
                "front_requests": self.requests,
                "front_http_requests": self.http_requests,
                "front_bad_frames": self.bad_frames,
                "front_sheds": self.sheds,
                "front_overloads": self.overloads,
                "front_timeouts": self.timeouts,
                "front_errors": self.errors,
                "front_canary_requests": self.canary_requests,
                "front_promotes": self.promotes,
                "front_rollbacks": self.rollbacks,
                "front_wire_p50_ms": round(
                    1000.0 * self._wire.percentile(0.50), 3
                ),
                "front_wire_p95_ms": round(
                    1000.0 * self._wire.percentile(0.95), 3
                ),
                "front_wire_max_ms": round(1000.0 * self._wire.max, 3),
            }
            if reset:
                self._reset_reservoirs()
        return out


class TenantStats:
    """Thread-safe per-tenant QoS counters (serve/front/qos.py;
    docs/SERVING.md 'Network front') — the `tenant_*` family. All
    cumulative: shed ordering is a run-level contract ("overload sheds
    strictly lowest-priority first"), and the per-tenant split in
    `per_tenant()` is the evidence the shed-ordering test asserts on.

      tenant_count          distinct tenants seen this run
      tenant_served         requests admitted past QoS, all tenants
      tenant_shed_rate      requests shed by a tenant's token bucket
                            (per-tenant rate cap, not overload)
      tenant_shed_priority  requests shed by priority-ordered overload
                            protection (queue depth past the tenant
                            class's threshold)
      tenant_shed_total     tenant_shed_rate + tenant_shed_priority
      tenant_errors         typed errors returned to tenants after
                            admission (dispatch/timeout/overload)
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: Dict[str, Dict[str, int]] = {}

    def _row(self, tenant: str) -> Dict[str, int]:
        row = self._tenants.get(tenant)
        if row is None:
            row = {"served": 0, "shed_rate": 0, "shed_priority": 0,
                   "errors": 0}
            self._tenants[tenant] = row
        return row

    def record_served(self, tenant: str) -> None:
        with self._lock:
            self._row(tenant)["served"] += 1

    def record_shed(self, tenant: str, cause: str) -> None:
        """cause: 'rate' (token bucket) or 'priority' (overload shed)."""
        with self._lock:
            key = "shed_rate" if cause == "rate" else "shed_priority"
            self._row(tenant)[key] += 1

    def record_error(self, tenant: str) -> None:
        with self._lock:
            self._row(tenant)["errors"] += 1

    def per_tenant(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {t: dict(row) for t, row in self._tenants.items()}

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            rows = list(self._tenants.values())
            shed_rate = sum(r["shed_rate"] for r in rows)
            shed_priority = sum(r["shed_priority"] for r in rows)
            return {
                "tenant_count": len(rows),
                "tenant_served": sum(r["served"] for r in rows),
                "tenant_shed_rate": shed_rate,
                "tenant_shed_priority": shed_priority,
                "tenant_shed_total": shed_rate + shed_priority,
                "tenant_errors": sum(r["errors"] for r in rows),
            }


class Timer:
    """Running steps/sec meter for the actor/learner rate metrics.
    Monotonic clock: a wall-clock jump (NTP step, manual date set) on a
    multi-hour run must not spike or zero the reported rate — the round-5
    Humanoid runs report rates over ~20h windows where this matters."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t = time.monotonic()
        self._n = 0

    def tick(self, n: int = 1) -> None:
        self._n += n

    def rate(self) -> float:
        dt = time.monotonic() - self._t
        return self._n / dt if dt > 0 else 0.0

    def exclude(self, seconds: float) -> None:
        """Remove `seconds` from the measured window — for off-path work
        (e.g. inline evals) that must not deflate the reported rate."""
        self._t += seconds
