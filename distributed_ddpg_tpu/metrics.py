"""Structured metrics: JSONL + stdout (SURVEY.md §5 'Metrics / logging').

Replaces the reference's TensorBoard scalar summaries [RECALL] with
append-only JSONL (one object per event, machine-parseable by the bench
harness) plus optional human lines. Tracked quantities follow SURVEY.md §5:
episode return, losses, mean Q, grad norms, buffer fill, actor/learner
steps/sec, staleness.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    def __init__(self, path: str = "", echo: bool = True):
        self._file = open(path, "a", buffering=1) if path else None
        self._echo = echo
        self._t0 = time.time()

    def log(self, kind: str, step: int, **fields: Any) -> Dict[str, Any]:
        rec = {
            "kind": kind,
            "step": step,
            "wall_time": round(time.time() - self._t0, 3),
            **{k: _jsonable(v) for k, v in fields.items()},
        }
        line = json.dumps(rec)
        if self._file:
            self._file.write(line + "\n")
        if self._echo:
            print(line, file=sys.stdout, flush=True)
        return rec

    def close(self) -> None:
        if self._file:
            self._file.close()


def _jsonable(v):
    try:
        return round(float(v), 6)
    except (TypeError, ValueError):
        return v


class Timer:
    """Running steps/sec meter for the actor/learner rate metrics."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t = time.time()
        self._n = 0

    def tick(self, n: int = 1) -> None:
        self._n += n

    def rate(self) -> float:
        dt = time.time() - self._t
        return self._n / dt if dt > 0 else 0.0
