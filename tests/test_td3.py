"""TD3 (arXiv 1802.09477; beyond-parity family like D4PG): twin-critic
ensemble via a stacked leading axis + vmap, min-over-ensemble Bellman
targets, target-policy smoothing keyed by fold_in(seed, step), and
delayed actor/target updates under lax.cond."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import init_train_state, jit_learner_step
from distributed_ddpg_tpu.ops import losses
from distributed_ddpg_tpu.types import Batch

OBS, ACT, B = 5, 2, 16


def _cfg(**kw):
    base = dict(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        twin_critic=True, seed=0,
    )
    base.update(kw)
    return DDPGConfig(**base)


def _batch(rng):
    return Batch(
        obs=jnp.asarray(rng.standard_normal((B, OBS)), jnp.float32),
        action=jnp.asarray(rng.uniform(-1, 1, (B, ACT)), jnp.float32),
        reward=jnp.asarray(rng.standard_normal(B), jnp.float32),
        discount=jnp.full((B,), 0.99, jnp.float32),
        next_obs=jnp.asarray(rng.standard_normal((B, OBS)), jnp.float32),
        weight=jnp.ones((B,), jnp.float32),
    )


def test_twin_init_stacks_independent_critics():
    s = init_train_state(_cfg(), OBS, ACT, seed=0)
    for layer in s.critic_params:
        assert layer["w"].shape[0] == 2 and layer["w"].ndim == 3
        # Independent inits: the two ensemble members must differ.
        assert not np.allclose(layer["w"][0], layer["w"][1])
    # Actor unchanged (rank 2).
    assert s.actor_params[0]["w"].ndim == 2


def test_min_over_ensemble_target():
    """The TD3 target must use min(Q1', Q2'): make the ensemble disagree by
    a known offset and check the realized target against a hand-computed
    one through the public loss (td = y - q)."""
    cfg = _cfg(target_noise=0.0)
    s = init_train_state(cfg, OBS, ACT, seed=0)
    # Bias critic 1's output bias far above critic 0: min must pick 0's.
    biased = list(dict(l) for l in s.critic_params)
    last = dict(biased[-1])
    last["b"] = jnp.asarray(s.critic_params[-1]["b"]).at[1].add(100.0)
    biased[-1] = last
    target_critic = tuple(biased)

    batch = _batch(np.random.default_rng(0))
    key = jax.random.PRNGKey(0)
    _, td = losses.td3_critic_loss(
        s.critic_params, s.target_actor_params, target_critic, batch,
        1.0, key, 0.0, 0.5,
    )
    # Hand-compute y from member 0 only (the min, since member 1 is +100).
    from distributed_ddpg_tpu.models.mlp import actor_apply, critic_apply

    na = actor_apply(s.target_actor_params, batch.next_obs, 1.0)
    q0 = critic_apply(
        jax.tree.map(lambda x: x[0], target_critic), batch.next_obs, na, 1
    )
    y = batch.reward + batch.discount * q0
    q_on = jnp.stack([
        critic_apply(
            jax.tree.map(lambda x: x[i], s.critic_params),
            batch.obs, batch.action, 1,
        )
        for i in (0, 1)
    ])
    expect_td = y[None] - q_on
    np.testing.assert_allclose(
        np.asarray(td), np.asarray(expect_td.mean(0)), rtol=1e-5, atol=1e-6
    )


def test_policy_delay_and_counts():
    cfg = _cfg(policy_delay=3)
    s = init_train_state(cfg, OBS, ACT, seed=0)
    step = jit_learner_step(cfg, 1.0, donate=False)
    batch = _batch(np.random.default_rng(1))
    actor_updates = 0
    prev = np.asarray(s.actor_params[0]["w"]).copy()
    for i in range(6):
        out = step(s, batch)
        s = out.state
        now = np.asarray(s.actor_params[0]["w"])
        if not np.array_equal(now, prev):
            actor_updates += 1
        prev = now.copy()
    # Updates at critic steps 0 and 3 (state.step pre-increment % delay).
    assert actor_updates == 2
    assert int(s.actor_opt.count) == 2
    assert int(s.critic_opt.count) == 6


def test_target_smoothing_is_deterministic_and_active():
    cfg_noise = _cfg(target_noise=0.2)
    cfg_clean = _cfg(target_noise=0.0)
    s = init_train_state(cfg_noise, OBS, ACT, seed=0)
    batch = _batch(np.random.default_rng(2))
    sn = jit_learner_step(cfg_noise, 1.0, donate=False)
    sc = jit_learner_step(cfg_clean, 1.0, donate=False)
    out1 = sn(s, batch)
    out2 = sn(s, batch)
    # fold_in(seed, step) stream: same state+batch -> identical result.
    np.testing.assert_array_equal(
        np.asarray(out1.td_errors), np.asarray(out2.td_errors)
    )
    # Noise actually perturbs the target (vs the clean config).
    clean = sc(s, batch)
    assert not np.allclose(
        np.asarray(out1.td_errors), np.asarray(clean.td_errors)
    )


def test_td3_config_gates():
    with pytest.raises(ValueError, match="policy_delay"):
        DDPGConfig(policy_delay=0)
    with pytest.raises(ValueError, match="families"):
        DDPGConfig(twin_critic=True, distributional=True)
    with pytest.raises(ValueError, match="oracle"):
        DDPGConfig(twin_critic=True, backend="native")
    with pytest.raises(ValueError, match="fused_update"):
        DDPGConfig(twin_critic=True, fused_update=True)
    # TD3 knobs without twin_critic would silently do nothing.
    with pytest.raises(ValueError, match="silently"):
        DDPGConfig(policy_delay=2)
    with pytest.raises(ValueError, match="silently"):
        DDPGConfig(target_noise=0.2)
    from distributed_ddpg_tpu.ops import fused_chunk

    # TD3 is INSIDE the kernel envelope (round 4, second pass): twin
    # members flatten to rank-2 refs, noise streams in, updates delay
    # under pl.when. Parity: test_fused_chunk.py::test_fused_chunk_td3_*.
    assert fused_chunk.supported(_cfg())


def test_td3_sharded_learner_on_mesh():
    """The twin ensemble (rank-3 leaves) must flow through the mesh pspec
    trees, the device-replay sample chunk, and donation on the 8-device
    CPU mesh."""
    from distributed_ddpg_tpu.parallel import mesh as mesh_lib
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import DeviceReplay
    from distributed_ddpg_tpu.types import pack_batch_np

    cfg = _cfg(policy_delay=2, target_noise=0.2, batch_size=8)
    mesh = mesh_lib.make_mesh(data_axis=4, model_axis=2, devices=jax.devices())
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mesh=mesh, chunk_size=4)
    assert not lrn.fused_chunk_active  # TD3 -> scan path
    rng = np.random.default_rng(3)
    n = 256
    dr = DeviceReplay(1024, OBS, ACT, mesh=lrn.mesh, block_size=128)
    dr.add_packed(
        pack_batch_np(
            {
                "obs": rng.standard_normal((n, OBS)).astype(np.float32),
                "action": rng.uniform(-1, 1, (n, ACT)).astype(np.float32),
                "reward": rng.standard_normal(n).astype(np.float32),
                "discount": np.full(n, 0.99, np.float32),
                "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
            }
        )
    )
    out = lrn.run_sample_chunk(dr)
    assert np.isfinite(float(out.metrics["critic_loss"]))
    out2 = lrn.run_sample_chunk(dr)
    assert np.isfinite(float(out2.metrics["critic_loss"]))
    # 2 chunks x 4 steps, delay 2 -> 4 actor updates.
    assert int(jax.device_get(lrn.state.actor_opt.count)) == 4
    assert int(jax.device_get(lrn.state.critic_opt.count)) == 8


@pytest.mark.slow
def test_td3_train_jax_end_to_end(tmp_path):
    from distributed_ddpg_tpu.train import train_jax

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), num_actors=2,
        twin_critic=True, policy_delay=2, target_noise=0.2,
        total_env_steps=4_000, replay_min_size=500, replay_capacity=20_000,
        eval_every=0, max_ingest_ratio=50.0,
        log_path=str(tmp_path / "m.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] >= 40
    assert np.isfinite(out["final_return"])
