"""Telemetry-plane tests (obs/; docs/OBSERVABILITY.md §4).

Four tiers:
  - unit: the /healthz state machine (named degraded conditions, the
    draining latch, read-time probes), Prometheus rendering (family
    grouping, bool coercion, the health trio), the live exporter's three
    endpoints over real HTTP, straggler detection, the pod aggregator's
    record reduction, the run-start header record, and the clock-aligned
    merge-trace fuser.
  - guards: the hot-path overhead pin (MetricsLogger.log under a live
    scraper stays <2% of a realistic chunk body — the same discipline as
    test_trace.py's span guard) and the SIGUSR2 / watchdog-stall trace
    export paths.
  - schema drift (ISSUE 18 satellite): a real CPU train run's emitted
    JSONL keys must all appear in docs/OBSERVABILITY.md, AND every
    pod_*/serve_*/fused_* field the docs tables promise must actually be
    emitted by the corresponding Stats snapshot / pod record.
  - 2-process gloo drill (slow; OBS_FULL=1 in scripts/obs_smoke.sh): live
    /metrics scrape showing pod spread keys, a faults.py peer loss
    flipping /healthz healthy->degraded on the survivor, both processes
    exiting EXIT_POD_DEGRADED, and merge-trace fusing both hosts' trace
    files into one clock-aligned Perfetto timeline.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.metrics import (
    FusedBeatStats,
    MetricsLogger,
    PodStats,
    ServeStats,
)
from distributed_ddpg_tpu.obs import (
    ObsExporter,
    PodAggregator,
    detect_straggler,
    health,
    render_prometheus,
)
from distributed_ddpg_tpu.obs import aggregate

CHILD = Path(__file__).parent / "multihost_child.py"
REPO = str(CHILD.parent.parent)
DOCS = Path(REPO) / "docs" / "OBSERVABILITY.md"


@pytest.fixture(autouse=True)
def _clean_singletons():
    """The health singleton and the trace ring are process-wide; a test
    that latches `draining` or enables the recorder must not leak either
    into its neighbors."""
    health.get().reset()
    yield
    health.get().reset()
    trace.disable()


def _http(url: str, timeout: float = 5.0):
    """(status, content_type, body) — 4xx/5xx return, they don't raise."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.headers.get("Content-Type", ""), \
                r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type", ""), \
            e.read().decode("utf-8")


# --------------------------------------------------------------------------
# health state machine (obs/health.py)
# --------------------------------------------------------------------------


def test_health_starts_healthy():
    state, reasons = health.get().state()
    assert state == health.HEALTHY and reasons == []
    snap = health.get().snapshot()
    assert snap["state"] == "healthy" and snap["code"] == 0
    assert snap["reasons"] == []
    assert snap["t_unix"] >= snap["since_unix"]


def test_health_note_sets_and_clears_degraded():
    h = health.get()
    h.note("pod_state_degraded")
    assert h.state() == (health.DEGRADED, ["pod_state_degraded"])
    h.note("guardrail_quarantine")
    assert h.state()[1] == ["guardrail_quarantine", "pod_state_degraded"]
    # Reversible: the elastic pod growing back clears its condition.
    h.note("pod_state_degraded", active=False)
    h.note("guardrail_quarantine", active=False)
    assert h.state() == (health.HEALTHY, [])


def test_health_drain_latches_first_reason():
    h = health.get()
    h.drain("watchdog stall: no trainer progress for 60s")
    h.drain("preempted (SIGTERM)")  # later churn must not overwrite
    h.note("pod_state_degraded")    # draining dominates conditions
    state, reasons = h.state()
    assert state == health.DRAINING
    assert reasons == ["watchdog stall: no trainer progress for 60s"]
    assert h.snapshot()["code"] == 2


def test_health_probe_evaluated_at_read_time():
    h = health.get()
    flag = [False]
    h.register_probe("serve_overloaded", lambda: flag[0])
    assert h.state()[0] == health.HEALTHY
    flag[0] = True  # no note() call: the probe alone must flip the state
    assert h.state() == (health.DEGRADED, ["serve_overloaded"])
    flag[0] = False
    assert h.state()[0] == health.HEALTHY


def test_health_raising_probe_reads_probe_error():
    h = health.get()
    h.register_probe("serve_overloaded", lambda: 1 / 0)
    state, reasons = h.state()
    # "Cannot determine health" must gate exactly like "unhealthy".
    assert state == health.DEGRADED
    assert reasons == ["serve_overloaded:probe_error"]


def test_health_reset_returns_fresh():
    h = health.get()
    h.note("x")
    h.drain("terminal")
    h.register_probe("p", lambda: True)
    h.reset()
    assert h.state() == (health.HEALTHY, [])


# --------------------------------------------------------------------------
# Prometheus rendering (obs/exporter.py)
# --------------------------------------------------------------------------


def test_render_prometheus_families_not_interleaved():
    latest = {
        "train": {"kind": "train", "learner_steps_per_sec": 42.5,
                  "pod_beats": 7},
        "pod": {"kind": "pod", "learner_steps_per_sec": 1.5},
    }
    text = render_prometheus(latest, {"t_unix_base": 123.5}, health.get())
    assert 'ddpg_learner_steps_per_sec{kind="train"} 42.5' in text
    assert 'ddpg_learner_steps_per_sec{kind="pod"} 1.5' in text
    assert "ddpg_t_unix_base 123.5" in text
    # Exposition format: ONE TYPE line per family, samples contiguous.
    lines = text.strip().splitlines()
    assert lines.count("# TYPE ddpg_learner_steps_per_sec gauge") == 1
    current = None
    for ln in lines:
        if ln.startswith("# TYPE "):
            current = ln.split()[2]
            continue
        assert current is not None and ln.startswith(current), (
            f"sample {ln!r} outside its family block ({current})"
        )


def test_render_prometheus_values_and_sanitization():
    latest = {"train": {
        "kind": "train",
        "flag": True,          # bool -> 1
        "note": "a string",    # unexportable: dropped
        "nested": {"a": 1},    # unexportable: dropped
        "weird-key:1": 3.0,    # sanitized name
    }}
    text = render_prometheus(latest)
    assert 'ddpg_flag{kind="train"} 1' in text
    assert "a string" not in text and "nested" not in text
    assert 'ddpg_weird_key_1{kind="train"} 3' in text


def test_render_prometheus_health_trio():
    health.get().note("pod_state_degraded")
    text = render_prometheus(None, None, health.get())
    assert "ddpg_health_code 1" in text
    assert 'ddpg_health{state="degraded"} 1' in text
    assert 'ddpg_health{state="healthy"} 0' in text
    assert 'ddpg_health{state="draining"} 0' in text


# --------------------------------------------------------------------------
# live ingress endpoints (obs/exporter.py over real HTTP)
# --------------------------------------------------------------------------


def test_exporter_endpoints(tmp_path):
    h = health.get()
    latest = {"train": {"kind": "train", "learner_steps_per_sec": 42.5}}
    ex = ObsExporter(
        0,  # ephemeral: tests must not fight over a fixed port
        health=h,
        latest_fn=lambda: latest,
        counters_fn=lambda: {"t_unix_base": 5.25},
        trace_dir=str(tmp_path),
    ).start()
    try:
        assert ex.port > 0
        code, ctype, body = _http(ex.url("/metrics"))
        assert code == 200 and "version=0.0.4" in ctype
        assert 'ddpg_learner_steps_per_sec{kind="train"} 42.5' in body
        assert "ddpg_t_unix_base 5.25" in body
        assert "ddpg_obs_scrapes_total" in body
        assert f"ddpg_pid {os.getpid()}" in body

        code, ctype, body = _http(ex.url("/healthz"))
        assert code == 200 and ctype.startswith("application/json")
        assert json.loads(body)["state"] == "healthy"

        h.note("pod_state_degraded")
        code, _, body = _http(ex.url("/healthz"))
        snap = json.loads(body)
        assert code == 503 and snap["state"] == "degraded"
        assert snap["reasons"] == ["pod_state_degraded"]
        h.note("pod_state_degraded", active=False)
        assert _http(ex.url("/healthz"))[0] == 200

        h.drain("preempted (SIGTERM)")
        code, _, body = _http(ex.url("/healthz"))
        assert code == 503 and json.loads(body)["state"] == "draining"

        code, _, body = _http(ex.url("/nope"))
        assert code == 404 and "/metrics /healthz /trace" in body

        # The scrape counter is itself scraped (previous scrapes counted).
        _, _, body = _http(ex.url("/metrics"))
        m = re.search(r"ddpg_obs_scrapes_total (\d+)", body)
        assert m and int(m.group(1)) >= 1
    finally:
        ex.stop()


def test_exporter_trace_endpoint(tmp_path):
    ex = ObsExporter(0, trace_dir=str(tmp_path)).start()
    try:
        _, _, body = _http(ex.url("/trace"))
        assert json.loads(body) == {"enabled": False, "events": 0}

        trace.configure(capacity=64)
        with trace.span("live_work"):
            pass
        _, _, body = _http(ex.url("/trace"))
        obj = json.loads(body)
        assert obj["enabled"] is True and obj["events"] >= 1
        assert obj["path"] == os.path.join(str(tmp_path),
                                           "trace_ondemand.json")
        doc = json.loads(Path(obj["path"]).read_text())
        assert any(e.get("name") == "live_work" for e in doc["traceEvents"])
    finally:
        ex.stop()


def test_exporter_counters_fn_failure_degrades_to_basics():
    ex = ObsExporter(0, counters_fn=lambda: 1 / 0).start()
    try:
        code, _, body = _http(ex.url("/metrics"))
        assert code == 200 and "ddpg_pid" in body  # basics survive
    finally:
        ex.stop()


def test_exporter_bind_conflict_raises_oserror():
    """train.py downgrades a taken port to a warning — the typed failure
    it catches is OSError from start()."""
    ex = ObsExporter(0).start()
    try:
        with pytest.raises(OSError):
            ObsExporter(ex.port).start()
    finally:
        ex.stop()


# --------------------------------------------------------------------------
# hot-path overhead guard (the telemetry plane must stay off the hot path)
# --------------------------------------------------------------------------


def test_obs_logging_overhead_under_2_percent():
    """MetricsLogger.log (the ONLY train-loop cost the ingress adds — the
    exporter renders on the scrape thread) must cost <2% of a realistic
    chunk body, WHILE a scraper hammers /metrics. Costs measured
    separately min-over-repeats, the test_trace.py discipline: a
    subtraction of two noisy ~20ms wall timings would flake on scheduler
    jitter."""
    log = MetricsLogger("", echo=False)
    ex = ObsExporter(0, latest_fn=log.latest).start()
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                _http(ex.url("/metrics"), timeout=2.0)
            except OSError:
                pass

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    a = np.random.default_rng(0).standard_normal((160, 160)).astype(np.float32)
    try:
        def log_cost_s() -> float:
            n = 5_000
            t0 = time.perf_counter()
            for i in range(n):
                log.log("train", i, learner_steps_per_sec=42.5,
                        critic_loss=0.1, buffer_fill=0.5)
            return (time.perf_counter() - t0) / n

        def body_cost_s() -> float:
            n = 50
            t0 = time.perf_counter()
            for _ in range(n):
                x = a
                for _ in range(6):
                    x = x @ a
            return (time.perf_counter() - t0) / n

        log_cost_s(), body_cost_s()  # warm pools + code paths
        cost = min(log_cost_s() for _ in range(3))
        body = min(body_cost_s() for _ in range(5))
        overhead = cost / body
        assert overhead < 0.02, (
            f"obs logging overhead {overhead:.2%} "
            f"(log {cost * 1e6:.2f}us vs body {body * 1e6:.1f}us)"
        )
    finally:
        stop.set()
        t.join(timeout=5)
        ex.stop()


# --------------------------------------------------------------------------
# straggler detection + pod aggregation (obs/aggregate.py)
# --------------------------------------------------------------------------


def test_detect_straggler_two_hosts_relative_test():
    # 2-host pods pin z-scores at +/-1: the relative test must carry.
    assert detect_straggler([10.0, 30.0]) == 1
    assert detect_straggler([30.0, 10.0]) == 0
    assert detect_straggler([10.0, 11.0]) == -1  # inside rel_thresh


def test_detect_straggler_zscore_population():
    assert detect_straggler([10.0, 10.0, 10.0, 100.0]) == 3
    assert detect_straggler([10.0, 10.0, 10.0, 10.0]) == -1
    assert detect_straggler([10.0, 11.0, 9.0, 10.5]) == -1


def test_detect_straggler_absolute_floor_and_degenerate_inputs():
    # 3x ratio but microsecond scale: the min_abs_ms floor must gate it.
    assert detect_straggler([0.1, 0.3]) == -1
    assert detect_straggler([0.1, 0.3], min_abs_ms=0.1) == 1
    assert detect_straggler([5.0]) == -1
    assert detect_straggler([]) == -1


def test_pod_aggregator_single_host_returns_none():
    agg = PodAggregator(gather_fn=lambda vec: vec.reshape(1, -1))
    assert agg.collect(beats=10, ingest_rows=100) is None


def test_pod_aggregator_reduces_and_attributes():
    gathered = np.zeros((2, aggregate.SLOTS), np.int64)
    # host 0: beat 10ms, 5 rows/s, backlog 0;  host 1: beat 500ms,
    # 4 rows/s, backlog 2; clocks 250ms apart. Slots are milli-scaled.
    gathered[0] = [10_000, 5_000, 0, 1_000_000]
    gathered[1] = [500_000, 4_000, 2_000, 1_000_250]
    stats = PodStats()
    agg = PodAggregator(gather_fn=lambda vec: gathered, stats=stats)
    rec = agg.collect(beats=50, ingest_rows=1000, transfer_backlog=0)
    assert rec["pod_agg_hosts"] == 2
    assert rec["pod_beat_ms_min"] == 10.0
    assert rec["pod_beat_ms_max"] == 500.0
    assert rec["pod_beat_ms_spread"] == 490.0
    assert rec["pod_ingest_rows_per_s_min"] == 4.0
    assert rec["pod_ingest_rows_per_s_max"] == 5.0
    assert rec["pod_ingest_rows_per_s_spread"] == 1.0
    assert rec["pod_transfer_backlog_max"] == 2.0
    assert rec["pod_clock_spread_ms"] == 250.0
    assert rec["pod_straggler_host"] == 1
    snap = stats.snapshot()
    assert snap["pod_stragglers"] == 1
    assert snap["pod_straggler_host"] == 1


def test_pod_aggregator_sample_rates_are_interval_scoped():
    agg = PodAggregator(gather_fn=lambda v: v.reshape(1, -1))
    agg.sample(beats=0, ingest_rows=0, transfer_backlog=0)
    time.sleep(0.05)
    vec = agg.sample(beats=10, ingest_rows=500, transfer_backlog=3)
    # 10 beats over ~50ms -> ~5ms/beat; backlog is a plain gauge.
    assert 1_000 <= vec[aggregate.SLOT_BEAT_MS] <= 50_000
    assert vec[aggregate.SLOT_TRANSFER_BACKLOG] == 3_000
    assert vec[aggregate.SLOT_INGEST_RATE] > 0


# --------------------------------------------------------------------------
# run-start header record (MetricsLogger; ISSUE 18 satellite)
# --------------------------------------------------------------------------


def test_metrics_logger_writes_header_with_unix_base(tmp_path):
    path = tmp_path / "run.jsonl"
    log = MetricsLogger(str(path), echo=False)
    first = json.loads(path.read_text().splitlines()[0])
    assert first["kind"] == "header"
    assert first["t_unix_base"] == log.t_unix_base
    assert abs(log.t_unix_base - time.time()) < 60.0
    assert first["pid"] == os.getpid()
    log.log("train", 5, learner_steps_per_sec=1.0)
    latest = log.latest()
    assert set(latest) == {"header", "train"}
    # wall_time stays RELATIVE; the header's absolute base anchors it.
    assert latest["train"]["wall_time"] < 60.0


# --------------------------------------------------------------------------
# drain paths: watchdog stall + SIGUSR2 export
# --------------------------------------------------------------------------


def test_watchdog_stall_drains_health():
    from distributed_ddpg_tpu.watchdog import Watchdog

    fired = threading.Event()
    wd = Watchdog(0.3, progress=lambda: 0, on_stall=fired.set,
                  stall_dir=None).start()
    try:
        assert fired.wait(timeout=10.0), "watchdog never fired"
        state, reasons = health.get().state()
        # /healthz must already read terminal while artifacts are written.
        assert state == health.DRAINING
        assert reasons and "watchdog stall" in reasons[0]
    finally:
        wd.stop()


def test_pod_abort_linger_serves_latched_draining_verdict():
    """ISSUE 19 satellite: during the pod-abort linger window (rank 0
    keeps its ingress up briefly so one last scrape can read the
    verdict), /healthz must return 503 with state `draining` and the
    LATCHED degraded reason — not a fresh `healthy`. The drain handshake
    is train.drain_for_pod_exit, factored out of pod_degraded_exit so
    this contract is testable without os._exit."""
    from distributed_ddpg_tpu import train

    health.get().note("pod peer lost: process 1")
    ex = ObsExporter(0).start()
    try:
        train.drain_for_pod_exit(train.EXIT_POD_SHRINK)
        code, _, body = _http(ex.url("/healthz"))
        assert code == 503
        snap = json.loads(body)
        assert snap["state"] == "draining"
        assert any("pod peer lost" in r for r in snap["reasons"])
        # Latched: a later recovery signal must NOT un-drain the verdict.
        health.get().note("pod peer lost: process 1", active=False)
        code, _, body = _http(ex.url("/healthz"))
        assert code == 503
        assert json.loads(body)["state"] == "draining"
    finally:
        ex.stop()


def test_drain_for_pod_exit_without_prior_reason_names_the_code():
    from distributed_ddpg_tpu import train

    train.drain_for_pod_exit(train.EXIT_POD_DEGRADED)
    state, reasons = health.get().state()
    assert state == health.DRAINING
    assert reasons == ["pod abort (exit 76)"]


@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"),
                    reason="platform has no SIGUSR2")
def test_sigusr2_reexports_live_trace(tmp_path):
    prev = signal.getsignal(signal.SIGUSR2)
    path = tmp_path / "live" / "trace.json"
    try:
        trace.configure(capacity=128)
        assert trace.install_signal_export(str(path)) is True
        with trace.span("before_poke"):
            pass
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.monotonic() + 5.0
        while not path.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        doc = json.loads(path.read_text())
        assert any(
            e.get("name") == "before_poke" for e in doc["traceEvents"]
        )
    finally:
        signal.signal(signal.SIGUSR2, prev)


@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"),
                    reason="platform has no SIGUSR2")
def test_install_signal_export_refuses_off_main_thread(tmp_path):
    out = []
    t = threading.Thread(
        target=lambda: out.append(
            trace.install_signal_export(str(tmp_path / "t.json"))
        )
    )
    t.start()
    t.join()
    assert out == [False]


# --------------------------------------------------------------------------
# merge-trace (tools/runs.py): clock-aligned pod timelines
# --------------------------------------------------------------------------


def _fake_host_trace(path, *, wall_t0, offset_ms, process_index, pid,
                     span_ts):
    doc = {
        "traceEvents": [
            {"name": "beat", "ph": "X", "pid": pid, "tid": 1,
             "ts": span_ts, "dur": 500, "args": {}},
            # trace.py metadata events carry NO ts — the merge must cope.
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
             "args": {"name": "learner"}},
        ],
        "otherData": {"wall_t0": wall_t0, "pid": pid,
                      "process_index": process_index,
                      "clock_offset_ms": offset_ms},
    }
    path.write_text(json.dumps(doc))
    return path


def test_merge_traces_aligns_clocks_and_remaps_pids(tmp_path):
    from distributed_ddpg_tpu.tools.runs import merge_traces

    a = _fake_host_trace(tmp_path / "h0.json", wall_t0=1000.0,
                         offset_ms=0.0, process_index=0, pid=111,
                         span_ts=1000)
    # Host 1's recorder started 200ms later on a clock the handshake
    # measured 250ms AHEAD: its aligned anchor (999.95) is the earliest.
    b = _fake_host_trace(tmp_path / "h1.json", wall_t0=1000.2,
                         offset_ms=250.0, process_index=1, pid=222,
                         span_ts=1000)
    out = tmp_path / "merged.json"
    n_events, n_hosts = merge_traces([str(a), str(b)], str(out))
    assert n_hosts == 2
    doc = json.loads(out.read_text())
    spans = {e["pid"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert set(spans) == {0, 1}  # original pids remapped to host index
    # Host 0 shifts +50ms onto the common base; host 1 anchors it.
    assert spans[0]["ts"] == pytest.approx(51_000.0)
    assert spans[1]["ts"] == pytest.approx(1_000.0)
    pnames = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
              if e.get("name") == "process_name"}
    assert "host0 pid=111" in pnames[0] and "host1 pid=222" in pnames[1]
    sort_idx = {e["pid"]: e["args"]["sort_index"]
                for e in doc["traceEvents"]
                if e.get("name") == "process_sort_index"}
    assert sort_idx == {0: 0, 1: 1}
    assert doc["otherData"]["merged_from"] == [str(a), str(b)]
    assert doc["otherData"]["t_unix_base"] == pytest.approx(999.95)
    assert n_events == len(doc["traceEvents"])


def test_merge_traces_foreign_file_and_errors(tmp_path):
    from distributed_ddpg_tpu.tools.runs import merge_traces

    # A foreign Chrome trace (no otherData): host = file order, no shift.
    foreign = tmp_path / "foreign.json"
    foreign.write_text(json.dumps({"traceEvents": [
        {"name": "x", "ph": "X", "pid": 9, "tid": 1, "ts": 5, "dur": 1},
    ]}))
    out = tmp_path / "m.json"
    n_events, n_hosts = merge_traces([str(foreign)], str(out))
    assert n_hosts == 1
    doc = json.loads(out.read_text())
    span = [e for e in doc["traceEvents"] if e.get("ph") == "X"][0]
    assert span["pid"] == 0 and span["ts"] == 5

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"not_a_trace": True}))
    with pytest.raises(ValueError):
        merge_traces([str(bad)], str(out))


def test_merge_trace_cli(tmp_path):
    a = _fake_host_trace(tmp_path / "h0.json", wall_t0=10.0, offset_ms=0.0,
                         process_index=0, pid=1, span_ts=0)
    b = _fake_host_trace(tmp_path / "h1.json", wall_t0=10.0, offset_ms=0.0,
                         process_index=1, pid=2, span_ts=0)
    out = tmp_path / "pod.json"
    res = subprocess.run(
        [sys.executable, "-m", "distributed_ddpg_tpu.tools.runs",
         "merge-trace", str(a), str(b), "--out", str(out)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 0, res.stderr
    assert "2 host trace(s)" in res.stdout
    assert {e["pid"] for e in json.loads(out.read_text())["traceEvents"]} \
        == {0, 1}
    # Unreadable input: exit 1, not a traceback.
    res = subprocess.run(
        [sys.executable, "-m", "distributed_ddpg_tpu.tools.runs",
         "merge-trace", str(tmp_path / "missing.json"),
         "--out", str(out)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert res.returncode == 1


# --------------------------------------------------------------------------
# tools.runs: TPU-probe failure tails are skipped (ISSUE 18 satellite)
# --------------------------------------------------------------------------


def test_summarize_skips_probe_failure_tails(tmp_path, capsys):
    from distributed_ddpg_tpu.tools.runs import summarize_run

    path = tmp_path / "r.jsonl"
    good = {"kind": "train", "step": 100, "wall_time": 1.0,
            "learner_steps_per_sec": 100.0}
    path.write_text(
        json.dumps(good) + "\n"
        + json.dumps({**good, "step": 200, "wall_time": 2.0}) + "\n"
        # The BENCH_r04/r05 shape: a CPU-fallback record with the failure
        # recorded as a structured field — its numbers must not poison
        # the digest or any A/B against a healthy baseline.
        + json.dumps({"kind": "train", "step": 300, "wall_time": 3.0,
                      "learner_steps_per_sec": 1.0,
                      "tpu_error": "probe timeout"}) + "\n"
    )
    digest = summarize_run(str(path))
    assert digest["records"]["train"] == 2
    assert digest["metrics"]["learner_steps_per_sec"]["last"] == 100.0
    err = capsys.readouterr().err
    assert "skipped 1 record" in err and "TPU-probe failure" in err


def test_compare_inherits_probe_failure_skip(tmp_path):
    from distributed_ddpg_tpu.tools.runs import compare_runs

    rec = {"kind": "train", "step": 100, "wall_time": 1.0,
           "learner_steps_per_sec": 100.0}
    a = tmp_path / "a.jsonl"
    a.write_text(json.dumps(rec) + "\n")
    b = tmp_path / "b.jsonl"
    b.write_text(
        json.dumps(rec) + "\n"
        + json.dumps({**rec, "step": 200, "learner_steps_per_sec": 1.0,
                      "probe_error": "selftest timeout"}) + "\n"
    )
    text, rows = compare_runs(str(a), str(b))
    lsps = [r for r in rows if r[0] == "learner_steps_per_sec"]
    # The fallback record dropped: no phantom 99% regression.
    assert lsps and lsps[0][1] == lsps[0][2] == 100.0, rows


# --------------------------------------------------------------------------
# schema drift (ISSUE 18 satellite): docs tables <-> emitted keys
# --------------------------------------------------------------------------


def _documented_family_keys(prefixes):
    """Backticked field tokens from the FIELDS column of every 3-column
    docs/OBSERVABILITY.md table row, slash-groups expanded — the same
    shorthand the ObservabilityDrift lint reads."""
    from distributed_ddpg_tpu.analysis.rules import _expand_slash

    keys = set()
    for line in DOCS.read_text().splitlines():
        s = line.strip()
        if not s.startswith("|"):
            continue
        cells = [c.strip() for c in s.strip("|").split("|")]
        if len(cells) < 3 or set(cells[0]) <= {"-", " "}:
            continue
        for tok in re.findall(r"`([^`]*)`", cells[1]):
            for sub in re.findall(r"[a-z][a-z0-9_/<>]*", tok):
                for k in _expand_slash(sub):
                    if "<" not in k and k.startswith(prefixes):
                        keys.add(k)
    return keys


def test_documented_pod_serve_fused_keys_are_emitted():
    """Direction 2 of the drift pin: every `pod_*`/`serve_*`/`fused_*`
    field the docs tables promise must actually exist in the emitted key
    universe — a doc row for a renamed/removed field is a lie operators
    will alert on."""
    emitted = set(PodStats().snapshot())
    emitted |= set(ServeStats().snapshot())
    emitted |= set(FusedBeatStats().snapshot())
    gathered = np.zeros((2, aggregate.SLOTS), np.int64)
    gathered[1, aggregate.SLOT_BEAT_MS] = 100_000
    emitted |= set(PodAggregator(gather_fn=lambda v: gathered)
                   .collect(beats=1, ingest_rows=1))
    # serve_client_fallbacks is emitted by the actor pool, not ServeStats;
    # pin it to its emitting source so it can't silently vanish either.
    pool_src = (Path(REPO) / "distributed_ddpg_tpu" / "actors"
                / "pool.py").read_text()
    emitted |= {k for k in ("serve_client_fallbacks",)
                if f'"{k}"' in pool_src}

    documented = _documented_family_keys(("pod_", "serve_", "fused_"))
    assert documented, "no pod_/serve_/fused_ fields found in docs tables"
    phantom = sorted(documented - emitted)
    assert not phantom, (
        f"docs/OBSERVABILITY.md documents fields nothing emits: {phantom}"
    )


def test_train_run_keys_are_documented(tmp_path):
    """Direction 1: a real CPU train run's JSONL keys must ALL appear in
    docs/OBSERVABILITY.md (matched with the ObservabilityDrift lint's own
    token/template semantics). Doubles as the end-to-end --obs_port pin:
    a live scraper thread must see the header base and /healthz 200 while
    the run is in flight."""
    from distributed_ddpg_tpu.analysis.rules import (
        _doc_field_patterns,
        _doc_mentions,
        _expand_slash,
    )
    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.train import train_jax

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    seen = {"metrics": None, "healthz": None}
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                code, _, body = _http(
                    f"http://127.0.0.1:{port}/metrics", timeout=2.0)
                if code == 200 and "ddpg_t_unix_base" in body:
                    seen["metrics"] = body
                code, _, body = _http(
                    f"http://127.0.0.1:{port}/healthz", timeout=2.0)
                if code == 200:
                    seen["healthz"] = json.loads(body)
                if seen["metrics"] is not None and seen["healthz"] is not None:
                    return
            except OSError:
                pass
            stop.wait(0.3)

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    log_path = tmp_path / "train.jsonl"
    cfg = DDPGConfig(
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=1,
        # test_trace.py's sizing: paced ingest carries the budget past the
        # 50-chunk log cadence so at least one train record lands.
        total_env_steps=4_000,
        replay_min_size=1_500,
        replay_capacity=16_384,
        max_ingest_ratio=6.0,
        eval_every=600,
        eval_episodes=1,
        obs_port=port,
        log_path=str(log_path),
    )
    try:
        out = train_jax(cfg)
    finally:
        stop.set()
        t.join(timeout=10)
    assert out["learner_steps"] > 0

    assert seen["metrics"] is not None, "scraper never reached /metrics"
    assert seen["healthz"] is not None, "scraper never saw /healthz 200"
    assert seen["healthz"]["state"] == "healthy"

    doc_text = DOCS.read_text()
    plain = {
        t2 for tok in re.findall(r"[a-z][a-z0-9_/<>]*", doc_text)
        for t2 in _expand_slash(tok) if "<" not in t2
    }
    patterns = _doc_field_patterns(doc_text)
    records = [json.loads(ln) for ln in log_path.read_text().splitlines()]
    kinds = {r["kind"] for r in records}
    assert "header" in kinds and "train" in kinds and "final" in kinds
    undocumented = sorted({
        key
        for r in records
        for key in r
        if not _doc_mentions(key, plain, patterns)
    })
    assert not undocumented, (
        f"run emitted keys docs/OBSERVABILITY.md never mentions: "
        f"{undocumented}"
    )


def test_clock_handshake_single_process_is_none():
    from distributed_ddpg_tpu.parallel import multihost

    assert multihost.clock_handshake() is None


# --------------------------------------------------------------------------
# 2-process gloo drill (slow): live scrape, peer loss, merged timeline
# --------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _free_port_pair() -> int:
    """Base port with base+1 also free (child obs port = base + pid)."""
    for _ in range(20):
        with socket.socket() as a:
            a.bind(("127.0.0.1", 0))
            base = a.getsockname()[1]
            if base + 1 > 65_535:
                continue
            with socket.socket() as b:
                try:
                    b.bind(("127.0.0.1", base + 1))
                except OSError:
                    continue
                return base
    raise RuntimeError("no adjacent free port pair")


def _infra_flake(results) -> bool:
    """The known multiprocess-CPU gloo stream race (see test_pod.py's
    twin): any SIGABRT / gloo EnforceNotMet marks the launch infra-torn,
    not a verdict on the contract under test."""
    return any(
        rc == -signal.SIGABRT
        or "gloo::EnforceNotMet" in out
        or "Gloo all-reduce failed" in out
        for rc, out in results
    )


def _try_http(url: str):
    try:
        return _http(url, timeout=2.0)
    except OSError:
        return None  # not up yet / already gone


def _obs_drill(base: Path):
    """Launch the 2-process pod with the ingress + per-process traces
    armed and process 1 scripted to freeze at its 55th steady-state beat
    (past the 50-chunk cadence, so rank 0's pod record exists). The
    parent live-polls proc0's /metrics and /healthz throughout. Returns
    ([(rc, out)] per process, observations dict)."""
    base.mkdir(parents=True, exist_ok=True)
    log_dir = base / "logs"
    log_dir.mkdir()
    trace_root = base / "traces"
    trace_root.mkdir()
    obs_base = _free_port_pair()
    child_env = {
        **os.environ,
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # The pod deadline must win against the runtime's own heartbeat
        # killer (same rationale as test_pod.py).
        "POD_RUNTIME_HEARTBEAT_TIMEOUT_S": "300",
        # hang, not kill: both processes must run their abort path and
        # EXPORT their trace rings for the merge assertion. Background
        # beats so the hung process's own frozen beat is bounded by its
        # lockstep-lane deadline (the test_pod.py hang-drill shape).
        "POD_FAULTS": "pod:1:hang@55~600",
        "POD_TIMEOUT_S": "6",
        "POD_STARTUP_GRACE_S": "120",
        "POD_CKPT_DIR": "",
        "POD_LOG_DIR": str(log_dir),
        "POD_TOTAL_STEPS": "500000",
        "POD_BG_SYNC": "1",
        "POD_OBS_PORT_BASE": str(obs_base),
        "POD_TRACE_DIR": str(trace_root),
    }
    coord = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(CHILD), str(pid), "2", str(coord),
             "podtrain"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO, env=child_env,
        )
        for pid in range(2)
    ]
    seen = {"metrics_up": False, "healthy_seen": False, "spread": None,
            "agg_hosts": None, "degraded_json": None}
    deadline = time.monotonic() + 360.0
    try:
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs):
                break
            got = _try_http(f"http://127.0.0.1:{obs_base}/metrics")
            if got is not None and got[0] == 200:
                seen["metrics_up"] = True
                body = got[2]
                m = re.search(
                    r'ddpg_pod_beat_ms_spread\{kind="pod"\} '
                    r'([0-9.eE+-]+)', body)
                if m:
                    seen["spread"] = float(m.group(1))
                m = re.search(
                    r'ddpg_pod_agg_hosts\{kind="pod"\} ([0-9.eE+-]+)',
                    body)
                if m:
                    seen["agg_hosts"] = float(m.group(1))
            got = _try_http(f"http://127.0.0.1:{obs_base}/healthz")
            if got is not None:
                code, _, body = got
                try:
                    snap = json.loads(body)
                except ValueError:
                    snap = None
                if snap is not None:
                    if code == 200 and snap.get("state") == "healthy":
                        seen["healthy_seen"] = True
                    elif code == 503:
                        seen["degraded_json"] = snap
            time.sleep(0.25)
    finally:
        results = []
        for p in procs:
            try:
                out, _ = p.communicate(
                    timeout=max(5.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            results.append((p.returncode, out))
    return results, seen


@pytest.mark.slow
def test_two_process_scrape_peer_loss_and_merged_timeline(tmp_path):
    """ISSUE 18 acceptance drill: a 2-process CPU pod serving live
    ingress shows the pod spread keys on rank 0's /metrics, flips
    /healthz healthy -> degraded when a scripted faults.py peer freeze
    declares peer loss, exits EXIT_POD_DEGRADED on both processes, and
    merge-trace fuses both hosts' trace files into one clock-aligned
    timeline with a process track per host."""
    from distributed_ddpg_tpu.tools.runs import merge_traces
    from distributed_ddpg_tpu.train import EXIT_POD_DEGRADED

    results = seen = base = None
    for attempt in range(3):
        base = tmp_path / f"attempt{attempt}"
        results, seen = _obs_drill(base)
        if not _infra_flake(results):
            break
    (rc0, out0), (rc1, out1) = results
    assert rc0 == EXIT_POD_DEGRADED, f"proc0 rc={rc0}\n{out0}"
    assert rc1 == EXIT_POD_DEGRADED, f"proc1 rc={rc1}\n{out1}"
    for out in (out0, out1):
        assert "pod peer lost" in out, out
        assert "degraded=1" in out, out

    # --- live-scrape observations (collected DURING the run) ---
    assert seen["metrics_up"], seen
    assert seen["healthy_seen"], seen
    assert seen["agg_hosts"] == 2.0, seen
    assert seen["spread"] is not None and seen["spread"] >= 0.0, seen
    snap = seen["degraded_json"]
    assert snap is not None, f"/healthz never flipped\n{out0}"
    assert snap["state"] in ("degraded", "draining"), snap
    assert any("pod_peer_lost" in r for r in snap["reasons"]), snap

    # The pod record also landed in rank 0's JSONL stream.
    recs = [
        json.loads(ln)
        for ln in (base / "logs" / "proc0.jsonl").read_text().splitlines()
        if ln.startswith("{")
    ]
    pods = [r for r in recs if r.get("kind") == "pod"]
    assert pods, "rank 0 logged no pod record"
    assert all("pod_beat_ms_spread" in r for r in pods)
    assert {r["pod_agg_hosts"] for r in pods} == {2}

    # --- merged pod timeline ---
    t0p = base / "traces" / "proc0" / "trace.json"
    t1p = base / "traces" / "proc1" / "trace.json"
    assert t0p.exists(), f"proc0 exported no trace\n{out0}"
    assert t1p.exists(), f"proc1 exported no trace\n{out1}"
    merged = base / "trace_merged.json"
    n_events, n_hosts = merge_traces([str(t0p), str(t1p)], str(merged))
    assert n_hosts == 2 and n_events > 0
    doc = json.loads(merged.read_text())
    evs = doc["traceEvents"]
    assert {e["pid"] for e in evs if e.get("ph") == "X"} == {0, 1}, (
        "merged timeline must carry span tracks from BOTH hosts"
    )
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e.get("name") == "process_name"}
    assert set(pnames) == {0, 1}
    for e in evs:
        if e.get("ph") in ("X", "i"):
            assert isinstance(e.get("ts"), (int, float)) and e["ts"] >= 0
