"""Pod supervisor tests (distributed_ddpg_tpu/supervisor/; ISSUE 19;
docs/OPERATIONS.md "Pod supervisor runbook").

Tier-1 (fast, no jax in the children): the typed exit-code contract
(exits.py), the pure generation classifier + backoff curve, the JSONL
event log, the rejoin prober's damping state machine driven
synchronously, /healthz probing against a real ObsExporter, and the
supervisor's decision paths exercised end-to-end with scripted stdlib
children — crash-loop breaker, numeric refusal, preemption, and the
full shrink -> probe-gated grow -> success cycle in seconds.

Slow: the gloo acceptance drill — a real 2-process podtrain pod under
the supervisor, `pod:1:kill@12` in generation 1 only, auto-shrink to a
degraded singleton, health-gated stop-the-world grow back to 2, clean
completion. Zero operator actions between kill and PASS.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
from pathlib import Path

import pytest

from distributed_ddpg_tpu import exits
from distributed_ddpg_tpu.metrics import SupervisorStats
from distributed_ddpg_tpu.obs import health
from distributed_ddpg_tpu.obs.exporter import ObsExporter
from distributed_ddpg_tpu.obs.probe import ProbeResult, probe_healthz
from distributed_ddpg_tpu.supervisor import (
    EventLog,
    HealthProber,
    PodSupervisor,
    SupervisorConfig,
    SupervisorGaveUp,
    classify_generation,
)
from distributed_ddpg_tpu.supervisor.core import backoff_for
from distributed_ddpg_tpu.tools import runs as runs_cli
from distributed_ddpg_tpu.tools import supervise as supervise_cli

TESTS = Path(__file__).resolve().parent
REPO = str(TESTS.parent)
CHILD = TESTS / "multihost_child.py"


@pytest.fixture(autouse=True)
def _healthy_singleton():
    health.get().reset()
    yield
    health.get().reset()


# --------------------------------------------------------------------------
# exits.py: the one-place contract
# --------------------------------------------------------------------------


def test_exit_contract_values_are_the_documented_ones():
    assert exits.EXIT_OK == 0
    assert exits.EXIT_WATCHDOG_STALL == 70
    assert exits.EXIT_PREEMPTED == 75
    assert exits.EXIT_POD_DEGRADED == 76
    assert exits.EXIT_NUMERIC == 77
    assert exits.EXIT_POD_SHRINK == 78
    assert exits.EXIT_SUPERVISOR_GAVE_UP == 79
    # Every typed code has an event-log name, and they are unique.
    assert len(set(exits.NAMES.values())) == len(exits.NAMES) == 7


def test_describe_covers_typed_signal_untyped_and_unknown():
    assert exits.describe(exits.EXIT_POD_SHRINK) == "pod_shrink_ready"
    assert exits.describe(0) == "ok"
    assert exits.describe(-signal.SIGKILL) == "signal:SIGKILL"
    assert exits.describe(-signal.SIGTERM) == "signal:SIGTERM"
    assert exits.describe(1) == "exit:1"
    assert exits.describe(None) == "unknown"


def test_train_reexports_are_the_same_objects():
    # train.py re-exports the constants (its public API predates
    # exits.py); drift between the two would fork the contract.
    train = pytest.importorskip("distributed_ddpg_tpu.train")
    assert train.EXIT_PREEMPTED is exits.EXIT_PREEMPTED
    assert train.EXIT_POD_DEGRADED is exits.EXIT_POD_DEGRADED
    assert train.EXIT_POD_SHRINK is exits.EXIT_POD_SHRINK
    assert train.EXIT_NUMERIC is exits.EXIT_NUMERIC


# --------------------------------------------------------------------------
# pure decision logic: classifier + backoff
# --------------------------------------------------------------------------


def test_classify_generation_matrix():
    E = exits
    # all clean -> success
    assert classify_generation([0, 0]) == "success"
    # numeric outranks EVERYTHING, including a pending resize
    assert classify_generation([0, E.EXIT_NUMERIC]) == "numeric"
    assert classify_generation(
        [E.EXIT_NUMERIC, E.EXIT_POD_SHRINK]) == "numeric"
    assert classify_generation([E.EXIT_NUMERIC], grow_pending=True) \
        == "numeric"
    # self-initiated resize: the SIGTERM exits carry no new information
    assert classify_generation([E.EXIT_PREEMPTED], grow_pending=True) \
        == "resize"
    # shrink needs a 78 AND somebody actually dead-by-signal
    assert classify_generation(
        [E.EXIT_POD_SHRINK, -signal.SIGKILL]) == "shrink"
    assert classify_generation(
        [E.EXIT_POD_SHRINK, None, 0]) == "shrink"
    # all-78, nobody dead: lockstep abort -> full-strength relaunch
    assert classify_generation(
        [E.EXIT_POD_SHRINK, E.EXIT_POD_SHRINK]) == "relaunch"
    # the relaunch family
    for code in (E.EXIT_WATCHDOG_STALL, E.EXIT_PREEMPTED,
                 E.EXIT_POD_DEGRADED, 1):
        assert classify_generation([code, 0]) == "relaunch", code
    assert classify_generation([-signal.SIGKILL, -signal.SIGKILL]) \
        == "relaunch"


def test_backoff_doubles_and_caps():
    assert backoff_for(0, 1.0, 60.0) == 0.0
    assert backoff_for(1, 1.0, 60.0) == 1.0
    assert backoff_for(2, 1.0, 60.0) == 2.0
    assert backoff_for(4, 1.0, 60.0) == 8.0
    assert backoff_for(50, 1.0, 60.0) == 60.0  # capped


# --------------------------------------------------------------------------
# event log
# --------------------------------------------------------------------------


def test_event_log_round_trips_jsonl(tmp_path):
    path = str(tmp_path / "sup.jsonl")
    log = EventLog(path)
    log.emit("spawn", gen=1, proc=0, members=2)
    log.emit("exit", gen=1, proc=0, code=78,
             code_name="pod_shrink_ready")
    log.emit("shrink", gen=1, members=2, target=1)
    log.close()
    recs = [json.loads(line) for line in open(path)]
    assert [r["event"] for r in recs] == ["spawn", "exit", "shrink"]
    assert all(r["kind"] == "supervisor" for r in recs)
    assert all("wall_time" in r and "t_unix" in r for r in recs)
    assert log.by_event("shrink")[0]["target"] == 1
    # path='' keeps the in-memory mirror working with no file
    mem = EventLog("")
    mem.emit("start", target=2)
    assert mem.by_event("start")[0]["target"] == 2
    mem.close()


# --------------------------------------------------------------------------
# rejoin prober: damping state machine (synchronous poll_once)
# --------------------------------------------------------------------------


class _ScriptedProbe:
    """probe_fn stand-in: pops the next scripted verdict per call."""

    def __init__(self, verdicts):
        self.verdicts = list(verdicts)

    def __call__(self, host, port):
        healthy = self.verdicts.pop(0) if self.verdicts else True
        return ProbeResult(healthy, healthy,
                           "healthy" if healthy else "down")


def _prober(verdicts, *, k=3, hysteresis=0.0, transitions=None):
    p = HealthProber(
        {1: ("127.0.0.1", 1)},
        interval_s=0.01,
        healthy_k=k,
        hysteresis_s=hysteresis,
        probe_fn=_ScriptedProbe(verdicts),
        on_transition=(
            (lambda s, t, r: transitions.append((s, t)))
            if transitions is not None else None
        ),
    )
    p.set_watched([1])
    return p


def test_prober_requires_k_consecutive_healthy():
    transitions = []
    p = _prober([True, True, False, True, True, True],
                transitions=transitions)
    for _ in range(2):
        p.poll_once()
    assert p.ready_slots() == []          # 2 < K=3
    p.poll_once()                          # flap resets the count
    assert p.ready_slots() == []
    for _ in range(3):
        p.poll_once()
    assert p.ready_slots() == [1]
    assert transitions == [(1, "up"), (1, "flap"), (1, "up"), (1, "ready")]


def test_prober_hysteresis_gates_a_fast_k(monkeypatch):
    # K satisfied immediately but the slot hasn't been continuously
    # healthy for hysteresis_s: not ready until the clock catches up.
    p = _prober([True] * 10, k=2, hysteresis=3600.0)
    for _ in range(5):
        p.poll_once()
    assert p.ready_slots() == []
    # Re-anchor the hysteresis clock into the past: now it clears.
    with p._lock:
        p._watched[1].last_unhealthy -= 7200.0
    assert p.ready_slots() == [1]


def test_prober_unwatch_drops_state():
    p = _prober([True] * 6, k=2, hysteresis=0.0)
    p.poll_once()
    p.poll_once()
    assert p.ready_slots() == [1]
    p.set_watched([])                      # slot rejoined: stop watching
    assert p.ready_slots() == []
    p.set_watched([1])                     # lost again: starts cold
    p.poll_once()
    assert p.ready_slots() == []


# --------------------------------------------------------------------------
# /healthz probing against a real exporter
# --------------------------------------------------------------------------


def test_probe_healthz_states(tmp_path):
    ex = ObsExporter(0).start()
    try:
        r = probe_healthz("127.0.0.1", ex.port)
        assert r.reachable and r.healthy and r.state == "healthy"
        assert bool(r) is True
        health.get().drain("pod abort (exit 78)")
        r = probe_healthz("127.0.0.1", ex.port)
        assert r.reachable and not r.healthy and r.state == "draining"
        assert bool(r) is False
    finally:
        ex.stop()
        health.get().reset()
    # Stopped exporter: connection refused -> down, never raises.
    r = probe_healthz("127.0.0.1", ex.port)
    assert not r.reachable and not r.healthy and r.state == "down"


# --------------------------------------------------------------------------
# supervisor decision paths with scripted stdlib children (fast)
# --------------------------------------------------------------------------


def _cmd(code_or_script):
    """command_builder for a fixed one-liner child."""
    script = (
        f"import sys; sys.exit({code_or_script})"
        if isinstance(code_or_script, int) else code_or_script
    )

    def build(proc, nprocs, port, gen):
        return [sys.executable, "-c", script], {}

    return build


def _fast_cfg(tmp_path, **kw):
    base = dict(
        procs=1,
        backoff_base_s=0.01,
        backoff_max_s=0.05,
        breaker_failures=3,
        breaker_window_s=60.0,
        healthy_run_s=60.0,
        drain_grace_s=5.0,
        kill_grace_s=2.0,
        event_log=str(tmp_path / "sup.jsonl"),
        report_path=str(tmp_path / "gave_up.json"),
    )
    base.update(kw)
    return SupervisorConfig(**base)


def test_crash_loop_trips_breaker_with_typed_report(tmp_path):
    cfg = _fast_cfg(tmp_path)
    sup = PodSupervisor(cfg, _cmd(1))
    with pytest.raises(SupervisorGaveUp) as ei:
        sup.run()
    assert ei.value.reason == "crash_loop"
    assert ei.value.report_path == cfg.report_path
    report = json.loads(open(cfg.report_path).read())
    assert report["reason"] == "crash_loop"
    assert report["last_exit_names"] == ["exit:1"]
    assert report["counters"]["supervisor_breaker_trips"] == 1
    assert report["counters"]["supervisor_gave_up"] == 1
    # 3 generations ran, each emitted spawn + exit; breaker + gave_up +
    # final all landed in the JSONL stream.
    events = [json.loads(line) for line in open(cfg.event_log)]
    names = [e["event"] for e in events]
    assert names.count("spawn") == 3
    assert names.count("exit") == 3
    assert "breaker" in names and "gave_up" in names
    assert names[-1] == "final"
    final = events[-1]
    assert final["code"] == exits.EXIT_SUPERVISOR_GAVE_UP
    assert final["supervisor_generations"] == 3


def test_numeric_abort_refused_by_default(tmp_path):
    sup = PodSupervisor(_fast_cfg(tmp_path), _cmd(77))
    with pytest.raises(SupervisorGaveUp) as ei:
        sup.run()
    assert ei.value.reason == "numeric_abort"
    assert sup.stats.snapshot()["supervisor_numeric_refusals"] == 1
    assert sup.stats.snapshot()["supervisor_generations"] == 1  # no retry
    assert "guardrail_" in ei.value.report["detail"]


def test_numeric_budget_allows_counted_relaunches(tmp_path):
    sup = PodSupervisor(_fast_cfg(tmp_path, max_numeric=2), _cmd(77))
    with pytest.raises(SupervisorGaveUp) as ei:
        sup.run()
    assert ei.value.reason == "numeric_abort"
    snap = sup.stats.snapshot()
    assert snap["supervisor_generations"] == 3   # 2 budgeted relaunches
    assert snap["supervisor_relaunches"] == 2
    reasons = [e["reason"] for e in sup.events.by_event("relaunch")]
    assert reasons == ["numeric_abort (1/2)", "numeric_abort (2/2)"]


def test_healthy_generation_resets_the_breaker(tmp_path):
    # Children die instantly, but healthy_run_s=0 classifies every
    # generation as long-lived: consecutive resets, backoff stays 0, the
    # window never fills — the supervisor keeps relaunching until the
    # generation budget (the test's own bound) gives up.
    cfg = _fast_cfg(tmp_path, healthy_run_s=0.0, max_generations=6)
    sup = PodSupervisor(cfg, _cmd(1))
    with pytest.raises(SupervisorGaveUp) as ei:
        sup.run()
    assert ei.value.reason == "generation_budget"
    snap = sup.stats.snapshot()
    assert snap["supervisor_generations"] == 6
    assert snap["supervisor_backoffs"] == 0      # never a failing streak


def test_request_stop_preempts_and_drains(tmp_path):
    cfg = _fast_cfg(tmp_path, kill_grace_s=5.0)
    sup = PodSupervisor(
        cfg, _cmd("import time; time.sleep(600)"))
    rc = {}
    t = threading.Thread(target=lambda: rc.update(v=sup.run()))
    t.start()
    # Wait for the child to be spawned, then preempt the supervisor.
    deadline = time.monotonic() + 10.0
    while not sup.events.by_event("spawn"):
        assert time.monotonic() < deadline, "child never spawned"
        time.sleep(0.02)
    sup.request_stop()
    t.join(timeout=15.0)
    assert not t.is_alive()
    assert rc["v"] == exits.EXIT_PREEMPTED
    # The sleeping child was SIGTERMed (default handler: death by signal).
    (exit_ev,) = sup.events.by_event("exit")
    assert exit_ev["code_name"] == "signal:SIGTERM"


def test_spawn_failure_feeds_breaker_not_crash(tmp_path):
    def build(proc, nprocs, port, gen):
        return ["/nonexistent/binary/for/this/test"], {}

    sup = PodSupervisor(_fast_cfg(tmp_path), build)
    with pytest.raises(SupervisorGaveUp) as ei:
        sup.run()
    assert ei.value.reason == "crash_loop"
    assert any(
        e["code_name"].startswith("spawn_error")
        for e in sup.events.by_event("exit")
    )


_CYCLE_CHILD = textwrap.dedent("""\
    import os, signal, sys, time
    proc, gen = int(sys.argv[1]), int(sys.argv[2])
    if gen == 1:
        if proc == 1:
            os.kill(os.getpid(), signal.SIGKILL)   # the lost peer
        time.sleep(0.4)                            # peer-loss detection
        sys.exit(78)                               # slices verified
    elif gen == 2:
        # Degraded singleton: run until the grow SIGTERM, take the
        # emergency-checkpoint exit.
        signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))
        time.sleep(600)
        sys.exit(75)
    else:
        sys.exit(0)                                # full strength again
""")


def test_full_shrink_probe_grow_cycle(tmp_path):
    """The whole autonomous story, in seconds, with scripted children:
    gen1 (N=2) loses proc 1 -> survivor exits 78 -> shrink to M=1;
    the stand-in peer's /healthz (a real ObsExporter) clears the
    K+hysteresis gate -> stop-the-world SIGTERM -> grow back to N=2;
    gen3 completes -> supervisor exits 0. Then `tools.runs summarize`
    renders the event log as a supervision timeline."""
    child = tmp_path / "child.py"
    child.write_text(_CYCLE_CHILD)

    def build(proc, nprocs, port, gen):
        return [sys.executable, str(child), str(proc), str(gen)], {}

    ex = ObsExporter(0).start()   # the lost peer's stand-in ingress
    cfg = _fast_cfg(
        tmp_path,
        procs=2,
        drain_grace_s=10.0,
        kill_grace_s=5.0,
        probe_interval_s=0.05,
        probe_healthy_k=2,
        probe_hysteresis_s=0.1,
        grow_defer_s=0.5,
        max_generations=6,
    )
    sup = PodSupervisor(
        cfg, build, probe_targets={1: ("127.0.0.1", ex.port)}
    )
    try:
        rc = sup.run()
    finally:
        ex.stop()
    assert rc == 0

    shrinks = sup.events.by_event("shrink")
    grows = sup.events.by_event("grow")
    assert len(shrinks) == 1 and shrinks[0]["members"] == 2 \
        and shrinks[0]["target"] == 1
    assert len(grows) == 1 and grows[0]["members"] == 1 \
        and grows[0]["target"] == 2
    assert sup.events.by_event("grow_initiated")[0]["slots"] == [1]
    # The prober's edges made it into the stream (up -> ready at least).
    transitions = [e["transition"] for e in sup.events.by_event("probe")]
    assert "up" in transitions and "ready" in transitions
    snap = sup.stats.snapshot()
    assert snap["supervisor_shrinks"] == 1
    assert snap["supervisor_grows"] == 1
    assert snap["supervisor_probe_ready"] >= 1
    assert snap["supervisor_gave_up"] == 0
    # Generation 3 was full strength again.
    gen3 = [e for e in sup.events.by_event("spawn") if e["gen"] == 3]
    assert len(gen3) == 2

    # The event log is a first-class run artifact: summarize renders it.
    digest = runs_cli.summarize_run(cfg.event_log)
    assert digest["supervisor"]["counters"]["supervisor_grows"] == 1
    text = runs_cli.render_summary(digest)
    assert "supervision timeline" in text
    assert "shrink" in text and "grow" in text


def test_cli_parses_and_gives_up_typed(tmp_path):
    """End-to-end through the tools.supervise CLI surface: flag
    plumbing, {gen} substitution in --env, and the typed gave-up exit."""
    rc = supervise_cli.main(
        [
            "--procs", "1",
            "--backoff-base", "0.01",
            "--breaker-failures", "2",
            "--breaker-window", "60",
            "--event-log", str(tmp_path / "cli.jsonl"),
            "--report", str(tmp_path / "cli_report.json"),
            "--child-logs", str(tmp_path / "children"),
            "--env", "SUPERVISE_TEST_GEN={gen}",
            "--",
            sys.executable, "-c",
            "import os, sys; sys.exit(int(os.environ"
            "['SUPERVISE_TEST_GEN']) * 0 + 1)",
        ]
    )
    assert rc == exits.EXIT_SUPERVISOR_GAVE_UP
    report = json.loads(open(tmp_path / "cli_report.json").read())
    assert report["reason"] == "crash_loop"
    # Child stdout/stderr landed in per-generation capture files.
    logs = sorted(os.listdir(tmp_path / "children"))
    assert logs == ["gen1_proc0.log", "gen2_proc0.log"]


def test_cli_rejects_missing_command_and_bad_env(capsys):
    assert supervise_cli.main(["--procs", "1"]) == 2
    with pytest.raises(SystemExit):
        supervise_cli.main(
            ["--procs", "1", "--env", "NOEQUALS", "--", "true"]
        )


# --------------------------------------------------------------------------
# the gloo acceptance drill (slow)
# --------------------------------------------------------------------------


def _drill_events(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def _drill_flaked(event_log, child_log_dir) -> bool:
    """The known multiprocess-CPU gloo stream race (test_pod._infra_flake,
    docs/RESILIENCE.md): SIGABRT or the gloo abort markers in a child
    capture. Not the supervision contract under test — retry fresh."""
    events = _drill_events(event_log)
    if any(
        e["event"] == "exit" and e.get("code") == -signal.SIGABRT
        for e in events
    ):
        return True
    for name in os.listdir(child_log_dir):
        text = (Path(child_log_dir) / name).read_text(errors="replace")
        if "gloo::EnforceNotMet" in text or "Gloo all-reduce failed" in text:
            return True
    return False


@pytest.mark.slow
def test_supervised_two_process_elastic_drill(tmp_path):
    """ISSUE 19 acceptance: the unattended version of test_pod.py's
    elastic drill. The supervisor launches a 2-process podtrain pod;
    `pod:1:kill@12` (armed on every full-strength pre-shrink generation,
    so gloo infra flakes can't outrun it) kills a
    writer past a checkpoint cadence; the survivor exits 78; the
    supervisor auto-shrinks to a degraded singleton; a stand-in healthy
    /healthz for the lost slot clears the probe gate; the supervisor
    SIGTERMs the singleton at a checkpoint boundary and relaunches at
    N=2, which adopts the 1-writer slice set, reports grows=1 with a
    healthy state, and completes its budget. Zero operator actions; the
    event log carries >=1 shrink and >=1 grow."""
    for attempt in range(3):
        ckpt_dir = tmp_path / f"ckpt{attempt}"
        child_logs = tmp_path / f"children{attempt}"
        event_log = str(tmp_path / f"sup{attempt}.jsonl")
        os.makedirs(child_logs, exist_ok=True)
        sup_ref = []

        def build(proc, nprocs, port, gen,
                  _ckpt=str(ckpt_dir), _base=tmp_path, _attempt=attempt,
                  _ref=sup_ref):
            log_dir = _base / f"logs{_attempt}_gen{gen}"
            os.makedirs(log_dir, exist_ok=True)
            env = {
                "PYTHONPATH": REPO + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
                "POD_RUNTIME_HEARTBEAT_TIMEOUT_S": "300",
                "POD_REPLAY_SHARDING": "sharded",
                "POD_TIMEOUT_S": "20",
                "POD_STARTUP_GRACE_S": "120",
                "POD_CKPT_DIR": _ckpt,
                "POD_LOG_DIR": str(log_dir),
            }
            # Phase-driven env, keyed off the pod composition instead of
            # the generation NUMBER: a gloo infra flake (docs/RESILIENCE
            # .md) can burn whole generations before the scripted kill
            # ever fires, so the kill must re-arm on every full-strength
            # pre-shrink relaunch. Budgets mirror the elastic test: the
            # pre-shrink pod and the degraded singleton never finish on
            # their own (the kill / the grow SIGTERM end them); the
            # grown pod's budget is already satisfied by the restored
            # offset -> adopt + clean exit 0. Production uses
            # --env-first for one-shot injection; this closure IS the
            # drill's scripted chaos.
            grown = bool(_ref and _ref[0].events.by_event("grow"))
            if nprocs == 2 and not grown:       # phase 1: arm the kill
                env["POD_FAULTS"] = "pod:1:kill@12"
                env["POD_TOTAL_STEPS"] = "500000"
                env["POD_CKPT_EVERY"] = "16"
            elif nprocs == 1:                   # phase 2: degraded M=1
                env["POD_TOTAL_STEPS"] = "500000"
                # Write the 1-writer slice set promptly (the elastic
                # test's checkpoint_every=1).
                env["POD_CKPT_EVERY"] = "1"
            else:                               # phase 3: grown back
                env["POD_TOTAL_STEPS"] = "1"
                env["POD_CKPT_EVERY"] = "16"
            argv = [sys.executable, str(CHILD), str(proc), str(nprocs),
                    str(port), "podtrain"]
            return argv, env

        ex = ObsExporter(0).start()   # lost slot 1's stand-in /healthz
        cfg = SupervisorConfig(
            procs=2,
            backoff_base_s=0.5,
            backoff_max_s=5.0,
            breaker_failures=0,          # flakes retry at THIS level
            healthy_run_s=10.0,
            max_generations=8,
            drain_grace_s=150.0,         # survivor needs the pod deadline
            kill_grace_s=60.0,           # emergency checkpoint on SIGTERM
            probe_interval_s=1.0,
            probe_healthy_k=3,
            probe_hysteresis_s=2.0,
            # The singleton must adopt + write a cadence first: defer the
            # stop-the-world resize past jax import + compile.
            grow_defer_s=75.0,
            event_log=event_log,
            report_path=str(tmp_path / f"report{attempt}.json"),
            child_log_dir=str(child_logs),
        )
        sup = PodSupervisor(
            cfg, build, probe_targets={1: ("127.0.0.1", ex.port)}
        )
        sup_ref.append(sup)
        rc = {}

        def _run():
            try:
                rc.update(v=sup.run())
            except SupervisorGaveUp as e:   # generation budget: a flake
                rc.update(gave_up=e.reason)  # storm — retried below

        t = threading.Thread(target=_run)
        t.start()
        t.join(timeout=720.0)
        if t.is_alive():                 # wedged (infra): drain + retry
            sup.request_stop()
            t.join(timeout=120.0)
        ex.stop()
        health.get().reset()
        if rc.get("v") == 0 and not t.is_alive():
            break
        assert _drill_flaked(event_log, child_logs), (
            f"drill failed for a non-flake reason: rc={rc!r}\n"
            + "\n".join(map(json.dumps, _drill_events(event_log)))
        )
    assert rc.get("v") == 0, "all attempts infra-flaked"

    events = _drill_events(event_log)
    names = [e["event"] for e in events]
    assert names.count("shrink") >= 1, names
    assert names.count("grow") >= 1, names
    shrink = next(e for e in events if e["event"] == "shrink")
    assert (shrink["members"], shrink["target"]) == (2, 1)
    grow = next(e for e in events if e["event"] == "grow")
    assert (grow["members"], grow["target"]) == (1, 2)
    final = events[-1]
    assert final["event"] == "final" and final["code"] == 0
    assert final["supervisor_shrinks"] >= 1
    assert final["supervisor_grows"] >= 1
    assert final["supervisor_gave_up"] == 0

    # The grown generation adopted the singleton's slice set and cleared
    # the degraded state (the PODRESULT line in its capture). A flake can
    # burn post-grow generations too, so read the LAST generation — with
    # rc == 0 it is the one that completed its budget.
    gen = max(e["gen"] for e in events if e["event"] == "spawn")
    grown = [
        (Path(cfg.child_log_dir) / f"gen{gen}_proc{p}.log").read_text(
            errors="replace")
        for p in range(2)
    ]
    for out in grown:
        assert " adopted=1 " in out, out[-2000:]
        assert " grows=1 " in out, out[-2000:]
        assert "degraded=0" in out, out[-2000:]
