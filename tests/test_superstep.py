"""Compile-once multi-beat superstep (parallel/superstep.py;
docs/FUSED_BEAT.md §superstep):

- **bit-identity at the superstep/beat seam**: a B-beat superstep (one
  `lax.fori_loop` dispatch) must equal B sequential fused beats
  BIT-FOR-BIT for fixed seeds — uniform + PER, replicated + sharded,
  guarded + unguarded. This is the oracle that lets the superstep ship
  without its own quality story, the same anchoring discipline the fused
  beat itself used against the dispatch-per-phase loop. The load-bearing
  structural fact (recorded in the module docstring): ALL B beats run
  inside the loop body, which XLA compiles as its own isolated
  computation — a beat inlined into the main computation gets
  cross-optimized with its surroundings and drifts at the ULP level.
- **one host sync per superstep**: stats/health accumulate in the
  device-side carry; the dispatch counter proves B beats rode one
  dispatch.
- **quarantine mid-superstep**: the chaos vector fires INSIDE the loop,
  the stacked health carry reports WHICH beat went bad
  (first_bad_beat), and the drop semantics match the per-beat path.
- **config validation** and **train/bench/gate integration**.
"""

import json

import numpy as np
import pytest

import jax

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.train import train_jax


def _cfg(**kw):
    base = dict(
        env_id="Pendulum-v1",
        actor_backend="device",
        num_actors=0,
        device_actor_envs=8,
        device_actor_chunk=2,
        learner_chunk=2,
        batch_size=8,
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        replay_capacity=256,
        fused_chunk="off",
        fused_beat="on",
        seed=3,
    )
    base.update(kw)
    return DDPGConfig(**base)


def _setup(config, sharded):
    """One (learner, pool, replay) stack with the ring pre-warmed by four
    standalone rollout chunks — both arms of the A/B build through here,
    so their pre-dispatch state is identical (test_megastep.py idiom)."""
    from distributed_ddpg_tpu.actors.device_pool import DeviceActorPool
    from distributed_ddpg_tpu.parallel import mesh as mesh_lib
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )

    n = 2 if sharded else 1
    placement = "sharded" if sharded else "replicated"
    mesh = mesh_lib.make_mesh(n, 1, devices=jax.devices("cpu")[:n])
    pool = DeviceActorPool(config, mesh=mesh)
    learner = ShardedLearner(
        config, pool.obs_dim, pool.act_dim, pool.action_scale,
        action_offset=pool.action_offset, mesh=mesh, chunk_size=2,
        replay_sharding=placement,
    )
    cls = DevicePrioritizedReplay if config.prioritized else DeviceReplay
    replay = cls(
        config.replay_capacity, pool.obs_dim, pool.act_dim, mesh=mesh,
        block_size=16, async_ship=False, replay_sharding=placement,
    )
    pool.set_params(learner.state.actor_params)
    for _ in range(4):
        pool.run_chunk(replay)
    return learner, pool, replay


def _leaves_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
        )
        for x, y in zip(la, lb)
    )


def _assert_stacks_equal(sup, seq, per):
    ls, rs = sup[2], seq[2]
    assert _leaves_equal(ls.storage, rs.storage)
    assert int(jax.device_get(ls.ptr)) == int(jax.device_get(rs.ptr))
    assert int(jax.device_get(ls.size)) == int(jax.device_get(rs.size))
    assert _leaves_equal(sup[0].state, seq[0].state)
    assert _leaves_equal(sup[0]._key, seq[0]._key)
    assert _leaves_equal(sup[1]._carry, seq[1]._carry)
    if per:
        assert _leaves_equal(ls.priorities, rs.priorities)
        assert _leaves_equal(ls.max_priority, rs.max_priority)


@pytest.mark.parametrize("guard", [False, True],
                         ids=["unguarded", "guarded"])
@pytest.mark.parametrize("per", [False, True], ids=["uniform", "per"])
@pytest.mark.parametrize("sharded", [False, True],
                         ids=["replicated", "sharded"])
def test_superstep_bit_identical_to_sequential_beats(per, sharded, guard):
    """One B=4 superstep == four sequential fused beats: storage/ptr/
    size, the full TrainState, the sampling key, the rollout carry,
    (PER) priorities, and (guarded) the health view are bit-identical."""
    from distributed_ddpg_tpu.parallel.megastep import FusedMegastep
    from distributed_ddpg_tpu.parallel.superstep import FusedSuperstep

    config = _cfg(prioritized=per, guardrails=guard, superstep_beats=4)
    sup = _setup(config, sharded)
    ss = FusedSuperstep(config, *sup)
    ss.run_superstep(betas=0.5 if per else None)

    seq = _setup(config, sharded)
    ms = FusedMegastep(config, *seq)
    for _ in range(4):
        ms.run_beat(beta=0.5 if per else None)

    _assert_stacks_equal(sup, seq, per)
    if guard:
        hs = sup[0].poll_health()
        # The stacked health carry adds the per-beat attribution key;
        # the cumulative counters themselves must match the scalar path.
        assert hs.pop("first_bad_beat") == -1
        assert hs == seq[0].poll_health()


def test_superstep_b1_matches_single_beats():
    """B=1 is today's behavior: three one-beat supersteps == three
    per-beat dispatches, bit-for-bit (the degenerate-loop oracle)."""
    from distributed_ddpg_tpu.parallel.megastep import FusedMegastep
    from distributed_ddpg_tpu.parallel.superstep import FusedSuperstep

    config = _cfg(superstep_beats=1)
    sup = _setup(config, sharded=False)
    ss = FusedSuperstep(config, *sup)
    for _ in range(3):
        ss.run_superstep()

    seq = _setup(config, sharded=False)
    ms = FusedMegastep(config, *seq)
    for _ in range(3):
        ms.run_beat()

    _assert_stacks_equal(sup, seq, per=False)


def test_superstep_single_host_sync_per_dispatch():
    """B beats ride ONE dispatch: the stats layer counts supersteps and
    beats separately, and fused_beat_ms reads as whole-dispatch wall
    amortized over B (the /B headline)."""
    from distributed_ddpg_tpu.parallel.superstep import FusedSuperstep

    config = _cfg(superstep_beats=4)
    learner, pool, replay = _setup(config, sharded=False)
    ss = FusedSuperstep(config, learner, pool, replay)
    for _ in range(2):
        ss.run_superstep()
    snap = ss.snapshot()
    assert snap["fused_supersteps"] == 2
    assert snap["fused_beats"] == 8
    assert snap["fused_superstep_beats"] == 4.0
    assert snap["fused_beat_ms"] > 0


def test_quarantine_mid_superstep_reports_first_bad_beat():
    """numeric:grad:nan@3 poisons learner step 3 — beat index 1 of the
    first B=2 superstep. The stacked health carry localizes it
    (first_bad_beat=1), the update is dropped on device, and the next
    (clean) superstep reports first_bad_beat=-1 with cumulative
    counters intact."""
    from distributed_ddpg_tpu.parallel.superstep import FusedSuperstep

    config = _cfg(
        guardrails=True, faults="numeric:grad:nan@3", superstep_beats=2,
    )
    learner, pool, replay = _setup(config, sharded=False)
    ss = FusedSuperstep(config, learner, pool, replay)
    ss.run_superstep()  # steps 1-4: step 3 poisoned, in beat index 1
    h = learner.poll_health()
    assert h["total"] == 4
    assert h["nonfinite"] == 1
    assert h["skipped"] == 1
    assert h["first_bad_beat"] == 1
    for leaf in jax.tree.leaves(learner.state.actor_params):
        assert np.isfinite(np.asarray(jax.device_get(leaf))).all()
    ss.run_superstep()  # steps 5-8: clean
    h = learner.poll_health()
    assert h["total"] == 8
    assert h["nonfinite"] == 1
    assert h["first_bad_beat"] == -1


def test_superstep_rebuilds_after_learner_program_rebuild():
    """set_lr_scale (the rollback LR backoff) rebuilds the learner's
    chunk bodies; the next run_superstep must recompose the loop body
    against them instead of dispatching the stale closures."""
    from distributed_ddpg_tpu.parallel.superstep import FusedSuperstep

    config = _cfg(superstep_beats=2)
    learner, pool, replay = _setup(config, sharded=False)
    ss = FusedSuperstep(config, learner, pool, replay)
    ss.run_superstep()
    v0 = ss._learner_version
    learner.set_lr_scale(0.5)
    ss.run_superstep()
    assert ss._learner_version == learner.programs_version != v0


def test_superstep_config_validation():
    """The superstep_beats rejection matrix (config.py)."""
    with pytest.raises(ValueError, match="superstep_beats must be"):
        _cfg(superstep_beats=0)
    # B > 1 composes FUSED beats; there is no unfused dispatch to wrap.
    with pytest.raises(ValueError, match="superstep_beats > 1"):
        _cfg(fused_beat="off", superstep_beats=2)
    assert _cfg(fused_beat="off", superstep_beats=1).superstep_beats == 1
    assert _cfg(superstep_beats=4).superstep_beats == 4


def _ondevice_cfg(**kw):
    base = dict(
        env_id="Pendulum-v1",
        backend="jax_ondevice",
        num_actors=8,
        batch_size=32,
        replay_capacity=4096,
        replay_min_size=64,
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        total_env_steps=2048,
        seed=0,
    )
    base.update(kw)
    return DDPGConfig(**base)


def test_ondevice_superstep_bit_identical_and_stacked_stats():
    """The whole-run-fusion rung rides the same oracle: one B=2
    ondevice superstep == two sequential chunk dispatches (full Carry:
    train state, env state, ring, RNG), and the stacked ChunkStats
    finalize to a host dict with the same schema and the summed
    learn-step count. Pinned to a SINGLE-device mesh: that is where the
    loop-body isolation argument gives exact codegen parity; the
    multi-device SPMD path drifts at the ULP level from collective
    scheduling and is covered (at tolerance) by the test below."""
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG
    from distributed_ddpg_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(1, 1, devices=jax.devices("cpu")[:1])
    t_sup = OnDeviceDDPG(
        _ondevice_cfg(superstep_beats=2), mesh=mesh, chunk_size=4
    )
    t_seq = OnDeviceDDPG(_ondevice_cfg(), mesh=mesh, chunk_size=4)

    # Three rounds so later supersteps run fully past the learn gate.
    # EVERY chunk's stats are finalized (the counter accumulates there).
    for _ in range(3):
        stats = t_sup.run_superstep()
        host_sup = t_sup.finalize_stats(stats)
        for _ in range(2):
            host_seq = t_seq.finalize_stats(t_seq.run_chunk())

    assert t_sup.env_steps == t_seq.env_steps
    assert t_sup.learn_steps == t_seq.learn_steps
    assert _leaves_equal(t_sup.carry, t_seq.carry)
    # Stacked finalize: same schema as the scalar path, finite metrics.
    assert set(host_sup) == set(host_seq)
    for k, v in host_sup.items():
        assert np.isfinite(v), f"{k} not finite in stacked finalize"


def test_ondevice_superstep_spmd_matches_at_tolerance():
    """The SPMD (8 virtual device) ondevice superstep: integer/
    bookkeeping state (step counters, ring ptr/size, RNG key) stays
    EXACT vs sequential chunks, and every float leaf agrees to float32
    tolerance. Bitwise parity is a single-device property — under a
    multi-device mesh XLA schedules the collectives differently inside
    the fori_loop body than in the standalone chunk program, an
    ULP-level reassociation the oracle above cannot demand here."""
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    t_sup = OnDeviceDDPG(_ondevice_cfg(superstep_beats=2), chunk_size=4)
    t_seq = OnDeviceDDPG(_ondevice_cfg(), chunk_size=4)
    for _ in range(3):
        t_sup.finalize_stats(t_sup.run_superstep())
        for _ in range(2):
            t_seq.finalize_stats(t_seq.run_chunk())

    assert t_sup.env_steps == t_seq.env_steps
    assert t_sup.learn_steps == t_seq.learn_steps
    for a, b in zip(
        jax.tree.leaves(t_sup.carry), jax.tree.leaves(t_seq.carry)
    ):
        a = np.asarray(jax.device_get(a))
        b = np.asarray(jax.device_get(b))
        if a.dtype.kind == "f":
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
        else:
            assert np.array_equal(a, b)


def _train_cfg(tmp_path, **kw):
    base = dict(
        env_id="Pendulum-v1",
        actor_backend="device",
        num_actors=0,
        device_actor_envs=8,
        device_actor_chunk=2,
        learner_chunk=2,
        batch_size=16,
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        replay_capacity=2048,
        replay_min_size=64,
        # 64 warmup rows + 384 steady rows = 24 beats = 6 B=4 supersteps:
        # both arms land exactly on the budget, so the parity assert
        # compares equal-work runs (budget checks run once per superstep).
        total_env_steps=448,
        eval_every=0,
        eval_episodes=1,
        fused_chunk="off",
        fused_beat="on",
        log_path=str(tmp_path / "run.jsonl"),
    )
    base.update(kw)
    return DDPGConfig(**base)


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_train_superstep_matches_per_beat_dispatch(tmp_path):
    """TRAIN-LEVEL parity (the seam the unit oracle cannot see — loop
    accounting, cadences, warmup handoff): superstep_beats=4 and =1
    finish with the same learner-step count, env-step production, and a
    bit-identical param checksum, and the superstep run reports the
    dispatch amortization in its final record."""
    outs = {}
    for beats in (1, 4):
        cfg = _train_cfg(tmp_path, superstep_beats=beats,
                         log_path=str(tmp_path / f"b{beats}.jsonl"))
        outs[beats] = train_jax(cfg)
    assert outs[4]["fused_beat_active"] is True
    assert outs[4]["learner_steps"] == outs[1]["learner_steps"]
    assert outs[4]["devactor_env_steps"] == outs[1]["devactor_env_steps"]
    assert outs[4]["param_checksum"] == outs[1]["param_checksum"]
    finals = [r for r in _records(str(tmp_path / "b4.jsonl"))
              if r["kind"] == "final"]
    assert finals
    final = finals[-1]
    for key in ("fused_beats", "fused_supersteps", "fused_superstep_beats",
                "fused_beat_ms"):
        assert key in final, f"{key} missing from the final record"
    assert final["fused_superstep_beats"] == 4.0
    assert final["fused_beats"] == 4 * final["fused_supersteps"]


def test_train_superstep_guarded_smoke(tmp_path):
    """Guarded superstep end-to-end: the stacked health carry feeds the
    monitor without tripping quarantine on a healthy run."""
    cfg = _train_cfg(tmp_path, superstep_beats=4, guardrails=True)
    out = train_jax(cfg)
    assert out["fused_beat_active"] is True
    assert out["learner_steps"] > 0
    assert out["guardrail_skipped_updates"] == 0


def test_superstep_bench_phase_and_gate_key_registered():
    """The BENCH_SUPERSTEP wiring exists end to end: bench.py registers
    the superstep phase, and scripts/ci_gate.sh's default keys pin the
    higher-is-better superstep_steps_per_s."""
    import pathlib

    import bench

    assert "superstep" in bench._PHASES
    gate = pathlib.Path(__file__).parent.parent / "scripts" / "ci_gate.sh"
    text = gate.read_text(encoding="utf-8")
    assert ",superstep_steps_per_s" in text  # no '-' prefix: higher is better
