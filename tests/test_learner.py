"""Learner-step tests: determinism under seed, target-network Polyak
semantics inside the fused step, PER weight plumbing, distributional path
shape/grad sanity (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import (
    init_train_state,
    jit_learner_step,
    make_learner_step,
)
from distributed_ddpg_tpu.types import Batch

OBS, ACT, B = 5, 2, 16


def _batch(key, b=B):
    ks = jax.random.split(key, 3)
    return Batch(
        obs=jax.random.normal(ks[0], (b, OBS)),
        action=jax.random.uniform(ks[1], (b, ACT), minval=-1, maxval=1),
        reward=jax.random.normal(ks[2], (b,)),
        discount=jnp.full((b,), 0.99),
        next_obs=jax.random.normal(ks[0], (b, OBS)),
        weight=jnp.ones((b,)),
    )


def _cfg(**kw):
    base = dict(actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B)
    base.update(kw)
    return DDPGConfig(**base)


def test_step_deterministic_under_seed():
    cfg = _cfg()
    batch = _batch(jax.random.PRNGKey(7))
    outs = []
    for _ in range(2):
        state = init_train_state(cfg, OBS, ACT, seed=3)
        step = jit_learner_step(cfg, 1.0, donate=False)
        out = step(state, batch)
        out = step(out.state, batch)
        outs.append(out)
    for a, b in zip(jax.tree.leaves(outs[0].state), jax.tree.leaves(outs[1].state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(outs[0].state.step) == 2


def test_polyak_semantics_in_step():
    """After one step: target == tau*new_online + (1-tau)*old_target, with
    old_target == init online params (hard copy at init, SURVEY.md §3.4)."""
    cfg = _cfg(tau=0.25)
    state = init_train_state(cfg, OBS, ACT, seed=0)
    step = jit_learner_step(cfg, 1.0, donate=False)
    out = step(state, _batch(jax.random.PRNGKey(0)))
    expect = jax.tree.map(
        lambda new, old: 0.25 * new + 0.75 * old,
        out.state.actor_params,
        state.actor_params,  # == initial target
    )
    for a, b in zip(jax.tree.leaves(expect), jax.tree.leaves(out.state.target_actor_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_per_weights_scale_critic_grads():
    """Zero IS weights must zero the critic TD gradient (only L2 remains)."""
    cfg = _cfg()
    state = init_train_state(cfg, OBS, ACT, seed=0)
    step = make_learner_step(cfg, 1.0)
    batch = _batch(jax.random.PRNGKey(1))
    zero_w = batch._replace(weight=jnp.zeros((B,)))
    out = step(state, zero_w)
    np.testing.assert_allclose(float(out.metrics["critic_loss"]), 0.0, atol=1e-7)
    # Critic params unchanged direction-wise: grads were exactly zero → Adam
    # update is 0/(0+eps) = 0.
    for a, b in zip(jax.tree.leaves(state.critic_params), jax.tree.leaves(out.state.critic_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


def test_td_errors_shape_and_finite():
    cfg = _cfg()
    state = init_train_state(cfg, OBS, ACT, seed=0)
    out = jit_learner_step(cfg, 1.0, donate=False)(state, _batch(jax.random.PRNGKey(2)))
    td = np.asarray(out.td_errors)
    assert td.shape == (B,) and np.isfinite(td).all()


def test_distributional_step_runs_and_learns_shapes():
    cfg = _cfg(distributional=True, num_atoms=21, v_min=-10.0, v_max=10.0)
    state = init_train_state(cfg, OBS, ACT, seed=0)
    # Critic final layer must have num_atoms outputs.
    assert state.critic_params[-1]["w"].shape[-1] == 21
    step = jit_learner_step(cfg, 1.0, donate=False)
    out = step(state, _batch(jax.random.PRNGKey(3)))
    assert np.isfinite(float(out.metrics["critic_loss"]))
    assert out.td_errors.shape == (B,)
    # Params actually moved.
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(state.critic_params), jax.tree.leaves(out.state.critic_params))
    )
    assert moved


def test_critic_l2_regularization_applied():
    cfg0 = _cfg(critic_l2=0.0)
    cfg1 = _cfg(critic_l2=0.1)
    state = init_train_state(cfg0, OBS, ACT, seed=0)
    batch = _batch(jax.random.PRNGKey(4))
    l0 = make_learner_step(cfg0, 1.0)(state, batch).metrics["critic_loss"]
    l1 = make_learner_step(cfg1, 1.0)(state, batch).metrics["critic_loss"]
    assert float(l1) > float(l0)
