"""Shared fused-chunk vs XLA-scan parity check, used by BOTH tiers:

- tests/test_fused_chunk.py runs it in pallas interpret mode with tight
  tolerances (the bit-level oracle, no TPU needed), and
- tests/tpu_child.py runs it natively compiled on a real TPU with
  fp-noise tolerances (two different on-TPU programs accumulate in
  different orders).

One body, parameterized by (interpret, tolerances), so the two tiers can
never drift apart semantically.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ddpg_tpu.learner import init_train_state, make_learner_step
from distributed_ddpg_tpu.ops import fused_chunk
from distributed_ddpg_tpu.types import pack_batch_np, unpack_batch


def make_packed_batches(rng, k: int, b: int, obs: int, act: int):
    return pack_batch_np(
        {
            "obs": rng.standard_normal((k, b, obs)).astype(np.float32),
            "action": rng.uniform(-1, 1, (k, b, act)).astype(np.float32),
            "reward": rng.standard_normal((k, b)).astype(np.float32),
            "discount": np.full((k, b), 0.99, np.float32),
            "next_obs": rng.standard_normal((k, b, obs)).astype(np.float32),
            "weight": rng.uniform(0.5, 1.0, (k, b)).astype(np.float32),
        }
    )


def assert_fused_matches_scan(
    cfg,
    obs: int,
    act: int,
    k: int,
    scale,
    offset,
    interpret: bool | None,
    rtol: float,
    atol: float,
    metric_rtol: float | None = None,
):
    """Run the megakernel chunk and K sequential scan-path steps on the same
    batches; assert end state, TD errors, and chunk-mean metrics agree.
    Returns the kernel's metrics dict."""
    state = init_train_state(cfg, obs, act, seed=cfg.seed)
    packed = make_packed_batches(
        np.random.default_rng(7), k, cfg.batch_size, obs, act
    )
    run = fused_chunk.make_fused_chunk_fn(
        cfg, obs, act, scale, offset, chunk_size=k, interpret=interpret
    )
    new_state, td, metrics = jax.jit(run)(state, jnp.asarray(packed))

    step = make_learner_step(cfg, scale, action_offset=offset)
    ref = state
    ref_tds, ref_ms = [], []
    for i in range(k):
        out = step(ref, unpack_batch(jnp.asarray(packed[i]), obs, act))
        ref = out.state
        ref_tds.append(np.asarray(out.td_errors))
        ref_ms.append(out.metrics)

    def close(a, b):
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=rtol, atol=atol
            ),
            a,
            b,
        )

    close(new_state.actor_params, ref.actor_params)
    close(new_state.critic_params, ref.critic_params)
    close(new_state.target_actor_params, ref.target_actor_params)
    close(new_state.target_critic_params, ref.target_critic_params)
    close(new_state.actor_opt.mu, ref.actor_opt.mu)
    close(new_state.critic_opt.nu, ref.critic_opt.nu)
    # The reference scan IS the count oracle: TD3's delayed actor updates
    # advance actor_opt.count less often than the critic's.
    assert int(new_state.actor_opt.count) == int(ref.actor_opt.count)
    assert int(new_state.critic_opt.count) == int(ref.critic_opt.count) == k
    assert int(new_state.step) == k
    if cfg.sac:
        # SAC: the in-kernel temperature must track the scan path's.
        close(new_state.log_alpha, ref.log_alpha)
        if cfg.sac_autotune:
            close(new_state.alpha_opt.mu, ref.alpha_opt.mu)
            close(new_state.alpha_opt.nu, ref.alpha_opt.nu)
            assert int(new_state.alpha_opt.count) == int(
                ref.alpha_opt.count
            ) == k
    np.testing.assert_allclose(
        np.asarray(td), np.stack(ref_tds), rtol=rtol, atol=atol
    )
    m_rtol = metric_rtol if metric_rtol is not None else rtol
    for name in metrics:
        want = float(np.mean([float(m[name]) for m in ref_ms]))
        np.testing.assert_allclose(
            float(metrics[name]), want, rtol=m_rtol, atol=atol
        )
    return metrics
