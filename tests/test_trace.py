"""Flight-recorder tests (trace.py): ring semantics, Chrome trace-event
export shape (what makes the file Perfetto-loadable), thread tagging, the
<2% overhead guard, and the end-to-end train_jax integration — a traced
CPU run must produce spans from >=3 distinct threads and JSONL records
carrying t_dispatch_p95 (the PR's acceptance criteria)."""

import json
import threading
import time

import numpy as np
import pytest

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.trace import TraceRecorder


@pytest.fixture(autouse=True)
def _clean_singleton():
    """Tests that enable the module singleton must not leak it into other
    tests' hot paths (span() goes from no-op to recording)."""
    yield
    trace.disable()


# --------------------------------------------------------------------------
# recorder semantics
# --------------------------------------------------------------------------

def test_span_and_instant_export_shape(tmp_path):
    rec = TraceRecorder(capacity=256)
    with rec.span("work", n=3):
        time.sleep(0.002)
    rec.instant("marker", step=7)
    path = tmp_path / "t.json"
    n = rec.export(str(path))
    assert n >= 3  # thread_name metadata + span + instant
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    metas = [e for e in events if e["ph"] == "M"]
    assert len(spans) == 1 and len(instants) == 1
    assert spans[0]["name"] == "work"
    assert spans[0]["dur"] >= 2000  # microseconds
    assert spans[0]["args"] == {"n": 3}
    assert instants[0]["args"] == {"step": 7}
    # Perfetto requirements: every event has pid/tid/ts; thread_name
    # metadata names the track.
    for e in spans + instants:
        assert {"pid", "tid", "ts"} <= set(e)
    assert metas and metas[0]["name"] == "thread_name"


def test_ring_overwrites_oldest():
    rec = TraceRecorder(capacity=16)
    for i in range(100):
        rec.instant(f"e{i}")
    events = [e for e in rec.events() if e["ph"] == "i"]
    assert len(events) <= 16
    names = {e["name"] for e in events}
    assert "e99" in names and "e0" not in names


def test_window_filter():
    rec = TraceRecorder(capacity=64)
    rec.instant("old")
    time.sleep(0.15)
    rec.instant("new")
    recent = [e for e in rec.events(window_s=0.1) if e["ph"] == "i"]
    assert [e["name"] for e in recent] == ["new"]


def test_complete_records_explicit_interval():
    rec = TraceRecorder(capacity=64)
    t0 = time.perf_counter()
    rec.complete("stall", t0, 0.25, rows=64)
    span = [e for e in rec.events() if e["ph"] == "X"][0]
    assert span["name"] == "stall"
    assert 240_000 <= span["dur"] <= 260_000  # ~250ms in us


def test_threads_get_distinct_tids():
    rec = TraceRecorder(capacity=256)

    def work(tag):
        with rec.span(tag):
            time.sleep(0.01)

    threads = [
        threading.Thread(target=work, args=(f"w{i}",), name=f"tracer-{i}")
        for i in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with rec.span("main"):
        pass
    events = rec.events()
    spans = [e for e in events if e["ph"] == "X"]
    assert len({e["tid"] for e in spans}) == 4
    names = {
        e["args"]["name"] for e in events if e.get("name") == "thread_name"
    }
    assert {"tracer-0", "tracer-1", "tracer-2"} <= names


def test_disabled_module_api_is_noop(tmp_path):
    trace.disable()
    with trace.span("x"):
        pass
    trace.instant("y")
    assert trace.export(str(tmp_path / "no.json")) == 0
    assert not (tmp_path / "no.json").exists()


def test_stall_report_artifacts(tmp_path):
    trace.configure(capacity=128)
    with trace.span("pre_stall_work"):
        pass
    paths = trace.stall_report(
        str(tmp_path), reason="test stall", timeout_s=1.0,
        extra={"beat": 42},
    )
    assert set(paths) == {"report", "trace"}
    report = json.loads((tmp_path / trace.STALL_REPORT).read_text())
    assert report["reason"] == "test stall"
    assert report["beat"] == 42
    me = [
        t for t in report["threads"]
        if t["name"] == threading.current_thread().name
    ]
    assert me and any("test_trace" in line for line in me[0]["stack"])
    tr = json.loads((tmp_path / trace.STALL_TRACE).read_text())
    assert any(
        e.get("name") == "pre_stall_work" for e in tr["traceEvents"]
    )


# --------------------------------------------------------------------------
# overhead guard (ISSUE satellite: recorder adds <2% to a CPU micro-loop)
# --------------------------------------------------------------------------

def test_trace_overhead_under_2_percent():
    """An ENABLED recorder's span bracket must cost <2% of a realistic
    hot-loop body (~0.5ms of numpy work — the scale of one small CPU
    chunk dispatch). The two costs are measured SEPARATELY, min-over-
    repeats: the per-span cost from a tight empty-span loop (~2us,
    stable), the body from a plain loop — a subtraction of two noisy
    ~20ms timings would make the guard flake on scheduler jitter (the
    body jitters ~10x the span cost per iteration on a busy 1-core CI
    box). Fails only on a real hot-path regression (e.g. someone adding
    allocation, locking, or current_thread() back to _record)."""
    trace.configure(capacity=65_536)
    a = np.random.default_rng(0).standard_normal((160, 160)).astype(np.float32)

    def span_cost_s() -> float:
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            with trace.span("micro"):
                pass
        return (time.perf_counter() - t0) / n

    def body_cost_s() -> float:
        n = 50
        t0 = time.perf_counter()
        for _ in range(n):
            x = a
            for _ in range(6):
                x = x @ a
        return (time.perf_counter() - t0) / n

    span_cost_s(), body_cost_s()  # warm BLAS pools + code paths
    span = min(span_cost_s() for _ in range(3))
    body = min(body_cost_s() for _ in range(5))
    overhead = span / body
    assert overhead < 0.02, (
        f"tracing overhead {overhead:.2%} "
        f"(span {span * 1e6:.2f}us vs body {body * 1e6:.1f}us)"
    )


# --------------------------------------------------------------------------
# end-to-end: traced train run (PR acceptance criteria)
# --------------------------------------------------------------------------

def test_train_jax_traced_run_multithread_timeline(tmp_path):
    """A short CPU train run with tracing on must produce a Perfetto-
    loadable trace containing spans from >=3 distinct threads (learner
    dispatch/ingest, ingest shipper, eval worker) and train JSONL records
    carrying t_dispatch_p95 — the PR's acceptance criteria, kept tier-1.

    Sizing: replay_min_size > block_size (1024) stages a full block during
    warmup, and ~2000 post-warmup env steps stage another — in async mode
    full blocks ship ONLY on the ingest-ship thread, so its traced span is
    deterministic, not a race."""
    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.train import train_jax

    log_path = tmp_path / "train.jsonl"
    cfg = DDPGConfig(
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=1,
        # Train-kind records only log on the 50-chunk cadence (400 learner
        # steps at chunk=8). A free-running actor burns the env budget
        # during the first dispatch's multi-second XLA compile, ending the
        # run after a handful of chunks — so pace ingest to the learner:
        # with ratio 6, the budget (4000 - 1500 warmup)/6 ≈ 417 learner
        # steps, deterministically past the 400-step log cadence.
        total_env_steps=4_000,
        replay_min_size=1_500,
        replay_capacity=16_384,
        max_ingest_ratio=6.0,
        eval_every=600,
        eval_episodes=1,
        trace_dir=str(tmp_path),
        log_path=str(log_path),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0

    doc = json.loads((tmp_path / "trace.json").read_text())
    events = doc["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    tid_names = {
        e["tid"]: e["args"]["name"]
        for e in events
        if e.get("name") == "thread_name"
    }
    span_threads = {tid_names.get(e["tid"], "?") for e in spans}
    assert len(span_threads) >= 3, (
        f"expected spans from >=3 threads, got {sorted(span_threads)}"
    )
    # Ingest dispatch runs on the unified transfer scheduler's thread by
    # default (docs/TRANSFER.md); transfer_scheduler=False falls back to
    # the PR-1 private shipper thread.
    assert "transfer-sched" in span_threads, sorted(span_threads)
    span_names = {e["name"] for e in spans}
    assert "dispatch" in span_names       # learner phase bracket
    assert "ingest_ship" in span_names    # scheduled ingest work item
    assert "transfer_ingest" in span_names  # the scheduler's class span
    assert "eval_rollout" in span_names   # eval worker thread

    train_recs = [
        json.loads(line)
        for line in log_path.read_text().splitlines()
        if '"train"' in line
    ]
    assert any("t_dispatch_p95" in r for r in train_recs), (
        "train JSONL records must carry reservoir tail latencies"
    )

    # The actor worker (separate process) exports its own per-process
    # trace on clean exit; Perfetto merges the files by pid.
    worker_trace = tmp_path / "trace_actor0.json"
    assert worker_trace.exists()
    wdoc = json.loads(worker_trace.read_text())
    assert any(
        e.get("name") == "actor_flush" for e in wdoc["traceEvents"]
    )
