"""Megakernel x mesh composition (parallel/learner.py fused-mesh path).

Three layers of evidence, mirroring how the path is built:

1. EXACT parity: the fused-mesh chunk must equal a host-built reference of
   the algorithm it claims to implement — per-device megakernel chunks on
   reproduced per-device draws, float state averaged at the boundary
   (K-step local SGD). Interpret mode = bit-level oracle, so tolerances
   are tight.
2. BOUNDED divergence: local SGD vs the scan path's per-step psum on the
   same buffer must land within a small fraction of the total parameter
   movement — the tolerance-bounded scan parity VERDICT r3 #4 asks for.
3. Activation envelope: data-only meshes compose; model-parallel meshes
   and fused_mesh='off' fall back to scan without error; fused_chunk='on'
   errors loudly when composition is impossible.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import init_train_state, make_learner_step
from distributed_ddpg_tpu.ops import fused_chunk
from distributed_ddpg_tpu.parallel import mesh as mesh_lib
from distributed_ddpg_tpu.parallel.learner import ShardedLearner
from distributed_ddpg_tpu.replay.device import DeviceReplay
from distributed_ddpg_tpu.types import pack_batch_np, unpack_batch

OBS, ACT = 5, 3


def _cfg(**kw):
    base = dict(
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        batch_size=8,
        fused_chunk="on",  # force the kernel (interpret mode) off-TPU
        seed=3,
    )
    base.update(kw)
    return DDPGConfig(**base)


def _filled_replay(mesh, n=512, capacity=1024, seed=0):
    rng = np.random.default_rng(seed)
    dr = DeviceReplay(capacity, OBS, ACT, mesh=mesh, block_size=128)
    dr.add_packed(
        pack_batch_np(
            {
                "obs": rng.standard_normal((n, OBS)).astype(np.float32),
                "action": rng.uniform(-1, 1, (n, ACT)).astype(np.float32),
                "reward": rng.standard_normal(n).astype(np.float32),
                "discount": np.full(n, 0.99, np.float32),
                "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
            }
        )
    )
    return dr


def test_fused_mesh_activates_and_runs_on_data_mesh():
    cfg = _cfg(learner_chunk=4)
    mesh = mesh_lib.make_mesh(data_axis=8, devices=jax.devices())
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mesh=mesh, chunk_size=4)
    assert lrn.fused_mesh_active and lrn.fused_chunk_active
    dr = _filled_replay(lrn.mesh)
    out = lrn.run_sample_chunk(dr)
    # td: [K, global_batch]; scale_batch_with_data default -> 8 * 8 = 64
    assert out.td_errors.shape == (4, 64)
    assert lrn.fused_chunk_error is None
    for v in out.metrics.values():
        assert np.isfinite(float(v))
    # Second chunk exercises the donated steady state.
    out2 = lrn.run_sample_chunk(dr)
    assert np.isfinite(float(out2.metrics["critic_loss"]))


@pytest.mark.slow
def test_fused_mesh_exact_parity_with_local_sgd_reference():
    """The fused-mesh chunk must BE chunk-boundary-averaged local SGD: per
    device d, draws come from fold_in(split(key)[1], d); each device runs
    the kernel-equivalent K scan steps from the shared start state; float
    state is averaged. Reproduce that on the host with make_learner_step
    (already pinned to the kernel by tests/test_fused_chunk.py) and demand
    tight agreement in interpret mode."""
    K, D = 3, 4
    cfg = _cfg()
    mesh = mesh_lib.make_mesh(data_axis=D, devices=jax.devices()[:D])
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mesh=mesh, chunk_size=K)
    assert lrn.fused_mesh_active
    b_local = lrn.global_batch // D
    assert b_local == cfg.batch_size

    dr = _filled_replay(lrn.mesh)
    storage = np.asarray(jax.device_get(dr.device_state()[0]))
    size = int(len(dr))

    out = lrn.run_sample_chunk(dr)

    # --- host reference ---------------------------------------------------
    key = jax.random.PRNGKey(cfg.seed)
    _, sub = jax.random.split(key)
    step = make_learner_step(cfg, 1.0, action_offset=0.0)
    state0 = init_train_state(cfg, OBS, ACT, seed=cfg.seed)
    end_states, tds = [], []
    for d in range(D):
        dkey = jax.random.fold_in(sub, d)
        idx = np.asarray(
            jax.random.randint(dkey, (K, b_local), 0, max(size, 1))
        )
        batches = unpack_batch(jnp.asarray(storage[idx]), OBS, ACT)
        s = state0
        dev_tds = []
        for k in range(K):
            o = jax.jit(step)(s, jax.tree.map(lambda x: x[k], batches))
            s = o.state
            dev_tds.append(np.asarray(o.td_errors))
        end_states.append(s)
        tds.append(np.stack(dev_tds))  # [K, b_local]

    def favg(getter):
        return jax.tree.map(
            lambda *xs: np.mean(np.stack([np.asarray(x) for x in xs]), 0),
            *[getter(s) for s in end_states],
        )

    got = jax.device_get(out.state)
    for getter, got_tree in [
        (lambda s: s.actor_params, got.actor_params),
        (lambda s: s.critic_params, got.critic_params),
        (lambda s: s.target_actor_params, got.target_actor_params),
        (lambda s: s.target_critic_params, got.target_critic_params),
        (lambda s: s.actor_opt.mu, got.actor_opt.mu),
        (lambda s: s.critic_opt.nu, got.critic_opt.nu),
    ]:
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=2e-4, atol=1e-6
            ),
            favg(getter),
            got_tree,
        )
    # td layout: device-d rows live at columns [d*b_local:(d+1)*b_local].
    ref_td = np.concatenate(tds, axis=1)
    np.testing.assert_allclose(
        ref_td, np.asarray(out.td_errors), rtol=2e-4, atol=1e-5
    )
    # Counts advanced by K, not averaged away.
    assert int(got.actor_opt.count) == K
    assert int(got.step) == K


def _l2_gap(a, b):
    leaves = lambda s: jax.tree.leaves(s.critic_params) + jax.tree.leaves(
        s.actor_params
    )
    return (
        sum(
            float(np.sum((np.asarray(x) - np.asarray(y)) ** 2))
            for x, y in zip(leaves(a), leaves(b))
        )
        ** 0.5
    )


@pytest.mark.slow
def test_fused_mesh_bounded_divergence_vs_scan_path():
    """Local SGD (fused mesh) vs per-step psum (scan path): the two also
    draw DIFFERENT sample streams, so raw parameter distance conflates
    algorithmic divergence with resampling noise. The honest null model is
    the scan path against itself under a different draw seed; the
    cross-algorithm gap must stay within a small factor of that null gap
    (measured here: 1.08 vs null 0.79 at K=8, D=4, 48 steps — local
    averaging adds ~40% on top of resampling noise, far below total
    movement 1.74)."""
    K, D, CHUNKS = 8, 4, 6
    mesh = mesh_lib.make_mesh(data_axis=D, devices=jax.devices()[:D])

    def run(fused, draw_seed=None):
        cfg = _cfg(fused_chunk=fused, actor_lr=1e-3, critic_lr=1e-3)
        lrn = ShardedLearner(
            cfg, OBS, ACT, action_scale=1.0, mesh=mesh, chunk_size=K
        )
        assert lrn.fused_mesh_active == (fused == "on")
        if draw_seed is not None:
            lrn._key = jax.device_put(
                jax.random.PRNGKey(draw_seed), lrn._key.sharding
            )
        dr = _filled_replay(lrn.mesh)
        for _ in range(CHUNKS):
            out = lrn.run_sample_chunk(dr)
            assert np.isfinite(float(out.metrics["critic_loss"]))
        return jax.device_get(lrn.state)

    scan_a = run("off")
    scan_b = run("off", draw_seed=777)
    mesh_a = run("on")
    null_gap = _l2_gap(scan_b, scan_a)
    cross_gap = _l2_gap(mesh_a, scan_a)
    moved = _l2_gap(scan_a, init_train_state(_cfg(), OBS, ACT, seed=3))
    assert null_gap > 0 and moved > 0
    assert cross_gap < 2.0 * null_gap, (cross_gap, null_gap)
    assert cross_gap < moved, (cross_gap, moved)


@pytest.mark.parametrize(
    "extra",
    [
        # One family rides the fast tier (TD3: delayed updates + noise
        # streams, the trickiest schedule); the others run in the slow tier.
        pytest.param(
            dict(distributional=True, num_atoms=21, v_min=-5.0, v_max=5.0),
            marks=pytest.mark.slow,
        ),
        dict(twin_critic=True, policy_delay=2, target_noise=0.2),
        pytest.param(dict(sac=True), marks=pytest.mark.slow),
    ],
    ids=["d4pg", "td3", "sac"],
)
def test_fused_mesh_runs_all_families(extra):
    """The mesh composition must cover every kernel-envelope family: D4PG
    (C51 head in-kernel), TD3 (twin groups + per-device axis-folded
    smoothing noise — each replica draws iid eps), and SAC (axis-folded
    sampling streams + the temperature pmean'd at the chunk boundary)."""
    cfg = _cfg(**extra)
    mesh = mesh_lib.make_mesh(data_axis=4, devices=jax.devices()[:4])
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mesh=mesh, chunk_size=3)
    assert lrn.fused_mesh_active
    dr = _filled_replay(lrn.mesh)
    out = lrn.run_sample_chunk(dr)
    assert lrn.fused_chunk_error is None
    assert out.td_errors.shape == (3, 8 * 4)
    for v in out.metrics.values():
        assert np.isfinite(float(v))
    out2 = lrn.run_sample_chunk(dr)
    assert np.isfinite(float(out2.metrics["critic_loss"]))
    if "twin_critic" in extra:
        # Delay 2 over 6 critic steps -> 3 actor updates, replicas agree.
        assert int(jax.device_get(lrn.state.actor_opt.count)) == 3
        assert int(jax.device_get(lrn.state.critic_opt.count)) == 6
    if "sac" in extra:
        # The learned temperature moved and stayed a replicated scalar.
        la = jax.device_get(lrn.state.log_alpha)
        assert np.isfinite(float(la))
        assert int(jax.device_get(lrn.state.alpha_opt.count)) == 6


def test_fused_mesh_respects_off_and_model_parallel():
    mesh = mesh_lib.make_mesh(data_axis=4, model_axis=2, devices=jax.devices())
    lrn = ShardedLearner(
        _cfg(fused_chunk="auto"), OBS, ACT, action_scale=1.0, mesh=mesh
    )
    assert not lrn.fused_mesh_active and not lrn.fused_chunk_active

    mesh_d = mesh_lib.make_mesh(data_axis=8, devices=jax.devices())
    lrn2 = ShardedLearner(
        _cfg(fused_chunk="auto", fused_mesh="off"),
        OBS, ACT, action_scale=1.0, mesh=mesh_d,
    )
    assert not lrn2.fused_mesh_active and not lrn2.fused_chunk_active
    # Scan path still trains.
    dr = _filled_replay(lrn2.mesh, n=256)
    out = lrn2.run_sample_chunk(dr)
    assert np.isfinite(float(out.metrics["critic_loss"]))

    with pytest.raises(ValueError, match="fused_chunk='on'"):
        ShardedLearner(
            _cfg(fused_chunk="on"), OBS, ACT, action_scale=1.0, mesh=mesh
        )
    with pytest.raises(ValueError, match="fused_chunk='on'"):
        ShardedLearner(
            _cfg(fused_chunk="on", fused_mesh="off"),
            OBS, ACT, action_scale=1.0, mesh=mesh_d,
        )
