"""MetricsLogger tests (SURVEY.md §5 'Metrics / logging'): JSONL records and
the TensorBoard parity sink."""

import json
import os

import pytest

from distributed_ddpg_tpu.metrics import MetricsLogger, Timer


def test_jsonl_records(tmp_path):
    path = tmp_path / "m.jsonl"
    log = MetricsLogger(str(path), echo=False)
    log.log("train", 10, critic_loss=0.5, note="hi")
    log.log("eval", 20, eval_return=-100.0)
    log.close()
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert [r["kind"] for r in recs] == ["train", "eval"]
    assert recs[0]["critic_loss"] == 0.5
    assert recs[0]["note"] == "hi"          # non-numeric passes through
    assert recs[1]["step"] == 20


@pytest.mark.slow
def test_tensorboard_sink(tmp_path):
    tb_dir = tmp_path / "tb"
    log = MetricsLogger(echo=False, tb_dir=str(tb_dir))
    assert log._tb is not None, "torch TB writer should be available here"
    log.log("train", 1, critic_loss=1.25, episode_return=None)
    log.close()
    events = [
        os.path.join(r, f)
        for r, _, fs in os.walk(tb_dir)
        for f in fs
        if "tfevents" in f
    ]
    assert events, "no TensorBoard event file written"
    assert os.path.getsize(events[0]) > 0


def test_timer_rates():
    t = Timer()
    t.tick(10)
    assert t.rate() > 0
    t.reset()
    assert t.rate() == 0.0
