"""MetricsLogger tests (SURVEY.md §5 'Metrics / logging'): JSONL records,
field-type preservation, PhaseTimers tail latencies, and the TensorBoard
parity sink."""

import json
import os
import time

import numpy as np
import pytest

from distributed_ddpg_tpu.metrics import MetricsLogger, PhaseTimers, Timer, _jsonable


def test_jsonl_records(tmp_path):
    path = tmp_path / "m.jsonl"
    log = MetricsLogger(str(path), echo=False)
    log.log("train", 10, critic_loss=0.5, note="hi")
    log.log("eval", 20, eval_return=-100.0)
    log.close()
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    # The run-start header record (docs/OBSERVABILITY.md §1) always leads.
    assert [r["kind"] for r in recs] == ["header", "train", "eval"]
    assert recs[0]["t_unix_base"] > 0 and recs[0]["pid"] == os.getpid()
    assert recs[1]["critic_loss"] == 0.5
    assert recs[1]["note"] == "hi"          # non-numeric passes through
    assert recs[2]["step"] == 20


@pytest.mark.slow
def test_tensorboard_sink(tmp_path):
    tb_dir = tmp_path / "tb"
    log = MetricsLogger(echo=False, tb_dir=str(tb_dir))
    assert log._tb is not None, "torch TB writer should be available here"
    log.log("train", 1, critic_loss=1.25, episode_return=None)
    log.close()
    events = [
        os.path.join(r, f)
        for r, _, fs in os.walk(tb_dir)
        for f in fs
        if "tfevents" in f
    ]
    assert events, "no TensorBoard event file written"
    assert os.path.getsize(events[0]) > 0


def test_jsonable_preserves_bool_and_int_types(tmp_path):
    """The old blanket float() coerced bools to 1.0/0.0 and ints to
    floats in every JSONL record — downstream parsers then can't tell
    `fused_chunk_active: true` from a measured scalar. Native AND numpy
    scalar types must round-trip; float rounding stays."""
    assert _jsonable(True) is True
    assert _jsonable(False) is False
    assert _jsonable(np.bool_(True)) is True
    assert _jsonable(7) == 7 and isinstance(_jsonable(7), int)
    assert _jsonable(np.int64(7)) == 7 and isinstance(_jsonable(np.int64(7)), int)
    assert _jsonable(1.23456789) == 1.234568
    assert _jsonable(np.float32(0.5)) == 0.5
    assert _jsonable("s") == "s" and _jsonable(None) is None

    path = tmp_path / "m.jsonl"
    log = MetricsLogger(str(path), echo=False)
    log.log("train", 1, active=True, count=3, loss=0.25)
    log.close()
    rec = json.loads(path.read_text().splitlines()[-1])
    assert rec["active"] is True
    assert rec["count"] == 3 and not isinstance(rec["count"], float)
    assert rec["loss"] == 0.25


def test_timer_rates():
    t = Timer()
    t.tick(10)
    assert t.rate() > 0
    t.reset()
    assert t.rate() == 0.0


def test_timer_survives_wall_clock_jumps(monkeypatch):
    """Timer measures on the monotonic clock: a wall-clock step (NTP,
    manual date set) mid-window must not distort the rate."""
    t = Timer()
    t.tick(100)
    # A wall-clock jump would change time.time() arbitrarily; the rate
    # must derive from time.monotonic() only.
    real_time = time.time
    monkeypatch.setattr(time, "time", lambda: real_time() + 3600.0)
    rate = t.rate()
    assert rate > 10  # 100 ticks over ms-scale elapsed, not over an hour


def test_phase_timers_percentiles_and_reset():
    p = PhaseTimers()
    for i in range(40):
        with p.phase("dispatch"):
            # One 25ms outlier against fast calls: sleep granularity on a
            # busy box is ~1ms, so the outlier is placed 10x above any
            # plausible jitter on the fast path.
            time.sleep(0.025 if i == 39 else 0.0002)
    snap = p.snapshot()
    assert snap["n_dispatch"] == 40
    for key in ("t_dispatch_ms", "t_dispatch_p50", "t_dispatch_p95",
                "t_dispatch_max"):
        assert key in snap, key
    # Ordering invariants of a (mean, p50, p95, max) family over a
    # distribution with one large outlier.
    assert snap["t_dispatch_p50"] <= snap["t_dispatch_p95"] <= snap["t_dispatch_max"]
    assert snap["t_dispatch_max"] >= 20.0  # the 25ms outlier, in ms
    assert snap["t_dispatch_p50"] < 15.0   # the typical fast call
    # Interval reset: the next snapshot starts fresh.
    assert p.snapshot() == {}


def test_phase_timers_emit_trace_spans():
    """Every phase bracket doubles as a flight-recorder span (the same
    bracket feeds the scalar record and the Perfetto timeline)."""
    from distributed_ddpg_tpu import trace

    trace.configure(capacity=64)
    try:
        p = PhaseTimers()
        with p.phase("ckpt"):
            pass
        spans = [e for e in trace.get().events() if e["ph"] == "X"]
        assert any(e["name"] == "ckpt" for e in spans)
    finally:
        trace.disable()
