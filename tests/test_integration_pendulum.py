"""Integration ladder rung 1 (SURVEY.md §4, BASELINE.json:7): Pendulum-v1,
1 worker, small nets — must solve within the step budget, deterministic
given the seed. Uses the built-in zero-dependency Pendulum env."""

import numpy as np
import pytest

from distributed_ddpg_tpu.agent import DDPGAgent
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs import make, spec_of


def _run(total_steps: int, seed: int = 0) -> float:
    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(64, 64),
        critic_hidden=(64, 64),
        replay_capacity=100_000,
        replay_min_size=1_000,
        batch_size=64,
        actor_lr=3e-4,
        critic_lr=1e-3,
        tau=5e-3,
        seed=seed,
    )
    env = make(cfg.env_id, seed=seed, prefer_builtin=True)
    agent = DDPGAgent(cfg, spec_of(env))
    obs, _ = env.reset(seed=seed)
    agent.reset_episode()
    for _ in range(total_steps):
        a = agent.act(obs)
        nobs, r, term, trunc, _ = env.step(a)
        agent.observe(obs, a, r, term, nobs)
        obs = nobs
        if term or trunc:
            obs, _ = env.reset()
            agent.reset_episode()
        agent.train_step()
    return agent.evaluate(
        make(cfg.env_id, seed=9_999, prefer_builtin=True), episodes=5
    )


@pytest.mark.slow
def test_pendulum_solves():
    ret = _run(30_000)
    assert ret > -250.0, f"Pendulum not solved: eval return {ret}"


@pytest.mark.slow
def test_pendulum_short_run_improves():
    """Cheap CI proxy: 10k steps must clearly beat a random policy
    (random evals around -1200..-1500; trained-10k runs land near -780)."""
    ret = _run(10_000)
    assert ret > -1050.0, f"no learning signal: eval return {ret}"
