"""Device-resident prioritized replay (replay/device.py
DevicePrioritizedReplay + parallel/learner.py run_sample_chunk_per):
distribution parity against the host sum-tree semantics, IS-weight formula
parity, the fused chunk end-to-end, and checkpoint roundtrip."""

import jax
import jax.numpy as jnp
import numpy as np

import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.parallel.learner import ShardedLearner
from distributed_ddpg_tpu.parallel.mesh import make_mesh
from distributed_ddpg_tpu.replay.device import (
    DevicePrioritizedReplay,
    draw_per_indices,
)
from distributed_ddpg_tpu.types import pack_batch_np


def _packed_rows(n, width, seed=0):
    rng = np.random.default_rng(seed)
    return (0.1 * rng.standard_normal((n, width))).astype(np.float32)


def test_draw_per_indices_proportional_and_weights():
    """Empirical frequency of the stratified inverse-CDF draw must match
    p_i / sum(p) (the defining property of proportional PER, same as the
    host SumTree.stratified_sample), and the IS weights must equal the
    host formula (N * P(i))^-beta / max."""
    cap = 64
    rng = np.random.default_rng(0)
    prios = np.zeros(cap, np.float32)
    n = 48
    prios[:n] = rng.uniform(0.1, 2.0, n).astype(np.float32)
    probs = prios / prios.sum()

    k, b, draws = 25, 64, 40
    counts = np.zeros(cap)
    beta = 0.7
    for d in range(draws):
        idx, w = jax.jit(draw_per_indices, static_argnums=3)(
            jax.random.PRNGKey(d), jnp.asarray(prios), jnp.int32(n),
            (k, b), jnp.float32(beta),
        )
        idx = np.asarray(idx)
        counts += np.bincount(idx.reshape(-1), minlength=cap)
        # IS weights: host formula on the same indices.
        w_host = (n * probs[idx]) ** (-beta)
        w_host = w_host / w_host.max(axis=-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(w), w_host, rtol=2e-4)

    freq = counts / counts.sum()
    # 64k total draws: proportional to priorities within a few percent.
    np.testing.assert_allclose(freq[:n], probs[:n], atol=0.004)
    assert counts[n:].sum() == 0, "sampled beyond the fill"


def test_device_per_insert_stamps_max_priority():
    mesh = make_mesh(-1, 1)
    rep = DevicePrioritizedReplay(512, 4, 2, mesh=mesh, block_size=64)
    rep.add_packed(_packed_rows(128, rep.width))
    assert len(rep) == 128
    prios = np.asarray(jax.device_get(rep.priorities))
    np.testing.assert_allclose(prios[:128], 1.0)  # initial max priority
    np.testing.assert_allclose(prios[128:], 0.0)  # empty slots zero-mass


@pytest.mark.slow
def test_run_sample_chunk_per_updates_priorities():
    cfg = DDPGConfig(
        actor_hidden=(16, 16), critic_hidden=(16, 16), batch_size=16,
        prioritized=True, fused_chunk="off", seed=0,
    )
    mesh = make_mesh(-1, 1)
    learner = ShardedLearner(cfg, 4, 2, action_scale=1.0, mesh=mesh,
                             chunk_size=4)
    rep = DevicePrioritizedReplay(1024, 4, 2, mesh=mesh, block_size=64,
                                  alpha=cfg.per_alpha, eps=cfg.per_eps)
    rep.add_packed(_packed_rows(256, rep.width))

    before = np.asarray(jax.device_get(rep.priorities)).copy()
    out = learner.run_sample_chunk_per(rep, beta=0.5)
    assert np.isfinite(float(out.metrics["critic_loss"]))
    assert int(jax.device_get(learner.state.step)) == 4

    after = np.asarray(jax.device_get(rep.priorities))
    changed = np.flatnonzero(before[:256] != after[:256])
    # 4 steps x 16 samples = 64 draws; duplicates allowed but most land.
    assert len(changed) >= 16, f"only {len(changed)} priorities updated"
    # Updated priorities follow (|td| + eps)^alpha — strictly positive and
    # not the insert stamp value.
    assert np.all(after[:256] > 0)
    # Second chunk keeps working with the updated vector (beta annealed).
    out2 = learner.run_sample_chunk_per(rep, beta=0.9)
    assert np.isfinite(float(out2.metrics["critic_loss"]))
    assert int(jax.device_get(learner.state.step)) == 8


def test_device_per_checkpoint_roundtrip(tmp_path):
    from distributed_ddpg_tpu import checkpoint as ckpt_lib
    from distributed_ddpg_tpu.learner import init_train_state

    cfg = DDPGConfig(actor_hidden=(16, 16), critic_hidden=(16, 16),
                     prioritized=True)
    state = init_train_state(cfg, 4, 2, seed=0)
    mesh = make_mesh(-1, 1)
    rep = DevicePrioritizedReplay(256, 4, 2, mesh=mesh, block_size=32)
    rep.add_packed(_packed_rows(96, rep.width))
    # Perturb priorities so the roundtrip carries non-trivial values.
    rep.set_per_state(
        rep.priorities.at[:96].set(jnp.linspace(0.2, 3.0, 96)),
        jnp.float32(3.0),
    )
    ckpt_lib.save(str(tmp_path), 11, state, rep, cfg)

    fresh = DevicePrioritizedReplay(256, 4, 2, mesh=mesh, block_size=32)
    template = init_train_state(cfg, 4, 2, seed=5)
    _, step, _ = ckpt_lib.restore(str(tmp_path), template, fresh)
    assert step == 11 and len(fresh) == 96
    np.testing.assert_allclose(
        np.asarray(jax.device_get(fresh.priorities))[:96],
        np.linspace(0.2, 3.0, 96), rtol=1e-6,
    )
    assert float(jax.device_get(fresh.max_priority)) == 3.0
    np.testing.assert_allclose(
        np.asarray(jax.device_get(fresh.storage))[:96],
        np.asarray(jax.device_get(rep.storage))[:96],
    )


@pytest.mark.slow
def test_fused_per_matches_scan_per():
    """PER x megakernel (round 4): with fused_chunk='on' the PER chunk runs
    the kernel (draw + priority scatter stay XLA ops, IS weights ride the
    packed weight column); same key stream -> identical draws -> the end
    state, TD errors, metrics, AND the updated priority vector must match
    the scan path at interpret-oracle tolerances. Covers DDPG, D4PG, and
    SAC (round-4 kernel envelope)."""
    for extra in (
        {},
        dict(distributional=True, num_atoms=21, v_min=-5.0, v_max=5.0),
        dict(sac=True),
    ):
        results = {}
        for mode in ("on", "off"):
            cfg = DDPGConfig(
                actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=16,
                prioritized=True, fused_chunk=mode, seed=7, **extra,
            )
            mesh = make_mesh(1, 1, devices=jax.devices()[:1])
            lrn = ShardedLearner(
                cfg, 4, 2, action_scale=1.0, mesh=mesh, chunk_size=4
            )
            assert lrn.fused_per_active == (mode == "on")
            rep = DevicePrioritizedReplay(
                512, 4, 2, mesh=mesh, block_size=64,
                alpha=cfg.per_alpha, eps=cfg.per_eps,
            )
            rep.add_packed(_packed_rows(256, rep.width))
            out = lrn.run_sample_chunk_per(rep, beta=0.5)
            assert lrn.fused_chunk_error is None
            results[mode] = (
                jax.device_get(lrn.state),
                np.asarray(out.td_errors),
                {k: float(v) for k, v in jax.device_get(out.metrics).items()},
                np.asarray(jax.device_get(rep.priorities)),
            )
        s_on, td_on, m_on, p_on = results["on"]
        s_off, td_off, m_off, p_off = results["off"]
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
            ),
            s_on.critic_params, s_off.critic_params,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
            ),
            s_on.actor_opt.mu, s_off.actor_opt.mu,
        )
        np.testing.assert_allclose(td_on, td_off, rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(p_on, p_off, rtol=2e-4, atol=1e-6)
        for k in m_on:
            np.testing.assert_allclose(m_on[k], m_off[k], rtol=5e-4, atol=1e-6)
