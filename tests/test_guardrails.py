"""Numerical-health guardrails (guardrails.py; docs/RESILIENCE.md
'Numerical health'): probe units (NaN/Inf/z-score triggers, injection
ordinals, bad-row capture), the zero-overhead disabled path and its
bit-identity to the pre-guardrail programs, rollback support machinery
(diverged-checkpoint quarantine, direct source quarantine), and the
tier-1 chaos acceptance run — `numeric:grad:nan@k` must roll the run back
to a manifest-valid step < k and still complete its budget with finite
params."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ddpg_tpu import checkpoint as ckpt_lib
from distributed_ddpg_tpu import guardrails
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.faults import FaultPlan
from distributed_ddpg_tpu.learner import (
    init_train_state,
    make_learner_step,
)
from distributed_ddpg_tpu.types import Batch

OBS, ACT, B = 3, 1, 16


def _cfg(**kw):
    return DDPGConfig(
        actor_hidden=(8, 8), critic_hidden=(8, 8), batch_size=B, **kw
    )


def _batch(rng, reward_scale=1.0, poison_obs=False, poison_reward=None):
    obs = rng.standard_normal((B, OBS)).astype(np.float32)
    if poison_obs:
        obs[0, 0] = np.nan
    reward = (reward_scale * rng.standard_normal(B)).astype(np.float32)
    if poison_reward is not None:
        reward[0] = poison_reward
    return Batch(
        obs=jnp.asarray(obs),
        action=jnp.asarray(rng.standard_normal((B, ACT)).astype(np.float32)),
        reward=jnp.asarray(reward),
        discount=jnp.full((B,), 0.99, jnp.float32),
        next_obs=jnp.asarray(
            rng.standard_normal((B, OBS)).astype(np.float32)
        ),
        weight=jnp.ones((B,), jnp.float32),
    )


def _leaves_equal(a, b):
    return all(
        np.array_equal(x, y)
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# ---------------------------------------------------------------------------
# faults grammar
# ---------------------------------------------------------------------------


def test_numeric_fault_grammar_parses_and_routes():
    plan = FaultPlan.parse(
        "numeric:grad:nan@500;numeric:loss:spike@7;numeric:replay:inf@42"
    )
    assert plan.numeric_steps() == {"grad": (500,), "loss": (7,)}
    assert plan.numeric_replay_rows() == (42,)
    # Config-level validation accepts the same specs.
    _cfg(faults="numeric:grad:nan@500", guardrails=True, data_axis=1)


@pytest.mark.parametrize(
    "spec",
    [
        "numeric:grad:inf@5",      # wrong kind for the target
        "numeric:loss:nan@5",
        "numeric:params:nan@5",    # unknown target
        "numeric:grad:crash@5",    # non-numeric kind
    ],
)
def test_numeric_fault_grammar_rejects_bad_pairs(spec):
    with pytest.raises(ValueError, match="numeric"):
        FaultPlan.parse(spec)


# ---------------------------------------------------------------------------
# probe units (unjitted guarded step)
# ---------------------------------------------------------------------------


def test_guarded_step_passes_healthy_and_skips_nan_batch():
    cfg = _cfg()
    step = make_learner_step(cfg, 1.0)
    guarded = guardrails.make_guarded_step(step, zmax=8.0, warmup=64)
    state = init_train_state(cfg, OBS, ACT, seed=0)
    g = guardrails.init_guard_state()
    rng = np.random.default_rng(0)

    healthy, g, td, m = guarded(
        state, g, _batch(rng), jnp.asarray(False)
    )
    assert int(g.total) == 1 and int(g.skipped) == 0
    assert not _leaves_equal(healthy.actor_params, state.actor_params)
    assert np.all(np.isfinite(np.asarray(td)))

    # A NaN-poisoned batch: update dropped (params/opt identical), step
    # counter still advances, TD zeroed, metrics zeroed.
    bad_state, g, td, m = guarded(
        healthy, g, _batch(rng, poison_obs=True), jnp.asarray(False)
    )
    assert int(g.total) == 2
    assert int(g.nonfinite) == 1 and int(g.skipped) == 1
    assert _leaves_equal(bad_state.actor_params, healthy.actor_params)
    assert _leaves_equal(bad_state.critic_opt, healthy.critic_opt)
    assert int(bad_state.step) == int(healthy.step) + 1
    assert np.all(np.asarray(td) == 0.0)
    assert float(m["critic_loss"]) == 0.0

    # An Inf reward (the poisoned-replay-row shape) trips the same path.
    _, g, _, _ = guarded(
        bad_state, g, _batch(rng, poison_reward=np.inf), jnp.asarray(False)
    )
    assert int(g.nonfinite) == 2


def test_guarded_step_zscore_spike_detector():
    cfg = _cfg()
    step = make_learner_step(cfg, 1.0)
    guarded = guardrails.make_guarded_step(step, zmax=6.0, warmup=8)
    state = init_train_state(cfg, OBS, ACT, seed=0)
    g = guardrails.init_guard_state()
    rng = np.random.default_rng(1)
    for _ in range(12):  # past warmup: EWMA armed
        state, g, _, _ = guarded(state, g, _batch(rng), jnp.asarray(False))
    assert int(g.warm) >= 8 and int(g.skipped) == 0

    spiked, g, _, _ = guarded(
        state, g, _batch(rng, reward_scale=1e6), jnp.asarray(False)
    )
    assert int(g.spikes) == 1 and int(g.skipped) == 1
    assert _leaves_equal(spiked.actor_params, state.actor_params)
    # The spike must NOT have polluted its own baseline: the next healthy
    # step passes.
    _, g, _, _ = guarded(spiked, g, _batch(rng), jnp.asarray(False))
    assert int(g.skipped) == 1


def test_guarded_step_pre_bad_flag_forces_skip():
    cfg = _cfg()
    step = make_learner_step(cfg, 1.0)
    guarded = guardrails.make_guarded_step(step, zmax=8.0, warmup=64)
    state = init_train_state(cfg, OBS, ACT, seed=0)
    g = guardrails.init_guard_state()
    rng = np.random.default_rng(2)
    new, g, _, _ = guarded(state, g, _batch(rng), jnp.asarray(True))
    assert int(g.skipped) == 1
    assert _leaves_equal(new.actor_params, state.actor_params)


def test_numeric_injection_fires_once_per_monotonic_ordinal():
    cfg = _cfg()
    step = make_learner_step(cfg, 1.0)
    guarded = guardrails.make_guarded_step(
        step, zmax=8.0, warmup=64, inject={"grad": (3,)}
    )
    state = init_train_state(cfg, OBS, ACT, seed=0)
    g = guardrails.init_guard_state()
    rng = np.random.default_rng(3)
    skipped_at = []
    for i in range(5):
        prev = int(g.skipped)
        state, g, _, _ = guarded(state, g, _batch(rng), jnp.asarray(False))
        if int(g.skipped) > prev:
            skipped_at.append(i + 1)
    assert skipped_at == [3]
    # Ordinals key on GuardState.total — re-running the same step numbers
    # with a PRESERVED clock (the rollback contract) must not re-fire.
    g2 = guardrails.init_guard_state(total=int(g.total))
    for _ in range(3):
        state, g2, _, _ = guarded(state, g2, _batch(rng), jnp.asarray(False))
    assert int(g2.skipped) == 0


def test_batch_row_health_screens_and_captures_indices():
    rng = np.random.default_rng(4)
    packed = rng.standard_normal((4, 8, 5)).astype(np.float32)
    packed[1, 2, 0] = np.inf
    packed[3, 0, 4] = np.nan
    idx = rng.integers(0, 1000, (4, 8)).astype(np.int32)
    pre_bad, count, bad_idx = guardrails.batch_row_health(
        jnp.asarray(packed), jnp.asarray(idx)
    )
    assert list(np.asarray(pre_bad)) == [False, True, False, True]
    assert int(count) == 2
    got = set(int(v) for v in np.asarray(bad_idx) if v >= 0)
    assert got == {int(idx[1, 2]), int(idx[3, 0])}
    # Host-fed path: indices unknown -> all -1, counts still real.
    _, count2, none_idx = guardrails.batch_row_health(
        jnp.asarray(packed), None
    )
    assert int(count2) == 2 and np.all(np.asarray(none_idx) == -1)


# ---------------------------------------------------------------------------
# learner integration: disabled path, parity, health plumbing
# ---------------------------------------------------------------------------


def _filled_learner(guard, rng_seed=0, faults="", per=False, **cfg_kw):
    from distributed_ddpg_tpu.parallel import mesh as mesh_lib
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )

    cfg = _cfg(
        guardrails=guard, faults=faults, prioritized=per, **cfg_kw,
    )
    # One-device mesh: the conftest's 8 virtual CPU devices would shard
    # the batch; single-device keeps the frozen-reference parity simple.
    mesh = mesh_lib.make_mesh(1, 1, devices=jax.devices()[:1])
    learner = ShardedLearner(cfg, OBS, ACT, 1.0, chunk_size=4, mesh=mesh)
    cls = DevicePrioritizedReplay if per else DeviceReplay
    rep = cls(
        1000, OBS, ACT, mesh=learner.mesh, block_size=64,
        track_sources=guard,
    )
    rng = np.random.default_rng(rng_seed)
    rep.add_packed(
        rng.standard_normal((256, rep.width)).astype(np.float32), source=1
    )
    rep.drain_pending()
    return learner, rep


def test_disabled_path_has_no_probe_surface():
    learner, rep = _filled_learner(guard=False)
    assert not learner.guard_enabled
    assert learner.poll_health() is None
    assert len(learner.bad_indices()) == 0
    assert not hasattr(learner, "_guard")
    out = learner.run_sample_chunk(rep)
    assert np.isfinite(float(out.metrics["critic_loss"]))
    assert learner.poll_health() is None  # still nothing to report


def test_guardrails_off_bit_identical_to_pre_guardrail_programs():
    """The acceptance parity pin: with guardrails disabled, the sample-
    chunk program must produce BIT-identical state to the pre-guardrail
    implementation (frozen here as a reference: draw_chunk + lax.scan
    over make_learner_step, the exact PR-6-era path)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_ddpg_tpu.learner import StepOutput
    from distributed_ddpg_tpu.types import unpack_batch

    learner, rep = _filled_learner(guard=False)
    cfg = learner.config
    step = make_learner_step(cfg, 1.0, action_offset=0.0)
    K, BB = 4, learner.global_batch

    def ref_fn(s, key, storage, size):
        key, sub = jax.random.split(key)
        idx = jax.random.randint(sub, (K, BB), 0, jnp.maximum(size, 1))
        packed = storage[idx]
        packed = jax.lax.with_sharding_constraint(
            packed, NamedSharding(learner.mesh, P(None, "data", None))
        )
        batches = unpack_batch(packed, OBS, ACT)

        def body(carry, b):
            out = step(carry, b)
            return out.state, (out.td_errors, out.metrics)

        s, (tds, ms) = jax.lax.scan(body, s, batches, unroll=4)
        return StepOutput(
            state=s, td_errors=tds, metrics=jax.tree.map(jnp.mean, ms)
        ), key

    ref = jax.jit(ref_fn)
    rs = jax.tree.map(jnp.asarray, jax.device_get(learner.state))
    rk = jax.random.PRNGKey(cfg.seed)
    storage, size = rep.device_state()
    for _ in range(4):
        learner.run_sample_chunk(rep)
        out, rk = ref(rs, rk, storage, size)
        rs = out.state
    assert _leaves_equal(
        jax.device_get(learner.state), jax.device_get(rs)
    ), "guardrails-off diverged from the pre-guardrail reference"


@pytest.mark.parametrize(
    "per",
    [False, pytest.param(True, marks=pytest.mark.slow)],  # PER build is
    # a second full compile; the uniform variant carries tier-1
)
def test_guardrails_on_healthy_matches_off(per):
    """Armed-but-clean guardrails must be behavior-neutral: same draws,
    same math, zero skips — states match to float tolerance (the extra
    probe consumers change XLA fusion, so bitwise is not guaranteed ON;
    bit-identity is the OFF path's contract, pinned above)."""
    outs = []
    for guard in (False, True):
        learner, rep = _filled_learner(guard=guard, per=per)
        for _ in range(4):
            if per:
                learner.run_sample_chunk_per(rep, 0.5)
            else:
                learner.run_sample_chunk(rep)
        outs.append(jax.device_get(learner.state))
        if guard:
            h = learner.poll_health()
            assert h["total"] == 16 and h["skipped"] == 0
            assert h["bad_rows"] == 0
    for x, y in zip(jax.tree.leaves(outs[0]), jax.tree.leaves(outs[1])):
        np.testing.assert_allclose(
            np.asarray(x, np.float64), np.asarray(y, np.float64),
            rtol=2e-4, atol=2e-5,
        )


def test_bad_rows_attribution_reset_clock_and_reseed():
    """One learner session covering the rollback-support plumbing: bad
    sampled rows are detected and attributed to their ingest source;
    reset_guard keeps the cumulative counters and monotonic clock while
    clearing the reportable health word; reseed changes the sampling
    key. (set_lr_scale's recompile is exercised end-to-end by the chaos
    rollback test below — no separate compile paid here.)"""
    learner, rep = _filled_learner(guard=True)
    rng = np.random.default_rng(9)
    bad = rng.standard_normal((64, rep.width)).astype(np.float32)
    bad[:, OBS + ACT] = np.inf  # reward column
    rep.add_packed(bad, source=3)
    rep.drain_pending()
    for _ in range(4):
        learner.run_sample_chunk(rep)
    h = learner.poll_health()
    assert h["bad_rows"] > 0 and h["skipped"] > 0
    idx = learner.bad_indices()
    assert len(idx) > 0
    srcs = set(int(s) for s in rep.sources_of(idx))
    assert srcs == {3}, f"bad rows misattributed: {srcs}"

    learner.reset_guard()
    assert learner.poll_health() is None
    learner.run_sample_chunk(rep)
    after = learner.poll_health()
    # Cumulative counters and the monotonic clock survived the reset
    # (the EWMA fields reset; chunk 5 of 4 steps -> total 20).
    assert after["skipped"] >= h["skipped"] and after["total"] == 20

    k0 = np.asarray(jax.device_get(learner._key)).copy()
    learner.reseed(7)
    assert not np.array_equal(
        k0, np.asarray(jax.device_get(learner._key))
    )


# ---------------------------------------------------------------------------
# rollback support machinery
# ---------------------------------------------------------------------------


def test_discard_above_quarantines_diverged_checkpoints(tmp_path):
    cfg = _cfg()
    state = init_train_state(cfg, 4, 2, seed=0)
    for step in (10, 20, 30):
        ckpt_lib.save(str(tmp_path), step, state, None, cfg, keep=0)
    discarded = ckpt_lib.discard_above(str(tmp_path), 10)
    assert discarded == [20, 30]
    assert ckpt_lib.latest_step(str(tmp_path)) == 10
    assert ckpt_lib.valid_steps(str(tmp_path)) == [10]
    for s in (20, 30):
        assert (tmp_path / f"diverged_step_{s}").is_dir()
        assert not (tmp_path / f"manifest_{s}.json").exists()
    assert ckpt_lib.discard_above(str(tmp_path), 10) == []


def test_pool_quarantine_source_direct():
    from distributed_ddpg_tpu.actors.pool import ActorPool
    from distributed_ddpg_tpu.envs.registry import EnvSpec

    spec = EnvSpec(
        obs_dim=OBS, act_dim=ACT,
        action_low=np.full(ACT, -1.0, np.float32),
        action_high=np.full(ACT, 1.0, np.float32),
    )
    pool = ActorPool(_cfg(num_actors=2), spec)
    assert pool.quarantine_source(0, why="numeric")
    assert pool.quarantined_count == 1
    assert not pool.quarantine_source(0), "double-quarantine must no-op"
    assert not pool.quarantine_source(99), "bad slot id must no-op"
    assert pool.recovery_counters()["actor_quarantined"] == 1


def test_config_validation():
    with pytest.raises(ValueError, match="scan path"):
        _cfg(guardrails=True, fused_chunk="on")
    with pytest.raises(ValueError, match="jax_tpu"):
        _cfg(guardrails=True, backend="native")
    with pytest.raises(ValueError, match="guardrail_lr_backoff"):
        _cfg(guardrail_lr_backoff=0.0)
    with pytest.raises(ValueError, match="guardrail_zmax"):
        _cfg(guardrail_zmax=-1.0)


# ---------------------------------------------------------------------------
# tools + gate rendering
# ---------------------------------------------------------------------------


def test_tools_runs_guardrail_digest_and_gate_pin(tmp_path):
    from distributed_ddpg_tpu.tools.runs import (
        gate_bench,
        render_summary,
        summarize_run,
    )

    path = tmp_path / "run.jsonl"
    recs = [
        {"kind": "train", "step": 100, "wall_time": 1.0,
         "guardrail_rollbacks": 0, "guardrail_skipped_updates": 0},
        {"kind": "final", "step": 200, "wall_time": 2.0,
         "guardrail_rollbacks": 1, "guardrail_skipped_updates": 3,
         "guardrail_last_rollback_step": 120},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    digest = summarize_run(str(path))
    assert digest["guardrail"]["guardrail_rollbacks"]["last"] == 1
    assert "numerical health" in render_summary(digest)

    # ci_gate's -guardrail_rollbacks pin: a zero baseline on a
    # lower-is-better counter FAILS any nonzero candidate (plain relative
    # thresholds cannot express "regressed from never-happened").
    ok, lines = gate_bench(
        {"guardrail_rollbacks": 0}, {"guardrail_rollbacks": 2},
        threshold=0.1, keys=("-guardrail_rollbacks",),
    )
    assert not ok and any("zero-baseline pin" in ln for ln in lines)
    ok, _ = gate_bench(
        {"guardrail_rollbacks": 0}, {"guardrail_rollbacks": 0},
        threshold=0.1, keys=("-guardrail_rollbacks",),
    )
    assert ok
    # The pin is for integer COUNTERS only: a float-0.0 latency baseline
    # means "no samples recorded" and must keep SKIPping, not fail the
    # first candidate that records any latency at all.
    ok, lines = gate_bench(
        {"transfer_d2h_p95": 0.0}, {"transfer_d2h_p95": 0.29},
        threshold=0.1, keys=("-transfer_d2h_p95",),
    )
    assert ok and any("SKIP" in ln for ln in lines)


# ---------------------------------------------------------------------------
# tier-1 chaos acceptance: injected NaN -> rollback -> budget completes
# ---------------------------------------------------------------------------


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            if line.strip().startswith("{"):
                out.append(json.loads(line))
    return out


# Re-tiered to slow (ISSUE 15 tier-1 budget): 81s rollback chaos train run; the healthy-parity + unit battery keep
# guardrails tier-1 coverage
@pytest.mark.slow
def test_numeric_nan_chaos_rolls_back_and_completes(tmp_path):
    """The acceptance run (ISSUE 7): a CPU training run with an injected
    `numeric:grad:nan@k` must complete its env budget, report >= 1
    guardrail rollback whose restore step is manifest-valid and < k, and
    end with finite params."""
    from distributed_ddpg_tpu.train import train_jax

    K = 400
    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16), critic_hidden=(16, 16),
        num_actors=1,
        total_env_steps=2_000,
        replay_min_size=256,
        replay_capacity=20_000,
        eval_every=0,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=100,
        log_path=str(tmp_path / "g.jsonl"),
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        actor_throttle_s=0.002,
        guardrails=True,
        guardrail_rollback_k=1,   # one NaN step is enough to repair
        guardrail_lr_cooldown_steps=500,
        faults=f"numeric:grad:nan@{K}",
    )
    out = train_jax(cfg)

    assert out["learner_steps"] > K, f"budget did not complete: {out}"
    assert not out["numeric_failed"]
    assert out["guardrail_rollbacks"] >= 1
    assert out["guardrail_nonfinite_steps"] >= 1
    restored = out["guardrail_last_rollback_step"]
    assert 0 < restored < K, (
        f"rollback must restore a pre-divergence step < {K}: {restored}"
    )
    # End params are finite (the poisoned update never landed).
    assert np.isfinite(out["param_checksum"])
    # The final JSONL record carries the guardrail digest.
    final = [r for r in _records(cfg.log_path) if r["kind"] == "final"][-1]
    assert final["guardrail_rollbacks"] == out["guardrail_rollbacks"]
    assert final["guardrail_last_rollback_step"] == restored
    # The latest retained checkpoint is from the REPAIRED timeline and
    # verifies clean.
    step = ckpt_lib.latest_step(cfg.checkpoint_dir)
    assert step is not None
    ok, why = ckpt_lib.verify_checkpoint(cfg.checkpoint_dir, step)
    assert ok, why


@pytest.mark.slow
def test_numeric_abort_exhausted_budget_flags_exit_contract(tmp_path):
    """Rollback budget 0: the first sustained-divergence trigger must
    take the documented numeric abort — run ends early, numeric_failed
    rides the summary (main() exits 77), no final eval of poisoned
    params."""
    from distributed_ddpg_tpu.train import train_jax

    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16), critic_hidden=(16, 16),
        num_actors=1,
        total_env_steps=100_000,   # far beyond: the abort must end it
        replay_min_size=256,
        replay_capacity=20_000,
        eval_every=0,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=100,
        log_path=str(tmp_path / "a.jsonl"),
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        actor_throttle_s=0.002,
        guardrails=True,
        guardrail_rollback_k=1,
        guardrail_max_rollbacks=0,
        faults="numeric:grad:nan@50",
    )
    out = train_jax(cfg)
    assert out["numeric_failed"]
    assert out["guardrail_rollbacks"] == 0
    assert out["final_return"] is None
    assert out["learner_steps"] < 5_000


# ---------------------------------------------------------------------------
# slow: poisoned replay row -> source quarantine, end to end
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_numeric_replay_poison_quarantines_source(tmp_path):
    """`numeric:replay:inf@k` poisons a real ingested row; sampling it
    must skip the step, record the row, attribute it to the worker that
    produced it, and quarantine that slot through the pool breaker."""
    from distributed_ddpg_tpu.train import train_jax

    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16), critic_hidden=(16, 16),
        num_actors=2,
        total_env_steps=2_500,
        replay_min_size=256,
        replay_capacity=20_000,
        eval_every=0,
        log_path=str(tmp_path / "q.jsonl"),
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        actor_throttle_s=0.002,
        guardrails=True,
        guardrail_rollback_k=0,        # isolate the quarantine path
        guardrail_source_offenses=1,
        faults="numeric:replay:inf@300",
    )
    out = train_jax(cfg)
    assert out["guardrail_bad_rows"] >= 1
    assert out["guardrail_source_quarantines"] >= 1
    assert out["learner_steps"] > 0
