"""Network serving front tests (serve/front/; docs/SERVING.md 'Network
front').

Pins the PR-20 acceptance contract: the wire framing + typed error codes
(no request-level failure ever kills the acceptor), per-tenant QoS with
STRICTLY lowest-priority-first overload shedding, versioned snapshots
with canary promote / gated rollback / re-promote (the tier-1 drill,
driven by the injected `front:canary:regress` chaos), the SAC serve
head's per-client sampling parity, and the front_*/tenant_* digest +
ci_gate key plumbing."""

import hashlib
import http.client
import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from distributed_ddpg_tpu.actors.policy import (
    NumpyPolicy,
    actor_head_dim,
    layout_size,
    param_layout,
)
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.faults import FaultPlan, InjectedFault
from distributed_ddpg_tpu.serve import InferenceServer
from distributed_ddpg_tpu.serve.batcher import Batcher
from distributed_ddpg_tpu.serve.front import (
    CanaryGate,
    FrontClient,
    FrontError,
    FrontServer,
    QosGate,
    SnapshotStore,
    TokenBucket,
    parse_tenants,
    wire,
)

OBS, ACT = 5, 2
LAYOUT = param_layout(OBS, ACT, (16, 16))


def _flat(seed=0, layout=LAYOUT):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(layout_size(layout)) * 0.3).astype(
        np.float32
    )


def _obs(seed=1):
    return np.random.default_rng(seed).standard_normal(OBS).astype(
        np.float32
    )


def _make_engine(**kw):
    def make():
        return InferenceServer(
            LAYOUT, np.ones(ACT, np.float32),
            max_batch=kw.get("max_batch", 8),
            max_latency_s=kw.get("max_latency_s", 0.002),
            max_queue=kw.get("max_queue", 64),
        )
    return make


def _start_front(**kw):
    """A started FrontServer with 'v1' published stable (ephemeral ports;
    http unless disabled)."""
    front = FrontServer(_make_engine(), **kw)
    front.publish("v1", _flat(1))
    return front.start()


# ---------------------------------------------------------------------------
# wire: framing + request validation + typed error contract
# ---------------------------------------------------------------------------


def test_wire_frame_roundtrip_and_framing_errors():
    a, b = socket.socketpair()
    try:
        wire.send_frame(a, {"tenant": "t", "request_id": 1, "obs": [0.5]})
        obj = wire.read_frame(b)
        assert obj == {"tenant": "t", "request_id": 1, "obs": [0.5]}

        # Oversized length prefix = lost framing.
        a.sendall(struct.pack(">I", wire.MAX_FRAME + 1))
        with pytest.raises(wire.WireError) as e:
            wire.read_frame(b)
        assert e.value.code == "bad_frame"

        # Well-framed garbage body is recoverable (typed, not torn).
        a2, b2 = socket.socketpair()
        try:
            body = b"not json"
            a2.sendall(struct.pack(">I", len(body)) + body)
            with pytest.raises(wire.WireError):
                wire.read_frame(b2)
            # A non-dict JSON body is bad_frame too.
            body = b"[1,2]"
            a2.sendall(struct.pack(">I", len(body)) + body)
            with pytest.raises(wire.WireError):
                wire.read_frame(b2)
        finally:
            a2.close()
            b2.close()

        # Clean EOF before any byte -> None; EOF mid-frame -> torn.
        a3, b3 = socket.socketpair()
        a3.close()
        assert wire.read_frame(b3) is None
        b3.close()
        a4, b4 = socket.socketpair()
        a4.sendall(struct.pack(">I", 100) + b"{")
        a4.close()
        with pytest.raises(wire.WireError):
            wire.read_frame(b4)
        b4.close()
    finally:
        a.close()
        b.close()


def test_wire_validate_request_and_error_codes():
    good = wire.validate_request(
        {"tenant": "t", "request_id": 3, "obs": [1, 2.5]}
    )
    assert good == {"tenant": "t", "request_id": 3, "obs": [1, 2.5],
                    "version": None}
    for bad in (
        {},                                             # no tenant
        {"tenant": 7, "request_id": 1, "obs": [1.0]},   # non-str tenant
        {"tenant": "t", "obs": [1.0]},                  # no request_id
        {"tenant": "t", "request_id": True, "obs": [1.0]},  # bool rid
        {"tenant": "t", "request_id": 1},               # no obs
        {"tenant": "t", "request_id": 1, "obs": []},    # empty obs
        {"tenant": "t", "request_id": 1, "obs": [1.0, "x"]},  # non-number
        {"tenant": "t", "request_id": 1, "obs": [1.0], "version": 4},
    ):
        with pytest.raises(wire.WireError) as e:
            wire.validate_request(bad)
        assert e.value.code == "bad_frame"

    assert set(wire.error_response(1, "shed", "m")) == {
        "request_id", "error", "message",
    }
    with pytest.raises(ValueError):
        wire.error_response(1, "not_a_code", "m")
    with pytest.raises(ValueError):
        wire.WireError("not_a_code", "m")
    with pytest.raises(wire.WireError):
        wire.encode_frame({"obs": [0.0] * (wire.MAX_FRAME // 4)})


# ---------------------------------------------------------------------------
# qos: tenant table grammar, token bucket, priority-staggered thresholds
# ---------------------------------------------------------------------------


def test_parse_tenants_grammar():
    table = parse_tenants("gold:0;silver:1:10;bronze:3:5:20")
    assert table["gold"].priority == 0 and table["gold"].rate == 0.0
    assert table["silver"] == ("silver", 1, 10.0, 10.0)  # burst = rate
    assert table["bronze"].burst == 20.0
    assert parse_tenants("") == {}
    assert parse_tenants(" ; ") == {}
    for bad in (
        "gold",            # no priority
        "gold:0:1:2:3",    # too many fields
        ":0",              # empty name
        "gold:x",          # non-numeric priority
        "gold:-1",         # negative priority
        "gold:0:-2",       # negative rate
        "gold:0:5:0.5",    # burst < 1
        "gold:0;gold:1",   # duplicate
    ):
        with pytest.raises(ValueError):
            parse_tenants(bad)


def test_token_bucket_fake_clock():
    b = TokenBucket(rate=2.0, burst=2.0)
    assert b.allow(0.0) and b.allow(0.0)   # burst drains
    assert not b.allow(0.0)                # empty
    assert not b.allow(0.25)               # 0.5 tokens refilled: still < 1
    assert b.allow(0.5)                    # 1 token back
    assert b.allow(10.0)                   # refill caps at burst
    assert b.allow(10.0)
    assert not b.allow(10.0)


def test_qos_thresholds_strictly_priority_ordered():
    gate = QosGate(parse_tenants("a:0;b:1;c:2;d:3"), default_priority=2,
                   shed_start=0.5)
    # Priority 0 never depth-sheds; lower classes shed strictly earlier.
    assert gate.threshold(0) == 1.0
    ts = [gate.threshold(p) for p in (1, 2, 3)]
    assert ts[0] > ts[1] > ts[2] == 0.5  # lowest class sheds at shed_start
    assert gate.priority("a") == 0
    assert gate.priority("unknown") == 2  # default class


def test_qos_admit_rate_and_priority_causes():
    clock = [0.0]
    gate = QosGate(
        parse_tenants("gold:0;capped:1:1:1;bronze:2"),
        shed_start=0.5, clock=lambda: clock[0],
    )
    # Token bucket fires regardless of load.
    assert gate.admit("capped", 0, 100) is None
    assert gate.admit("capped", 0, 100) == "rate"
    clock[0] = 1.0
    assert gate.admit("capped", 0, 100) is None
    # Depth shedding: bronze (lowest) sheds at 50%, gold never.
    assert gate.admit("bronze", 49, 100) is None
    assert gate.admit("bronze", 50, 100) == "priority"
    assert gate.admit("gold", 99, 100) is None


# ---------------------------------------------------------------------------
# snapshots: store lifecycle + deterministic canary routing
# ---------------------------------------------------------------------------


def test_snapshot_store_lifecycle_and_routing():
    store = SnapshotStore()
    with pytest.raises(RuntimeError):
        store.route("t", 1)  # nothing published yet
    store.publish("v1", _flat(1))
    assert store.stable == "v1"  # first publish becomes stable
    with pytest.raises(ValueError):
        store.publish("v1", _flat(2))  # versions are immutable
    frozen = store.get("v1")
    with pytest.raises(ValueError):
        frozen[0] = 9.0  # read-only copy

    store.publish("v2", _flat(2))
    assert store.route("t", 1) == ("v1", False)  # no canary yet
    with pytest.raises(ValueError):
        store.start_canary("v1", 0.5)  # already stable
    with pytest.raises(KeyError):
        store.start_canary("v9", 0.5)
    with pytest.raises(ValueError):
        store.start_canary("v2", 1.0)  # fraction must be in (0,1)
    store.start_canary("v2", 0.5)
    with pytest.raises(ValueError):
        store.start_canary("v2", 0.5)  # one canary at a time

    # Deterministic split: same request always routes the same way, and
    # both arms actually receive traffic at fraction=0.5.
    routes = [store.route("tenant", rid) for rid in range(200)]
    assert routes == [store.route("tenant", rid) for rid in range(200)]
    arms = {is_canary for _, is_canary in routes}
    assert arms == {True, False}

    assert store.promote() == "v2"
    assert store.stable == "v2" and store.candidate is None
    assert store.route("tenant", 1) == ("v2", False)
    assert store.rollback() is None  # idempotent with no canary
    store.publish("v3", _flat(3))
    store.start_canary("v3", 0.3)
    assert store.rollback() == "v3"
    assert store.stable == "v2"
    with pytest.raises(ValueError):
        store.promote()  # no candidate left


def test_canary_gate_verdicts():
    # Not enough data -> None; clean candidate -> promote.
    gate = CanaryGate(min_requests=5, threshold=0.5)
    for i in range(4):
        gate.record(False, 0.010)
        gate.record(True, 0.010)
    assert gate.verdict() is None
    gate.record(False, 0.010)
    gate.record(True, 0.010)
    assert gate.verdict() == "promote"

    # Latency regression past threshold -> rollback.
    gate.reset()
    for i in range(6):
        gate.record(False, 0.010)
        gate.record(True, 0.030)  # 3x stable p95
    assert gate.verdict() == "rollback"
    s = gate.stats()
    assert s["candidate_p95_ms"] > s["stable_p95_ms"]

    # Error-rate gate trips WITHOUT waiting for the latency quota.
    gate.reset()
    for i in range(5):
        gate.record(False, 0.010)
        gate.record(True, 0.010, error=True)
    assert gate.verdict() == "rollback"

    # reset() forgets the previous round.
    gate.reset()
    assert gate.verdict() is None


# ---------------------------------------------------------------------------
# front server end to end: TCP, HTTP, typed errors, acceptor survival
# ---------------------------------------------------------------------------


def test_front_tcp_end_to_end_and_typed_errors():
    front = _start_front()
    try:
        with FrontClient(front.port, tenant="t0") as cli:
            action, version = cli.act(_obs())
            assert action.shape == (ACT,) and version == "v1"
            # Served action matches the engine's policy math.
            pol = NumpyPolicy(LAYOUT, np.ones(ACT, np.float32))
            pol.load_flat(_flat(1))
            assert np.array_equal(action, pol(_obs()).reshape(-1))

            # Explicit version pin; unknown version is a typed bad_frame.
            _, v = cli.act(_obs(), version="v1")
            assert v == "v1"
            with pytest.raises(FrontError) as e:
                cli.act(_obs(), version="nope")
            assert e.value.code == "bad_frame"

            # Malformed request objects answer typed ON THE SAME
            # connection — which keeps serving afterwards.
            resp = cli.request({"tenant": "", "request_id": 1,
                                "obs": [1.0]})
            assert resp["error"] == "bad_frame"
            resp = cli.request({"tenant": "t0", "request_id": "x",
                                "obs": [1.0]})
            assert resp["error"] == "bad_frame"
            action, _ = cli.act(_obs())
            assert action.shape == (ACT,)
        snap = front.snapshot()
        assert snap["front_requests"] >= 4
        assert snap["front_bad_frames"] >= 2
        assert snap["front_wire_p95_ms"] > 0.0
        assert snap["tenant_served"] >= 3
    finally:
        front.stop()


def test_front_bad_length_prefix_tears_only_that_connection():
    front = _start_front()
    try:
        good = FrontClient(front.port, tenant="survivor")
        bad = socket.create_connection(("127.0.0.1", front.port),
                                       timeout=5.0)
        # Garbage length prefix: one typed bad_frame answer, then THAT
        # connection closes.
        bad.sendall(struct.pack(">I", wire.MAX_FRAME + 7))
        resp = wire.read_frame(bad)
        assert resp["error"] == "bad_frame"
        assert bad.recv(1) == b""  # server closed it
        bad.close()
        # Everyone else keeps serving.
        action, _ = good.act(_obs())
        assert action.shape == (ACT,)
        good.close()
        assert front.snapshot()["front_bad_frames"] >= 1
    finally:
        front.stop()


def test_front_http_adapter():
    front = _start_front()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", front.http_port,
                                          timeout=5.0)
        body = json.dumps({"tenant": "h", "request_id": 1,
                           "obs": _obs().tolist()})
        conn.request("POST", "/act", body,
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        obj = json.loads(r.read())
        assert r.status == 200
        assert obj["version"] == "v1" and len(obj["action"]) == ACT

        # Unparseable body -> 400 typed bad_frame.
        conn.request("POST", "/act", "not json")
        r = conn.getresponse()
        assert r.status == 400
        assert json.loads(r.read())["error"] == "bad_frame"

        # Wrong path -> 404.
        conn.request("POST", "/elsewhere", body)
        r = conn.getresponse()
        assert r.status == 404
        r.read()

        # Typed request-level error maps to its advisory status.
        conn.request("POST", "/act", json.dumps({"request_id": 1,
                                                 "obs": [1.0]}))
        r = conn.getresponse()
        assert r.status == 400
        assert json.loads(r.read())["error"] == "bad_frame"
        conn.close()
        snap = front.snapshot()
        assert snap["front_http_requests"] >= 1
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# chaos: fault grammar + injected drills (acceptor never dies)
# ---------------------------------------------------------------------------


def test_front_fault_grammar():
    plan = FaultPlan.parse(
        "front:accept:stall@1~0.01;front:frame:corrupt@2;"
        "front:canary:regress@3~0.05"
    )
    assert plan.front_canary_regressions() == ((3, 0.05),)
    assert plan.site("front", "accept")._by_at  # accept specs routed
    for bad in (
        "front:accept:corrupt@1",   # corrupt is frame-only
        "front:canary:stall@1",     # regress is the only canary kind
        "front:frame:stall@1",
        "front:unknown:stall@1",
    ):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_front_frame_corrupt_fault_connection_survives():
    plan = FaultPlan.parse("front:frame:corrupt@2")
    front = FrontServer(_make_engine(),
                        fault_frame=plan.site("front", "frame"))
    front.publish("v1", _flat(1))
    front.start()
    try:
        with FrontClient(front.port, tenant="t") as cli:
            cli.act(_obs())                      # frame 1: clean
            with pytest.raises(FrontError) as e:
                cli.act(_obs())                  # frame 2: injected corrupt
            assert e.value.code == "bad_frame"
            action, _ = cli.act(_obs())          # frame 3: SAME connection
            assert action.shape == (ACT,)
        assert front.snapshot()["front_bad_frames"] >= 1
    finally:
        front.stop()


def test_front_accept_stall_fault_acceptor_survives():
    plan = FaultPlan.parse("front:accept:stall@1~0.05")
    site = plan.site("front", "accept")
    front = FrontServer(_make_engine(), fault_accept=site)
    front.publish("v1", _flat(1))
    front.start()
    try:
        t0 = time.monotonic()
        with FrontClient(front.port, tenant="t") as cli:
            cli.act(_obs())  # first connection eats the stall
        assert time.monotonic() - t0 >= 0.05
        assert site.fired
        with FrontClient(front.port, tenant="t") as cli:
            cli.act(_obs())  # later connections unaffected
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# tier-1 drill: overload sheds strictly lowest-priority-first
# ---------------------------------------------------------------------------


class _BlockedEngine:
    """A front engine whose dispatcher is parked inside apply until
    released — the queue DEPTH is under test control, so shed thresholds
    are exercised deterministically instead of by racing load."""

    sac = False

    def __init__(self, max_queue=20):
        self.release = threading.Event()
        self.entered = threading.Event()
        self.batcher = Batcher(self._apply, max_batch=1,
                               max_latency_s=0.001, max_queue=max_queue)

    def _apply(self, batch):
        self.entered.set()
        self.release.wait(timeout=30.0)
        return batch[:, :ACT].copy()

    def refresh(self, flat):
        pass

    def start(self):
        self.batcher.start()
        return self

    def close(self, timeout=5.0):
        self.release.set()
        self.batcher.close(timeout=timeout)


def test_shed_ordering_strictly_lowest_priority_first():
    """The QoS acceptance drill: under a deep queue, bronze (priority 2)
    sheds before silver (1), silver before gold (0), and gold NEVER
    depth-sheds — with the per-tenant counters proving the order."""
    engines = []

    def make():
        eng = _BlockedEngine(max_queue=20)
        engines.append(eng)
        return eng

    front = FrontServer(
        make, tenants="gold:0;silver:1;bronze:2",
        shed_start=0.5, timeout_s=0.05, http_port=None,
    )
    front.publish("v1", _flat(1))
    front.start()
    try:
        def req(tenant, rid):
            return front.handle_request(
                {"tenant": tenant, "request_id": rid,
                 "obs": _obs().tolist()}
            )

        # Park the dispatcher inside apply with one sacrificial request.
        resp = req("gold", 1)
        eng = engines[0]
        assert eng.entered.wait(timeout=5.0)
        assert resp["error"] == "timeout"  # typed, acceptor alive

        def fill_to(depth):
            while eng.batcher.depth() < depth:
                eng.batcher.submit(np.zeros(OBS, np.float32),
                                   lambda _r: None)

        # Thresholds (max_queue=20, shed_start=0.5, P=2):
        # bronze sheds at depth >= 10, silver at >= 15, gold never.
        fill_to(10)
        assert req("bronze", 2)["error"] == "shed"
        assert req("silver", 3)["error"] == "timeout"  # admitted
        assert req("gold", 4)["error"] == "timeout"    # admitted

        fill_to(16)
        assert req("bronze", 5)["error"] == "shed"
        assert req("silver", 6)["error"] == "shed"
        assert req("gold", 7)["error"] == "timeout"    # still admitted

        per = front.tenant_stats.per_tenant()
        assert per["bronze"]["shed_priority"] == 2
        assert per["silver"]["shed_priority"] == 1
        assert per["gold"]["shed_priority"] == 0
        # Strict ordering: shed counts are monotone in priority class.
        assert (per["bronze"]["shed_priority"]
                > per["silver"]["shed_priority"]
                > per["gold"]["shed_priority"])
        snap = front.snapshot()
        assert snap["front_sheds"] == 3
        assert snap["tenant_shed_priority"] == 3
        assert snap["front_timeouts"] == 4

        # Release the dispatcher; everything drains and serves again.
        eng.release.set()
        deadline = time.monotonic() + 5.0
        while eng.batcher.depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        # (retry: the 0.05s server deadline is tight under box load)
        for attempt in range(20):
            ok = req("bronze", 8 + attempt)
            if "action" in ok:
                break
        assert "action" in ok
    finally:
        front.stop()


def test_tenant_rate_cap_shed_cause():
    """The 'rate' shed cause fires from the tenant's own bucket even with
    an empty queue — counted under tenant_shed_rate, not priority."""
    front = FrontServer(_make_engine(), tenants="capped:1:0.001:1",
                        http_port=None)
    front.publish("v1", _flat(1))
    front.start()
    try:
        def req(rid):
            return front.handle_request(
                {"tenant": "capped", "request_id": rid,
                 "obs": _obs().tolist()}
            )
        assert "action" in req(1)        # burst token
        resp = req(2)                    # bucket empty (0.001/s refill)
        assert resp["error"] == "shed" and "rate" in resp["message"]
        per = front.tenant_stats.per_tenant()
        assert per["capped"]["shed_rate"] == 1
        assert per["capped"]["shed_priority"] == 0
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# tier-1 drill: canary promote -> gated rollback -> re-promote
# ---------------------------------------------------------------------------


def test_canary_drill_rollback_then_repromote():
    """The version-lifecycle acceptance drill: an injected sustained
    candidate regression (front:canary:regress) must be auto-rolled-back
    by the live gate — never promoted — and once the regression is gone
    the SAME version re-canaries and promotes, all over one surviving
    TCP connection with typed responses throughout."""
    plan = FaultPlan.parse("front:canary:regress@1~0.05")
    front = FrontServer(
        _make_engine(), canary_fraction=0.5, canary_min_requests=5,
        canary_threshold=0.5, http_port=None,
        canary_regressions=plan.front_canary_regressions(),
    )
    front.publish("v1", _flat(1))
    front.publish("v2", _flat(2))
    front.start()
    try:
        cli = FrontClient(front.port, tenant="drill", timeout_s=10.0)

        def drive_until(pred, budget=400):
            for _ in range(budget):
                cli.act(_obs())  # front_timeout_s=2 bounds each request
                if pred(front.snapshot()):
                    return True
            return False

        # Round 1: regressing candidate. The gate must roll back.
        front.start_canary("v2")
        assert drive_until(lambda s: s["front_rollbacks"] >= 1), \
            "regressing canary was never rolled back"
        snap = front.snapshot()
        assert snap["front_promotes"] == 0, "regressing canary promoted!"
        assert snap["front_canary_requests"] > 0
        assert front.store.stable == "v1"
        assert front.store.candidate is None

        # Round 2: the regression is fixed (injection cleared); the same
        # version re-canaries and must promote. Both arms now run the
        # identical engine, but scheduler jitter on a loaded box can
        # still fake a p95 delta over 5-sample arms — re-canary on a
        # spurious rollback rather than flake.
        front._canary_regs = ()
        promoted = False
        for _attempt in range(5):
            before = front.snapshot()
            front.start_canary("v2")
            assert drive_until(
                lambda s, b=before: s["front_promotes"] > b["front_promotes"]
                or s["front_rollbacks"] > b["front_rollbacks"]
            )
            if front.snapshot()["front_promotes"] > before["front_promotes"]:
                promoted = True
                break
        assert promoted, "fixed candidate never re-promoted"
        assert front.store.stable == "v2"

        # Zero acceptor deaths: the connection that drove the whole
        # drill still serves, from the promoted version.
        action, version = cli.act(_obs())
        assert action.shape == (ACT,) and version == "v2"
        cli.close()
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# SAC serve head: per-client server-side sampling parity
# ---------------------------------------------------------------------------

SAC_LAYOUT = param_layout(OBS, actor_head_dim(ACT, sac=True), (16, 16))
SAC_SEED = 11
LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0


def _sac_server(**kw):
    return InferenceServer(
        SAC_LAYOUT, np.ones(ACT, np.float32), sac=True, seed=SAC_SEED,
        log_std_min=LOG_STD_MIN, log_std_max=LOG_STD_MAX,
        max_batch=kw.get("max_batch", 8),
        max_latency_s=kw.get("max_latency_s", 0.002),
        max_queue=kw.get("max_queue", 64),
    )


def _local_sac_reference(flat, obs, tenant, request_id):
    """Independent recomputation of the served SAC sample: the same head
    math (soft clamp incl.) and the same sha256-derived per-request key —
    the parity oracle docs/SERVING.md 'SAC serve head' promises."""
    pol = NumpyPolicy(SAC_LAYOUT, np.ones(ACT, np.float32))
    pol.load_flat(flat)
    raw = pol.head(obs).reshape(-1)
    mean, log_std_raw = raw[:ACT], raw[ACT:]
    log_std = LOG_STD_MIN + 0.5 * (LOG_STD_MAX - LOG_STD_MIN) * (
        np.tanh(log_std_raw) + 1.0
    )
    head = np.concatenate([mean, log_std]).astype(np.float32)
    mean, log_std = head[:ACT], head[ACT:]
    digest = hashlib.sha256(
        f"{SAC_SEED}:{tenant}:{request_id}".encode()
    ).digest()
    rng = np.random.default_rng(int.from_bytes(digest[:8], "little"))
    eps = rng.standard_normal(mean.shape).astype(np.float32)
    u = mean + np.exp(log_std) * eps
    return np.tanh(u).astype(np.float32)  # scale=1, offset=0


def test_sac_sample_parity_and_key_schedule():
    server = _sac_server().start()
    try:
        flat = _flat(3, SAC_LAYOUT)
        server.refresh(flat)
        client = server.client(timeout_s=5.0)
        obs = _obs(7)
        for tenant, rid in (("local", 1), ("local", 2)):
            served = client.act(obs)
            expected = _local_sac_reference(flat, obs, tenant, rid)
            assert np.array_equal(served, expected), (tenant, rid)
        # Different (tenant, request_id) -> different exploration draws;
        # identical key -> identical action (replayable).
        head = server._compute(obs[None, :])[0]
        a = server.sample(head, tenant="a", request_id=1)
        b = server.sample(head, tenant="b", request_id=1)
        a2 = server.sample(head, tenant="a", request_id=1)
        assert np.array_equal(a, a2)
        assert not np.array_equal(a, b)
        # explore=False is the deterministic squash.
        det = server.sample(head, tenant="a", request_id=1, explore=False)
        assert np.array_equal(det, np.tanh(head[:ACT]).astype(np.float32))
        assert np.all(np.abs(a) <= 1.0)
    finally:
        server.close()

    # The deterministic server rejects sample() loudly.
    det_server = InferenceServer(LAYOUT, np.ones(ACT, np.float32))
    with pytest.raises(RuntimeError):
        det_server.sample(np.zeros(ACT), tenant="t", request_id=1)


def test_sac_served_over_the_network_front():
    """End-to-end wire parity: the SAME (tenant, request_id) replays to
    the SAME sampled action across connections, bit-identical to the
    local reference for a fixed key schedule."""
    flat = _flat(5, SAC_LAYOUT)
    front = FrontServer(_sac_server, http_port=None)
    front.publish("v1", flat)
    front.start()
    try:
        obs = _obs(9)
        with FrontClient(front.port, tenant="alice") as cli:
            for rid in (10, 11):
                action, version = cli.act(obs, request_id=rid)
                expected = _local_sac_reference(flat, obs, "alice", rid)
                assert np.array_equal(action, expected)
            replay, _ = cli.act(obs, request_id=10)
        with FrontClient(front.port, tenant="bob") as cli:
            other, _ = cli.act(obs, request_id=10)
        assert np.array_equal(
            replay, _local_sac_reference(flat, obs, "alice", 10)
        )
        assert not np.array_equal(replay, other)  # no shared RNG stream
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# config: the front knob surface
# ---------------------------------------------------------------------------


def test_config_front_validation():
    # sac + serve_actors is now a supported pairing (the SAC serve head).
    DDPGConfig(serve_actors=True, sac=True)
    # The front rides serve_actors.
    with pytest.raises(ValueError):
        DDPGConfig(front_port=7777)
    DDPGConfig(serve_actors=True, front_port=7777)
    DDPGConfig(serve_actors=True, front_http_port=7778)
    for bad in (
        dict(front_port=-1),
        dict(front_port=70000),
        dict(front_http_port=70000),
        dict(serve_actors=True, front_port=7777, front_http_port=7777),
        dict(front_timeout_s=0.0),
        dict(front_canary_fraction=0.0),
        dict(front_canary_fraction=1.0),
        dict(front_canary_min_requests=0),
        dict(front_canary_threshold=0.0),
        dict(front_default_priority=-1),
        dict(front_shed_start=0.0),
        dict(front_shed_start=1.5),
        dict(front_tenants="gold"),           # malformed table
        dict(front_tenants="a:0;a:1"),        # duplicate tenant
    ):
        with pytest.raises(ValueError):
            DDPGConfig(**bad)


# ---------------------------------------------------------------------------
# tools: socket bench + runs digest + gate key
# ---------------------------------------------------------------------------


def test_socket_bench_closed_loop():
    from distributed_ddpg_tpu.tools.serve_bench import run_socket_bench

    r = run_socket_bench(
        clients=2, duration_s=0.4, obs_dim=4, act_dim=2, hidden=(8, 8),
        max_batch=4, max_latency_ms=2.0, tenants="gold:0;bronze:3",
    )
    assert r["transport"] == "socket"
    assert r["served_rps"] > 0
    assert r["front_requests"] > 0
    assert r["wire_p95_ms"] > 0
    assert r["front_wire_p95_ms"] > 0
    assert r["tenant_count"] == 2  # the tenant table named the clients


def test_runs_summarize_and_compare_render_front_digest(tmp_path):
    from distributed_ddpg_tpu.tools import runs

    path = tmp_path / "front.jsonl"
    recs = [
        {"kind": "train", "step": 100, "wall_time": 1.0,
         "front_requests": 40, "front_sheds": 1, "front_wire_p95_ms": 3.0,
         "front_rollbacks": 0, "tenant_served": 39, "tenant_shed_total": 1},
        {"kind": "train", "step": 200, "wall_time": 2.0,
         "front_requests": 90, "front_sheds": 3, "front_wire_p95_ms": 5.0,
         "front_rollbacks": 1, "tenant_served": 87, "tenant_shed_total": 3},
        {"kind": "final", "step": 200, "wall_time": 2.5,
         "front_requests": 95, "front_wire_p95_ms": 4.0},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    digest = runs.summarize_run(str(path))
    assert digest["front"]["front_requests"]["last"] == 95
    assert digest["front"]["front_wire_p95_ms"]["max"] == 5.0
    text = runs.render_summary(digest)
    assert "network front" in text
    assert "front_wire_p95_ms" in text
    _, rows = runs.compare_runs(str(path), str(path))
    assert any(r[0] == "front_wire_p95_ms" for r in rows)


def test_gate_front_key_skip_and_fail_semantics():
    """-front_wire_p95_ms: SKIP against pre-front baselines, FAIL a wire
    latency regression once a socket bench is the baseline."""
    from distributed_ddpg_tpu.tools.runs import gate_bench

    keys = ("-front_wire_p95_ms",)
    ok, lines = gate_bench({"value": 1.0}, {"value": 1.0}, 0.1, keys)
    assert ok and all("SKIP" in ln for ln in lines)
    base = {"front_wire_p95_ms": 5.0}
    assert gate_bench(base, {"front_wire_p95_ms": 5.2}, 0.1, keys)[0]
    assert not gate_bench(base, {"front_wire_p95_ms": 9.0}, 0.1, keys)[0]
    # Dropping the key the baseline had must FAIL, not skip.
    assert not gate_bench(base, {"value": 1.0}, 0.1, keys)[0]


# ---------------------------------------------------------------------------
# slow: end-to-end train run with the front armed (FRONT_FULL smoke)
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_train_with_front_armed(tmp_path):
    """train.py arms the front next to served actors: external TCP
    traffic lands during the run and front_* / tenant_* ride the final
    record."""
    from distributed_ddpg_tpu.train import train_jax

    port = _free_port()
    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=2,
        total_env_steps=1_500,
        replay_min_size=256,
        replay_capacity=20_000,
        eval_every=0,
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        log_path=str(tmp_path / "front.jsonl"),
        serve_actors=True,
        serve_max_batch=8,
        serve_max_latency_ms=1.0,
        front_port=port,
        front_tenants="gold:0;bronze:3",
    )
    served = [0]
    stop = threading.Event()

    def external_traffic():
        obs = np.zeros(3, np.float32)  # Pendulum obs dim
        while not stop.is_set():
            try:
                with FrontClient(port, tenant="gold",
                                 timeout_s=2.0) as cli:
                    while not stop.is_set():
                        cli.act(obs)
                        served[0] += 1
                        time.sleep(0.01)
            except (OSError, FrontError, ConnectionError):
                time.sleep(0.05)  # front not up yet / shutting down

    t = threading.Thread(target=external_traffic, daemon=True)
    t.start()
    try:
        out = train_jax(cfg)
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert out["learner_steps"] > 0
    with open(cfg.log_path) as f:
        recs = [json.loads(ln) for ln in f if ln.strip().startswith("{")]
    finals = [r for r in recs if r.get("kind") == "final"]
    assert finals
    final = finals[-1]
    for key in ("front_requests", "front_sheds", "front_wire_p95_ms",
                "tenant_count", "tenant_served"):
        assert key in final, f"{key} missing from the final record"
    if served[0]:
        assert final["front_requests"] >= served[0]
        assert final["tenant_count"] >= 1
