"""--strict_sync lockstep mode (actors/sync_pool.py; SURVEY.md §5 race
detection, VERDICT r4 Missing #5): two runs of the same config must produce
BIT-IDENTICAL metrics — content and order — once wall-clock-derived fields
are stripped. This is the deterministic-repro contract that makes async
races debuggable by contrast."""

import json

import pytest

from distributed_ddpg_tpu.config import DDPGConfig

# Wall-clock-derived fields: everything else must match bit for bit. The
# ingest COUNT fields (ship_calls, coalesce_mean, queue_rows) stay in the
# contract — strict_sync forces inline shipping, so the ship schedule
# itself must be deterministic; only its timings may vary.
_TIME_KEYS = (
    "wall_time", "learner_steps_per_sec", "actor_steps_per_sec",
    "ingest_rows_per_sec", "ingest_stall_ms", "ingest_ship_ms",
    # Replay-placement dispatch tails (metrics.ReplayShardStats) are
    # wall-clock like ingest_ship_ms; the placement COUNT fields
    # (replay_ingest_bytes*, shard count/fill) stay in the contract.
    "replay_exchange_ms_p50", "replay_exchange_ms_p95",
)


def _strip(record: dict) -> dict:
    return {
        k: v
        for k, v in record.items()
        if k not in _TIME_KEYS and not k.startswith("t_")
    }


def _run(tmp_path, tag: str) -> list:
    from distributed_ddpg_tpu.train import train_jax

    log = tmp_path / f"{tag}.jsonl"
    config = DDPGConfig(
        env_id="Pendulum-v1",
        backend="jax_tpu",
        strict_sync=True,
        num_actors=2,
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        n_step=2,
        batch_size=32,
        replay_min_size=192,
        total_env_steps=1000,
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        eval_every=400,
        log_path=str(log),
    )
    train_jax(config)
    return [json.loads(line) for line in log.read_text().splitlines()]


class TestStrictSync:
    def test_two_runs_bit_identical(self, tmp_path):
        a = _run(tmp_path, "a")
        b = _run(tmp_path, "b")
        assert len(a) == len(b)
        assert any(r["kind"] == "train" for r in a)
        assert any(r["kind"] == "eval" for r in a)
        for ra, rb in zip(a, b):
            assert _strip(ra) == _strip(rb)

    def test_requires_ratio_gates(self):
        with pytest.raises(ValueError, match="ratio"):
            DDPGConfig(strict_sync=True)

    def test_rejects_native_backend(self):
        with pytest.raises(ValueError, match="native"):
            DDPGConfig(
                strict_sync=True, backend="native",
                max_learn_ratio=1.0, max_ingest_ratio=1.0,
            )

    def test_rejects_host_replay(self):
        with pytest.raises(ValueError, match="device replay"):
            DDPGConfig(
                strict_sync=True, backend="jax_tpu", host_replay=True,
                max_learn_ratio=1.0, max_ingest_ratio=1.0,
            )
