"""Parity: the pallas megakernel chunk (ops/fused_chunk.py) must reproduce
the XLA scan path (learner.make_learner_step applied K times) on identical
batches — same params, targets, Adam moments, TD errors, and metrics."""

import jax
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.ops import fused_chunk
from distributed_ddpg_tpu.types import pack_batch_np

OBS, ACT, B, K = 5, 3, 16, 4


def _batches(rng, k):
    return pack_batch_np(
        {
            "obs": rng.standard_normal((k, B, OBS)).astype(np.float32),
            "action": rng.uniform(-1, 1, (k, B, ACT)).astype(np.float32),
            "reward": rng.standard_normal((k, B)).astype(np.float32),
            "discount": np.full((k, B), 0.99, np.float32),
            "next_obs": rng.standard_normal((k, B, OBS)).astype(np.float32),
            "weight": rng.uniform(0.5, 1.0, (k, B)).astype(np.float32),
        }
    )


def _assert_tree_close(a, b, rtol=2e-5, atol=1e-6):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol
        ),
        a,
        b,
    )


@pytest.mark.parametrize(
    "hidden,scale,offset",
    [
        ((32, 32), 2.0, 0.0),
        # Deeper nets + asymmetric action box: same oracle, second shape —
        # slow tier keeps the fast tier's one-per-branch representative rule.
        pytest.param((32, 24, 16), 1.5, 0.25, marks=pytest.mark.slow),
    ],
)
def test_fused_chunk_matches_scan(hidden, scale, offset):
    """Interpret-mode parity at tight tolerances — the bit-level oracle.
    The same body runs natively compiled on real TPU via tests/tpu_child.py
    (fused_parity_util.assert_fused_matches_scan)."""
    from fused_parity_util import assert_fused_matches_scan

    cfg = DDPGConfig(
        actor_hidden=hidden, critic_hidden=hidden, batch_size=B, seed=3
    )
    assert fused_chunk.supported(cfg)
    assert_fused_matches_scan(
        cfg, OBS, ACT, K, scale, offset,
        interpret=True, rtol=2e-5, atol=1e-6, metric_rtol=5e-5,
    )


def test_fused_chunk_c51_matches_scan():
    """D4PG envelope: the in-kernel categorical projection (triangular-
    kernel accumulation) + closed-form CE/expected-value cotangents must
    reproduce the autodiff scan path at bit-oracle tolerances."""
    from fused_parity_util import assert_fused_matches_scan

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 24, 16), batch_size=B,
        distributional=True, num_atoms=21, v_min=-5.0, v_max=5.0, seed=3,
    )
    assert fused_chunk.supported(cfg)
    assert_fused_matches_scan(
        cfg, OBS, ACT, K, 1.5, 0.25,
        interpret=True, rtol=2e-4, atol=1e-5, metric_rtol=5e-4,
    )


@pytest.mark.parametrize(
    "distributional",
    [False, pytest.param(True, marks=pytest.mark.slow)],
)
def test_fused_chunk_bf16_matches_scan(distributional):
    """Mixed precision: the kernel's bf16-operand/f32-accumulate dots must
    track the scan path's (models/mlp._dense) within bf16 rounding — the
    two differ only in where autodiff inserts the casts on the backward
    pass, so tolerances are bf16-level, not bit-level."""
    from fused_parity_util import assert_fused_matches_scan

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        compute_dtype="bfloat16", distributional=distributional,
        num_atoms=21, v_min=-5.0, v_max=5.0, seed=3,
    )
    assert fused_chunk.supported(cfg)
    assert_fused_matches_scan(
        cfg, OBS, ACT, K, 2.0, 0.0,
        interpret=True, rtol=3e-2, atol=3e-3, metric_rtol=3e-2,
    )


# Both params slow since round 5: the delay=2 leg was the fast tier's
# second-biggest line item (63s interpret-mode compile+run); the TD3
# kernel branch keeps a fast-feedback guard via the scan-path TD3 tests
# and a HARDWARE guard via the runbook's tpu_td3 stage.
@pytest.mark.parametrize(
    "delay,noise",
    [
        pytest.param(1, 0.0, marks=pytest.mark.slow),
        pytest.param(2, 0.2, marks=pytest.mark.slow),
    ],
)
def test_fused_chunk_td3_matches_scan(delay, noise):
    """TD3 in the kernel: twin members as separate rank-2 ref groups,
    min-over-ensemble targets, smoothing noise STREAMED from the scan
    path's exact fold_in(seed, step) draw (bit-comparable), and delayed
    actor/target updates under pl.when with closed-form actor-count
    bookkeeping. The reference scan is also the Adam-count oracle."""
    from fused_parity_util import assert_fused_matches_scan

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 24, 16), batch_size=B,
        twin_critic=True, policy_delay=delay, target_noise=noise, seed=3,
    )
    assert fused_chunk.supported(cfg)
    assert_fused_matches_scan(
        cfg, OBS, ACT, 5, 1.5, 0.25,
        interpret=True, rtol=2e-4, atol=1e-5, metric_rtol=5e-4,
    )


@pytest.mark.slow
def test_fused_chunk_td3_step_offset_continuity():
    """The delayed-update schedule and the noise stream key off the GLOBAL
    step, so a chunk starting at an arbitrary step0 must keep matching the
    scan path — two consecutive fused chunks vs two scan chunks through
    the public run_sample_chunk API (same draw stream)."""
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.parallel.mesh import make_mesh
    from distributed_ddpg_tpu.replay.device import DeviceReplay

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        twin_critic=True, policy_delay=2, target_noise=0.2, seed=5,
    )
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    rows = _batches(np.random.default_rng(11), 16).reshape(-1, 2 * OBS + ACT + 3)
    results = {}
    for mode in ("on", "off"):
        lrn = ShardedLearner(
            cfg.replace(fused_chunk=mode), OBS, ACT,
            action_scale=1.0, mesh=mesh, chunk_size=3,  # odd K: step0 drifts
        )
        assert lrn.fused_chunk_active == (mode == "on")
        rep = DeviceReplay(
            capacity=256, obs_dim=OBS, act_dim=ACT, mesh=mesh, block_size=256
        )
        rep.add_packed(rows)
        for _ in range(3):  # chunk boundaries at steps 3, 6 (odd offsets)
            out = lrn.run_sample_chunk(rep)
        results[mode] = (jax.device_get(lrn.state), np.asarray(out.td_errors))
    s_on, td_on = results["on"]
    s_off, td_off = results["off"]
    _assert_tree_close(s_on.critic_params, s_off.critic_params, rtol=5e-4, atol=1e-5)
    _assert_tree_close(s_on.actor_params, s_off.actor_params, rtol=5e-4, atol=1e-5)
    _assert_tree_close(s_on.target_critic_params, s_off.target_critic_params, rtol=5e-4, atol=1e-5)
    np.testing.assert_allclose(td_on, td_off, rtol=5e-4, atol=1e-4)
    assert int(s_on.actor_opt.count) == int(s_off.actor_opt.count)
    assert int(s_on.critic_opt.count) == 9


@pytest.mark.slow
def test_sharded_learner_fused_path_matches_scan_path():
    """On a 1-device mesh, fused_chunk='on' must reproduce fused_chunk='off'
    through the public run_sample_chunk API: both draw the same (K, B) index
    block from the same key stream, so state and TD errors must agree."""
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.parallel.mesh import make_mesh
    from distributed_ddpg_tpu.replay.device import DeviceReplay

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B, seed=5
    )
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    rng = np.random.default_rng(11)
    rows = pack_batch_np(
        {
            "obs": rng.standard_normal((256, OBS)).astype(np.float32),
            "action": rng.uniform(-1, 1, (256, ACT)).astype(np.float32),
            "reward": rng.standard_normal(256).astype(np.float32),
            "discount": np.full(256, 0.99, np.float32),
            "next_obs": rng.standard_normal((256, OBS)).astype(np.float32),
            "weight": np.ones(256, np.float32),
        }
    )

    results = {}
    for mode in ("on", "off"):
        lrn = ShardedLearner(
            cfg.replace(fused_chunk=mode), OBS, ACT,
            action_scale=1.0, mesh=mesh, chunk_size=K,
        )
        assert lrn.fused_chunk_active == (mode == "on")
        rep = DeviceReplay(
            capacity=256, obs_dim=OBS, act_dim=ACT, mesh=mesh, block_size=256
        )
        rep.add_packed(rows)
        out = lrn.run_sample_chunk(rep)
        results[mode] = (
            jax.device_get(lrn.state),
            np.asarray(out.td_errors),
            {k_: float(v) for k_, v in jax.device_get(out.metrics).items()},
        )

    _assert_tree_close(results["on"][0].actor_params, results["off"][0].actor_params)
    _assert_tree_close(results["on"][0].critic_opt.mu, results["off"][0].critic_opt.mu)
    np.testing.assert_allclose(results["on"][1], results["off"][1], rtol=2e-5, atol=1e-6)
    for k_ in results["on"][2]:
        np.testing.assert_allclose(
            results["on"][2][k_], results["off"][2][k_], rtol=5e-5, atol=1e-6
        )


def test_auto_mode_falls_back_on_kernel_failure(monkeypatch):
    """fused_chunk='auto': a megakernel that dies at first dispatch (the
    round-2 Mosaic BlockSpec failure mode) must degrade to the XLA scan
    path with a warning — and keep training — instead of raising."""
    from distributed_ddpg_tpu.ops import fused_chunk as fc
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.parallel.mesh import make_mesh
    from distributed_ddpg_tpu.replay.device import DeviceReplay

    monkeypatch.setattr(fc, "runs_native", lambda: True)

    def broken_make(*args, **kwargs):
        def run(state, batches):
            raise RuntimeError("mosaic boom")

        return run

    monkeypatch.setattr(fc, "make_fused_chunk_fn", broken_make)
    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        fused_chunk="auto",
    )
    lrn = ShardedLearner(
        cfg, OBS, ACT, action_scale=1.0,
        mesh=make_mesh(1, 1, devices=jax.devices()[:1]), chunk_size=K,
    )
    assert lrn.fused_chunk_active
    rep = DeviceReplay(
        capacity=64, obs_dim=OBS, act_dim=ACT, mesh=lrn.mesh, block_size=64
    )
    rep.add_packed(_batches(np.random.default_rng(3), 4).reshape(-1, rep.width))
    with pytest.warns(UserWarning, match="falling back"):
        out = lrn.run_sample_chunk(rep)
    assert not lrn.fused_chunk_active
    assert np.isfinite(float(out.metrics["critic_loss"]))
    out2 = lrn.run_sample_chunk(rep)  # steady state keeps working
    assert np.isfinite(float(out2.metrics["critic_loss"]))


def test_fused_chunk_on_requires_envelope():
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError):
        ShardedLearner(
            DDPGConfig(critic_l2=1e-4, fused_chunk="on"),
            OBS, ACT, action_scale=1.0,
            mesh=make_mesh(1, 1, devices=jax.devices()[:1]),
        )


def test_supported_gates():
    # D4PG (C51), bf16, and SAC are INSIDE the envelope since round 4.
    assert fused_chunk.supported(DDPGConfig(distributional=True))
    assert fused_chunk.supported(DDPGConfig(compute_dtype="bfloat16"))
    assert fused_chunk.supported(DDPGConfig(sac=True))
    assert fused_chunk.supported(DDPGConfig(sac=True, sac_autotune=False))
    assert not fused_chunk.supported(
        DDPGConfig(distributional=True, num_atoms=512)  # unroll cap
    )
    assert not fused_chunk.supported(DDPGConfig(critic_l2=1e-4))
    assert not fused_chunk.supported(DDPGConfig(action_insert_layer=0))
    assert not fused_chunk.supported(DDPGConfig(critic_hidden=(32,)))
    with pytest.raises(ValueError):
        fused_chunk.make_fused_chunk_fn(
            DDPGConfig(critic_l2=1e-4), OBS, ACT, 1.0
        )
    # VMEM budget gate: huge nets fall back to the XLA scan path.
    big = DDPGConfig(actor_hidden=(1024, 1024), critic_hidden=(1024, 1024))
    assert fused_chunk.supported(big)
    assert not fused_chunk.fits_vmem(big, OBS, ACT)
    with pytest.raises(ValueError, match="VMEM"):
        fused_chunk.make_fused_chunk_fn(big, OBS, ACT, 1.0)
    assert fused_chunk.fits_vmem(DDPGConfig(), 17, 6)  # bench scale fits
    # Config typo guard: only auto/on/off are accepted.
    with pytest.raises(ValueError, match="fused_chunk"):
        DDPGConfig(fused_chunk="Off")


@pytest.mark.parametrize(
    "autotune",
    [
        pytest.param(True, marks=pytest.mark.slow),
        pytest.param(False, marks=pytest.mark.slow),
    ],
)
def test_fused_chunk_sac_matches_scan(autotune):
    """SAC in the kernel (round 4): Gaussian head split + tanh soft-clamp,
    reparameterized sampling from the scan path's exact fold_in stream
    (pre-drawn, streamed like TD3's smoothing noise), entropy-corrected
    twin TD targets, hand-written backward through the squash log-prob,
    and the learned temperature's scalar Adam — all vs the autodiff scan
    path at bit-oracle tolerances. Covers both the learned-alpha and the
    fixed-alpha configurations."""
    from fused_parity_util import assert_fused_matches_scan

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 24, 16), batch_size=B,
        sac=True, sac_autotune=autotune, seed=3,
    )
    assert fused_chunk.supported(cfg)
    assert_fused_matches_scan(
        cfg, OBS, ACT, K, 1.5, 0.25,
        interpret=True, rtol=2e-4, atol=1e-5, metric_rtol=5e-4,
    )


@pytest.mark.slow
def test_fused_chunk_sac_bf16_matches_scan():
    """SAC x mixed precision: bf16 dots with f32 accumulation on both the
    Gaussian head and the twin critics, bf16-level tolerances."""
    from fused_parity_util import assert_fused_matches_scan

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        sac=True, compute_dtype="bfloat16", seed=3,
    )
    assert fused_chunk.supported(cfg)
    assert_fused_matches_scan(
        cfg, OBS, ACT, K, 2.0, 0.0,
        interpret=True, rtol=3e-2, atol=3e-3, metric_rtol=4e-2,
    )


@pytest.mark.slow
def test_fused_chunk_sac_step_offset_continuity():
    """SAC's sampling streams key off the GLOBAL step (fold_in(base,
    step)), so a second fused chunk starting at step0=K must keep matching
    the scan path — run two consecutive chunks through the raw kernel fn
    and the scan, comparing end log_alpha and actor params."""
    from distributed_ddpg_tpu.learner import init_train_state, make_learner_step
    from distributed_ddpg_tpu.types import unpack_batch
    import jax.numpy as jnp

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        sac=True, seed=9,
    )
    state = init_train_state(cfg, OBS, ACT, seed=9)
    run = fused_chunk.make_fused_chunk_fn(
        cfg, OBS, ACT, 1.5, 0.25, chunk_size=3, interpret=True
    )
    packed = _batches(np.random.default_rng(13), 6)
    fused = state
    for c in range(2):
        fused, _, _ = jax.jit(run)(fused, jnp.asarray(packed[3 * c : 3 * c + 3]))
    step = make_learner_step(cfg, 1.5, action_offset=0.25)
    ref = state
    for i in range(6):
        ref = step(ref, unpack_batch(jnp.asarray(packed[i]), OBS, ACT)).state
    np.testing.assert_allclose(
        float(fused.log_alpha), float(ref.log_alpha), rtol=2e-4, atol=1e-6
    )
    _assert_tree_close(fused.actor_params, ref.actor_params, rtol=5e-4, atol=1e-5)
    assert int(fused.step) == int(ref.step) == 6
