"""Chaos harness integration tests (docs/RESILIENCE.md; SURVEY.md §4
'Fault/elastic' taken to production grade): a CPU training run under a
scripted multi-fault schedule must keep making learner progress, end
resumable, and resume; a corrupted latest checkpoint must fall back to the
previous retained one through the REAL train_jax resume path; SIGTERM must
produce an emergency checkpoint and the documented exit code (75)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from distributed_ddpg_tpu import checkpoint as ckpt_lib
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.train import EXIT_PREEMPTED, train_jax

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            if line.strip().startswith("{"):
                out.append(json.loads(line))
    return out


# Re-tiered to slow (ISSUE 15 tier-1 budget): 96s soak on the contended CI box; sigterm/corrupt-latest keep the
# tier-1 chaos smokes
@pytest.mark.slow
def test_chaos_soak_multi_fault_schedule(tmp_path):
    """The headline soak: three distinct fault kinds — worker crash, worker
    hang (silent-heartbeat path), checkpoint write IO error — scripted into
    one short CPU run. The run must complete its env budget (progress
    after every fault), recover each worker through the backoff respawn
    path, absorb the write failure via retry, and leave a VALID latest
    checkpoint a second run resumes from."""
    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=2,
        total_env_steps=4_000,
        replay_min_size=256,
        replay_capacity=20_000,
        eval_every=0,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=200,
        log_path=str(tmp_path / "chaos.jsonl"),
        # 1:1 rate caps = the reference's synchronous schedule: learner and
        # ingest advance together at the throttled actor rate, so the run
        # lasts long enough for every scheduled fault to fire AND recover.
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        actor_throttle_s=0.004,
        # Fast supervision for test time; production defaults are 30/0.5/30.
        heartbeat_timeout_s=2.0,
        respawn_backoff_s=0.05,
        respawn_backoff_max_s=0.5,
        ckpt_write_retries=2,
        ckpt_retry_backoff_s=0.05,
        faults=(
            "worker:0:crash@300"      # process death -> liveness respawn
            ";worker:1:hang@600"      # frozen, no heartbeats -> silent respawn
            ";ckpt:write:ioerror@1"   # first write attempt fails -> retry
        ),
    )
    out = train_jax(cfg)

    # The env budget completed: learner progress continued after each fault
    # (a dead fleet or a wedged writer would have stalled the run instead).
    assert out["learner_steps"] > 0
    assert out["actor_respawns"] >= 2, (
        f"crash + hang should both respawn: {out}"
    )
    assert out["actor_quarantined"] == 0
    assert out["ckpt_write_retries"] >= 1, (
        f"injected ckpt ioerror was never retried: {out}"
    )
    assert not out["preempted"]

    # Learner kept advancing after the fleet faults fired.
    recs = _records(cfg.log_path)
    trains = [r for r in recs if r["kind"] == "train"]
    faulted = [r for r in trains if r.get("actor_respawns", 0) >= 1]
    if faulted:
        assert out["learner_steps"] > faulted[0]["learner_steps"], (
            "no learner progress after the first respawn"
        )
    final = [r for r in recs if r["kind"] == "final"][-1]
    assert final["actor_respawns"] == out["actor_respawns"]
    assert final["ckpt_write_retries"] == out["ckpt_write_retries"]

    # A valid (manifest-verified) checkpoint landed despite the IO fault...
    step = ckpt_lib.latest_step(cfg.checkpoint_dir)
    assert step is not None and step > 0
    ok, why = ckpt_lib.verify_checkpoint(cfg.checkpoint_dir, step)
    assert ok, why

    # ...and a fresh run resumes from it (fault-free this time).
    cfg2 = cfg.replace(
        faults="",
        total_env_steps=cfg.total_env_steps + 600,
        log_path=str(tmp_path / "resume.jsonl"),
    )
    out2 = train_jax(cfg2)
    assert out2["learner_steps"] >= step, (
        f"resume started below the checkpointed step {step}: {out2}"
    )


# Re-tiered to slow (ISSUE 15 tier-1 budget): 42s two-run compile-dominated resume walk; the sigterm smoke keeps
# chaos tier-1 coverage
@pytest.mark.slow
def test_corrupt_latest_checkpoint_resume_falls_back(tmp_path, capfd):
    """Acceptance: a run with a corrupted LATEST checkpoint restores from
    the previous retained one — through train_jax's own resume path, not
    just the checkpoint-module unit test (tests/test_faults.py)."""
    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=1,
        total_env_steps=1_200,
        replay_min_size=256,
        replay_capacity=20_000,
        eval_every=0,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=100,
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        log_path=str(tmp_path / "a.jsonl"),
    )
    train_jax(cfg)
    steps = sorted(
        int(n.split("_", 1)[1])
        for n in os.listdir(cfg.checkpoint_dir)
        if n.startswith("step_")
    )
    assert len(steps) >= 2, f"need >=2 retained checkpoints, got {steps}"
    latest, fallback = steps[-1], steps[-2]

    # Corrupt the latest: truncate its largest payload file.
    root = os.path.join(cfg.checkpoint_dir, f"step_{latest}")
    files = []
    for dirpath, _, names in os.walk(root):
        files += [os.path.join(dirpath, n) for n in names]
    target = max(files, key=os.path.getsize)
    with open(target, "r+b") as f:
        f.truncate(max(os.path.getsize(target) // 2, 1))

    capfd.readouterr()  # drop the first run's output
    cfg2 = cfg.replace(
        total_env_steps=cfg.total_env_steps + 400,
        log_path=str(tmp_path / "b.jsonl"),
    )
    out2 = train_jax(cfg2)
    captured = capfd.readouterr()
    assert f"step_{latest} failed verification" in captured.err
    assert f"resumed from {cfg2.checkpoint_dir} at learner step {fallback}" in (
        captured.out
    )
    assert out2["learner_steps"] >= fallback
    # The corrupt checkpoint was quarantined (kept for forensics, out of
    # the step_N namespace) so the resumed run could re-checkpoint at or
    # past that step without colliding with the corrupt leftovers. If a
    # step_<latest> directory exists NOW, it is a FRESH re-checkpoint
    # from the resumed run (whether it survives depends on how many later
    # cadence points the resumed run reached before retention pruning —
    # pacing, not correctness): it must verify clean, unlike the
    # quarantined original.
    assert os.path.isdir(
        os.path.join(cfg.checkpoint_dir, f"corrupt_step_{latest}")
    )
    if os.path.isdir(root):
        ok, why = ckpt_lib.verify_checkpoint(cfg.checkpoint_dir, latest)
        assert ok, f"re-checkpoint at step_{latest} is not clean: {why}"


def test_sigterm_takes_emergency_checkpoint_and_exits_75(tmp_path):
    """The preemption contract (docs/RESILIENCE.md): SIGTERM mid-training
    -> one emergency checkpoint + exit code EXIT_PREEMPTED (75), so a
    driver can tell 'preempted, resumable' from 'crashed' (and from the
    watchdog's 70). Runs the real CLI in a subprocess."""
    ckpt_dir = tmp_path / "ckpt"
    log_path = tmp_path / "m.jsonl"
    cmd = [
        sys.executable, "-m", "distributed_ddpg_tpu.train",
        "--env_id=Pendulum-v1",
        "--actor_hidden=16,16", "--critic_hidden=16,16",
        "--num_actors=1",
        "--total_env_steps=2000000",       # far beyond the test's lifetime
        "--replay_min_size=256",
        "--replay_capacity=20000",
        "--eval_every=0",
        f"--checkpoint_dir={ckpt_dir}",
        "--checkpoint_every=1000000000",   # cadence never fires: any
                                           # checkpoint is the emergency one
        f"--log_path={log_path}",
    ]
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    try:
        # Wait until the learner is demonstrably training (first train
        # record) so the SIGTERM lands mid-run, then preempt.
        deadline = time.time() + 240
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if log_path.exists() and '"kind": "train"' in log_path.read_text():
                break
            time.sleep(0.5)
        assert proc.poll() is None, (
            f"trainer died before SIGTERM: {proc.communicate()[1][-2000:]}"
        )
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == EXIT_PREEMPTED, (
        f"exit {proc.returncode} != {EXIT_PREEMPTED};\nstderr: {err[-3000:]}"
    )
    assert "emergency checkpoint" in err
    step = ckpt_lib.latest_step(str(ckpt_dir))
    assert step is not None, "no emergency checkpoint was written"
    ok, why = ckpt_lib.verify_checkpoint(str(ckpt_dir), step)
    assert ok, why
    final = [r for r in _records(log_path) if r["kind"] == "final"]
    assert final and final[-1]["emergency_ckpt"] == 1


# --------------------------------------------------------------------------
# elastic-pod slice faults (faults.py `slice` component; docs/RESILIENCE.md
# shrink/grow state machine, docs/REPLAY_SHARDING.md all-writer slices)
# --------------------------------------------------------------------------


def test_slice_fault_specs_parse_and_scope_to_process():
    from distributed_ddpg_tpu.faults import FaultPlan

    plan = FaultPlan.parse("slice:0:corrupt@1;slice:1:kill@2", seed=0)
    assert bool(plan.slice_site(0)) and bool(plan.slice_site(1))
    assert not plan.slice_site(2)
    assert {s.describe() for s in plan.specs} == {
        "slice:0:corrupt@1", "slice:1:kill@2",
    }
    # Only corrupt/kill apply to slice writes; targets are process ids.
    with pytest.raises(ValueError, match="slice"):
        FaultPlan.parse("slice:0:hang@1")
    with pytest.raises(ValueError, match="slice"):
        FaultPlan.parse("slice:x:corrupt@1")


def _synthetic_slice_sets(seed, size=96, width=7, nslices=2, capacity=128):
    """A logical replay state plus its position-strided slice partition
    (replay/device.py split_slice_state) — checkpoint-layer drills don't
    need a live sharded buffer."""
    import numpy as np

    from distributed_ddpg_tpu.replay.device import split_slice_state

    rng = np.random.default_rng(seed)
    state = {
        "packed": rng.standard_normal((size, width)).astype(np.float32),
        "ptr": np.asarray(0),
        "size": np.asarray(size),
    }
    return state, split_slice_state(state, nslices, capacity)


def test_slice_corruption_quarantines_one_slice_and_falls_back(
    tmp_path, capfd
):
    """Torn-shard-write drill (slice:1:corrupt@2): writer 1's second
    slice write lands torn AFTER its digest sidecar, so verification
    catches the tear, quarantines ONLY that slice (the step's sibling
    slice and learner state stay valid), and adoption falls back to the
    newest OLDER complete set — the adopt-verified-slice branch. With no
    older complete set the lookup returns None: the exit-76 fallback
    branch (train.py)."""
    import numpy as np

    from distributed_ddpg_tpu.faults import FaultPlan
    from distributed_ddpg_tpu.replay.device import merge_slice_states

    d = str(tmp_path / "ckpt")
    plan = FaultPlan.parse("slice:1:corrupt@2", seed=0)
    sites = [plan.slice_site(0), plan.slice_site(1)]

    # Step 10: both writers land clean (writer 1's site ticks ordinal 1).
    state10, slices10 = _synthetic_slice_sets(seed=3)
    for k, sl in enumerate(slices10):
        ckpt_lib.write_replay_slice(d, 10, k, 2, sl, fault=sites[k])
    complete, n, _ = ckpt_lib.slice_status(d, 10)
    assert complete and n == 2

    # Step 20: writer 1's second write fires the injected tear.
    state20, slices20 = _synthetic_slice_sets(seed=4)
    for k, sl in enumerate(slices20):
        ckpt_lib.write_replay_slice(d, 20, k, 2, sl, fault=sites[k])
    assert sites[1].fired == ["slice:1:corrupt@2"]
    complete, n, status = ckpt_lib.slice_status(d, 20)
    assert not complete and n == 2
    ok0, _ = status[0]
    ok1, why1 = status[1]
    assert ok0 and not ok1, status
    assert "mismatch" in why1, why1

    # Quarantine moves ONLY the torn slice out of the namespace.
    capfd.readouterr()
    complete, _ = ckpt_lib.verify_replay_slices(d, 20, quarantine=True)
    assert not complete
    assert "quarantined corrupt replay slice" in capfd.readouterr().err
    root = os.path.join(d, ckpt_lib.SLICE_DIRNAME, "step_20")
    assert os.path.exists(os.path.join(root, "slice_1_of_2.npz.corrupt"))
    assert os.path.exists(os.path.join(root, "slice_0_of_2.npz"))

    # Adopt-verified-slice branch: fallback lands on step 10, and the
    # merged set reproduces the original logical state bit-for-bit.
    assert ckpt_lib.latest_complete_slice_step(d) == 10
    merged = merge_slice_states(ckpt_lib.load_replay_slices(d, 10))
    np.testing.assert_array_equal(merged["packed"], state10["packed"])
    assert int(merged["size"]) == int(state10["size"])

    # Exit-76 fallback branch: nothing complete below step 10.
    assert ckpt_lib.latest_complete_slice_step(d, at_or_below=9) is None
    # load_replay_slices refuses the incomplete step loudly.
    with pytest.raises(RuntimeError, match="incomplete"):
        ckpt_lib.load_replay_slices(d, 20)


def test_slice_kill_dies_before_any_byte_lands(tmp_path):
    """Peer-loss-during-checkpoint drill (slice:0:kill@1): the writer
    SIGKILLs itself before any byte of its slice lands — the dead peer's
    files simply never exist, the step's set reads incomplete, and
    adoption must fall back to an older complete set (or exit 76 when
    none exists). Runs in a subprocess: the kill is a real SIGKILL."""
    d = str(tmp_path / "ckpt")
    code = (
        "import numpy as np\n"
        "from distributed_ddpg_tpu import checkpoint as ckpt_lib\n"
        "from distributed_ddpg_tpu.faults import FaultPlan\n"
        "site = FaultPlan.parse('slice:0:kill@1', seed=0).slice_site(0)\n"
        "sl = {'positions': np.arange(4, dtype=np.int64),\n"
        "      'rows': np.zeros((4, 3), np.float32),\n"
        "      'ptr': np.asarray(0), 'size': np.asarray(4),\n"
        "      'capacity': np.asarray(8)}\n"
        f"ckpt_lib.write_replay_slice({d!r}, 5, 0, 2, sl, fault=site)\n"
        "print('UNREACHABLE')\n"
    )
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stdout, proc.stderr,
    )
    assert "UNREACHABLE" not in proc.stdout
    # No byte landed: neither payload nor sidecar, so the set is simply
    # incomplete and nothing needs quarantining.
    root = os.path.join(d, ckpt_lib.SLICE_DIRNAME, "step_5")
    assert not os.path.exists(os.path.join(root, "slice_0_of_2.npz"))
    assert not os.path.exists(os.path.join(root, "slice_0_of_2.json"))
    complete, _, _ = ckpt_lib.slice_status(d, 5)
    assert not complete
    assert ckpt_lib.latest_complete_slice_step(d) is None
