"""On-device backend tests (envs/jax_envs.py + ondevice.py).

- Dynamics equivalence: JaxPendulum must reproduce the builtin numpy
  Pendulum (envs/pendulum.py) step-for-step from the same state/actions —
  the guarantee that `Pendulum-v1` results compare across backends.
- Auto-reset semantics: boundary flags, boot_obs vs post-reset obs.
- OnDeviceDDPG: chunk execution on the 8-device CPU mesh (conftest.py),
  replay fill accounting, learning gate at replay_min_size, finite metrics,
  episode-return extraction, checkpoint round-trip of the replay ring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs.jax_envs import JaxPendulum, make_jax_env
from distributed_ddpg_tpu.envs.pendulum import Pendulum


def test_jax_pendulum_matches_numpy_dynamics():
    from distributed_ddpg_tpu.envs.jax_envs import PendulumState

    jenv, nenv = JaxPendulum(), Pendulum(seed=0)
    nenv.reset(seed=3)
    th, thdot = nenv._state
    state = PendulumState(
        th=jnp.float32(th), thdot=jnp.float32(thdot), t=jnp.int32(0)
    )
    rng = np.random.default_rng(7)
    key = jax.random.PRNGKey(1)
    for i in range(60):
        a = rng.uniform(-2, 2, 1).astype(np.float32)
        key, k = jax.random.split(key)
        out = jenv.step(state, jnp.asarray(a), k)
        nobs, nrew, _, ntrunc, _ = nenv.step(a)
        assert not ntrunc
        np.testing.assert_allclose(np.asarray(out.obs), nobs, atol=1e-4)
        np.testing.assert_allclose(float(out.reward), nrew, atol=1e-4)
        assert not bool(out.done)
        state = out.state


def test_jax_pendulum_autoreset():
    env = JaxPendulum()
    key = jax.random.PRNGKey(0)
    state = env.init(key)
    state = state._replace(t=jnp.int32(env.max_episode_steps - 1))
    out = env.step(state, jnp.zeros(1), jax.random.PRNGKey(42))
    assert bool(out.done)
    assert int(out.state.t) == 0                       # fresh episode
    # boot_obs is the PRE-reset observation, obs the post-reset one.
    assert not np.allclose(np.asarray(out.obs), np.asarray(out.boot_obs))


def test_make_jax_env_unknown():
    with pytest.raises(ValueError, match="no on-device"):
        make_jax_env("HalfCheetah-v4")


def test_jax_mountain_car_matches_gymnasium_dynamics():
    gymnasium = pytest.importorskip("gymnasium")
    from distributed_ddpg_tpu.envs.jax_envs import JaxMountainCar, MountainCarState

    genv = gymnasium.make("MountainCarContinuous-v0")
    gobs, _ = genv.reset(seed=5)
    jenv = JaxMountainCar()
    state = MountainCarState(
        pos=jnp.float32(gobs[0]), vel=jnp.float32(gobs[1]), t=jnp.int32(0)
    )
    rng = np.random.default_rng(11)
    key = jax.random.PRNGKey(2)
    for i in range(80):
        a = rng.uniform(-1, 1, 1).astype(np.float32)
        key, k = jax.random.split(key)
        out = jenv.step(state, jnp.asarray(a), k)
        gobs, grew, gterm, gtrunc, _ = genv.step(a)
        assert not (gterm or gtrunc)
        np.testing.assert_allclose(np.asarray(out.obs), gobs, atol=1e-5)
        np.testing.assert_allclose(float(out.reward), grew, atol=1e-5)
        assert not bool(out.done)
        state = out.state


def test_builtin_mountain_car_matches_gymnasium():
    gymnasium = pytest.importorskip("gymnasium")
    from distributed_ddpg_tpu.envs.mountain_car import MountainCarContinuous

    genv = gymnasium.make("MountainCarContinuous-v0")
    gobs, _ = genv.reset(seed=5)
    benv = MountainCarContinuous(seed=0)
    benv.reset(seed=0)
    benv._pos, benv._vel = float(gobs[0]), float(gobs[1])
    rng = np.random.default_rng(11)
    for _ in range(80):
        a = rng.uniform(-1, 1, 1).astype(np.float32)
        bobs, brew, bterm, btrunc, _ = benv.step(a)
        gobs, grew, gterm, gtrunc, _ = genv.step(a)
        np.testing.assert_allclose(bobs, gobs, atol=1e-6)
        np.testing.assert_allclose(brew, grew, atol=1e-6)
        assert (bterm, btrunc) == (gterm, gtrunc)


def test_jax_mountain_car_terminates_at_goal():
    from distributed_ddpg_tpu.envs.jax_envs import JaxMountainCar, MountainCarState

    env = JaxMountainCar()
    state = MountainCarState(
        pos=jnp.float32(0.449), vel=jnp.float32(0.05), t=jnp.int32(10)
    )
    out = env.step(state, jnp.ones(1), jax.random.PRNGKey(3))
    assert bool(out.terminated) and bool(out.done)
    assert float(out.reward) == pytest.approx(100.0 - 0.1)
    assert int(out.state.t) == 0                       # auto-reset happened
    assert float(out.boot_obs[0]) >= env.goal_position  # pre-reset next obs
    assert -0.6 <= float(out.obs[0]) <= -0.4            # fresh start


def test_ondevice_stores_zero_discount_on_termination():
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    cfg = _tiny_config(
        env_id="MountainCarContinuous-v0", num_actors=8, replay_min_size=4096
    )
    trainer = OnDeviceDDPG(cfg, chunk_size=128)
    # Plant every env just below the goal moving fast: the first step
    # terminates all of them.
    carry = trainer.carry
    env_state = jax.device_get(carry.env_state)
    env_state = type(env_state)(
        pos=jnp.full_like(env_state.pos, 0.449),
        vel=jnp.full_like(env_state.vel, 0.07),
        t=env_state.t,
    )
    trainer.carry = carry._replace(env_state=jax.device_put(env_state))
    trainer.run_chunk()
    rows = np.asarray(jax.device_get(trainer.carry.storage))
    size = int(jax.device_get(trainer.carry.size))
    obs_dim, act_dim = trainer.obs_dim, trainer.act_dim
    discount_col = obs_dim + act_dim + 1
    discounts = rows[:size, discount_col]
    # The first 8 stored rows are the terminal transitions -> discount 0;
    # later in-episode rows keep gamma.
    assert np.all(discounts[:8] == 0.0)
    assert np.any(discounts[8:] == np.float32(cfg.gamma))


def _tiny_config(**kw):
    base = dict(
        env_id="Pendulum-v1",
        backend="jax_ondevice",
        num_actors=8,
        batch_size=32,
        replay_capacity=4096,
        replay_min_size=64,
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        total_env_steps=2048,
        seed=0,
    )
    base.update(kw)
    return DDPGConfig(**base)


def test_ondevice_chunk_and_gate():
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    trainer = OnDeviceDDPG(_tiny_config(), chunk_size=4)
    # Chunk 1: 4*8 = 32 rows < replay_min_size=64 -> no learning yet.
    stats = trainer.run_chunk()
    host = trainer.finalize_stats(stats)
    assert trainer.env_steps == 32
    assert trainer.learn_steps == 0
    assert int(jax.device_get(trainer.carry.size)) == 32
    # Chunk 2: crosses the 64-row gate mid-chunk -> some but maybe not all
    # iterations learn.
    stats = trainer.run_chunk()
    host = trainer.finalize_stats(stats)
    assert trainer.learn_steps > 0
    assert np.isfinite(host["critic_loss"])
    assert int(jax.device_get(trainer.carry.train.step)) == trainer.learn_steps


def test_ondevice_episode_returns_and_replay_roundtrip():
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    trainer = OnDeviceDDPG(_tiny_config(num_actors=4), chunk_size=256)
    stats = trainer.run_chunk()   # 1024 env steps -> several 200-step episodes
    host = trainer.finalize_stats(stats)
    assert host["episodes"] >= 4
    assert host["episode_return"] < 0  # pendulum cost is negative

    d = trainer.replay_state_dict()
    assert d["packed"].shape[0] == int(d["size"]) > 0
    trainer2 = OnDeviceDDPG(_tiny_config(num_actors=4), chunk_size=256)
    trainer2.load_replay_state(d)
    assert int(jax.device_get(trainer2.carry.size)) == int(d["size"])
    np.testing.assert_allclose(
        np.asarray(jax.device_get(trainer2.carry.storage))[: int(d["size"])],
        d["packed"],
    )


def test_ondevice_warmup_gates():
    """The ring-fill warmup gate saturates at capacity, so an over-budget
    warmup must be rejected; warmup also applies to OU families when set
    explicitly (worker.py parity)."""
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    with pytest.raises(ValueError, match="warmup_uniform_steps"):
        OnDeviceDDPG(
            _tiny_config(warmup_uniform_steps=8192), chunk_size=4
        )  # capacity 4096
    trainer = OnDeviceDDPG(
        _tiny_config(warmup_uniform_steps=64), chunk_size=4
    )
    trainer.run_chunk()  # traces with the where-branch active


def test_ondevice_rejects_per_and_nstep():
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    with pytest.raises(ValueError, match="uniform replay only"):
        OnDeviceDDPG(_tiny_config(prioritized=True))
    with pytest.raises(ValueError, match="1-step"):
        OnDeviceDDPG(_tiny_config(n_step=3))


@pytest.mark.slow
def test_ondevice_runs_all_families():
    """The fully-fused backend (env + replay + learner in one XLA program)
    must compose with every algorithm family: the TD3 lax.cond-delayed
    updates and fold_in noise, and the D4PG categorical head, both trace
    cleanly inside the ondevice scan."""
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    for extra in (
        dict(twin_critic=True, policy_delay=2, target_noise=0.2),
        dict(distributional=True, num_atoms=21, v_min=-200.0, v_max=200.0),
        # SAC: on-device tanh-Gaussian sampling + jnp.where uniform warmup
        # + the temperature scalar riding the donated carry.
        dict(sac=True, warmup_uniform_steps=32),
    ):
        trainer = OnDeviceDDPG(_tiny_config(**extra), chunk_size=4)
        for _ in range(4):
            stats = trainer.run_chunk()
        host = trainer.finalize_stats(stats)
        assert np.isfinite(host["critic_loss"])
        assert trainer.learn_steps > 0
        if extra.get("sac"):
            import jax as _jax

            assert np.isfinite(
                float(_jax.device_get(trainer.state.log_alpha))
            )
