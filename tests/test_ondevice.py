"""On-device backend tests (envs/jax_envs.py + ondevice.py).

- Dynamics equivalence: JaxPendulum must reproduce the builtin numpy
  Pendulum (envs/pendulum.py) step-for-step from the same state/actions —
  the guarantee that `Pendulum-v1` results compare across backends.
- Auto-reset semantics: boundary flags, boot_obs vs post-reset obs.
- OnDeviceDDPG: chunk execution on the 8-device CPU mesh (conftest.py),
  replay fill accounting, learning gate at replay_min_size, finite metrics,
  episode-return extraction, checkpoint round-trip of the replay ring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs.jax_envs import JaxPendulum, make_jax_env
from distributed_ddpg_tpu.envs.pendulum import Pendulum


def test_jax_pendulum_matches_numpy_dynamics():
    from distributed_ddpg_tpu.envs.jax_envs import PendulumState

    jenv, nenv = JaxPendulum(), Pendulum(seed=0)
    nenv.reset(seed=3)
    th, thdot = nenv._state
    state = PendulumState(
        th=jnp.float32(th), thdot=jnp.float32(thdot), t=jnp.int32(0)
    )
    rng = np.random.default_rng(7)
    key = jax.random.PRNGKey(1)
    for i in range(60):
        a = rng.uniform(-2, 2, 1).astype(np.float32)
        key, k = jax.random.split(key)
        out = jenv.step(state, jnp.asarray(a), k)
        nobs, nrew, _, ntrunc, _ = nenv.step(a)
        assert not ntrunc
        np.testing.assert_allclose(np.asarray(out.obs), nobs, atol=1e-4)
        np.testing.assert_allclose(float(out.reward), nrew, atol=1e-4)
        assert not bool(out.done)
        state = out.state


def test_jax_pendulum_autoreset():
    env = JaxPendulum()
    key = jax.random.PRNGKey(0)
    state = env.init(key)
    state = state._replace(t=jnp.int32(env.max_episode_steps - 1))
    out = env.step(state, jnp.zeros(1), jax.random.PRNGKey(42))
    assert bool(out.done)
    assert int(out.state.t) == 0                       # fresh episode
    # boot_obs is the PRE-reset observation, obs the post-reset one.
    assert not np.allclose(np.asarray(out.obs), np.asarray(out.boot_obs))


def test_make_jax_env_unknown():
    with pytest.raises(ValueError, match="no on-device"):
        make_jax_env("HalfCheetah-v4")


def _tiny_config(**kw):
    base = dict(
        env_id="Pendulum-v1",
        backend="jax_ondevice",
        num_actors=8,
        batch_size=32,
        replay_capacity=4096,
        replay_min_size=64,
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        total_env_steps=2048,
        seed=0,
    )
    base.update(kw)
    return DDPGConfig(**base)


def test_ondevice_chunk_and_gate():
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    trainer = OnDeviceDDPG(_tiny_config(), chunk_size=4)
    # Chunk 1: 4*8 = 32 rows < replay_min_size=64 -> no learning yet.
    stats = trainer.run_chunk()
    host = trainer.finalize_stats(stats)
    assert trainer.env_steps == 32
    assert trainer.learn_steps == 0
    assert int(jax.device_get(trainer.carry.size)) == 32
    # Chunk 2: crosses the 64-row gate mid-chunk -> some but maybe not all
    # iterations learn.
    stats = trainer.run_chunk()
    host = trainer.finalize_stats(stats)
    assert trainer.learn_steps > 0
    assert np.isfinite(host["critic_loss"])
    assert int(jax.device_get(trainer.carry.train.step)) == trainer.learn_steps


def test_ondevice_episode_returns_and_replay_roundtrip():
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    trainer = OnDeviceDDPG(_tiny_config(num_actors=4), chunk_size=256)
    stats = trainer.run_chunk()   # 1024 env steps -> several 200-step episodes
    host = trainer.finalize_stats(stats)
    assert host["episodes"] >= 4
    assert host["episode_return"] < 0  # pendulum cost is negative

    d = trainer.replay_state_dict()
    assert d["packed"].shape[0] == int(d["size"]) > 0
    trainer2 = OnDeviceDDPG(_tiny_config(num_actors=4), chunk_size=256)
    trainer2.load_replay_state(d)
    assert int(jax.device_get(trainer2.carry.size)) == int(d["size"])
    np.testing.assert_allclose(
        np.asarray(jax.device_get(trainer2.carry.storage))[: int(d["size"])],
        d["packed"],
    )


def test_ondevice_rejects_per_and_nstep():
    from distributed_ddpg_tpu.ondevice import OnDeviceDDPG

    with pytest.raises(ValueError, match="uniform replay only"):
        OnDeviceDDPG(_tiny_config(prioritized=True))
    with pytest.raises(ValueError, match="1-step"):
        OnDeviceDDPG(_tiny_config(n_step=3))
