"""Invariant lint engine tests (distributed_ddpg_tpu/analysis/;
docs/ANALYSIS.md): known-good/known-bad fixture pairs per rule under
tests/lint_fixtures/, the suppression grammar, the JSON output schema,
the CLI exit-code contract, the gate scripts — and the self-run pinning
the live tree clean, fast (<5 s), and jax-free.

Everything here is tier-1: pure-stdlib engine, no backend, no device.
"""

import json
import subprocess
import sys
import time
from collections import Counter
from pathlib import Path

import pytest

from distributed_ddpg_tpu.analysis import RULES, run_lint
from distributed_ddpg_tpu.analysis.engine import (
    BAD_SUPPRESSION,
    PARSE_ERROR,
    UNUSED_SUPPRESSION,
    render_human,
    write_json,
)
from distributed_ddpg_tpu.tools import lint as lint_cli
from distributed_ddpg_tpu.tools import runs as runs_cli

TESTS = Path(__file__).resolve().parent
REPO = TESTS.parent
PKG = REPO / "distributed_ddpg_tpu"
FIX = TESTS / "lint_fixtures"

EXPECTED_RULES = {
    "collective-discipline",
    "timeout-discipline",
    "donation-safety",
    "typed-error",
    "lock-discipline",
    "observability-drift",
    "recompile-hazard",
    "exit-code-literal",
}


def lint_tree(name, **kw):
    root = FIX / name
    docs = root / "docs"
    return run_lint(root, docs_root=docs if docs.is_dir() else None, **kw)


# ---------------------------------------------------------------------------
# registry + fixture trees
# ---------------------------------------------------------------------------


def test_rule_registry_has_the_contract_rules():
    names = {r.name for r in RULES}
    assert EXPECTED_RULES <= names
    # Unique names: the suppression grammar and --rules filter key on them.
    assert len([r.name for r in RULES]) == len(names)
    assert all(r.doc for r in RULES)


def test_clean_tree_is_silent():
    result = lint_tree("clean")
    assert result.findings == []
    assert result.files >= 10


def test_dirty_tree_fires_every_rule_with_expected_counts():
    result = lint_tree("dirty")
    counts = Counter(f.rule for f in result.findings)
    assert counts == {
        "collective-discipline": 6,
        "timeout-discipline": 7,
        "donation-safety": 3,
        "typed-error": 2,
        "lock-discipline": 4,
        "observability-drift": 3,
        "recompile-hazard": 5,
        "exit-code-literal": 3,
    }
    # Nothing in the dirty tree is suppressed — every finding gates.
    assert len(result.unsuppressed) == len(result.findings) == 33


def test_dirty_tree_known_bad_locations():
    by_rule = {}
    for f in lint_tree("dirty").findings:
        by_rule.setdefault(f.rule, []).append(f)
    # donation-safety names the dead variable and the donating callee.
    msgs = [f.message for f in by_rule["donation-safety"]]
    assert any("`state`" in m and "step()" in m for m in msgs)
    assert any("`batch`" in m and "apply_batch()" in m for m in msgs)
    # The local-def factory idiom tracks the FULL multi-arg donate tuple:
    # reading position 4 (not just arg 0) after dispatch is flagged.
    assert any("`priorities`" in m and "chunk_step()" in m for m in msgs)
    # recompile-hazard covers all five jit-key hazard shapes.
    prog_msgs = [f.message for f in by_rule["recompile-hazard"]]
    assert any("loop body" in m and "`k`" in m for m in prog_msgs)
    assert any("@jax.jit on a def inside a loop body" in m for m in prog_msgs)
    assert any("one expression" in m for m in prog_msgs)
    assert any("static position 1" in m for m in prog_msgs)
    assert any("traced body of lax.fori_loop" in m for m in prog_msgs)
    # timeout-discipline reports the literal it saw.
    assert any("600s" in f.message for f in by_rule["timeout-discipline"])
    # observability-drift covers both metric drift and fault-grammar drift.
    paths = {f.path for f in by_rule["observability-drift"]}
    assert paths == {"metrics.py", "faults.py"}
    assert any("ghost" in f.message for f in by_rule["observability-drift"])
    # lock-discipline: the lambda body itself is never the finding — only
    # the sibling wait AFTER the deferred callback (bad_after_deferred).
    lock_lines = {f.line for f in by_rule["lock-discipline"]
                  if f.path == "serve/locks.py"}
    assert len(lock_lines) == 4
    # ...and the blocking queue.get is among them, by name.
    assert any("q.get()" in f.message for f in by_rule["lock-discipline"])
    # exit-code-literal: both the call form and the shadowing assignment.
    exit_msgs = [f.message for f in by_rule["exit-code-literal"]]
    assert any("78" in m and "_exit()" in m for m in exit_msgs)
    assert any("_EXIT_CODE" in m and "70" in m for m in exit_msgs)
    assert {f.path for f in by_rule["exit-code-literal"]} == {"runner.py"}


def test_doc_coupled_checks_silent_without_a_docs_tree(tmp_path):
    # Bare file set, no docs dir: doc-coupled rules stay silent — but an
    # existing docs dir MISSING a file is a finding.
    (tmp_path / "metrics.py").write_text(
        "class FooStats:\n"
        "    def snapshot(self):\n"
        "        return {\"foo_thing\": 1}\n"
    )
    (tmp_path / "faults.py").write_text('COMPONENTS = ("worker",)\n')
    assert run_lint(tmp_path, docs_root=None).findings == []
    docs = tmp_path / "docs"
    docs.mkdir()
    missing = run_lint(tmp_path, docs_root=docs).unsuppressed
    assert missing and all("not found" in f.message for f in missing)


def test_expand_slash_replaces_only_the_last_segment():
    from distributed_ddpg_tpu.analysis.rules import _expand_slash

    assert _expand_slash("transfer_pool_buffers/fence_waits") == [
        "transfer_pool_buffers", "transfer_pool_fence_waits",
    ]
    assert _expand_slash("replay_exchange_ms_p50/p95") == [
        "replay_exchange_ms_p50", "replay_exchange_ms_p95",
    ]


def test_rules_filter_scopes_the_run():
    result = lint_tree("dirty", rule_names=["timeout-discipline"])
    assert {f.rule for f in result.findings} == {"timeout-discipline"}
    assert len(result.findings) == 7
    assert result.rules == ["timeout-discipline"]


# ---------------------------------------------------------------------------
# suppression grammar
# ---------------------------------------------------------------------------


def test_reasoned_suppressions_suppress_inline_and_comment_only():
    result = run_lint(FIX / "suppress", paths=[FIX / "suppress" / "ok.py"])
    assert result.unsuppressed == []
    suppressed = [f for f in result.findings if f.suppressed]
    assert len(suppressed) == 2  # inline + comment-only coverage
    assert all(f.suppression_reason.startswith("fixture") for f in suppressed)


def test_reasonless_suppression_keeps_the_finding_and_is_reported():
    result = run_lint(FIX / "suppress", paths=[FIX / "suppress" / "bad.py"])
    rules = [f.rule for f in result.unsuppressed]
    assert "timeout-discipline" in rules  # the finding stays live
    assert BAD_SUPPRESSION in rules       # and the bad escape is its own
    assert UNUSED_SUPPRESSION not in rules


def test_unused_suppression_is_reported():
    result = run_lint(FIX / "suppress", paths=[FIX / "suppress" / "unused.py"])
    assert [f.rule for f in result.unsuppressed] == [UNUSED_SUPPRESSION]


def test_grammar_inside_a_docstring_is_not_a_suppression():
    result = run_lint(
        FIX / "suppress", paths=[FIX / "suppress" / "docstring.py"]
    )
    rules = [f.rule for f in result.unsuppressed]
    assert rules == ["timeout-discipline"]  # live — and no unused-suppression


def test_rules_subset_does_not_report_foreign_suppressions():
    # Under a --rules subset, suppressions of inactive rules cannot be
    # proven stale — only a full-registry run may call them unused.
    result = run_lint(
        FIX / "suppress", paths=[FIX / "suppress" / "ok.py"],
        rule_names=["lock-discipline"],
    )
    assert result.findings == []


def test_suppression_of_unknown_rule_is_reported(tmp_path):
    src = tmp_path / "typo.py"
    src.write_text("X = 1  # lint: ok(donation-safty): typo'd rule name\n")
    result = run_lint(tmp_path, paths=[src])
    assert [f.rule for f in result.unsuppressed] == [BAD_SUPPRESSION]
    assert "unknown rule" in result.unsuppressed[0].message


def test_malformed_suppression_is_reported(tmp_path):
    src = tmp_path / "malformed.py"
    src.write_text(
        "import time\n\n\n"
        "def f():\n"
        "    time.sleep(5)  # lint: ok(timeout-discipline) forgot colon\n"
    )
    result = run_lint(tmp_path, paths=[src])
    rules = sorted(f.rule for f in result.unsuppressed)
    assert rules == [BAD_SUPPRESSION, "timeout-discipline"]
    assert any("malformed" in f.message for f in result.unsuppressed)


def test_suppression_matches_anywhere_in_the_statement_span(tmp_path):
    # A multi-line call's only room for the comment may be its closing
    # line; the finding anchors to the call's FIRST line but the span
    # covers the whole statement.
    src = tmp_path / "span.py"
    src.write_text(
        "import time\n\n\n"
        "def f():\n"
        "    time.sleep(\n"
        "        5,\n"
        "    )  # lint: ok(timeout-discipline): fixture reason\n"
    )
    result = run_lint(tmp_path, paths=[src])
    assert result.unsuppressed == []
    assert [f.suppressed for f in result.findings] == [True]
    assert result.findings[0].line == 5
    assert result.findings[0].end_line == 7


def test_suppression_covers_expression_anchored_finding_in_statement(tmp_path):
    # donation-safety anchors to the READ expression, which may sit lines
    # above the only place with room for the comment (the closing paren).
    # The suppression span is the whole enclosing simple statement — and
    # a covered finding must not double-report as unused-suppression.
    src = tmp_path / "donate.py"
    src.write_text(
        "import jax\n\n"
        "step = jax.jit(lambda s: s, donate_argnums=(0,))\n\n\n"
        "def run(state, combine):\n"
        "    out = step(state)\n"
        "    r = combine(\n"
        "        state,\n"
        "    )  # lint: ok(donation-safety): fixture reason\n"
        "    return r, out\n"
    )
    result = run_lint(tmp_path, paths=[src])
    assert result.unsuppressed == [], "\n".join(
        f.render() for f in result.unsuppressed
    )
    assert [f.rule for f in result.findings] == ["donation-safety"]
    assert result.findings[0].suppressed


def test_field_suppression_does_not_cover_sibling_fields(tmp_path):
    # A *Stats snapshot dict is ONE simple statement; if suppressions
    # matched the statement span, one per-field escape would silently
    # cover every sibling field's future drift. Field findings are exact:
    # the comment suppresses its own line's key only.
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "| foo | `foo_documented` | docs |\n"
    )
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "runs.py").write_text("FAMILIES = ['foo_']\n")
    (tmp_path / "metrics.py").write_text(
        "class FooStats:\n"
        "    def snapshot(self):\n"
        "        return {\n"
        "            'foo_documented': 1,\n"
        "            'foo_undoc_a': 2,  "
        "# lint: ok(observability-drift): fixture reason\n"
        "            'foo_undoc_b': 3,\n"
        "        }\n"
    )
    result = run_lint(tmp_path, docs_root=tmp_path / "docs")
    live = [f for f in result.findings if not f.suppressed]
    assert [f.rule for f in live] == ["observability-drift"]
    assert "foo_undoc_b" in live[0].message
    sup = [f for f in result.findings if f.suppressed]
    assert len(sup) == 1 and "foo_undoc_a" in sup[0].message


def test_directory_scans_skip_test_trees(tmp_path):
    # The rules enforce NON-test hot-path discipline: linting a repo root
    # must not drown in test-code waits or the deliberately dirty fixture
    # trees. An explicitly named test file still lints.
    (tmp_path / "tests").mkdir()
    bad = "import time\n\n\ndef f():\n    time.sleep(600)\n"
    (tmp_path / "tests" / "test_waits.py").write_text(bad)
    (tmp_path / "tests" / "conftest.py").write_text(bad)
    (tmp_path / "mod.py").write_text("X = 1\n")
    result = run_lint(tmp_path)
    assert result.files == 1
    assert result.findings == []
    explicit = run_lint(
        tmp_path, paths=[tmp_path / "tests" / "test_waits.py"]
    )
    assert [f.rule for f in explicit.findings] == ["timeout-discipline"]


def test_nested_dispatch_lock_reports_each_violation_once(tmp_path):
    src = tmp_path / "nested.py"
    src.write_text(
        "def f(a, b):\n"
        "    with a.dispatch_lock:\n"
        "        with b.dispatch_lock:\n"
        "            b.q.get()\n"
    )
    result = run_lint(tmp_path, paths=[src])
    assert len(result.findings) == 1
    assert result.findings[0].rule == "lock-discipline"


def test_donation_safety_tracks_annotated_assignments(tmp_path):
    src = tmp_path / "ann.py"
    src.write_text(
        "import jax\n"
        "from typing import Callable\n\n\n"
        "class L:\n"
        "    def setup(self):\n"
        "        self.step: Callable = jax.jit(_step, donate_argnums=(0,))\n\n"
        "    def run(self, state):\n"
        "        out = self.step(state)\n"
        "        return state.params\n"
    )
    result = run_lint(tmp_path, paths=[src])
    assert [f.rule for f in result.findings] == ["donation-safety"]
    assert "`state.params`" in result.findings[0].message


def test_field_suppression_does_not_mask_class_level_renderer_drift(tmp_path):
    # The family-renderer finding anchors to the class HEADER line, so a
    # reasoned field-level suppression inside the body cannot swallow it
    # via statement-span matching.
    (tmp_path / "metrics.py").write_text(
        "class FooStats:\n"
        "    def snapshot(self):\n"
        "        return {\n"
        '            "foo_thing": 1,'
        "  # lint: ok(observability-drift): fixture reason\n"
        "        }\n"
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "OBSERVABILITY.md").write_text("no rows\n")
    (docs / "RESILIENCE.md").write_text("## Failure matrix\n")
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "runs.py").write_text("# renders nothing\n")
    result = run_lint(tmp_path, docs_root=docs)
    live = [f.message for f in result.unsuppressed]
    assert any("no renderer reference" in m for m in live), live


def test_one_comment_may_cover_several_rules(tmp_path):
    src = tmp_path / "multi.py"
    src.write_text(
        "import time\n\n\n"
        "def f(t):\n"
        "    time.sleep(5)  "
        "# lint: ok(timeout-discipline, lock-discipline): fixture reason\n"
    )
    result = run_lint(tmp_path, paths=[src])
    assert result.unsuppressed == []  # suppressed, and no unused report
    assert [f.suppressed for f in result.findings] == [True]


# ---------------------------------------------------------------------------
# engine mechanics: parse errors, JSON schema, human rendering
# ---------------------------------------------------------------------------


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    result = run_lint(tmp_path, paths=[bad])
    assert [f.rule for f in result.findings] == [PARSE_ERROR]
    assert result.unsuppressed  # a non-parsing file gates


def test_json_schema(tmp_path):
    result = lint_tree("dirty")
    out = tmp_path / "findings.json"
    write_json(result, out)
    obj = json.loads(out.read_text())
    assert obj["version"] == 1
    assert set(obj["counts"]) == {"files", "findings", "suppressed"}
    assert obj["counts"]["findings"] == 33
    assert obj["counts"]["suppressed"] == 0
    assert sorted(obj["rules"]) == sorted(r.name for r in RULES)
    assert isinstance(obj["elapsed_s"], float)
    for f in obj["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "end_line",
                          "message", "suppressed", "suppression_reason"}
        assert "/" not in f["path"] or "\\" not in f["path"]


def test_human_rendering_has_locations_and_summary():
    result = lint_tree("dirty")
    text = render_human(result)
    assert "transfer/waits.py:" in text
    assert text.splitlines()[-1].endswith("s")  # "... in N.NNs" summary


# ---------------------------------------------------------------------------
# CLI exit-code contract
# ---------------------------------------------------------------------------


def test_cli_exit_0_on_clean_tree(capsys):
    rc = lint_cli.main([
        str(FIX / "clean"), "--root", str(FIX / "clean"),
        "--docs", str(FIX / "clean" / "docs"), "--quiet",
    ])
    assert rc == 0
    assert "0 findings" in capsys.readouterr().out


def test_cli_exit_2_on_dirty_tree(capsys):
    rc = lint_cli.main([
        str(FIX / "dirty"), "--root", str(FIX / "dirty"),
        "--docs", str(FIX / "dirty" / "docs"),
    ])
    assert rc == 2
    err = capsys.readouterr().err
    assert "lint: FAIL" in err


def test_cli_usage_errors_exit_1(capsys, tmp_path):
    assert lint_cli.main(["--rules", "no-such-rule"]) == 1
    assert lint_cli.main([str(FIX / "does-not-exist")]) == 1
    # A path matching no .py files must error, not pass as a clean run.
    (tmp_path / "README.md").write_text("no python here\n")
    assert lint_cli.main([str(tmp_path), "--root", str(tmp_path)]) == 1
    assert "no Python files" in capsys.readouterr().err


def test_cli_subpath_target_keeps_package_anchoring(capsys):
    # Linting one file inside the package must anchor rule path-scoping
    # to the PACKAGE root: parallel/multihost.py stays the exempt module,
    # not a freshly-rooted "multihost.py" full of collective findings.
    rc = lint_cli.main([str(PKG / "parallel" / "multihost.py"), "--quiet"])
    assert rc == 0, capsys.readouterr().out


def test_cli_repo_anchored_root_keeps_rule_scoping(capsys):
    # --root <repo> makes every relpath start with distributed_ddpg_tpu/;
    # rulepath strips the package prefix so the multihost exemption,
    # typed-error subsystem scoping, and metrics.py lookups still hold.
    rc = lint_cli.main([
        "--root", str(REPO), "--docs", str(REPO / "docs"),
        str(PKG), "--quiet",
    ])
    assert rc == 0, capsys.readouterr().out


def test_cli_path_outside_root_is_a_usage_error(tmp_path, capsys):
    stray = tmp_path / "stray.py"
    stray.write_text("X = 1\n")
    rc = lint_cli.main([str(stray), "--root", str(PKG)])
    assert rc == 1
    assert "outside the lint root" in capsys.readouterr().err


def test_cli_list_rules(capsys):
    assert lint_cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in EXPECTED_RULES:
        assert name in out


# ---------------------------------------------------------------------------
# tools.runs lint subcommand (the CI-box digest renderer)
# ---------------------------------------------------------------------------


def test_runs_lint_renders_fail_digest(tmp_path, capsys):
    out = tmp_path / "findings.json"
    write_json(lint_tree("dirty"), out)
    rc = runs_cli.main(["lint", str(out)])
    assert rc == 2
    text = capsys.readouterr().out
    assert "LINT FAIL" in text
    assert "timeout-discipline" in text
    assert "transfer/waits.py:" in text


def test_runs_lint_renders_pass_digest(tmp_path, capsys):
    out = tmp_path / "findings.json"
    write_json(lint_tree("clean"), out)
    rc = runs_cli.main(["lint", str(out)])
    assert rc == 0
    assert "LINT PASS" in capsys.readouterr().out


def test_runs_lint_missing_file_exits_1(tmp_path, capsys):
    assert runs_cli.main(["lint", str(tmp_path / "nope.json")]) == 1


def test_runs_lint_non_object_json_exits_1(tmp_path, capsys):
    trunc = tmp_path / "trunc.json"
    trunc.write_text("[]\n")
    assert runs_cli.main(["lint", str(trunc)]) == 1
    assert "not a findings object" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# self-run: the shipped tree is clean, fast, and jax-free
# ---------------------------------------------------------------------------


def test_self_run_live_tree_is_clean_and_fast():
    # CPU time, not wall clock: the <5s budget is about the engine's own
    # cost, and the CI box's documented contention (CHANGES.md PR 9:
    # ~60% wall slowdowns under load) must not turn tier-1 red on it.
    t0 = time.process_time()
    result = run_lint(PKG, docs_root=REPO / "docs")
    elapsed = time.process_time() - t0
    assert result.unsuppressed == [], "\n".join(
        f.render() for f in result.unsuppressed
    )
    # Suppressions in the live tree must all carry reasons (engine enforces)
    # and there are known, documented ones — not zero, not an explosion.
    assert 0 < sum(f.suppressed for f in result.findings) < 20
    assert elapsed < 5.0, f"lint took {elapsed:.1f}s (budget 5s)"


def test_cli_never_imports_jax():
    # A clean interpreter (not this conftest-jax'd one): the engine must
    # lint the fixture trees without jax ever landing in sys.modules.
    code = (
        "import sys\n"
        "from distributed_ddpg_tpu.tools import lint\n"
        f"rc = lint.main([{str(FIX / 'clean')!r}, '--root', "
        f"{str(FIX / 'clean')!r}, '--docs', "
        f"{str(FIX / 'clean' / 'docs')!r}, '--quiet'])\n"
        "assert rc == 0, rc\n"
        "assert 'jax' not in sys.modules, 'lint imported jax'\n"
    )
    subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, check=True, timeout=60,
    )


# ---------------------------------------------------------------------------
# gate scripts
# ---------------------------------------------------------------------------


def test_lint_gate_script_passes_fixture_tree(tmp_path):
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "lint_gate.sh"), "--quiet",
         "--root", str(FIX / "clean"), "--docs",
         str(FIX / "clean" / "docs"), str(FIX / "clean")],
        env={"PATH": "/usr/bin:/bin:/usr/local/bin",
             "LINT_JSON": str(tmp_path / "findings.json")},
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert (tmp_path / "findings.json").is_file()


def test_lint_gate_script_fails_on_findings(tmp_path):
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "lint_gate.sh"), "--quiet",
         "--root", str(FIX / "dirty"), "--docs",
         str(FIX / "dirty" / "docs"), str(FIX / "dirty")],
        env={"PATH": "/usr/bin:/bin:/usr/local/bin",
             "LINT_JSON": str(tmp_path / "findings.json")},
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2, (proc.stdout, proc.stderr)
    assert "tools.runs lint" in proc.stderr  # points at the digest renderer


def test_lint_gate_script_skips_without_analysis_package(tmp_path):
    # Old baselines predate the linter: the gate must SKIP, not fail.
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    gate = scripts / "lint_gate.sh"
    gate.write_text((REPO / "scripts" / "lint_gate.sh").read_text())
    proc = subprocess.run(
        ["bash", str(gate)],
        env={"PATH": "/usr/bin:/bin:/usr/local/bin"},
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    assert "SKIP" in proc.stderr


def test_ci_gate_lint_prestep_runs_before_usage_check():
    # `ci_gate.sh --lint` with no candidate: the lint pre-step runs (on
    # the real package — this is the wiring pin) and the usage error
    # afterwards exits 1, not the lint gate's 2.
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "ci_gate.sh"), "--lint"],
        env={"PATH": "/usr/bin:/bin:/usr/local/bin"},
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    assert "files," in proc.stdout  # the lint summary line ran first


# ---------------------------------------------------------------------------
# --changed-only (the sub-second pre-commit mode; docs/ANALYSIS.md)
# ---------------------------------------------------------------------------


def _git(repo, *args):
    subprocess.run(
        ["git", "-C", str(repo), "-c", "user.name=t", "-c",
         "user.email=t@t", *args],
        check=True, capture_output=True, timeout=30,
    )


@pytest.fixture()
def lint_repo(tmp_path):
    """A tiny git repo: one clean file, one file carrying the 5 known
    recompile-hazard findings — both committed, so HEAD is the baseline."""
    repo = (tmp_path / "repo").resolve()
    (repo / "replay").mkdir(parents=True)
    (repo / "replay" / "donate.py").write_text(
        (FIX / "clean" / "replay" / "donate.py").read_text(),
        encoding="utf-8",
    )
    (repo / "progs.py").write_text(
        (FIX / "dirty" / "progs.py").read_text(), encoding="utf-8"
    )
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-q", "-m", "seed")
    return repo


def test_changed_only_nothing_changed(lint_repo, capsys):
    rc = lint_cli.main(["--changed-only", "HEAD", "--root", str(lint_repo)])
    assert rc == 0
    assert "nothing to lint" in capsys.readouterr().out


def test_changed_only_scopes_to_the_diff(lint_repo, capsys):
    # progs.py carries 5 recompile-hazard findings, but only the CLEAN
    # file changed: the scoped run must not see them.
    donate = lint_repo / "replay" / "donate.py"
    donate.write_text(donate.read_text() + "\n# touched\n",
                      encoding="utf-8")
    rc = lint_cli.main(["--changed-only", "HEAD", "--root", str(lint_repo)])
    assert rc == 0
    capsys.readouterr()
    # Once the dirty file changes too, its findings gate the scoped run.
    progs = lint_repo / "progs.py"
    progs.write_text(progs.read_text() + "\n# touched\n", encoding="utf-8")
    rc = lint_cli.main(["--changed-only", "HEAD", "--root", str(lint_repo)])
    assert rc == 2
    assert "recompile-hazard" in capsys.readouterr().out


def test_changed_only_sees_untracked_files(lint_repo):
    # A new file must lint BEFORE its first commit.
    (lint_repo / "replay" / "fresh.py").write_text(
        (FIX / "dirty" / "progs.py").read_text(), encoding="utf-8"
    )
    rc = lint_cli.main(["--changed-only", "HEAD", "--root", str(lint_repo)])
    assert rc == 2


def test_changed_only_bad_ref_errors(lint_repo, capsys):
    rc = lint_cli.main(
        ["--changed-only", "no-such-ref", "--root", str(lint_repo)]
    )
    assert rc == 1
    assert "--changed-only" in capsys.readouterr().err


def test_recompile_hazard_nested_loop_reports_once(tmp_path):
    # ast.walk scans the inner loop once per ancestor loop; the hazard
    # must still report once, keeping the richer (captured-loop-var)
    # message.
    (tmp_path / "nested.py").write_text(
        "import jax\n\n\n"
        "def f(xs):\n"
        "    for i in range(2):\n"
        "        for k in range(3):\n"
        "            g = jax.jit(lambda x: x * k)\n"
        "            xs = g(xs)\n"
        "    return xs\n",
        encoding="utf-8",
    )
    result = run_lint(tmp_path, rule_names=["recompile-hazard"])
    msgs = [f.message for f in result.findings]
    assert len(msgs) == 1
    assert "captures loop variable `k`" in msgs[0]


def test_recompile_hazard_skips_deferred_builders(tmp_path):
    # A def (or lambda) inside a loop DEFERS execution — the
    # ProgramSpec-builder idiom must not gate; and partial(jax.jit, ...)
    # invoked inline only BUILDS the wrapper (the sanctioned bind-once
    # factory), it traces nothing.
    (tmp_path / "deferred.py").write_text(
        "import jax\n"
        "from functools import partial\n\n\n"
        "def make_specs(fns):\n"
        "    specs = []\n"
        "    for fn in fns:\n"
        "        def build(fn=fn):\n"
        "            return jax.jit(fn)\n"
        "        specs.append(build)\n"
        "        deferred = lambda: jax.jit(fn)\n"
        "        specs.append(deferred)\n"
        "    return specs\n\n\n"
        "class Holder:\n"
        "    def __init__(self, step):\n"
        "        self.step = partial(jax.jit, donate_argnums=(0,))(step)\n",
        encoding="utf-8",
    )
    result = run_lint(tmp_path, rule_names=["recompile-hazard"])
    assert [f.message for f in result.findings] == []


def test_changed_only_intersects_explicit_paths(lint_repo, capsys):
    # Explicit path args compose as a FILTER within the changed set: a
    # pre-commit hook scoped to one subsystem must not fail on unrelated
    # changed files elsewhere in the tree.
    for name in ("replay/donate.py", "progs.py"):
        p = lint_repo / name
        p.write_text(p.read_text() + "\n# touched\n", encoding="utf-8")
    rc = lint_cli.main(
        ["--changed-only", "HEAD", "--root", str(lint_repo),
         str(lint_repo / "replay")]
    )
    assert rc == 0  # the dirty progs.py changed too, but is out of scope
    capsys.readouterr()
    rc = lint_cli.main(
        ["--changed-only", "HEAD", "--root", str(lint_repo),
         str(lint_repo / "progs.py")]
    )
    assert rc == 2
    assert "recompile-hazard" in capsys.readouterr().out


def test_changed_only_explicit_scope_nothing_changed(lint_repo, capsys):
    # Only the out-of-scope file changed: the scoped run lints nothing
    # and says so (exit 0), instead of failing on the unrelated change.
    progs = lint_repo / "progs.py"
    progs.write_text(progs.read_text() + "\n# touched\n", encoding="utf-8")
    rc = lint_cli.main(
        ["--changed-only", "HEAD", "--root", str(lint_repo),
         str(lint_repo / "replay")]
    )
    assert rc == 0
    assert "nothing to lint" in capsys.readouterr().out


def test_git_changed_files_diff_relative_config(lint_repo):
    # Under `git config diff.relative true`, `git diff --name-only` from
    # a subdir prints SUBDIR-relative paths: the diff must run at the
    # toplevel so joining against it stays correct — a mis-join here
    # silently lints nothing and reads as green.
    from distributed_ddpg_tpu.analysis.engine import git_changed_files

    _git(lint_repo, "config", "diff.relative", "true")
    donate = lint_repo / "replay" / "donate.py"
    donate.write_text(donate.read_text() + "\n# touched\n", encoding="utf-8")
    changed = git_changed_files(lint_repo / "replay", "HEAD")
    assert changed == [str(donate)]


def test_git_changed_files_untracked_from_subdir(lint_repo):
    # `git ls-files --others` prints cwd-relative paths: untracked files
    # must still resolve when the lint root sits DEEPER than the git
    # toplevel (the default package-root invocation).
    from distributed_ddpg_tpu.analysis.engine import git_changed_files

    fresh = lint_repo / "replay" / "fresh.py"
    fresh.write_text("x = 1\n", encoding="utf-8")
    changed = git_changed_files(lint_repo / "replay", "HEAD")
    assert changed == [str(fresh)]
