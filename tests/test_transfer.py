"""Unified transfer scheduler tests (transfer/; docs/TRANSFER.md).

Covers: work-class fair queuing (anti-starvation — prefetch latency stays
bounded under an ingest flood and vice versa), the lockstep lane's strict
FIFO + absolute priority, bounded scheduler-thread restart under an
injected `transfer:dispatch:crash` fault (and TransferError past the
budget), inline d2h accounting, the host-buffer pool's fencing, the
adaptive-coalesce controller's grow/shrink rules, and — the tier-1 CPU
smoke — a short scheduler-enabled train run whose `transfer_*` snapshot
must be present and self-consistent in every train record, plus a chaos
run injecting a scheduler-thread crash through the real train path.
"""

import json
import threading
import time

import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.faults import FaultPlan
from distributed_ddpg_tpu.transfer import (
    AdaptiveCoalesce,
    HostBufferPool,
    TransferError,
    TransferScheduler,
)

# --------------------------------------------------------------------------
# scheduler core
# --------------------------------------------------------------------------


def test_submit_runs_and_returns_result():
    s = TransferScheduler().start()
    try:
        assert s.submit("ingest", lambda: 41 + 1).result(timeout=5) == 42
        snap = s.snapshot()
        assert snap["transfer_dispatches"] == 1
        assert snap["transfer_ingest_items"] == 1
    finally:
        s.close()


def test_item_exception_fails_ticket_not_scheduler():
    s = TransferScheduler().start()
    try:
        t = s.submit("ingest", lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            t.result(timeout=5)
        # The scheduler survived — later items still run.
        assert s.submit("prefetch", lambda: "ok").result(timeout=5) == "ok"
        assert s.alive
    finally:
        s.close()


def test_lockstep_lane_is_fifo_and_preempts():
    """Lockstep items run in submission order and ahead of a backlog of
    bulk items — the collective-order invariant multi-host depends on."""
    s = TransferScheduler().start()
    order = []
    gate = threading.Event()
    try:
        # Head-of-line blocker so everything below queues behind it.
        s.submit("ingest", lambda: gate.wait(10))
        for i in range(4):
            s.submit("ingest", lambda i=i: order.append(("ingest", i)))
        ticks = [
            s.submit("lockstep", lambda i=i: order.append(("beat", i)))
            for i in range(3)
        ]
        gate.set()
        for t in ticks:
            t.result(timeout=5)
        beats = [e for e in order if e[0] == "beat"]
        assert beats == [("beat", 0), ("beat", 1), ("beat", 2)]
        # All beats ran before any queued ingest item got a turn.
        assert order[:3] == beats, order
    finally:
        s.close()


def test_fair_queue_anti_starvation():
    """Under a sustained ingest flood of slow items, a prefetch item's
    queue latency stays bounded by ~one in-flight item, not the flood."""
    item_s = 0.03
    s = TransferScheduler().start()
    try:
        stop = threading.Event()

        def slow_ingest():
            time.sleep(item_s)
            return 1 << 20  # pretend 1MB moved

        def keep_flooding():
            # Maintain a deep ingest backlog the whole test.
            for _ in range(200):
                if stop.is_set():
                    return
                while not stop.is_set():
                    depths = s.queue_depths()
                    if depths["ingest"] < 8:
                        break
                    time.sleep(0.002)
                s.submit("ingest", slow_ingest)

        flooder = threading.Thread(target=keep_flooding, daemon=True)
        flooder.start()
        time.sleep(5 * item_s)  # flood is established
        latencies = []
        for _ in range(5):
            t0 = time.perf_counter()
            s.submit(
                "prefetch", lambda: time.sleep(item_s), nbytes=1 << 20
            ).result(timeout=10)
            latencies.append(time.perf_counter() - t0)
            time.sleep(item_s)
        stop.set()
        flooder.join(timeout=5)
        # Bound: own service time + at most ~2 in-flight/fair-share items
        # (generous margin for CI noise). A FIFO queue behind an 8-deep
        # flood would exceed this several-fold.
        assert max(latencies) < 6 * item_s, latencies
    finally:
        s.close()


def test_injected_crash_recovers_transparently_within_budget():
    """transfer:dispatch:crash@k kills the scheduler THREAD before the
    item runs; within the restart budget the crash must be TRANSPARENT
    to submitters — the in-flight item requeues and runs on the
    restarted thread (a prefetch h2d or lockstep beat must not die
    because the scheduler hiccuped)."""
    plan = FaultPlan.parse("transfer:dispatch:crash@1", seed=0)
    s = TransferScheduler(
        fault=plan.site("transfer", "dispatch"), max_restarts=2
    ).start()
    try:
        t1 = s.submit("prefetch", lambda: "ran")
        assert t1.result(timeout=10) == "ran"
        assert s.restarts == 1 and s.alive
        # The restarted thread keeps serving.
        assert s.submit("ingest", lambda: "more").result(timeout=5) == "more"
    finally:
        s.close()


def test_injected_crash_loop_exhausts_budget_then_transfer_error():
    """Past max_restarts the failure is structural: the stuck item fails
    with the real exception, the scheduler declares itself dead, and all
    pending + future work raises TransferError — the _IngestShipper
    bounded-restart contract, scheduler-shaped."""
    from distributed_ddpg_tpu.faults import InjectedFault

    plan = FaultPlan.parse(
        "transfer:dispatch:crash@1;transfer:dispatch:crash@2;"
        "transfer:dispatch:crash@3",
        seed=0,
    )
    s = TransferScheduler(
        fault=plan.site("transfer", "dispatch"), max_restarts=2
    ).start()
    try:
        # The item requeues through crashes 1 and 2; crash 3 exhausts the
        # budget and the item finally fails with the injected fault.
        t1 = s.submit("ingest", lambda: "never")
        with pytest.raises(InjectedFault):
            t1.result(timeout=10)
        assert s.restarts == 2
        deadline = time.monotonic() + 5
        while s.alive and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not s.alive
        with pytest.raises(TransferError):
            s.submit("ingest", lambda: "refused")
    finally:
        s.close()


def test_run_inline_accounts_d2h():
    s = TransferScheduler().start()
    try:
        out = s.run_inline(
            "d2h", lambda: np.zeros(1024, np.float32),
            nbytes_of=lambda r: r.nbytes, label="params_d2h",
        )
        assert out.shape == (1024,)
        snap = s.snapshot()
        assert snap["transfer_d2h_items"] == 1
        assert snap["transfer_d2h_bytes"] == 4096
        # Inline d2h is not a scheduled dispatch.
        assert snap["transfer_dispatches"] == 0
    finally:
        s.close()


def test_close_fails_pending_tickets():
    s = TransferScheduler().start()
    gate = threading.Event()
    s.submit("ingest", lambda: gate.wait(10))
    t = s.submit("ingest", lambda: "queued")
    s.close(timeout=0.2)
    gate.set()
    with pytest.raises(TransferError):
        t.result(timeout=5)


# --------------------------------------------------------------------------
# adaptive coalesce controller
# --------------------------------------------------------------------------


def test_adaptive_grows_on_backlog_and_shrinks_on_stall():
    c = AdaptiveCoalesce(hi=8, block_size=64)
    assert c.cap() == 1
    # Sustained backlog: cap doubles toward the ceiling.
    c.observe_ship(1, 0.001, queue_rows=10 * 64)
    assert c.cap() == 2
    c.observe_ship(2, 0.002, queue_rows=10 * 64)
    c.observe_ship(4, 0.004, queue_rows=10 * 64)
    assert c.cap() == 8
    c.observe_ship(8, 0.008, queue_rows=10 * 64)
    assert c.cap() == 8  # clamped at hi
    # Dispatch stall (per-block time >> EWMA): shrink.
    c.observe_ship(8, 8 * 0.1, queue_rows=10 * 64)
    assert c.cap() == 4
    assert c.grows >= 3 and c.shrinks == 1
    snap = c.snapshot()
    assert snap["transfer_coalesce_cap"] == 4
    assert snap["transfer_coalesce_shrinks"] == 1


def test_adaptive_idle_queue_keeps_cap():
    c = AdaptiveCoalesce(hi=8, block_size=64)
    for _ in range(5):
        c.observe_ship(1, 0.001, queue_rows=0)
    assert c.cap() == 1 and c.grows == 0


# --------------------------------------------------------------------------
# host buffer pool
# --------------------------------------------------------------------------


class _Fence:
    def __init__(self):
        self.ev = threading.Event()
        self.waited = False

    def block_until_ready(self):
        self.waited = True
        self.ev.wait(5)


def test_host_pool_recycles_after_fence():
    pool = HostBufferPool(width=4, depth=2)
    a = pool.acquire(8)
    b = pool.acquire(8)
    assert a is not b and pool.allocations == 2
    fence = _Fence()
    fence.ev.set()
    pool.commit(a, fence)
    c = pool.acquire(8)  # depth reached: waits the (ready) fence
    assert c is a and fence.waited
    assert pool.allocations == 2  # steady state: no new allocation
    pool.commit(b, None)
    assert pool.acquire(8) is b
    # Distinct shapes pool independently.
    d = pool.acquire(16)
    assert d.shape == (16, 4) and pool.allocations == 3


# --------------------------------------------------------------------------
# tier-1 CPU smoke: scheduler-enabled train run, transfer_* snapshot
# --------------------------------------------------------------------------


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            if line.strip().startswith("{"):
                out.append(json.loads(line))
    return out


def _smoke_config(tmp_path, **kw):
    return DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=1,
        total_env_steps=2_500,
        replay_min_size=256,
        replay_capacity=20_000,
        eval_every=0,
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        log_path=str(tmp_path / "m.jsonl"),
        **kw,
    )


# Re-tiered to slow (ISSUE 15 tier-1 budget): 59s compile-dominated train smoke; test_train_scheduler_off_still_runs
# keeps the tier-1 transfer train smoke
@pytest.mark.slow
def test_train_smoke_transfer_snapshot_present_and_consistent(tmp_path):
    """Acceptance smoke (ISSUE 5): a short scheduler-enabled CPU train run
    emits the transfer_* family in its records, and the numbers are
    self-consistent — dispatches equal the per-class item sum, the
    adaptive cap stays inside [1, ingest_coalesce], ingest actually
    flowed through the scheduler, and the final record still carries the
    classic ingest_* digest alongside."""
    from distributed_ddpg_tpu.train import train_jax

    cfg = _smoke_config(tmp_path)
    assert cfg.transfer_scheduler  # the production default under test
    out = train_jax(cfg)
    assert out["learner_steps"] > 0

    recs = _records(cfg.log_path)
    trains = [r for r in recs if r["kind"] == "train"]
    assert trains, "no train records logged"
    for r in trains:
        for key in (
            "transfer_dispatches", "transfer_restarts",
            "transfer_ingest_items", "transfer_ingest_bytes",
            "transfer_ingest_ms", "transfer_ingest_p95",
            "transfer_prefetch_items", "transfer_d2h_items",
            "transfer_lockstep_items", "transfer_queue_ingest",
            "transfer_coalesce_cap", "transfer_coalesce_grows",
            "transfer_coalesce_shrinks", "transfer_pool_buffers",
        ):
            assert key in r, f"{key} missing from train record"
        assert r["transfer_dispatches"] == (
            r["transfer_ingest_items"]
            + r["transfer_prefetch_items"]
            + r["transfer_lockstep_items"]
        )
        assert 1 <= r["transfer_coalesce_cap"] <= cfg.ingest_coalesce
        assert r["transfer_restarts"] == 0
        # Classic ingest digest still rides along (docs/INGEST.md).
        assert "ingest_rows_per_sec" in r
    total_ingest_items = sum(r["transfer_ingest_items"] for r in trains)
    total_d2h = sum(r["transfer_d2h_items"] for r in trains)
    assert total_ingest_items > 0, "no ingest flowed through the scheduler"
    assert total_d2h > 0, "learner d2h never accounted"
    assert sum(r["transfer_ingest_bytes"] for r in trains) > 0
    finals = [r for r in recs if r["kind"] == "final"]
    assert finals and "transfer_dispatches" in finals[-1]


# Re-tiered to slow (ISSUE 15 tier-1 budget): 34s fault-injected train run; scheduler crash recovery units stay
# tier-1
@pytest.mark.slow
def test_train_chaos_scheduler_crash_recovers(tmp_path):
    """Chaos (ISSUE 5 satellite): an injected transfer-scheduler thread
    crash mid-run recovers through the bounded self-restart path — the
    run completes its budget and the restart is visible in the records
    and the recovery counters."""
    from distributed_ddpg_tpu.train import train_jax

    # crash@1: the FIRST scheduled dispatch dies (a rate-capped smoke run
    # only ships a handful of coalesced super-blocks, so a later ordinal
    # might never be reached).
    cfg = _smoke_config(tmp_path, faults="transfer:dispatch:crash@1")
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    recs = _records(cfg.log_path)
    restarts = [
        r.get("transfer_restarts", 0)
        for r in recs
        if r["kind"] in ("train", "final")
    ]
    assert max(restarts) >= 1, (
        f"injected scheduler crash never surfaced in transfer_restarts: "
        f"{restarts}"
    )


def test_train_scheduler_off_still_runs(tmp_path):
    """transfer_scheduler=False recovers the PR-1 private-shipper
    pipeline: no transfer_* fields, ingest_* digest intact."""
    from distributed_ddpg_tpu.train import train_jax

    cfg = _smoke_config(tmp_path, transfer_scheduler=False)
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    trains = [r for r in _records(cfg.log_path) if r["kind"] == "train"]
    assert trains
    assert all("transfer_dispatches" not in r for r in trains)
    assert all("ingest_rows_per_sec" in r for r in trains)
