"""Pallas fused Adam+Polyak kernel vs the reference ops (interpret mode on
CPU): numerical equivalence at the op level and through full learner steps."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import init_train_state, jit_learner_step
from distributed_ddpg_tpu.ops.fused_update import fused_adam_polyak
from distributed_ddpg_tpu.ops.optim import adam_update
from distributed_ddpg_tpu.ops.polyak import polyak_update
from distributed_ddpg_tpu.types import Batch, OptState


def _tree(key, shapes):
    ks = jax.random.split(key, len(shapes))
    return {f"p{i}": jax.random.normal(ks[i], s) for i, s in enumerate(shapes)}


def test_fused_matches_reference_ops():
    # Ragged leaf sizes force the pad/unpad path (total not tile-aligned).
    shapes = [(17, 256), (256,), (256, 129), (3,)]
    key = jax.random.PRNGKey(0)
    params = _tree(key, shapes)
    targets = _tree(jax.random.PRNGKey(1), shapes)
    opt = OptState(
        mu=jax.tree.map(jnp.zeros_like, params),
        nu=jax.tree.map(jnp.zeros_like, params),
        count=jnp.zeros((), jnp.int32),
    )
    p_f, opt_f, t_f = params, opt, targets
    p_r, opt_r, t_r = params, opt, targets
    for i in range(3):
        grads = jax.tree.map(lambda x: jnp.sin(x + i), p_r)
        p_f, opt_f, t_f = fused_adam_polyak(p_f, grads, opt_f, t_f, 1e-3, 0.05)
        p_r, opt_r = adam_update(p_r, grads, opt_r, 1e-3)
        t_r = polyak_update(p_r, t_r, 0.05)
        for a, b in zip(jax.tree.leaves((p_f, opt_f.mu, opt_f.nu, t_f)),
                        jax.tree.leaves((p_r, opt_r.mu, opt_r.nu, t_r))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
    assert int(opt_f.count) == 3


def test_learner_step_fused_matches_unfused():
    OBS, ACT, B = 5, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    batch = Batch(
        obs=jax.random.normal(ks[0], (B, OBS)),
        action=jax.random.uniform(ks[1], (B, ACT), minval=-1, maxval=1),
        reward=jax.random.normal(ks[2], (B,)),
        discount=jnp.full((B,), 0.99),
        next_obs=jax.random.normal(ks[0], (B, OBS)),
        weight=jnp.ones((B,)),
    )
    outs = {}
    for fused in (False, True):
        cfg = DDPGConfig(actor_hidden=(32, 32), critic_hidden=(32, 32), fused_update=fused)
        state = init_train_state(cfg, OBS, ACT, seed=3)
        step = jit_learner_step(cfg, 1.0, donate=False)
        out = step(state, batch)
        out = step(out.state, batch)
        outs[fused] = out
    for a, b in zip(jax.tree.leaves(outs[False].state), jax.tree.leaves(outs[True].state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
