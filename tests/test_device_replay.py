"""Device-resident replay tests: jitted ring insert with wraparound,
fused-sampling learner chunks (zero h2d), checkpoint roundtrip."""

import jax
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.parallel.learner import ShardedLearner
from distributed_ddpg_tpu.parallel.mesh import make_mesh
from distributed_ddpg_tpu.replay.device import DeviceReplay
from distributed_ddpg_tpu.types import pack_batch_np, packed_width

OBS, ACT, B = 4, 2, 64
W = packed_width(OBS, ACT)


def _rows(rng, n):
    return pack_batch_np(
        {
            "obs": rng.standard_normal((n, OBS)).astype(np.float32),
            "action": rng.uniform(-1, 1, (n, ACT)).astype(np.float32),
            "reward": rng.standard_normal(n).astype(np.float32),
            "discount": np.full(n, 0.99, np.float32),
            "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
            "weight": np.ones(n, np.float32),
        }
    )


def test_insert_and_wraparound():
    mesh = make_mesh(-1, 1)
    rep = DeviceReplay(capacity=256, obs_dim=OBS, act_dim=ACT, mesh=mesh, block_size=64)
    rng = np.random.default_rng(0)
    blocks = [_rows(rng, 64) for _ in range(5)]  # 320 rows > capacity 256
    for b in blocks:
        rep.add_packed(b)
    assert len(rep) == 256
    state = rep.state_dict()
    assert int(state["ptr"]) == 320 % 256 == 64
    # Slots 0..63 hold block 4 (wrapped); slots 64..127 hold block 1.
    stored = np.asarray(jax.device_get(rep.storage))
    np.testing.assert_allclose(stored[:64], blocks[4], rtol=1e-6)
    np.testing.assert_allclose(stored[64:128], blocks[1], rtol=1e-6)


def test_pending_accumulates_until_block():
    mesh = make_mesh(-1, 1)
    rep = DeviceReplay(capacity=256, obs_dim=OBS, act_dim=ACT, mesh=mesh, block_size=64)
    rng = np.random.default_rng(1)
    rep.add_packed(_rows(rng, 30))
    assert len(rep) == 0 and rep.pending_rows == 30
    rep.add_packed(_rows(rng, 40))   # 70 total -> one 64-block ships
    assert len(rep) == 64 and rep.pending_rows == 6
    rep.flush()
    assert len(rep) == 128 and rep.pending_rows == 0


def test_fused_sampling_chunk():
    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B, seed=0
    )
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, chunk_size=4)
    rep = DeviceReplay(
        capacity=1024, obs_dim=OBS, act_dim=ACT, mesh=lrn.mesh, block_size=256
    )
    rep.add_packed(_rows(np.random.default_rng(2), 512))
    out = lrn.run_sample_chunk(rep)
    # Default scale_batch_with_data: B rows per data-axis device (8 fake
    # devices in the test mesh -> global batch 8B).
    assert np.asarray(out.td_errors).shape == (4, lrn.global_batch)
    assert lrn.global_batch == 8 * B
    assert np.isfinite(float(out.metrics["critic_loss"]))
    assert int(jax.device_get(lrn.state.step)) == 4
    # Keys advance: two chunks give different losses (different samples).
    out2 = lrn.run_sample_chunk(rep)
    assert float(out2.metrics["critic_loss"]) != float(out.metrics["critic_loss"])


@pytest.mark.slow
def test_sample_chunk_matches_manual_steps():
    """The pre-gathered sample chunk must equal K plain steps over the same
    indices: replicate the chunk's key-split + randint sampling, gather on
    the host, feed the single-step path, and compare final params."""
    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B, seed=0
    )
    K = 3
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, chunk_size=K)
    ref = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, chunk_size=K)
    rep = DeviceReplay(
        capacity=1024, obs_dim=OBS, act_dim=ACT, mesh=lrn.mesh, block_size=256
    )
    rep.add_packed(_rows(np.random.default_rng(4), 512))

    # Reproduce the indices sample_chunk_fn will draw from lrn._key
    # (global_batch rows per step: B per data-axis device).
    key = jax.device_get(lrn._key)
    _, sub = jax.random.split(key)
    idx = np.asarray(
        jax.random.randint(sub, (K, lrn.global_batch), 0, len(rep))
    )

    out = lrn.run_sample_chunk(rep)
    assert np.asarray(out.td_errors).shape == (K, lrn.global_batch)

    storage = np.asarray(jax.device_get(rep.storage))
    from distributed_ddpg_tpu.types import unpack_batch

    for k in range(K):
        ref_out = ref.step(unpack_batch(storage[idx[k]], OBS, ACT)._asdict())
        np.testing.assert_allclose(
            np.asarray(ref_out.td_errors),
            np.asarray(out.td_errors)[k],
            rtol=1e-5, atol=1e-6,
        )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        jax.device_get(ref.state.actor_params),
        jax.device_get(lrn.state.actor_params),
    )


def test_device_replay_checkpoint_roundtrip():
    mesh = make_mesh(-1, 1)
    rep = DeviceReplay(capacity=128, obs_dim=OBS, act_dim=ACT, mesh=mesh, block_size=32)
    rep.add_packed(_rows(np.random.default_rng(3), 96))
    state = rep.state_dict()
    fresh = DeviceReplay(capacity=128, obs_dim=OBS, act_dim=ACT, mesh=mesh, block_size=32)
    fresh.load_state_dict(state)
    assert len(fresh) == 96
    np.testing.assert_allclose(
        np.asarray(jax.device_get(fresh.storage))[:96],
        np.asarray(jax.device_get(rep.storage))[:96],
    )
