"""Actor-pool tests (SURVEY.md §4 'Fault/elastic tests'): workers stream
transitions, param broadcast reaches policies, a killed worker is respawned
and the learner side keeps running."""

import time

import numpy as np
import pytest

from distributed_ddpg_tpu.actors import NumpyPolicy, flatten_params, param_layout
from distributed_ddpg_tpu.actors.pool import ActorPool
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs import make, spec_of
from distributed_ddpg_tpu.learner import init_train_state
from distributed_ddpg_tpu.replay import UniformReplay

HID = (16, 16)


def _setup(num_actors=2, **kw):
    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=HID,
        critic_hidden=HID,
        num_actors=num_actors,
        replay_capacity=50_000,
        **kw,
    )
    env = make(cfg.env_id, seed=0, prefer_builtin=True)
    spec = spec_of(env)
    state = init_train_state(cfg, spec.obs_dim, spec.act_dim, seed=0)
    return cfg, spec, state


@pytest.mark.parametrize(
    "mode", ["boot", pytest.param("midrun", marks=pytest.mark.slow)]
)
def test_workers_exit_when_pool_dies_hard(mode):
    """Orphan guard (worker.py): a pool process that dies WITHOUT stop() —
    SIGKILL, or the stall watchdog's os._exit — must not leave workers
    running forever (observed in-round: 64 orphaned Humanoid workers after
    a hard kill). 'boot' kills the pool before workers finish booting
    (first loop-top guard catches it); 'midrun' kills it while workers are
    blocked in full-transport put() backpressure (the guarded timeout loop
    must catch it — a bare blocking put would hang forever)."""
    import os
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "orphan_child.py"),
         mode],
        capture_output=True,
        text=True,
        timeout=120,
        env={
            **os.environ,
            "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        },
    )
    assert out.returncode == 70, f"child failed to set up: {out.stderr[-2000:]}"
    pids = [int(p) for line in out.stdout.splitlines()
            if line.startswith("PIDS") for p in line.split()[1:]]
    assert pids, f"no worker pids reported: {out.stdout!r}"
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [p for p in pids if _pid_alive(p)]
        if not alive:
            return
        time.sleep(0.5)
    # Clean up before failing so orphans don't leak into other tests.
    for p in alive:
        try:
            os.kill(p, 9)
        except OSError:
            pass
    raise AssertionError(f"orphaned workers still alive after 30s: {alive}")


def _pid_alive(pid: int) -> bool:
    import os

    try:
        os.kill(pid, 0)
    except OSError:
        return False
    # A reaped-by-init zombie still answers signal 0; read the state.
    # No /proc (non-Linux): assume alive — the conservative answer keeps
    # the test honest instead of vacuously passing on live orphans.
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split(")")[-1].split()[0] != "Z"
    except OSError:
        return not os.path.exists("/proc")


def test_numpy_policy_matches_jax_actor():
    import jax

    from distributed_ddpg_tpu.learner import make_act_fn

    cfg, spec, state = _setup()
    layout = param_layout(spec.obs_dim, spec.act_dim, HID)
    pol = NumpyPolicy(layout, spec.action_scale, spec.action_offset)
    pol.load_flat(flatten_params(jax.device_get(state.actor_params)))
    act = make_act_fn(cfg, spec.action_scale, spec.action_offset)
    obs = np.random.default_rng(0).standard_normal((7, spec.obs_dim)).astype(np.float32)
    np.testing.assert_allclose(
        pol(obs), np.asarray(act(state.actor_params, obs)), rtol=1e-5, atol=1e-6
    )


@pytest.mark.slow
@pytest.mark.parametrize("transport", ["shm", "queue"])
def test_pool_streams_transitions_and_respawns(transport):
    from distributed_ddpg_tpu import native

    if transport == "shm" and not native.available():
        pytest.skip("native toolchain unavailable")
    cfg, spec, state = _setup(
        num_actors=2, faults="worker:0:crash@200", transport=transport
    )
    replay = UniformReplay(cfg.replay_capacity, spec.obs_dim, spec.act_dim)
    import jax

    pool = ActorPool(cfg, spec, heartbeat_timeout=15.0)
    pool.start(jax.device_get(state.actor_params))
    try:
        deadline = time.time() + 60
        while len(replay) < 1000 and time.time() < deadline:
            pool.drain_into(replay)
            time.sleep(0.1)
        assert len(replay) >= 1000, f"only {len(replay)} transitions arrived"
        # Transitions must be sane Pendulum data.
        s = replay.sample(64)
        assert np.all(np.abs(s["action"]) <= 2.0 + 1e-5)
        assert np.all(s["reward"] <= 0.0)
        assert np.all((s["discount"] == 0.0) | (s["discount"] > 0.9))

        # Worker 0 crashes at step 200 (injected); monitor must respawn it
        # and data must keep flowing afterwards.
        time.sleep(0.5)
        stats = pool.monitor()
        deadline = time.time() + 30
        while stats["total_respawns"] == 0 and time.time() < deadline:
            time.sleep(0.5)
            stats = pool.monitor()
        assert stats["total_respawns"] >= 1, "injected-fault worker never respawned"
        before = len(replay)
        deadline = time.time() + 30
        while len(replay) < before + 200 and time.time() < deadline:
            pool.drain_into(replay)
            time.sleep(0.1)
        assert len(replay) >= before + 200, "no data after respawn"

        # Param broadcast: version bump reaches workers without error, and
        # subsequently drained experience carries a bounded staleness
        # (SURVEY.md §5 'params-staleness per actor').
        pool.broadcast(jax.device_get(state.actor_params), learner_step=500)
        deadline = time.time() + 30
        while pool.drain_into(replay) == 0 and time.time() < deadline:
            time.sleep(0.1)
        st = pool.staleness()
        assert 0 <= st["staleness_mean"] <= 500
        assert 0 <= st["staleness_max"] <= 500
        assert pool.episode_stats() is not None
    finally:
        pool.stop()
