"""Test harness: force JAX onto a virtual 8-device CPU platform BEFORE any
backend is initialized (SURVEY.md §4 'Distributed without a cluster'). This
exercises the mesh/sharding/collective paths with no TPU attached; the driver
separately dry-runs the multichip path via __graft_entry__.dryrun_multichip.

Note: this image's site customization registers a remote 'axon' TPU platform
and programmatically sets jax_platforms='axon,cpu', which overrides the
JAX_PLATFORMS env var — so we must win the override via jax.config.update
AFTER importing jax, in addition to setting XLA_FLAGS for the fake devices.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Placement-invariant PRNG — the repo-wide RNG scheme (see the note in
# parallel/mesh.py): set here too so tests that touch jax.random before
# importing parallel.mesh trace under the same scheme.
jax.config.update("jax_threefry_partitionable", True)
