"""Deliberately-broken jitted programs for the program-contract analyzer
(analysis/programs.py; docs/ANALYSIS.md "Layer 2").

Each registry below is a tiny `program_specs()`-shaped callable the
proganalyze CLI can load via `--specs tests/program_fixtures.py:<name>`
and tests/test_programs.py drives in-process. One registry per failure
mode, so each broken program independently proves its check fires with
an exact finding count:

- `broken_donation_specs`   — a donated buffer whose shape/dtype matches
                              no output: lowering records NO aliasing
                              for it (the silent 2x HBM class).
- `broken_callback_specs`   — a `pure_callback` embedded in the jitted
                              program (the host-round-trip-per-beat
                              class).
- `collective_specs_v1/_v2` — the SAME program name tracing psum->pmax
                              vs pmax->psum: golden one, check the
                              other, and the collective-order gate must
                              fire (the pod-fork/exit-76 class).
- `broken_beat_group_specs` — two variants claiming one beat_group with
                              different collective orders.
- `clean_specs`             — a well-formed donating + collective
                              program for golden roundtrip tests.

These run under the same probe mesh as the live registries; everything
is traced/lowered only — nothing here ever executes.
"""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_ddpg_tpu.analysis.programs import (
    BuiltProgram,
    ProgramSpec,
    probe_mesh,
)
from distributed_ddpg_tpu.parallel.mesh import shard_map

OWNER = "tests/program_fixtures.py"


# -- unaliased donation -----------------------------------------------------


def _unaliased_donation() -> BuiltProgram:
    # buf is donated but (7,) f32 matches no output (the only output is
    # (3,) f32): XLA cannot alias it, the donation silently buys nothing.
    fn = jax.jit(lambda buf, x: x * 2.0, donate_argnums=(0,))
    return BuiltProgram(
        fn, (np.zeros(7, np.float32), np.zeros(3, np.float32)), (0,)
    )


def broken_donation_specs():
    return [
        ProgramSpec("fixture.donation.unaliased", OWNER, _unaliased_donation)
    ]


# -- host-callback leak -----------------------------------------------------


def _callback_leak() -> BuiltProgram:
    def fn(x):
        y = x + 1.0
        return jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct(x.shape, x.dtype), y
        )

    return BuiltProgram(jax.jit(fn), (np.zeros(4, np.float32),))


def broken_callback_specs():
    return [ProgramSpec("fixture.callback.leak", OWNER, _callback_leak)]


# -- collective order -------------------------------------------------------


def _collective_pair(order: str):
    def build() -> BuiltProgram:
        mesh = probe_mesh()

        def body(x):
            if order == "sum-first":
                s = jax.lax.psum(x, "data")
                m = jax.lax.pmax(x, "data")
            else:
                m = jax.lax.pmax(x, "data")
                s = jax.lax.psum(x, "data")
            return s + m

        fn = jax.jit(
            shard_map(body, mesh, in_specs=P("data"), out_specs=P("data"))
        )
        return BuiltProgram(fn, (np.zeros(8, np.float32),))

    return build


def collective_specs_v1():
    return [
        ProgramSpec(
            "fixture.collective.pair", OWNER, _collective_pair("sum-first")
        )
    ]


def collective_specs_v2():
    # Same name, opposite order: checked against v1's golden this is the
    # reorder that forks a pod's device-op streams.
    return [
        ProgramSpec(
            "fixture.collective.pair", OWNER, _collective_pair("max-first")
        )
    ]


# -- beat-group divergence --------------------------------------------------


def broken_beat_group_specs():
    return [
        ProgramSpec(
            "fixture.beat.a", OWNER, _collective_pair("sum-first"),
            beat_group="fixture-beat",
        ),
        ProgramSpec(
            "fixture.beat.b", OWNER, _collective_pair("max-first"),
            beat_group="fixture-beat",
        ),
    ]


# -- clean program (roundtrip oracle) ---------------------------------------


def _clean_program() -> BuiltProgram:
    mesh = probe_mesh()

    def body(acc, x):
        return acc + jax.lax.psum(x, "data")

    fn = jax.jit(
        shard_map(body, mesh, in_specs=(P("data"), P("data")),
                  out_specs=P("data")),
        donate_argnums=(0,),
    )
    return BuiltProgram(
        fn, (np.zeros(8, np.float32), np.zeros(8, np.float32)), (0,)
    )


def clean_specs():
    return [ProgramSpec("fixture.clean", OWNER, _clean_program)]
