"""Sharded-learner tests on the virtual 8-device CPU mesh (SURVEY.md §4
'Distributed without a cluster'): auto (jit+sharding) vs explicit
(shard_map+pmean) vs single-device reference — all must agree; TP sharding
must actually partition params; the scan chunk must equal K single steps."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import init_train_state, jit_learner_step
from distributed_ddpg_tpu.parallel import mesh as mesh_lib
from distributed_ddpg_tpu.parallel.learner import ShardedLearner
from distributed_ddpg_tpu.parallel.prefetch import ChunkPrefetcher
from distributed_ddpg_tpu.replay import UniformReplay
from distributed_ddpg_tpu.types import batch_from_numpy

OBS, ACT, B = 4, 2, 64


def _cfg(**kw):
    base = dict(actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B, seed=0)
    base.update(kw)
    return DDPGConfig(**base)


def _np_batch(rng, b=B):
    return {
        "obs": rng.standard_normal((b, OBS)).astype(np.float32),
        "action": rng.uniform(-1, 1, (b, ACT)).astype(np.float32),
        "reward": rng.standard_normal(b).astype(np.float32),
        "discount": np.full(b, 0.99, np.float32),
        "next_obs": rng.standard_normal((b, OBS)).astype(np.float32),
        "weight": np.ones(b, np.float32),
    }


def test_mesh_shapes():
    assert len(jax.devices()) == 8, "conftest should provide 8 fake CPU devices"
    m = mesh_lib.make_mesh(-1, 1)
    assert m.shape == {"data": 8, "model": 1}
    m = mesh_lib.make_mesh(-1, 2)
    assert m.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        mesh_lib.make_mesh(3, 2)


@pytest.mark.parametrize("mode", ["auto", "explicit"])
def test_sharded_matches_single_device(mode):
    cfg = _cfg()
    rng = np.random.default_rng(0)
    batches = [_np_batch(rng) for _ in range(4)]

    ref_state = init_train_state(cfg, OBS, ACT, seed=0)
    ref_step = jit_learner_step(cfg, 1.0, donate=False)
    for nb in batches:
        ref_out = ref_step(ref_state, batch_from_numpy(nb))
        ref_state = ref_out.state

    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mode=mode)
    for nb in batches:
        out = lrn.step(nb)
    np.testing.assert_allclose(
        float(out.metrics["critic_loss"]), float(ref_out.metrics["critic_loss"]),
        rtol=1e-4,
    )
    for a, b in zip(
        jax.tree.leaves(jax.device_get(lrn.state.actor_params)),
        jax.tree.leaves(jax.device_get(ref_state.actor_params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.sort(np.asarray(out.td_errors)),
        np.sort(np.asarray(ref_out.td_errors)),
        rtol=1e-3, atol=1e-5,
    )


def test_tensor_parallel_params_actually_sharded():
    cfg = _cfg(model_axis=2)
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0)
    # Layer 0 kernel (OBS x 32) should be column-parallel over 'model'.
    spec = lrn.state.actor_params[0]["w"].sharding.spec
    assert spec == P(None, "model")
    # And a step must still run + stay finite.
    out = lrn.step(_np_batch(np.random.default_rng(1)))
    assert np.isfinite(float(out.metrics["critic_loss"]))


def test_tp_matches_dp_numerically():
    cfg_dp = _cfg(model_axis=1)
    cfg_tp = _cfg(model_axis=2)
    rng = np.random.default_rng(2)
    batches = [_np_batch(rng) for _ in range(3)]
    lrn_dp = ShardedLearner(cfg_dp, OBS, ACT, action_scale=1.0)
    lrn_tp = ShardedLearner(cfg_tp, OBS, ACT, action_scale=1.0)
    for nb in batches:
        out_dp = lrn_dp.step(nb)
        out_tp = lrn_tp.step(nb)
    np.testing.assert_allclose(
        float(out_tp.metrics["critic_loss"]),
        float(out_dp.metrics["critic_loss"]),
        rtol=1e-4,
    )
    for a, b in zip(
        jax.tree.leaves(jax.device_get(lrn_tp.state.critic_params)),
        jax.tree.leaves(jax.device_get(lrn_dp.state.critic_params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_chunk_equals_k_single_steps():
    cfg = _cfg()
    rng = np.random.default_rng(3)
    batches = [_np_batch(rng) for _ in range(5)]
    lrn_a = ShardedLearner(cfg, OBS, ACT, action_scale=1.0)
    for nb in batches:
        lrn_a.step(nb)
    lrn_b = ShardedLearner(cfg, OBS, ACT, action_scale=1.0)
    stacked = {k: np.stack([nb[k] for nb in batches]) for k in batches[0]}
    out = lrn_b.run_chunk(stacked)
    assert np.asarray(out.td_errors).shape == (5, B)
    for a, b in zip(
        jax.tree.leaves(jax.device_get(lrn_a.state)),
        jax.tree.leaves(jax.device_get(lrn_b.state)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_prefetcher_feeds_chunks():
    cfg = _cfg(replay_capacity=1024)
    replay = UniformReplay(1024, OBS, ACT, seed=0)
    rng = np.random.default_rng(4)
    nb = _np_batch(rng, b=512)
    replay.add_batch(nb["obs"], nb["action"], nb["reward"], nb["discount"], nb["next_obs"])
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0)
    pf = ChunkPrefetcher(replay, lrn.put_chunk, batch_size=B, chunk_size=4, depth=2).start()
    try:
        for _ in range(3):
            chunk, indices = pf.next(timeout=30)
            assert indices.shape == (4, B)
            out = lrn.run_chunk_async(chunk)
            assert np.isfinite(float(out.metrics["critic_loss"]))
    finally:
        pf.stop()


def test_multihost_noop_single_process():
    from distributed_ddpg_tpu.parallel import multihost

    assert multihost.initialize() is False
    info = multihost.process_info()
    assert info["process_count"] == 1 and info["global_device_count"] == 8


def test_prefetcher_surfaces_worker_exception_promptly():
    class BoomReplay:
        def sample(self, n):
            raise RuntimeError("boom")

    cfg = _cfg()
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0)
    pf = ChunkPrefetcher(BoomReplay(), lrn.put_chunk, B, 2, depth=2).start()
    import time as _time

    t0 = _time.time()
    with pytest.raises(RuntimeError, match="prefetch thread died"):
        pf.next(timeout=30)
    assert _time.time() - t0 < 5, "exception should surface promptly, not on timeout"
    pf.stop()


@pytest.mark.parametrize(
    "scaled,want_batch",
    [pytest.param(True, 4 * B, marks=pytest.mark.slow), (False, B)],
)
def test_scale_batch_with_data(scaled, want_batch):
    """Per-device batch semantics (config.scale_batch_with_data): on a
    4-device data mesh the sampling paths draw batch_size rows PER DEVICE
    (global batch 4B), so adding chips adds throughput instead of slicing
    a fixed 64 rows thinner; False preserves the fixed-global semantics."""
    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )
    from distributed_ddpg_tpu.types import pack_batch_np

    cfg = _cfg(scale_batch_with_data=scaled)
    mesh = mesh_lib.make_mesh(4, 1, devices=jax.devices()[:4])
    K = 3
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mesh=mesh, chunk_size=K)
    assert lrn.global_batch == want_batch
    rng = np.random.default_rng(0)
    rows = pack_batch_np(_np_batch(rng, b=2048))
    rep = DeviceReplay(4096, OBS, ACT, mesh=mesh, block_size=1024)
    rep.add_packed(rows)
    out = lrn.run_sample_chunk(rep)
    assert out.td_errors.shape == (K, want_batch)
    assert np.isfinite(float(out.metrics["critic_loss"]))

    per = DevicePrioritizedReplay(4096, OBS, ACT, mesh=mesh, block_size=1024)
    per.add_packed(rows)
    out = lrn.run_sample_chunk_per(per, beta=0.5)
    assert out.td_errors.shape == (K, want_batch)
    assert np.isfinite(float(out.metrics["critic_loss"]))
