"""Subprocess helper for test_workers_exit_when_pool_dies_hard: start an
ActorPool, print the worker pids, then die WITHOUT pool.stop() — the same
exit a SIGKILL or the stall watchdog's os._exit(70) produces. The parent
test asserts the workers notice the reparenting and exit on their own.

Modes (argv[1]):
  boot    die immediately after start() — workers are still booting and
          must catch the orphaning at their first loop-top guard.
  midrun  die after the workers have filled the BOUNDED queue transport —
          workers are blocked inside put() backpressure and must escape
          via the guarded timeout loop, not hang on the dead drainer."""

import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

from distributed_ddpg_tpu.actors.pool import ActorPool  # noqa: E402
from distributed_ddpg_tpu.config import DDPGConfig  # noqa: E402
from distributed_ddpg_tpu.envs import make, spec_of  # noqa: E402
from distributed_ddpg_tpu.learner import init_train_state  # noqa: E402


def main() -> None:
    cfg = DDPGConfig(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=2,
        transport="queue",  # no native .so dependency in this test
    )
    env = make(cfg.env_id, seed=0, prefer_builtin=True)
    spec = spec_of(env)
    state = init_train_state(cfg, spec.obs_dim, spec.act_dim, seed=0)
    pool = ActorPool(cfg, spec)
    pool.start(jax.device_get(state.actor_params))
    print("PIDS", " ".join(str(p.pid) for p in pool._procs), flush=True)
    if len(sys.argv) > 1 and sys.argv[1] == "midrun":
        # Never drain: builtin-Pendulum workers boot in a couple of
        # seconds and fill the bounded queue almost immediately after,
        # so by the deadline they are blocked in put() backpressure.
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                # qsize() raises NotImplementedError on macOS (no
                # sem_getvalue); there the 10s deadline alone gates the kill.
                if pool._queue.qsize() >= pool._queue._maxsize:
                    break
            except NotImplementedError:
                pass
            time.sleep(0.2)
    # Hard death: no stop_flag, no atexit, no daemon cleanup.
    os._exit(70)


if __name__ == "__main__":
    main()
