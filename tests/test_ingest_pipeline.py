"""Ingest pipeline tests (docs/INGEST.md).

The coalesced / async host->HBM replay ingest must be BIT-IDENTICAL to the
seed's serial block-at-a-time shipping for the same inflow — storage, ptr,
size (and PER priorities) — including the flush() padding block. Plus: the
host staging ring's FIFO/wrap/growth behavior, backpressure + observability
surface, shipper-death surfacing, ChunkPrefetcher stop hardening, and the
bench ingest smoke fields (so a perf/observability regression in this path
fails tests instead of only showing up in round benches).
"""

import pathlib
import sys
import threading
import time

import jax
import numpy as np
import pytest

from distributed_ddpg_tpu.parallel.mesh import make_mesh
from distributed_ddpg_tpu.parallel.prefetch import ChunkPrefetcher, PrefetchTimeout
from distributed_ddpg_tpu.replay.device import (
    DevicePrioritizedReplay,
    DeviceReplay,
    IngestError,
)
from distributed_ddpg_tpu.replay.staging import HostStagingRing
from distributed_ddpg_tpu.types import packed_width

OBS, ACT = 4, 2
W = packed_width(OBS, ACT)

# Irregular inflow: sub-block trickles, exact blocks, multi-block bursts,
# and enough total volume to wrap the 1024-capacity ring.
INFLOW_SIZES = (30, 400, 64, 7, 999, 128, 1000, 3)


def _inflow(seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((n, W)).astype(np.float32) for n in INFLOW_SIZES]


def _snap(rep):
    return (
        np.asarray(jax.device_get(rep.storage)),
        int(jax.device_get(rep.ptr)),
        int(jax.device_get(rep.size)),
    )


def _mk(cls=DeviceReplay, **kw):
    mesh = make_mesh(-1, 1)
    kw.setdefault("block_size", 64)
    return cls(capacity=1024, obs_dim=OBS, act_dim=ACT, mesh=mesh, **kw)


# --------------------------------------------------------------------------
# Host staging ring
# --------------------------------------------------------------------------

def test_ring_fifo_wrap_growth_and_peek():
    ring = HostStagingRing(3, 4)
    rows = np.arange(30, dtype=np.float32).reshape(10, 3)
    ring.push(rows[:2])
    assert len(ring) == 2 and ring.capacity == 4
    np.testing.assert_array_equal(ring.pop(1), rows[:1])
    ring.push(rows[2:5])            # head=1, tail wraps
    assert len(ring) == 4
    np.testing.assert_array_equal(ring.peek(4), rows[1:5])  # FIFO across wrap
    np.testing.assert_array_equal(ring.peek_cols(1, 2, 10), rows[1:5, 1:3])
    np.testing.assert_array_equal(ring.pop(4), rows[1:5])
    ring.push(rows)                  # 10 > capacity 4 -> grows, FIFO intact
    assert ring.capacity >= 10 and len(ring) == 10
    np.testing.assert_array_equal(ring.pop(10), rows)
    with pytest.raises(ValueError):
        ring.pop(1)


def test_ring_pop_is_owned_copy():
    ring = HostStagingRing(2, 8)
    a = np.ones((3, 2), np.float32)
    ring.push(a)
    out = ring.pop(3)
    ring.push(np.full((8, 2), 7.0, np.float32))  # overwrite the region
    np.testing.assert_array_equal(out, a)        # popped rows unaffected


# --------------------------------------------------------------------------
# Coalesced / async parity vs the seed's serial ship sequence
# --------------------------------------------------------------------------

def test_coalesced_parity_with_serial():
    serial = _mk(max_coalesce=1)     # the seed's block-at-a-time sequence
    coal = _mk(max_coalesce=8)
    for b in _inflow():
        serial.add_packed(b)
        coal.add_packed(b)
    assert serial.pending_rows == coal.pending_rows
    serial.flush()
    coal.flush()
    s0, p0, n0 = _snap(serial)
    s1, p1, n1 = _snap(coal)
    assert (p0, n0) == (p1, n1)
    np.testing.assert_array_equal(s0, s1)


def test_async_shipper_parity_with_serial():
    serial = _mk(max_coalesce=1)
    asy = _mk(async_ship=True, max_coalesce=4, staging_blocks=4)
    try:
        for b in _inflow(seed=1):
            serial.add_packed(b)
            asy.add_packed(b)
        asy.drain_pending()
        assert serial.pending_rows == asy.pending_rows
        serial.flush()
        asy.flush()
        s0, p0, n0 = _snap(serial)
        s1, p1, n1 = _snap(asy)
        assert (p0, n0) == (p1, n1)
        np.testing.assert_array_equal(s0, s1)
    finally:
        asy.close()


def test_per_coalesced_async_parity_with_serial():
    """PER: the super-block priority stamp must equal k serial stamps —
    same max_priority (it only changes in the learner), same index range."""
    serial = _mk(DevicePrioritizedReplay, max_coalesce=1)
    asy = _mk(DevicePrioritizedReplay, async_ship=True, max_coalesce=8)
    try:
        for b in _inflow(seed=2):
            serial.add_packed(b)
            asy.add_packed(b)
        asy.drain_pending()
        serial.flush()
        asy.flush()
        s0, p0, n0 = _snap(serial)
        s1, p1, n1 = _snap(asy)
        assert (p0, n0) == (p1, n1)
        np.testing.assert_array_equal(s0, s1)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(serial.priorities)),
            np.asarray(jax.device_get(asy.priorities)),
        )
    finally:
        asy.close()


def test_reward_sample_includes_staged_rows():
    rep = _mk()
    rows = np.zeros((30, W), np.float32)
    rows[:, OBS + ACT] = 3.5      # reward column
    rows[:, OBS + ACT + 1] = 0.9  # discount column
    rep.add_packed(rows)          # sub-block: stays staged
    assert len(rep) == 0 and rep.pending_rows == 30
    r, d = rep.reward_sample()
    assert r.shape == (30,)
    np.testing.assert_allclose(r, 3.5)
    np.testing.assert_allclose(d, 0.9)


# --------------------------------------------------------------------------
# Backpressure, observability, error surfacing
# --------------------------------------------------------------------------

def test_ingest_stats_and_queue_drain():
    asy = _mk(async_ship=True, max_coalesce=4, staging_blocks=2)
    try:
        rng = np.random.default_rng(3)
        for _ in range(10):
            asy.add_packed(rng.standard_normal((64, W)).astype(np.float32))
        asy.drain_pending()
        snap = asy.ingest_snapshot()
        for key in (
            "ingest_rows_per_sec", "ingest_ship_calls",
            "ingest_coalesce_mean", "ingest_stall_ms", "ingest_ship_ms",
            "ingest_queue_rows",
        ):
            assert key in snap, key
        assert snap["ingest_ship_calls"] >= 1
        assert snap["ingest_coalesce_mean"] >= 1.0
        assert snap["ingest_queue_rows"] == 0
        assert len(asy) == 640
    finally:
        asy.close()


def test_shipper_death_surfaces_named_error():
    class Boom(DeviceReplay):
        def _ship(self, chunk):
            raise RuntimeError("boom h2d")

    rep = _mk(Boom, async_ship=True)
    try:
        rows = np.zeros((64, W), np.float32)
        with pytest.raises(IngestError, match="shipper thread died"):
            for _ in range(200):     # shipper dies on the first full block
                rep.add_packed(rows)
                time.sleep(0.01)
            pytest.fail("shipper death never surfaced")
    finally:
        rep.close()


def test_close_falls_back_to_inline_shipping():
    asy = _mk(async_ship=True)
    asy.close()
    asy.add_packed(np.zeros((64, W), np.float32))  # inline path post-close
    assert len(asy) == 64


# --------------------------------------------------------------------------
# ChunkPrefetcher stop/timeout hardening
# --------------------------------------------------------------------------

class _TinyReplay:
    def __init__(self, delay=0.0):
        self.delay = delay

    def sample(self, n):
        if self.delay:
            time.sleep(self.delay)
        return {"x": np.zeros(n, np.float32), "indices": np.arange(n)}


def test_prefetch_stop_returns_even_with_wedged_put():
    release = threading.Event()

    def wedged_put(chunk):
        release.wait(30.0)
        return chunk

    pf = ChunkPrefetcher(_TinyReplay(), wedged_put, 4, 2, depth=1).start()
    time.sleep(0.3)  # let the worker enter the wedged transfer
    t0 = time.monotonic()
    with pytest.warns(UserWarning, match="did not exit"):
        ok = pf.stop(timeout=0.5)
    assert not ok
    assert time.monotonic() - t0 < 3.0, "stop() must not hang on a wedged put"
    release.set()  # let the leaked daemon thread finish


def test_prefetch_stop_skips_put_after_stop():
    puts = []

    def counting_put(chunk):
        puts.append(1)
        return chunk

    pf = ChunkPrefetcher(_TinyReplay(delay=0.4), counting_put, 4, 1, depth=1)
    pf.start()
    time.sleep(0.1)            # worker is inside sample()
    assert pf.stop(timeout=5.0)
    assert not puts, "stop observed between sample and put must skip the put"


def test_prefetch_next_timeout_raises_named_error():
    release = threading.Event()

    def wedged_put(chunk):
        release.wait(30.0)
        return chunk

    pf = ChunkPrefetcher(_TinyReplay(), wedged_put, 4, 2, depth=1).start()
    try:
        with pytest.raises(PrefetchTimeout, match="worker alive"):
            pf.next(timeout=0.4)
    finally:
        release.set()
        pf.stop()


# --------------------------------------------------------------------------
# Bench ingest smoke (CI guard on the BENCH json ingest breakdown)
# --------------------------------------------------------------------------

def test_bench_ingest_smoke(monkeypatch):
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import bench

    monkeypatch.setenv("BENCH_SECONDS", "1")
    out = bench.phase_ingest()
    fields = out["ingest_bench"]
    for key in (
        "rate", "t_dispatch_ms", "t_dispatch_p95",
        "t_ingest_ms", "t_ingest_p95",
        "ingest_rows_per_sec", "ingest_ship_calls", "ingest_coalesce_mean",
        "ingest_stall_ms", "ingest_ship_ms", "ingest_queue_rows",
    ):
        assert key in fields, key
    assert fields["rate"] > 0
    assert fields["ingest_ship_calls"] >= 1
    assert fields["t_dispatch_p95"] >= 0
