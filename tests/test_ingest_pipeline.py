"""Ingest pipeline tests (docs/INGEST.md).

The coalesced / async host->HBM replay ingest must be BIT-IDENTICAL to the
seed's serial block-at-a-time shipping for the same inflow — storage, ptr,
size (and PER priorities) — including the flush() padding block. Plus: the
host staging ring's FIFO/wrap/growth behavior, backpressure + observability
surface, shipper-death surfacing, ChunkPrefetcher stop hardening, and the
bench ingest smoke fields (so a perf/observability regression in this path
fails tests instead of only showing up in round benches).
"""

import pathlib
import sys
import threading
import time

import jax
import numpy as np
import pytest

from distributed_ddpg_tpu.parallel.mesh import make_mesh
from distributed_ddpg_tpu.parallel.prefetch import ChunkPrefetcher, PrefetchTimeout
from distributed_ddpg_tpu.replay.device import (
    DevicePrioritizedReplay,
    DeviceReplay,
    IngestError,
)
from distributed_ddpg_tpu.replay.staging import HostStagingRing
from distributed_ddpg_tpu.types import packed_width

OBS, ACT = 4, 2
W = packed_width(OBS, ACT)

# Irregular inflow: sub-block trickles, exact blocks, multi-block bursts,
# and enough total volume to wrap the 1024-capacity ring.
INFLOW_SIZES = (30, 400, 64, 7, 999, 128, 1000, 3)


def _inflow(seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((n, W)).astype(np.float32) for n in INFLOW_SIZES]


def _snap(rep):
    return (
        np.asarray(jax.device_get(rep.storage)),
        int(jax.device_get(rep.ptr)),
        int(jax.device_get(rep.size)),
    )


def _mk(cls=DeviceReplay, **kw):
    mesh = make_mesh(-1, 1)
    kw.setdefault("block_size", 64)
    return cls(capacity=1024, obs_dim=OBS, act_dim=ACT, mesh=mesh, **kw)


# --------------------------------------------------------------------------
# Host staging ring
# --------------------------------------------------------------------------

def test_ring_fifo_wrap_growth_and_peek():
    ring = HostStagingRing(3, 4)
    rows = np.arange(30, dtype=np.float32).reshape(10, 3)
    ring.push(rows[:2])
    assert len(ring) == 2 and ring.capacity == 4
    np.testing.assert_array_equal(ring.pop(1), rows[:1])
    ring.push(rows[2:5])            # head=1, tail wraps
    assert len(ring) == 4
    np.testing.assert_array_equal(ring.peek(4), rows[1:5])  # FIFO across wrap
    np.testing.assert_array_equal(ring.peek_cols(1, 2, 10), rows[1:5, 1:3])
    np.testing.assert_array_equal(ring.pop(4), rows[1:5])
    ring.push(rows)                  # 10 > capacity 4 -> grows, FIFO intact
    assert ring.capacity >= 10 and len(ring) == 10
    np.testing.assert_array_equal(ring.pop(10), rows)
    with pytest.raises(ValueError):
        ring.pop(1)


def test_ring_pop_is_owned_copy():
    ring = HostStagingRing(2, 8)
    a = np.ones((3, 2), np.float32)
    ring.push(a)
    out = ring.pop(3)
    ring.push(np.full((8, 2), 7.0, np.float32))  # overwrite the region
    np.testing.assert_array_equal(out, a)        # popped rows unaffected


# --------------------------------------------------------------------------
# Coalesced / async parity vs the seed's serial ship sequence
# --------------------------------------------------------------------------

def test_coalesced_parity_with_serial():
    serial = _mk(max_coalesce=1)     # the seed's block-at-a-time sequence
    coal = _mk(max_coalesce=8)
    for b in _inflow():
        serial.add_packed(b)
        coal.add_packed(b)
    assert serial.pending_rows == coal.pending_rows
    serial.flush()
    coal.flush()
    s0, p0, n0 = _snap(serial)
    s1, p1, n1 = _snap(coal)
    assert (p0, n0) == (p1, n1)
    np.testing.assert_array_equal(s0, s1)


def test_async_shipper_parity_with_serial():
    serial = _mk(max_coalesce=1)
    asy = _mk(async_ship=True, max_coalesce=4, staging_blocks=4)
    try:
        for b in _inflow(seed=1):
            serial.add_packed(b)
            asy.add_packed(b)
        asy.drain_pending()
        assert serial.pending_rows == asy.pending_rows
        serial.flush()
        asy.flush()
        s0, p0, n0 = _snap(serial)
        s1, p1, n1 = _snap(asy)
        assert (p0, n0) == (p1, n1)
        np.testing.assert_array_equal(s0, s1)
    finally:
        asy.close()


def test_per_coalesced_async_parity_with_serial():
    """PER: the super-block priority stamp must equal k serial stamps —
    same max_priority (it only changes in the learner), same index range."""
    serial = _mk(DevicePrioritizedReplay, max_coalesce=1)
    asy = _mk(DevicePrioritizedReplay, async_ship=True, max_coalesce=8)
    try:
        for b in _inflow(seed=2):
            serial.add_packed(b)
            asy.add_packed(b)
        asy.drain_pending()
        serial.flush()
        asy.flush()
        s0, p0, n0 = _snap(serial)
        s1, p1, n1 = _snap(asy)
        assert (p0, n0) == (p1, n1)
        np.testing.assert_array_equal(s0, s1)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(serial.priorities)),
            np.asarray(jax.device_get(asy.priorities)),
        )
    finally:
        asy.close()


def test_scheduler_adaptive_pool_parity_with_serial():
    """The full transfer-scheduler ingest path (scheduled work items +
    adaptive coalesce cap + pooled host buffers, docs/TRANSFER.md) must
    leave storage/ptr/size bit-identical to the seed's serial sequence —
    the adaptive cap only changes WHEN rows land, never WHERE."""
    from distributed_ddpg_tpu.transfer import TransferScheduler

    serial = _mk(max_coalesce=1)
    sched = TransferScheduler().start()
    try:
        via = _mk(
            async_ship=True, max_coalesce=8, staging_blocks=4,
            scheduler=sched, adaptive_coalesce=True, host_pool=True,
        )
        assert via._shipper is None, "scheduler path must not spawn a thread"
        for b in _inflow(seed=7):
            serial.add_packed(b)
            via.add_packed(b)
        via.drain_pending()
        assert serial.pending_rows == via.pending_rows
        serial.flush()
        via.flush()
        s0, p0, n0 = _snap(serial)
        s1, p1, n1 = _snap(via)
        assert (p0, n0) == (p1, n1)
        np.testing.assert_array_equal(s0, s1)
        snap = sched.snapshot()
        assert snap["transfer_ingest_items"] >= 1
        assert 1 <= via.transfer_snapshot()["transfer_coalesce_cap"] <= 8
        via.close()
    finally:
        sched.close()


def test_adaptive_cap_jitter_keeps_parity():
    """Adversarial adaptive-cap schedule: force the effective cap through
    an arbitrary trajectory mid-stream and assert bit-identity anyway —
    the structural guarantee the adaptive controller leans on."""
    class _JitterCap:
        def __init__(self):
            self.seq = [1, 4, 2, 8, 1, 2, 4, 8]
            self.i = 0

        def cap(self):
            self.i += 1
            return self.seq[self.i % len(self.seq)]

        def observe_ship(self, blocks, ship_s, queue_rows):
            pass

        def snapshot(self):
            return {}

    serial = _mk(max_coalesce=1)
    jit = _mk(max_coalesce=8)
    jit._adaptive = _JitterCap()
    for b in _inflow(seed=8):
        serial.add_packed(b)
        jit.add_packed(b)
    serial.flush()
    jit.flush()
    s0, p0, n0 = _snap(serial)
    s1, p1, n1 = _snap(jit)
    assert (p0, n0) == (p1, n1)
    np.testing.assert_array_equal(s0, s1)


def test_per_scheduler_parity_with_serial():
    """PER through the scheduler path: priority stamps must equal the
    serial sequence's too (same max_priority, same index ranges)."""
    from distributed_ddpg_tpu.transfer import TransferScheduler

    serial = _mk(DevicePrioritizedReplay, max_coalesce=1)
    sched = TransferScheduler().start()
    try:
        via = _mk(
            DevicePrioritizedReplay, async_ship=True, max_coalesce=8,
            scheduler=sched, adaptive_coalesce=True, host_pool=True,
        )
        for b in _inflow(seed=9):
            serial.add_packed(b)
            via.add_packed(b)
        via.drain_pending()
        serial.flush()
        via.flush()
        s0, p0, n0 = _snap(serial)
        s1, p1, n1 = _snap(via)
        assert (p0, n0) == (p1, n1)
        np.testing.assert_array_equal(s0, s1)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(serial.priorities)),
            np.asarray(jax.device_get(via.priorities)),
        )
        via.close()
    finally:
        sched.close()


def test_scheduler_ingest_failure_bounded_restart():
    """A failing ingest work item recovers through the same bounded
    budget as a dying _IngestShipper thread, then surfaces IngestError."""
    from distributed_ddpg_tpu.transfer import TransferScheduler

    class Boom(DeviceReplay):
        def _ship(self, chunk):
            raise RuntimeError("boom h2d")

    sched = TransferScheduler().start()
    try:
        rep = _mk(Boom, async_ship=True, scheduler=sched)
        rows = np.zeros((64, W), np.float32)
        with pytest.raises(IngestError, match="shipper thread died"):
            for _ in range(300):
                rep.add_packed(rows)
                time.sleep(0.01)
            pytest.fail("scheduler-path ingest death never surfaced")
        assert rep.ingest_snapshot()["ingest_shipper_restarts"] == 3
        rep.close()
    finally:
        sched.close()


def test_reward_sample_includes_staged_rows():
    rep = _mk()
    rows = np.zeros((30, W), np.float32)
    rows[:, OBS + ACT] = 3.5      # reward column
    rows[:, OBS + ACT + 1] = 0.9  # discount column
    rep.add_packed(rows)          # sub-block: stays staged
    assert len(rep) == 0 and rep.pending_rows == 30
    r, d = rep.reward_sample()
    assert r.shape == (30,)
    np.testing.assert_allclose(r, 3.5)
    np.testing.assert_allclose(d, 0.9)


# --------------------------------------------------------------------------
# Backpressure, observability, error surfacing
# --------------------------------------------------------------------------

def test_ingest_stats_and_queue_drain():
    asy = _mk(async_ship=True, max_coalesce=4, staging_blocks=2)
    try:
        rng = np.random.default_rng(3)
        for _ in range(10):
            asy.add_packed(rng.standard_normal((64, W)).astype(np.float32))
        asy.drain_pending()
        snap = asy.ingest_snapshot()
        for key in (
            "ingest_rows_per_sec", "ingest_ship_calls",
            "ingest_coalesce_mean", "ingest_stall_ms", "ingest_ship_ms",
            "ingest_queue_rows",
        ):
            assert key in snap, key
        assert snap["ingest_ship_calls"] >= 1
        assert snap["ingest_coalesce_mean"] >= 1.0
        assert snap["ingest_queue_rows"] == 0
        assert len(asy) == 640
    finally:
        asy.close()


def test_shipper_death_surfaces_named_error():
    class Boom(DeviceReplay):
        def _ship(self, chunk):
            raise RuntimeError("boom h2d")

    rep = _mk(Boom, async_ship=True)
    try:
        rows = np.zeros((64, W), np.float32)
        with pytest.raises(IngestError, match="shipper thread died"):
            for _ in range(200):     # shipper dies on the first full block
                rep.add_packed(rows)
                time.sleep(0.01)
            pytest.fail("shipper death never surfaced")
    finally:
        rep.close()


def test_close_falls_back_to_inline_shipping():
    asy = _mk(async_ship=True)
    asy.close()
    asy.add_packed(np.zeros((64, W), np.float32))  # inline path post-close
    assert len(asy) == 64


# --------------------------------------------------------------------------
# ChunkPrefetcher stop/timeout hardening
# --------------------------------------------------------------------------

class _TinyReplay:
    def __init__(self, delay=0.0):
        self.delay = delay

    def sample(self, n):
        if self.delay:
            time.sleep(self.delay)
        return {"x": np.zeros(n, np.float32), "indices": np.arange(n)}


def test_prefetch_stop_returns_even_with_wedged_put():
    release = threading.Event()

    def wedged_put(chunk):
        release.wait(30.0)
        return chunk

    pf = ChunkPrefetcher(_TinyReplay(), wedged_put, 4, 2, depth=1).start()
    time.sleep(0.3)  # let the worker enter the wedged transfer
    t0 = time.monotonic()
    with pytest.warns(UserWarning, match="did not exit"):
        ok = pf.stop(timeout=0.5)
    assert not ok
    assert time.monotonic() - t0 < 3.0, "stop() must not hang on a wedged put"
    release.set()  # let the leaked daemon thread finish


def test_prefetch_stop_skips_put_after_stop():
    puts = []

    def counting_put(chunk):
        puts.append(1)
        return chunk

    pf = ChunkPrefetcher(_TinyReplay(delay=0.4), counting_put, 4, 1, depth=1)
    pf.start()
    time.sleep(0.1)            # worker is inside sample()
    assert pf.stop(timeout=5.0)
    assert not puts, "stop observed between sample and put must skip the put"


def test_prefetch_next_timeout_raises_named_error():
    release = threading.Event()

    def wedged_put(chunk):
        release.wait(30.0)
        return chunk

    pf = ChunkPrefetcher(_TinyReplay(), wedged_put, 4, 2, depth=1).start()
    try:
        with pytest.raises(PrefetchTimeout, match="worker alive"):
            pf.next(timeout=0.4)
    finally:
        release.set()
        pf.stop()


# --------------------------------------------------------------------------
# Bench ingest smoke (CI guard on the BENCH json ingest breakdown)
# --------------------------------------------------------------------------

def test_bench_ingest_smoke(monkeypatch):
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import bench

    monkeypatch.setenv("BENCH_SECONDS", "1")
    out = bench.phase_ingest()
    fields = out["ingest_bench"]
    for key in (
        "rate", "t_dispatch_ms", "t_dispatch_p95",
        "t_ingest_ms", "t_ingest_p95",
        "ingest_rows_per_sec", "ingest_ship_calls", "ingest_coalesce_mean",
        "ingest_stall_ms", "ingest_ship_ms", "ingest_queue_rows",
    ):
        assert key in fields, key
    assert fields["rate"] > 0
    assert fields["ingest_ship_calls"] >= 1
    assert fields["t_dispatch_p95"] >= 0
    # Transfer-scheduler smoke (docs/TRANSFER.md): the bench runs the
    # production scheduler path by default; its snapshot must be present
    # and self-consistent (the CI gate pins transfer_ingest_p95).
    transfer = out["transfer_bench"]
    for key in (
        "transfer_dispatches", "transfer_ingest_items",
        "transfer_ingest_bytes", "transfer_ingest_ms",
        "transfer_ingest_p95", "transfer_coalesce_cap",
        "transfer_coalesce_grows", "transfer_restarts",
    ):
        assert key in transfer, key
    assert transfer["transfer_ingest_items"] >= 1
    assert transfer["transfer_ingest_bytes"] > 0
    assert transfer["transfer_dispatches"] == (
        transfer["transfer_ingest_items"]
        + transfer["transfer_prefetch_items"]
        + transfer["transfer_lockstep_items"]
    )
    assert transfer["transfer_restarts"] == 0
