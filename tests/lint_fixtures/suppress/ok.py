"""Fixture: reasoned suppressions — inline and comment-only coverage."""
import time


def shutdown(thread):
    time.sleep(5)  # lint: ok(timeout-discipline): fixture — documented grace


def shutdown2(q):
    # lint: ok(timeout-discipline): fixture — comment-only covers next stmt
    q.get(timeout=30)
