"""Fixture: a suppression matching no finding is itself reported."""

X = 1  # lint: ok(timeout-discipline): nothing here violates it
