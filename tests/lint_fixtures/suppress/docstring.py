"""Fixture: the grammar quoted in a docstring is not a suppression.

    time.sleep(5)  # lint: ok(timeout-discipline): docstring example
"""
import time

time.sleep(5)
