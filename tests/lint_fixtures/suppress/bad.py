"""Fixture: a reasonless suppression suppresses nothing and is reported."""
import time


def shutdown(thread):
    time.sleep(5)  # lint: ok(timeout-discipline)
