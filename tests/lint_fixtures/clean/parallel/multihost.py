"""Fixture: the one module allowed to touch the raw pod machinery."""
import jax
import jax.experimental.multihost_utils as multihost_utils


def initialize():
    jax.distributed.initialize()
    return multihost_utils.sync_global_devices("boot")
