"""Fixture: deadlines behind named knobs pass timeout-discipline."""
import time

# Documented shutdown grace, bounded by the scheduler's close() contract.
STOP_DRAIN_S = 5.0


def drain(ticket, q):
    ticket.result(timeout=STOP_DRAIN_S)
    time.sleep(0.1)
    q.get(timeout=0.5)


def lookups(counts, cfg):
    # .get's positionals are a dict key / queue block flag, not deadlines.
    return counts.get(5), cfg.get("retries", 30)
