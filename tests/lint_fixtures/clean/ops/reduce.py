"""Fixture: ops/ is a jit-building layer — raw collectives allowed."""
import jax


def psum_tree(x):
    return jax.lax.psum(x, "data")
