"""The one-place exemption: a module named exits.py may spell the typed
codes as literals — it IS the contract everyone else imports."""

EXIT_PREEMPTED = 75
EXIT_POD_SHRINK = 78
EXIT_SUPERVISOR_GAVE_UP = 79
