"""Fixture: typed raises pass typed-error."""


class ServeTimeout(RuntimeError):
    pass


def overload(pending, cap):
    if pending > cap:
        raise ServeTimeout("deadline expired")
    raise ValueError("bad request")
