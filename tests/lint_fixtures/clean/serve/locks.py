"""Fixture: poll-under-lock and gather-then-lock pass lock-discipline."""


class Dispatcher:
    def poll(self, fut):
        with self.dispatch_lock:
            return fut.result(timeout=0.0)

    def ordered(self, beat):
        vals = beat_allgather([beat])
        with self.dispatch_lock:
            return vals

    def deferred(self, ev):
        with self.dispatch_lock:
            # The lambda runs later, outside the lock.
            return submit(lambda: ev.wait())

    def poll_queue(self, q):
        with self.dispatch_lock:
            return q.get(timeout=0.0)

    def poll_queue_nonblocking(self, q):
        with self.dispatch_lock:
            return q.get(False)

    def lookup(self, table, key):
        with self.dispatch_lock:
            # dict.get: a key lookup, not a wait.
            return table.get(key, 0)
