"""Fixture: a collective inside a nested jit-program body is fine
anywhere — that is the shard_map closure shape."""
import jax


class Ring:
    def build(self):
        def body(block):
            return jax.lax.all_gather(block, "data")

        return body
