"""Fixture: the sanctioned re-bind idiom passes donation-safety."""
import jax


def train_step(state, batch):
    return state


step = jax.jit(train_step, donate_argnums=(0,))


def good_dispatch(state, batch):
    state = step(state, batch)
    return state
