"""Fixture: the sanctioned re-bind idiom passes donation-safety."""
import jax


def train_step(state, batch):
    return state


step = jax.jit(train_step, donate_argnums=(0,))


def good_dispatch(state, batch):
    state = step(state, batch)
    return state


def _jit_chunk(fn):
    return jax.jit(fn, donate_argnums=(0, 1, 4))


chunk_step = _jit_chunk(train_step)


def good_multi_arg(state, key, storage, size, priorities):
    out, key, priorities = chunk_step(state, key, storage, size, priorities)
    return out, key, priorities
