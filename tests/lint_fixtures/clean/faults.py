"""Fixture: every component has its failure-matrix row."""

COMPONENTS = ("worker",)
