"""Typed exits routed through the named constants: no findings.
An untyped shell status (sys.exit(2)) is not the contract's business."""
import sys

from exits import EXIT_PREEMPTED


def stop(code=EXIT_PREEMPTED):
    sys.exit(code)


def usage_error():
    sys.exit(2)
