"""Fixture renderer that covers the foo_* family."""

FAMILIES = ("foo_",)
