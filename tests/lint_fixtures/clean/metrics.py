"""Fixture: a fully documented, fully rendered *Stats family."""


class FooStats:
    def snapshot(self):
        out = {"foo_thing": 1}
        out["foo_other_thing"] = 2.0
        return out
