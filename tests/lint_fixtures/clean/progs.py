"""Fixture: sanctioned jit patterns pass recompile-hazard — hoisted
wrappers, the per-shape dict cache (replay/device.py _get_insert idiom),
and hashable static args."""
import jax


step = jax.jit(lambda s, n: s * n, static_argnums=(1,))
apply_fn = jax.jit(lambda v: v + 1)


@jax.jit
def decorated_apply(v):
    # A decorated def OUTSIDE any loop builds its wrapper once — clean.
    return v - 1


class ShapeCache:
    def __init__(self):
        self._cache = {}

    def program(self, m):
        fn = self._cache.get(m)
        if fn is None:
            fn = jax.jit(lambda x: x.reshape(m, -1))
            self._cache[m] = fn
        return fn


def good_loop(xs):
    outs = []
    for _ in range(4):
        outs.append(apply_fn(xs))
    return outs


def good_static_tuple(x):
    return step(x, (1, 2))


def good_fori_body(x):
    # The superstep idiom: the traced fori_loop body closes over a PLAIN
    # hoisted callable; the one jit wraps the function containing the
    # loop (not shown) — the body itself stays jit-free.
    def body(i, c):
        return apply_fn(c)
    return jax.lax.fori_loop(0, 4, body, x)
