"""Fixture: a fault component missing from the failure matrix."""

COMPONENTS = ("worker", "ghost")
