"""Fixture: inline literal deadlines timeout-discipline flags, next to the
sub-second poll cadences it deliberately allows."""
import time
from time import sleep


def nap():
    sleep(600)


def drain(ticket, q, thread):
    ticket.result(timeout=600.0)
    ticket.result(timeout=10 * 60)   # constant-folded spelling of 600s
    q.get(timeout=2.0)
    q.get(True, 600.0)    # queue.get's positional timeout form
    thread.join(30)
    time.sleep(5)
    time.sleep(0.1)       # poll cadence: allowed
    q.get(timeout=0.5)    # poll cadence: allowed
