"""Fixture: the jit-key hazard shapes recompile-hazard flags."""
import jax


step = jax.jit(lambda s, n: s * n, static_argnums=(1,))


def bad_loop_jit(xs):
    outs = []
    for k in range(4):
        f = jax.jit(lambda x: x * k)
        outs.append(f(xs))
    return outs


def bad_decorated_loop_jit(xs):
    outs = []
    for k in range(4):
        @jax.jit
        def g(x):
            return x * k
        outs.append(g(xs))
    return outs


def bad_inline_jit(x):
    return jax.jit(lambda v: v + 1)(x)


def bad_static_list(x):
    return step(x, [1, 2])


def bad_fori_body_jit(x):
    def body(i, c):
        # jit inside the TRACED loop body: re-enters the jit machinery
        # on every composition of the enclosing program.
        f = jax.jit(lambda v: v + 1)
        return f(c)
    return jax.lax.fori_loop(0, 4, body, x)
