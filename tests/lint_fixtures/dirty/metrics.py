"""Fixture: a *Stats family with an undocumented field and no renderer."""


class FooStats:
    def snapshot(self):
        out = {"foo_thing": 1}
        out["foo_other_thing"] = 2.0
        return out
