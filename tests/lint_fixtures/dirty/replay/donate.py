"""Fixture: the PR-9 read-after-donate bug class donation-safety flags."""
import functools

import jax


def train_step(state, batch):
    return state


step = jax.jit(train_step, donate_argnums=(0,))
donate = functools.partial(jax.jit, donate_argnums=(1,))
apply_batch = donate(train_step)


def bad_dispatch(state, batch):
    out = step(state, batch)
    return state


def bad_factory(state, batch):
    out = apply_batch(state, batch)
    return batch


def _jit_chunk(fn):
    """The parallel/learner.py local-def factory idiom: the helper's
    return carries the multi-arg donate tuple."""
    return jax.jit(fn, donate_argnums=(0, 1, 4))


chunk_step = _jit_chunk(train_step)


def bad_multi_arg(state, key, storage, size, priorities):
    out = chunk_step(state, key, storage, size, priorities)
    return priorities
