"""exit-code-literal fixtures: the typed exit codes spelled as bare
literals instead of the named constants from distributed_ddpg_tpu.exits.
Three findings: one shadowing EXIT_* assignment, two bare-literal exits.
"""
import os
import sys

_EXIT_CODE = 70  # BAD: local exit-code constant shadows the contract


def abandon(pod_shrink_ready):
    if pod_shrink_ready:
        os._exit(78)  # BAD: bare typed code in os._exit
    sys.exit(75)  # BAD: bare typed code in sys.exit
