"""Fixture: the bare raises typed-error forbids inside subsystem dirs."""


def overload(pending, cap):
    if pending > cap:
        raise RuntimeError("queue full")
    raise Exception("unreachable")
