"""Fixture: blocking waits and pod collectives under dispatch_lock."""


class Dispatcher:
    def bad_wait(self, fut):
        with self.dispatch_lock:
            return fut.result()

    def bad_collective(self, beat):
        with self.dispatch_lock:
            return beat_allgather([beat])

    def bad_after_deferred(self, ev):
        with self.dispatch_lock:
            cb = lambda: ev.wait()
            submit(cb)
            ev.wait()

    def bad_queue_get(self, q):
        with self.dispatch_lock:
            return q.get()
