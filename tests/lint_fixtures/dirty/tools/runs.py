"""Fixture renderer that knows nothing about the family metrics.py emits."""

FAMILIES = ("bar_",)
