"""Fixture: every shape collective-discipline flags (docs/ANALYSIS.md)."""
import jax
import jax.experimental.multihost_utils
from jax.experimental import multihost_utils


def bootstrap():
    jax.distributed.initialize()
    multihost_utils.sync_global_devices("ready")


def reduce_metrics(x):
    return jax.lax.psum(x, "data")


def reduce_aliased(x):
    from jax.lax import psum as psum_alias

    return psum_alias(x, "data")
