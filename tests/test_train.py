"""End-to-end driver tests: the native CLI path, the full async jax path
(actors + prefetch + sharded learner) on the fake 8-device mesh, and
checkpoint save/restore including replay (SURVEY.md §4 'Integration' and
'Fault/elastic' rows)."""

import os

import numpy as np
import pytest

from distributed_ddpg_tpu import checkpoint as ckpt_lib
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import init_train_state
from distributed_ddpg_tpu.replay import PrioritizedReplay
from distributed_ddpg_tpu.train import train_jax, train_native


def test_train_native_runs_and_reports_rate():
    cfg = DDPGConfig(
        backend="native",
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        total_env_steps=1500,
        replay_min_size=200,
        replay_capacity=10_000,
        eval_every=1000,
    )
    out = train_native(cfg)
    assert out["learner_steps"] == 1500 - 200 + 1
    assert out["learner_steps_per_sec"] > 10


def test_learner_chunk_resolution():
    """config.learner_chunk: explicit value wins; 0 = auto (8 on the CPU
    test platform, 800 only on kernel-native TPU backends)."""
    from distributed_ddpg_tpu.parallel.learner import resolve_learner_chunk

    assert resolve_learner_chunk(DDPGConfig(learner_chunk=4)) == 4
    assert resolve_learner_chunk(DDPGConfig()) == 8  # conftest pins cpu
    import distributed_ddpg_tpu.ops.fused_chunk as fc

    orig = fc.runs_native
    fc.runs_native = lambda: True
    try:
        assert resolve_learner_chunk(DDPGConfig()) == 800
    finally:
        fc.runs_native = orig
    with pytest.raises(ValueError, match="learner_chunk"):
        DDPGConfig(learner_chunk=-1)
    # The two rate caps point at each other: with ratio product < 1 each
    # allowance waits on the other forever (livelock); product >= 1 is the
    # equal-return gate's both-sides pin and must be accepted.
    with pytest.raises(ValueError, match="livelock"):
        DDPGConfig(max_learn_ratio=0.5, max_ingest_ratio=0.5)
    DDPGConfig(max_learn_ratio=1.0, max_ingest_ratio=1.0)
    DDPGConfig(max_learn_ratio=1.0, max_ingest_ratio=50.0)
    # Staleness-sweep experiment knob (worker-side env-production brake).
    DDPGConfig(actor_throttle_s=0.25)
    with pytest.raises(ValueError, match="actor_throttle_s"):
        DDPGConfig(actor_throttle_s=-0.1)


@pytest.mark.slow
def test_train_jax_max_learn_ratio_caps_learner(tmp_path):
    """max_learn_ratio: the learner may not run ahead of
    replay_min_size + ratio * env_steps (the equal-return gate's knob —
    free-running async would do orders of magnitude more grad steps per
    env step than the reference's sync semantics)."""
    cfg = DDPGConfig(
        backend="jax_tpu",
        env_id="Pendulum-v1",
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        num_actors=2,
        total_env_steps=3_000,
        replay_min_size=500,
        replay_capacity=20_000,
        max_learn_ratio=1.0,
        eval_every=0,
        log_path=str(tmp_path / "metrics.jsonl"),
    )
    out = train_jax(cfg)
    # Overshoot is bounded by one chunk past the cap at the final env-step
    # count (env steps keep arriving while the last chunks dispatch, so use
    # the generous bound: budget + one chunk).
    from distributed_ddpg_tpu.parallel.learner import resolve_learner_chunk

    chunk = resolve_learner_chunk(cfg)
    assert out["learner_steps"] > 0
    assert out["learner_steps"] <= cfg.replay_min_size + cfg.total_env_steps * 1.1 + chunk


def test_train_jax_tiny_budget_takes_at_least_one_chunk(tmp_path):
    """Regression: with free ingest (max_ingest_ratio=0) a fast actor can
    deliver the entire env-step budget during warmup. The budget break must
    not fire before the first learner dispatch — a run that met
    replay_min_size and reports success must have learner_steps > 0.
    Budget == replay_min_size makes the overfill deterministic: warmup
    necessarily consumes the whole budget."""
    cfg = DDPGConfig(
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=1,
        total_env_steps=128,
        replay_min_size=128,
        replay_capacity=5_000,
        eval_every=0,
        log_path=str(tmp_path / "metrics.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0


@pytest.mark.slow
def test_train_jax_auto_support_resolves_and_reports(tmp_path):
    """train_jax with --v_min=auto --v_max=auto: the warmup sizing must
    resolve concrete bounds before the first dispatch, and the running
    expansion check (incl. the round-5 data-corroboration closure over
    replay.reward_sample) must execute without error and report
    v_min/v_max/support_refusals in the metrics stream."""
    import json

    path = tmp_path / "metrics.jsonl"
    cfg = DDPGConfig(
        distributional=True,
        num_atoms=11,
        v_min=float("nan"),  # the 'auto' sentinel (config.from_flags)
        v_max=float("nan"),
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=1,
        total_env_steps=1_200,
        replay_min_size=256,
        replay_capacity=5_000,
        eval_every=0,
        # Lockstep + a tiny pinned chunk: the support metrics ride the
        # 50*chunk cadence, which a free-running tiny budget never reaches
        # (the whole env budget can drain during the first compile).
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        learner_chunk=4,
        log_path=str(path),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    sup = [r for r in rows if "v_min" in r and "v_max" in r]
    assert sup, "no support metrics reported"
    assert all(np.isfinite(r["v_min"]) and np.isfinite(r["v_max"])
               for r in sup)
    assert all(r["v_min"] < r["v_max"] for r in sup)
    assert "support_refusals" in sup[-1]


@pytest.mark.slow
def test_train_jax_async_pipeline(tmp_path):
    cfg = DDPGConfig(
        backend="jax_tpu",
        env_id="Pendulum-v1",
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        num_actors=2,
        total_env_steps=4_000,
        replay_min_size=500,
        replay_capacity=50_000,
        prioritized=True,
        n_step=3,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=40,
        log_path=str(tmp_path / "metrics.jsonl"),
        # Rate-limit ingest so the 4000-step budget guarantees >= ~70
        # learner steps regardless of how fast the actors produce (the shm
        # transport buffers far more than the old queue did).
        max_ingest_ratio=50.0,
    )
    out = train_jax(cfg)
    assert out["learner_steps"] >= 40
    assert np.isfinite(out["final_return"])
    # JSONL metrics were written.
    lines = open(cfg.log_path).read().strip().splitlines()
    assert len(lines) >= 1
    # A checkpoint landed.
    assert ckpt_lib.latest_step(cfg.checkpoint_dir) is not None


def test_checkpoint_roundtrip_with_replay(tmp_path):
    cfg = DDPGConfig(actor_hidden=(16, 16), critic_hidden=(16, 16), prioritized=True)
    state = init_train_state(cfg, 4, 2, seed=0)
    replay = PrioritizedReplay(64, 4, 2, seed=0)
    rng = np.random.default_rng(0)
    for i in range(20):
        replay.add(
            rng.standard_normal(4).astype(np.float32),
            rng.standard_normal(2).astype(np.float32),
            float(i), 0.99,
            rng.standard_normal(4).astype(np.float32),
        )
    replay.update_priorities(np.arange(20), np.linspace(0.1, 2.0, 20))

    path = ckpt_lib.save(str(tmp_path), 42, state, replay, cfg)
    assert os.path.exists(path)

    fresh_replay = PrioritizedReplay(64, 4, 2, seed=1)
    template = init_train_state(cfg, 4, 2, seed=99)
    restored, step, env_steps = ckpt_lib.restore(str(tmp_path), template, fresh_replay)
    assert step == 42
    assert len(fresh_replay) == 20
    np.testing.assert_array_equal(fresh_replay.reward[:20], replay.reward[:20])
    np.testing.assert_allclose(
        fresh_replay._tree.get(np.arange(20)), replay._tree.get(np.arange(20))
    )
    import jax

    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(jax.device_get(state))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_auto_support_host_replay_multiprocess_rejected(monkeypatch):
    """Host replay is process-local; auto-support bounds derived from it
    would differ per replica and fork the compiled programs — train_jax
    must refuse the combination loudly."""
    import jax as jax_mod

    monkeypatch.setattr(jax_mod, "process_count", lambda: 2)
    cfg = DDPGConfig(
        distributional=True,
        num_atoms=11,
        v_min=float("nan"),
        v_max=float("nan"),
        host_replay=True,
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        total_env_steps=256,
        replay_min_size=128,
    )
    with pytest.raises(ValueError, match="host_replay.*multi-process"):
        train_jax(cfg)


def test_checkpoint_retention_prunes_old_steps(tmp_path):
    """Latest-N retention (round-5 disk incident: a full-replay checkpoint
    is ~3 GB and the saver kept every cadence point — a 2M-step run would
    fill the disk). Old step_*/config_* pairs must go; keep=0 keeps all;
    restore must still find the latest."""
    cfg = DDPGConfig(actor_hidden=(16, 16), critic_hidden=(16, 16))
    state = init_train_state(cfg, 4, 2, seed=0)
    for step in (10, 20, 30, 40, 50):
        ckpt_lib.save(str(tmp_path), step, state, None, cfg, keep=3)
    kept = sorted(p for p in os.listdir(tmp_path) if p.startswith("step_"))
    assert kept == ["step_30", "step_40", "step_50"]
    cfgs = sorted(p for p in os.listdir(tmp_path) if p.startswith("config_"))
    assert cfgs == ["config_30.json", "config_40.json", "config_50.json"]
    assert ckpt_lib.latest_step(str(tmp_path)) == 50
    # keep=0 disables pruning entirely.
    ckpt_lib.save(str(tmp_path), 60, state, None, cfg, keep=0)
    assert len([p for p in os.listdir(tmp_path) if p.startswith("step_")]) == 4


def test_checkpoint_retention_protects_fresh_save_from_stale_dirs(tmp_path):
    """A fresh run reusing a directory with HIGHER-numbered stale
    checkpoints (the --resume=false reuse workflow) must never prune the
    checkpoint it just wrote — numeric sorting alone would. And the stale
    higher-numbered dirs themselves must GO (loudly): left in place they
    would permanently occupy the keep-N retention slots (every later save
    deleting the run's own previous checkpoint) and keep
    latest_step()/resume pointing at another run's state."""
    cfg = DDPGConfig(actor_hidden=(16, 16), critic_hidden=(16, 16))
    state = init_train_state(cfg, 4, 2, seed=0)
    for stale in (100_000, 110_000, 120_000):
        ckpt_lib.save(str(tmp_path), stale, state, None, cfg, keep=0)
    ckpt_lib.save(str(tmp_path), 10_000, state, None, cfg, keep=3)
    kept = {p for p in os.listdir(tmp_path) if p.startswith("step_")}
    assert kept == {"step_10000"}, (
        f"stale higher-numbered checkpoints must be pruned: {kept}"
    )
    # Resume now finds THIS run's state, and the next saves rebuild the
    # keep-N redundancy below it.
    assert ckpt_lib.latest_step(str(tmp_path)) == 10_000
    ckpt_lib.save(str(tmp_path), 20_000, state, None, cfg, keep=3)
    ckpt_lib.save(str(tmp_path), 30_000, state, None, cfg, keep=3)
    kept = sorted(p for p in os.listdir(tmp_path) if p.startswith("step_"))
    assert kept == ["step_10000", "step_20000", "step_30000"]


@pytest.mark.slow
def test_train_jax_device_replay_path(tmp_path):
    """Uniform replay -> device-resident buffer with fused on-device
    sampling (the zero-h2d steady-state path); periodic eval runs in the
    background thread and still lands its JSONL records."""
    cfg = DDPGConfig(
        backend="jax_tpu",
        env_id="Pendulum-v1",
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        num_actors=2,
        total_env_steps=3_000,
        replay_min_size=300,
        replay_capacity=20_000,
        prioritized=False,
        eval_every=1_000,
        eval_episodes=1,
        log_path=str(tmp_path / "metrics.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    assert np.isfinite(out["final_return"])
    import json

    kinds = [json.loads(l)["kind"] for l in open(cfg.log_path)]
    assert "eval" in kinds, f"no background-eval record in {kinds}"
    # Per-phase timing breakdown (SURVEY.md §5) rides in the train/final
    # records (train cadence is 50 chunks; short runs still get the final).
    recs = [json.loads(l) for l in open(cfg.log_path)]
    assert any("t_dispatch_ms" in r for r in recs), recs


def test_async_saver_snapshot_isolation(tmp_path):
    """save_async must snapshot at call time: mutations made to the replay
    AFTER save_async returns (but possibly before the background write
    finishes) must not leak into the checkpoint. Also: while the writer is
    busy, further saves coalesce (skip) instead of queueing."""
    from distributed_ddpg_tpu.replay import UniformReplay

    cfg = DDPGConfig(actor_hidden=(16, 16), critic_hidden=(16, 16))
    state = init_train_state(cfg, 4, 2, seed=0)
    replay = UniformReplay(50_000, 4, 2, seed=0)
    rng = np.random.default_rng(0)
    obs = rng.standard_normal((40_000, 4)).astype(np.float32)
    replay.add_batch(
        obs,
        rng.standard_normal((40_000, 2)).astype(np.float32),
        np.arange(40_000, dtype=np.float32),
        np.full(40_000, 0.99, np.float32),
        obs,
    )
    saver = ckpt_lib.AsyncSaver()
    assert saver.save_async(str(tmp_path), 3, state, replay, cfg) is True
    # Mutate immediately — the background write must not see this.
    replay.reward[:40_000] = -1.0
    saver.wait()
    fresh = UniformReplay(50_000, 4, 2, seed=1)
    _, step, _ = ckpt_lib.restore(str(tmp_path), init_train_state(cfg, 4, 2, seed=2), fresh)
    assert step == 3 and len(fresh) == 40_000
    np.testing.assert_array_equal(
        fresh.reward[:40_000], np.arange(40_000, dtype=np.float32)
    )


def test_checkpoint_roundtrip_device_replay(tmp_path):
    """Restore must work into a fresh (empty) DeviceReplay template — the
    resume path in train_jax."""
    from distributed_ddpg_tpu.parallel.mesh import make_mesh
    from distributed_ddpg_tpu.replay.device import DeviceReplay
    from distributed_ddpg_tpu.types import pack_batch_np

    cfg = DDPGConfig(actor_hidden=(16, 16), critic_hidden=(16, 16))
    state = init_train_state(cfg, 4, 2, seed=0)
    mesh = make_mesh(-1, 1)
    rep = DeviceReplay(128, 4, 2, mesh=mesh, block_size=32)
    rng = np.random.default_rng(0)
    rep.add_packed(
        pack_batch_np(
            {
                "obs": rng.standard_normal((64, 4)).astype(np.float32),
                "action": rng.standard_normal((64, 2)).astype(np.float32),
                "reward": rng.standard_normal(64).astype(np.float32),
                "discount": np.full(64, 0.99, np.float32),
                "next_obs": rng.standard_normal((64, 4)).astype(np.float32),
            }
        )
    )
    ckpt_lib.save(str(tmp_path), 7, state, rep, cfg)

    fresh = DeviceReplay(128, 4, 2, mesh=mesh, block_size=32)
    template = init_train_state(cfg, 4, 2, seed=9)
    restored, step, env_steps = ckpt_lib.restore(str(tmp_path), template, fresh)
    assert step == 7 and len(fresh) == 64
    import jax

    np.testing.assert_allclose(
        np.asarray(jax.device_get(fresh.storage))[:64],
        np.asarray(jax.device_get(rep.storage))[:64],
    )


def test_restore_rejects_incompatible_config(tmp_path):
    cfg = DDPGConfig(actor_hidden=(16, 16), critic_hidden=(16, 16))
    state = init_train_state(cfg, 4, 2, seed=0)
    ckpt_lib.save(str(tmp_path), 5, state, None, cfg, env_steps=1234)
    # Same config restores fine and carries env_steps.
    _, step, env_steps = ckpt_lib.restore(
        str(tmp_path), init_train_state(cfg, 4, 2, seed=1), config=cfg
    )
    assert step == 5 and env_steps == 1234
    # Changed architecture must be rejected with a named mismatch.
    bad = DDPGConfig(actor_hidden=(32, 32), critic_hidden=(16, 16))
    with pytest.raises(ValueError, match="actor_hidden"):
        ckpt_lib.restore(
            str(tmp_path), init_train_state(bad, 4, 2, seed=1), config=bad
        )
