"""TPU-present test tier (VERDICT.md round-2 Missing #5 / Next #5): tests
that compile NATIVELY on an attached TPU, auto-skipped when none is
attached. Each case runs in a subprocess (tests/tpu_child.py) because
conftest.py pins this process's JAX to the virtual CPU platform — the very
pin that made the round-2 megakernel failure invisible to the suite.

Run explicitly:  python -m pytest tests/test_tpu.py -m tpu -q
(The default suite also collects these; they skip in seconds without TPU.)
"""

import json
import os
import subprocess
import sys

import pytest

# Also `slow`: without a TPU attached these skip in seconds, but against
# a WEDGED tunnel (plugin present, compute hung — the 2026-07-31 flap
# pattern) the session probe fixture costs its full 90s bound, which is
# the fast tier's single biggest line item. The recovery runbook invokes
# this file explicitly (no -m filter), so the tpu tier still runs there.
pytestmark = [pytest.mark.tpu, pytest.mark.slow]

CHILD = os.path.join(os.path.dirname(__file__), "tpu_child.py")


def _run_child(case: str, timeout: float = 600) -> dict:
    # Strip the parent suite's CPU pin, and surgically remove only the
    # conftest-injected virtual-device token from XLA_FLAGS — any
    # operator-supplied flags must reach the child unchanged.
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    if "XLA_FLAGS" in env:
        kept = [
            tok
            for tok in env["XLA_FLAGS"].split()
            if "xla_force_host_platform_device_count" not in tok
        ]
        if kept:
            env["XLA_FLAGS"] = " ".join(kept)
        else:
            del env["XLA_FLAGS"]
    env["JAX_TRACEBACK_FILTERING"] = "off"
    proc = subprocess.run(
        [sys.executable, CHILD, case],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-15:]
        raise AssertionError(f"{case} child failed:\n" + "\n".join(tail))
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError(f"{case} child printed no JSON: {proc.stdout!r}")


@pytest.fixture(scope="session")
def tpu():
    if os.environ.get("TPU_TIER", "") == "skip":
        # Explicit bypass for dev/CI runs that know no chip is attached —
        # skips without paying the probe at all.
        pytest.skip("TPU tier bypassed (TPU_TIER=skip)")
    try:
        # 90s is THE liveness bound (scripts/tpu_alive.py / the recovery
        # runbook): covers a cold connect+compile (~30-40s observed) with
        # margin, while a WEDGED tunnel costs the fast tier exactly one
        # bounded probe instead of a long hang (a 180s probe was the fast
        # tier's single biggest line item during the 2026-07 incident).
        probe = _run_child("probe", timeout=90)
    except Exception as e:  # backend init failure == no usable TPU
        pytest.skip(f"no native TPU backend: {e}")
    if not probe.get("is_tpu"):
        pytest.skip(f"no native TPU backend attached: {probe}")
    return probe


def test_fused_kernel_native_parity(tpu):
    """The pallas megakernel must COMPILE under real Mosaic (not interpret
    mode) and match the XLA scan path on the same chunk."""
    out = _run_child("fused_parity")
    assert out["ok"]


def test_fused_kernel_native_parity_c51(tpu):
    """The D4PG (C51) kernel branch — in-kernel categorical projection and
    closed-form cotangents — must compile under real Mosaic and match the
    scan path."""
    out = _run_child("fused_parity_c51")
    assert out["ok"]


def test_fused_kernel_native_parity_bf16(tpu):
    """The bf16 kernel (MXU-rate dots, f32 accumulate) must compile under
    real Mosaic and track the bf16 scan path within rounding."""
    out = _run_child("fused_parity_bf16")
    assert out["ok"]


def test_fused_kernel_native_parity_td3(tpu):
    """The TD3 kernel branch — twin member groups, streamed smoothing
    noise, pl.when-delayed updates — must compile under real Mosaic and
    match the scan path."""
    out = _run_child("fused_parity_td3")
    assert out["ok"]


def test_fused_kernel_native_parity_sac(tpu):
    """The SAC kernel branch — Gaussian-head lane split, streamed sampling
    normals, squash log-prob backward, scalar temperature Adam — must
    compile under real Mosaic and match the scan path."""
    out = _run_child("fused_parity_sac")
    assert out["ok"]


def test_device_replay_ingest_and_sample_chunk(tpu):
    """Real h2d DeviceReplay ingest + the production run_sample_chunk
    dispatch; fused_chunk='auto' must actually activate on real TPU (if it
    silently fell back, the flagship path is not being tested)."""
    out = _run_child("sample_chunk")
    assert out["ok"]
    assert out["fused_chunk_active"], (
        "megakernel did not activate on real TPU: "
        f"{out.get('fused_chunk_error')}"
    )
    # The native capture must carry the ingest breakdown (ROADMAP item:
    # CPU sweeps had it, TPU captures dropped it) — these are the fields
    # BENCH comparisons and tools.runs read.
    assert out["ingest_ship_calls"] >= 1
    assert out["ingest_rows_per_sec"] > 0
