"""Child process for tests/test_multihost.py: one of N processes in a
jax.distributed CPU cluster (SURVEY.md §4 'Multi-host path tested with
jax.distributed.initialize across local subprocesses').

Each process contributes 2 fake CPU devices; the global (data=N*2, model=1)
mesh spans processes, so the learner's gradient AllReduce crosses the
process boundary (Gloo here, DCN on a real pod — parallel/multihost.py).
Runs one deterministic learner chunk and prints a parity line the parent
compares across processes and against a single-process run.

Usage: python multihost_child.py <process_id> <num_processes> <port>
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])

    # Exercise the production bootstrap via its env-var path.
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
    os.environ["JAX_NUM_PROCESSES"] = str(nprocs)
    os.environ["JAX_PROCESS_ID"] = str(pid)

    from distributed_ddpg_tpu.parallel import multihost

    assert multihost.initialize() is True
    info = multihost.process_info()
    assert info["process_count"] == nprocs, info
    assert info["global_device_count"] == 2 * nprocs, info

    import numpy as np

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner

    run_parity_chunk(ShardedLearner, DDPGConfig, np, tag=f"proc{pid}")


def run_parity_chunk(ShardedLearner, DDPGConfig, np, tag: str) -> None:
    """Deterministic 2-step chunk at batch 16 over however many devices are
    visible; prints 'PARITY <tag> <critic_loss> <param_checksum>'."""
    config = DDPGConfig(
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        batch_size=16,
        seed=0,
    )
    learner = ShardedLearner(config, 5, 2, action_scale=1.0, chunk_size=2)
    rng = np.random.default_rng(0)
    k, b = 2, config.batch_size
    chunk = {
        "obs": rng.standard_normal((k, b, 5)).astype(np.float32),
        "action": rng.uniform(-1, 1, (k, b, 2)).astype(np.float32),
        "reward": rng.standard_normal((k, b)).astype(np.float32),
        "discount": np.full((k, b), 0.99, np.float32),
        "next_obs": rng.standard_normal((k, b, 5)).astype(np.float32),
        "weight": np.ones((k, b), np.float32),
    }
    out = learner.run_chunk(chunk)
    import jax

    loss = float(jax.device_get(out.metrics["critic_loss"]))
    leaves = jax.tree.leaves(jax.device_get(learner.state.actor_params))
    checksum = float(sum(np.abs(leaf).sum() for leaf in leaves))
    print(f"PARITY {tag} {loss:.8f} {checksum:.6f}", flush=True)


if __name__ == "__main__":
    main()
