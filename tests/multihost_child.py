"""Child process for tests/test_multihost.py: one of N processes in a
jax.distributed CPU cluster (SURVEY.md §4 'Multi-host path tested with
jax.distributed.initialize across local subprocesses').

Each process contributes 2 fake CPU devices; the global (data=N*2, model=1)
mesh spans processes, so the learner's gradient AllReduce crosses the
process boundary (Gloo here, DCN on a real pod — parallel/multihost.py).
Runs one deterministic learner chunk and prints a parity line the parent
compares across processes and against a single-process run.

Usage: python multihost_child.py <process_id> <num_processes> <port> [mode]
  mode = chunk  (default): one deterministic learner chunk, parity line
  mode = replay: DeviceReplay lockstep ingest (sync_ship) + fused-sampling
                 chunk; asserts the replicated storage is identical and
                 contains BOTH processes' rows exactly once
  mode = train:  the FULL train_jax loop (actors + device replay + sharded
                 learner) across the process boundary; parity on the final
                 param checksum (VERDICT.md round-1 Missing #3)
  mode = fused:  the megakernel x mesh composition (fused_mesh, K-step
                 local SGD) on a mesh that SPANS processes — the
                 chunk-boundary param pmean crosses the process boundary
                 (Gloo here, DCN on a pod); parity on the end state
  mode = coalesce: coalesced lockstep sync_ship (super-block all-gather
                 insert with the on-device per-process interleave
                 transpose) vs the seed's serial max_coalesce=1 sequence
                 in the SAME cluster — storage/ptr/size must come out
                 bit-identical (docs/INGEST.md)
  mode = podtrain: the full train_jax loop under the POD-RESILIENCE
                 contract (docs/RESILIENCE.md pod rows): pod fault specs
                 (pod:<proc>:kill|hang@beat), collective deadline, and
                 checkpoint dirs arrive via POD_* env vars; the child
                 exits train.EXIT_POD_DEGRADED (76) when a peer is lost
                 and 0 on a clean (or resumed) completion. Parent:
                 tests/test_pod.py.

Every mode runs `multihost.startup_barrier` right after initialize: the
one-time generous rendezvous absorbs backend-init/import skew under box
load, which used to surface as startup heartbeat timeouts in these
children on contended hosts (CHANGES.md PR 5 note).
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "chunk"

    if nprocs > 1:
        # Exercise the production bootstrap via its env-var path.
        os.environ["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
        os.environ["JAX_NUM_PROCESSES"] = str(nprocs)
        os.environ["JAX_PROCESS_ID"] = str(pid)

        # The multiprocess CPU backend needs an explicit collectives
        # transport (the Gloo the module docstring's 'Gloo here, DCN on a
        # pod' refers to): without it, cross-process computations fail
        # with "Multiprocess computations aren't implemented on the CPU
        # backend". Set before the backend is created, and only on the
        # actual child path — gloo setup requires a distributed client,
        # so a single-process import of this module (the parity oracle)
        # must not inherit it.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from distributed_ddpg_tpu.parallel import multihost

    if nprocs > 1:
        assert multihost.initialize() is True
        info = multihost.process_info()
        assert info["process_count"] == nprocs, info
        assert info["global_device_count"] == 2 * nprocs, info

        # Startup hardening (ISSUE 6 satellite): rendezvous once with a
        # generous grace so a peer still paying backend-init/import cost
        # under box load doesn't turn the first real collective into a
        # "startup heartbeat timeout" flake. Distinct from (and much
        # larger than) any steady-state collective deadline the mode
        # then arms.
        multihost.startup_barrier(
            float(os.environ.get("POD_STARTUP_GRACE_S", "240"))
        )
    # nprocs == 1: no distributed bootstrap, no gloo, no barrier — the
    # shape of a supervisor's shrunk-to-one generation (ISSUE 19). The
    # run behaves like the elastic test's in-process M=1 adoption phase
    # (tests/test_pod.py test_two_process_elastic_shrink_then_grow).

    import numpy as np

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner

    if mode == "podtrain":
        run_pod_train(pid, tag=f"proc{pid}")
    elif mode == "chunk":
        run_parity_chunk(ShardedLearner, DDPGConfig, np, tag=f"proc{pid}")
    elif mode == "replay":
        run_replay_parity(pid, nprocs, tag=f"proc{pid}")
    elif mode == "coalesce":
        run_coalesced_ingest_parity(pid, tag=f"proc{pid}")
    elif mode == "bgsync":
        run_background_sync_ship_parity(pid, tag=f"proc{pid}")
    elif mode == "train":
        run_train_parity(tag=f"proc{pid}")
    elif mode == "fused":
        run_fused_mesh_parity(tag=f"proc{pid}")
    else:
        raise SystemExit(f"unknown mode {mode!r}")


def run_pod_train(pid: int, tag: str) -> None:
    """Full train_jax under the pod-resilience contract. Parameterized by
    env vars (the parent launches N identical children, so per-run knobs
    can't ride argv):

      POD_FAULTS          --faults plan (e.g. 'pod:1:kill@40'); same
                          string everywhere — only the targeted process
                          fires, every process ticks the beat ordinal
      POD_CKPT_DIR        shared checkpoint dir ('' = no checkpoints)
      POD_LOG_DIR         JSONL dir; this child writes proc<pid>.jsonl
      POD_TOTAL_STEPS     global env-step budget
      POD_TIMEOUT_S       pod_collective_timeout_s
      POD_STARTUP_GRACE_S pod_startup_grace_s (also the barrier above)
      POD_BG_SYNC         '1' = background sync_ship beats (the
                          production default). Default '0' here: chunk
                          execution overlapping lane beats can tickle a
                          pre-existing concurrent-gloo-collective race
                          on the multiprocess CPU backend (the PR-5
                          child-flake note), and THIS harness is pinning
                          the pod-abort contract, not the overlap.
      POD_OBS_PORT_BASE   when set, arm the telemetry ingress on port
                          base+pid (obs/exporter.py): the parent scrapes
                          /metrics and /healthz live during the drill
                          (tests/test_obs.py; docs/OBSERVABILITY.md §4)
      POD_TRACE_DIR       when set, arm the flight recorder with
                          trace_dir=<dir>/proc<pid> — each child exports
                          its own trace.json for the parent's merge-trace
                          assertion (clock-aligned pod timeline)

    Prints 'PODRESULT <tag> steps=<n> degraded=<0|1> elected=<step>
    adopted=<n> shrinks=<n> grows=<n> shrinkready=<0|1>' and exits with
    train.py's documented code (78 on pod degradation with a complete
    replay slice set on disk — relaunch-smaller-ready; 76 on pod
    degradation without one; 75 on preemption, 0 clean) so the parent
    asserts the REAL contract."""
    import tempfile

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.train import EXIT_PREEMPTED, train_jax

    # The multiprocess CPU backend races concurrently-executing
    # computations that both carry gloo collectives (async dispatch lets
    # the sync_ship insert / learner chunk still be executing when the
    # next host gather runs — observed as nondeterministic
    # `gloo EnforceNotMet op.preamble.length <= op.nbytes` stream
    # corruption). Synchronous dispatch serializes the per-process device
    # stream, so the only collective failures this harness sees are the
    # INJECTED ones under test. CPU-test-only: real TPU backends separate
    # collective channels in hardware.
    import jax as _jax

    _jax.config.update("jax_cpu_enable_async_dispatch", False)

    log_dir = os.environ.get("POD_LOG_DIR", "")
    obs_port_base = int(os.environ.get("POD_OBS_PORT_BASE", "0"))
    trace_root = os.environ.get("POD_TRACE_DIR", "")
    trace_dir = ""
    if trace_root:
        trace_dir = os.path.join(trace_root, f"proc{pid}")
        os.makedirs(trace_dir, exist_ok=True)
    config = DDPGConfig(
        backend="jax_tpu",
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        batch_size=16,
        num_actors=1,
        total_env_steps=int(os.environ.get("POD_TOTAL_STEPS", "200000")),
        replay_min_size=128,
        replay_capacity=8192,
        eval_every=0,
        eval_episodes=1,
        checkpoint_dir=os.environ.get("POD_CKPT_DIR", ""),
        # Small cadence so the pod has retained checkpoints besides the
        # emergency one — the resume election must pick among several.
        checkpoint_every=int(os.environ.get("POD_CKPT_EVERY", "64")),
        faults=os.environ.get("POD_FAULTS", ""),
        # Sharded device replay (docs/REPLAY_SHARDING.md): the sharded-
        # mode chaos run drives the SAME pod contract over the
        # shard_exchange beat lane (POD_REPLAY_SHARDING=sharded).
        replay_sharding=os.environ.get("POD_REPLAY_SHARDING", "replicated"),
        pod_collective_timeout_s=float(os.environ.get("POD_TIMEOUT_S", "20")),
        pod_startup_grace_s=float(
            os.environ.get("POD_STARTUP_GRACE_S", "240")
        ),
        sync_ship_background=os.environ.get("POD_BG_SYNC", "0") == "1",
        log_path=(
            os.path.join(log_dir, f"proc{pid}.jsonl")
            if log_dir
            else tempfile.mktemp(suffix=".jsonl")
        ),
        # The pod deadline owns hang detection here; the watchdog's
        # os._exit(70) would race the clean-abort path under test.
        watchdog_s=0.0,
        # Telemetry plane (obs/; docs/OBSERVABILITY.md §4): per-process
        # ingress port and per-process trace ring, both off unless the
        # parent opts in.
        obs_port=(obs_port_base + pid) if obs_port_base else 0,
        trace_dir=trace_dir,
    )
    out = train_jax(config)
    print(
        f"PODRESULT {tag} steps={out['learner_steps']} "
        f"degraded={int(bool(out.get('pod_degraded')))} "
        f"elected={out.get('pod_resume_step_elected', -1)} "
        f"adopted={out.get('pod_slices_adopted', 0)} "
        f"shrinks={out.get('pod_shrinks', 0)} "
        f"grows={out.get('pod_grows', 0)} "
        f"shrinkready={int(bool(out.get('pod_shrink_ready')))}",
        flush=True,
    )
    if out.get("pod_degraded"):
        # The documented exit discipline (leader linger + os._exit) —
        # the same call train.main() makes, including the elastic
        # shrink-ready 78/76 split (docs/RESILIENCE.md).
        from distributed_ddpg_tpu.train import (
            EXIT_POD_DEGRADED,
            EXIT_POD_SHRINK,
            pod_degraded_exit,
        )

        pod_degraded_exit(
            code=(
                EXIT_POD_SHRINK
                if out.get("pod_shrink_ready")
                else EXIT_POD_DEGRADED
            )
        )
    if out.get("preempted"):
        raise SystemExit(EXIT_PREEMPTED)


def run_fused_mesh_parity(tag: str) -> None:
    """Megakernel x mesh across the process boundary: every one of the 4
    global devices (2 per process) runs the whole K-step chunk in one
    pallas launch (interpret mode on CPU) on its own draws, then the
    chunk-boundary float-state pmean rides the cross-process collective.
    Identical replicated storage on both processes -> the per-device draws
    are a pure function of the replicated key stream -> both processes
    must print identical losses and end-state checksums; a fork means the
    boundary AllReduce or the axis-folded draw streams diverged."""
    import numpy as np

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import DeviceReplay

    obs_dim, act_dim = 5, 2
    config = DDPGConfig(
        actor_hidden=(16, 16), critic_hidden=(16, 16), batch_size=8,
        seed=0, fused_chunk="on",
    )
    learner = ShardedLearner(
        config, obs_dim, act_dim, action_scale=1.0, chunk_size=2
    )
    assert learner.fused_mesh_active, (
        "fused_mesh must activate on the cross-process data mesh: "
        f"{learner.fused_chunk_error}"
    )
    replay = DeviceReplay(
        256, obs_dim, act_dim, mesh=learner.mesh, block_size=64
    )
    rng = np.random.default_rng(7)
    width = replay.width
    # Multi-process add_packed only buffers host-side; rows land via the
    # lockstep sync_ship (same discipline as run_replay_parity — without
    # it the storage stays empty and the parity check is vacuous).
    replay.add_packed(rng.standard_normal((128, width)).astype(np.float32))
    moved = replay.sync_ship()
    moved += replay.sync_ship(force=True)
    assert moved > 0 and len(replay) > 0, (moved, len(replay))
    out = learner.run_sample_chunk(replay)
    import jax

    loss = float(jax.device_get(out.metrics["critic_loss"]))
    out2 = learner.run_sample_chunk(replay)
    loss2 = float(jax.device_get(out2.metrics["critic_loss"]))
    leaves = jax.tree.leaves(jax.device_get(learner.state.actor_params))
    checksum = float(sum(np.abs(leaf).sum() for leaf in leaves))
    print(f"PARITY {tag} {loss:.8f}/{loss2:.8f} {checksum:.6f}", flush=True)


def run_coalesced_ingest_parity(pid: int, tag: str) -> None:
    """Two DeviceReplay instances in the SAME jax.distributed cluster, fed
    identical per-process rows: `serial` ships with max_coalesce=1 (the
    seed's exact one-global-block-per-collective sequence), `coal` with
    max_coalesce=4 (super-block all-gather inserts whose on-device
    transpose must reproduce the serial per-process block interleave).
    Every process calls both replays' sync_ship at the same points, so the
    collective schedule stays lockstep; the parity line carries a local
    bit-identity verdict plus the coalesced storage checksum the parent
    compares across processes (replica consistency)."""
    import numpy as np

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import DeviceReplay

    obs_dim, act_dim = 5, 2
    config = DDPGConfig(
        actor_hidden=(16, 16), critic_hidden=(16, 16), batch_size=16, seed=0
    )
    learner = ShardedLearner(config, obs_dim, act_dim, action_scale=1.0,
                             chunk_size=2)
    serial = DeviceReplay(8192, obs_dim, act_dim, mesh=learner.mesh,
                          block_size=128, max_coalesce=1)
    coal = DeviceReplay(8192, obs_dim, act_dim, mesh=learner.mesh,
                        block_size=128, max_coalesce=4)
    r = np.random.default_rng(50 + pid)
    # 5 full blocks (serial: 5 collectives; coal: one k=4 + one k=1) plus
    # a 37-row remainder for the force-padded block.
    rows = (0.1 * r.standard_normal((5 * 128 + 37, serial.width))).astype(
        np.float32
    )
    for rep in (serial, coal):
        rep.add_packed(rows.copy())
        moved = rep.sync_ship()
        moved += rep.sync_ship(force=True)
        assert moved == len(rows), (moved, len(rows))

    import jax

    s0 = np.asarray(jax.device_get(serial.storage))
    s1 = np.asarray(jax.device_get(coal.storage))
    identical = bool(
        np.array_equal(s0, s1)
        and int(jax.device_get(serial.ptr)) == int(jax.device_get(coal.ptr))
        and int(jax.device_get(serial.size)) == int(jax.device_get(coal.size))
    )
    checksum = float(np.abs(s1).sum())
    print(f"PARITY {tag} {int(identical)} {checksum:.4f}", flush=True)


def run_background_sync_ship_parity(pid: int, tag: str) -> None:
    """Background lockstep sync_ship (docs/TRANSFER.md) vs the synchronous
    reference IN THE SAME CLUSTER: `serial` ships with blocking learner-
    thread collectives (the PR-1 path), `bg` issues beats on the transfer
    scheduler's lockstep lane (sync_ship_begin, counts snapshot at token
    time) and only waits tickets at the gate points. Storage/ptr/size
    must come out bit-identical, and the replicas must agree. Per-process
    program order keeps the collective schedule consistent: every serial
    collective completes before any bg beat is issued."""
    import numpy as np

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import DeviceReplay
    from distributed_ddpg_tpu.transfer import TransferScheduler

    obs_dim, act_dim = 5, 2
    config = DDPGConfig(
        actor_hidden=(16, 16), critic_hidden=(16, 16), batch_size=16, seed=0
    )
    learner = ShardedLearner(config, obs_dim, act_dim, action_scale=1.0,
                             chunk_size=2)
    serial = DeviceReplay(8192, obs_dim, act_dim, mesh=learner.mesh,
                          block_size=128, max_coalesce=4)
    sched = TransferScheduler().start()
    bg = DeviceReplay(8192, obs_dim, act_dim, mesh=learner.mesh,
                      block_size=128, max_coalesce=4,
                      scheduler=sched, background_sync=True)
    assert bg._bg_sync, "background beats must arm on a multi-process mesh"
    r = np.random.default_rng(70 + pid)
    rows = (0.1 * r.standard_normal((5 * 128 + 37, serial.width))).astype(
        np.float32
    )
    # Reference: synchronous beats, two waves + a force pad.
    serial.add_packed(rows[:300].copy())
    serial.sync_ship()
    serial.add_packed(rows[300:].copy())
    serial.sync_ship()
    serial.sync_ship(force=True)
    # Background: identical wave structure, beats issued WITHOUT waiting
    # (t1 resolves only after t2 was issued — genuinely overlapped), the
    # force beat routed synchronously through the same lane.
    bg.add_packed(rows[:300].copy())
    t1 = bg.sync_ship_begin()
    bg.add_packed(rows[300:].copy())
    t2 = bg.sync_ship_begin()
    moved1 = t1.result(timeout=240)
    moved2 = t2.result(timeout=240)
    moved3 = bg.sync_ship(force=True)
    assert moved1 + moved2 + moved3 == len(rows), (moved1, moved2, moved3)

    import jax

    s0 = np.asarray(jax.device_get(serial.storage))
    s1 = np.asarray(jax.device_get(bg.storage))
    identical = bool(
        np.array_equal(s0, s1)
        and int(jax.device_get(serial.ptr)) == int(jax.device_get(bg.ptr))
        and int(jax.device_get(serial.size)) == int(jax.device_get(bg.size))
    )
    snap = sched.snapshot()
    assert snap["transfer_lockstep_items"] == 3, snap
    sched.close()
    checksum = float(np.abs(s1).sum())
    print(f"PARITY {tag} {int(identical)} {checksum:.4f}", flush=True)


def run_replay_parity(pid: int, nprocs: int, tag: str) -> None:
    """Each process buffers DIFFERENT local rows (seeded by pid), then the
    lockstep sync_ship gathers them into the replicated storage. Asserts:
    size == sum of contributions, and the storage checksum equals the sum
    over ALL processes' rows (each process recomputes every process's rows
    from the seeds) — i.e. every row landed exactly once, identically on
    every replica. Then runs one fused-sampling learner chunk and prints
    its loss for cross-process comparison."""
    import numpy as np

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import DeviceReplay

    obs_dim, act_dim = 5, 2
    config = DDPGConfig(
        actor_hidden=(16, 16), critic_hidden=(16, 16), batch_size=16, seed=0
    )
    learner = ShardedLearner(config, obs_dim, act_dim, action_scale=1.0,
                             chunk_size=2)
    rep = DeviceReplay(4096, obs_dim, act_dim, mesh=learner.mesh,
                       block_size=256)

    def rows_for(p: int) -> "np.ndarray":
        r = np.random.default_rng(100 + p)
        # Keep values in a sane range so the sampled learner chunk is finite.
        return (0.1 * r.standard_normal((300, rep.width))).astype(np.float32)

    rep.add_packed(rows_for(pid))
    assert len(rep) == 0, "multi-host add_packed must only buffer"
    moved = rep.sync_ship()          # min(300, 300) // 256 -> 1 block each
    assert moved == 256, moved
    moved2 = rep.sync_ship(force=True)   # remainders, padded
    assert moved2 == 44, moved2

    import jax

    size = len(rep)
    assert size == nprocs * 2 * 256, size  # 2 global blocks of nprocs*256
    storage = np.asarray(jax.device_get(rep.storage))[:size]
    got = float(np.abs(storage).sum())
    # Expected: every process's 300 real rows once, plus the force-padded
    # repetition of each remainder (tile(44 rows) -> 256 = 5x44 full + 36).
    expected = 0.0
    for p in range(nprocs):
        rows = rows_for(p)
        expected += float(np.abs(rows[:256]).sum())
        rem = rows[256:]
        reps = -(-256 // len(rem))
        expected += float(np.abs(np.tile(rem, (reps, 1))[:256]).sum())
    assert abs(got - expected) < 1e-2, (got, expected)

    out = learner.run_sample_chunk(rep)
    loss = float(jax.device_get(out.metrics["critic_loss"]))
    print(f"PARITY {tag} {loss:.8f} {got:.4f}", flush=True)


def run_train_parity(tag: str) -> None:
    """The full train_jax driver — actor pool, lockstep device-replay
    ingest, globally-budgeted loop — across the process boundary."""
    import tempfile

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.train import train_jax

    config = DDPGConfig(
        backend="jax_tpu",
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        batch_size=16,
        num_actors=1,
        total_env_steps=2500,   # GLOBAL budget (summed over processes)
        replay_min_size=128,
        replay_capacity=8192,
        eval_every=0,
        eval_episodes=1,
        log_path=tempfile.mktemp(suffix=".jsonl"),
        # Watchdog under LOCKSTEP collectives: a healthy 2-process run must
        # not false-fire (beats advance through the collective waits); a
        # genuinely wedged peer stalls both processes and both exit 70.
        watchdog_s=120.0,
    )
    out = train_jax(config)
    print(
        f"PARITY {tag} {out['learner_steps']} {out['param_checksum']:.6f}",
        flush=True,
    )


def run_parity_chunk(ShardedLearner, DDPGConfig, np, tag: str) -> None:
    """Deterministic 2-step chunk at batch 16 over however many devices are
    visible; prints 'PARITY <tag> <critic_loss> <param_checksum>'."""
    config = DDPGConfig(
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        batch_size=16,
        seed=0,
    )
    learner = ShardedLearner(config, 5, 2, action_scale=1.0, chunk_size=2)
    rng = np.random.default_rng(0)
    k, b = 2, config.batch_size
    chunk = {
        "obs": rng.standard_normal((k, b, 5)).astype(np.float32),
        "action": rng.uniform(-1, 1, (k, b, 2)).astype(np.float32),
        "reward": rng.standard_normal((k, b)).astype(np.float32),
        "discount": np.full((k, b), 0.99, np.float32),
        "next_obs": rng.standard_normal((k, b, 5)).astype(np.float32),
        "weight": np.ones((k, b), np.float32),
    }
    out = learner.run_chunk(chunk)
    import jax

    loss = float(jax.device_get(out.metrics["critic_loss"]))
    leaves = jax.tree.leaves(jax.device_get(learner.state.actor_params))
    checksum = float(sum(np.abs(leaf).sum() for leaf in leaves))
    print(f"PARITY {tag} {loss:.8f} {checksum:.6f}", flush=True)


if __name__ == "__main__":
    main()
