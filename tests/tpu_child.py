"""Child process for tests/test_tpu.py: runs one native-TPU test case and
prints a JSON result line.

The main test suite pins every test to the virtual CPU platform
(conftest.py), which is exactly how the round-2 megakernel Mosaic bug
escaped: the pallas kernel had only ever compiled in interpret mode
(VERDICT.md round-2 Missing #5). This child runs OUTSIDE that pin — it
lets the platform resolve to the attached accelerator (axon/TPU) — so the
tpu-marked tests exercise real Mosaic compilation, real h2d, and the real
device replay path. Cases:

  probe         -> {"is_tpu": bool, "platform": ..., "device_kind": ...}
  fused_parity  -> native megakernel vs XLA scan path on one chunk
  sample_chunk  -> DeviceReplay ingest + ShardedLearner.run_sample_chunk
                   (the production zero-h2d path), fused kernel active
"""

import json
import os
import sys

# Run as a script: sys.path[0] is tests/, so put the repo root (the package
# parent) ahead of it.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _probe() -> dict:
    import jax

    from distributed_ddpg_tpu.ops.fused_chunk import runs_native

    dev = jax.devices()[0]
    return {
        "is_tpu": runs_native(),
        "platform": dev.platform,
        "device_kind": dev.device_kind,
    }


OBS, ACT, B, K = 17, 6, 64, 8


def _packed(rng, k):
    from distributed_ddpg_tpu.types import pack_batch_np

    return pack_batch_np(
        {
            "obs": rng.standard_normal((k, B, OBS)).astype(np.float32),
            "action": rng.uniform(-1, 1, (k, B, ACT)).astype(np.float32),
            "reward": rng.standard_normal((k, B)).astype(np.float32),
            "discount": np.full((k, B), 0.99, np.float32),
            "next_obs": rng.standard_normal((k, B, OBS)).astype(np.float32),
            "weight": np.ones((k, B), np.float32),
        }
    )


def _fused_parity() -> dict:
    """Natively-compiled megakernel vs the XLA scan path on one chunk — the
    SAME parity body the interpret-mode oracle runs (fused_parity_util),
    at fp-noise tolerances: two different on-TPU programs accumulate in
    different orders."""
    from fused_parity_util import assert_fused_matches_scan

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.ops import fused_chunk

    assert fused_chunk.runs_native(), "fused_parity needs a native TPU backend"
    cfg = DDPGConfig(
        actor_hidden=(256, 256), critic_hidden=(256, 256), batch_size=B, seed=3
    )
    metrics = assert_fused_matches_scan(
        cfg, OBS, ACT, K, 1.0, 0.0,
        interpret=None,  # None = native on TPU (make_fused_chunk_fn default)
        rtol=2e-2, atol=1e-2,
    )
    return {"ok": True, "critic_loss": float(metrics["critic_loss"])}


def _sample_chunk() -> dict:
    """Real h2d ingest into DeviceReplay + the production run_sample_chunk
    dispatch with the megakernel active (fused_chunk defaults to 'auto' and
    must activate on real TPU)."""
    import jax

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.ops import fused_chunk
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.parallel.mesh import make_mesh
    from distributed_ddpg_tpu.replay.device import DeviceReplay

    # Unlike the parity cases this one would run happily on CPU (fused
    # 'auto' just falls back to scan) — so a silent CPU fallback would
    # print ok:true and retire the runbook stage without ever touching
    # the chip. Assert native like every other case.
    assert fused_chunk.runs_native(), "sample_chunk needs a native TPU backend"
    cfg = DDPGConfig(
        actor_hidden=(256, 256), critic_hidden=(256, 256), batch_size=B
    )
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mesh=mesh, chunk_size=K)
    rep = DeviceReplay(4096, OBS, ACT, mesh=mesh, block_size=1024)
    rng = np.random.default_rng(0)
    rows = _packed(rng, 64).reshape(-1, rep.width)  # 64*B = 4096 rows
    rep.add_packed(rows)
    assert len(rep) == 4096
    out = lrn.run_sample_chunk(rep)
    loss = float(out.metrics["critic_loss"])
    assert np.isfinite(loss)
    assert int(jax.device_get(lrn.state.step)) == K
    out2 = lrn.run_sample_chunk(rep)
    assert np.isfinite(float(out2.metrics["critic_loss"]))
    # ingest_* observability fields ride the native capture (ROADMAP open
    # item: CPU scaling sweeps carried them, TPU captures dropped them) —
    # the REAL h2d ship cost is exactly the number the CPU sweeps can't
    # measure. The snapshot must describe the 4 real 1024-row ships above.
    ingest = rep.ingest_snapshot()
    assert ingest["ingest_ship_calls"] >= 1, ingest
    return {
        "ok": True,
        "fused_chunk_active": lrn.fused_chunk_active,
        "fused_chunk_error": lrn.fused_chunk_error,
        "critic_loss": loss,
        **ingest,
    }


def _fused_parity_c51() -> dict:
    """Native Mosaic compile + parity for the D4PG (C51) kernel branch —
    the in-kernel categorical projection and closed-form cotangents."""
    from fused_parity_util import assert_fused_matches_scan

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.ops import fused_chunk

    assert fused_chunk.runs_native(), "needs a native TPU backend"
    cfg = DDPGConfig(
        actor_hidden=(256, 256), critic_hidden=(256, 256), batch_size=B,
        distributional=True, num_atoms=51, v_min=-150.0, v_max=150.0, seed=3,
    )
    metrics = assert_fused_matches_scan(
        cfg, OBS, ACT, K, 1.0, 0.0,
        interpret=None, rtol=2e-2, atol=1e-2,
    )
    return {"ok": True, "critic_loss": float(metrics["critic_loss"])}


def _fused_parity_bf16() -> dict:
    """Native bf16 megakernel (MXU-rate dots, f32 accumulate) vs the bf16
    scan path — bf16-rounding tolerances."""
    from fused_parity_util import assert_fused_matches_scan

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.ops import fused_chunk

    assert fused_chunk.runs_native(), "needs a native TPU backend"
    cfg = DDPGConfig(
        actor_hidden=(256, 256), critic_hidden=(256, 256), batch_size=B,
        compute_dtype="bfloat16", seed=3,
    )
    metrics = assert_fused_matches_scan(
        cfg, OBS, ACT, K, 1.0, 0.0,
        interpret=None, rtol=5e-2, atol=2e-2,
    )
    return {"ok": True, "critic_loss": float(metrics["critic_loss"])}


def _fused_parity_td3() -> dict:
    """Native Mosaic compile + parity for the TD3 kernel branch — twin
    member groups, streamed smoothing noise, pl.when-delayed updates."""
    from fused_parity_util import assert_fused_matches_scan

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.ops import fused_chunk

    assert fused_chunk.runs_native(), "needs a native TPU backend"
    cfg = DDPGConfig(
        actor_hidden=(256, 256), critic_hidden=(256, 256), batch_size=B,
        twin_critic=True, policy_delay=2, target_noise=0.2, seed=3,
    )
    metrics = assert_fused_matches_scan(
        cfg, OBS, ACT, K, 1.0, 0.0,
        interpret=None, rtol=2e-2, atol=1e-2,
    )
    return {"ok": True, "critic_loss": float(metrics["critic_loss"])}


def _fused_parity_sac() -> dict:
    """Native Mosaic compile + parity for the SAC kernel branch — the
    Gaussian-head lane split/concat, streamed sampling normals, squash
    log-prob backward, and the temperature's scalar Adam on (1,1) refs."""
    from fused_parity_util import assert_fused_matches_scan

    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.ops import fused_chunk

    assert fused_chunk.runs_native(), "needs a native TPU backend"
    cfg = DDPGConfig(
        actor_hidden=(256, 256), critic_hidden=(256, 256), batch_size=B,
        sac=True, seed=3,
    )
    metrics = assert_fused_matches_scan(
        cfg, OBS, ACT, K, 1.0, 0.0,
        interpret=None, rtol=2e-2, atol=1e-2,
    )
    return {"ok": True, "critic_loss": float(metrics["critic_loss"])}


CASES = {
    "probe": _probe,
    "fused_parity": _fused_parity,
    "fused_parity_c51": _fused_parity_c51,
    "fused_parity_bf16": _fused_parity_bf16,
    "fused_parity_td3": _fused_parity_td3,
    "fused_parity_sac": _fused_parity_sac,
    "sample_chunk": _sample_chunk,
}


if __name__ == "__main__":
    print(json.dumps(CASES[sys.argv[1]]()), flush=True)
