"""Pod-resilience layer tests (parallel/multihost.py pod machinery;
docs/RESILIENCE.md pod rows).

Three tiers:
  - unit: the collective-deadline wrapper (hung fake collective raises
    PodPeerLost AT the deadline; the single-process path short-circuits
    with zero overhead), the resume-step election rule, pod fault-spec
    parsing, PodStats fields, checkpoint.valid_steps, and the transfer
    scheduler's lockstep-lane deadline (an in-flight lockstep ticket
    FAILS, never hangs).
  - 2-process gloo (tier-1): a scripted peer HANG (pod:1:hang@3) makes
    both processes exit EXIT_POD_DEGRADED within the deadline — the fast
    end-to-end proof of the deadline wiring.
  - 3-process gloo chaos (slow): kill one process mid-run; both survivors
    exit 76 with manifest-valid emergency checkpoints, and a subsequent
    3-process relaunch elects ONE common resume step on every process
    (asserted via pod_resume_step_elected in each child's JSONL).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from distributed_ddpg_tpu import checkpoint as ckpt_lib
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.faults import FaultPlan
from distributed_ddpg_tpu.metrics import PodStats
from distributed_ddpg_tpu.parallel import multihost
from distributed_ddpg_tpu.parallel.multihost import PodPeerLost

CHILD = Path(__file__).parent / "multihost_child.py"
REPO = str(CHILD.parent.parent)


# --------------------------------------------------------------------------
# deadline wrapper units
# --------------------------------------------------------------------------


def test_deadline_unconfigured_short_circuits_on_caller_thread():
    """Single-process contract: with no deadline configured the wrapper
    must be a DIRECT call — same thread, no helper machinery, zero
    overhead (the production default for every non-pod run)."""
    seen = []
    before = threading.active_count()
    out = multihost.call_with_deadline(
        lambda: seen.append(threading.get_ident()) or 41 + 1
    )
    assert out == 42
    assert seen == [threading.get_ident()]
    assert threading.active_count() == before


def test_hung_fake_collective_raises_pod_peer_lost_at_deadline():
    stats = PodStats()
    multihost.configure_pod(0.3, stats=stats)
    try:
        t0 = time.monotonic()
        with pytest.raises(PodPeerLost) as err:
            multihost.call_with_deadline(
                lambda: time.sleep(10), label="fake_allgather"
            )
        elapsed = time.monotonic() - t0
        # Fired at the deadline, not after the hang resolved.
        assert 0.25 <= elapsed < 5.0, elapsed
        assert err.value.reason == "timeout"
        assert "fake_allgather" in str(err.value)
        assert stats.peer_lost == 1
    finally:
        multihost.configure_pod(0.0)


def test_deadline_explicit_timeout_overrides_default():
    # Explicit 0 disables even with a configured default.
    multihost.configure_pod(0.1)
    try:
        assert (
            multihost.call_with_deadline(lambda: "ok", timeout_s=0) == "ok"
        )
        with pytest.raises(PodPeerLost):
            multihost.call_with_deadline(
                lambda: time.sleep(5), timeout_s=0.2
            )
    finally:
        multihost.configure_pod(0.0)


def test_deadline_propagates_fn_exception():
    multihost.configure_pod(5.0)
    try:
        with pytest.raises(ZeroDivisionError):
            multihost.call_with_deadline(lambda: 1 / 0)
    finally:
        multihost.configure_pod(0.0)


def test_deadline_records_near_miss_and_slack():
    stats = PodStats()
    multihost.configure_pod(0.2, stats=stats)
    try:
        multihost.call_with_deadline(lambda: time.sleep(0.18))  # > 80%
        multihost.call_with_deadline(lambda: None)  # plenty of slack
        snap = stats.snapshot()
        assert snap["pod_collective_near_misses"] == 1
        assert snap["pod_collective_slack_p95_ms"] > 0
        assert snap["pod_peer_lost"] == 0
    finally:
        multihost.configure_pod(0.0)


def test_grant_extends_deadline_window():
    multihost.configure_pod(0.2)
    try:
        multihost.grant(5.0)
        # Slower than the base deadline, inside the granted window: ok.
        assert multihost.call_with_deadline(
            lambda: time.sleep(0.4) or "late-but-fine"
        ) == "late-but-fine"
    finally:
        multihost.configure_pod(0.0)


def test_parse_peer_from_transport_errors():
    assert multihost._parse_peer("coordination service: task 2 failed") == 2
    assert multihost._parse_peer("Peer rank 1 closed connection") == 1
    assert multihost._parse_peer("connection reset") is None


# --------------------------------------------------------------------------
# resume-step election rule
# --------------------------------------------------------------------------


def test_common_step_elects_greatest_common():
    gathered = [
        [100, 200, 300, -1],
        [200, 300, 400, -1],
        [0, 200, 300, -1],
    ]
    assert multihost._common_step(gathered) == 300


def test_common_step_no_overlap_is_minus_one():
    assert multihost._common_step([[100, -1], [200, -1]]) == -1
    # A process with NO checkpoints forces a fresh (but agreed) start.
    assert multihost._common_step([[100, 200], [-1, -1]]) == -1


def test_common_step_single_process():
    assert multihost._common_step([[7, 9, -1]]) == 9


# --------------------------------------------------------------------------
# pod fault specs (faults.py)
# --------------------------------------------------------------------------


def test_pod_fault_specs_parse_and_scope_to_process():
    plan = FaultPlan.parse("pod:1:kill@6;pod:0:hang@2~60", seed=0)
    assert bool(plan.pod_site(0)) and bool(plan.pod_site(1))
    assert not plan.pod_site(2)
    kinds = {s.kind for s in plan.specs}
    assert kinds == {"kill", "hang"}
    # Pod hang without an explicit duration defaults LONG: it must
    # outlast the collective deadline, not a few-second site timeout.
    hang = [s for s in FaultPlan.parse("pod:0:hang@1").specs][0]
    assert hang.duration_s >= 600


def test_pod_fault_spec_validation():
    with pytest.raises(ValueError):
        FaultPlan.parse("pod:x:kill@5")  # non-integer process id
    with pytest.raises(ValueError):
        FaultPlan.parse("worker:1:kill@5")  # kill is pod-only
    with pytest.raises(ValueError):
        FaultPlan.parse("pod:0:ioerror@5")  # not a pod kind
    # Config-level validation accepts the pod grammar.
    cfg = DDPGConfig(faults="pod:1:kill@40")
    assert cfg.fault_plan().pod_site(1)


def test_pod_fault_hang_sleeps_at_beat_ordinal():
    plan = FaultPlan.parse("pod:0:hang@2~0.2", seed=0)
    site = plan.pod_site(0)
    t0 = time.monotonic()
    site.tick()  # beat 1: nothing
    assert time.monotonic() - t0 < 0.1
    site.tick()  # beat 2: sleeps the scripted duration
    assert time.monotonic() - t0 >= 0.2
    assert site.fired == ["pod:0:hang@2"]


# --------------------------------------------------------------------------
# PodStats + config knobs
# --------------------------------------------------------------------------


def test_pod_stats_snapshot_fields():
    s = PodStats()
    s.record_peer_lost()
    s.record_abort()
    s.record_resume_elected(120)
    s.note_beat()
    snap = s.snapshot()
    assert snap["pod_peer_lost"] == 1
    assert snap["pod_aborts"] == 1
    assert snap["pod_resume_step_elected"] == 120
    assert snap["pod_beats"] == 1
    assert "pod_collective_near_misses" in snap
    assert "pod_collective_slack_p95_ms" in snap


def test_config_validates_pod_knobs():
    with pytest.raises(ValueError):
        DDPGConfig(pod_collective_timeout_s=-1.0)
    with pytest.raises(ValueError):
        DDPGConfig(pod_startup_grace_s=-1.0)
    assert DDPGConfig(pod_collective_timeout_s=0.0)  # 0 = off is legal


# --------------------------------------------------------------------------
# checkpoint.valid_steps (the election's input)
# --------------------------------------------------------------------------


def _fake_checkpoint(directory: str, step: int, payload: bytes) -> None:
    root = os.path.join(directory, f"step_{step}")
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, "data.bin"), "wb") as f:
        f.write(payload)
    ckpt_lib._write_manifest(directory, step)


def test_valid_steps_excludes_corrupt_and_orders(tmp_path):
    d = str(tmp_path)
    _fake_checkpoint(d, 10, b"aaaa")
    _fake_checkpoint(d, 30, b"bbbb")
    _fake_checkpoint(d, 20, b"cccc")
    assert ckpt_lib.valid_steps(d) == [10, 20, 30]
    # Corrupt one after its manifest was written: it must drop out.
    with open(os.path.join(d, "step_20", "data.bin"), "wb") as f:
        f.write(b"XXXXXXXX")
    assert ckpt_lib.valid_steps(d) == [10, 30]
    assert ckpt_lib.valid_steps(d, limit=1) == [30]
    assert ckpt_lib.valid_steps(str(tmp_path / "missing")) == []
    assert ckpt_lib.valid_steps("") == []


# --------------------------------------------------------------------------
# transfer scheduler: lockstep-lane deadline
# --------------------------------------------------------------------------


def test_lockstep_ticket_fails_at_deadline_not_hangs():
    """An in-flight lockstep beat whose collective hangs must FAIL its
    ticket with PodPeerLost at the lane deadline — the waiter (train's
    wait_beat / run_ordered) gets a typed error, never an eternal block —
    and the scheduler thread survives to serve later work."""
    from distributed_ddpg_tpu.transfer import TransferScheduler

    s = TransferScheduler(lockstep_timeout_s=0.3).start()
    try:
        t0 = time.monotonic()
        ticket = s.submit("lockstep", lambda: time.sleep(10), label="beat_1")
        with pytest.raises(PodPeerLost):
            ticket.result(timeout=10)
        assert time.monotonic() - t0 < 5.0
        assert s.alive
        # The lane keeps serving after the failed beat.
        assert s.submit("lockstep", lambda: "ok").result(timeout=5) == "ok"
        # Non-lockstep classes are never deadline-wrapped.
        assert s.submit(
            "ingest", lambda: time.sleep(0.5) or 7
        ).result(timeout=5) == 7
    finally:
        s.close()


def test_lockstep_zero_timeout_pays_no_wrapper():
    from distributed_ddpg_tpu.transfer import TransferScheduler

    s = TransferScheduler().start()  # default: no deadline
    try:
        assert s.submit(
            "lockstep", lambda: time.sleep(0.2) or "slow-ok"
        ).result(timeout=5) == "slow-ok"
    finally:
        s.close()


def test_queued_lockstep_tickets_fail_on_abort():
    """close() (the coordinated-abort drain path train.py takes on peer
    loss) fails QUEUED lockstep beats before the join — a stale beat must
    never fire a collective against a degraded pod."""
    from distributed_ddpg_tpu.transfer import TransferError, TransferScheduler

    s = TransferScheduler().start()
    gate = threading.Event()
    s.submit("lockstep", lambda: gate.wait(10))
    queued = s.submit("lockstep", lambda: "stale beat")
    s.close(timeout=0.2)
    gate.set()
    with pytest.raises(TransferError):
        queued.result(timeout=5)


# --------------------------------------------------------------------------
# tools.runs pod digest
# --------------------------------------------------------------------------


def test_tools_runs_renders_pod_digest(tmp_path):
    from distributed_ddpg_tpu.tools.runs import render_summary, summarize_run

    rec = {
        "kind": "train", "step": 100,
        "pod_peer_lost": 1, "pod_aborts": 1,
        "pod_resume_step_elected": 96, "pod_beats": 12,
        "pod_collective_near_misses": 2,
        "pod_collective_slack_p95_ms": 500.0,
    }
    path = tmp_path / "pod.jsonl"
    path.write_text(
        json.dumps(rec) + "\n"
        + json.dumps({**rec, "kind": "final", "step": 200}) + "\n"
    )
    digest = summarize_run(str(path))
    assert digest["pod"]["pod_resume_step_elected"]["last"] == 96
    assert digest["pod"]["pod_peer_lost"]["last"] == 1
    text = render_summary(digest)
    assert "pod resilience" in text and "pod_collective_slack_p95_ms" in text
    # No elastic events -> no elastic verdict line.
    assert "elastic:" not in text
    # Elastic events render the adoption/shrink/grow verdict with the
    # typed degraded state (docs/RESILIENCE.md shrink/grow machine).
    elastic = tmp_path / "elastic.jsonl"
    elastic.write_text(json.dumps({
        **rec, "kind": "final", "step": 200,
        "pod_slices_adopted": 1, "pod_slice_adopted_step": 96,
        "pod_shrinks": 1, "pod_grows": 0, "pod_state_degraded": 1,
    }) + "\n")
    etext = render_summary(summarize_run(str(elastic)))
    assert "elastic: 1 slice adoption(s) (step 96)" in etext, etext
    assert "1 shrink(s)" in etext and "0 grow(s)" in etext, etext
    assert "DEGRADED" in etext, etext
    # Single-process logs carry no pod_* keys: no pod section.
    clean = tmp_path / "clean.jsonl"
    clean.write_text(json.dumps({"kind": "train", "step": 1}) + "\n")
    assert not summarize_run(str(clean))["pod"]
    assert "pod resilience" not in render_summary(summarize_run(str(clean)))


# --------------------------------------------------------------------------
# gloo integration: real multi-process pods
# --------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_pod(nprocs: int, env: dict, timeout: int):
    """Launch an N-process podtrain cluster; returns the per-process
    (returncode, stdout) list. Any process that outlives the slowest
    clean exit by the timeout is SIGKILLed (a scripted hang can leave a
    child sleeping — the contract under test is about the others)."""
    port = _free_port()
    child_env = {
        **os.environ,
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # The pod deadline must WIN the race against the JAX runtime's
        # own heartbeat killer (LOG(FATAL), no emergency checkpoint) —
        # parallel/multihost.initialize stretches the runtime tolerance.
        "POD_RUNTIME_HEARTBEAT_TIMEOUT_S": "300",
        **env,
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(CHILD), str(pid), str(nprocs), str(port),
             "podtrain"],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
            env=child_env,
        )
        for pid in range(nprocs)
    ]
    results = []
    deadline = time.monotonic() + timeout
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(5.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        results.append((p.returncode, out))
    return results


def _infra_flake(results) -> bool:
    """True when a pod launch died of the KNOWN multiprocess-CPU gloo
    stream race (concurrently-executing collective computations sharing
    TCP pairs — pre-existing, noted in docs/RESILIENCE.md), not of the
    pod contract under test. The race manifests as a C++ abort (SIGABRT):
    either the raw gloo preamble-mismatch terminate, an XlaRuntimeError
    whose buffer carries 'Gloo all-reduce failed', or — on the peer that
    merely witnessed the first abort — the coordination-service LOG(FATAL).
    No contract under test ever exits via SIGABRT (expected outcomes are
    the injected SIGKILL, the 76/78 clean aborts, or 0; a Python bug exits
    1), so any -6 in the set marks the launch infra-torn. A HEALTHY pod
    abort wraps its transport error in 'pod peer lost'."""
    return any(
        rc == -signal.SIGABRT
        or "gloo::EnforceNotMet" in out
        or "Gloo all-reduce failed" in out
        for rc, out in results
    )


def _launch_pod_retrying(nprocs: int, env: dict, timeout: int, attempts: int = 3):
    last = None
    for _ in range(attempts):
        last = _launch_pod(nprocs, env, timeout)
        if not _infra_flake(last):
            return last
    return last


# Re-tiered to slow (ISSUE 15 tier-1 budget): 66s gloo 2-process spawn+compile; all multi-process pod smokes now
# ride the slow tier — the single-process deadline units stay tier-1
@pytest.mark.slow
def test_two_process_peer_hang_exits_pod_degraded(tmp_path):
    """Fast 2-process deadline test (tier-1): process 1 freezes inside
    its first steady-state lockstep beat (pod:1:hang@1); BOTH processes must exit
    EXIT_POD_DEGRADED — the healthy peer because its beat-3 collective
    misses the deadline, the hung one because its own lane deadline
    bounds the frozen beat. Nobody blocks forever."""
    from distributed_ddpg_tpu.train import EXIT_POD_DEGRADED

    results = _launch_pod_retrying(
        2,
        {
            "POD_FAULTS": "pod:1:hang@1~600",
            "POD_TIMEOUT_S": "6",
            # Also the first-dispatch compile grant: the hang fires at the
            # first post-compile beat, so detection lands within
            # ~grace + timeout of the freeze — keep the bound test-sized.
            "POD_STARTUP_GRACE_S": "30",
            "POD_CKPT_DIR": "",
            "POD_LOG_DIR": str(tmp_path),
            "POD_TOTAL_STEPS": "200000",
            # Background beats: the hang fires inside warmup (no chunk in
            # flight), and only the lockstep-lane wrap can bound the HUNG
            # process's own frozen beat — that's the path under test.
            "POD_BG_SYNC": "1",
        },
        timeout=240,
    )
    for rc, out in results:
        assert rc == EXIT_POD_DEGRADED, f"rc={rc}\n{out}"
        assert "pod peer lost" in out, out
        assert "degraded=1" in out, out


@pytest.mark.slow
def test_three_process_kill_one_chaos_then_common_resume(tmp_path):
    """The pod chaos acceptance test (ISSUE 6): a 3-process gloo pod,
    process 1 SIGKILLs itself at its 12th steady-state lockstep beat
    (mid-run: 11 learner chunks past warmup, with at least one cadence
    checkpoint retained). Both survivors must exit EXIT_POD_DEGRADED within
    pod_collective_timeout_s + the compile grace, each leaving a
    manifest-valid emergency checkpoint at the SAME learner step
    (process 0 in the shared dir, process 2 in its proc2/ subdir). A
    subsequent 3-process relaunch must elect that step on EVERY process
    (pod_resume_step_elected in each child's JSONL) and complete
    cleanly."""
    from distributed_ddpg_tpu.train import EXIT_POD_DEGRADED

    # --- phase 1: kill process 1 mid-run ---
    # Retried with FRESH dirs when the known gloo infra race (not the
    # contract under test) aborts the cluster — see _infra_flake.
    for attempt in range(3):
        ckpt_dir = str(tmp_path / f"ckpt{attempt}")
        log_dir = str(tmp_path / f"logs{attempt}")
        os.makedirs(log_dir, exist_ok=True)
        base_env = {
            "POD_CKPT_DIR": ckpt_dir,
            "POD_LOG_DIR": log_dir,
            "POD_TIMEOUT_S": "20",
            "POD_STARTUP_GRACE_S": "120",
            "POD_CKPT_EVERY": "64",
        }
        results = _launch_pod(
            3,
            {**base_env,
             "POD_FAULTS": "pod:1:kill@12",
             "POD_TOTAL_STEPS": "500000"},
            timeout=420,
        )
        if not _infra_flake(results):
            break
    (rc0, out0), (rc1, out1), (rc2, out2) = results
    assert rc1 == -signal.SIGKILL, f"proc1 should die by SIGKILL: {rc1}\n{out1}"
    for pid, (rc, out) in ((0, (rc0, out0)), (2, (rc2, out2))):
        assert rc == EXIT_POD_DEGRADED, f"proc{pid} rc={rc}\n{out}"
        assert "emergency checkpoint" in out, out
    # Both survivors aborted at the SAME lockstep point: the emergency
    # step in the shared dir (proc0) equals the only step in proc2's
    # per-process dir, and both are manifest-valid.
    main_steps = ckpt_lib.valid_steps(ckpt_dir)
    assert main_steps, "proc0 left no valid checkpoint"
    proc2_steps = ckpt_lib.valid_steps(os.path.join(ckpt_dir, "proc2"))
    assert proc2_steps, "proc2 left no valid emergency checkpoint"
    emergency = max(main_steps)
    assert emergency > 0, "abort happened before any learning"
    assert max(proc2_steps) == emergency, (main_steps, proc2_steps)
    ok, why = ckpt_lib.verify_checkpoint(ckpt_dir, emergency)
    assert ok, why
    ok, why = ckpt_lib.verify_checkpoint(
        os.path.join(ckpt_dir, "proc2"), max(proc2_steps)
    )
    assert ok, why

    # --- phase 2: relaunch the full pod; all 3 elect the common step ---
    resume_log_dir = str(tmp_path / "logs_resume")  # phase 1 logged -1s
    os.makedirs(resume_log_dir, exist_ok=True)
    results = _launch_pod_retrying(
        3,
        # Budget 1: already satisfied by the restored env-step offset, so
        # the resumed pod takes one lockstep chunk and exits cleanly —
        # the assertion is about the election, not more training.
        {**base_env, "POD_FAULTS": "", "POD_TOTAL_STEPS": "1",
         "POD_LOG_DIR": resume_log_dir},
        timeout=420,
    )
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"resume proc{pid} rc={rc}\n{out}"
        assert f"resume election: step {emergency}" in out, out
    elected = []
    for pid in range(3):
        with open(os.path.join(resume_log_dir, f"proc{pid}.jsonl")) as f:
            recs = [json.loads(line) for line in f if line.startswith("{")]
        vals = {
            r["pod_resume_step_elected"]
            for r in recs
            if "pod_resume_step_elected" in r
        }
        assert vals, f"proc{pid} logged no pod_resume_step_elected"
        elected.append(vals)
    assert all(v == {emergency} for v in elected), (emergency, elected)


@pytest.mark.slow
def test_two_process_kill_one_sharded_replay_exits_pod_degraded(tmp_path):
    """Sharded-replay chaos (ISSUE 10): the SAME pod kill contract over
    the shard_exchange beat lane. A 2-process gloo pod runs with
    replay_sharding='sharded' (storage partitioned over the 4-device
    mesh, sync_ship beats landing via the all-gather + owner-masked
    scatter); process 1 SIGKILLs itself at its 3rd steady-state beat. The
    survivor must exit EXIT_POD_DEGRADED within the deadline and leave a
    manifest-valid emergency checkpoint — written WITHOUT replay contents
    (no single-writer snapshot spans the shards), which must not break
    manifest validity or the exit contract."""
    from distributed_ddpg_tpu.train import EXIT_POD_DEGRADED

    for attempt in range(3):
        ckpt_dir = str(tmp_path / f"ckpt{attempt}")
        log_dir = str(tmp_path / f"logs{attempt}")
        os.makedirs(log_dir, exist_ok=True)
        results = _launch_pod(
            2,
            {
                "POD_FAULTS": "pod:1:kill@3",
                "POD_REPLAY_SHARDING": "sharded",
                "POD_TIMEOUT_S": "15",
                "POD_STARTUP_GRACE_S": "120",
                "POD_CKPT_DIR": ckpt_dir,
                "POD_LOG_DIR": log_dir,
                "POD_TOTAL_STEPS": "500000",
            },
            timeout=300,
        )
        if not _infra_flake(results):
            break
    (rc0, out0), (rc1, out1) = results
    assert rc1 == -signal.SIGKILL, f"proc1 should die by SIGKILL: {rc1}\n{out1}"
    assert rc0 == EXIT_POD_DEGRADED, f"proc0 rc={rc0}\n{out0}"
    assert "pod peer lost" in out0, out0
    assert "emergency checkpoint" in out0, out0
    # The sharded-mode writer omitted replay contents, loudly, and the
    # state-only emergency checkpoint still verifies manifest-valid.
    assert "omitted from checkpoints" in out0, out0
    steps = ckpt_lib.valid_steps(ckpt_dir)
    assert steps, "survivor left no manifest-valid emergency checkpoint"
    ok, why = ckpt_lib.verify_checkpoint(ckpt_dir, max(steps))
    assert ok, why
    # Beats rode the shard_exchange class (the survivor's JSONL carries
    # the accounting) — pinned so a refactor can't silently fold sharded
    # beats back into plain lockstep.
    with open(os.path.join(log_dir, "proc0.jsonl")) as f:
        recs = [json.loads(line) for line in f if line.startswith("{")]
    assert any(
        r.get("transfer_shard_exchange_items", 0) > 0
        or r.get("pod_beats", 0) > 0
        for r in recs
    ), "no beat accounting in survivor records"


@pytest.mark.slow
def test_two_process_elastic_shrink_then_grow(tmp_path):
    """Elastic-pod acceptance drill (docs/RESILIENCE.md shrink/grow state
    machine; docs/REPLAY_SHARDING.md all-writer slices).

    Phase 1 (N=2, sharded replay): process 1 SIGKILLs itself at its 12th
    steady-state beat — past at least one checkpoint cadence, so a
    complete, digest-verified 2-writer replay slice set is on disk. The
    survivor must exit EXIT_POD_SHRINK (78, shrink-ready), not plain 76.

    Phase 2 (M=1, in-process): a single-process relaunch on the same
    checkpoint_dir restores the elected step, adopts the 2-writer set —
    the dead peer's experience included — reshards it to one process,
    and reports the typed degraded state (pod_shrinks/pod_state_degraded
    surface even though the run is single-process). Its own cadence then
    writes a 1-writer set.

    Phase 3 (N=2 again): the grown pod adopts the 1-writer set, reshards
    back to two processes, reports grows=1 with a healthy state, and
    exits cleanly."""
    from distributed_ddpg_tpu.train import EXIT_POD_SHRINK, train_jax

    # --- phase 1: kill one of two writers past a checkpoint cadence ---
    # 5 attempts: the longer 12-beat run gives the known gloo startup
    # race (see _infra_flake) more surface than the 3-beat siblings.
    for attempt in range(5):
        ckpt_dir = str(tmp_path / f"ckpt{attempt}")
        log_dir = str(tmp_path / f"logs{attempt}")
        os.makedirs(log_dir, exist_ok=True)
        results = _launch_pod(
            2,
            {
                "POD_FAULTS": "pod:1:kill@12",
                "POD_REPLAY_SHARDING": "sharded",
                "POD_TIMEOUT_S": "20",
                "POD_STARTUP_GRACE_S": "120",
                "POD_CKPT_DIR": ckpt_dir,
                "POD_CKPT_EVERY": "16",
                "POD_LOG_DIR": log_dir,
                "POD_TOTAL_STEPS": "500000",
            },
            timeout=420,
        )
        if not _infra_flake(results):
            break
    (rc0, out0), (rc1, out1) = results
    assert rc1 == -signal.SIGKILL, f"proc1 should die by SIGKILL: {rc1}\n{out1}"
    assert rc0 == EXIT_POD_SHRINK, f"proc0 rc={rc0}\n{out0}"
    assert "shrinkready=1" in out0, out0
    assert "shrink-ready" in out0, out0
    adopt_step = ckpt_lib.latest_complete_slice_step(ckpt_dir)
    assert adopt_step is not None, "no complete slice set after phase 1"
    assert len(ckpt_lib.load_replay_slices(ckpt_dir, adopt_step)) == 2

    # --- phase 2: shrink to one process; adopt the dead peer's replay ---
    with open(os.path.join(log_dir, "proc0.jsonl")) as f:
        recs = [json.loads(line) for line in f if line.startswith("{")]
    max_env = max(int(r.get("step", 0)) for r in recs)
    cfg = DDPGConfig(
        backend="jax_tpu",
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        batch_size=16,
        num_actors=1,
        # A few hundred env steps past the restored offset: enough for
        # at least one learner step (and so one cadence), small enough
        # to keep the drill test-sized.
        total_env_steps=max_env + 400,
        replay_min_size=128,
        replay_capacity=8192,
        eval_every=0,
        eval_episodes=1,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=1,  # write the 1-writer slice set promptly
        replay_sharding="sharded",
        log_path=str(tmp_path / "shrunk.jsonl"),
        watchdog_s=0.0,
    )
    out = train_jax(cfg)
    assert out.get("pod_slices_adopted", 0) == 1, out
    assert out.get("pod_slice_adopted_step", -1) == adopt_step, out
    assert out.get("pod_shrinks", 0) == 1, out
    assert out.get("pod_state_degraded", 0) == 1, out
    assert not out.get("pod_degraded"), out
    one_writer = ckpt_lib.latest_complete_slice_step(ckpt_dir)
    assert one_writer is not None and one_writer > adopt_step, (
        one_writer, adopt_step,
    )
    assert len(ckpt_lib.load_replay_slices(ckpt_dir, one_writer)) == 1

    # --- phase 3: grow back to two processes ---
    grow_logs = str(tmp_path / "logs_grow")
    os.makedirs(grow_logs, exist_ok=True)
    results = _launch_pod_retrying(
        2,
        {
            "POD_FAULTS": "",
            "POD_REPLAY_SHARDING": "sharded",
            "POD_TIMEOUT_S": "20",
            "POD_STARTUP_GRACE_S": "120",
            "POD_CKPT_DIR": ckpt_dir,
            "POD_CKPT_EVERY": "16",
            "POD_LOG_DIR": grow_logs,
            # Budget already satisfied by the restored offset: the grown
            # pod adopts, takes one lockstep chunk, and exits cleanly.
            "POD_TOTAL_STEPS": "1",
        },
        timeout=420,
        attempts=5,
    )
    for pid, (rc, out_g) in enumerate(results):
        assert rc == 0, f"grow proc{pid} rc={rc}\n{out_g}"
        assert " adopted=1 " in out_g, out_g
        assert " grows=1 " in out_g, out_g
        assert "degraded=0" in out_g, out_g
