"""Replay tests (SURVEY.md §4): ring wraparound, sampling distribution,
sum-tree invariants, PER weights, n-step return math, checkpoint round-trip."""

import numpy as np
import pytest

from distributed_ddpg_tpu.replay.nstep import NStepAccumulator
from distributed_ddpg_tpu.replay.prioritized import PrioritizedReplay
from distributed_ddpg_tpu.replay.sum_tree import SumTree
from distributed_ddpg_tpu.replay.uniform import UniformReplay


def _fill(buf, n, obs_dim=3, act_dim=2, start=0):
    for i in range(start, start + n):
        buf.add(
            np.full(obs_dim, i, np.float32),
            np.full(act_dim, i, np.float32),
            float(i),
            0.99,
            np.full(obs_dim, i + 1, np.float32),
        )


def test_ring_wraparound():
    buf = UniformReplay(capacity=8, obs_dim=3, act_dim=2)
    _fill(buf, 11)
    assert len(buf) == 8
    # Slots 0..2 were overwritten by items 8,9,10.
    assert buf.reward[0] == 8.0 and buf.reward[2] == 10.0 and buf.reward[3] == 3.0


def test_uniform_sampling_distribution():
    buf = UniformReplay(capacity=64, obs_dim=1, act_dim=1, seed=0)
    _fill(buf, 64, obs_dim=1, act_dim=1)
    counts = np.zeros(64)
    for _ in range(200):
        s = buf.sample(64)
        np.testing.assert_array_equal(s["obs"][:, 0], s["reward"])  # SoA alignment
        counts[s["indices"]] += 1
    # Each slot expected 200 hits; loose 5-sigma band.
    assert counts.min() > 100 and counts.max() < 320


def test_sum_tree_invariants():
    t = SumTree(capacity=10)  # rounds to 16
    rng = np.random.default_rng(0)
    prios = rng.uniform(0.1, 2.0, size=10)
    t.set(np.arange(10), prios)
    np.testing.assert_allclose(t.total, prios.sum(), rtol=1e-12)
    # Every internal node equals the sum of its children.
    tree = t.tree
    for node in range(1, t.capacity):
        np.testing.assert_allclose(tree[node], tree[2 * node] + tree[2 * node + 1])
    # Descent hits the right leaf for exact prefix sums.
    cum = np.cumsum(prios)
    idx = t.sample(cum - 1e-9)
    np.testing.assert_array_equal(idx, np.arange(10))


def test_sum_tree_sampling_proportional():
    t = SumTree(capacity=4)
    t.set(np.arange(4), np.array([1.0, 0.0, 3.0, 0.0]))
    rng = np.random.default_rng(1)
    idx = t.stratified_sample(4000, rng)
    counts = np.bincount(idx, minlength=4)
    assert counts[1] == 0 and counts[3] == 0
    np.testing.assert_allclose(counts[2] / counts[0], 3.0, rtol=0.15)


def test_per_weights_and_priority_update():
    buf = PrioritizedReplay(capacity=32, obs_dim=1, act_dim=1, alpha=1.0, beta=1.0, seed=0)
    _fill(buf, 32, obs_dim=1, act_dim=1)
    s = buf.sample(16)
    # Fresh buffer: all priorities equal → all IS weights 1.
    np.testing.assert_allclose(s["weight"], 1.0)
    # Give slot 5 a huge TD error; it should dominate sampling.
    buf.update_priorities(np.array([5]), np.array([100.0]))
    hits = sum((buf.sample(32)["indices"] == 5).sum() for _ in range(50))
    assert hits > 1000  # ~76% of 1600 draws expected
    # And its IS weight must be the minimum (most down-weighted).
    s = buf.sample(256)
    w_of_5 = s["weight"][s["indices"] == 5]
    assert len(w_of_5) and np.all(w_of_5 <= s["weight"].max())
    assert np.all(s["weight"] <= 1.0 + 1e-9)


def test_nstep_returns():
    acc = NStepAccumulator(n=3, gamma=0.5, num_envs=1)
    out = []
    rewards = [1.0, 2.0, 3.0, 4.0]
    for t, r in enumerate(rewards):
        obs = np.array([[float(t)]])
        nxt = np.array([[float(t + 1)]])
        done = [t == 3]
        out.extend(acc.push(obs, obs, [r], done, nxt))
    # Window [0,1,2]: R = 1 + .5*2 + .25*3 = 2.75, discount .125, bootstrap obs 3
    o, a, r, d, nobs = out[0]
    assert o[0] == 0.0 and r == 2.75 and d == np.float32(0.5**3) and nobs[0] == 3.0
    # Window [1,2,3] ends at terminal: R = 2 + .5*3 + .25*4 = 4.5, discount 0
    o, a, r, d, _ = out[1]
    assert o[0] == 1.0 and r == 4.5 and d == 0.0
    # Flushed partials [2,3] and [3]
    o, a, r, d, _ = out[2]
    assert o[0] == 2.0 and r == 3.0 + 0.5 * 4.0 and d == 0.0
    o, a, r, d, _ = out[3]
    assert o[0] == 3.0 and r == 4.0 and d == 0.0
    assert len(out) == 4


def test_replay_checkpoint_roundtrip():
    for cls in (UniformReplay, PrioritizedReplay):
        buf = cls(capacity=16, obs_dim=2, act_dim=1, seed=0)
        _fill(buf, 10, obs_dim=2, act_dim=1)
        if isinstance(buf, PrioritizedReplay):
            buf.update_priorities(np.arange(10), np.linspace(0.1, 1.0, 10))
        state = buf.state_dict()
        fresh = cls(capacity=16, obs_dim=2, act_dim=1, seed=0)
        fresh.load_state_dict(state)
        assert len(fresh) == 10
        np.testing.assert_array_equal(fresh.obs[:10], buf.obs[:10])
        if isinstance(buf, PrioritizedReplay):
            np.testing.assert_allclose(
                fresh._tree.get(np.arange(10)), buf._tree.get(np.arange(10))
            )
