"""Fault-injection layer unit tests (faults.py; docs/RESILIENCE.md): the
--faults grammar, seeded determinism, FaultSite call-ordinal semantics,
checkpoint write-retry + manifest verification + restore fallback, the
pool monitor's backoff/quarantine/zero-rows machinery (with a stubbed
spawn — no real worker processes), shipper restart, and the
ChunkPrefetcher hang paths the PR-1 hardening never had tests for."""

import os
import time
import warnings

import numpy as np
import pytest

from distributed_ddpg_tpu import checkpoint as ckpt_lib
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.faults import FaultPlan, FaultSpec, InjectedFault

# ---------------------------------------------------------------------------
# grammar / plan semantics
# ---------------------------------------------------------------------------


def test_parse_full_grammar():
    p = FaultPlan.parse(
        "worker:2:crash@5000; worker:0:hang@8000;ckpt:write:ioerror@2;"
        "shipper:slow@3~0.01;prefetch:sample:hang@1~0.5"
    )
    assert len(p.specs) == 5
    by_kind = {s.kind: s for s in p.specs}
    assert by_kind["crash"].component == "worker"
    assert by_kind["crash"].target == "2"
    assert by_kind["crash"].at == 5000
    assert by_kind["ioerror"].target == "write"
    assert by_kind["slow"].duration_s == 0.01  # explicit ~ wins
    assert by_kind["hang"].duration_s > 0  # seeded default for site hangs


def test_parse_empty_and_legacy():
    assert not FaultPlan.parse("")
    assert not FaultPlan.parse(None)
    legacy = FaultPlan.parse("actor:3:1500")  # old --inject_fault form
    assert legacy.specs == (FaultSpec("worker", "3", "crash", 1500, 0.0),)


@pytest.mark.parametrize(
    "bad",
    [
        "worker:0:banana@2",      # unknown kind
        "gpu:0:crash@2",          # unknown component
        "worker:0:crash",         # missing trigger
        "worker:0:crash@zero",    # non-integer trigger
        "worker:0:crash@0",       # trigger < 1
        "worker:abc:crash@5",     # non-integer worker id
        "worker:0:ioerror@5",     # site-only kind on a worker
        "ckpt:write:stall@5",     # worker-only kind on a site
        "a:b:c:d:crash@5",        # too many fields
        "worker:0:slow@5~-1",     # negative duration
    ],
)
def test_parse_rejects(bad):
    with pytest.raises(ValueError, match="bad fault spec"):
        FaultPlan.parse(bad)


def test_seeded_durations_deterministic():
    spec = "worker:0:slow@10;shipper:slow@2;prefetch:sample:hang@1"
    a = FaultPlan.parse(spec, seed=7)
    b = FaultPlan.parse(spec, seed=7)
    c = FaultPlan.parse(spec, seed=8)
    assert [s.duration_s for s in a.specs] == [s.duration_s for s in b.specs]
    assert [s.duration_s for s in a.specs] != [s.duration_s for s in c.specs]


def test_for_worker_incarnation_semantics():
    p = FaultPlan.parse("worker:1:crash@100;worker:1:crashloop@50;worker:0:hang@10")
    first = p.for_worker(1, incarnation=0)
    assert ("crash", 100, 0.0) in first
    assert ("crash", 50, 0.0) in first  # crashloop arms as a crash
    respawn = p.for_worker(1, incarnation=3)
    assert respawn == [("crash", 50, 0.0)]  # ONLY crashloop re-arms
    assert p.for_worker(2) == []


def test_site_ordinal_and_ioerror():
    site = FaultPlan.parse("ckpt:write:ioerror@3").site("ckpt", "write")
    site.tick()
    site.tick()
    with pytest.raises(InjectedFault):
        site.tick()
    site.tick()  # one-shot: the 4th call sails through
    assert site.calls == 4
    assert site.fired == ["ckpt:write:ioerror@3"]
    # InjectedFault must be an OSError: recovery paths written for real IO
    # failures treat the injected article identically.
    assert issubclass(InjectedFault, OSError)


def test_cli_inject_fault_alias_folds_into_faults():
    """Pre-chaos-harness scripts pass --inject_fault=actor:<id>:<step>;
    the flag must keep working as an alias that folds into the plan."""
    c = DDPGConfig.from_flags(["--inject_fault=actor:0:200"])
    assert c.fault_plan().for_worker(0) == [("crash", 200, 0.0)]
    c2 = DDPGConfig.from_flags(
        ["--faults=worker:1:hang@50", "--inject_fault=actor:0:200"]
    )
    assert len(c2.fault_plan().specs) == 2


def test_config_validates_fault_grammar():
    DDPGConfig(faults="worker:0:crash@200")  # valid parses
    with pytest.raises(ValueError, match="bad fault spec"):
        DDPGConfig(faults="worker:0:nope@1")
    with pytest.raises(ValueError, match="heartbeat_timeout_s"):
        DDPGConfig(heartbeat_timeout_s=0.0)
    with pytest.raises(ValueError, match="quarantine_respawns"):
        DDPGConfig(quarantine_respawns=-1)
    with pytest.raises(ValueError, match="ckpt_write_retries"):
        DDPGConfig(ckpt_write_retries=-1)


# ---------------------------------------------------------------------------
# typed-exit injection: pod:<proc>:exit@<beat>:<code> (ISSUE 19)
# ---------------------------------------------------------------------------


def test_parse_pod_exit_grammar_round_trip():
    p = FaultPlan.parse("pod:1:exit@5:77")
    (s,) = p.specs
    assert (s.component, s.target, s.kind, s.at, s.code) == \
        ("pod", "1", "exit", 5, 77)
    assert s.describe() == "pod:1:exit@5:77"
    # The plan repr round-trips through the same describe().
    assert "pod:1:exit@5:77" in repr(p)
    # Composes with the rest of the grammar.
    both = FaultPlan.parse("pod:0:exit@3:78; worker:1:crash@100")
    assert {s.kind for s in both.specs} == {"exit", "crash"}


@pytest.mark.parametrize(
    "bad",
    [
        "pod:1:exit@5",            # exit needs the trailing :<code>
        "pod:1:exit@5:banana",     # non-integer code
        "pod:1:exit@5:300",        # out of 0..255
        "pod:1:exit@5:-1",         # negative is a signal, not a status
        "worker:1:exit@5:77",      # pod-only kind
        "pod:1:kill@5:77",         # the 4-field form is exit-only
    ],
)
def test_parse_pod_exit_rejects(bad):
    with pytest.raises(ValueError, match="bad fault spec"):
        FaultPlan.parse(bad)


def test_pod_exit_fires_os_exit_with_scripted_code(monkeypatch):
    """The exit kind hard-exits with EXACTLY the scripted status at the
    scripted beat ordinal, on the targeted process only — the lever that
    drills every supervisor branch (exits.py) without real peer loss."""
    calls = []
    monkeypatch.setattr(os, "_exit", lambda code: calls.append(code))
    plan = FaultPlan.parse("pod:1:exit@3:77")
    bystander = plan.pod_site(0)
    victim = plan.pod_site(1)
    for _ in range(4):
        bystander.tick()
    assert calls == []                   # wrong process: never fires
    victim.tick()
    victim.tick()
    assert calls == []                   # beats 1-2: not yet
    victim.tick()                        # beat 3: the scripted exit
    assert calls == [77]
    assert victim.fired == ["pod:1:exit@3:77"]


# ---------------------------------------------------------------------------
# checkpoint: retry, manifest, fallback chain
# ---------------------------------------------------------------------------


def _tiny_state(cfg, seed=0):
    from distributed_ddpg_tpu.learner import init_train_state

    return init_train_state(cfg, 3, 1, seed=seed)


def test_ckpt_write_retry_consumes_injected_ioerror(tmp_path):
    cfg = DDPGConfig(actor_hidden=(8, 8), critic_hidden=(8, 8))
    state = _tiny_state(cfg)
    site = FaultPlan.parse("ckpt:write:ioerror@1").site("ckpt", "write")
    path = ckpt_lib.save(
        str(tmp_path), 5, state, None, cfg,
        retries=2, backoff_s=0.01, fault=site,
    )
    assert os.path.isdir(path)
    ok, why = ckpt_lib.verify_checkpoint(str(tmp_path), 5)
    assert ok, why
    # The retry advanced the site ordinal: attempt 1 failed, attempt 2 wrote.
    assert site.calls == 2


def test_ckpt_write_retries_exhausted_raises(tmp_path):
    cfg = DDPGConfig(actor_hidden=(8, 8), critic_hidden=(8, 8))
    state = _tiny_state(cfg)
    site = FaultPlan.parse(
        "ckpt:write:ioerror@1;ckpt:write:ioerror@2;ckpt:write:ioerror@3"
    ).site("ckpt", "write")
    with pytest.raises(OSError):
        ckpt_lib.save(
            str(tmp_path), 5, state, None, cfg,
            retries=2, backoff_s=0.01, fault=site,
        )
    # No half-written step directory may survive a failed save.
    assert not os.path.isdir(tmp_path / "step_5")


def test_async_saver_counts_write_retries(tmp_path):
    cfg = DDPGConfig(actor_hidden=(8, 8), critic_hidden=(8, 8))
    state = _tiny_state(cfg)
    site = FaultPlan.parse("ckpt:write:ioerror@1").site("ckpt", "write")
    saver = ckpt_lib.AsyncSaver()
    assert saver.save_async(
        str(tmp_path), 7, state, None, cfg,
        retries=2, backoff_s=0.01, fault=site,
    )
    saver.wait()
    assert saver.write_retries == 1
    assert ckpt_lib.latest_step(str(tmp_path)) == 7


def _corrupt_checkpoint(directory, step):
    """Truncate the largest file under step_<step> — the bit-rot /
    half-write shape the manifest digest exists to catch."""
    root = os.path.join(directory, f"step_{step}")
    files = []
    for dirpath, _, names in os.walk(root):
        files += [os.path.join(dirpath, n) for n in names]
    target = max(files, key=os.path.getsize)
    with open(target, "r+b") as f:
        f.truncate(max(os.path.getsize(target) // 2, 1))


def test_restore_falls_back_past_corrupt_latest(tmp_path):
    cfg = DDPGConfig(actor_hidden=(8, 8), critic_hidden=(8, 8))
    state = _tiny_state(cfg)
    ckpt_lib.save(str(tmp_path), 10, state, None, cfg, env_steps=111)
    ckpt_lib.save(str(tmp_path), 20, state, None, cfg, env_steps=222)
    _corrupt_checkpoint(str(tmp_path), 20)
    ok, why = ckpt_lib.verify_checkpoint(str(tmp_path), 20)
    assert not ok and "mismatch" in why
    # An EXPLICIT step request never falls back — precise asks fail loud.
    with pytest.raises(Exception):
        ckpt_lib.restore(str(tmp_path), _tiny_state(cfg, seed=9), step=20)
    restored, step, env_steps = ckpt_lib.restore(
        str(tmp_path), _tiny_state(cfg, seed=9), config=cfg
    )
    assert step == 10 and env_steps == 111
    # The corrupt checkpoint is quarantined out of the step_N namespace so
    # a resumed run re-reaching step 20 can write there again (orbax
    # refuses existing destinations) — payload kept for forensics.
    assert not (tmp_path / "step_20").exists()
    assert (tmp_path / "corrupt_step_20").is_dir()
    assert not (tmp_path / "manifest_20.json").exists()
    assert ckpt_lib.latest_step(str(tmp_path)) == 10


def test_restore_falls_back_when_load_fails_but_verification_passes(tmp_path):
    """Corruption the crc spot-check can't see (no manifest + gutted
    payload, orbax raising ValueError for the tree mismatch) must still
    fall back — only check_config_compatible's ValueError may abort the
    chain."""
    import shutil

    cfg = DDPGConfig(actor_hidden=(8, 8), critic_hidden=(8, 8))
    state = _tiny_state(cfg)
    ckpt_lib.save(str(tmp_path), 10, state, None, cfg, env_steps=111)
    ckpt_lib.save(str(tmp_path), 20, state, None, cfg)
    os.unlink(tmp_path / "manifest_20.json")   # pre-manifest checkpoint
    for name in os.listdir(tmp_path / "step_20"):
        full = tmp_path / "step_20" / name
        shutil.rmtree(full) if full.is_dir() else os.unlink(full)
    ok, why = ckpt_lib.verify_checkpoint(str(tmp_path), 20)
    assert ok and "no manifest" in why  # verification cannot see it
    restored, step, env_steps = ckpt_lib.restore(
        str(tmp_path), _tiny_state(cfg, seed=9), config=cfg
    )
    assert step == 10 and env_steps == 111
    # A config incompatibility is a contract violation, not corruption:
    # it must abort the chain loudly, never silently fall back.
    bad = cfg.replace(actor_hidden=(16, 16))
    with pytest.raises(ValueError, match="actor_hidden"):
        ckpt_lib.restore(str(tmp_path), _tiny_state(bad, seed=9), config=bad)


def test_restore_all_corrupt_raises_with_history(tmp_path):
    cfg = DDPGConfig(actor_hidden=(8, 8), critic_hidden=(8, 8))
    state = _tiny_state(cfg)
    for step in (1, 2):
        ckpt_lib.save(str(tmp_path), step, state, None, cfg)
        _corrupt_checkpoint(str(tmp_path), step)
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        ckpt_lib.restore(str(tmp_path), _tiny_state(cfg, seed=9))


def test_manifest_pruned_with_checkpoint(tmp_path):
    cfg = DDPGConfig(actor_hidden=(8, 8), critic_hidden=(8, 8))
    state = _tiny_state(cfg)
    for step in (10, 20, 30, 40):
        ckpt_lib.save(str(tmp_path), step, state, None, cfg, keep=2)
    names = sorted(os.listdir(tmp_path))
    assert "manifest_30.json" in names and "manifest_40.json" in names
    assert "manifest_10.json" not in names and "manifest_20.json" not in names


# ---------------------------------------------------------------------------
# pool monitor: backoff, quarantine, zero-rows detector (stubbed spawn)
# ---------------------------------------------------------------------------


class _FakeProc:
    def __init__(self, alive=True):
        self._alive = alive
        self.terminated = False

    def is_alive(self):
        return self._alive and not self.terminated

    def terminate(self):
        self.terminated = True

    def join(self, timeout=None):
        pass


def _stub_pool(monkeypatch, **cfg_kw):
    """An ActorPool whose _spawn never forks: monitor()'s supervision state
    machine can be driven directly, with _FakeProc / heartbeat pokes
    standing in for real worker behavior."""
    from distributed_ddpg_tpu.actors.pool import ActorPool
    from distributed_ddpg_tpu.envs import make, spec_of

    cfg = DDPGConfig(
        env_id="Pendulum-v1", actor_hidden=(8, 8), critic_hidden=(8, 8),
        num_actors=1, transport="queue", **cfg_kw,
    )
    env = make(cfg.env_id, seed=0, prefer_builtin=True)
    pool = ActorPool(cfg, spec_of(env))
    spawned = []

    def fake_spawn(i):
        spawned.append(i)
        pool._incarnation[i] += 1
        pool._heartbeat[i] = 0.0
        pool._last_rows_t[i] = 0.0
        pool._procs[i] = None  # stays dead: every respawn fails again

    monkeypatch.setattr(pool, "_spawn", fake_spawn)
    return pool, spawned


def test_monitor_backoff_then_quarantine(monkeypatch):
    pool, spawned = _stub_pool(
        monkeypatch,
        respawn_backoff_s=0.05, respawn_backoff_max_s=0.2,
        quarantine_respawns=3, quarantine_window_s=60.0,
    )
    # Failure #1 detected; the respawn must NOT happen on the same call
    # (backoff pending), only after the backoff expires.
    stats = pool.monitor()
    assert stats["respawned"] == 0 and pool._pending_respawn[0]
    time.sleep(0.06)
    stats = pool.monitor()
    assert stats["respawned"] == 1 and spawned == [0]
    # The stub leaves the slot dead, so failures accumulate: #2 respawns
    # after its (longer) backoff, #3 trips the breaker.
    time.sleep(0.01)
    pool.monitor()  # detect failure #2
    time.sleep(0.25)
    assert pool.monitor()["respawned"] == 1  # respawn #2
    stats = pool.monitor()  # detect failure #3 -> quarantine
    assert stats["quarantined"] == 1
    assert pool._quarantined[0]
    assert pool.recovery_counters() == {
        "actor_respawns": 2, "actor_quarantined": 1,
        "actor_unquarantined": 0,
    }
    # Quarantined slots are never touched before the probe cooldown
    # (default quarantine_probe_s is minutes; this test never reaches it).
    time.sleep(0.25)
    assert pool.monitor()["respawned"] == 0
    assert spawned == [0, 0]


def test_monitor_quarantine_probe_recovers_slot(monkeypatch):
    """Quarantine probing (docs/RESILIENCE.md): after quarantine_probe_s
    the monitor probes the slot with ONE respawn; sustained progress
    (rows + surviving quarantine_window_s) un-quarantines it and the
    actor_unquarantined counter rides recovery_counters."""
    pool, spawned = _stub_pool(
        monkeypatch,
        respawn_backoff_s=0.0, quarantine_respawns=2,
        quarantine_window_s=0.05, quarantine_probe_s=0.1,
    )
    # Two immediate failures -> quarantine.
    pool.monitor()
    pool.monitor()
    pool.monitor()
    assert pool.quarantined_count == 1
    n_before_probe = len(spawned)
    # Before the cooldown: untouched.
    assert pool.monitor()["respawned"] == 0
    time.sleep(0.12)
    stats = pool.monitor()  # cooldown elapsed -> probe respawn
    assert stats["respawned"] == 1
    assert len(spawned) == n_before_probe + 1
    assert not pool._quarantined[0] and pool._probing[0]
    # Probe succeeds: worker alive, heartbeating, delivering rows.
    pool._procs[0] = _FakeProc()
    pool._heartbeat[0] = time.time()
    pool._note_version(0, 0)          # rows drained from the probed slot
    time.sleep(0.06)                  # survive quarantine_window_s
    pool._heartbeat[0] = time.time()
    pool.monitor()
    assert not pool._probing[0] and pool.quarantined_count == 0
    assert pool.recovery_counters()["actor_unquarantined"] == 1


def test_monitor_probe_heartbeats_without_rows_is_not_progress(monkeypatch):
    """The zero-rows detector ARMS _last_rows_t at the first heartbeat;
    that arming write must not satisfy the probe's sustained-progress
    check — a heartbeating-but-rowless probe is not a recovery."""
    pool, _ = _stub_pool(
        monkeypatch,
        respawn_backoff_s=0.0, quarantine_respawns=2,
        quarantine_window_s=0.05, quarantine_probe_s=0.1,
        actor_no_progress_s=10.0,  # detector armed, far from firing
    )
    pool.monitor()
    pool.monitor()
    pool.monitor()
    assert pool.quarantined_count == 1
    time.sleep(0.12)
    pool.monitor()                    # probe respawn
    assert pool._probing[0]
    pool._procs[0] = _FakeProc()
    pool._heartbeat[0] = time.time()
    pool.monitor()                    # arms the zero-rows clock, NO rows
    time.sleep(0.06)                  # past quarantine_window_s
    pool._heartbeat[0] = time.time()
    pool.monitor()
    assert pool._probing[0], "rowless heartbeats must not end the probe"
    assert pool.recovery_counters()["actor_unquarantined"] == 0


def test_monitor_quarantine_probe_failure_requarantines(monkeypatch):
    """A failed probe goes STRAIGHT back to quarantine for another
    cooldown — no backoff/breaker loop, no respawn stampede."""
    pool, spawned = _stub_pool(
        monkeypatch,
        respawn_backoff_s=0.0, quarantine_respawns=2,
        quarantine_window_s=0.05, quarantine_probe_s=0.1,
    )
    pool.monitor()
    pool.monitor()
    pool.monitor()
    assert pool.quarantined_count == 1
    time.sleep(0.12)
    pool.monitor()                    # probe respawn (stub leaves it dead)
    assert pool._probing[0]
    pool.monitor()                    # dead probe detected
    assert pool.quarantined_count == 1 and not pool._probing[0]
    assert pool.recovery_counters()["actor_unquarantined"] == 0
    n = len(spawned)
    pool.monitor()                    # cooldown restarted: no respawn yet
    assert len(spawned) == n


def test_monitor_zero_rows_blind_spot(monkeypatch):
    """The watchdog coverage note's actor-side blind spot: a worker that
    heartbeats but delivers no rows past actor_no_progress_s must be
    respawned through the same path as a dead one."""
    pool, spawned = _stub_pool(
        monkeypatch,
        actor_no_progress_s=0.1, respawn_backoff_s=0.01,
        quarantine_respawns=0,  # breaker off: isolate the detector
    )
    proc = _FakeProc()
    pool._procs[0] = proc
    pool._heartbeat[0] = time.time()  # booted and heartbeating
    assert pool.monitor()["respawned"] == 0  # arms the zero-rows clock
    # Fresh heartbeats keep coming, but no rows ever do.
    time.sleep(0.15)
    pool._heartbeat[0] = time.time()
    pool.monitor()  # detects no_rows -> terminates + pending respawn
    assert proc.terminated
    time.sleep(0.02)
    pool.monitor()
    assert spawned == [0]
    # Control: rows arriving reset the clock — no respawn.
    proc2 = _FakeProc()
    pool._procs[0] = proc2
    pool._heartbeat[0] = time.time()
    pool.monitor()
    time.sleep(0.15)
    pool._heartbeat[0] = time.time()
    pool._note_version(0, 0)  # rows drained from this worker
    pool.monitor()
    assert not proc2.terminated and spawned == [0]


def test_monitor_quarantine_window_prunes_old_failures(monkeypatch):
    """Failures OUTSIDE quarantine_window_s must not count toward the
    breaker — only a crash LOOP quarantines, not occasional mortality."""
    pool, spawned = _stub_pool(
        monkeypatch,
        respawn_backoff_s=0.0, quarantine_respawns=3,
        quarantine_window_s=0.1,
    )
    for _ in range(6):  # 6 failures, each in its own expired window
        pool.monitor()  # detect (backoff 0 -> respawn same/next call)
        pool.monitor()
        time.sleep(0.12)
    assert pool.quarantined_count == 0
    assert len(spawned) >= 5


def test_monitor_fault_site_slows_supervision(monkeypatch):
    """pool:monitor:slow — the supervisor ITSELF lags; training must only
    see late detection, never a crash."""
    pool, _ = _stub_pool(
        monkeypatch,
        faults="pool:monitor:slow@1~0.15",
        quarantine_respawns=0, respawn_backoff_s=0.0,
    )
    t0 = time.monotonic()
    pool.monitor()
    assert time.monotonic() - t0 >= 0.14
    t0 = time.monotonic()
    pool.monitor()  # one-shot: the second pass is full speed
    assert time.monotonic() - t0 < 0.1


# ---------------------------------------------------------------------------
# ChunkPrefetcher under injected sampler faults (PR-1 hardening, untested)
# ---------------------------------------------------------------------------


class _TinyReplay:
    def __init__(self):
        self.rng = np.random.default_rng(0)

    def sample(self, n):
        return {
            "obs": self.rng.standard_normal((n, 3)).astype(np.float32),
            "indices": np.arange(n),
        }


def test_prefetch_timeout_under_injected_sampler_hang():
    """A hung sampler (prefetch:sample:hang) must surface as the NAMED
    PrefetchTimeout — worker alive, no chunk — not a bare queue.Empty."""
    from distributed_ddpg_tpu.parallel.prefetch import (
        ChunkPrefetcher,
        PrefetchTimeout,
    )

    site = FaultPlan.parse("prefetch:sample:hang@1~1.5").site(
        "prefetch", "sample"
    )
    pf = ChunkPrefetcher(
        _TinyReplay(), lambda c: c, 4, 2, depth=1, fault=site
    ).start()
    try:
        with pytest.raises(PrefetchTimeout, match="worker alive"):
            pf.next(timeout=0.3)
        # After the hang lifts, the pipeline self-heals: the chunk arrives.
        chunk, indices = pf.next(timeout=10.0)
        assert chunk["obs"].shape == (2, 4, 3)
    finally:
        assert pf.stop(timeout=5.0) is True


def test_prefetch_stop_during_sampler_hang_leaks_loudly():
    """stop() during an in-flight sampler hang cannot join in time: it must
    warn and return False (leak the daemon) rather than hang teardown —
    and the thread must still exit once the hang lifts."""
    from distributed_ddpg_tpu.parallel.prefetch import ChunkPrefetcher

    site = FaultPlan.parse("prefetch:sample:hang@1~1.0").site(
        "prefetch", "sample"
    )
    pf = ChunkPrefetcher(
        _TinyReplay(), lambda c: c, 4, 2, depth=1, fault=site
    ).start()
    time.sleep(0.1)  # let the worker enter the hang
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert pf.stop(timeout=0.2) is False
    assert any("leaking" in str(w.message) for w in caught)
    pf._thread.join(timeout=5.0)
    assert not pf._thread.is_alive()


def test_prefetch_sampler_crash_surfaces_in_next():
    from distributed_ddpg_tpu.parallel.prefetch import (
        ChunkPrefetcher,
        PrefetchTimeout,
    )

    site = FaultPlan.parse("prefetch:sample:crash@1").site(
        "prefetch", "sample"
    )
    pf = ChunkPrefetcher(
        _TinyReplay(), lambda c: c, 4, 2, depth=1, fault=site
    ).start()
    try:
        with pytest.raises(RuntimeError, match="prefetch thread died") as ei:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                try:
                    pf.next(timeout=0.5)
                except PrefetchTimeout:
                    # PrefetchTimeout IS a RuntimeError: a slow worker
                    # start under load must not satisfy the raises()
                    # with the wrong exception — keep polling until the
                    # crash itself surfaces.
                    continue
        assert isinstance(ei.value.__cause__, InjectedFault)
    finally:
        pf.stop()


# ---------------------------------------------------------------------------
# ingest shipper: injected crash -> supervised restart
# ---------------------------------------------------------------------------


def test_shipper_restart_after_injected_crash():
    from distributed_ddpg_tpu.replay.device import DeviceReplay

    site = FaultPlan.parse("shipper:ship:crash@1").site("shipper", "ship")
    rep = DeviceReplay(
        4096, 3, 1, block_size=64, async_ship=True, fault=site
    )
    try:
        rng = np.random.default_rng(0)
        block = rng.standard_normal((64, rep.width)).astype(np.float32)
        rep.add_packed(block)  # shipper's first dispatch crashes
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            s = rep._shipper
            if s is not None and s.exc is not None:
                break
            time.sleep(0.02)
        # Producer path notices, restarts the shipper, and rows flow again.
        rep.add_packed(block)
        rep.drain_pending()
        assert len(rep) >= 64
        snap = rep.ingest_snapshot()
        assert snap["ingest_shipper_restarts"] == 1
    finally:
        rep.close()
