"""C51 auto-support sizing (ops/support_auto.py; VERDICT r4 Weak #4 / Next #7).

The hand-tuned supports this replaces (docs/EVIDENCE.md §3): Pendulum
[-1600, 0], LunarLander ±400, HalfCheetah widened to [-100, 1000] after the
±150 default saturated at Q≈600. The tests pin the auto rules to those
values: initial sizing from real builtin-Pendulum warmup rewards must land
in the hand-tuned ballpark, and the expansion rule must grow a warmup-sized
HalfCheetah support past the trained Q range.
"""

import math

import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.ops import support_auto


def _pendulum_warmup_rewards(n: int = 5000, seed: int = 0) -> np.ndarray:
    from distributed_ddpg_tpu.envs import make

    env = make("Pendulum-v1", seed=seed, prefer_builtin=True)
    rng = np.random.default_rng(seed)
    obs, _ = env.reset(seed=seed)
    rewards = []
    for _ in range(n):
        action = rng.uniform(-2.0, 2.0, size=(1,)).astype(np.float32)
        obs, r, term, trunc, _ = env.step(action)
        rewards.append(r)
        if term or trunc:
            obs, _ = env.reset()
    return np.asarray(rewards, np.float32)


class TestInitialBounds:
    def test_pendulum_matches_hand_tuned(self):
        # Hand-tuned support: [-1600, 0]. Dense all-negative rewards in
        # [-16.3, 0] with gamma 0.99 must reproduce that geometry from data.
        v_min, v_max = support_auto.initial_bounds(
            _pendulum_warmup_rewards(), gamma=0.99, n_step=1
        )
        assert -2500.0 <= v_min <= -1000.0
        assert 0.0 <= v_max <= 400.0

    def test_sparse_terminal_rewards_inside_support(self):
        # LunarLander-style: small dense shaping plus rare ±100 terminals.
        # The raw extremes must be inside the support even though the 1/99
        # percentiles clip them away.
        rng = np.random.default_rng(1)
        r = rng.normal(0.0, 1.0, size=10_000)
        r[::500] = 100.0
        r[250::500] = -100.0
        v_min, v_max = support_auto.initial_bounds(r, gamma=0.99, n_step=1)
        assert v_min <= -100.0
        assert v_max >= 100.0

    def test_terminal_rewards_excluded_from_persistent_bound(self):
        # LunarLander warmup regression (measured, round 5): random-policy
        # crashes put -100 terminals inside the 1st percentile, and the
        # persistent bound multiplied them by the ~34-step horizon —
        # support [-3731, 639] where the hand value was ±400. With the
        # discount mask the terminals only enter via the raw extreme.
        rng = np.random.default_rng(3)
        n = 20_000
        r = rng.normal(-0.5, 1.5, size=n)
        d = np.full(n, 0.99**3)
        term = rng.random(n) < 0.02  # crash every ~50 transitions
        r[term] = -100.0
        d[term] = 0.0
        v_min, v_max = support_auto.initial_bounds(
            r, gamma=0.99, n_step=3, discounts=d
        )
        assert v_min <= -100.0  # crash reward itself stays inside
        assert v_min >= -1000.0  # but is not horizon-multiplied to -3700
        assert v_max <= 500.0

    def test_nstep_rewards_use_effective_discount(self):
        # n-step rewards are ~n× larger but bootstrap through gamma^n; the
        # two effects cancel, so 1-step and 3-step sizing must agree to
        # within the margin factor, not differ by ~n×.
        rng = np.random.default_rng(2)
        r1 = rng.uniform(-1.0, 0.0, size=5000)
        lo1, _ = support_auto.initial_bounds(r1, gamma=0.99, n_step=1)
        lo3, _ = support_auto.initial_bounds(3.0 * r1, gamma=0.99, n_step=3)
        assert 0.5 < lo3 / lo1 < 2.0

    def test_all_terminal_warmup_skips_horizon(self):
        # Bandit-style env: every transition terminal, nothing bootstraps —
        # true returns ARE the rewards, so the support must not be
        # horizon-multiplied ~100x into one-atom resolution.
        rng = np.random.default_rng(4)
        r = rng.uniform(-1.0, 1.0, size=2000)
        d = np.zeros(2000)
        v_min, v_max = support_auto.initial_bounds(
            r, gamma=0.99, n_step=1, discounts=d
        )
        assert -3.0 <= v_min <= -1.0
        assert 1.0 <= v_max <= 3.0

    def test_degenerate_rewards_get_floor_width(self):
        v_min, v_max = support_auto.initial_bounds(
            np.zeros(100), gamma=0.99, n_step=1
        )
        assert v_max - v_min >= 2 * support_auto.MIN_HALF_WIDTH - 1e-9

    def test_rejects_empty(self):
        with pytest.raises(ValueError):
            support_auto.initial_bounds(np.array([np.nan]), 0.99)


class TestMaybeExpand:
    def test_cheetah_growth_covers_trained_q(self):
        # Warmup random-policy sizing gives HalfCheetah roughly ±200; the
        # trained critic reaches Q ≈ 600 (docs/EVIDENCE.md §3 — the ±150
        # saturation incident). Feeding the climbing mean_q (always inside
        # the current support — projection clips) must grow v_max past the
        # hand-tuned 1000 in a handful of geometric expansions.
        v_min, v_max = -200.0, 200.0
        expansions = 0
        for q in [50.0, 120.0, 170.0, 550.0, 1300.0]:
            q = min(q, v_max)  # mean_q physically cannot exceed v_max
            grown = support_auto.maybe_expand(v_min, v_max, q)
            if grown is not None:
                v_min, v_max = grown
                expansions += 1
        assert v_max >= 1000.0
        assert v_min == -200.0  # low edge never approached, never moved
        assert expansions <= 3  # geometric, not incremental

    def test_centered_q_is_stable(self):
        assert support_auto.maybe_expand(-150.0, 150.0, 0.0) is None
        assert support_auto.maybe_expand(-150.0, 150.0, 80.0) is None

    def test_negative_drift_expands_low_edge(self):
        grown = support_auto.maybe_expand(-150.0, 150.0, -140.0)
        assert grown is not None
        v_min, v_max = grown
        assert v_min < -150.0 and v_max == 150.0

    def test_nan_mean_q_is_ignored(self):
        assert support_auto.maybe_expand(-150.0, 150.0, float("nan")) is None

    def test_oversized_support_does_not_fire_on_healthy_q(self):
        # Round-5 LunarLander v1 regression: support accidentally sized
        # [-3731, 639] + mean_q -11.7 (healthy, tiny) expanded v_max to
        # 5010 under the old span-relative trigger. The proximity rule
        # scales with |mean_q|, so a small Q far from both edges in its
        # own units must not fire, no matter how wide the support is.
        assert support_auto.maybe_expand(-3731.1, 639.3, -11.7) is None
        assert support_auto.maybe_expand(-3731.1, 639.3, 100.0) is None

    def test_near_zero_edge_stays_expandable(self):
        # Pendulum-style v_max ~ 0: mean_q -> 0 from below never crosses
        # zero, but the MIN_HALF_WIDTH floor keeps the edge detectable.
        grown = support_auto.maybe_expand(-1600.0, 0.0, -0.1)
        assert grown is not None
        assert grown[1] > 0.0

    def test_cooldown_blocks_the_reinterpretation_cascade(self):
        # The stretch is affine with unchanged logits, so right after an
        # expansion the reinterpreted mean_q lands near the NEW edge again
        # — an immediate re-check would re-fire forever. The cooldown must
        # hold it until SGD has had the relearn horizon.
        lo, hi, mean_q = -10.0, 10.0, 8.5
        grown = support_auto.maybe_expand(lo, hi, mean_q)
        assert grown is not None
        new_lo, new_hi = grown
        # z' = lo + (z - lo) * (new_range / old_range): the critic's
        # unchanged distribution now decodes to the stretched mean_q.
        mean_q2 = new_lo + (mean_q - lo) * (new_hi - new_lo) / (hi - lo)
        # Still near the new edge (the cascade's core) ...
        assert support_auto.maybe_expand(new_lo, new_hi, mean_q2) is not None
        # ... so the cooldown must hold it, then re-arm.
        assert (
            support_auto.maybe_expand(
                new_lo, new_hi, mean_q2, steps_since_expansion=50
            )
            is None
        )
        assert (
            support_auto.maybe_expand(
                new_lo, new_hi, mean_q2,
                steps_since_expansion=support_auto.COOLDOWN_STEPS,
            )
            is not None
        )

    def test_diverged_critic_refused_by_data(self):
        # Round-5 HalfCheetah seed-1 incident (module docstring): critic
        # diverged to mean_q ≈ +2400 while replay rewards stayed at the
        # random-policy scale (returns ≈ -400); the mean_q-only rule
        # expanded [-96, 639] -> ... -> [-118, 5907], giving the
        # divergence more room each time. With data corroboration the
        # trigger fires but the replay rewards cap the support: refused.
        flat = lambda: (-120.0, 640.0)  # data bound ~= the current support
        assert (
            support_auto.maybe_expand(
                -96.0, 639.0, 560.0, data_bounds_fn=flat
            )
            is None
        )

    def test_grown_data_bound_drives_the_new_edge(self):
        # Healthy growth: the policy actually earns bigger rewards, the
        # rule-1 bound over the CURRENT replay outgrows the support, and
        # the expansion lands exactly on the data-derived edge (one
        # recompile straight to the supported size, not blind 3x hops).
        grown = support_auto.maybe_expand(
            -96.0, 639.0, 560.0, data_bounds_fn=lambda: (-130.0, 2500.0)
        )
        assert grown == (-96.0, 2500.0)

    def test_corroborated_trigger_gets_geometric_headroom(self):
        # The data bound gates but does not cap (HalfCheetah seed-0
        # round-5 measurement: capping at the lagging percentile bound
        # throttled a healthy run to 3672 vs 5075 uncapped). Data just
        # past the gate -> the GEOMETRIC edge wins when larger.
        grown = support_auto.maybe_expand(
            -118.0, 70.0, 55.0, data_bounds_fn=lambda: (-118.0, 120.0)
        )
        assert grown is not None
        # geometric: center -24 + 3*94 = 258 > data 120
        assert grown[1] > 250.0

    def test_low_edge_corroboration_symmetric(self):
        grown = support_auto.maybe_expand(
            -150.0, 150.0, -140.0, data_bounds_fn=lambda: (-900.0, 100.0)
        )
        assert grown == (-900.0, 150.0)
        assert (
            support_auto.maybe_expand(
                -150.0, 150.0, -140.0, data_bounds_fn=lambda: (-150.0, 100.0)
            )
            is None
        )

    def test_data_fn_not_called_without_trigger(self):
        # The reward-column pull is ~100k rows; it must be lazy.
        def boom():
            raise AssertionError("data_bounds_fn called without a trigger")

        assert (
            support_auto.maybe_expand(-150.0, 150.0, 0.0, data_bounds_fn=boom)
            is None
        )

    def test_controller_counts_refusals_with_cooldown(self):
        ctl = support_auto.SupportController()
        calls = 0

        def flat():
            nonlocal calls
            calls += 1
            return (-120.0, 640.0)

        cd = support_auto.COOLDOWN_STEPS
        # Refusals are cooled down like expansions: a pinned diverged
        # mean_q must not re-pay the reward-column pull every check.
        for step, want_refusals in (
            (50, 1),          # trigger fires, data refuses
            (100, 1),         # inside the refusal cooldown: silently held
            (50 + cd, 2),     # re-armed, refused again
            (100 + 2 * cd, 3),
        ):
            assert (
                ctl.check(-96.0, 639.0, 560.0, step, data_bounds_fn=flat)
                is None
            )
            assert ctl.refusals == want_refusals
        assert calls == 3  # the held check never pulled the column
        # A corroborated expansion still applies and does not count.
        grown = ctl.check(
            -96.0, 639.0, 560.0, 200 + 3 * cd,
            data_bounds_fn=lambda: (-120.0, 2500.0),
        )
        assert grown == (-96.0, 2500.0)
        assert ctl.refusals == 3


class TestConfigPlumbing:
    def test_auto_flag_parses_to_nan(self):
        c = DDPGConfig.from_flags(
            ["--distributional=true", "--v_min=auto", "--v_max=auto"]
        )
        assert math.isnan(c.v_min) and math.isnan(c.v_max)
        assert c.v_support_auto

    def test_concrete_flags_still_parse(self):
        c = DDPGConfig.from_flags(
            ["--distributional=true", "--v_min=-400", "--v_max=400"]
        )
        assert c.v_min == -400.0 and not c.v_support_auto

    def test_single_sided_auto_rejected(self):
        with pytest.raises(ValueError, match="BOTH"):
            DDPGConfig(
                distributional=True, v_min=float("nan"), v_max=150.0
            )

    def test_auto_requires_distributional(self):
        with pytest.raises(ValueError, match="distributional"):
            DDPGConfig(v_min=float("nan"), v_max=float("nan"))

    def test_inverted_concrete_bounds_rejected(self):
        with pytest.raises(ValueError, match="v_min"):
            DDPGConfig(distributional=True, v_min=150.0, v_max=-150.0)

    def test_checkpoint_compat_treats_nan_as_equal(self):
        from distributed_ddpg_tpu.checkpoint import _compat_eq

        assert _compat_eq(float("nan"), float("nan"))
        assert _compat_eq(1.0, 1.0)
        assert not _compat_eq(float("nan"), 1.0)
        assert not _compat_eq(1.0, 2.0)


class TestBoundsPersistence:
    def test_resolved_bounds_ride_the_checkpoint(self, tmp_path):
        # Expansion-derived bounds are unrecoverable from reward stats, so
        # restore must hand back exactly what was saved — and checkpoints
        # written without the field must restore cleanly without it.
        from distributed_ddpg_tpu import checkpoint as ckpt_lib
        from distributed_ddpg_tpu.learner import init_train_state

        config = DDPGConfig(
            distributional=True, actor_hidden=(8, 8), critic_hidden=(8, 8)
        )
        state = init_train_state(config, 3, 1, seed=0)
        ckpt_lib.save(
            str(tmp_path / "auto"), 7, state, None, config,
            v_bounds=(-200.0, 1400.0),
        )
        meta = {}
        _, step, _ = ckpt_lib.restore(
            str(tmp_path / "auto"), state, meta_out=meta
        )
        assert step == 7
        assert meta["v_bounds"] == (-200.0, 1400.0)

        ckpt_lib.save(str(tmp_path / "plain"), 9, state, None, config)
        meta = {}
        ckpt_lib.restore(str(tmp_path / "plain"), state, meta_out=meta)
        assert "v_bounds" not in meta


class TestAgentIntegration:
    def test_pendulum_agent_resolves_and_trains(self):
        # End-to-end on builtin Pendulum: the agent must resolve concrete
        # bounds at the first train step (warmup-reward sizing), keep them
        # in the hand-tuned ballpark, and produce finite metrics.
        from distributed_ddpg_tpu.agent import DDPGAgent
        from distributed_ddpg_tpu.envs import make, spec_of

        config = DDPGConfig(
            distributional=True,
            v_min=float("nan"),
            v_max=float("nan"),
            actor_hidden=(32, 32),
            critic_hidden=(32, 32),
            replay_min_size=400,
            batch_size=32,
            total_env_steps=600,
        )
        env = make(config.env_id, seed=0, prefer_builtin=True)
        agent = DDPGAgent(config, spec_of(env))
        obs, _ = env.reset(seed=0)
        metrics = None
        for _ in range(600):
            action = agent.act(obs)
            next_obs, r, term, trunc, _ = env.step(action)
            agent.observe(obs, action, r, term, next_obs)
            obs = next_obs
            if term or trunc:
                obs, _ = env.reset()
                agent.reset_episode()
            m = agent.train_step()
            if m is not None:
                metrics = m
        assert metrics is not None
        assert not agent.config.v_support_auto  # resolved to concrete floats
        assert -3000.0 <= agent.config.v_min <= -500.0
        assert agent.config.v_max <= 500.0
        assert np.isfinite(metrics["critic_loss"])
        assert np.isfinite(metrics["mean_q"])


class TestShardedLearnerRebuild:
    def test_set_value_bounds_rebuilds_and_trains(self):
        # An auto-config learner builds (lazily — nan bounds never trace),
        # resolves via set_value_bounds, and the rebuilt chunk program
        # trains with finite metrics on the new support.
        import jax

        from distributed_ddpg_tpu.parallel.learner import ShardedLearner
        from distributed_ddpg_tpu.types import pack_batch_np

        config = DDPGConfig(
            distributional=True,
            v_min=float("nan"),
            v_max=float("nan"),
            actor_hidden=(16, 16),
            critic_hidden=(16, 16),
            batch_size=8,
            scale_batch_with_data=False,
        )
        obs_dim, act_dim = 3, 1
        learner = ShardedLearner(config, obs_dim, act_dim, 1.0, chunk_size=2)
        learner.set_value_bounds(-120.0, 40.0)
        rng = np.random.default_rng(0)
        chunk = {
            "obs": rng.standard_normal((2, 8, obs_dim)).astype(np.float32),
            "action": rng.uniform(-1, 1, (2, 8, act_dim)).astype(np.float32),
            "reward": rng.uniform(-1, 0, (2, 8)).astype(np.float32),
            "discount": np.full((2, 8), 0.99, np.float32),
            "next_obs": rng.standard_normal((2, 8, obs_dim)).astype(np.float32),
        }
        out = learner.run_chunk(chunk)
        metrics = learner.metrics_to_host(out)
        assert np.isfinite(metrics["critic_loss"])
        # mean_q lives on the resolved support
        assert -120.0 <= metrics["mean_q"] <= 40.0
        assert learner.config.v_min == -120.0
