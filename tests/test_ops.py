"""Unit tests for the pure ops (SURVEY.md §4 'Unit' row): Polyak = exact
lerp, Adam vs optax oracle, losses vs hand-computed closed forms, OU noise
mean-reversion statistics, action squashing at bounds."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.models.mlp import (
    actor_apply,
    actor_init,
    critic_apply,
    critic_init,
)
from distributed_ddpg_tpu.ops import losses
from distributed_ddpg_tpu.ops.noise import OUNoise
from distributed_ddpg_tpu.ops.optim import adam_update
from distributed_ddpg_tpu.ops.polyak import polyak_update
from distributed_ddpg_tpu.types import Batch, OptState


def test_polyak_is_exact_lerp():
    online = {"w": jnp.ones((3,)) * 2.0}
    target = {"w": jnp.zeros((3,))}
    out = polyak_update(online, target, tau=0.25)
    np.testing.assert_allclose(out["w"], 0.5 * jnp.ones(3))


def test_adam_matches_optax():
    params = {"w": jnp.array([1.0, -2.0, 3.0]), "b": jnp.array([0.5])}
    opt = OptState(
        mu=jax.tree.map(jnp.zeros_like, params),
        nu=jax.tree.map(jnp.zeros_like, params),
        count=jnp.zeros((), jnp.int32),
    )
    ox = optax.adam(1e-3)
    ox_state = ox.init(params)
    p_mine, p_ox = params, params
    for i in range(5):
        grads = jax.tree.map(lambda x: jnp.sin(x + i), p_ox)
        p_mine, opt = adam_update(p_mine, jax.tree.map(lambda x: jnp.sin(x + i), p_mine), opt, 1e-3)
        updates, ox_state = ox.update(grads, ox_state, p_ox)
        p_ox = optax.apply_updates(p_ox, updates)
    for k in params:
        np.testing.assert_allclose(p_mine[k], p_ox[k], rtol=1e-6, atol=1e-7)


def test_critic_loss_closed_form():
    """On a linear critic with known weights the TD loss has a closed form."""
    # 1-layer critic (action inserted at layer 0): Q = [s, a] @ w + b
    params = ({"w": jnp.array([[1.0], [2.0]]), "b": jnp.array([0.5])},)
    tparams = params
    # target actor: single layer mapping s -> a, tanh-squashed
    aparams = ({"w": jnp.array([[0.0]]), "b": jnp.array([0.0])},)
    batch = Batch(
        obs=jnp.array([[1.0]]),
        action=jnp.array([[2.0]]),
        reward=jnp.array([1.0]),
        discount=jnp.array([0.9]),
        next_obs=jnp.array([[0.0]]),
        weight=jnp.array([1.0]),
    )
    # mu'(s') = tanh(0) = 0; Q'(s'=0, a=0) = 0.5 → y = 1 + 0.9*0.5 = 1.45
    # Q(s,a) = 1*1 + 2*2 + 0.5 = 5.5 → td = -4.05, loss = 16.4025
    loss, td = losses.critic_loss(
        params, aparams, tparams, batch, action_scale=1.0, action_insert_layer=0
    )
    np.testing.assert_allclose(float(loss), 4.05**2, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(td), [-4.05], rtol=1e-6)


def test_actor_loss_is_negative_mean_q():
    key = jax.random.PRNGKey(0)
    ap = actor_init(key, 3, 2, (16,))
    cp = critic_init(key, 3, 2, (16,), action_insert_layer=1)
    obs = jax.random.normal(key, (8, 3))
    batch = Batch(obs=obs, action=None, reward=None, discount=None, next_obs=None, weight=None)
    loss = losses.actor_loss(ap, cp, batch, action_scale=1.0)
    a = actor_apply(ap, obs, 1.0)
    q = critic_apply(cp, obs, a, 1)
    np.testing.assert_allclose(float(loss), -float(jnp.mean(q)), rtol=1e-6)


def test_action_squashing_at_bounds():
    """Saturated pre-activations must squash exactly to ±action_scale."""
    params = (
        {"w": jnp.full((1, 1), 100.0), "b": jnp.zeros((1,))},
    )
    out_hi = actor_apply(params, jnp.array([[1.0]]), action_scale=2.0)
    out_lo = actor_apply(params, jnp.array([[-1.0]]), action_scale=2.0)
    np.testing.assert_allclose(out_hi, [[2.0]], atol=1e-5)
    np.testing.assert_allclose(out_lo, [[-2.0]], atol=1e-5)


def test_ou_noise_mean_reversion():
    """Long-run OU statistics: mean ~ mu, std ~ sigma*sqrt(dt/(2*theta*dt - theta^2*dt^2))
    ~ sigma/sqrt(2*theta) for small dt. Check mean reversion + bounded std."""
    ou = OUNoise((1,), theta=0.15, sigma=0.2, dt=1.0, seed=0)
    samples = np.array([ou() for _ in range(20000)])
    # Discrete-time OU: x_{t+1} = (1-theta)x_t + sigma*N → var = sigma²/(1-(1-theta)²)
    expected_std = 0.2 / np.sqrt(1 - (1 - 0.15) ** 2)
    assert abs(samples[5000:].mean()) < 0.05
    np.testing.assert_allclose(samples[5000:].std(), expected_std, rtol=0.1)
    ou.reset()
    np.testing.assert_allclose(ou.state, 0.0)


def test_categorical_projection_identity():
    """With reward=0, discount=1 the projection is the identity."""
    support = losses.categorical_support(-1.0, 1.0, 5)
    probs = jnp.array([[0.1, 0.2, 0.4, 0.2, 0.1]])
    out = losses.categorical_projection(
        support, probs, jnp.array([0.0]), jnp.array([1.0])
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(probs), atol=1e-6)


def test_categorical_projection_terminal_delta():
    """Terminal transition (discount=0) projects all mass onto reward atom."""
    support = losses.categorical_support(-1.0, 1.0, 5)  # atoms at -1,-.5,0,.5,1
    probs = jnp.full((1, 5), 0.2)
    out = losses.categorical_projection(
        support, probs, jnp.array([0.5]), jnp.array([0.0])
    )
    np.testing.assert_allclose(np.asarray(out)[0], [0, 0, 0, 1.0, 0], atol=1e-6)
    # Off-atom reward splits mass linearly between neighbors.
    out = losses.categorical_projection(
        support, probs, jnp.array([0.25]), jnp.array([0.0])
    )
    np.testing.assert_allclose(np.asarray(out)[0], [0, 0, 0.5, 0.5, 0], atol=1e-6)


def test_projection_mass_conserved():
    key = jax.random.PRNGKey(1)
    support = losses.categorical_support(-10.0, 10.0, 51)
    logits = jax.random.normal(key, (32, 51))
    probs = jax.nn.softmax(logits, -1)
    r = jax.random.uniform(key, (32,), minval=-5, maxval=5)
    d = jax.random.uniform(key, (32,), minval=0, maxval=1)
    out = losses.categorical_projection(support, probs, r, d)
    np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, rtol=1e-5)


def test_actor_offset_for_asymmetric_spaces():
    """tanh output must map onto [low, high] when the box is asymmetric."""
    params = ({"w": jnp.full((1, 1), 100.0), "b": jnp.zeros((1,))},)
    # Box [0, 1]: scale 0.5, offset 0.5.
    hi = actor_apply(params, jnp.array([[1.0]]), action_scale=0.5, action_offset=0.5)
    lo = actor_apply(params, jnp.array([[-1.0]]), action_scale=0.5, action_offset=0.5)
    np.testing.assert_allclose(hi, [[1.0]], atol=1e-5)
    np.testing.assert_allclose(lo, [[0.0]], atol=1e-5)


def test_action_insert_layer_validation():
    with pytest.raises(ValueError):
        critic_init(jax.random.PRNGKey(0), 3, 2, (16, 16), action_insert_layer=3)
    with pytest.raises(ValueError):
        DDPGConfig(critic_hidden=(16, 16), action_insert_layer=5)
