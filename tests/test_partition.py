"""The regex partition-rule engine (parallel/partition.py; docs/MESH.md)
and the 2D ('data','model') mesh composition it unlocks: the rule tables
must reproduce the legacy hardcoded alternation bit-for-bit at the seed
shapes, a model_axis=2 run must compose with sharded replay + device
actors + the serve jax backend + the fused beat and land float-tolerance
parity against the model_axis=1 oracle, and checkpoints must roundtrip
across placements bit-identically."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import init_train_state
from distributed_ddpg_tpu.parallel import mesh as mesh_lib
from distributed_ddpg_tpu.parallel import partition
from distributed_ddpg_tpu.parallel.learner import ShardedLearner
from distributed_ddpg_tpu.types import pack_batch_np

OBS, ACT = 4, 2


# ---------------------------------------------------------------------------
# the rule engine itself
# ---------------------------------------------------------------------------


def _legacy_layer_pspec(i, n, shape, m):
    """The pre-engine mesh._layer_pspec, verbatim — the oracle the rule
    tables must reproduce bit-for-bit (docs/MESH.md 'Rule grammar')."""
    if len(shape) == 3:
        inner = _legacy_layer_pspec(i, n, shape[1:], m)
        return {"w": P(None, *inner["w"]), "b": P(None, *inner["b"])}
    in_dim, out_dim = shape
    if m == 1 or i == n - 1:
        return {"w": P(None, None), "b": P(None)}
    if i % 2 == 0:
        if out_dim % m == 0:
            return {"w": P(None, "model"), "b": P("model")}
    else:
        if in_dim % m == 0:
            return {"w": P("model", None), "b": P(None)}
    return {"w": P(None, None), "b": P(None)}


def _legacy_net_pspec(params, m):
    n = len(params)
    return tuple(
        _legacy_layer_pspec(i, n, params[i]["w"].shape, m) for i in range(n)
    )


@pytest.mark.parametrize(
    "cfg_kw",
    [
        {},  # the seed DDPG shapes
        dict(twin_critic=True, target_noise=0.1),  # rank-3 ensemble leaves
        dict(sac=True),  # double-width Gaussian head + alpha machinery
        dict(distributional=True),  # wide categorical value head
        dict(actor_hidden=(400, 300), critic_hidden=(400, 300)),
        dict(actor_hidden=(64, 64, 64), critic_hidden=(64, 64, 64)),
    ],
)
def test_rules_reproduce_legacy_pspec(cfg_kw):
    """The default tables reproduce the old hardcoded Megatron
    alternation EXACTLY — same specs, same arity, same indivisible
    fallbacks — at every model size, for every algorithm family."""
    cfg = DDPGConfig(**cfg_kw)
    state = init_train_state(cfg, 3, 1, 0)
    for m in (1, 2, 4, 8):
        for net in ("actor_params", "critic_params"):
            params = getattr(state, net)
            assert partition.net_pspec(params, m) == _legacy_net_pspec(
                params, m
            ), (cfg_kw, m, net)


def test_state_pspec_opt_moments_match_params():
    """Adam moments derive from the SAME table as the params — they can
    never shard differently (the checkpoint/pointer-swap invariant)."""
    state = init_train_state(DDPGConfig(), OBS, ACT, 0)
    mesh = mesh_lib.make_mesh(-1, 2)
    sp = partition.state_pspec(state, mesh)
    assert sp.actor_opt.mu == sp.actor_params
    assert sp.actor_opt.nu == sp.actor_params
    assert sp.critic_opt.mu == sp.critic_params
    assert sp.target_actor_params == sp.actor_params
    assert sp.step == P() and sp.actor_opt.count == P()


def test_rule_engine_semantics():
    leaf = lambda *s: np.zeros(s, np.float32)
    # first match wins — the specific override beats the generic rule
    tree = {"head": {"w": leaf(8, 4)}}
    rules = [
        (r"head/w$", P(None, None)),
        (r"/w$", P(None, "model")),
    ]
    spec = partition.match_partition_rules(rules, tree, 2)
    assert spec["head"]["w"] == P(None, None)
    # rank alignment: a rank-2 spec covers a rank-3 stacked leaf
    tree = ({"w": leaf(2, 8, 4)},)
    spec = partition.match_partition_rules([(r"w$", P(None, "model"))], tree, 2)
    assert spec[0]["w"] == P(None, None, "model")
    # indivisible -> whole-leaf replication, not an error
    spec = partition.match_partition_rules([(r"w$", P(None, "model"))],
                                           ({"w": leaf(8, 5)},), 2)
    assert spec[0]["w"] == P(None, None)
    # scalars replicate without consulting the table
    spec = partition.match_partition_rules([], {"count": leaf()}, 2)
    assert spec["count"] == P()
    # unmatched path is a hard error naming the path
    with pytest.raises(partition.PartitionRuleError, match="0/w"):
        partition.match_partition_rules([(r"nope", P())], ({"w": leaf(4, 4)},), 2)
    # a spec outranking its leaf is a table bug, not a silent truncation
    with pytest.raises(partition.PartitionRuleError, match="outrank"):
        partition.match_partition_rules(
            [(r"b$", P(None, "model"))], ({"b": leaf(4)},), 2)


# ---------------------------------------------------------------------------
# config validation matrix (docs/MESH.md decision table)
# ---------------------------------------------------------------------------


def test_config_tp_validation_matrix():
    # newly legal: TP composes with sharded replay / device actors /
    # serve jax / fused beat
    DDPGConfig(model_axis=2, replay_sharding="sharded", fused_chunk="off")
    DDPGConfig(
        model_axis=2, replay_sharding="sharded", actor_backend="device",
        num_actors=0, fused_beat="on", fused_chunk="off",
    )
    DDPGConfig(model_axis=2, serve_actors=True, serve_backend="jax")
    # genuine rejections, each naming the knob to flip
    with pytest.raises(ValueError, match="model_axis must be >= 1"):
        DDPGConfig(model_axis=0)
    with pytest.raises(ValueError, match="backend='jax_tpu'"):
        DDPGConfig(model_axis=2, backend="native")
    with pytest.raises(ValueError, match="fused_chunk='auto'"):
        DDPGConfig(model_axis=2, fused_chunk="on")
    with pytest.raises(ValueError, match="actor_hidden"):
        DDPGConfig(model_axis=2, actor_hidden=(255, 256))
    with pytest.raises(ValueError, match="critic_hidden"):
        DDPGConfig(model_axis=4, critic_hidden=(256, 130))
    # explicit shard_map mode stays data-parallel only (learner-level)
    with pytest.raises(ValueError, match="data-parallel only"):
        ShardedLearner(
            DDPGConfig(model_axis=2), OBS, ACT, action_scale=1.0,
            mode="explicit",
        )


# ---------------------------------------------------------------------------
# the 2D composition + the model_axis=1 parity oracle
# ---------------------------------------------------------------------------


def _rows(n=2048, seed=0):
    rng = np.random.default_rng(seed)
    return pack_batch_np({
        "obs": rng.standard_normal((n, OBS)).astype(np.float32),
        "action": rng.uniform(-1, 1, (n, ACT)).astype(np.float32),
        "reward": rng.standard_normal(n).astype(np.float32),
        "discount": np.full(n, 0.99, np.float32),
        "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
        "weight": np.ones(n, np.float32),
    })


def _state_diff(a, b):
    return max(
        float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _learner_end_state(model_axis, per=False):
    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=32,
        model_axis=model_axis, fused_chunk="off", prioritized=per,
        scale_batch_with_data=False, replay_sharding="sharded",
        replay_capacity=4096,
    )
    # Fixed data axis (4) across arms: same index/noise streams (the
    # placement-invariant PRNG note in parallel/mesh.py), so the end
    # states are float-tolerance comparable.
    mesh = mesh_lib.make_mesh(4, model_axis,
                              devices=jax.devices()[: 4 * model_axis])
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mesh=mesh,
                         chunk_size=3, replay_sharding="sharded")
    cls = DevicePrioritizedReplay if per else DeviceReplay
    rep = cls(4096, OBS, ACT, mesh=mesh, block_size=1024, async_ship=False,
              replay_sharding="sharded")
    rep.add_packed(_rows())
    rep.drain_pending()
    for _ in range(3):
        if per:
            lrn.run_sample_chunk_per(rep, beta=0.5)
        else:
            lrn.run_sample_chunk(rep)
    return jax.device_get(lrn.state), lrn


def test_tp_sharded_replay_learner_parity():
    """model_axis=2 x replay_sharding='sharded': params actually shard
    on 'model', the ring stays partitioned on 'data', and the learner
    end state matches the model_axis=1 oracle to float tolerance (same
    data axis => same sampled index stream)."""
    ref, _ = _learner_end_state(1)
    tp, lrn = _learner_end_state(2)
    assert lrn.state.actor_params[0]["w"].sharding.spec == P(None, "model")
    assert _state_diff(ref, tp) < 1e-5


@pytest.mark.slow
def test_tp_sharded_replay_per_parity():
    """Same oracle for the prioritized path: the sharded PER draw's
    index stream is a function of the DATA axis partition only, so TP
    changes nothing but matmul reduction order."""
    ref, _ = _learner_end_state(1, per=True)
    tp, _ = _learner_end_state(2, per=True)
    assert _state_diff(ref, tp) < 1e-5


def _fused_beat_end_state(model_axis, per=False):
    from distributed_ddpg_tpu.actors.device_pool import DeviceActorPool
    from distributed_ddpg_tpu.parallel.megastep import FusedMegastep
    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )

    cfg = DDPGConfig(
        env_id="Pendulum-v1", actor_backend="device", num_actors=0,
        device_actor_envs=8, device_actor_chunk=2,
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=16,
        scale_batch_with_data=False, prioritized=per,
        model_axis=model_axis, fused_chunk="off", fused_beat="on",
        replay_sharding="sharded", replay_capacity=4096,
    )
    mesh = mesh_lib.make_mesh(4, model_axis,
                              devices=jax.devices()[: 4 * model_axis])
    pool = DeviceActorPool(cfg, mesh=mesh)
    lrn = ShardedLearner(
        cfg, pool.obs_dim, pool.act_dim, pool.action_scale,
        action_offset=pool.action_offset, mesh=mesh, chunk_size=2,
        replay_sharding="sharded",
    )
    cls = DevicePrioritizedReplay if per else DeviceReplay
    rep = cls(4096, pool.obs_dim, pool.act_dim, mesh=mesh, block_size=16,
              async_ship=False, replay_sharding="sharded")
    pool.set_params(lrn.state.actor_params)
    while len(rep) < cfg.batch_size:
        pool.run_chunk(rep)
    ms = FusedMegastep(cfg, lrn, pool, rep)
    for _ in range(3):
        ms.run_beat(beta=0.5) if per else ms.run_beat()
    logical = rep._to_logical_rows(np.asarray(jax.device_get(rep.storage)))
    return jax.device_get(lrn.state), logical


def test_tp_fused_beat_full_composition_parity():
    """The acceptance composition: model_axis=2 x sharded replay x
    device actors x fused_beat='on' runs as ONE donated-carry beat
    program on the 8-virtual-device mesh, and both the learner end
    state AND the ring contents (logical order) match the model_axis=1
    oracle to float tolerance."""
    ref_state, ref_ring = _fused_beat_end_state(1)
    tp_state, tp_ring = _fused_beat_end_state(2)
    assert _state_diff(ref_state, tp_state) < 1e-5
    assert float(np.max(np.abs(ref_ring - tp_ring))) < 1e-5


@pytest.mark.slow
def test_tp_fused_beat_per_parity():
    ref_state, ref_ring = _fused_beat_end_state(1, per=True)
    tp_state, tp_ring = _fused_beat_end_state(2, per=True)
    assert _state_diff(ref_state, tp_state) < 1e-5
    assert float(np.max(np.abs(ref_ring - tp_ring))) < 1e-5


def test_serve_jax_tp_matches_oracle():
    """serve_backend='jax' over a TP mesh: kernels genuinely shard on
    'model' (same rule table as the learner) and served actions match
    both the single-device jax apply and the numpy bit-parity oracle to
    float tolerance."""
    from distributed_ddpg_tpu.actors.policy import layout_size, param_layout
    from distributed_ddpg_tpu.serve.server import InferenceServer

    layout = param_layout(3, 1, (32, 32))
    rng = np.random.default_rng(0)
    flat = rng.standard_normal(layout_size(layout)).astype(np.float32) * 0.1
    obs = rng.standard_normal((8, 3)).astype(np.float32)

    mesh = mesh_lib.make_mesh(4, 2)
    tp = InferenceServer(layout, np.ones(1, np.float32), backend="jax",
                         max_batch=8, mesh=mesh)
    tp.refresh(flat)
    assert tp._jax_params[0]["w"].sharding.spec == P(None, "model")
    ref = InferenceServer(layout, np.ones(1, np.float32), backend="numpy",
                          max_batch=8)
    ref.refresh(flat)
    np.testing.assert_allclose(
        tp._compute(obs), ref._compute(obs), rtol=1e-5, atol=1e-6
    )
    # the numpy oracle refuses a mesh — it IS the single-device path
    with pytest.raises(ValueError, match="numpy"):
        InferenceServer(layout, np.ones(1, np.float32), backend="numpy",
                        mesh=mesh)


# ---------------------------------------------------------------------------
# checkpoint portability across placement
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip_across_model_axis(tmp_path):
    """Save at model_axis=1, restore at model_axis=2 (and back):
    checkpoints store the LOGICAL (unsharded) state — like the sharded
    replay ring's wire format — so the roundtrip is bit-identical and a
    run can change its TP degree at any resume point."""
    from distributed_ddpg_tpu import checkpoint as ckpt_lib

    cfg = DDPGConfig(actor_hidden=(32, 32), critic_hidden=(32, 32))
    state = init_train_state(cfg, OBS, ACT, seed=0)

    def place(model_axis):
        mesh = mesh_lib.make_mesh(8 // model_axis, model_axis)
        return jax.device_put(
            state, mesh_lib.to_named(mesh, mesh_lib.state_pspec(state, mesh))
        ), mesh

    st1, _ = place(1)
    ckpt_lib.save(str(tmp_path / "a"), 7, st1, config=cfg)
    restored, step, _ = ckpt_lib.restore(str(tmp_path / "a"), state)
    assert step == 7
    st2, mesh2 = place(2)
    # restore lands host-side; placing it under the TP mesh is the
    # train.py resume path (device_put with the learner's sharding)
    st2_restored = jax.device_put(
        restored, mesh_lib.to_named(mesh2, mesh_lib.state_pspec(state, mesh2))
    )
    assert st2_restored.actor_params[0]["w"].sharding.spec == P(None, "model")
    for a, b in zip(jax.tree.leaves(jax.device_get(st2_restored)),
                    jax.tree.leaves(jax.device_get(st1))):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # and back: save the TP-placed tree, restore replicated, bit-identical
    ckpt_lib.save(str(tmp_path / "b"), 9, st2_restored, config=cfg)
    back, _, _ = ckpt_lib.restore(str(tmp_path / "b"), state)
    for a, b in zip(jax.tree.leaves(back),
                    jax.tree.leaves(jax.device_get(st1))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# mesh_* observability (metrics.MeshStats; docs/OBSERVABILITY.md)
# ---------------------------------------------------------------------------


def test_runs_summarize_and_compare_render_mesh_digest(tmp_path):
    """tools.runs renders the mesh_* family as its own digest section;
    compare deltas the per-device bytes (lower-is-better) and treats the
    mesh shape as context."""
    import json

    from distributed_ddpg_tpu.tools import runs

    path = tmp_path / "mesh.jsonl"
    recs = [
        {"kind": "train", "step": 100, "wall_time": 1.0,
         "mesh_data_axis": 4, "mesh_model_axis": 2,
         "mesh_param_bytes_per_device": 1000,
         "mesh_param_bytes_total": 2000},
        {"kind": "final", "step": 200, "wall_time": 2.0,
         "mesh_data_axis": 4, "mesh_model_axis": 2,
         "mesh_param_bytes_per_device": 1000,
         "mesh_param_bytes_total": 2000},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    digest = runs.summarize_run(str(path))
    assert digest["mesh"]["mesh_model_axis"]["last"] == 2
    assert digest["mesh"]["mesh_param_bytes_per_device"]["last"] == 1000
    text = runs.render_summary(digest)
    assert "mesh / tensor parallelism" in text
    assert "mesh_param_bytes_per_device" in text
    _, rows = runs.compare_runs(str(path), str(path))
    assert any(r[0] == "mesh_param_bytes_per_device" for r in rows)
    assert not any(r[0] == "mesh_model_axis" for r in rows)


def test_mesh_stats_measures_tp_bytes():
    """mesh_param_bytes_per_device is read from live sharding metadata
    and divides by the model axis for the rule-sharded majority."""
    from distributed_ddpg_tpu.metrics import MeshStats

    state = init_train_state(
        DDPGConfig(actor_hidden=(64, 64), critic_hidden=(64, 64)), OBS, ACT, 0
    )

    def bytes_at(model_axis):
        mesh = mesh_lib.make_mesh(8 // model_axis, model_axis)
        placed = jax.device_put(
            state, mesh_lib.to_named(mesh, mesh_lib.state_pspec(state, mesh))
        )
        snap = MeshStats(mesh.shape["data"], model_axis).snapshot(
            jax.tree.leaves(placed)
        )
        assert snap["mesh_model_axis"] == model_axis
        assert snap["mesh_param_bytes_total"] == sum(
            int(np.prod(np.asarray(l.shape, dtype=np.int64)))
            * l.dtype.itemsize
            for l in jax.tree.leaves(placed)
        )
        return snap["mesh_param_bytes_per_device"]

    full, half = bytes_at(1), bytes_at(2)
    # 64-wide hiddens shard cleanly; final layers + the 66-wide critic
    # insert layer replicate, so the ratio sits between 1.5 and 2.
    assert 1.5 < full / half <= 2.0
