"""Stall-watchdog unit tests (SURVEY.md §5 'Failure detection': the
learner-side complement to the actor heartbeats tested in test_actors) and
the train_jax wiring: the watchdog must fire on frozen progress, must NOT
fire while progress advances or after stop(), and a watchdog-enabled
training run must complete without a false positive."""

import json
import threading
import time

import pytest

from distributed_ddpg_tpu import trace
from distributed_ddpg_tpu.watchdog import Watchdog


def test_fires_on_frozen_progress():
    fired = threading.Event()
    w = Watchdog(timeout_s=0.3, progress=lambda: 0, on_stall=fired.set).start()
    try:
        assert fired.wait(timeout=2.0), "watchdog never fired on frozen progress"
    finally:
        w.stop()


def test_silent_while_progress_advances():
    fired = threading.Event()
    beat = [0]

    def pump():
        t0 = time.monotonic()
        while time.monotonic() - t0 < 1.0:
            beat[0] += 1
            time.sleep(0.02)

    w = Watchdog(
        timeout_s=0.3, progress=lambda: beat[0], on_stall=fired.set
    ).start()
    try:
        pump()
        assert not fired.is_set(), "watchdog fired despite advancing progress"
    finally:
        w.stop()


def test_stop_prevents_firing():
    fired = threading.Event()
    w = Watchdog(timeout_s=0.3, progress=lambda: 0, on_stall=fired.set).start()
    w.stop()
    assert not fired.wait(timeout=0.8), "watchdog fired after stop()"


def test_grant_suppresses_firing_until_deadline():
    """grant(extra_s) is a wall-clock suppression window: the watchdog must
    not fire during it even with frozen progress AND intervening beats
    (beats between grant() and the protected long call must not consume
    the allowance), and must fire once it expires."""
    fired = threading.Event()
    beat = [0]
    w = Watchdog(
        timeout_s=0.2, progress=lambda: beat[0], on_stall=fired.set
    ).start()
    try:
        w.grant(1.2)
        beat[0] += 1  # beat AFTER the grant — must not consume it
        assert not fired.wait(timeout=0.8), "fired inside the grant window"
        assert fired.wait(timeout=2.0), "never fired after the grant expired"
    finally:
        w.stop()


def test_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        Watchdog(timeout_s=0.0, progress=lambda: 0)


def test_stall_writes_report_and_trace_before_on_stall(tmp_path):
    """The stall path must land stall_report.json (structured thread
    stacks) AND stall_trace.json (the flight-recorder tail) BEFORE
    on_stall runs — the default on_stall os._exits, so anything written
    after it would never exist. Asserted by checking file presence FROM
    INSIDE the on_stall override."""
    trace.configure(capacity=256)
    try:
        with trace.span("pre_stall_phase"):
            pass
        seen = {}
        fired = threading.Event()

        def on_stall():
            seen["report"] = (tmp_path / "stall_report.json").exists()
            seen["trace"] = (tmp_path / "stall_trace.json").exists()
            fired.set()

        w = Watchdog(
            timeout_s=0.3, progress=lambda: 0, on_stall=on_stall,
            stall_dir=str(tmp_path),
        ).start()
        try:
            assert fired.wait(timeout=2.0), "watchdog never fired"
        finally:
            w.stop()
        assert seen == {"report": True, "trace": True}
        assert set(w.stall_artifacts) == {"report", "trace"}

        report = json.loads((tmp_path / "stall_report.json").read_text())
        assert "no trainer progress" in report["reason"]
        assert report["timeout_s"] == 0.3
        assert report["last_progress_value"] == "0"
        assert report["stalled_s"] >= 0.3
        # The watchdog's own thread must be among the structured stacks
        # (it is alive at dump time), and stacks must be real frames.
        names = {t["name"] for t in report["threads"]}
        assert "stall-watchdog" in names
        assert all(t["stack"] for t in report["threads"])

        tr = json.loads((tmp_path / "stall_trace.json").read_text())
        assert any(
            e.get("name") == "pre_stall_phase" for e in tr["traceEvents"]
        )
    finally:
        trace.disable()


def test_stall_report_without_tracing_still_written(tmp_path):
    """Tracing off (the default for tests/interactive runs): the stall
    path still writes the structured report — only the trace artifact is
    skipped."""
    trace.disable()
    fired = threading.Event()
    w = Watchdog(
        timeout_s=0.3, progress=lambda: 0, on_stall=fired.set,
        stall_dir=str(tmp_path),
    ).start()
    try:
        assert fired.wait(timeout=2.0)
    finally:
        w.stop()
    assert (tmp_path / "stall_report.json").exists()
    assert not (tmp_path / "stall_trace.json").exists()
    report = json.loads((tmp_path / "stall_report.json").read_text())
    assert report["trace_events"] == 0


def test_grant_suppression_with_stall_dir(tmp_path):
    """grant() must keep suppressing the stall path with artifact writing
    configured: no artifacts may appear during the grant window (a report
    written for a suppressed stall would be a false alarm on disk), and
    the artifacts + on_stall must both fire after it expires."""
    fired = threading.Event()
    w = Watchdog(
        timeout_s=0.2, progress=lambda: 0, on_stall=fired.set,
        stall_dir=str(tmp_path),
    ).start()
    try:
        w.grant(1.2)
        assert not fired.wait(timeout=0.8), "fired inside the grant window"
        assert not (tmp_path / "stall_report.json").exists(), (
            "stall artifacts written during an active grant"
        )
        assert fired.wait(timeout=2.0), "never fired after the grant expired"
        assert (tmp_path / "stall_report.json").exists()
    finally:
        w.stop()


def test_train_jax_with_watchdog_completes(tmp_path):
    """A watchdog-enabled run must finish cleanly: the beats placed through
    train_jax (init, warmup, loop, teardown) keep a healthy run ahead of
    the timeout, and the wrapper stops the watchdog on return — no
    delayed os._exit can hit the test process afterwards."""
    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.train import train_jax

    cfg = DDPGConfig(
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=1,
        total_env_steps=600,
        replay_min_size=128,
        replay_capacity=5_000,
        eval_every=0,
        watchdog_s=60.0,  # generous: any stall this long is a real hang
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    # The watchdog thread must be gone (stopped by the wrapper).
    time.sleep(0.1)
    assert not any(
        t.name == "stall-watchdog" and t.is_alive()
        for t in threading.enumerate()
    )
