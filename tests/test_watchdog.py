"""Stall-watchdog unit tests (SURVEY.md §5 'Failure detection': the
learner-side complement to the actor heartbeats tested in test_actors) and
the train_jax wiring: the watchdog must fire on frozen progress, must NOT
fire while progress advances or after stop(), and a watchdog-enabled
training run must complete without a false positive."""

import threading
import time

import pytest

from distributed_ddpg_tpu.watchdog import Watchdog


def test_fires_on_frozen_progress():
    fired = threading.Event()
    w = Watchdog(timeout_s=0.3, progress=lambda: 0, on_stall=fired.set).start()
    try:
        assert fired.wait(timeout=2.0), "watchdog never fired on frozen progress"
    finally:
        w.stop()


def test_silent_while_progress_advances():
    fired = threading.Event()
    beat = [0]

    def pump():
        t0 = time.monotonic()
        while time.monotonic() - t0 < 1.0:
            beat[0] += 1
            time.sleep(0.02)

    w = Watchdog(
        timeout_s=0.3, progress=lambda: beat[0], on_stall=fired.set
    ).start()
    try:
        pump()
        assert not fired.is_set(), "watchdog fired despite advancing progress"
    finally:
        w.stop()


def test_stop_prevents_firing():
    fired = threading.Event()
    w = Watchdog(timeout_s=0.3, progress=lambda: 0, on_stall=fired.set).start()
    w.stop()
    assert not fired.wait(timeout=0.8), "watchdog fired after stop()"


def test_grant_suppresses_firing_until_deadline():
    """grant(extra_s) is a wall-clock suppression window: the watchdog must
    not fire during it even with frozen progress AND intervening beats
    (beats between grant() and the protected long call must not consume
    the allowance), and must fire once it expires."""
    fired = threading.Event()
    beat = [0]
    w = Watchdog(
        timeout_s=0.2, progress=lambda: beat[0], on_stall=fired.set
    ).start()
    try:
        w.grant(1.2)
        beat[0] += 1  # beat AFTER the grant — must not consume it
        assert not fired.wait(timeout=0.8), "fired inside the grant window"
        assert fired.wait(timeout=2.0), "never fired after the grant expired"
    finally:
        w.stop()


def test_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        Watchdog(timeout_s=0.0, progress=lambda: 0)


def test_train_jax_with_watchdog_completes(tmp_path):
    """A watchdog-enabled run must finish cleanly: the beats placed through
    train_jax (init, warmup, loop, teardown) keep a healthy run ahead of
    the timeout, and the wrapper stops the watchdog on return — no
    delayed os._exit can hit the test process afterwards."""
    from distributed_ddpg_tpu.config import DDPGConfig
    from distributed_ddpg_tpu.train import train_jax

    cfg = DDPGConfig(
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=1,
        total_env_steps=600,
        replay_min_size=128,
        replay_capacity=5_000,
        eval_every=0,
        watchdog_s=60.0,  # generous: any stall this long is a real hang
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    # The watchdog thread must be gone (stopped by the wrapper).
    time.sleep(0.1)
    assert not any(
        t.name == "stall-watchdog" and t.is_alive()
        for t in threading.enumerate()
    )
