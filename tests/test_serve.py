"""Batched policy-inference service tests (serve/; docs/SERVING.md).

Pins the batcher's dispatch contract (at exactly max_batch; at
max_latency with a partial batch; flush-on-shutdown loses nothing;
bounded-queue backpressure raises typed ServeOverload), the bit-identity
parity of served actions against the per-worker act() path, the
transfer-scheduler `serve` class routing, the serve fault grammar, and —
tier-1 chaos — that served actor workers DEGRADE to their local act()
path instead of deadlocking when the serving stack stalls or crashes."""

import json
import threading
import time

import numpy as np
import pytest

from distributed_ddpg_tpu.actors.policy import (
    NumpyPolicy,
    layout_size,
    param_layout,
)
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.faults import FaultPlan
from distributed_ddpg_tpu.metrics import ServeStats
from distributed_ddpg_tpu.serve import (
    Batcher,
    InferenceServer,
    ServeClosed,
    ServeDispatchError,
    ServeOverload,
    ServeTimeout,
)
from distributed_ddpg_tpu.train import train_jax

OBS, ACT = 5, 2
LAYOUT = param_layout(OBS, ACT, (16, 16))


def _flat(seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(layout_size(LAYOUT)) * 0.3).astype(np.float32)


def _obs(n, seed=1):
    return np.random.default_rng(seed).standard_normal((n, OBS)).astype(
        np.float32
    )


def _echo(batch):
    # Identity-ish apply: first ACT obs columns back, so row identity is
    # checkable without a policy.
    return batch[:, :ACT].copy()


def _collect(n):
    """(callback, results, done) triple for n expected completions."""
    results = [None] * n
    done = threading.Event()
    remaining = [n]
    lock = threading.Lock()

    def cb_for(i):
        def cb(result):
            results[i] = result
            with lock:
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.set()
        return cb

    return cb_for, results, done


# ---------------------------------------------------------------------------
# Batcher dispatch contract
# ---------------------------------------------------------------------------


def test_batcher_dispatches_at_exactly_max_batch():
    """A full batch goes out immediately — it must NOT wait out a long
    latency window."""
    stats = ServeStats(max_batch=4)
    b = Batcher(_echo, max_batch=4, max_latency_s=30.0, max_queue=64,
                stats=stats).start()
    try:
        cb_for, results, done = _collect(4)
        obs = _obs(4)
        for i in range(4):
            b.submit(obs[i], cb_for(i))
        assert done.wait(2.0), "full batch waited on the latency deadline"
        for i in range(4):
            assert np.array_equal(results[i], obs[i, :ACT])
        snap = stats.snapshot()
        assert snap["serve_batches"] == 1
        assert snap["serve_requests"] == 4
        assert snap["serve_fill_mean"] == 1.0
    finally:
        b.close()


def test_batcher_dispatches_partial_batch_at_deadline():
    stats = ServeStats(max_batch=64)
    b = Batcher(_echo, max_batch=64, max_latency_s=0.05, max_queue=64,
                stats=stats).start()
    try:
        cb_for, results, done = _collect(3)
        obs = _obs(3)
        t0 = time.monotonic()
        for i in range(3):
            b.submit(obs[i], cb_for(i))
        assert done.wait(2.0), "partial batch never dispatched at deadline"
        assert time.monotonic() - t0 < 1.0
        snap = stats.snapshot()
        assert snap["serve_batches"] == 1  # ONE partial batch, not three
        assert all(results[i] is not None for i in range(3))
    finally:
        b.close()


def test_batcher_flush_on_shutdown_loses_nothing():
    """close() delivers every accepted request — huge deadline, huge batch,
    so only the shutdown flush can have dispatched them."""
    b = Batcher(_echo, max_batch=1024, max_latency_s=3600.0,
                max_queue=64).start()
    cb_for, results, done = _collect(5)
    obs = _obs(5)
    for i in range(5):
        b.submit(obs[i], cb_for(i))
    b.close()
    assert done.wait(0.5), "flush-on-shutdown dropped requests"
    for i in range(5):
        assert np.array_equal(results[i], obs[i, :ACT])
    with pytest.raises(ServeClosed):
        b.submit(obs[0], lambda r: None)


def test_batcher_bounded_queue_raises_typed_overload():
    gate = threading.Event()

    def blocking_apply(batch):
        gate.wait(10.0)
        return _echo(batch)

    stats = ServeStats(max_batch=1)
    b = Batcher(blocking_apply, max_batch=1, max_latency_s=0.0, max_queue=3,
                stats=stats).start()
    try:
        obs = _obs(8)
        b.submit(obs[0], lambda r: None)  # dispatched, blocked in apply
        deadline = time.monotonic() + 5.0
        # Fill the queue to max_queue, then the next submit must shed.
        filled = 0
        while filled < 3 and time.monotonic() < deadline:
            try:
                b.submit(obs[1 + filled], lambda r: None)
                filled += 1
            except ServeOverload:
                time.sleep(0.01)  # racing the dispatcher's own popleft
        with pytest.raises(ServeOverload):
            for _ in range(8):  # queue can't drain: apply is blocked
                b.submit(obs[7], lambda r: None)
        assert stats.snapshot()["serve_overloads"] >= 1
    finally:
        gate.set()
        b.close()


def test_malformed_obs_fails_batch_typed_not_batcher():
    """A wrong-shaped observation must fail ITS batch typed — the stack
    happens inside the per-batch try — and the service keeps serving."""
    b = Batcher(_echo, max_batch=2, max_latency_s=0.02, max_queue=8).start()
    try:
        cb_for, results, done = _collect(2)
        b.submit(np.zeros(OBS, np.float32), cb_for(0))
        b.submit(np.zeros(OBS + 1, np.float32), cb_for(1))  # wrong obs_dim
        assert done.wait(2.0)
        assert any(isinstance(r, ServeDispatchError) for r in results)
        cb2, r2, d2 = _collect(1)
        b.submit(np.zeros(OBS, np.float32), cb2(0))
        assert d2.wait(2.0), "batcher died on a malformed batch"
        assert not isinstance(r2[0], BaseException)
    finally:
        b.close()


def test_batcher_dispatch_error_fails_batch_typed_and_survives():
    calls = [0]

    def flaky(batch):
        calls[0] += 1
        if calls[0] == 1:
            raise RuntimeError("boom")
        return _echo(batch)

    stats = ServeStats(max_batch=2)
    b = Batcher(flaky, max_batch=2, max_latency_s=0.02, max_queue=64,
                stats=stats).start()
    try:
        cb_for, results, done = _collect(2)
        obs = _obs(4)
        b.submit(obs[0], cb_for(0))
        b.submit(obs[1], cb_for(1))
        assert done.wait(2.0)
        assert all(isinstance(r, ServeDispatchError) for r in results[:2])
        # The batcher SURVIVED the failed batch: later requests serve.
        cb_for2, results2, done2 = _collect(1)
        b.submit(obs[2], cb_for2(0))
        assert done2.wait(2.0)
        assert np.array_equal(results2[0], obs[2, :ACT])
        assert stats.snapshot()["serve_errors"] == 1
    finally:
        b.close()


# ---------------------------------------------------------------------------
# InferenceServer + clients
# ---------------------------------------------------------------------------


def test_served_actions_bit_identical_to_local_act():
    """The parity oracle (docs/SERVING.md): served actions == the
    per-worker act() path's NumpyPolicy output, BITWISE, for the same
    params — under real batched dispatch (concurrent submitters)."""
    flat = _flat()
    local = NumpyPolicy(LAYOUT, action_scale=1.5, action_offset=0.25)
    local.load_flat(flat)
    srv = InferenceServer(
        LAYOUT, 1.5, 0.25, max_batch=8, max_latency_s=0.02, max_queue=256,
    ).start()
    try:
        srv.refresh(flat)
        cli = srv.client(timeout_s=5.0)
        obs = _obs(32)
        results = [None] * 32

        def go(i):
            results[i] = cli.act(obs[i])

        threads = [threading.Thread(target=go, args=(i,)) for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        for i in range(32):
            expect = local(obs[i])[0]
            assert results[i].dtype == expect.dtype
            assert np.array_equal(results[i], expect), (
                f"row {i}: served action differs from local act() "
                f"(max delta {np.abs(results[i] - expect).max()})"
            )
        assert srv.snapshot()["serve_batches"] >= 4  # real batching happened
    finally:
        srv.close()


def test_jax_backend_serves_and_matches_to_tolerance():
    flat = _flat()
    local = NumpyPolicy(LAYOUT, action_scale=1.0)
    local.load_flat(flat)
    srv = InferenceServer(
        LAYOUT, 1.0, max_batch=4, max_latency_s=0.01, max_queue=64,
        backend="jax",
    ).start()
    try:
        srv.refresh(flat)
        cli = srv.client(timeout_s=30.0)  # first call pays the jit compile
        obs = _obs(6)
        for i in range(6):
            got = cli.act(obs[i])
            np.testing.assert_allclose(got, local(obs[i])[0], atol=1e-5)
    finally:
        srv.close()


def test_client_timeout_is_typed():
    gate = threading.Event()

    def blocking_apply(batch):
        gate.wait(10.0)
        return _echo(batch)

    b = Batcher(blocking_apply, max_batch=1, max_latency_s=0.0, max_queue=8)
    b.start()
    srv = InferenceServer(LAYOUT, 1.0, max_batch=1, max_latency_s=0.0,
                          max_queue=8)
    srv.batcher.close()  # replace the real batcher with the blocking one
    srv.batcher = b
    try:
        cli = srv.client(timeout_s=0.1)
        with pytest.raises(ServeTimeout):
            cli.act(_obs(1)[0])
    finally:
        gate.set()
        b.close()


def test_param_refresh_from_broadcast_buffer_seqlock():
    """The server refreshes from the pool's shared buffer: an EVEN version
    installs, an ODD (write in progress) version is skipped."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    shared = ctx.Array("f", layout_size(LAYOUT), lock=False)
    version = ctx.Value("l", 0)
    flat = _flat()
    np.frombuffer(shared, dtype=np.float32)[:] = flat
    version.value = 2
    srv = InferenceServer(
        LAYOUT, 1.0, max_batch=1, max_latency_s=0.0, max_queue=8,
        param_source=(shared, version),
    ).start()
    try:
        cli = srv.client(timeout_s=5.0)
        local = NumpyPolicy(LAYOUT, 1.0)
        local.load_flat(flat)
        obs = _obs(1)[0]
        assert np.array_equal(cli.act(obs), local(obs)[0])
        # Mid-write version (odd): the server must KEEP the old params.
        np.frombuffer(shared, dtype=np.float32)[:] = 0.0
        version.value = 3
        assert np.array_equal(cli.act(obs), local(obs)[0])
        # Write complete: the new params install.
        version.value = 4
        assert np.array_equal(cli.act(obs), np.zeros(ACT, np.float32))
        assert srv.snapshot()["serve_param_refreshes"] >= 2
    finally:
        srv.close()


def test_serve_rides_transfer_scheduler_serve_class():
    from distributed_ddpg_tpu.transfer import TransferScheduler

    sched = TransferScheduler().start()
    srv = InferenceServer(
        LAYOUT, 1.0, max_batch=2, max_latency_s=0.01, max_queue=64,
        scheduler=sched,
    ).start()
    try:
        srv.refresh(_flat())
        cli = srv.client(timeout_s=5.0)
        for row in _obs(4):
            cli.act(row)
        snap = sched.snapshot()
        assert snap["transfer_serve_items"] >= 2
        assert snap["transfer_serve_bytes"] > 0
        # serve counts into the scheduled-dispatch total like any class.
        assert snap["transfer_dispatches"] >= snap["transfer_serve_items"]
    finally:
        srv.close()
        sched.close()


def test_serve_dispatch_fails_typed_when_scheduler_dead():
    """A dead transfer scheduler must surface as a typed dispatch error
    (clients fall back), never a hang."""
    from distributed_ddpg_tpu.transfer import TransferScheduler

    sched = TransferScheduler().start()
    sched.close()
    srv = InferenceServer(
        LAYOUT, 1.0, max_batch=1, max_latency_s=0.0, max_queue=8,
        scheduler=sched,
    ).start()
    try:
        srv.refresh(_flat())
        cli = srv.client(timeout_s=5.0)
        with pytest.raises(ServeDispatchError):
            cli.act(_obs(1)[0])
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# fault grammar + config validation
# ---------------------------------------------------------------------------


def test_serve_fault_grammar():
    plan = FaultPlan.parse(
        "serve:batcher:stall@2~0.5;serve:dispatch:crash@3", seed=0
    )
    specs = {s.describe() for s in plan.specs}
    assert specs == {"serve:batcher:stall@2", "serve:dispatch:crash@3"}
    site = plan.site("serve", "dispatch")
    site.tick()
    site.tick()
    from distributed_ddpg_tpu.faults import InjectedFault

    with pytest.raises(InjectedFault):
        site.tick()
    with pytest.raises(ValueError):
        FaultPlan.parse("serve:batcher:crash@1")  # crash is dispatch-only
    with pytest.raises(ValueError):
        FaultPlan.parse("serve:unknown:stall@1")


def test_config_validation():
    with pytest.raises(ValueError):
        DDPGConfig(serve_actors=True, backend="native")
    with pytest.raises(ValueError):
        DDPGConfig(
            serve_actors=True, strict_sync=True,
            max_learn_ratio=1.0, max_ingest_ratio=1.0,
        )
    # PR 20: sac + serve_actors is a supported pairing (the SAC serve
    # head, docs/SERVING.md) — it must CONSTRUCT now.
    DDPGConfig(serve_actors=True, sac=True)
    with pytest.raises(ValueError):
        DDPGConfig(serve_max_batch=0)
    with pytest.raises(ValueError):
        DDPGConfig(serve_backend="torch")
    DDPGConfig(serve_actors=True)  # valid default combination


# ---------------------------------------------------------------------------
# tools: serve_bench + runs digest + gate keys
# ---------------------------------------------------------------------------


def test_serve_bench_digest():
    from distributed_ddpg_tpu.tools.serve_bench import run_serve_bench

    r = run_serve_bench(
        clients=2, duration_s=0.4, obs_dim=4, act_dim=2, hidden=(8, 8),
        max_batch=4, max_latency_ms=2.0,
    )
    assert r["serve_requests"] > 0
    assert r["served_rps"] > 0
    assert r["local_act_rps"] > 0
    assert "serve_p95_ms" in r and "serve_queue_depth_p95" in r


def test_runs_summarize_and_compare_render_serve_digest(tmp_path):
    from distributed_ddpg_tpu.tools import runs

    path = tmp_path / "serve.jsonl"
    recs = [
        {"kind": "train", "step": 100, "wall_time": 1.0,
         "serve_requests": 50, "serve_batches": 10, "serve_p95_ms": 4.0,
         "serve_fill_mean": 0.5, "serve_queue_depth_p95": 2.0,
         "serve_client_fallbacks": 0},
        {"kind": "train", "step": 200, "wall_time": 2.0,
         "serve_requests": 120, "serve_batches": 25, "serve_p95_ms": 6.0,
         "serve_fill_mean": 0.6, "serve_queue_depth_p95": 3.0,
         "serve_client_fallbacks": 1},
        {"kind": "final", "step": 200, "wall_time": 2.5,
         "serve_requests": 130, "serve_p95_ms": 5.0},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    digest = runs.summarize_run(str(path))
    assert digest["serve"]["serve_requests"]["last"] == 130
    assert digest["serve"]["serve_p95_ms"]["max"] == 6.0
    text = runs.render_summary(digest)
    assert "inference serving" in text
    assert "serve_p95_ms" in text
    _, rows = runs.compare_runs(str(path), str(path))
    assert any(r[0] == "serve_p95_ms" for r in rows)


def test_gate_serve_keys_skip_and_fail_semantics():
    """The ci_gate serve keys: SKIP against a pre-serve baseline, FAIL a
    latency regression once a serve-carrying bench is the baseline."""
    from distributed_ddpg_tpu.tools.runs import gate_bench

    keys = ("-serve_p95_ms", "-serve_queue_depth_p95")
    ok, lines = gate_bench({"value": 1.0}, {"value": 1.0}, 0.1, keys)
    assert ok and all("SKIP" in ln for ln in lines)
    base = {"serve_p95_ms": 5.0, "serve_queue_depth_p95": 4.0}
    good = {"serve_p95_ms": 5.2, "serve_queue_depth_p95": 4.0}
    bad = {"serve_p95_ms": 9.0, "serve_queue_depth_p95": 4.0}
    assert gate_bench(base, good, 0.1, keys)[0]
    assert not gate_bench(base, bad, 0.1, keys)[0]
    # A candidate that DROPS the metric the baseline had must fail.
    assert not gate_bench(base, {"serve_queue_depth_p95": 4.0}, 0.1, keys)[0]


# ---------------------------------------------------------------------------
# tier-1 integration: served actors train; chaos degrades, never deadlocks
# ---------------------------------------------------------------------------


def _serve_train_config(tmp_path, **kw):
    base = dict(
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        num_actors=2,
        total_env_steps=1_200,
        replay_min_size=256,
        replay_capacity=20_000,
        eval_every=0,
        max_learn_ratio=1.0,
        max_ingest_ratio=1.0,
        log_path=str(tmp_path / "serve.jsonl"),
        serve_actors=True,
        serve_max_batch=8,
        serve_max_latency_ms=1.0,
    )
    base.update(kw)
    return DDPGConfig(**base)


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            if line.strip().startswith("{"):
                out.append(json.loads(line))
    return out


def test_train_smoke_served_actors(tmp_path):
    """Served-actor training end to end: the run completes its budget on
    served actions, serve_* (incl. the p50/p95 tails) ride the records,
    and the serve traffic is accounted under the transfer scheduler's
    serve class."""
    cfg = _serve_train_config(tmp_path)
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    assert out["serve_requests"] > 0, f"nothing was served: {out}"
    assert out["serve_batches"] > 0
    assert out["serve_overloads"] == 0
    assert out["serve_errors"] == 0
    # The summary shares the final record's ONE snapshot — the latency
    # tails must be real, not zeroed by a double snapshot.
    assert out["serve_p95_ms"] > 0.0
    recs = _records(cfg.log_path)
    finals = [r for r in recs if r.get("kind") == "final"]
    assert finals
    f = finals[-1]
    for key in (
        "serve_requests", "serve_batches", "serve_fill_mean",
        "serve_p50_ms", "serve_p95_ms", "serve_max_ms",
        "serve_queue_depth_p95", "serve_client_fallbacks",
        "transfer_serve_items",
    ):
        assert key in f, f"{key} missing from the final record"
    assert f["serve_requests"] > 0
    assert f["transfer_serve_items"] > 0
    assert f["serve_p95_ms"] > 0.0
    # Load-tolerant healthy-run assertion (the strict == 0 form red-ed
    # repeatedly under contended-box load — the known pre-existing flake
    # per the PR-9/11/12 notes): on a loaded box a slow batcher dispatch
    # can push a worker past serve_timeout_s once or twice, and that
    # bounded degrade-and-recover IS the designed behavior, not a
    # failure. What a healthy run must still show: the budget completed
    # on served actions (asserted above), nothing deadlocked, nothing
    # was shed, and fallbacks stayed bounded — an unbounded count would
    # mean the fleet abandoned the server entirely. The chaos test below
    # pins the deliberate degrade path with its own >= 1 assertion.
    assert f["serve_overloads"] == 0
    assert f["serve_errors"] == 0
    assert f["serve_client_fallbacks"] <= 8, (
        f"serve fallbacks not bounded under load: {f['serve_client_fallbacks']}"
    )


# Re-tiered to slow (ISSUE 15 tier-1 budget): 87s fault-injected train soak; test_train_smoke_served_actors keeps
# the tier-1 serve train smoke
@pytest.mark.slow
def test_chaos_served_actors_degrade_to_local_act(tmp_path):
    """The serve chaos contract (docs/SERVING.md): a dispatch crash AND a
    batcher stall both push served workers onto their local act() path —
    the run keeps training to its full budget, nothing deadlocks, and the
    fallback counter proves the degradation happened."""
    cfg = _serve_train_config(
        tmp_path,
        serve_timeout_s=0.3,
        serve_fallback_s=0.5,
        faults="serve:dispatch:crash@3;serve:batcher:stall@30~1.5",
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0, f"run stalled under serve chaos: {out}"
    assert out["serve_errors"] >= 1, (
        f"injected dispatch crash never fired: {out}"
    )
    assert out["serve_client_fallbacks"] >= 1, (
        f"no worker degraded to local act(): {out}"
    )
    # Degraded, not dead: serving continued after both faults.
    assert out["serve_requests"] > 0
