"""Mixed precision (compute_dtype='bfloat16'): bf16 MXU matmuls with f32
accumulation and f32 master params. Checks the bf16 step stays close to the
f32 step, keeps f32 state dtypes, and is properly gated."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import init_train_state, make_learner_step
from distributed_ddpg_tpu.types import Batch

OBS, ACT, B = 6, 2, 32


def _batch(rng):
    return Batch(
        obs=jnp.asarray(rng.standard_normal((B, OBS)), jnp.float32),
        action=jnp.asarray(rng.uniform(-1, 1, (B, ACT)), jnp.float32),
        reward=jnp.asarray(rng.standard_normal(B), jnp.float32),
        discount=jnp.full((B,), 0.99, jnp.float32),
        next_obs=jnp.asarray(rng.standard_normal((B, OBS)), jnp.float32),
        weight=jnp.ones((B,), jnp.float32),
    )


@pytest.mark.parametrize("distributional", [False, True])
def test_bf16_step_tracks_f32(distributional):
    cfg32 = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        distributional=distributional,
    )
    cfg16 = cfg32.replace(compute_dtype="bfloat16")
    state = init_train_state(cfg32, OBS, ACT, seed=0)
    batch = _batch(np.random.default_rng(0))

    out32 = make_learner_step(cfg32, 1.0)(state, batch)
    out16 = make_learner_step(cfg16, 1.0)(state, batch)

    # Master params stay f32 after a bf16 step.
    for leaf in jax.tree.leaves(out16.state.actor_params):
        assert leaf.dtype == jnp.float32
    # One step in bf16 stays close to f32 (matmul rounding only; f32
    # accumulation keeps the error at the bf16 input-rounding level).
    c32 = float(out32.metrics["critic_loss"])
    c16 = float(out16.metrics["critic_loss"])
    assert np.isfinite(c16)
    np.testing.assert_allclose(c16, c32, rtol=0.05, atol=5e-3)
    a32 = np.asarray(
        jax.tree.leaves(out32.state.actor_params)[0], dtype=np.float32
    )
    a16 = np.asarray(
        jax.tree.leaves(out16.state.actor_params)[0], dtype=np.float32
    )
    np.testing.assert_allclose(a16, a32, rtol=0.1, atol=2e-3)


def test_bf16_gates():
    with pytest.raises(ValueError, match="compute_dtype"):
        DDPGConfig(compute_dtype="fp16")
    with pytest.raises(ValueError, match="bit-comparability"):
        DDPGConfig(compute_dtype="bfloat16", backend="native")
    # The megakernel admits bf16 since round 4 (bf16 dots, f32 accumulate);
    # parity is pinned in tests/test_fused_chunk.py::test_fused_chunk_bf16_*.
    from distributed_ddpg_tpu.ops import fused_chunk

    assert fused_chunk.supported(DDPGConfig(compute_dtype="bfloat16"))
