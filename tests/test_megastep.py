"""Fused training megastep (parallel/megastep.py; docs/FUSED_BEAT.md):

- **bit-identity at the fused/unfused seam**: a fused beat sequence must
  equal the separate-dispatch sequence (learner chunk -> param swap ->
  rollout -> insert) BIT-FOR-BIT for fixed seeds — uniform + PER,
  replicated + sharded placement. This is the oracle that lets the fused
  path ship without its own quality story, exactly how the coalesced
  ingest and sharded placement anchored to their serial/replicated
  references.
- **guardrails inside the fused program**: the numeric:grad:nan@K chaos
  vector fires inside the beat, the health word reports it, and the
  update is dropped on device — guardrails=True keeps the fast path.
- **config validation**: the fused_beat rejection matrix.
- **train integration**: a fused (and guarded-fused) train_jax run
  completes its budget with fused_* observability in the records.
"""

import json

import numpy as np
import pytest

import jax

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.train import train_jax

OBS, ACT = 3, 1


def _cfg(**kw):
    base = dict(
        env_id="Pendulum-v1",
        actor_backend="device",
        num_actors=0,
        device_actor_envs=8,
        device_actor_chunk=2,
        learner_chunk=2,
        batch_size=8,
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        replay_capacity=256,
        fused_chunk="off",
        seed=3,
    )
    base.update(kw)
    return DDPGConfig(**base)


def _setup(config, sharded):
    """One (learner, pool, replay) stack with the ring pre-warmed by four
    standalone rollout chunks — both arms of the A/B build through here,
    so their pre-beat state is identical."""
    from distributed_ddpg_tpu.actors.device_pool import DeviceActorPool
    from distributed_ddpg_tpu.parallel import mesh as mesh_lib
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import (
        DevicePrioritizedReplay,
        DeviceReplay,
    )

    n = 2 if sharded else 1
    placement = "sharded" if sharded else "replicated"
    mesh = mesh_lib.make_mesh(n, 1, devices=jax.devices("cpu")[:n])
    pool = DeviceActorPool(config, mesh=mesh)
    learner = ShardedLearner(
        config, pool.obs_dim, pool.act_dim, pool.action_scale,
        action_offset=pool.action_offset, mesh=mesh, chunk_size=2,
        replay_sharding=placement,
    )
    cls = DevicePrioritizedReplay if config.prioritized else DeviceReplay
    replay = cls(
        config.replay_capacity, pool.obs_dim, pool.act_dim, mesh=mesh,
        block_size=16, async_ship=False, replay_sharding=placement,
    )
    pool.set_params(learner.state.actor_params)
    for _ in range(4):
        pool.run_chunk(replay)
    return learner, pool, replay


def _leaves_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
        )
        for x, y in zip(la, lb)
    )


@pytest.mark.parametrize("per", [False, True], ids=["uniform", "per"])
@pytest.mark.parametrize("sharded", [False, True],
                         ids=["replicated", "sharded"])
def test_fused_beat_bit_identical_to_separate_dispatches(per, sharded):
    """Three fused beats == three (chunk -> swap -> rollout -> insert)
    dispatch sequences: storage/ptr/size, the full TrainState, the
    sampling key, the rollout carry, and (PER) the priority vector are
    all bit-identical."""
    from distributed_ddpg_tpu.parallel.megastep import FusedMegastep

    config = _cfg(prioritized=per, fused_beat="on")
    lf, pf, rf = _setup(config, sharded)
    ms = FusedMegastep(config, lf, pf, rf)
    for _ in range(3):
        ms.run_beat(beta=0.5 if per else None)

    lu, pu, ru = _setup(config, sharded)
    for _ in range(3):
        if per:
            lu.run_sample_chunk_per(ru, 0.5)
        else:
            lu.run_sample_chunk(ru)
        pu.set_params(lu.state.actor_params)
        pu.run_chunk(ru)

    assert _leaves_equal(rf.storage, ru.storage)
    assert int(jax.device_get(rf.ptr)) == int(jax.device_get(ru.ptr))
    assert int(jax.device_get(rf.size)) == int(jax.device_get(ru.size))
    assert _leaves_equal(lf.state, lu.state)
    assert _leaves_equal(lf._key, lu._key)
    assert _leaves_equal(pf._carry, pu._carry)
    assert pf.steps_done == pu.steps_done
    if per:
        assert _leaves_equal(rf.priorities, ru.priorities)
        assert _leaves_equal(rf.max_priority, ru.max_priority)


def test_guarded_fused_beat_matches_guarded_dispatches():
    """The guarded composition is the same seam: guarded fused beats ==
    guarded separate dispatches, health word included."""
    from distributed_ddpg_tpu.parallel.megastep import FusedMegastep

    config = _cfg(fused_beat="on", guardrails=True)
    lf, pf, rf = _setup(config, sharded=False)
    ms = FusedMegastep(config, lf, pf, rf)
    for _ in range(3):
        ms.run_beat()

    lu, pu, ru = _setup(config, sharded=False)
    for _ in range(3):
        lu.run_sample_chunk(ru)
        pu.set_params(lu.state.actor_params)
        pu.run_chunk(ru)

    assert _leaves_equal(rf.storage, ru.storage)
    assert _leaves_equal(lf.state, lu.state)
    assert lf.poll_health() == lu.poll_health()


def test_guardrail_quarantine_fires_inside_fused_beat():
    """numeric:grad:nan@3 poisons the third guarded learner step INSIDE
    the fused program: the health word reports the skip, and the dropped
    update leaves params equal to the previous step's (the tree-select
    quarantine ran on device)."""
    from distributed_ddpg_tpu.parallel.megastep import FusedMegastep

    config = _cfg(
        fused_beat="on", guardrails=True, faults="numeric:grad:nan@3",
    )
    learner, pool, replay = _setup(config, sharded=False)
    ms = FusedMegastep(config, learner, pool, replay)
    ms.run_beat()  # steps 1-2: clean
    h = learner.poll_health()
    assert h["total"] == 2 and h["nonfinite"] == 0
    ms.run_beat()  # steps 3-4: step 3 poisoned
    h = learner.poll_health()
    assert h["total"] == 4
    assert h["nonfinite"] == 1
    assert h["skipped"] == 1
    # The probe kept every param leaf finite despite the NaN batch.
    for leaf in jax.tree.leaves(learner.state.actor_params):
        assert np.isfinite(np.asarray(jax.device_get(leaf))).all()


def test_fused_beat_rebuilds_after_learner_program_rebuild():
    """set_lr_scale (the rollback LR backoff) rebuilds the learner's
    chunk bodies; the next run_beat must recompose against them instead
    of dispatching the stale closures."""
    from distributed_ddpg_tpu.parallel.megastep import FusedMegastep

    config = _cfg(fused_beat="on")
    learner, pool, replay = _setup(config, sharded=False)
    ms = FusedMegastep(config, learner, pool, replay)
    ms.run_beat()
    v0 = ms._learner_version
    learner.set_lr_scale(0.5)
    ms.run_beat()
    assert ms._learner_version == learner.programs_version != v0


def test_fused_beat_config_validation():
    """The fused_beat rejection matrix (config.py; docs/FUSED_BEAT.md)."""
    with pytest.raises(ValueError, match="fused_beat must be"):
        _cfg(fused_beat="maybe")
    # Host actors have no compilable rollout leg.
    with pytest.raises(ValueError, match="actor_backend='device'"):
        DDPGConfig(fused_beat="on", actor_backend="host", num_actors=1)
    # The Pallas megakernel has no slot inside a larger program.
    with pytest.raises(ValueError, match="megakernel"):
        _cfg(fused_beat="on", fused_chunk="on")
    # The ratio gates need independently dispatchable phases.
    with pytest.raises(ValueError, match="ratio"):
        _cfg(fused_beat="on", max_ingest_ratio=1.0, max_learn_ratio=1.0)
    # n_step > 1 / serve_actors fail through the device-actor validation
    # the fused beat builds on.
    with pytest.raises(ValueError, match="n_step"):
        _cfg(fused_beat="on", n_step=3)
    with pytest.raises(ValueError, match="serve"):
        _cfg(fused_beat="on", serve_actors=True)
    # The native backend has no device programs to fuse.
    with pytest.raises(ValueError, match="jax_tpu|native"):
        DDPGConfig(fused_beat="on", backend="native")
    # 'auto' and 'off' always parse.
    assert _cfg(fused_beat="auto").fused_beat == "auto"
    assert _cfg(fused_beat="off").fused_beat == "off"


def _train_cfg(tmp_path, **kw):
    base = dict(
        env_id="Pendulum-v1",
        actor_backend="device",
        num_actors=0,
        device_actor_envs=8,
        device_actor_chunk=2,
        learner_chunk=2,
        batch_size=16,
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        replay_capacity=2048,
        replay_min_size=64,
        total_env_steps=400,
        eval_every=0,
        eval_episodes=1,
        fused_chunk="off",
        fused_beat="on",
        log_path=str(tmp_path / "run.jsonl"),
    )
    base.update(kw)
    return DDPGConfig(**base)


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_train_fused_beat_with_guardrails(tmp_path):
    """End-to-end: guardrails=True no longer forces the unfused path —
    the fused megastep carries the guarded steady-state loop to its
    budget, with fused_* observability in the final record."""
    cfg = _train_cfg(tmp_path, guardrails=True)
    out = train_jax(cfg)
    assert out["fused_beat_active"] is True
    assert out["learner_steps"] > 0
    assert out["guardrail_skipped_updates"] == 0  # healthy run
    finals = [r for r in _records(cfg.log_path) if r["kind"] == "final"]
    assert finals
    for key in ("fused_beats", "fused_steps_per_s", "fused_rows_per_s",
                "fused_beat_ms", "fused_beat_p95"):
        assert key in finals[-1], f"{key} missing from the final record"
    assert out["devactor_env_steps"] > 0


def test_train_fused_vs_unfused_identical_end_state(tmp_path):
    """TRAIN-LEVEL parity (the seam the unit parity above cannot see —
    loop accounting, cadences, warmup handoff): the same config run with
    fused_beat='on' and 'off' must finish with the same learner-step
    count, the same env-step production, and a bit-identical param
    checksum. Pins the whole dispatch-gating wiring — e.g. a fused beat
    that ALSO fell through to the unfused after_chunk would double the
    step accounting and extra-roll the envs, and only this test sees it."""
    outs = {}
    for mode in ("on", "off"):
        cfg = _train_cfg(tmp_path, fused_beat=mode,
                         log_path=str(tmp_path / f"{mode}.jsonl"))
        outs[mode] = train_jax(cfg)
    assert outs["on"]["fused_beat_active"] is True
    assert outs["off"]["fused_beat_active"] is False
    assert outs["on"]["learner_steps"] == outs["off"]["learner_steps"]
    assert (
        outs["on"]["devactor_env_steps"] == outs["off"]["devactor_env_steps"]
    )
    assert outs["on"]["param_checksum"] == outs["off"]["param_checksum"]


def test_fused_bench_phase_and_gate_key_registered():
    """The BENCH_FUSED wiring exists end to end: bench.py registers the
    fused phase, and scripts/ci_gate.sh's default keys pin the
    higher-is-better fused_steps_per_s (SKIP-vs-old-baselines semantics
    come free from the shared gate machinery)."""
    import pathlib

    import bench

    assert "fused" in bench._PHASES
    gate = pathlib.Path(__file__).parent.parent / "scripts" / "ci_gate.sh"
    text = gate.read_text(encoding="utf-8")
    assert ",fused_steps_per_s" in text  # no '-' prefix: higher is better


def test_train_fused_beat_off_keeps_dispatch_per_phase(tmp_path):
    """fused_beat='off' pins the dispatch-per-phase loop; the summary
    reports the gating fact and no fused_* fields ride the records."""
    cfg = _train_cfg(tmp_path, fused_beat="off")
    out = train_jax(cfg)
    assert out["fused_beat_active"] is False
    assert out["learner_steps"] > 0
    finals = [r for r in _records(cfg.log_path) if r["kind"] == "final"]
    assert "fused_beats" not in finals[-1]
