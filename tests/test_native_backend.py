"""Backend equivalence (SURVEY.md §4 'Backend equivalence'; BASELINE.json:5):
the numpy `native` path and the jitted JAX path must produce
tolerance-bounded identical losses, TD errors, and parameter trajectories
from the same seed and the same replay contents."""

import jax
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import init_train_state, jit_learner_step
from distributed_ddpg_tpu.native_backend import NativeLearner
from distributed_ddpg_tpu.types import batch_from_numpy

OBS, ACT, B = 6, 3, 32


def _np_batch(rng, weighted=False):
    return {
        "obs": rng.standard_normal((B, OBS)).astype(np.float32),
        "action": rng.uniform(-1, 1, (B, ACT)).astype(np.float32),
        "reward": rng.standard_normal(B).astype(np.float32),
        "discount": np.full(B, 0.99, np.float32),
        "next_obs": rng.standard_normal((B, OBS)).astype(np.float32),
        "weight": (
            rng.uniform(0.2, 1.0, B).astype(np.float32)
            if weighted
            else np.ones(B, np.float32)
        ),
    }


@pytest.mark.parametrize("l2,weighted,offset", [(0.0, False, 0.0), (0.01, True, 0.5)])
def test_native_matches_jax_trajectory(l2, weighted, offset):
    cfg = DDPGConfig(
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        batch_size=B,
        critic_l2=l2,
        tau=5e-3,
    )
    state = init_train_state(cfg, OBS, ACT, seed=0)
    native = NativeLearner(cfg, state, action_scale=1.5, action_offset=offset)
    jstep = jit_learner_step(cfg, 1.5, donate=False, action_offset=offset)

    rng = np.random.default_rng(0)
    for i in range(10):
        nb = _np_batch(rng, weighted)
        out = jstep(state, batch_from_numpy(nb))
        state = out.state
        nm = native.step(nb)
        np.testing.assert_allclose(
            nm["critic_loss"], float(out.metrics["critic_loss"]), rtol=2e-4,
            err_msg=f"critic loss diverged at step {i}",
        )
        np.testing.assert_allclose(
            nm["actor_loss"], float(out.metrics["actor_loss"]), rtol=2e-4, atol=1e-5,
            err_msg=f"actor loss diverged at step {i}",
        )
        np.testing.assert_allclose(
            nm["td_errors"], np.asarray(out.td_errors), rtol=1e-3, atol=1e-4
        )
    assert native.params_close_to(state), "param trajectories diverged beyond tolerance"
    assert native.step_count == int(state.step) == 10


def test_native_act_matches_jax():
    from distributed_ddpg_tpu.learner import make_act_fn

    cfg = DDPGConfig(actor_hidden=(32, 32), critic_hidden=(32, 32))
    state = init_train_state(cfg, OBS, ACT, seed=1)
    native = NativeLearner(cfg, state, action_scale=2.0)
    act = make_act_fn(cfg, 2.0)
    obs = np.random.default_rng(2).standard_normal((5, OBS)).astype(np.float32)
    np.testing.assert_allclose(
        native.act(obs), np.asarray(act(state.actor_params, obs)), rtol=1e-5, atol=1e-6
    )


def test_native_rejects_distributional():
    cfg = DDPGConfig(distributional=True)
    state = init_train_state(cfg, OBS, ACT, seed=0)
    with pytest.raises(NotImplementedError):
        NativeLearner(cfg, state, action_scale=1.0)
