"""Real multi-process DCN-path test (SURVEY.md §4: 'Multi-host path tested
with jax.distributed.initialize across local subprocesses').

Launches 2 subprocesses, each with 2 fake CPU devices, joined into one
jax.distributed cluster; the learner's (data=4) mesh then spans the process
boundary, so its gradient AllReduce runs over the cross-process collective
transport (Gloo on CPU; DCN on a real multi-host pod — the topology of
BASELINE.md's v5e-16 rung). Asserts:

- both processes complete a full ShardedLearner chunk (global SPMD works),
- they report bit-identical loss/params (SPMD consistency), and
- the result matches a single-process 4-device run of the same chunk
  (cross-process AllReduce computes the same reduction).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

CHILD = Path(__file__).parent / "multihost_child.py"
REPO = str(CHILD.parent.parent)
ENV = {**os.environ, "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_pair(mode: str, timeout: int = 240):
    """Launch 2 jax.distributed CPU processes in the given child mode;
    return the sorted PARITY payloads (one per process)."""
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(CHILD), str(pid), "2", str(port), mode],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
            env=ENV,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outs.append(out)
        assert p.returncode == 0, f"child failed:\n{out}"
    parity = sorted(
        line.split()[1:] for o in outs for line in o.splitlines()
        if line.startswith("PARITY")
    )
    assert len(parity) == 2, f"expected 2 parity lines, got {parity}\n{outs}"
    return parity


@pytest.mark.slow
def test_two_process_learner_parity():
    (_, loss0, sum0), (_, loss1, sum1) = _run_pair("chunk")
    assert loss0 == loss1, f"cross-process loss mismatch: {loss0} vs {loss1}"
    assert sum0 == sum1, f"cross-process param mismatch: {sum0} vs {sum1}"

    # Single-process oracle: same chunk on a 4-device single-process mesh.
    oracle = subprocess.run(
        [
            sys.executable,
            "-c",
            "import os; os.environ['XLA_FLAGS']='--xla_force_host_platform_device_count=4';"
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import numpy as np;"
            "from distributed_ddpg_tpu.config import DDPGConfig;"
            "from distributed_ddpg_tpu.parallel.learner import ShardedLearner;"
            "from tests.multihost_child import run_parity_chunk;"
            "run_parity_chunk(ShardedLearner, DDPGConfig, np, tag='single')",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
        env=ENV,
    )
    assert oracle.returncode == 0, oracle.stdout + oracle.stderr
    single = [
        line.split()[1:]
        for line in oracle.stdout.splitlines()
        if line.startswith("PARITY")
    ][0]
    _, loss_s, sum_s = single
    assert abs(float(loss0) - float(loss_s)) < 1e-5, (loss0, loss_s)
    assert abs(float(sum0) - float(sum_s)) < 1e-3, (sum0, sum_s)


@pytest.mark.slow
def test_two_process_device_replay_ingest():
    """Lockstep DeviceReplay ingest (sync_ship): each process contributes
    different rows; the replicated storage must come out identical on both
    replicas and contain both processes' rows exactly once (the round-1
    SPMD violation — per-process-local inserts — would fail the in-child
    checksum assertions and diverge the sampled-chunk loss)."""
    (_, loss0, store0), (_, loss1, store1) = _run_pair("replay")
    assert loss0 == loss1, f"sampled-chunk loss mismatch: {loss0} vs {loss1}"
    assert store0 == store1, f"storage checksum mismatch: {store0} vs {store1}"


@pytest.mark.slow
def test_two_process_coalesced_ingest_parity():
    """Coalesced lockstep ingest (docs/INGEST.md): the super-block
    all-gather insert's on-device transpose must land rows at EXACTLY the
    positions the seed's serial one-block-per-collective sequence does —
    each child compares a serial and a coalesced replay bit-for-bit in the
    same cluster, and the parent checks the replicas agree."""
    (_, ok0, ck0), (_, ok1, ck1) = _run_pair("coalesce")
    assert ok0 == "1", "coalesced storage != serial storage on proc0"
    assert ok1 == "1", "coalesced storage != serial storage on proc1"
    assert ck0 == ck1, f"replica checksum fork: {ck0} vs {ck1}"


@pytest.mark.slow
def test_two_process_background_sync_ship_parity():
    """Background lockstep sync_ship (docs/TRANSFER.md): beats issued on
    the transfer scheduler's ordered lane (counts snapshot at token time,
    learner-side waits only at the gates) must land storage bit-identical
    to the synchronous learner-thread collectives — and the replicas must
    agree. This is the acceptance check for moving the DCN ingest
    collective off the learner thread without breaking lockstep."""
    (_, ok0, ck0), (_, ok1, ck1) = _run_pair("bgsync")
    assert ok0 == "1", "background storage != synchronous storage on proc0"
    assert ok1 == "1", "background storage != synchronous storage on proc1"
    assert ck0 == ck1, f"replica checksum fork: {ck0} vs {ck1}"


@pytest.mark.slow
def test_two_process_fused_mesh_parity():
    """Megakernel x mesh (fused_mesh, K-step local SGD) on a {data:4} mesh
    spanning 2 processes: the chunk-boundary param pmean is a CROSS-PROCESS
    collective (Gloo here, DCN on a pod). Both processes must report
    identical chunk losses and end-state checksums — the multi-host
    analogue of the single-process fused-mesh parity suite, closing the
    gap between 'fused_mesh works on one host' and the BASELINE.json:11
    multi-host topology."""
    (_, losses0, ck0), (_, losses1, ck1) = _run_pair("fused")
    assert losses0 == losses1, f"fused chunk loss fork: {losses0} vs {losses1}"
    assert ck0 == ck1, f"param checksum fork: {ck0} vs {ck1}"


@pytest.mark.slow
def test_two_process_full_train_jax():
    """The FULL train_jax loop (actor pool -> lockstep device-replay ingest
    -> fused-sampling sharded learner -> globally-summed env-step budget)
    across 2 jax.distributed processes. Both processes must run the same
    number of learner steps (lockstep) and end with bit-identical actor
    params (SPMD consistency)."""
    (_, steps0, ck0), (_, steps1, ck1) = _run_pair("train", timeout=360)
    assert steps0 == steps1, f"learner step mismatch: {steps0} vs {steps1}"
    assert ck0 == ck1, f"param checksum mismatch: {ck0} vs {ck1}"
