"""SAC (arXiv 1801.01290/1812.05905; third beyond-parity family): stochastic
tanh-Gaussian actor with reparameterized sampling, twin critics (TD3's
stacked-leading-axis machinery), entropy-regularized Bellman targets, and a
learned temperature driving policy entropy toward -act_dim."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.learner import (
    init_train_state,
    jit_learner_step,
    make_act_fn,
)
from distributed_ddpg_tpu.ops import losses
from distributed_ddpg_tpu.types import Batch

OBS, ACT, B = 5, 2, 16


def _cfg(**kw):
    base = dict(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        sac=True, seed=0,
    )
    base.update(kw)
    return DDPGConfig(**base)


def _batch(rng):
    return Batch(
        obs=jnp.asarray(rng.standard_normal((B, OBS)), jnp.float32),
        action=jnp.asarray(rng.uniform(-1, 1, (B, ACT)), jnp.float32),
        reward=jnp.asarray(rng.standard_normal(B), jnp.float32),
        discount=jnp.full((B,), 0.99, jnp.float32),
        next_obs=jnp.asarray(rng.standard_normal((B, OBS)), jnp.float32),
        weight=jnp.ones((B,), jnp.float32),
    )


def test_sac_init_shapes():
    s = init_train_state(_cfg(), OBS, ACT, seed=0)
    # Gaussian head: final layer emits [mean | log_std] (2 * act_dim).
    assert s.actor_params[-1]["w"].shape[-1] == 2 * ACT
    # Twin critics: stacked leading axis, independent inits.
    for layer in s.critic_params:
        assert layer["w"].shape[0] == 2 and layer["w"].ndim == 3
        assert not np.allclose(layer["w"][0], layer["w"][1])
    # Temperature scalar + its own Adam state.
    assert np.isclose(float(s.log_alpha), np.log(0.2))
    assert int(s.alpha_opt.count) == 0
    # Non-SAC states keep None (empty pytree node) there.
    s2 = init_train_state(
        DDPGConfig(actor_hidden=(32,), critic_hidden=(32, 32)), OBS, ACT, seed=0
    )
    assert s2.log_alpha is None and s2.alpha_opt is None


def test_sac_log_prob_matches_torch_oracle():
    """sac_sample's log-density must equal an independent implementation:
    torch.distributions Normal -> tanh -> affine(scale, offset) via
    TransformedDistribution, evaluated at the same sampled actions."""
    torch = pytest.importorskip("torch")

    rng = np.random.default_rng(0)
    mean = rng.standard_normal((B, ACT)).astype(np.float32)
    log_std = rng.uniform(-2.0, 0.5, (B, ACT)).astype(np.float32)
    scale, offset = 1.7, 0.3
    action, lp = losses.sac_sample(
        jnp.asarray(mean), jnp.asarray(log_std), jax.random.PRNGKey(1),
        scale, offset,
    )
    dist = torch.distributions.TransformedDistribution(
        torch.distributions.Normal(
            torch.tensor(mean), torch.tensor(np.exp(log_std))
        ),
        [
            torch.distributions.transforms.TanhTransform(),
            torch.distributions.transforms.AffineTransform(offset, scale),
        ],
    )
    # Independent=sum over action dims.
    dist = torch.distributions.Independent(dist, 1)
    # Clip fractionally inside the box: atanh((a-offset)/scale) must stay
    # finite in the torch oracle (our jax path never inverts).
    a = np.clip(np.asarray(action), offset - scale + 1e-5, offset + scale - 1e-5)
    lp_torch = dist.log_prob(torch.tensor(a)).numpy()
    np.testing.assert_allclose(np.asarray(lp), lp_torch, rtol=1e-3, atol=1e-3)


def test_sac_entropy_target_in_env_units():
    """The -log(scale) Jacobian term: scaling the action box must shift
    log-probs by -sum(log scale) exactly (density lives in env units)."""
    rng = np.random.default_rng(2)
    mean = jnp.asarray(rng.standard_normal((B, ACT)), jnp.float32)
    log_std = jnp.asarray(rng.uniform(-1, 0, (B, ACT)), jnp.float32)
    k = jax.random.PRNGKey(3)
    _, lp1 = losses.sac_sample(mean, log_std, k, 1.0)
    _, lp4 = losses.sac_sample(mean, log_std, k, 4.0)
    # Exact up to the _TANH_EPS regularizer inside log(scale*(1-t^2)+eps).
    np.testing.assert_allclose(
        np.asarray(lp4), np.asarray(lp1) - ACT * np.log(4.0), atol=1e-4
    )


def test_sac_min_over_ensemble_target():
    """Bias target-critic member 1 far above member 0: the entropy-
    regularized target must track member 0 (the min)."""
    cfg = _cfg()
    s = init_train_state(cfg, OBS, ACT, seed=0)
    biased = list(dict(l) for l in s.critic_params)
    last = dict(biased[-1])
    last["b"] = jnp.asarray(s.critic_params[-1]["b"]).at[1].add(100.0)
    biased[-1] = last
    target_critic = tuple(biased)

    batch = _batch(np.random.default_rng(0))
    key = jax.random.PRNGKey(0)
    alpha = 0.2
    _, td = losses.sac_critic_loss(
        s.critic_params, s.actor_params, target_critic, batch,
        1.0, key, alpha, cfg.sac_log_std_min, cfg.sac_log_std_max,
    )
    from distributed_ddpg_tpu.models.mlp import (
        actor_gaussian_apply,
        critic_apply,
    )

    mean, log_std = actor_gaussian_apply(
        s.actor_params, batch.next_obs, cfg.sac_log_std_min, cfg.sac_log_std_max
    )
    na, nlp = losses.sac_sample(mean, log_std, key, 1.0)
    q0 = critic_apply(
        jax.tree.map(lambda x: x[0], target_critic), batch.next_obs, na, 1
    )
    y = batch.reward + batch.discount * (q0 - alpha * nlp)
    q_on = jnp.stack([
        critic_apply(
            jax.tree.map(lambda x: x[i], s.critic_params),
            batch.obs, batch.action, 1,
        )
        for i in (0, 1)
    ])
    expect_td = y[None] - q_on
    np.testing.assert_allclose(
        np.asarray(td), np.asarray(expect_td.mean(0)), rtol=1e-5, atol=1e-6
    )


def test_sac_alpha_autotune_direction_and_determinism():
    """One step must move log_alpha opposite the sign of
    (E[log pi] + target_H) — the exact gradient of the linear temperature
    objective — and the fold_in(seed, step) stream must make the step
    replayable bit-for-bit."""
    cfg = _cfg()
    s = init_train_state(cfg, OBS, ACT, seed=0)
    batch = _batch(np.random.default_rng(1))
    step = jit_learner_step(cfg, 1.0, donate=False)

    # Recompute the actor aux exactly as the step will: same folded key.
    key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed ^ 0x5AC0), s.step)
    _, k_cur = jax.random.split(key)
    _, mean_lp = losses.sac_actor_loss(
        s.actor_params, s.critic_params, batch, 1.0, k_cur,
        float(jnp.exp(s.log_alpha)), cfg.sac_log_std_min, cfg.sac_log_std_max,
    )
    tgt_h = -float(ACT)
    out1 = step(s, batch)
    out2 = step(s, batch)
    np.testing.assert_array_equal(
        np.asarray(out1.td_errors), np.asarray(out2.td_errors)
    )
    delta = float(out1.state.log_alpha) - float(s.log_alpha)
    # grad = -(mean_lp + tgt_h); Adam's first step moves against the grad.
    expected_sign = np.sign(float(mean_lp) + tgt_h)
    assert np.sign(delta) == expected_sign and delta != 0.0
    assert int(out1.state.alpha_opt.count) == 1
    # Fixed-alpha mode: log_alpha frozen, no alpha opt state.
    cfg_fixed = _cfg(sac_autotune=False)
    s_f = init_train_state(cfg_fixed, OBS, ACT, seed=0)
    out_f = jit_learner_step(cfg_fixed, 1.0, donate=False)(s_f, batch)
    assert float(out_f.state.log_alpha) == float(s_f.log_alpha)
    assert out_f.state.alpha_opt is None


def test_sac_numpy_policy_parity_and_sampling():
    """Worker-side numpy Gaussian policy: deterministic mode must match the
    jitted eval act fn bit-close; stochastic mode must actually spread."""
    from distributed_ddpg_tpu.actors.policy import (
        NumpyPolicy,
        actor_head_dim,
        flatten_params,
        param_layout,
    )

    cfg = _cfg()
    s = init_train_state(cfg, OBS, ACT, seed=0)
    layout = param_layout(OBS, actor_head_dim(ACT, True), (32, 32))
    flat = flatten_params(s.actor_params)
    det = NumpyPolicy(layout, 1.3, 0.1, gaussian=True)
    det.load_flat(flat)
    obs = np.random.default_rng(5).standard_normal((4, OBS)).astype(np.float32)
    act_fn = make_act_fn(cfg, 1.3, action_offset=0.1)
    np.testing.assert_allclose(
        det(obs), np.asarray(act_fn(s.actor_params, obs)), rtol=1e-5, atol=1e-5
    )
    sto = NumpyPolicy(layout, 1.3, 0.1, gaussian=True, stochastic=True, seed=7)
    sto.load_flat(flat)
    draws = np.stack([sto(obs[:1])[0] for _ in range(64)])
    assert draws.std(axis=0).min() > 1e-3  # actually stochastic
    assert np.all(np.abs(draws - 0.1) <= 1.3 + 1e-6)  # inside the box


def test_sac_warmup_uniform_resolution_and_acting():
    """warmup_uniform_steps: -1 auto-resolves to replay_min_size for SAC
    (its Gaussian exploration needs broad seed data — without it Pendulum
    sticks at ~-1100; with it, solved) and 0 for OU families; during
    warmup the agent's explore actions are uniform over the box."""
    from distributed_ddpg_tpu.agent import DDPGAgent
    from distributed_ddpg_tpu.envs import make, spec_of

    assert _cfg(replay_min_size=777).resolved_warmup_uniform() == 777
    assert DDPGConfig(replay_min_size=777).resolved_warmup_uniform() == 0
    assert _cfg(warmup_uniform_steps=5).resolved_warmup_uniform() == 5
    assert _cfg(warmup_uniform_steps=0).resolved_warmup_uniform() == 0
    with pytest.raises(ValueError, match="warmup_uniform_steps"):
        DDPGConfig(warmup_uniform_steps=-2)
    # A throttle at/above the pool's heartbeat timeout would respawn-loop
    # every worker (the sleep sits between heartbeat stamps).
    from distributed_ddpg_tpu.actors.pool import ActorPool as _AP
    from distributed_ddpg_tpu.envs import make as _make, spec_of as _spec_of

    _s = _spec_of(_make("Pendulum-v1", seed=0, prefer_builtin=True))
    with pytest.raises(ValueError, match="heartbeat"):
        _AP(DDPGConfig(actor_throttle_s=35.0), _s, heartbeat_timeout=30.0)

    cfg = _cfg(
        env_id="Pendulum-v1", replay_min_size=200, warmup_uniform_steps=200,
        actor_hidden=(16,), critic_hidden=(16, 16),
    )
    env = make(cfg.env_id, seed=0, prefer_builtin=True)
    spec = spec_of(env)
    agent = DDPGAgent(cfg, spec)
    obs, _ = env.reset(seed=0)
    draws = []
    for _ in range(200):
        a = agent.act(obs, explore=True)
        draws.append(a)
        agent.observe(obs, a, 0.0, False, obs)
    draws = np.stack(draws)
    # Uniform draws reach near the box edge; the init policy (std~0.22
    # pre-tanh around mean 0) essentially never does.
    assert np.abs(draws).max() > 0.95 * spec.action_high[0]
    assert np.abs(np.mean(draws)) < 0.5  # centered
    # Past the warmup budget, acting switches to the (narrow) policy.
    post = np.stack([agent.act(obs, explore=True) for _ in range(50)])
    assert np.abs(post).max() < 0.95 * spec.action_high[0]

    # Pool-side budget: resume progress and drained steps consume it, so a
    # respawned/resumed worker never re-injects random actions (ceil-split
    # across workers while any budget remains).
    from distributed_ddpg_tpu.actors.pool import ActorPool

    pool = ActorPool(_cfg(replay_min_size=1000, num_actors=4), spec)
    try:
        assert pool.warmup_budget_per_worker() == 250
        pool.env_steps_offset = 900
        assert pool.warmup_budget_per_worker() == 25
        pool._steps_received = 200
        assert pool.warmup_budget_per_worker() == 0
    finally:
        pool.stop()

    # target_entropy: nan = auto; an explicit 0.0 is a real target and
    # must NOT be remapped.
    import math

    assert math.isnan(DDPGConfig(sac=True).target_entropy)
    assert DDPGConfig(sac=True, target_entropy=0.0).target_entropy == 0.0


def test_sac_config_gates():
    with pytest.raises(ValueError, match="family"):
        DDPGConfig(sac=True, twin_critic=True)
    with pytest.raises(ValueError, match="family"):
        DDPGConfig(sac=True, distributional=True)
    with pytest.raises(ValueError, match="fused_update"):
        DDPGConfig(sac=True, fused_update=True)
    with pytest.raises(ValueError, match="backend"):
        DDPGConfig(sac=True, backend="native")
    # ondevice composes (tests/test_ondevice.py::test_ondevice_runs_all_families).
    DDPGConfig(sac=True, backend="jax_ondevice")
    with pytest.raises(ValueError, match="sac_alpha"):
        DDPGConfig(sac=True, sac_alpha=0.0)
    with pytest.raises(ValueError, match="log_std"):
        DDPGConfig(sac=True, sac_log_std_min=3.0)
    from distributed_ddpg_tpu.ops import fused_chunk

    # SAC is inside the megakernel envelope since round 4
    # (tests/test_fused_chunk.py SAC parity cases).
    assert fused_chunk.supported(_cfg())


def test_sac_sharded_learner_on_mesh():
    """The Gaussian head + twin ensemble + temperature scalar must flow
    through the mesh pspec trees (log_alpha replicates), device-replay
    sampling, and donation on the 8-device CPU mesh."""
    from distributed_ddpg_tpu.parallel import mesh as mesh_lib
    from distributed_ddpg_tpu.parallel.learner import ShardedLearner
    from distributed_ddpg_tpu.replay.device import DeviceReplay
    from distributed_ddpg_tpu.types import pack_batch_np

    cfg = _cfg(batch_size=8)
    mesh = mesh_lib.make_mesh(data_axis=4, model_axis=2, devices=jax.devices())
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, mesh=mesh, chunk_size=4)
    assert not lrn.fused_chunk_active  # SAC -> scan path
    rng = np.random.default_rng(3)
    n = 256
    dr = DeviceReplay(1024, OBS, ACT, mesh=lrn.mesh, block_size=128)
    dr.add_packed(
        pack_batch_np(
            {
                "obs": rng.standard_normal((n, OBS)).astype(np.float32),
                "action": rng.uniform(-1, 1, (n, ACT)).astype(np.float32),
                "reward": rng.standard_normal(n).astype(np.float32),
                "discount": np.full(n, 0.99, np.float32),
                "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
            }
        )
    )
    out = lrn.run_sample_chunk(dr)
    assert np.isfinite(float(out.metrics["critic_loss"]))
    out2 = lrn.run_sample_chunk(dr)
    assert np.isfinite(float(out2.metrics["critic_loss"]))
    # Temperature advanced once per learner step, replicated (scalar).
    assert int(jax.device_get(lrn.state.alpha_opt.count)) == 8
    assert np.asarray(jax.device_get(lrn.state.log_alpha)).ndim == 0


def test_sac_checkpoint_roundtrip(tmp_path):
    """log_alpha/alpha_opt must survive save->restore (None-defaulted
    TrainState fields change the SAC tree, not the other families')."""
    from distributed_ddpg_tpu import checkpoint as ckpt_lib
    from distributed_ddpg_tpu.replay import make_replay

    cfg = _cfg(checkpoint_dir=str(tmp_path / "ckpt"))
    s = init_train_state(cfg, OBS, ACT, seed=0)
    step = jit_learner_step(cfg, 1.0, donate=False)
    batch = _batch(np.random.default_rng(4))
    for _ in range(3):
        s = step(s, batch).state
    replay = make_replay(cfg, OBS, ACT)
    rng = np.random.default_rng(6)
    for _ in range(8):
        replay.add(
            rng.standard_normal((1, OBS)).astype(np.float32),
            rng.uniform(-1, 1, (1, ACT)).astype(np.float32),
            np.asarray([0.5], np.float32),
            np.asarray([0.99], np.float32),
            rng.standard_normal((1, OBS)).astype(np.float32),
        )
    ckpt_lib.save(cfg.checkpoint_dir, 3, s, replay, cfg, env_steps=30)
    template = init_train_state(cfg, OBS, ACT, seed=1)
    restored, rstep, renv = ckpt_lib.restore(
        cfg.checkpoint_dir, template, make_replay(cfg, OBS, ACT), config=cfg
    )
    assert rstep == 3
    np.testing.assert_array_equal(
        np.asarray(restored.log_alpha), np.asarray(s.log_alpha)
    )
    np.testing.assert_array_equal(
        np.asarray(restored.alpha_opt.mu), np.asarray(s.alpha_opt.mu)
    )


@pytest.mark.slow
def test_sac_train_jax_end_to_end(tmp_path):
    from distributed_ddpg_tpu.train import train_jax

    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), num_actors=2,
        sac=True, actor_lr=3e-4, critic_lr=3e-4,
        total_env_steps=4_000, replay_min_size=500, replay_capacity=20_000,
        eval_every=0, max_ingest_ratio=50.0,
        log_path=str(tmp_path / "m.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] >= 40
    assert np.isfinite(out["final_return"])
