"""Sharded device replay (replay_sharding='sharded'; replay/device.py,
docs/REPLAY_SHARDING.md): the ISSUE-10 acceptance suite.

Replicated mode is the bit-exact oracle: the sharded placement must land
the same logical ring (same ptr/size/contents), draw the same sample
stream from the same key, and produce bit-identical learner chunks —
while measurably landing ~1/N ingest bytes per row and holding ~1/N
storage bytes per device (the BENCH_SHARDED_REPLAY claims, asserted here
against the same measured counters the bench reads)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.parallel import multihost
from distributed_ddpg_tpu.parallel.learner import ShardedLearner
from distributed_ddpg_tpu.parallel.mesh import make_mesh
from distributed_ddpg_tpu.replay.device import (
    DevicePrioritizedReplay,
    DeviceReplay,
    make_sharded_per_draw,
)
from distributed_ddpg_tpu.types import pack_batch_np, packed_width

OBS, ACT, B = 4, 2, 64
W = packed_width(OBS, ACT)


def _rows(rng, n):
    return pack_batch_np(
        {
            "obs": rng.standard_normal((n, OBS)).astype(np.float32),
            "action": rng.uniform(-1, 1, (n, ACT)).astype(np.float32),
            "reward": rng.standard_normal(n).astype(np.float32),
            "discount": np.full(n, 0.99, np.float32),
            "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
            "weight": np.ones(n, np.float32),
        }
    )


def _pair(cls, mesh, capacity=256, block=64, **kw):
    return {
        mode: cls(capacity, OBS, ACT, mesh=mesh, block_size=block,
                  replay_sharding=mode, **kw)
        for mode in ("replicated", "sharded")
    }


# --------------------------------------------------------------------------
# ingest parity: same stream -> same logical ring (incl. wraparound)
# --------------------------------------------------------------------------


def test_sharded_ingest_matches_replicated_through_wraparound():
    mesh = make_mesh(-1, 1)
    reps = _pair(DeviceReplay, mesh)
    rng = np.random.default_rng(0)
    blocks = [_rows(rng, 64) for _ in range(5)]  # 320 rows > capacity 256
    for rep in reps.values():
        for b in blocks:
            rep.add_packed(b.copy())
    sa, sb = reps["replicated"].state_dict(), reps["sharded"].state_dict()
    assert int(sa["ptr"]) == int(sb["ptr"]) == 64
    assert int(sa["size"]) == int(sb["size"]) == 256
    np.testing.assert_array_equal(sa["packed"], sb["packed"])


def test_sharded_ingest_lands_one_copy_per_row():
    """The measured-bytes acceptance: with N simulated devices the sharded
    placement must land <= (replicated bytes / N) * 1.1 per ingested row
    and hold ~1/N storage bytes per device (~N x aggregate capacity)."""
    mesh = make_mesh(-1, 1)
    n_dev = mesh.shape["data"]
    assert n_dev == 8  # conftest pins 8 virtual devices
    reps = _pair(DeviceReplay, mesh, capacity=1024, block=128)
    rng = np.random.default_rng(1)
    for rep in reps.values():
        rep.add_packed(_rows(rng, 512))
    snap = {m: r.ingest_snapshot() for m, r in reps.items()}
    repl = snap["replicated"]["replay_ingest_bytes_per_row"]
    shard = snap["sharded"]["replay_ingest_bytes_per_row"]
    assert repl > 0 and shard > 0
    assert shard <= (repl / n_dev) * 1.1, (shard, repl)
    assert snap["sharded"]["replay_shard_count"] == n_dev
    assert (
        snap["replicated"]["replay_device_storage_bytes"]
        >= 0.9 * n_dev * snap["sharded"]["replay_device_storage_bytes"]
    )
    # Strided ownership keeps per-shard fill balanced within one row.
    assert (
        snap["sharded"]["replay_shard_fill_max"]
        - snap["sharded"]["replay_shard_fill_min"]
    ) <= 1


# --------------------------------------------------------------------------
# sampling parity oracle: same key -> bit-identical minibatches/chunks
# --------------------------------------------------------------------------


def test_sampling_parity_oracle_uniform_chunk_bit_identical():
    """ISSUE-10 acceptance: same ingest stream + same sampling key =>
    identical sampled minibatches. The strided placement preserves every
    logical position, the index draw is replica-identical, and the
    masked-gather + psum exchange adds exact zeros — so the WHOLE chunk
    (td errors, metrics, updated params) is bit-identical, not merely
    close."""
    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        seed=0, fused_chunk="off",
    )
    rng = np.random.default_rng(2)
    data = _rows(rng, 512)
    outs = {}
    for mode in ("replicated", "sharded"):
        lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, chunk_size=4,
                             replay_sharding=mode)
        rep = DeviceReplay(1024, OBS, ACT, mesh=lrn.mesh, block_size=256,
                           replay_sharding=mode)
        rep.add_packed(data.copy())
        out = lrn.run_sample_chunk(rep)
        outs[mode] = (
            np.asarray(out.td_errors),
            {k: float(v) for k, v in jax.device_get(out.metrics).items()},
            jax.device_get(lrn.state.actor_params),
        )
    np.testing.assert_array_equal(outs["replicated"][0], outs["sharded"][0])
    assert outs["replicated"][1] == outs["sharded"][1]
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        outs["replicated"][2], outs["sharded"][2],
    )


# --------------------------------------------------------------------------
# PER: stamp parity is exact; the two-level draw matches distributionally
# --------------------------------------------------------------------------


def test_per_stamp_parity_and_checkpoint_roundtrip():
    mesh = make_mesh(-1, 1)
    pers = _pair(DevicePrioritizedReplay, mesh)
    rng = np.random.default_rng(3)
    blocks = [_rows(rng, 64) for _ in range(3)]
    for per in pers.values():
        for b in blocks:
            per.add_packed(b.copy())
    pa, pb = pers["replicated"].state_dict(), pers["sharded"].state_dict()
    np.testing.assert_array_equal(pa["packed"], pb["packed"])
    np.testing.assert_array_equal(pa["priorities"], pb["priorities"])
    # Checkpoint wire format is placement-independent: a replicated
    # state_dict loads into a sharded buffer (and back) bit-exactly.
    fresh = DevicePrioritizedReplay(
        256, OBS, ACT, mesh=mesh, block_size=64, replay_sharding="sharded"
    )
    fresh.load_state_dict(pa)
    np.testing.assert_array_equal(
        fresh.state_dict()["priorities"], pa["priorities"]
    )
    np.testing.assert_array_equal(fresh.state_dict()["packed"], pa["packed"])


def test_sharded_per_draw_is_proportional():
    """Two-level sampler sanity: a row holding ~all the priority mass must
    dominate the draw, and every drawn index must be a live row."""
    mesh = make_mesh(-1, 1)
    per = DevicePrioritizedReplay(
        256, OBS, ACT, mesh=mesh, block_size=64, replay_sharding="sharded"
    )
    rng = np.random.default_rng(4)
    per.add_packed(_rows(rng, 192))
    # Overwrite priorities host-side: row 37 gets 1e4, everyone else 1.
    st = per.state_dict()
    st["priorities"] = np.ones(192, np.float32)
    st["priorities"][37] = 1e4
    per.load_state_dict(st)
    draw = make_sharded_per_draw(mesh)
    scalar = NamedSharding(mesh, P())
    fn = jax.jit(
        lambda k, p, s: draw(k, p, s, (8, 64), jnp.float32(0.4)),
        in_shardings=(scalar, NamedSharding(mesh, P("data")), scalar),
        out_shardings=(scalar, scalar),
    )
    idx, w = fn(
        jax.device_put(jax.random.PRNGKey(7), scalar),
        per.priorities,
        per.size,
    )
    idx = np.asarray(jax.device_get(idx))
    w = np.asarray(jax.device_get(w))
    assert idx.min() >= 0 and idx.max() < 192
    # Row 37 holds ~98% of the mass; stratified draws must overwhelmingly
    # pick it.
    assert (idx == 37).mean() > 0.9, (idx == 37).mean()
    assert np.isfinite(w).all() and w.max() == 1.0


def test_per_sharded_chunk_updates_priorities():
    cfg = DDPGConfig(
        actor_hidden=(32, 32), critic_hidden=(32, 32), batch_size=B,
        seed=0, fused_chunk="off", prioritized=True,
    )
    lrn = ShardedLearner(cfg, OBS, ACT, action_scale=1.0, chunk_size=3,
                         replay_sharding="sharded")
    rep = DevicePrioritizedReplay(
        1024, OBS, ACT, mesh=lrn.mesh, block_size=256,
        replay_sharding="sharded",
    )
    rep.add_packed(_rows(np.random.default_rng(5), 512))
    out = lrn.run_sample_chunk_per(rep, beta=0.5)
    assert np.isfinite(np.asarray(out.td_errors)).all()
    st = rep.state_dict()
    pr = st["priorities"]
    assert np.isfinite(pr).all() and (pr > 0).all()
    # Sampled rows re-stamped at (|td|+eps)^alpha — off the 1.0 max stamp.
    assert (np.abs(pr - 1.0) > 1e-9).any()
    assert float(st["max_priority"]) >= 1.0


# --------------------------------------------------------------------------
# device-actor insert legality (config + runtime)
# --------------------------------------------------------------------------


def test_insert_device_rows_parity_and_alignment():
    mesh = make_mesh(-1, 1)
    reps = _pair(DeviceReplay, mesh)
    dev_rows = np.random.default_rng(6).standard_normal((32, W)).astype(
        np.float32
    )
    blk = jax.device_put(
        jnp.asarray(dev_rows), NamedSharding(mesh, P(None, None))
    )
    for rep in reps.values():
        rep.insert_device_rows(blk)
    np.testing.assert_array_equal(
        reps["replicated"].state_dict()["packed"],
        reps["sharded"].state_dict()["packed"],
    )
    # Non-divisible inserts break the ptr-alignment invariant: refused.
    bad = jax.device_put(
        jnp.asarray(dev_rows[:30]), NamedSharding(mesh, P(None, None))
    )
    with pytest.raises(ValueError, match="divide over"):
        reps["sharded"].insert_device_rows(bad)


# --------------------------------------------------------------------------
# config validation
# --------------------------------------------------------------------------


def test_config_validates_sharded_mode():
    assert DDPGConfig(replay_sharding="sharded")  # legal default combo
    with pytest.raises(ValueError, match="replay_sharding"):
        DDPGConfig(replay_sharding="partitioned")
    with pytest.raises(ValueError, match="host_replay"):
        DDPGConfig(replay_sharding="sharded", host_replay=True)
    with pytest.raises(ValueError, match="scan path"):
        DDPGConfig(replay_sharding="sharded", fused_chunk="on")
    # PR 15 (docs/MESH.md): sharded replay COMPOSES with tensor
    # parallelism — ring on 'data' x params on 'model'; the old
    # model_axis rejection is lifted (parity pinned in
    # tests/test_partition.py).
    assert DDPGConfig(replay_sharding="sharded", model_axis=2)
    with pytest.raises(ValueError, match="backend"):
        DDPGConfig(replay_sharding="sharded", backend="native")
    with pytest.raises(ValueError, match="divide evenly"):
        DDPGConfig(replay_sharding="sharded", data_axis=3,
                   replay_capacity=1_000_000)
    # Device actors: chunk rows must split over the shards.
    with pytest.raises(ValueError, match="insert_device_rows"):
        DDPGConfig(
            replay_sharding="sharded", data_axis=8, replay_capacity=65536,
            actor_backend="device", num_actors=0,
            device_actor_envs=3, device_actor_chunk=1,
        )
    assert DDPGConfig(
        replay_sharding="sharded", data_axis=8, replay_capacity=65536,
        actor_backend="device", num_actors=0,
        device_actor_envs=16, device_actor_chunk=4,
    )


def test_replay_validates_alignment_at_construction():
    mesh = make_mesh(-1, 1)
    with pytest.raises(ValueError, match="capacity"):
        DeviceReplay(255, OBS, ACT, mesh=mesh, block_size=64,
                     replay_sharding="sharded")
    with pytest.raises(ValueError, match="block_size"):
        DeviceReplay(256, OBS, ACT, mesh=mesh, block_size=62,
                     replay_sharding="sharded")
    with pytest.raises(ValueError, match="mesh"):
        DeviceReplay(256, OBS, ACT, mesh=None, block_size=64,
                     replay_sharding="sharded")


# --------------------------------------------------------------------------
# background-beat deadline (ISSUE-10 satellite: no 10-minute silent stall)
# --------------------------------------------------------------------------


def test_beat_result_timeout_derives_from_pod_deadline():
    assert multihost.beat_result_timeout_s() == 600.0  # unarmed default
    multihost.configure_pod(20.0)
    try:
        t = multihost.beat_result_timeout_s()
        # 2x deadline + dispatch slack; far under the old hardcoded 600.
        assert 40.0 <= t <= 120.0, t
        multihost.grant(50.0)
        assert multihost.beat_result_timeout_s() > t  # grant extends
    finally:
        multihost.configure_pod(0.0)
    assert multihost.beat_result_timeout_s(default_s=7.0) == 7.0


# Re-tiered to slow (ISSUE 15 tier-1 budget): 30s deadline-expiry wait; the sharded train smoke + parity oracle
# keep replay-sharding tier-1 coverage
@pytest.mark.slow
def test_wedged_background_beat_surfaces_as_pod_peer_lost(monkeypatch):
    """A sync_ship whose background beat never resolves must raise typed
    PodPeerLost at the derived deadline — the exit-76 clean-abort path —
    instead of stalling for the old hardcoded 600s."""
    from distributed_ddpg_tpu.transfer.scheduler import TransferTicket

    mesh = make_mesh(-1, 1)
    rep = DeviceReplay(256, OBS, ACT, mesh=mesh, block_size=64)
    # Simulate the multi-host background-beat configuration without a
    # cluster: >1 processes (skips the single-process fast path), bg_sync
    # armed, and the issued beat never completes.
    rep._procs = 2
    rep._bg_sync = True
    monkeypatch.setattr(
        rep, "sync_ship_begin",
        lambda force=False: TransferTicket("wedged_beat"),
    )
    multihost.configure_pod(0.2)
    try:
        with pytest.raises(multihost.PodPeerLost, match="sync_ship beat"):
            rep.sync_ship()
    finally:
        multihost.configure_pod(0.0)


# --------------------------------------------------------------------------
# transfer scheduler: the shard_exchange ordered item type
# --------------------------------------------------------------------------


def test_shard_exchange_shares_ordered_lane_fifo():
    """shard_exchange items and lockstep items must execute in ONE strict
    FIFO (both are global device programs — reordering them across
    processes forks the pod), while being accounted as separate classes."""
    from distributed_ddpg_tpu.transfer import TransferScheduler

    s = TransferScheduler().start()
    try:
        order = []
        gate = threading.Event()
        t0 = s.submit("lockstep", lambda: gate.wait(10) and order.append(0))
        t1 = s.submit("shard_exchange", lambda: order.append(1))
        t2 = s.submit("lockstep", lambda: order.append(2))
        t3 = s.submit("shard_exchange", lambda: order.append(3))
        gate.set()
        for t in (t0, t1, t2, t3):
            t.result(timeout=10)
        assert order == [0, 1, 2, 3]
        snap = s.snapshot()
        assert snap["transfer_shard_exchange_items"] == 2
        assert snap["transfer_lockstep_items"] == 2
    finally:
        s.close()


def test_shard_exchange_beats_get_the_lane_deadline():
    from distributed_ddpg_tpu.transfer import TransferScheduler

    s = TransferScheduler(lockstep_timeout_s=0.3).start()
    try:
        ticket = s.submit(
            "shard_exchange", lambda: __import__("time").sleep(10),
            label="beat_1",
        )
        with pytest.raises(multihost.PodPeerLost):
            ticket.result(timeout=10)
        assert s.alive
    finally:
        s.close()


def test_sharded_beats_submit_as_shard_exchange():
    """sync_ship_begin routes sharded beats to the shard_exchange class
    (replicated beats stay lockstep) — pinned via a recording stub."""
    mesh = make_mesh(-1, 1)
    calls = []

    class FakeSched:
        def submit(self, cls, fn, nbytes=0, label=""):
            calls.append(cls)
            from distributed_ddpg_tpu.transfer.scheduler import TransferTicket

            t = TransferTicket(label)
            t._finish(result=0)
            return t

    for mode, expected in (("replicated", "lockstep"),
                           ("sharded", "shard_exchange")):
        rep = DeviceReplay(256, OBS, ACT, mesh=mesh, block_size=64,
                           replay_sharding=mode)
        rep._bg_sync = True
        rep._sched = FakeSched()
        rep.sync_ship_begin()
        assert calls[-1] == expected, (mode, calls)


# --------------------------------------------------------------------------
# CI gate + tools.runs rendering
# --------------------------------------------------------------------------


def test_ci_gate_replay_bytes_key_semantics():
    """-replay_ingest_bytes_per_row is lower-is-better, SKIPs against
    pre-sharded baselines, and FAILS a candidate landing more bytes/row."""
    from distributed_ddpg_tpu.tools.runs import gate_bench

    keys = ["value", "-replay_ingest_bytes_per_row"]
    ok, lines = gate_bench(
        {"value": 100.0},  # old baseline: key absent -> SKIP
        {"value": 100.0, "replay_ingest_bytes_per_row": 172.0},
        0.1, keys,
    )
    assert ok and any(
        l.startswith("SKIP replay_ingest_bytes_per_row") for l in lines
    )
    ok, lines = gate_bench(
        {"value": 100.0, "replay_ingest_bytes_per_row": 172.0},
        {"value": 100.0, "replay_ingest_bytes_per_row": 400.0},
        0.1, keys,
    )
    assert not ok and any(
        l.startswith("FAIL replay_ingest_bytes_per_row") for l in lines
    )
    ok, _ = gate_bench(
        {"value": 100.0, "replay_ingest_bytes_per_row": 172.0},
        {"value": 100.0, "replay_ingest_bytes_per_row": 171.0},
        0.1, keys,
    )
    assert ok


def test_tools_runs_replay_sharding_digest(tmp_path):
    import json

    from distributed_ddpg_tpu.tools.runs import (
        compare_runs,
        render_summary,
        summarize_run,
    )

    recs = [
        {"kind": "train", "step": 100, "replay_ingest_bytes_per_row": 172.0,
         "replay_shard_count": 8, "replay_shard_fill_min": 100,
         "replay_shard_fill_max": 101, "replay_exchange_ms_p95": 2.0,
         "replay_device_storage_bytes": 1409024},
        {"kind": "final", "step": 200, "replay_ingest_bytes_per_row": 172.0,
         "replay_shard_count": 8, "replay_shard_fill_min": 200,
         "replay_shard_fill_max": 200, "replay_exchange_ms_p95": 1.5,
         "replay_device_storage_bytes": 1409024},
    ]
    path = tmp_path / "run.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    digest = summarize_run(str(path))
    shard = digest["replay_sharding"]
    assert shard["replay_ingest_bytes_per_row"]["last"] == 172.0
    assert shard["replay_shard_count"]["last"] == 8
    text = render_summary(digest)
    assert "replay placement" in text
    assert "replay_ingest_bytes_per_row" in text
    _, rows = compare_runs(str(path), str(path))
    assert any(r[0] == "replay_ingest_bytes_per_row" for r in rows)


# --------------------------------------------------------------------------
# reward_sample (auto-support input) reads logical rows in sharded mode
# --------------------------------------------------------------------------


def test_reward_sample_parity_across_placements():
    mesh = make_mesh(-1, 1)
    reps = _pair(DeviceReplay, mesh, capacity=512, block=64)
    data = _rows(np.random.default_rng(8), 256)
    for rep in reps.values():
        rep.add_packed(data.copy())
    ra, da = reps["replicated"].reward_sample()
    rb, db = reps["sharded"].reward_sample()
    np.testing.assert_array_equal(ra, rb)
    np.testing.assert_array_equal(da, db)
    # Strided path too (max_n < size).
    ra, _ = reps["replicated"].reward_sample(max_n=100)
    rb, _ = reps["sharded"].reward_sample(max_n=100)
    np.testing.assert_array_equal(ra, rb)


# --------------------------------------------------------------------------
# end-to-end: the trainer runs sharded and resumes from its checkpoint
# --------------------------------------------------------------------------


def test_train_smoke_sharded_replay(tmp_path):
    """Tier-1 acceptance: a sharded-replay run trains end to end and its
    records carry the replay_* placement family with the full shard
    count. (Checkpoint-format roundtrips across placements are pinned at
    unit scale by test_per_stamp_parity_and_checkpoint_roundtrip; a
    second full train run here would only re-pay the XLA compiles.)"""
    import json

    from distributed_ddpg_tpu.train import train_jax

    ckpt = str(tmp_path / "ckpt")
    cfg = DDPGConfig(
        backend="jax_tpu",
        env_id="Pendulum-v1",
        actor_hidden=(16, 16),
        critic_hidden=(16, 16),
        batch_size=16,
        num_actors=1,
        replay_sharding="sharded",
        total_env_steps=900,
        replay_min_size=128,
        replay_capacity=8192,
        eval_every=100_000,
        checkpoint_dir=ckpt,
        checkpoint_every=8,
        log_path=str(tmp_path / "a.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    assert np.isfinite(out["final_return"])
    recs = [json.loads(l) for l in open(cfg.log_path)]
    shardy = [r for r in recs if "replay_shard_count" in r]
    assert shardy and shardy[-1]["replay_shard_count"] == 8
    assert any(r.get("replay_ingest_bytes", 0) > 0 for r in shardy)
    # The run checkpointed in the logical wire format (resumable by
    # either placement — unit-pinned above).
    from distributed_ddpg_tpu import checkpoint as ckpt_lib

    assert ckpt_lib.latest_step(ckpt) is not None


def test_sharded_per_draw_clamps_to_live_rows():
    """Partially-filled buffer: every drawn index must stay < size even
    when a stratified uniform lands on a shard-interval boundary — the
    sharded twin of draw_per_indices' size clamp (an unclamped draw
    would select an empty zero-priority slot and its (size*1e-12)^-beta
    IS weight would crush the batch's normalization)."""
    mesh = make_mesh(-1, 1)
    per = DevicePrioritizedReplay(
        256, OBS, ACT, mesh=mesh, block_size=64, replay_sharding="sharded"
    )
    per.add_packed(_rows(np.random.default_rng(9), 64))
    # Awkward live size (not a shard multiple) with uneven mass.
    st = per.state_dict()
    st["packed"] = st["packed"][:57]
    st["size"] = np.asarray(57)
    st["ptr"] = np.asarray(0)
    st["priorities"] = np.linspace(0.1, 5.0, 57).astype(np.float32)
    per.load_state_dict(st)
    draw = make_sharded_per_draw(mesh)
    scalar = NamedSharding(mesh, P())
    fn = jax.jit(
        lambda k, p, s: draw(k, p, s, (8, 64), jnp.float32(0.4)),
        in_shardings=(scalar, NamedSharding(mesh, P("data")), scalar),
        out_shardings=(scalar, scalar),
    )
    for seed in range(6):
        idx, w = fn(
            jax.device_put(jax.random.PRNGKey(seed), scalar),
            per.priorities, per.size,
        )
        idx = np.asarray(jax.device_get(idx))
        w = np.asarray(jax.device_get(w))
        assert idx.min() >= 0 and idx.max() < 57, (seed, idx.max())
        assert np.isfinite(w).all() and w.max() == 1.0
        # No zero-priority slot was ever selected: weights stay in a sane
        # dynamic range (an empty slot would produce a ~1e5x outlier max
        # that normalizes everything else to ~0).
        assert w.min() > 1e-4, (seed, w.min())


# --------------------------------------------------------------------------
# all-writer slices + N->M reshard matrix (ISSUE 17; docs/REPLAY_SHARDING.md
# 'All-writer replay slices', docs/RESILIENCE.md shrink/grow state machine)
# --------------------------------------------------------------------------


def test_slice_state_dict_single_process_covers_ring():
    """Single-process slice_state_dict is the whole logical ring as one
    1-of-1 slice: positions [0, size), rows in wire order, and (PER) the
    live priority vector — so a 1-process 'pod' writes the same format N
    writers do and merge_slice_states([slice]) is the identity."""
    from distributed_ddpg_tpu.replay.device import merge_slice_states

    mesh = make_mesh(-1, 1)
    for cls in (DeviceReplay, DevicePrioritizedReplay):
        rep = cls(256, OBS, ACT, mesh=mesh, block_size=64,
                  replay_sharding="sharded")
        rep.add_packed(_rows(np.random.default_rng(5), 128))
        sl = rep.slice_state_dict()
        np.testing.assert_array_equal(
            np.asarray(sl["positions"]), np.arange(128, dtype=np.int64)
        )
        assert int(sl["capacity"]) == 256
        st = rep.state_dict()
        merged = merge_slice_states([sl])
        np.testing.assert_array_equal(merged["packed"], st["packed"])
        assert int(merged["ptr"]) == int(st["ptr"])
        assert int(merged["size"]) == int(st["size"])
        if "priorities" in st:
            np.testing.assert_array_equal(
                np.asarray(merged["priorities"], np.float32),
                np.asarray(st["priorities"], np.float32),
            )


def test_reshard_matrix_roundtrip_equals_single_host_oracle(tmp_path):
    """The N->M reshard acceptance matrix over {1,2,4}^2, uniform + PER:
    an n-writer slice set (the split of a single-host oracle state)
    written through checkpoint.write_replay_slice, digest-verified,
    loaded back, merged, and loaded into a sharded buffer must reproduce
    the oracle's logical ring bit-for-bit — including the PER priority
    vector rebuild — and re-splitting to m writers round-trips the same
    state (the grow/shrink algebra is position-driven, so the writer
    count is free to change at every restart)."""
    from distributed_ddpg_tpu import checkpoint as ckpt_lib
    from distributed_ddpg_tpu.replay.device import (
        merge_slice_states,
        split_slice_state,
    )

    mesh = make_mesh(-1, 1)
    for cls in (DeviceReplay, DevicePrioritizedReplay):
        rng = np.random.default_rng(11)
        oracle_rep = cls(256, OBS, ACT, mesh=mesh, block_size=64,
                         replay_sharding="replicated")
        oracle_rep.add_packed(_rows(rng, 192))
        oracle = oracle_rep.state_dict()
        if "priorities" in oracle:
            # Non-uniform priorities so the vector rebuild is observable
            # (a uniform stamp would mask a dropped/reordered slice).
            oracle["priorities"] = np.linspace(
                0.2, 4.0, int(oracle["size"])
            ).astype(np.float32)
            oracle["max_priority"] = np.asarray(5.0, np.float32)
        target = cls(256, OBS, ACT, mesh=mesh, block_size=64,
                     replay_sharding="sharded")
        for n in (1, 2, 4):
            d = str(tmp_path / f"{cls.__name__}_n{n}")
            for k, sl in enumerate(split_slice_state(oracle, n, 256)):
                ckpt_lib.write_replay_slice(d, 7, k, n, sl)
            complete, nprocs = ckpt_lib.verify_replay_slices(d, 7)
            assert complete and nprocs == n, (complete, nprocs)
            merged = merge_slice_states(ckpt_lib.load_replay_slices(d, 7))
            np.testing.assert_array_equal(merged["packed"], oracle["packed"])
            # The production load path: the merged wire state lands in a
            # sharded buffer (the M-process counterpart scatters the same
            # replicated logical rows through the reshard program).
            target.load_state_dict(merged)
            back = target.state_dict()
            np.testing.assert_array_equal(back["packed"], oracle["packed"])
            assert int(back["ptr"]) == int(oracle["ptr"])
            assert int(back["size"]) == int(oracle["size"])
            if "priorities" in oracle:
                np.testing.assert_array_equal(
                    np.asarray(back["priorities"], np.float32),
                    oracle["priorities"],
                )
                assert float(back["max_priority"]) == 5.0
            for m in (1, 2, 4):
                # Re-split to m writers (the next incarnation's slice
                # set) and merge back: bit-identical to the oracle.
                reslices = split_slice_state(back, m, 256)
                assert len(reslices) == m
                assert sum(
                    len(s["positions"]) for s in reslices
                ) == int(oracle["size"])
                remerged = merge_slice_states(reslices)
                np.testing.assert_array_equal(
                    remerged["packed"], oracle["packed"]
                )
                if "priorities" in oracle:
                    np.testing.assert_array_equal(
                        np.asarray(remerged["priorities"], np.float32),
                        oracle["priorities"],
                    )


def test_merge_slice_states_rejects_holes_overlaps_and_forks():
    """A slice set that mixes worlds must fail LOUDLY: silently loading a
    holed or overlapping set would corrupt the data distribution the
    learner resumes on (docs/REPLAY_SHARDING.md)."""
    from distributed_ddpg_tpu.replay.device import (
        ReplayUsageError,
        merge_slice_states,
        split_slice_state,
    )

    rng = np.random.default_rng(13)
    state = {
        "packed": rng.standard_normal((64, W)).astype(np.float32),
        "ptr": np.asarray(0), "size": np.asarray(64),
    }
    a, b = split_slice_state(state, 2, 256)
    with pytest.raises(ReplayUsageError, match="does not cover"):
        merge_slice_states([a])                       # hole
    with pytest.raises(ReplayUsageError, match="overlap"):
        merge_slice_states([a, a])                    # overlap
    forked = dict(b, ptr=np.asarray(32))
    with pytest.raises(ReplayUsageError, match="ring scalars"):
        merge_slice_states([a, forked])               # mixed steps
    with pytest.raises(ReplayUsageError, match="empty"):
        merge_slice_states([])


def test_single_shard_sharded_load_state_dict_roundtrip():
    """A 1-device 'sharded' ring (data axis 1 — what a plain CLI run on
    one CPU device builds) must still load checkpoints: device_get hands
    back a read-only buffer and the logical permutation is an identity
    there, so the load path must copy before writing (regression: the
    elastic CLI resume crashed with 'assignment destination is
    read-only')."""
    from jax.sharding import Mesh

    mesh = Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("data", "model")
    )
    rng = np.random.default_rng(17)
    for cls in (DeviceReplay, DevicePrioritizedReplay):
        src = cls(128, OBS, ACT, mesh=mesh, block_size=32,
                  replay_sharding="sharded")
        src.add_packed(_rows(rng, 96))
        state = src.state_dict()
        dst = cls(128, OBS, ACT, mesh=mesh, block_size=32,
                  replay_sharding="sharded")
        dst.load_state_dict(state)
        back = dst.state_dict()
        np.testing.assert_array_equal(back["packed"], state["packed"])
        assert int(back["size"]) == 96
        if "priorities" in state:
            np.testing.assert_array_equal(
                np.asarray(back["priorities"], np.float32),
                np.asarray(state["priorities"], np.float32),
            )
