"""C++ replay core vs numpy oracle (SURVEY.md §4 'Unit' + the native-core
contract in native/__init__.py): identical trees, samples, and totals under
randomized operation sequences; graceful fallback when disabled."""

import numpy as np
import pytest

from distributed_ddpg_tpu import native
from distributed_ddpg_tpu.replay.sum_tree import SumTree

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def test_native_matches_numpy_fuzz():
    rng = np.random.default_rng(0)
    a = native.NativeSumTree(100)
    b = SumTree(100)
    assert a.capacity == b.capacity
    for round_ in range(50):
        n = int(rng.integers(1, 40))
        idx = rng.integers(0, 100, n)
        prio = rng.uniform(0.0, 5.0, n)
        a.set(idx, prio)
        b.set(idx, prio)
        np.testing.assert_allclose(a.tree, b.tree, rtol=1e-12, atol=1e-12)
        v = rng.uniform(0.0, max(a.total, 1e-9), 64)
        np.testing.assert_array_equal(a.sample(v), b.sample(v))
        np.testing.assert_allclose(a.get(np.arange(100)), b.get(np.arange(100)))


def test_native_stratified_statistics():
    t = native.NativeSumTree(4)
    t.set(np.arange(4), np.array([1.0, 0.0, 3.0, 0.0]))
    rng = np.random.default_rng(1)
    idx = t.stratified_sample(4000, rng)
    counts = np.bincount(idx, minlength=4)
    assert counts[1] == 0 and counts[3] == 0
    np.testing.assert_allclose(counts[2] / counts[0], 3.0, rtol=0.15)


def test_fallback_when_disabled(monkeypatch):
    import importlib

    monkeypatch.setenv("DDPG_DISABLE_NATIVE", "1")
    import distributed_ddpg_tpu.native as nat

    importlib.reload(nat)
    tree = nat.make_sum_tree(16)
    assert isinstance(tree, SumTree)
    # Restore the loaded state for other tests.
    monkeypatch.delenv("DDPG_DISABLE_NATIVE")
    importlib.reload(nat)
