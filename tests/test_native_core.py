"""C++ replay core vs numpy oracle (SURVEY.md §4 'Unit' + the native-core
contract in native/__init__.py): identical trees, samples, and totals under
randomized operation sequences; graceful fallback when disabled."""

import numpy as np
import pytest

from distributed_ddpg_tpu import native
from distributed_ddpg_tpu.replay.sum_tree import SumTree

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def test_native_matches_numpy_fuzz():
    rng = np.random.default_rng(0)
    a = native.NativeSumTree(100)
    b = SumTree(100)
    assert a.capacity == b.capacity
    for round_ in range(50):
        n = int(rng.integers(1, 40))
        idx = rng.integers(0, 100, n)
        prio = rng.uniform(0.0, 5.0, n)
        a.set(idx, prio)
        b.set(idx, prio)
        np.testing.assert_allclose(a.tree, b.tree, rtol=1e-12, atol=1e-12)
        v = rng.uniform(0.0, max(a.total, 1e-9), 64)
        np.testing.assert_array_equal(a.sample(v), b.sample(v))
        np.testing.assert_allclose(a.get(np.arange(100)), b.get(np.arange(100)))


def test_native_stratified_statistics():
    t = native.NativeSumTree(4)
    t.set(np.arange(4), np.array([1.0, 0.0, 3.0, 0.0]))
    rng = np.random.default_rng(1)
    idx = t.stratified_sample(4000, rng)
    counts = np.bincount(idx, minlength=4)
    assert counts[1] == 0 and counts[3] == 0
    np.testing.assert_allclose(counts[2] / counts[0], 3.0, rtol=0.15)


def test_fallback_when_disabled(monkeypatch):
    import importlib

    monkeypatch.setenv("DDPG_DISABLE_NATIVE", "1")
    import distributed_ddpg_tpu.native as nat

    importlib.reload(nat)
    tree = nat.make_sum_tree(16)
    assert isinstance(tree, SumTree)
    # Restore the loaded state for other tests.
    monkeypatch.delenv("DDPG_DISABLE_NATIVE")
    importlib.reload(nat)


# ---------------------------------------------------------------------------
# SPSC shared-memory ring (native.ShmRing over replay_core.cpp ring_*)
# ---------------------------------------------------------------------------


def _ring(rows=8, width=4):
    buf = bytearray(native.ShmRing.nbytes(rows, width))
    return native.ShmRing(buf, rows, width, init=True)


def test_ring_roundtrip_and_wraparound():
    r = _ring(rows=8, width=4)
    rng = np.random.default_rng(2)
    sent = []
    for chunk in (3, 5, 4, 6, 2):  # 20 rows through an 8-row ring
        rows = rng.standard_normal((chunk, 4)).astype(np.float32)
        pushed = 0
        while pushed < chunk:
            pushed += r.push(rows[pushed:])
            got = r.pop(64)
            if got.shape[0]:
                sent.append(got)
        # Drain fully so the next chunk always fits eventually.
        got = r.pop(64)
        if got.shape[0]:
            sent.append(got)
    out = np.concatenate(sent)
    assert out.shape == (20, 4)
    # FIFO order must be preserved across wraps; re-generate the stream.
    rng = np.random.default_rng(2)
    expect = np.concatenate(
        [rng.standard_normal((c, 4)).astype(np.float32) for c in (3, 5, 4, 6, 2)]
    )
    np.testing.assert_array_equal(out, expect)


def test_ring_full_partial_accept():
    r = _ring(rows=4, width=2)
    rows = np.arange(12, dtype=np.float32).reshape(6, 2)
    assert r.push(rows) == 4          # only capacity rows accepted
    assert len(r) == 4
    assert r.push(rows[4:]) == 0      # full
    got = r.pop(2)
    np.testing.assert_array_equal(got, rows[:2])
    assert r.push(rows[4:]) == 2      # space freed
    np.testing.assert_array_equal(r.pop(64), np.concatenate([rows[2:4], rows[4:]]))
    assert len(r) == 0


def _producer(buf, rows, width, n_rows):
    from distributed_ddpg_tpu import native
    import numpy as np
    import time

    ring = native.ShmRing(buf, rows, width, init=False)
    data = np.arange(n_rows * width, dtype=np.float32).reshape(n_rows, width)
    pushed = 0
    deadline = time.time() + 30
    while pushed < n_rows and time.time() < deadline:
        pushed += ring.push(data[pushed:])
    assert pushed == n_rows


def test_ring_cross_process():
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    ROWS, WIDTH, N = 64, 3, 1000
    buf = ctx.Array("B", native.ShmRing.nbytes(ROWS, WIDTH), lock=False)
    ring = native.ShmRing(buf, ROWS, WIDTH, init=True)
    p = ctx.Process(target=_producer, args=(buf, ROWS, WIDTH, N))
    p.start()
    got = []
    import time

    deadline = time.time() + 30
    total = 0
    while total < N and time.time() < deadline:
        rows = ring.pop(ROWS)
        if rows.shape[0]:
            got.append(rows)
            total += rows.shape[0]
    p.join(timeout=10)
    out = np.concatenate(got)
    expect = np.arange(N * WIDTH, dtype=np.float32).reshape(N, WIDTH)
    np.testing.assert_array_equal(out, expect)
