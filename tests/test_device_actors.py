"""Device-actor subsystem (actors/device_pool.py; docs/DEVICE_ACTORS.md):
seed-fixed transition parity against a host-stepped JaxPendulum reference
loop, the devactor: fault grammar + bounded-restart supervisor contract,
config validation, the tier-1 train smoke (devactor_* in records, ZERO
transfer_ingest_items from the device source), the bench A/B phase, the
ci_gate key semantics, and the tools.runs digest."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ddpg_tpu.actors.device_pool import (
    DeviceActorError,
    DeviceActorPool,
    resolve_device_actor_chunk,
)
from distributed_ddpg_tpu.config import DDPGConfig
from distributed_ddpg_tpu.envs.jax_envs import JaxPendulum
from distributed_ddpg_tpu.faults import FaultPlan, InjectedFault
from distributed_ddpg_tpu.learner import init_train_state
from distributed_ddpg_tpu.models.mlp import actor_apply
from distributed_ddpg_tpu.parallel import mesh as mesh_lib
from distributed_ddpg_tpu.replay.device import (
    DevicePrioritizedReplay,
    DeviceReplay,
)

E, K = 4, 6  # envs x scan steps for the unit-scale pool below


def _small_cfg(**kw):
    base = dict(
        env_id="Pendulum-v1",
        actor_backend="device",
        num_actors=0,
        device_actor_envs=E,
        device_actor_chunk=K,
        actor_hidden=(32, 32),
        critic_hidden=(32, 32),
        replay_capacity=4096,
    )
    base.update(kw)
    return DDPGConfig(**base)


def _one_device_mesh():
    return mesh_lib.make_mesh(data_axis=1, model_axis=1,
                              devices=jax.devices()[:1])


def _pool_with_params(cfg, mesh, fault=None):
    from jax.sharding import NamedSharding, PartitionSpec as P

    pool = DeviceActorPool(cfg, mesh=mesh, fault=fault)
    state = init_train_state(cfg, pool.obs_dim, pool.act_dim, cfg.seed)
    params = jax.device_put(
        state.actor_params,
        jax.tree.map(lambda _: NamedSharding(mesh, P()), state.actor_params),
    )
    pool.set_params(params)
    return pool, params


def test_chunk_resolution():
    assert resolve_device_actor_chunk(DDPGConfig(device_actor_chunk=5)) == 5
    assert resolve_device_actor_chunk(DDPGConfig()) == 8  # conftest pins cpu
    import distributed_ddpg_tpu.ops.fused_chunk as fc

    orig = fc.runs_native
    fc.runs_native = lambda: True
    try:
        assert resolve_device_actor_chunk(DDPGConfig()) == 64
    finally:
        fc.runs_native = orig


def test_device_actor_transition_parity_with_host_reference():
    """Seed-fixed parity: the rows the device pool landed in replay must
    match a HOST-stepped JaxPendulum reference loop that replays the
    rollout body's exact PRNG stream eagerly — obs / action / reward /
    boot_obs / discount all agree, so the compiled scan computes the same
    rollout a transparent per-step loop would."""
    cfg = _small_cfg()
    mesh = _one_device_mesh()
    pool, params = _pool_with_params(cfg, mesh)
    replay = DeviceReplay(cfg.replay_capacity, pool.obs_dim, pool.act_dim,
                          mesh=mesh, block_size=64, async_ship=False)
    assert pool.run_chunk(replay) == K * E
    landed = np.asarray(jax.device_get(replay.storage))[: K * E]

    # --- host reference: same key schedule, eager ops, no scan/jit ---
    env = JaxPendulum()
    params_host = jax.device_get(params)
    scale = pool.action_scale
    offset = pool.action_offset
    low = jnp.asarray(env.action_low)
    high = jnp.asarray(env.action_high)
    key = jax.random.PRNGKey(cfg.seed + 0xDA)
    k_init, key = jax.random.split(key)
    env_state = jax.vmap(env.init)(jax.random.split(k_init, E))
    obs = jax.vmap(env.observe)(env_state)
    ou = jnp.zeros((E, pool.act_dim), jnp.float32)
    expected = []
    for _ in range(K):
        key, k_ou, k_env, k_uni = jax.random.split(key, 4)
        ou = (
            ou
            + cfg.ou_theta * (0.0 - ou) * cfg.ou_dt
            + cfg.ou_sigma * jnp.sqrt(cfg.ou_dt)
            * jax.random.normal(k_ou, ou.shape, jnp.float32)
        )
        action = jnp.clip(
            actor_apply(params_host, obs, scale, offset) + ou * scale,
            low, high,
        )
        out = jax.vmap(env.step)(env_state, action,
                                 jax.random.split(k_env, E))
        discount = cfg.gamma * (
            1.0 - jnp.broadcast_to(out.terminated, (E,)).astype(jnp.float32)
        )
        expected.append(np.concatenate(
            [
                np.asarray(obs), np.asarray(action),
                np.asarray(out.reward)[:, None],
                np.asarray(discount)[:, None],
                np.asarray(out.boot_obs),
                np.ones((E, 1), np.float32),
            ],
            axis=-1,
        ))
        env_state, obs = out.state, out.obs
        ou = jnp.where(out.done[:, None], 0.0, ou)
    expected = np.concatenate(expected)  # [K*E, D], step-major
    np.testing.assert_allclose(landed, expected, rtol=1e-5, atol=1e-5)


def test_insert_device_rows_wraparound_and_per_stamp():
    """The donated device insert honors ring wraparound, and the PER
    subclass stamps landed rows with the running max priority (the
    every-transition-seen-once rule every other source follows)."""
    mesh = _one_device_mesh()
    per = DevicePrioritizedReplay(64, 3, 1, mesh=mesh, block_size=16,
                                  async_ship=False)
    width = per.width
    rows = jnp.arange(48 * width, dtype=jnp.float32).reshape(48, width)
    per.insert_device_rows(jax.device_put(rows))
    assert len(per) == 48
    prios = np.asarray(jax.device_get(per.priorities))
    assert (prios[:48] == 1.0).all() and (prios[48:] == 0.0).all()
    # Second insert wraps: 48 + 48 = 96 -> positions 48..63 then 0..31.
    per.insert_device_rows(jax.device_put(rows + 1000.0))
    assert len(per) == 64
    assert int(jax.device_get(per.ptr)) == 32
    storage = np.asarray(jax.device_get(per.storage))
    np.testing.assert_array_equal(
        storage[0], np.asarray(rows[16] + 1000.0)
    )
    assert (np.asarray(jax.device_get(per.priorities)) == 1.0).all()


def test_devactor_fault_grammar():
    plan = FaultPlan.parse("devactor:rollout:crash@2", seed=0)
    site = plan.site("devactor", "rollout")
    site.tick()
    with pytest.raises(InjectedFault):
        site.tick()
    # slow flavor parses with duration; bad kinds die at parse.
    FaultPlan.parse("devactor:rollout:slow@1~0.01", seed=0)
    with pytest.raises(ValueError, match="devactor"):
        DDPGConfig(faults="devactor:rollout:kill@1")


def test_devactor_bounded_restart_supervisor_contract():
    """A rollout-dispatch fault with the carry intact restarts bounded
    (counter devactor_restarts); past the budget the typed
    DeviceActorError surfaces."""
    cfg = _small_cfg()
    mesh = _one_device_mesh()
    plan = FaultPlan.parse("devactor:rollout:crash@1", seed=0)
    pool, _ = _pool_with_params(cfg, mesh,
                                fault=plan.site("devactor", "rollout"))
    replay = DeviceReplay(cfg.replay_capacity, pool.obs_dim, pool.act_dim,
                          mesh=mesh, block_size=64, async_ship=False)
    assert pool.run_chunk(replay) == K * E  # crash absorbed, rows landed
    assert pool.restarts == 1
    assert pool.snapshot()["devactor_restarts"] == 1

    # Budget exhaustion: every dispatch faults -> typed error, cause kept.
    plan = FaultPlan.parse(
        ";".join(f"devactor:rollout:crash@{i}" for i in range(1, 9)), seed=0
    )
    pool2, _ = _pool_with_params(cfg, mesh,
                                 fault=plan.site("devactor", "rollout"))
    with pytest.raises(DeviceActorError) as ei:
        pool2.run_chunk(replay)
    assert isinstance(ei.value.__cause__, InjectedFault)


def test_config_validation_rejects_unsupported_combos():
    with pytest.raises(ValueError, match="on-device \\(JAX\\)"):
        DDPGConfig(actor_backend="device", env_id="HalfCheetah-v4")
    with pytest.raises(ValueError, match="never call act\\(\\) on the host"):
        _small_cfg(serve_actors=True, num_actors=1)
    with pytest.raises(ValueError, match="jax_tpu"):
        DDPGConfig(actor_backend="device", backend="native")
    with pytest.raises(ValueError, match="n_step"):
        _small_cfg(n_step=3)
    with pytest.raises(ValueError, match="host_replay"):
        _small_cfg(host_replay=True)
    with pytest.raises(ValueError, match="strict_sync"):
        _small_cfg(strict_sync=True, max_learn_ratio=1.0,
                   max_ingest_ratio=1.0)
    with pytest.raises(ValueError, match="num_actors"):
        DDPGConfig(num_actors=0)  # host backend needs workers
    with pytest.raises(ValueError, match="actor_backend"):
        DDPGConfig(actor_backend="gpu")
    # One rollout chunk may not exceed the ring: the scatter would write
    # duplicate positions in unspecified order (silent corruption).
    with pytest.raises(ValueError, match="replay_capacity"):
        _small_cfg(device_actor_envs=512, device_actor_chunk=16,
                   replay_capacity=4096)
    _small_cfg()  # the happy path constructs


def test_train_smoke_device_actors(tmp_path):
    """Tier-1 acceptance: a device-actor-only run trains, every record
    carries devactor_* fields, and the transfer scheduler's ingest class
    moved ZERO items — the device source never touches it."""
    from distributed_ddpg_tpu.train import train_jax

    cfg = _small_cfg(
        backend="jax_tpu",
        device_actor_envs=8,
        device_actor_chunk=4,
        total_env_steps=1600,
        replay_min_size=200,
        replay_capacity=20_000,
        eval_every=100_000,  # final eval only: keep the smoke fast
        log_path=str(tmp_path / "m.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    assert np.isfinite(out["final_return"])
    assert out["devactor_env_steps"] >= cfg.total_env_steps
    assert out["devactor_restarts"] == 0
    recs = [json.loads(l) for l in open(cfg.log_path)]
    finals = [r for r in recs if r["kind"] == "final"]
    assert finals and "devactor_rows_per_s" in finals[-1]
    assert "devactor_chunk_p95" in finals[-1]
    # Zero scheduler-ingest attributable to the device source: this run
    # has no host workers, so the class must never move an item.
    seen = [r["transfer_ingest_items"] for r in recs
            if "transfer_ingest_items" in r]
    assert seen and all(v == 0 for v in seen)
    # The rollout bracket rides PhaseTimers -> per-chunk step tails.
    assert any("t_devactor_ms" in r for r in recs)


def test_device_only_warmup_with_ingest_ratio_gate(tmp_path):
    """Regression: with max_ingest_ratio armed and rows_per_chunk larger
    than min_fill, the device gate must still admit a chunk while any
    allowance remains (bounded one-chunk overshoot) — an all-or-nothing
    gate wedged warmup forever in a device-only run (no host workers to
    fill the buffer, learn_steps pinned at 0)."""
    from distributed_ddpg_tpu.train import train_jax

    cfg = _small_cfg(
        backend="jax_tpu",
        device_actor_envs=32,
        device_actor_chunk=4,     # 128 rows/chunk > min_fill of 100
        total_env_steps=600,
        replay_min_size=100,
        replay_capacity=20_000,
        max_ingest_ratio=1.0,
        max_learn_ratio=1.0,
        eval_every=100_000,
        log_path=str(tmp_path / "m.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    assert out["devactor_env_steps"] >= cfg.total_env_steps


@pytest.mark.slow
def test_side_by_side_host_and_device_actors(tmp_path):
    """Both backends feeding the same ring: a tiny device pool (4 rows per
    chunk) plus one host worker — the run's total env steps exceed the
    device share, proving host rows kept flowing through the ingest
    pipeline while device rows took the donated insert."""
    from distributed_ddpg_tpu.train import train_jax

    cfg = _small_cfg(
        backend="jax_tpu",
        num_actors=1,
        device_actor_envs=2,
        device_actor_chunk=2,
        total_env_steps=2000,
        replay_min_size=200,
        replay_capacity=20_000,
        eval_every=100_000,
        log_path=str(tmp_path / "m.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    assert out["devactor_env_steps"] > 0
    recs = [json.loads(l) for l in open(cfg.log_path)]
    final = [r for r in recs if r["kind"] == "final"][-1]
    # final["step"] is host + device env steps; strictly more than the
    # device share means the host pool contributed real rows.
    assert final["step"] > out["devactor_env_steps"]


def test_bench_devactor_phase_smoke(monkeypatch):
    """bench.py BENCH_DEVACTOR phase: the A/B JSON carries the scaling
    curve and the top-level devactor_rows_per_s the gate key pins, and
    the compiled rollout beats the python host loop at this env count."""
    import bench

    monkeypatch.setenv("BENCH_SECONDS", "0.25")
    monkeypatch.setenv("BENCH_DEVACTOR_ENVS", "16")
    monkeypatch.setenv("BENCH_DEVACTOR_CHUNK", "8")
    r = bench.phase_devactor()
    assert "devactor_scaling" in r and "16" in r["devactor_scaling"]
    point = r["devactor_scaling"]["16"]
    assert point["devactor_rows_per_s"] > 0
    assert point["host_rows_per_s"] > 0
    assert r["devactor_rows_per_s"] == point["devactor_rows_per_s"]
    assert r["devactor_vs_host"] == point["devactor_vs_host"]


def test_ci_gate_devactor_key_semantics():
    """devactor_rows_per_s: SKIP against pre-devactor baselines (arms on
    the first BENCH_DEVACTOR capture), FAIL on a real throughput drop."""
    from distributed_ddpg_tpu.tools.runs import gate_bench

    keys = ("value", "devactor_rows_per_s")
    ok, lines = gate_bench(
        {"value": 100.0}, {"value": 100.0, "devactor_rows_per_s": 5e5},
        0.1, keys,
    )
    assert ok and any(
        l.startswith("SKIP devactor_rows_per_s") for l in lines
    )
    ok, lines = gate_bench(
        {"value": 100.0, "devactor_rows_per_s": 5e5},
        {"value": 100.0, "devactor_rows_per_s": 2e5},
        0.1, keys,
    )
    assert not ok and any(
        l.startswith("FAIL devactor_rows_per_s") for l in lines
    )
    ok, _ = gate_bench(
        {"value": 100.0, "devactor_rows_per_s": 5e5},
        {"value": 100.0, "devactor_rows_per_s": 5.2e5},
        0.1, keys,
    )
    assert ok


def test_tools_runs_devactor_digest(tmp_path):
    """tools.runs summarize/compare render the devactor digest."""
    from distributed_ddpg_tpu.tools.runs import compare_runs, render_summary, summarize_run

    path = tmp_path / "run.jsonl"
    recs = [
        {"kind": "train", "step": 100, "devactor_rows_per_s": 1000.0,
         "devactor_chunk_p95": 5.0, "devactor_env_steps": 100,
         "devactor_restarts": 0},
        {"kind": "final", "step": 200, "devactor_rows_per_s": 1200.0,
         "devactor_chunk_p95": 4.0, "devactor_env_steps": 200,
         "devactor_restarts": 0},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    digest = summarize_run(str(path))
    assert digest["devactor"]["devactor_rows_per_s"]["last"] == 1200.0
    text = render_summary(digest)
    assert "device actors" in text and "devactor_rows_per_s" in text
    out, rows = compare_runs(str(path), str(path))
    assert any(r[0] == "devactor_rows_per_s" for r in rows)


# --------------------------------------------------------------------------
# rollout-state checkpointing (ISSUE-10 satellite; docs/DEVICE_ACTORS.md)
# --------------------------------------------------------------------------


def test_carry_state_roundtrip_continues_episodes():
    """carry_state_dict -> load_carry_state must resume the EXACT rollout
    stream: a restored pool's next chunk produces bit-identical rows to
    the uninterrupted pool's (env state, obs, OU state, and the PRNG key
    all ride the snapshot), and the episode accumulators carry over."""
    cfg = _small_cfg()
    mesh = _one_device_mesh()
    pool_a, params = _pool_with_params(cfg, mesh)
    rep_a = DeviceReplay(4096, pool_a.obs_dim, pool_a.act_dim, mesh=mesh,
                         block_size=64, async_ship=False)
    pool_a.run_chunk(rep_a)
    pool_a.run_chunk(rep_a)
    snap = pool_a.carry_state_dict()
    assert all(isinstance(v, np.ndarray) for v in snap.values())

    pool_b = DeviceActorPool(cfg, mesh=mesh)
    pool_b.set_params(params)
    assert pool_b.load_carry_state(snap) is True
    # Both pools now advance from the identical carry: next chunks match.
    rep_cont = DeviceReplay(4096, pool_a.obs_dim, pool_a.act_dim, mesh=mesh,
                            block_size=64, async_ship=False)
    rep_rest = DeviceReplay(4096, pool_a.obs_dim, pool_a.act_dim, mesh=mesh,
                            block_size=64, async_ship=False)
    pool_a.run_chunk(rep_cont)
    pool_b.run_chunk(rep_rest)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(rep_cont.storage)),
        np.asarray(jax.device_get(rep_rest.storage)),
    )
    # Device-side cumulative counters carried over (warmup gate input);
    # the host budget mirror deliberately did NOT (env_steps_offset owns
    # restored production).
    assert int(jax.device_get(pool_b._carry.steps)) == 3 * E * K
    assert pool_b.steps_done == E * K


def test_carry_state_mismatch_degrades_to_fresh():
    cfg = _small_cfg()
    mesh = _one_device_mesh()
    pool, _ = _pool_with_params(cfg, mesh)
    snap = pool.carry_state_dict()
    other = DeviceActorPool(_small_cfg(device_actor_envs=2 * E), mesh=mesh)
    assert other.load_carry_state(snap) is False  # shape mismatch: E differs
    assert other.load_carry_state({}) is False    # empty snapshot


def test_checkpoint_carries_devactor_sidecar(tmp_path):
    """checkpoint.save(devactor_state=...) writes devactor_carry.npz
    inside the step dir (manifest-covered), and restore() hands it back
    through meta_out — readable BEFORE the pool exists, the resume-order
    constraint train_jax lives under."""
    from distributed_ddpg_tpu import checkpoint as ckpt_lib

    cfg = _small_cfg()
    mesh = _one_device_mesh()
    pool, _ = _pool_with_params(cfg, mesh)
    rep = DeviceReplay(4096, pool.obs_dim, pool.act_dim, mesh=mesh,
                       block_size=64, async_ship=False)
    pool.run_chunk(rep)
    state = init_train_state(cfg, pool.obs_dim, pool.act_dim, cfg.seed)
    d = str(tmp_path)
    ckpt_lib.save(d, 7, state, rep, cfg, env_steps=E * K,
                  devactor_state=pool.carry_state_dict())
    import os

    assert os.path.exists(os.path.join(d, "step_7", "devactor_carry.npz"))
    ok, why = ckpt_lib.verify_checkpoint(d, 7)
    assert ok, why
    meta = {}
    _, step, env_steps = ckpt_lib.restore(d, state, rep, meta_out=meta)
    assert step == 7 and env_steps == E * K
    assert "devactor_carry" in meta
    fresh = DeviceActorPool(cfg, mesh=mesh)
    assert fresh.load_carry_state(meta["devactor_carry"]) is True
    assert int(jax.device_get(fresh._carry.steps)) == E * K


@pytest.mark.slow
def test_train_resume_restores_rollout_state(tmp_path):
    """End-to-end satellite acceptance: a checkpointed device-actor run
    resumed from disk continues its episodes — the restored carry's step
    counter is live in the resumed pool instead of E fresh resets.
    Slow-marked (two full train_jax runs); the tier-1 carry tests above
    pin the same contract at unit scale."""
    from distributed_ddpg_tpu import checkpoint as ckpt_lib
    from distributed_ddpg_tpu.train import train_jax

    ckpt_dir = str(tmp_path / "ckpt")
    cfg = _small_cfg(
        backend="jax_tpu",
        device_actor_envs=8,
        device_actor_chunk=4,
        total_env_steps=1200,
        replay_min_size=200,
        replay_capacity=20_000,
        eval_every=100_000,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=8,
        log_path=str(tmp_path / "a.jsonl"),
    )
    out = train_jax(cfg)
    assert out["learner_steps"] > 0
    step = ckpt_lib.latest_step(ckpt_dir)
    assert step is not None
    import os

    assert os.path.exists(
        os.path.join(ckpt_dir, f"step_{step}", "devactor_carry.npz")
    )
    # Resume with a larger budget: the restored pool must keep counting
    # from the checkpointed carry (its warmup gate stays closed) and the
    # run must complete cleanly.
    out2 = train_jax(cfg.replace(
        total_env_steps=2 * cfg.total_env_steps,
        log_path=str(tmp_path / "b.jsonl"),
    ))
    assert out2["learner_steps"] >= out["learner_steps"]
    assert out2["devactor_restarts"] == 0
