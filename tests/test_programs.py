"""Layer-2 program-contract analyzer tests (analysis/programs.py,
tools/proganalyze; docs/ANALYSIS.md "Layer 2").

The acceptance contract, pinned:
- the LIVE tree is clean — every registered program spec traces, every
  donated leaf aliases, every golden fingerprint in
  tests/golden_programs/ matches — inside a 30 s compile-free tracing
  budget;
- each deliberately-broken fixture program (tests/program_fixtures.py:
  unaliased donation, collective reorder, host-callback leak)
  INDEPENDENTLY drives exit 2 with a finding naming the program and the
  primitive/buffer;
- the golden workflow roundtrips: --update-golden writes, a check run
  agrees, a tampered golden gates, stale goldens are flagged and pruned.

Unlike tests/test_lint.py this file traces real jitted programs, so it
rides the conftest 8-virtual-device CPU platform — but nothing here ever
compiles or executes one.
"""

import json
import subprocess
import time
from pathlib import Path

import pytest

jax = pytest.importorskip("jax")

import program_fixtures as fx  # noqa: E402  (tests dir on sys.path)
from distributed_ddpg_tpu.analysis import programs as prog_lib  # noqa: E402
from distributed_ddpg_tpu.tools import proganalyze as prog_cli  # noqa: E402
from distributed_ddpg_tpu.tools import runs as runs_cli  # noqa: E402

TESTS = Path(__file__).resolve().parent
REPO = TESTS.parent
GOLDEN = TESTS / "golden_programs"
FIXMOD = str(TESTS / "program_fixtures.py")


def cli(args, tmp_path, name="report.json"):
    """In-process CLI run returning (rc, report-JSON)."""
    out = tmp_path / name
    rc = prog_cli.main(["--json", str(out), *args])
    return rc, json.loads(out.read_text(encoding="utf-8"))


# ---------------------------------------------------------------------------
# the live tree (acceptance pin)
# ---------------------------------------------------------------------------


def test_live_tree_clean_with_committed_goldens(tmp_path):
    rc, rep = cli([], tmp_path)
    assert rc == 0, rep["findings"]
    assert rep["counts"]["findings"] == 0
    # Every registered program spec has a committed golden — and no
    # golden outlives its program (the stale sweep ran and was silent).
    names = {p["name"] for p in rep["programs"]}
    assert names == {p.stem for p in GOLDEN.glob("*.json")}
    assert len(names) >= 18
    # Compile-free tracing budget: analysis time only (not the jax
    # import), so box contention can't red it. 45 s since the six .tp
    # program variants (PR 15, docs/MESH.md) grew the registry 26 -> 32
    # — the pre-TP registry traced in ~30 s cold on the contended box.
    assert rep["elapsed_s"] < 45.0


def test_every_spec_module_is_watched_by_changed_only():
    # programs.SPEC_MODULES (what default_specs imports) and
    # proganalyze._OWNER_FILES (what --changed-only watches without
    # importing jax) must stay in lockstep.
    module_files = {
        m.replace(".", "/") + ".py" for m in prog_lib.SPEC_MODULES
    }
    assert module_files == set(prog_cli._OWNER_FILES)
    # Every spec's declared owner resolves to a watched file.
    for spec in prog_lib.default_specs():
        assert "distributed_ddpg_tpu/" + spec.owner in module_files, spec.name


def test_guarded_variants_share_golden_collective_order():
    # The guarded and unguarded chunk dispatch at the same lockstep site:
    # their committed goldens must agree on the collective subsequence.
    for base in (
        "learner.chunk.hostfed",
        "learner.chunk.uniform",
        "learner.chunk.per",
        "learner.chunk.uniform.sharded",
        "learner.chunk.per.sharded",
    ):
        a = json.loads((GOLDEN / f"{base}.json").read_text(encoding="utf-8"))
        b = json.loads(
            (GOLDEN / f"{base}.guarded.json").read_text(encoding="utf-8")
        )
        assert a["collectives"] == b["collectives"], base
        assert a["fingerprint"] == b["fingerprint"], base


def test_golden_schema():
    for p in sorted(GOLDEN.glob("*.json")):
        obj = json.loads(p.read_text(encoding="utf-8"))
        assert obj["program"] == p.stem
        assert isinstance(obj["collectives"], list)
        assert obj["fingerprint"] == prog_lib.fingerprint(obj["collectives"])


# ---------------------------------------------------------------------------
# tracing internals
# ---------------------------------------------------------------------------


def test_fingerprint_is_order_sensitive():
    ab = prog_lib.fingerprint(["psum[data]", "pmax[data]"])
    ba = prog_lib.fingerprint(["pmax[data]", "psum[data]"])
    assert ab != ba
    assert ab == prog_lib.fingerprint(["psum[data]", "pmax[data]"])


def test_walk_finds_collectives_inside_scan():
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from distributed_ddpg_tpu.parallel.mesh import shard_map

    mesh = prog_lib.probe_mesh()

    def body(xs):
        def step(c, x):
            return c + jax.lax.psum(x, "data"), ()

        out, _ = jax.lax.scan(step, xs[0], xs)
        return out

    fn = jax.jit(shard_map(body, mesh, in_specs=P(None, "data"),
                           out_specs=P("data")))
    built = prog_lib.BuiltProgram(fn, (np.zeros((3, 8), np.float32),))
    collectives, callbacks, n_eqns = prog_lib.trace_program(built)
    assert collectives == ["psum[data]"]  # found through scan + shard_map
    assert not callbacks
    assert n_eqns > 0


# ---------------------------------------------------------------------------
# the three broken fixtures (acceptance pin: each independently exits 2)
# ---------------------------------------------------------------------------


def test_unaliased_donation_drives_exit_2(tmp_path):
    rc, rep = cli(
        ["--specs", f"{FIXMOD}:broken_donation_specs",
         "--golden", str(tmp_path / "g"), "--update-golden"],
        tmp_path,
    )
    assert rc == 2
    assert len(rep["findings"]) == 1
    f = rep["findings"][0]
    assert f["check"] == "donation-aliasing"
    assert f["program"] == "fixture.donation.unaliased"
    assert "7xf32" in f["message"]  # names the unaliasable buffer


def test_callback_leak_drives_exit_2(tmp_path):
    rc, rep = cli(
        ["--specs", f"{FIXMOD}:broken_callback_specs",
         "--golden", str(tmp_path / "g"), "--update-golden"],
        tmp_path,
    )
    assert rc == 2
    assert len(rep["findings"]) == 1
    f = rep["findings"][0]
    assert f["check"] == "host-callback"
    assert f["program"] == "fixture.callback.leak"
    assert "pure_callback" in f["message"]  # names the primitive


def test_collective_reorder_drives_exit_2(tmp_path):
    g = tmp_path / "g"
    rc, rep = cli(
        ["--specs", f"{FIXMOD}:collective_specs_v1",
         "--golden", str(g), "--update-golden"],
        tmp_path, "update.json",
    )
    assert rc == 0 and rep["updated"] == ["fixture.collective.pair"]
    rc, rep = cli(
        ["--specs", f"{FIXMOD}:collective_specs_v2", "--golden", str(g)],
        tmp_path, "check.json",
    )
    assert rc == 2
    assert len(rep["findings"]) == 1
    f = rep["findings"][0]
    assert f["check"] == "collective-order"
    assert f["program"] == "fixture.collective.pair"
    # The finding shows both orders, naming the reordered primitives.
    assert "psum[data]" in f["message"] and "pmax[data]" in f["message"]


def test_beat_group_divergence_gates(tmp_path):
    rep = prog_lib.analyze(
        fx.broken_beat_group_specs(), tmp_path / "g", update_golden=True
    )
    checks = [f.check for f in rep.findings]
    assert checks == ["beat-group"]
    assert "fixture-beat" in rep.findings[0].message


# ---------------------------------------------------------------------------
# the golden workflow
# ---------------------------------------------------------------------------


def test_missing_golden_gates(tmp_path):
    rep = prog_lib.analyze(fx.clean_specs(), tmp_path / "empty")
    assert [f.check for f in rep.findings] == ["collective-order"]
    assert "no golden fingerprint" in rep.findings[0].message


def test_update_golden_roundtrip(tmp_path):
    g = tmp_path / "g"
    rep = prog_lib.analyze(fx.clean_specs(), g, update_golden=True)
    assert not rep.findings and rep.updated == ["fixture.clean"]
    golden = json.loads(
        (g / "fixture.clean.json").read_text(encoding="utf-8")
    )
    assert golden["collectives"] == ["psum[data]"]
    # A check run agrees; a second update is a no-op (nothing re-listed).
    assert not prog_lib.analyze(fx.clean_specs(), g).findings
    assert prog_lib.analyze(fx.clean_specs(), g,
                            update_golden=True).updated == []
    # Tamper with the committed order -> the gate fires; re-update heals.
    golden["collectives"] = ["pmax[data]", "psum[data]"]
    (g / "fixture.clean.json").write_text(json.dumps(golden),
                                          encoding="utf-8")
    rep = prog_lib.analyze(fx.clean_specs(), g)
    assert [f.check for f in rep.findings] == ["collective-order"]
    rep = prog_lib.analyze(fx.clean_specs(), g, update_golden=True)
    assert rep.updated == ["fixture.clean"]
    assert not prog_lib.analyze(fx.clean_specs(), g).findings


def test_stale_golden_flagged_and_pruned(tmp_path):
    g = tmp_path / "g"
    prog_lib.analyze(fx.clean_specs(), g, update_golden=True)
    prog_lib.write_golden(g, "fixture.retired", ["psum[data]"])
    rep = prog_lib.analyze(fx.clean_specs(), g)
    assert [(f.check, f.program) for f in rep.findings] == [
        ("stale-golden", "fixture.retired")
    ]
    # A SCOPED run must not flag goldens of programs it never looked at.
    rep = prog_lib.analyze(fx.clean_specs(), g, only=["fixture.clean"])
    assert not rep.findings
    # --update-golden prunes and reports the retirement.
    rep = prog_lib.analyze(fx.clean_specs(), g, update_golden=True)
    assert rep.updated == ["-fixture.retired"]
    assert not (g / "fixture.retired.json").exists()
    assert not prog_lib.analyze(fx.clean_specs(), g).findings


def test_alternate_specs_registry_never_sweeps_live_goldens(tmp_path):
    # An alternate --specs registry covers NONE of the live programs:
    # against a golden dir holding other programs' goldens the stale
    # sweep must stay silent, and --update-golden must not PRUNE them —
    # the documented fixture invocation uses the default golden dir, so
    # a sweep here would flag (and a prune would delete) every
    # committed live golden.
    g = tmp_path / "g"
    prog_lib.write_golden(g, "live.program", ["psum[data]"])
    rc, rep = cli(
        ["--specs", f"{FIXMOD}:clean_specs", "--golden", str(g)],
        tmp_path, "check.json",
    )
    checks = {f["check"] for f in rep["findings"]}
    assert "stale-golden" not in checks
    assert checks == {"collective-order"}  # only the missing fixture golden
    rc, rep = cli(
        ["--specs", f"{FIXMOD}:clean_specs", "--golden", str(g),
         "--update-golden"],
        tmp_path, "update.json",
    )
    assert rc == 0 and rep["updated"] == ["fixture.clean"]
    assert (g / "live.program.json").exists()  # survived the update


def test_build_error_is_a_finding(tmp_path):
    def boom():
        raise RuntimeError("spec cannot build")

    rep = prog_lib.analyze(
        [prog_lib.ProgramSpec("fixture.broken.build", "x.py", boom)],
        tmp_path / "g",
    )
    assert [f.check for f in rep.findings] == ["build-error"]
    assert "spec cannot build" in rep.findings[0].message


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def test_cli_usage_errors(tmp_path):
    assert prog_cli.main(["--programs", "no.such.program",
                          "--specs", f"{FIXMOD}:clean_specs"]) == 1
    assert prog_cli.main(["--specs", str(tmp_path / "missing.py")]) == 1


def test_cli_scoped_run_matches_glob(tmp_path):
    g = tmp_path / "g"
    rc, _ = cli(
        ["--specs", f"{FIXMOD}:broken_beat_group_specs",
         "--golden", str(g), "--update-golden"],
        tmp_path, "update.json",
    )
    assert rc == 2  # the beat-group divergence
    # Scoped to one variant the group check sees a single member: clean.
    rc, rep = cli(
        ["--specs", f"{FIXMOD}:broken_beat_group_specs",
         "--golden", str(g), "--programs", "fixture.beat.a"],
        tmp_path, "scoped.json",
    )
    assert rc == 0
    assert [p["name"] for p in rep["programs"]] == ["fixture.beat.a"]


def test_cli_list(capsys):
    assert prog_cli.main(
        ["--list", "--specs", f"{FIXMOD}:broken_beat_group_specs"]
    ) == 0
    out = capsys.readouterr().out
    assert "fixture.beat.a" in out and "beat:fixture-beat" in out


# ---------------------------------------------------------------------------
# --changed-only scoping (jax-free fast path)
# ---------------------------------------------------------------------------


def _git(repo, *args):
    subprocess.run(
        ["git", "-C", str(repo), "-c", "user.name=t",
         "-c", "user.email=t@t", *args],
        check=True, capture_output=True, timeout=30,
    )


@pytest.fixture()
def fake_repo(tmp_path, monkeypatch):
    repo = (tmp_path / "repo").resolve()
    for rel in (
        "distributed_ddpg_tpu/parallel/learner.py",
        "distributed_ddpg_tpu/ondevice.py",
        "README.md",
    ):
        p = repo / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("x = 1\n", encoding="utf-8")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-q", "-m", "seed")
    monkeypatch.setattr(prog_cli, "_REPO_ROOT", repo)
    return repo


def test_changed_only_nothing_relevant(fake_repo):
    assert prog_cli._changed_scope("HEAD") == []
    (fake_repo / "README.md").write_text("y = 2\n", encoding="utf-8")
    assert prog_cli._changed_scope("HEAD") == []


def test_changed_only_scopes_to_owner_files(fake_repo):
    (fake_repo / "distributed_ddpg_tpu" / "parallel" / "learner.py"
     ).write_text("x = 2\n", encoding="utf-8")
    assert prog_cli._changed_scope("HEAD") == [
        "distributed_ddpg_tpu/parallel/learner.py"
    ]


def test_changed_only_analyzer_change_invalidates_everything(fake_repo):
    # An untracked file under analysis/ -> full run (None = no scoping).
    p = fake_repo / "distributed_ddpg_tpu" / "analysis" / "programs.py"
    p.parent.mkdir(parents=True)
    p.write_text("x = 1\n", encoding="utf-8")
    assert prog_cli._changed_scope("HEAD") is None


def test_changed_only_bad_ref_errors(fake_repo):
    with pytest.raises(RuntimeError, match="--changed-only"):
        prog_cli._changed_scope("no-such-ref")
    assert prog_cli.main(["--changed-only", "no-such-ref"]) == 1


def test_changed_only_exit_0_without_jax_work(fake_repo, capsys):
    # Nothing relevant changed: the CLI exits 0 before loading any spec.
    assert prog_cli.main(["--changed-only", "HEAD"]) == 0
    assert "nothing to analyze" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# tools.runs programs digest
# ---------------------------------------------------------------------------


def test_runs_programs_digest(tmp_path, capsys):
    g = tmp_path / "g"
    _, rep = cli(
        ["--specs", f"{FIXMOD}:collective_specs_v1",
         "--golden", str(g), "--update-golden"],
        tmp_path, "clean.json",
    )
    assert runs_cli.main(["programs", str(tmp_path / "clean.json")]) == 0
    out = capsys.readouterr().out
    assert "PROGRAMS PASS" in out and "fixture.collective.pair" in out

    cli(["--specs", f"{FIXMOD}:collective_specs_v2", "--golden", str(g)],
        tmp_path, "dirty.json")
    assert runs_cli.main(["programs", str(tmp_path / "dirty.json")]) == 2
    out = capsys.readouterr().out
    assert "PROGRAMS FAIL" in out and "collective-order" in out


def test_runs_programs_digest_bad_inputs(tmp_path, capsys):
    assert runs_cli.main(["programs", str(tmp_path / "nope.json")]) == 1
    trunc = tmp_path / "trunc.json"
    trunc.write_text("[]", encoding="utf-8")
    assert runs_cli.main(["programs", str(trunc)]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# gate scripts
# ---------------------------------------------------------------------------


def test_proganalyze_gate_script_fails_on_findings(tmp_path):
    json_path = tmp_path / "program_findings.json"
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "proganalyze_gate.sh"),
         "--specs", f"{FIXMOD}:broken_donation_specs",
         "--golden", str(tmp_path / "g"), "--update-golden"],
        env={"PATH": "/usr/bin:/bin:/usr/local/bin",
             "PROGRAM_JSON": str(json_path)},
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 2, (proc.stdout, proc.stderr)
    assert "tools.runs programs" in proc.stderr
    rep = json.loads(json_path.read_text(encoding="utf-8"))
    assert rep["findings"][0]["check"] == "donation-aliasing"


def test_proganalyze_gate_script_skips_without_analyzer(tmp_path):
    # Old baselines predate Layer 2: the gate must SKIP, not fail.
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    gate = scripts / "proganalyze_gate.sh"
    gate.write_text(
        (REPO / "scripts" / "proganalyze_gate.sh").read_text()
    )
    proc = subprocess.run(
        ["bash", str(gate)],
        env={"PATH": "/usr/bin:/bin:/usr/local/bin"},
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    assert "SKIP" in proc.stderr


@pytest.mark.slow
def test_ci_gate_programs_prestep_runs_before_usage_check():
    # `ci_gate.sh --programs` with no candidate: the program gate runs on
    # the real tree (the wiring pin), then the usage error exits 1 — not
    # the gate's 2 (the live tree is clean).
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "ci_gate.sh"), "--programs"],
        env={"PATH": "/usr/bin:/bin:/usr/local/bin"},
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    assert "programs," in proc.stdout  # the analyzer summary ran first


def test_changed_only_composes_with_programs_glob(fake_repo, capsys):
    # A glob that matches programs of UNCHANGED modules must say so, not
    # analyze zero programs and read green silently.
    (fake_repo / "distributed_ddpg_tpu" / "ondevice.py").write_text(
        "x = 2\n", encoding="utf-8"
    )
    assert prog_cli.main(
        ["--changed-only", "HEAD", "--programs", "learner.*"]
    ) == 0
    assert "nothing to analyze" in capsys.readouterr().out
    # With the owner changed, the glob composes as a filter in scope.
    (fake_repo / "distributed_ddpg_tpu" / "parallel" / "learner.py"
     ).write_text("x = 2\n", encoding="utf-8")
    rc = prog_cli.main(
        ["--changed-only", "HEAD", "--programs", "learner.chunk.hostfed"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "1 program" in out


def test_out_of_range_donated_index_gates(tmp_path):
    import numpy as np

    def build():
        fn = jax.jit(lambda x: x + 1.0)
        return prog_lib.BuiltProgram(fn, (np.zeros(3, np.float32),), (5,))

    rep = prog_lib.analyze(
        [prog_lib.ProgramSpec("fixture.donated.drift", "x.py", build)],
        tmp_path / "g",
    )
    assert [f.check for f in rep.findings] == ["build-error"]
    assert "out of range" in rep.findings[0].message
