"""tools.runs CLI tests (the tier-1 smoke the ISSUE's CI satellite asks
for): summarize + compare over fixture JSONL in the exact schema
metrics.MetricsLogger emits, and the bench-JSON regression gate — which
must exit nonzero on a synthetic 20% grad_steps_per_sec regression (the
PR's acceptance criterion)."""

import json
import subprocess
import sys

import pytest

from distributed_ddpg_tpu.tools import runs


def _write_jsonl(path, records):
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")


def _fixture_run(path, rate=100.0, dispatch_ms=5.0, p95=9.0):
    """A miniature train run in the real JSONL schema (kind/step/wall_time
    + t_* phase fields + ingest_* fields + eval/final records)."""
    records = []
    for i in range(1, 9):
        records.append({
            "kind": "train", "step": 500 * i, "wall_time": 2.0 * i,
            "learner_steps": 400 * i, "learner_steps_per_sec": rate + i,
            "buffer_fill": 500 * i, "episode_return": -900.0 + 10 * i,
            "critic_loss": 0.5, "mean_q": 1.0 + i,
            "t_dispatch_ms": dispatch_ms, "n_dispatch": 50,
            "t_dispatch_p50": dispatch_ms * 0.9,
            "t_dispatch_p95": p95, "t_dispatch_max": p95 * 2,
            "t_ingest_ms": 0.4, "n_ingest": 50,
            "ingest_rows_per_sec": 8000.0, "ingest_ship_calls": 4,
            "ingest_coalesce_mean": 2.0, "ingest_stall_ms": 0.0,
            "ingest_queue_rows": 128,
        })
        if i % 4 == 0:
            records.append({
                "kind": "eval", "step": 500 * i, "wall_time": 2.0 * i + 0.5,
                "eval_return": -800.0 + 50 * i,
            })
    records.append({
        "kind": "final", "step": 4000, "wall_time": 17.0,
        "learner_steps": 3200, "learner_steps_per_sec": rate,
        "final_return": -600.0,
    })
    _write_jsonl(path, records)
    return records


def test_summarize_digest_and_render(tmp_path):
    path = tmp_path / "run.jsonl"
    _fixture_run(path)
    digest = runs.summarize_run(str(path))
    assert digest["records"] == {"train": 8, "eval": 2, "final": 1}
    assert digest["steps"] == {"first": 500, "last": 4000}
    assert digest["metrics"]["learner_steps_per_sec"]["last"] == 108.0
    assert digest["phases"]["dispatch"]["p95_ms"] == 9.0
    assert digest["phases"]["dispatch"]["calls"] == 400
    assert digest["ingest"]["ingest_rows_per_sec"]["steady"] == 8000.0
    assert digest["eval"]["best"] == -400.0
    assert digest["final"]["final_return"] == -600.0
    text = runs.render_summary(digest)
    assert "dispatch" in text and "ingest_rows_per_sec" in text

    # Interleaved non-JSON lines (echo streams mix prints into stdout
    # captures) must be skipped, not fatal.
    noisy = tmp_path / "noisy.jsonl"
    noisy.write_text(
        "resumed from ckpt at step 3\n"
        + path.read_text()
        + "{broken json\n"
    )
    assert runs.summarize_run(str(noisy))["records"]["train"] == 8


def test_summarize_recovery_counters(tmp_path, capsys):
    """Fault history (docs/RESILIENCE.md): the cumulative recovery
    counters train.py logs must surface in the digest and the rendered
    summary, so `tools.runs summarize` shows a run's fault history."""
    path = tmp_path / "run.jsonl"
    records = _fixture_run(path)
    for i, r in enumerate(records):
        if r["kind"] in ("train", "final"):
            r["actor_respawns"] = min(i, 3)
            r["actor_quarantined"] = 0
            r["ckpt_write_retries"] = 1
            r["emergency_ckpt"] = 0
    _write_jsonl(path, records)
    digest = runs.summarize_run(str(path))
    assert digest["recovery"]["actor_respawns"]["last"] == 3
    assert digest["recovery"]["ckpt_write_retries"]["last"] == 1
    rendered = runs.render_summary(digest)
    assert "recovery / fault history" in rendered
    assert "actor_respawns" in rendered
    # A clean run renders the all-zero note instead of a table.
    clean = tmp_path / "clean.jsonl"
    recs2 = _fixture_run(clean)
    for r in recs2:
        if r["kind"] in ("train", "final"):
            r.update(actor_respawns=0, actor_quarantined=0,
                     ckpt_write_retries=0, emergency_ckpt=0)
    _write_jsonl(clean, recs2)
    assert "clean run" in runs.render_summary(runs.summarize_run(str(clean)))
    # compare: recovery counters ride the A/B table, lower-is-better.
    text, rows = runs.compare_runs(str(clean), str(path))
    row = [r for r in rows if r[0] == "actor_respawns"]
    assert row and row[0][2] == 3


def test_summarize_cli_smoke(tmp_path, capsys):
    path = tmp_path / "run.jsonl"
    _fixture_run(path)
    assert runs.main(["summarize", str(path)]) == 0
    out = capsys.readouterr().out
    assert "phase breakdown" in out
    assert runs.main(["summarize", "--json", str(path)]) == 0
    digest = json.loads(capsys.readouterr().out)
    assert digest["phases"]["dispatch"]["p95_ms"] == 9.0


def test_compare_flags_regressions(tmp_path, capsys):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _fixture_run(a, rate=100.0, dispatch_ms=5.0, p95=9.0)
    _fixture_run(b, rate=70.0, dispatch_ms=8.0, p95=30.0)  # slower + fatter tail
    assert runs.main(["compare", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    rate_line = next(l for l in out.splitlines()
                     if l.startswith("learner_steps_per_sec"))
    assert "!" in rate_line  # >=5% worse, higher-is-better
    p95_line = next(l for l in out.splitlines()
                    if l.startswith("t_dispatch_p95"))
    assert "!" in p95_line   # fatter tail flagged (lower-is-better)


# --------------------------------------------------------------------------
# gate (CI): exit nonzero on a synthetic 20% regression
# --------------------------------------------------------------------------

def _bench_json(path, value, dispatch_ms=1.0):
    path.write_text(json.dumps({
        "metric": "learner_grad_steps_per_sec",
        "unit": "grad_steps/s",
        "value": value,
        "t_dispatch_ms": dispatch_ms,
        "ingest_rows_per_sec": 8000.0,
        "scaling_cpu_virtual": {
            "scaled_batch": {"8": {"rows_per_sec": value * 64}}
        },
    }))


def test_gate_passes_within_threshold(tmp_path):
    _bench_json(tmp_path / "base.json", 100.0)
    _bench_json(tmp_path / "cand.json", 95.0)  # -5% < 10% threshold
    assert runs.main([
        "gate", str(tmp_path / "base.json"), str(tmp_path / "cand.json"),
    ]) == 0


def test_gate_fails_on_20pct_grad_steps_regression(tmp_path, capsys):
    """THE acceptance criterion: a synthetic 20% grad_steps_per_sec
    (bench 'value') regression must exit nonzero at the default 10%
    threshold."""
    _bench_json(tmp_path / "base.json", 100.0)
    _bench_json(tmp_path / "cand.json", 80.0)
    rc = runs.main([
        "gate", str(tmp_path / "base.json"), str(tmp_path / "cand.json"),
    ])
    assert rc == 2
    out = capsys.readouterr().out
    assert "FAIL value" in out and "GATE FAIL" in out


def test_gate_lower_is_better_and_dotted_keys(tmp_path):
    _bench_json(tmp_path / "base.json", 100.0, dispatch_ms=1.0)
    _bench_json(tmp_path / "cand.json", 100.0, dispatch_ms=1.5)
    # dispatch latency +50%: fails only when gated lower-is-better.
    assert runs.main([
        "gate", str(tmp_path / "base.json"), str(tmp_path / "cand.json"),
        "--keys", "value,-t_dispatch_ms",
    ]) == 2
    # Dotted path into the scaling curve gates nested values.
    assert runs.main([
        "gate", str(tmp_path / "base.json"), str(tmp_path / "cand.json"),
        "--keys", "scaling_cpu_virtual.scaled_batch.8.rows_per_sec",
    ]) == 0


def test_gate_missing_candidate_key_fails(tmp_path):
    """A metric that vanished from the candidate must FAIL (a silently
    dropped field reading as healthy is how regressions hide)."""
    _bench_json(tmp_path / "base.json", 100.0)
    (tmp_path / "cand.json").write_text(json.dumps({"metric": "x"}))
    assert runs.main([
        "gate", str(tmp_path / "base.json"), str(tmp_path / "cand.json"),
    ]) == 2


def test_gate_unwraps_driver_bench_wrapper(tmp_path):
    """BENCH_r*.json driver records embed the bench JSON in a 'tail'
    string; gate must read through the wrapper."""
    inner = {"metric": "x", "unit": "grad_steps/s", "value": 50.0}
    (tmp_path / "base.json").write_text(json.dumps(
        {"n": 5, "cmd": "python bench.py", "rc": 0,
         "tail": "noise | more noise " + json.dumps(inner)}
    ))
    _bench_json(tmp_path / "cand.json", 49.0)
    assert runs.main([
        "gate", str(tmp_path / "base.json"), str(tmp_path / "cand.json"),
    ]) == 0


def test_module_entrypoint_runs_without_jax_import(tmp_path):
    """`python -m distributed_ddpg_tpu.tools.runs` is the documented CLI;
    it must work as a module AND must not initialize jax (instant start,
    CI-safe on accelerator-less boxes) — asserted by poisoning the jax
    import path."""
    path = tmp_path / "run.jsonl"
    _fixture_run(path)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.modules['jax'] = None\n"
         "from distributed_ddpg_tpu.tools.runs import main\n"
         f"sys.exit(main(['summarize', {str(path)!r}]))"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "phase breakdown" in proc.stdout
