#!/bin/bash
# Staleness-knob sweep (SURVEY.md §7 hard-part (b); docs/EVIDENCE.md §4):
# HalfCheetah-v4, 16 actors, 300k env steps, seed 0, varying the
# learner-rate cap (grad steps per env step). ratio 1 both sides is the
# reference's sync semantics; 0 is free-running async (the learner runs as
# fast as the device allows). Watchdog on: a wedged tunnel must fail the
# run loudly (exit 70), not eat the sweep.
set -u
cd "$(dirname "$0")/.."
COMMON="--backend=jax_tpu --env_id=HalfCheetah-v4 --num_actors=16
        --total_env_steps=300000 --seed=0 --eval_every=30000
        --eval_episodes=3 --watchdog_s=300"
FAILED=0
run() { # name, extra flags...
  local name="$1"; shift
  echo "=== staleness sweep: $name $*"
  # Fresh artifact per attempt: the metrics sink appends, so a rerun after
  # a failed/partial run would interleave two step sequences in the JSONL
  # that docs/EVIDENCE.md cites.
  rm -f "runs/r3_staleness_${name}.jsonl"
  local rc=0
  python -m distributed_ddpg_tpu.train $COMMON "$@" \
    --log_path="runs/r3_staleness_${name}.jsonl" || rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "=== staleness sweep: $name FAILED (rc=$rc)" >&2
    FAILED=$((FAILED + 1))   # keep sweeping — later points still have value
  fi
}
# Optional row selector ($1): run ONE row so the recovery runbook can
# drain the sweep as per-row resumable stages across short tunnel
# windows (each row is ~7 min; observed windows can be ~3 min, so rows
# land only in long windows — but each landed row is durable evidence).
ONLY="${1:-}"
case "$ONLY" in
  ""|ratio1|ratio4|ratio16|free) ;;
  *) echo "unknown sweep row: $ONLY (rows: ratio1 ratio4 ratio16 free)" >&2
     exit 2 ;;  # a typo'd selector must NOT fall through to SWEEP_DONE
esac
want() { [ -z "$ONLY" ] || [ "$ONLY" = "$1" ]; }
want ratio1  && run ratio1  --max_learn_ratio=1 --max_ingest_ratio=1
want ratio4  && run ratio4  --max_learn_ratio=4
want ratio16 && run ratio16 --max_learn_ratio=16
want free    && run free
if [ "$FAILED" -gt 0 ]; then
  echo "SWEEP_INCOMPLETE: $FAILED run(s) failed" >&2
  exit 1
fi
echo SWEEP_DONE
